"""Headline benchmark: synthetic transformer training throughput + MFU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.
``vs_baseline`` is the ratio of this run's tokens/s/chip to the best value
recorded by any prior round's ``BENCH_r*.json`` in the repo root (1.0 when
none exists), so regressions are visible in the artifact itself. ``detail``
carries an analytic MFU: FLOPs/token = 6·N_params + 6·L·d·s (dense matmuls
fwd+bwd ≈ 6N, plus causal attention scores/values), against the chip's bf16
peak. The workload is BASELINE.json config #5 shaped to one chip:
Llama-style block stack (4 layers, 2048 hidden, bf16) full train step
(fwd+bwd+Adam) under jit.

``--fused-xent`` benches the pallas fused LM-head variant
(tpudist.ops.pallas.fused_xent): slightly lower tokens/s at batch 24 (two
extra logits-block matmuls in its recomputing backward) but it removes the
(tokens, vocab) logits tensor from HBM entirely — batch 96+ trains on one
v5e, where the plain path OOMs at 48.
"""

from __future__ import annotations

import argparse
import glob
import json
import re
import statistics
import time

import jax

from tpudist import data, engine
from tpudist.config import (DataConfig, ParallelConfig, TrainConfig,
                            flagship_model_config)

# bf16 peak TFLOP/s by device kind (dense); None → MFU not reported
PEAK_TFLOPS = [
    (re.compile(r"v5 ?lite|v5e", re.I), 197.0),
    (re.compile(r"v5p", re.I), 459.0),
    (re.compile(r"v4", re.I), 275.0),
    (re.compile(r"v6|trillium", re.I), 918.0),
]


def chip_peak_tflops(device_kind: str):
    for pat, peak in PEAK_TFLOPS:
        if pat.search(device_kind):
            return peak
    return None


def train_flops_per_token(n_params: int, cfg: TrainConfig) -> float:
    """6·N for the dense matmuls (fwd 2N + bwd 4N) plus causal attention:
    per layer fwd = 2·(2·s·d)·0.5 (QKᵀ + PV, halved by causality), ×3 for
    fwd+bwd."""
    m = cfg.model
    s = m.max_seq_len
    return 6.0 * n_params + 6.0 * m.n_layers * m.d_model * s


def best_prior_bench() -> float | None:
    """Best tokens/s/chip across prior rounds' BENCH_r*.json, anchored to
    this script's directory (cwd-independent)."""
    import os
    here = os.path.dirname(os.path.abspath(__file__))
    best = None
    for path in glob.glob(os.path.join(here, "BENCH_r*.json")):
        try:
            with open(path) as f:
                rec = json.load(f)
            val = rec.get("parsed", rec).get("value")
            if isinstance(val, (int, float)) and (best is None or val > best):
                best = float(val)
        except Exception:
            continue
    return best


def main() -> None:
    from tpudist.utils import maybe_force_platform, tune_tpu
    maybe_force_platform()
    tune_tpu()

    p = argparse.ArgumentParser()
    p.add_argument("--fused-xent", action="store_true",
                   help="bench the pallas fused LM-head variant")
    p.add_argument("--batch-per-chip", type=int, default=None)
    p.add_argument("--iters", type=int, default=60)
    args = p.parse_args()

    n_dev = jax.device_count()
    seq = 512
    # 48/chip: measured plateau on v5e for the plain path with the pallas
    # flash-attention kernel (24→83.9k, 32→86.0k, 48→87.1k, 64→83.5k
    # tok/s/chip; without flash the score tensors OOM this batch). The
    # fused head removes the logits tensor from HBM so it runs big-batch;
    # pairing it with remat keeps the backbone activations within HBM at
    # batch 96.
    # with TPUDIST_NO_FLASH the dense score tensors cap the plain path at
    # its old batch-24 plateau (48 OOMs)
    import os
    no_flash = bool(os.environ.get("TPUDIST_NO_FLASH"))
    per_chip = args.batch_per_chip or (
        96 if args.fused_xent else (24 if no_flash else 48))
    batch = per_chip * n_dev
    cfg = TrainConfig(
        batch_size=batch, lr=1e-3, seed=0, dtype="bfloat16",
        fused_xent=args.fused_xent, remat=args.fused_xent,
        data=DataConfig(n_samples=batch),
        model=flagship_model_config(max_seq_len=seq),
        parallel=ParallelConfig(data=-1))

    from tpudist.parallel import build_mesh
    from tpudist.parallel import sharding as shd
    mesh = build_mesh(cfg.parallel)
    state = engine.init_state(jax.random.PRNGKey(0), cfg, mesh)
    n_params = sum(x.size for x in jax.tree.leaves(state.params))
    step = engine.make_train_step(cfg, mesh)
    toks = data.make_synthetic_tokens(batch, seq + 1, cfg.model.vocab_size,
                                      seed=0)
    # place the batch once: steady-state training streams input during the
    # previous step, so per-step host transfer must not pollute the timing
    batch_t = shd.put_batch(mesh, (toks,))

    # warmup: trace + compile + first execution (fence via host transfer —
    # on tunneled/remote PJRT backends block_until_ready can return before
    # execution completes, inflating throughput ~100x)
    for _ in range(2):
        state, loss = step(state, batch_t)
    float(loss)

    # timing in groups: per-group fencing keeps the async queue honest, and
    # the 20-step group amortises the fence's pipeline drain (~100ms on the
    # tunneled backend — a 5-step group inflates step time ~8%)
    group, n_groups = 20, max(2, args.iters // 20)
    group_ms = []
    for _ in range(n_groups):
        t0 = time.perf_counter()
        for _ in range(group):
            state, loss = step(state, batch_t)
        float(loss)
        group_ms.append((time.perf_counter() - t0) * 1000 / group)

    step_ms = statistics.median(group_ms)
    toks_per_step = batch * seq
    tok_s_chip = toks_per_step / (step_ms / 1000) / n_dev

    device_kind = jax.devices()[0].device_kind
    peak = chip_peak_tflops(device_kind)
    achieved_tflops = (train_flops_per_token(n_params, cfg) * tok_s_chip
                       / 1e12)
    mfu_pct = round(100 * achieved_tflops / peak, 2) if peak else None

    prior = best_prior_bench()
    print(json.dumps({
        "metric": "transformer_train_tokens_per_sec_per_chip",
        "value": round(tok_s_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(tok_s_chip / prior, 4) if prior else 1.0,
        "detail": {
            "device": device_kind,
            "n_devices": n_dev,
            "global_batch": batch, "seq_len": seq,
            "lm_head": "fused_xent" if args.fused_xent else "plain",
            "n_params": n_params,
            "mfu_pct": mfu_pct,
            "achieved_tflops_per_chip": round(achieved_tflops, 1),
            "peak_tflops": peak,
            "steps_per_sec_per_chip": round(1000 / step_ms / n_dev, 4),
            "step_time_ms": round(step_ms, 2),
            "step_time_ms_min": round(min(group_ms), 2),
            "step_time_ms_max": round(max(group_ms), 2),
            "prior_best_tok_s_chip": prior,
        },
    }))


if __name__ == "__main__":
    main()
