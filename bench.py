"""Headline benchmark: synthetic transformer training steps/sec/chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no numbers (BASELINE.md: "published: {}"), so
``vs_baseline`` is reported as 1.0 by convention with the absolute value
carrying the signal. The workload is BASELINE.json config #5 shaped to one
chip: Llama-style block stack (4 layers, 2048 hidden, bf16) full train step
(fwd+bwd+Adam) under jit, batch sized to keep the MXU busy.
"""

from __future__ import annotations

import json
import time

import jax

from tpudist import data, engine
from tpudist.config import (DataConfig, ParallelConfig, TrainConfig,
                            flagship_model_config)


def main() -> None:
    from tpudist.utils import maybe_force_platform
    maybe_force_platform()
    n_dev = jax.device_count()
    seq = 512
    # 24/chip: measured sweet spot on v5e (69k tok/s/chip; 16→65k, 28→67k,
    # 30+ degrades under memory pressure)
    batch = 24 * n_dev
    cfg = TrainConfig(
        batch_size=batch, lr=1e-3, seed=0, dtype="bfloat16",
        data=DataConfig(n_samples=batch),
        model=flagship_model_config(max_seq_len=seq),
        parallel=ParallelConfig(data=-1))

    from tpudist.parallel import build_mesh
    mesh = build_mesh(cfg.parallel)
    state = engine.init_state(jax.random.PRNGKey(0), cfg, mesh)
    step = engine.make_train_step(cfg, mesh)
    toks = data.make_synthetic_tokens(batch, seq + 1, cfg.model.vocab_size,
                                      seed=0)
    batch_t = (toks,)

    # warmup: trace + compile + first execution (fence via host transfer —
    # on tunneled/remote PJRT backends block_until_ready can return before
    # execution completes, inflating throughput ~100x)
    for _ in range(2):
        state, loss = step(state, batch_t)
    float(loss)

    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        state, loss = step(state, batch_t)
    float(loss)
    dt = time.perf_counter() - t0

    toks_per_step = batch * seq
    tok_s_chip = toks_per_step * iters / dt / n_dev
    print(json.dumps({
        "metric": "transformer_train_tokens_per_sec_per_chip",
        "value": round(tok_s_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": 1.0,
        "detail": {
            "device": jax.devices()[0].device_kind,
            "n_devices": n_dev,
            "global_batch": batch, "seq_len": seq,
            "steps_per_sec_per_chip": round(iters / dt / n_dev, 4),
            "step_time_ms": round(1000 * dt / iters, 2),
        },
    }))


if __name__ == "__main__":
    main()
