"""Headline benchmark: synthetic transformer training throughput + MFU.

Default mode prints ONE JSON line: {"metric", "value", "unit",
"vs_baseline", "detail"}. ``vs_baseline`` is the ratio of this run's
tokens/s/chip to the best value recorded by any prior round's
``BENCH_r*.json`` in the repo root (1.0 when none exists), so regressions
are visible in the artifact itself. ``detail`` carries an analytic MFU:
FLOPs/token = 6·N_active + 6·L·d·s (dense matmuls fwd+bwd ≈ 6N, plus
causal attention scores/values), against the chip's bf16 peak. N_active
discounts non-routed expert weights for the MoE model (top_k/E of each
expert FFN does useful work per token — the honest convention; the
dispatch/combine einsums are framework overhead, not model FLOPs). The
workload is BASELINE.json config #5 shaped to one chip: Llama-style block
stack (4 layers, 2048 hidden, bf16) full train step (fwd+bwd+Adam) under
jit.

``--matrix`` instead benches the whole perf surface — {seq 512, 2048,
4096} × {plain, fused, chunked LM head} × {flash, no-flash} × {dense,
gqa, moe} (meaningful cells only; see ``MATRIX_ROWS``) — printing one JSONL
line per cell and writing the committed artifact ``BENCH_MATRIX.json``
plus a README-ready markdown table. One command, one artifact: the
reference's everything-is-an-observable-output stance
(reference slurm_train.sbatch:38,43) applied to performance claims.

``--fused-xent`` benches the pallas fused LM-head variant
(tpudist.ops.pallas.fused_xent): it removes the (tokens, vocab) logits
tensor from HBM entirely — batch 96+ trains on one v5e, where the plain
path OOMs. Its FLOP floor is 4 head matmuls vs the plain path's 3 (the
backward must recompute logits once; r4's merged backward kernel reaches
that floor — head-only fwd+bwd at the bench shape measured 95.7 ms vs
113.8 ms for the r3 split kernels and 75.2 ms plain, i.e. 1.27× plain
against the 1.33× FLOP ratio), so at batches where plain fits, plain
stays the default.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import statistics
import time

import jax

from tpudist import data, engine
from tpudist.config import (DataConfig, ModelConfig, ParallelConfig,
                            TrainConfig, flagship_model_config)
from tpudist.obs import mfu as obs_mfu
from tpudist.obs.hbm import HbmSampler

# bf16 peak table lives in tpudist.obs.mfu now (the train run's roofline
# record uses the same source); these aliases keep bench's surface stable
PEAK_TFLOPS = obs_mfu.PEAK_TFLOPS
chip_peak_tflops = obs_mfu.chip_peak_tflops


def _sweep_obs_fields(dispatch_fn, step_ms: float,
                      sampler: HbmSampler) -> dict:
    """The per-point utilization context the sweeps record alongside
    steps/s: compiled-program MFU (obs.mfu — on CPU the peak is unknown
    so mfu is None unless $TPUDIST_PEAK_TFLOPS pins it, but the FLOP and
    byte counts are always real) and the HBM high-water mark so a perf
    point's memory footprint rides in the artifact."""
    sampler.sample()
    f = obs_mfu.mfu_fields(obs_mfu.dispatch_cost(dispatch_fn),
                           step_ms / 1000.0)
    return {"mfu": f["mfu"],
            "model_flops_per_step": f["model_flops_per_step"],
            "achieved_gbps_per_chip": f["achieved_gbps_per_chip"],
            "hbm_peak_bytes": sampler.split()["hbm_peak_bytes"]}


def active_params(params, cfg: TrainConfig) -> int:
    """Parameters doing useful work per token: everything, minus the
    (1 − top_k/E) fraction of each MoE expert weight a token never visits."""
    total = sum(x.size for x in jax.tree.leaves(params))
    m = cfg.model
    if m.name != "moe":
        return total
    layers = params["layers"]
    expert = sum(layers[k].size for k in ("w_gate", "w_up", "w_down"))
    return total - int(expert * (1.0 - m.expert_top_k / m.n_experts))


def train_flops_per_token(n_active: int, cfg: TrainConfig) -> float:
    """6·N for the dense matmuls (fwd 2N + bwd 4N) plus causal attention:
    per layer fwd = 2·(2·s·d)·0.5 (QKᵀ + PV, halved by causality), ×3 for
    fwd+bwd."""
    m = cfg.model
    s = m.max_seq_len
    return 6.0 * n_active + 6.0 * m.n_layers * m.d_model * s


def best_prior_bench() -> float | None:
    """Best tokens/s/chip across prior rounds' BENCH_r*.json, anchored to
    this script's directory (cwd-independent)."""
    here = os.path.dirname(os.path.abspath(__file__))
    best = None
    for path in glob.glob(os.path.join(here, "BENCH_r*.json")):
        try:
            with open(path) as f:
                rec = json.load(f)
            val = rec.get("parsed", rec).get("value")
            if isinstance(val, (int, float)) and (best is None or val > best):
                best = float(val)
        except Exception:
            continue
    return best


def build_cfg(*, seq: int, per_chip: int, head: str = "plain",
              model: str = "transformer", remat: bool = False,
              moe_group: int = 256) -> TrainConfig:
    """One matrix cell's TrainConfig. ``head``: plain | fused | cN
    (chunked over N sequence chunks)."""
    n_dev = jax.device_count()
    batch = per_chip * n_dev
    if model == "gqa":
        # grouped-query flagship (16 q heads, 4 kv heads): the compact-kv
        # flash kernels hold the dense model's MFU while the kv
        # projections shrink 4x — BENCH_MATRIX.json row: 105,920 tok/s/chip,
        # 79.67% MFU on v5e at batch 56 (same batch as dense plain)
        mcfg = ModelConfig(name="transformer", vocab_size=32000, n_layers=4,
                           d_model=2048, n_heads=16, n_kv_heads=4,
                           d_ff=5504, max_seq_len=seq)
    elif model == "moe_cf1":
        # capacity_factor 1.0: computed expert rows == counted active rows
        # (cf 1.25 pays 25% extra FFN FLOPs for fewer dropped tokens —
        # a quality/throughput knob, benched as its own row, default kept
        # honest at 1.25). r5 sweep: ~10% step win over cf 1.25.
        mcfg = ModelConfig(name="moe", vocab_size=32000, n_layers=4,
                           d_model=2048, n_heads=16, n_kv_heads=16,
                           d_ff=2752, max_seq_len=seq, n_experts=8,
                           expert_top_k=2, moe_group_size=moe_group,
                           capacity_factor=1.0)
    elif model == "moe_gqa":
        # MoE backbone with grouped-query attention (16 q heads, 4 kv):
        # the two "beyond" model families composed — kv projections shrink
        # 4x on top of the routed FFN
        mcfg = ModelConfig(name="moe", vocab_size=32000, n_layers=4,
                           d_model=2048, n_heads=16, n_kv_heads=4,
                           d_ff=2752, max_seq_len=seq, n_experts=8,
                           expert_top_k=2, moe_group_size=moe_group)
    elif model == "moe":
        # d_ff 2752 per expert: active params/token = attn side + top2/8 of
        # the expert weights ≈ 267M — the same active size as the dense
        # flagship, so the MoE row reads apples-to-apples. (Experts at the
        # dense model's d_ff 5504 total 1.2B params, whose f32 Adam state
        # alone exceeds one v5e's 16 GB HBM past batch 4 — that shape
        # belongs to multi-chip expert parallelism, which the dryrun's
        # expert-axis mesh exercises.) Group 256, batch 32/chip: r4
        # measured optimum on v5e — 70.1k tok/s, 58.1% active-MFU (r3: 66.9k
        # at g512/b24; g128 55.4%, g384 56.7%, b40 53.4%, b48 OOM; an
        # index/gather dispatch prototype measured ~60k — its backward
        # scatter-adds serialize at ~21 GB/s, so the einsum dispatch
        # stays). The remaining gap to the dense 80% is structural at
        # one-chip batch: ~12% extra expert FLOPs from capacity-factor
        # slots (cf·k/E rows computed, k/E counted active), ~3% dispatch/
        # combine einsums, ~19 ms/step of Adam+weight HBM traffic for the
        # 674M TOTAL params (profiled: three ~6.4 ms 630 GB/s fusions),
        # and cap=80-row expert matmuls vs the MXU's appetite. (Total
        # params 674M: 65.5M embed + 67M attn + 541M experts.)
        mcfg = ModelConfig(name="moe", vocab_size=32000, n_layers=4,
                           d_model=2048, n_heads=16, n_kv_heads=16,
                           d_ff=2752, max_seq_len=seq, n_experts=8,
                           expert_top_k=2, moe_group_size=moe_group)
    else:
        mcfg = flagship_model_config(max_seq_len=seq)
    return TrainConfig(
        batch_size=batch, lr=1e-3, seed=0, dtype="bfloat16",
        fused_xent=(head == "fused"), remat=remat,
        # matrix rows pin their strategy (a row labeled "plain" must not
        # silently bench whatever auto picks); fused/cN rows are pinned by
        # their explicit flags below, which "auto" honors; head="auto"
        # benches the policy itself
        lm_head=("plain" if head == "plain" else "auto"),
        xent_chunks=(int(head[1:]) if head.startswith("c") else 0),
        data=DataConfig(n_samples=batch),
        model=mcfg,
        parallel=ParallelConfig(data=-1))


def measure(cfg: TrainConfig, iters: int = 60) -> dict:
    """Steady-state step time of cfg's train step on the live mesh.

    Timing in groups: per-group fencing (a host transfer — on tunneled
    PJRT backends block_until_ready can return before execution completes)
    keeps the async queue honest, and the 20-step group amortises the
    fence's pipeline drain (~100 ms tunneled; a 5-step group inflates step
    time ~8%)."""
    from tpudist.parallel import build_mesh
    from tpudist.parallel import sharding as shd
    mesh = build_mesh(cfg.parallel)
    state = engine.init_state(jax.random.PRNGKey(0), cfg, mesh)
    n_active = active_params(state.params, cfg)
    n_params = sum(x.size for x in jax.tree.leaves(state.params))
    sampler = HbmSampler(period_s=0)
    step = engine.make_train_step(cfg, mesh)
    seq = cfg.model.max_seq_len
    toks = data.make_synthetic_tokens(cfg.batch_size, seq + 1,
                                      cfg.model.vocab_size, seed=0)
    # place the batch once: steady-state training streams input during the
    # previous step, so per-step host transfer must not pollute the timing
    batch_t = shd.put_batch(mesh, (toks,))

    for _ in range(2):                       # trace + compile + warm
        state, loss = step(state, batch_t)
    float(loss)

    group, n_groups = 20, max(2, iters // 20)
    group_ms = []
    for _ in range(n_groups):
        t0 = time.perf_counter()
        for _ in range(group):
            state, loss = step(state, batch_t)
        float(loss)
        group_ms.append((time.perf_counter() - t0) * 1000 / group)

    n_dev = jax.device_count()
    step_ms = statistics.median(group_ms)
    tok_s_chip = cfg.batch_size * seq / (step_ms / 1000) / n_dev
    device_kind = jax.devices()[0].device_kind
    peak = chip_peak_tflops(device_kind)
    achieved = train_flops_per_token(n_active, cfg) * tok_s_chip / 1e12
    sampler.sample()
    return {
        "hbm_peak_bytes": sampler.split()["hbm_peak_bytes"],
        "device": device_kind,
        "n_devices": n_dev,
        "global_batch": cfg.batch_size,
        "seq_len": seq,
        "n_params": n_params,
        "n_active_params": n_active,
        "tok_s_chip": round(tok_s_chip, 1),
        "mfu_pct": round(100 * achieved / peak, 2) if peak else None,
        "achieved_tflops_per_chip": round(achieved, 1),
        "peak_tflops": peak,
        "step_time_ms": round(step_ms, 2),
        "step_time_ms_min": round(min(group_ms), 2),
        "step_time_ms_max": round(max(group_ms), 2),
    }


# --------------------------------------------------------- dispatch sweep


def _sweep_plan(cfg, n_steps: int):
    """Epoch-0 plan of the sweeps' tiny-MLP dataset (the probe harness
    consumes EpochPlans — the same input contract the train loop has)."""
    return data.plan_epoch(
        data.make_synthetic_data(n_steps * cfg.batch_size,
                                 cfg.data.n_features, cfg.data.seed),
        batch_size=cfg.batch_size, seed=cfg.seed, epoch=0)


def _dispatch_cell(cfg, mesh, k: int, n_steps: int, repeats: int) -> dict:
    """ms/step of the tiny-MLP train loop at superstep length k (k=1 =
    the per-step dispatch path, including its per-step put_batch — the
    real thing the superstep replaces). The compile/warmup/time-n-steps
    loop is tune.probe's — the sweep and the autotuner share one trial
    protocol, so BENCH_DISPATCH rows and probe trials are comparable."""
    from tpudist.tune import probe
    runner = probe.EpochRunner(cfg, mesh, k, _sweep_plan(cfg, n_steps),
                               n_steps)
    sampler = HbmSampler(period_s=0)   # manual sampling brackets the cell
    _, times, _ = probe.time_runner(runner, repeats=repeats)
    ms = statistics.median(times)
    return {"k": k, "step_ms": round(ms, 4),
            "steps_per_sec": round(1000 / ms, 1),
            **_sweep_obs_fields(runner.dispatch_fn, ms, sampler)}


def _staging_row(splan, superstep, budget_bytes, n_steps, ms,
                 sampler) -> dict:
    return {"mode": "streamed" if splan.streamed else "full_epoch",
            "budget_mb": (None if budget_bytes is None
                          else round(budget_bytes / 2**20, 4)),
            "slab_steps": splan.slab_steps, "n_slabs": splan.n_slabs,
            "epoch_mb": round(n_steps * splan.step_bytes / 2**20, 4),
            "superstep_compiles": len(superstep.traces),
            "step_ms": round(ms, 4),
            "steps_per_sec": round(1000 / ms, 1),
            **_sweep_obs_fields(superstep, ms, sampler)}


def run_staging_sweep(out_path: str, n_steps: int = 136,
                      repeats: int = 9) -> dict:
    """The staging-pipeline row: tiny-MLP steps/s at k=32 with full-epoch
    staging vs double-buffered streaming under a budget the epoch
    EXCEEDS by construction — the dataset that previously could not run
    (put_epoch staged the whole epoch or died) completes end-to-end.
    ``n_steps`` is deliberately not a k-multiple so both rows cross the
    zero-padded trailing partial slab; ``superstep_compiles`` must read
    1 in every row. The tracked artifact metric is the streamed/full
    steps/s ratio (the overlap claim: streaming should cost ~nothing)."""
    from tpudist.parallel import build_mesh
    from tpudist.tune import probe
    cfg = TrainConfig(batch_size=64, lr=1e-3, seed=0,
                      data=DataConfig(n_samples=n_steps * 64),
                      parallel=ParallelConfig(data=-1))
    mesh = build_mesh(cfg.parallel)
    k = 32
    plan = _sweep_plan(cfg, n_steps)
    batch_shards = mesh.shape["data"] * mesh.shape["fsdp"]
    step_bytes = max(1, plan.bytes_per_step // batch_shards)
    # budget: exactly two k-step slabs + slack — a fraction of the epoch,
    # so the streamed row IS the previously-impossible over-budget run
    budget = int(2.5 * k * step_bytes)
    cells = [(None,), (budget,)]
    runners = {}
    for (b,) in cells:
        # tune.probe's epoch harness IS train._superstep_epoch's staging
        # shape (full-epoch fast path or double-buffered streaming)
        runner = probe.EpochRunner(cfg, mesh, k, plan, n_steps,
                                   budget_bytes=b)
        state = runner.init_state()
        state, loss = runner.run_epoch(state)  # trace + compile + warm
        jax.device_get(loss)
        # per-MODE sampler, created before this mode's timed epochs:
        # its peak brackets this mode's footprint, not the whole sweep
        runners[b] = [runner, state, runner.dispatch_fn, runner.splan, [],
                      HbmSampler(period_s=0)]
    # interleave the two modes' timed epochs so host-load drift affects
    # both equally instead of biasing whichever cell ran later
    for _ in range(repeats):
        for (b,) in cells:
            r = runners[b]
            t0 = time.perf_counter()
            r[1], loss = r[0].run_epoch(r[1])
            jax.device_get(loss)              # fence
            r[4].append((time.perf_counter() - t0) * 1000 / n_steps)
            r[5].sample()
    rows = [_staging_row(runners[b][3], runners[b][2], b, n_steps,
                         statistics.median(runners[b][4]), runners[b][5])
            for (b,) in cells]
    by_mode = {r["mode"]: r for r in rows}
    # ratio as the median of per-round ratios: each round's full and
    # streamed epochs run back-to-back, so load drift cancels pairwise
    # (per-mode medians across rounds would re-introduce it)
    ratio = round(statistics.median(
        f / s for f, s in zip(runners[None][4], runners[budget][4])), 4)
    art = {
        "metric": "staging_streamed_vs_full_steps_ratio",
        "value": ratio,
        "unit": "streamed steps/s / full-epoch steps/s (k=32)",
        "detail": {
            "device": jax.devices()[0].device_kind,
            "n_devices": jax.device_count(),
            "model": "mlp", "global_batch": cfg.batch_size,
            "k": k, "n_steps": n_steps,
            "rows": rows,
            "over_budget_dataset_completed": (
                by_mode["streamed"]["epoch_mb"]
                > by_mode["streamed"]["budget_mb"]),
            "one_compile_per_run": all(
                r["superstep_compiles"] == 1 for r in rows),
        },
    }
    with open(out_path, "w") as f:
        json.dump(art, f, indent=1)
    print(json.dumps(art))
    return art


def run_memory_sweep(out_path: str, n_steps: int = 136) -> dict:
    """The memory-ledger row, BENCH_MEMORY.json: the HBM bucket bytes
    behind the two fixed-budget claims, computed from the SAME ledger
    arithmetic the train/serve lanes record (tpudist.obs.memledger) —
    (a) dense vs paged KV pool bytes for the serve lane's tiny
    transformer (pool + trash page + page table vs slots x max_seq),
    (b) full-epoch vs double-buffered streamed slab residency for the
    staging lane's over-budget tiny-MLP epoch (plan_slabs' own cut).
    Each row carries the ledger-derived columns (bucket bytes, headroom
    fraction, exactness) so the artifact states not just "paged is
    smaller" but how much device headroom each choice buys. Headline =
    paged/dense KV bucket byte ratio (< 1.0 is the claim)."""
    from tpudist.obs import memledger as memledger_lib
    from tpudist.parallel import build_mesh
    from tpudist.parallel import sharding as shd
    from tpudist.serve import kvcache
    from tpudist.serve.engine import init_params

    hbm = int(engine._device_hbm_bytes())
    rows = []

    def ledger_cols(led):
        return {"headroom_fraction": led["headroom_fraction"],
                "headroom_bytes": led["buckets"]["headroom"],
                "exact": led["exact"]}

    # (a) the serve lane's KV pair: same tiny transformer + pool shape
    # as run_serve_sweep's fixed-HBM pair, bytes from the specs' own
    # accounting (PagedCacheSpec.bytes includes trash page + table)
    model_cfg = ModelConfig(name="transformer", vocab_size=256,
                            n_layers=2, d_model=64, n_heads=4,
                            n_kv_heads=2, d_ff=128, max_seq_len=64)
    slots, max_seq, prompt_pad = 4, 64, 16
    mesh = build_mesh(ParallelConfig())
    params = init_params(model_cfg, mesh, seed=0)
    params_bytes = engine.state_bytes_per_device(params)
    dense_spec = kvcache.CacheSpec.from_model(
        model_cfg, slots=slots, max_seq=max_seq)
    paged_spec = kvcache.PagedCacheSpec.from_model(
        model_cfg, slots=2 * slots, max_seq=max_seq, page_tokens=8,
        pages=30)
    for mode, spec in (("dense", dense_spec), ("paged", paged_spec)):
        led = memledger_lib.build_ledger(
            total_hbm_bytes=hbm, params_bytes=params_bytes,
            kv_pool_bytes=spec.bytes, mode="serve")
        rows.append({"lane": "serve_kv", "mode": mode,
                     "slots": spec.slots,
                     "kv_pool_bytes": spec.bytes,
                     **ledger_cols(led)})
        print(json.dumps(rows[-1]))
    dense_kv, paged_kv = rows[0], rows[1]
    if paged_kv["kv_pool_bytes"] >= dense_kv["kv_pool_bytes"]:
        raise SystemExit(
            "memory sweep: paged KV bucket must be strictly smaller "
            f"than dense ({paged_kv['kv_pool_bytes']} vs "
            f"{dense_kv['kv_pool_bytes']} bytes)")

    # (b) the staging lane's slab pair: run_staging_sweep's over-budget
    # epoch shape, resident bytes from plan_slabs (x2 when streaming —
    # double-buffered) — no device work, this is the ledger's own math
    cfg = TrainConfig(batch_size=64, lr=1e-3, seed=0,
                      data=DataConfig(n_samples=n_steps * 64),
                      parallel=ParallelConfig(data=-1))
    k = 32
    plan = _sweep_plan(cfg, n_steps)
    batch_shards = mesh.shape["data"] * mesh.shape["fsdp"]
    step_bytes = max(1, plan.bytes_per_step // batch_shards)
    budget = int(2.5 * k * step_bytes)
    state = engine.init_state(jax.random.PRNGKey(cfg.seed), cfg, mesh)
    st_params = engine.state_bytes_per_device(state.params)
    st_opt = engine.state_bytes_per_device(state.opt_state)
    for mode, b in (("full", None), ("streamed", budget)):
        splan = shd.plan_slabs(n_steps, k, step_bytes, b)
        resident = (min(2, splan.n_slabs) * splan.slab_bytes
                    if splan.streamed else splan.slab_bytes)
        led = memledger_lib.build_ledger(
            total_hbm_bytes=hbm, params_bytes=st_params,
            opt_state_bytes=st_opt, slab_bytes=resident, mode="train")
        rows.append({"lane": "staging_slabs", "mode": mode,
                     "budget_bytes": b, "n_slabs": splan.n_slabs,
                     "slab_resident_bytes": resident,
                     **ledger_cols(led)})
        print(json.dumps(rows[-1]))
    full_row, streamed_row = rows[2], rows[3]
    if streamed_row["slab_resident_bytes"] \
            >= full_row["slab_resident_bytes"]:
        raise SystemExit(
            "memory sweep: streamed slab residency must be strictly "
            "smaller than the full-epoch stage "
            f"({streamed_row['slab_resident_bytes']} vs "
            f"{full_row['slab_resident_bytes']} bytes)")

    art = {
        "metric": "paged_vs_dense_kv_bytes_ratio",
        "value": round(paged_kv["kv_pool_bytes"]
                       / dense_kv["kv_pool_bytes"], 4),
        "unit": "paged KV bucket bytes / dense KV bucket bytes "
                "(< 1.0 at 2x the slots)",
        "detail": {
            "device": jax.devices()[0].device_kind,
            "n_devices": jax.device_count(),
            "total_hbm_bytes": hbm,
            "rows": rows,
            "staging_resident_ratio": round(
                streamed_row["slab_resident_bytes"]
                / full_row["slab_resident_bytes"], 4),
            "headroom_gain_fraction_kv": round(
                paged_kv["headroom_fraction"]
                - dense_kv["headroom_fraction"], 6),
            "headroom_gain_fraction_staging": round(
                streamed_row["headroom_fraction"]
                - full_row["headroom_fraction"], 6),
        },
    }
    with open(out_path, "w") as f:
        json.dump(art, f, indent=1)
    print(json.dumps({key: art[key]
                      for key in ("metric", "value", "unit")}))
    return art


def run_dispatch_sweep(out_path: str, n_steps: int = 128,
                       repeats: int = 5) -> dict:
    """The dispatch-overhead row: steps/s on the tiny MLP at superstep
    k=1 vs 8 vs 32. The model is deliberately dispatch-bound (the paper's
    regime), so the k=1→32 delta IS the per-step dispatch+fence cost;
    ``dispatch_overhead_ms`` (ms/step at k=1 minus ms/step at k=32) is
    the tracked artifact metric for future PRs."""
    from tpudist.parallel import build_mesh
    cfg = TrainConfig(batch_size=64, lr=1e-3, seed=0,
                      data=DataConfig(n_samples=n_steps * 64),
                      parallel=ParallelConfig(data=-1))
    mesh = build_mesh(cfg.parallel)
    rows = [_dispatch_cell(cfg, mesh, k, n_steps, repeats)
            for k in (1, 8, 32)]
    by_k = {r["k"]: r for r in rows}
    art = {
        "metric": "dispatch_overhead_ms_per_step",
        "value": round(by_k[1]["step_ms"] - by_k[32]["step_ms"], 4),
        "unit": "ms/step (k=1 minus k=32)",
        "detail": {
            "device": jax.devices()[0].device_kind,
            "n_devices": jax.device_count(),
            "model": "mlp", "global_batch": cfg.batch_size,
            "rows": rows,
            "speedup_k32_vs_k1": round(
                by_k[32]["steps_per_sec"] / by_k[1]["steps_per_sec"], 3),
        },
    }
    with open(out_path, "w") as f:
        json.dump(art, f, indent=1)
    print(json.dumps(art))
    return art


def run_tune_sweep(out_path: str, n_steps: int = 128,
                   repeats: int = 5) -> dict:
    """The autotuner row: heuristic-pick vs measured-probe steps/s on the
    CPU dispatch-bound tiny MLP, against the k={1,8,32} dispatch sweep as
    ground truth. ``--log-every 32`` shapes the legal k space to the full
    ladder {1..32}, so the search must climb the same curve the sweep
    measures; the artifact records whether the selected point lands
    within 10% of the sweep's best (the acceptance band) and that an
    immediate re-tune is a pure cache hit — zero probe trials."""
    import tempfile

    from tpudist import tune as tune_lib
    from tpudist.parallel import build_mesh
    cfg = TrainConfig(batch_size=64, lr=1e-3, seed=0, log_every=32,
                      autotune_cache_dir=tempfile.mkdtemp(
                          prefix="tpudist_tune_"),
                      data=DataConfig(n_samples=n_steps * 64),
                      parallel=ParallelConfig(data=-1))
    mesh = build_mesh(cfg.parallel)
    sweep = [_dispatch_cell(cfg, mesh, k, n_steps, repeats)
             for k in (1, 8, 32)]
    plan = _sweep_plan(cfg, n_steps)
    first = tune_lib.autotune(cfg, mesh, plan, mode="probe",
                              n_steps=n_steps, repeats=repeats)
    rerun = tune_lib.autotune(cfg, mesh, plan, mode="probe",
                              n_steps=n_steps, repeats=repeats)
    best_sps = max(r["steps_per_sec"] for r in sweep)
    sel_sps = first.steps_per_sec or 0.0
    art = {
        "metric": "autotuned_vs_heuristic_steps_ratio",
        "value": round(sel_sps / (first.baseline_steps_per_sec or sel_sps
                                  or 1.0), 4),
        "unit": "autotuned steps/s / heuristic-pick steps/s (tiny MLP)",
        "detail": {
            "device": jax.devices()[0].device_kind,
            "n_devices": jax.device_count(),
            "model": "mlp", "global_batch": cfg.batch_size,
            "n_steps": n_steps, "log_every": cfg.log_every,
            "sweep_rows": sweep,
            "selected": {**first.tuned.as_dict(),
                         "steps_per_sec": first.steps_per_sec},
            "heuristic_steps_per_sec": first.baseline_steps_per_sec,
            "tuning_status": first.status,
            "trials": first.trials, "pruned": first.pruned,
            "fingerprint": first.fingerprint,
            "within_10pct_of_sweep_best": bool(sel_sps >= 0.9 * best_sps),
            "rerun_source": rerun.source,
            "rerun_trials": rerun.trials,
            "rerun_is_pure_cache_hit": bool(
                rerun.source == "cache" and rerun.trials == 0),
        },
    }
    with open(out_path, "w") as f:
        json.dump(art, f, indent=1)
    print(json.dumps(art))
    return art


# ------------------------------------------------------------- ckpt sweep


def run_ckpt_sweep(out_path: str, n_steps: int = 64, repeats: int = 4,
                   k: int = 8) -> dict:
    """The checkpoint-overhead curve: tiny-MLP steps/s at k=8 with an
    epoch-end save under each checkpoint mode — none (baseline),
    orbax-sync, orbax-async, and the elastic sharded-manifest writer
    (tpudist.elastic.ckpt) — on the shared tune.probe epoch harness, so
    the rows are directly comparable to BENCH_DISPATCH/BENCH_STAGING.
    Each row splits the save cost the honest way the Checkpointer does:
    ``enqueue_ms`` (what the train loop pays inline, snapshot+handoff),
    ``drain_ms`` (the blocked time at close that async modes defer), and
    the steps/s DIP vs the no-checkpoint baseline (save windows timed
    INSIDE the per-epoch wall, so hidden async cost stays hidden and
    exposed sync cost shows). The tracked artifact metric is the
    sharded-manifest dip — the price of preemption survival."""
    import shutil
    import tempfile

    from tpudist import checkpoint as ckpt_lib
    from tpudist.elastic import ckpt as elastic_ckpt
    from tpudist.parallel import build_mesh
    from tpudist.tune import probe

    cfg = TrainConfig(batch_size=64, lr=1e-3, seed=0,
                      data=DataConfig(n_samples=n_steps * 64),
                      parallel=ParallelConfig(data=-1))
    mesh = build_mesh(cfg.parallel)
    plan = _sweep_plan(cfg, n_steps)

    def make_ckpt(mode, d):
        if mode == "none":
            return None
        if mode == "sharded":
            return elastic_ckpt.ShardedCheckpointer(d, use_async=True)
        return ckpt_lib.Checkpointer(d, use_async=(mode == "orbax-async"))

    rows = []
    for mode in ("none", "orbax-sync", "orbax-async", "sharded"):
        d = tempfile.mkdtemp(prefix=f"tpudist_ckpt_{mode}_")
        runner = probe.EpochRunner(cfg, mesh, k, plan, n_steps)
        state = runner.init_state()
        state, loss = runner.run_epoch(state)    # trace + compile + warm
        jax.device_get(loss)
        ck = make_ckpt(mode, d)
        ms, enq = [], []
        for r in range(repeats):
            t0 = time.perf_counter()
            state, loss = runner.run_epoch(state)
            jax.device_get(loss)                 # fence
            if ck is not None:
                ck.save(state, epoch=r + 1, step_in_epoch=0)
                enq.append(ck.last_enqueue_ms)
            ms.append((time.perf_counter() - t0) * 1000 / n_steps)
        t0 = time.perf_counter()
        if ck is not None:
            ck.close()
        drain = (time.perf_counter() - t0) * 1000
        shutil.rmtree(d, ignore_errors=True)
        step_ms = statistics.median(ms)
        rows.append({
            "mode": mode, "step_ms": round(step_ms, 4),
            "steps_per_sec": round(1000 / step_ms, 1),
            "enqueue_ms_mean": (round(statistics.mean(enq), 2)
                                if enq else None),
            "enqueue_ms_max": round(max(enq), 2) if enq else None,
            "drain_ms": round(drain, 2) if ck is not None else None,
            "saves": len(enq)})
    base = rows[0]["steps_per_sec"]
    for r in rows:
        r["steps_dip_pct"] = round(100 * (1 - r["steps_per_sec"] / base), 2)
    by_mode = {r["mode"]: r for r in rows}
    art = {
        "metric": "ckpt_sharded_steps_dip_pct",
        "value": by_mode["sharded"]["steps_dip_pct"],
        "unit": "% steps/s lost to sharded-manifest epoch saves vs no "
                "checkpointing (tiny MLP, k=8)",
        "detail": {
            "device": jax.devices()[0].device_kind,
            "n_devices": jax.device_count(),
            "model": "mlp", "global_batch": cfg.batch_size,
            "k": k, "n_steps": n_steps, "saves_per_mode": repeats,
            "rows": rows,
        },
    }
    with open(out_path, "w") as f:
        json.dump(art, f, indent=1)
    print(json.dumps(art))
    return art


# -------------------------------------------------------------- serve sweep


def run_serve_sweep(out_path: str, requests: int = 32,
                    max_new: int = 16, rate: float = 200.0) -> dict:
    """The serving row: decode-throughput curve over the decode_k ladder
    × KV layouts on the serve probe harness (tpudist.serve.tune — full
    occupancy, compiled superstep, same measurement the serve autotuner
    trusts), then ONE real continuous-batching run at the sweep's best
    point for the latency numbers only the request clock can produce:
    p50/p99 TTFT, inter-token latency, tokens/s/chip, and the SLO
    verdict. BENCH_SERVE.json on the shared artifact shape."""
    from tpudist.parallel import build_mesh
    from tpudist.serve import scheduler as sched
    from tpudist.serve import slo as slo_lib
    from tpudist.serve import tune as serve_tune
    from tpudist.serve.engine import (PagedServeEngine, ServeEngine,
                                      init_params)

    model_cfg = ModelConfig(name="transformer", vocab_size=256,
                            n_layers=2, d_model=64, n_heads=4,
                            n_kv_heads=2, d_ff=128, max_seq_len=64)
    slots, max_seq, prompt_pad = 4, 64, 16
    mesh = build_mesh(ParallelConfig())
    params = init_params(model_cfg, mesh, seed=0)

    rows = []
    for layout in ("st", "hs"):
        for k in (1, 8, 32):
            res = serve_tune.probe_candidate(
                model_cfg, mesh, params,
                serve_tune.ServeCandidate(decode_k=k, layout=layout),
                slots=slots, max_seq=max_seq, prompt_pad=prompt_pad)
            rows.append({"decode_k": k, "layout": layout,
                         "feasible": res.feasible,
                         "tokens_per_sec": round(res.tokens_per_sec, 2),
                         # inf dispatch_ms (pruned point) must not leak
                         # a bare `Infinity` token into the JSON
                         "dispatch_ms": (round(res.dispatch_ms, 4)
                                         if res.feasible else None),
                         "spread": round(res.spread, 4),
                         **({"error": res.error} if res.error else {})})
            print(json.dumps(rows[-1]))
    feasible = [r for r in rows if r["feasible"]]
    if not feasible:
        raise SystemExit(
            "serve sweep: every (decode_k, layout) point was infeasible "
            "on this device — see the per-point errors above; no "
            "BENCH_SERVE.json written")
    best = max(feasible, key=lambda r: r["tokens_per_sec"])

    engine = ServeEngine(model_cfg, mesh, slots=slots, max_seq=max_seq,
                         prompt_pad=prompt_pad,
                         decode_k=best["decode_k"],
                         layout=best["layout"])
    engine.warmup(params)
    reqs = sched.make_requests(requests, prompt_pad=prompt_pad,
                               vocab_size=model_cfg.vocab_size,
                               max_new=max_new, rate=rate, seed=0)
    summary = sched.run_serve(engine, params, reqs)
    engine.assert_two_programs()

    # Fixed-HBM dense-vs-paged pair: the tentpole's headline number.
    # Size the paged pool to STRICTLY FEWER KV bytes than the dense
    # cache (pool + trash page + page table vs slots×max_seq), then
    # drive both with the same shared-prefix load — the paged engine
    # must sustain strictly more concurrent sequences inside the
    # smaller footprint (one prefix page serves every slot; tails only
    # allocate pages they reach).
    pair_rows = []
    prefix_len, pair_reqs, pair_rate = 8, 24, 500.0
    # seed must match the pair stream below — the scheduler byte-checks
    # each prompt against the registered prefix before sharing pages
    shared = sched.shared_prefix_tokens(prefix_len,
                                        model_cfg.vocab_size, seed=1)
    for mode, eng in (
            ("dense", ServeEngine(
                model_cfg, mesh, slots=slots, max_seq=max_seq,
                prompt_pad=prompt_pad, decode_k=8, layout="st")),
            ("paged", PagedServeEngine(
                model_cfg, mesh, slots=2 * slots, max_seq=max_seq,
                prompt_pad=prompt_pad, decode_k=8, page_tokens=8,
                pages=30))):
        eng.warmup(params)
        rs = sched.make_requests(pair_reqs, prompt_pad=prompt_pad,
                                 vocab_size=model_cfg.vocab_size,
                                 max_new=max_new, rate=pair_rate,
                                 seed=1, prefix_len=prefix_len)
        summ = sched.run_serve(eng, params, rs, shared_prefix=shared)
        eng.assert_two_programs()
        pair_rows.append({
            "mode": mode, "slots": eng.slots,
            "kv_cache_bytes": eng.spec.bytes,
            "active_slots_peak": summ["active_slots_peak"],
            "completed": summ["completed"],
            "tokens_per_sec": summ["tokens_per_sec"],
            "kv_pages_used_peak": summ["kv_pages_used_peak"],
            "shared_prefix_len": summ["shared_prefix_len"]})
        print(json.dumps(pair_rows[-1]))
    dense_row, paged_row = pair_rows
    if paged_row["kv_cache_bytes"] >= dense_row["kv_cache_bytes"]:
        raise SystemExit(
            "serve sweep: paged KV footprint must be strictly smaller "
            f"than dense ({paged_row['kv_cache_bytes']} vs "
            f"{dense_row['kv_cache_bytes']} bytes)")
    if paged_row["active_slots_peak"] <= dense_row["active_slots_peak"]:
        raise SystemExit(
            "serve sweep: paged engine must sustain strictly more "
            "concurrent slots than dense at fixed HBM "
            f"({paged_row['active_slots_peak']} vs "
            f"{dense_row['active_slots_peak']})")

    art = {
        "metric": "serve_tokens_per_sec_per_chip",
        "value": summary["tokens_per_sec_per_chip"],
        "unit": "tokens/s/chip (continuous batching, greedy decode)",
        "detail": {
            "device": jax.devices()[0].device_kind,
            "n_devices": jax.device_count(),
            "model": "transformer", "slots": slots,
            "max_seq": max_seq, "prompt_pad": prompt_pad,
            "request_rate": rate,
            "sweep_rows": rows,
            "selected": {"decode_k": best["decode_k"],
                         "layout": best["layout"]},
            **{k: summary.get(k) for k in (
                "requests", "completed", "generated_tokens",
                "truncated", "wall_s", "dispatches", "tokens_per_sec",
                "queue_depth_max", "queue_depth_mean", "ttft_p50_s",
                "ttft_p99_s", "itl_p50_s", "itl_p99_s", "e2e_p50_s",
                "e2e_p99_s", "prefill_compiles", "decode_compiles")},
            "kv_cache_bytes": engine.spec.bytes,
            "paged_pair": pair_rows,
        },
        "slo": slo_lib.slo_block(summary),
    }
    with open(out_path, "w") as f:
        json.dump(art, f, indent=1)
    print(json.dumps({k: art[k] for k in ("metric", "value", "unit")}
                     | {"slo": art["slo"]["status"]}))
    return art


# ----------------------------------------------------------- overlap sweep


def _overlap_capture_exposed(run_once, tag: str) -> tuple:
    """``(exposed_comm_frac, exposed_comm_s)`` of ONE profiled epoch of
    ``run_once`` (obs.devtime's interval math over the jax.profiler
    capture — the SAME analysis the --profile-window train path grades
    with, so the bench's number and the run report's number are one
    measurement)."""
    import shutil
    import tempfile

    from tpudist.obs import devtime as devtime_lib
    cap = tempfile.mkdtemp(prefix=f"tpudist_ov_{tag}_")
    jax.profiler.start_trace(cap)
    run_once()
    jax.profiler.stop_trace()
    pod = devtime_lib.analyze_capture(cap)["pod"]
    shutil.rmtree(cap, ignore_errors=True)
    return pod["exposed_comm_frac"] or 0.0, pod["exposed_comm_s"]


def run_overlap_sweep(out_path: str, n_steps: int = 16, repeats: int = 2,
                      k: int = 1, rounds: int = 5) -> dict:
    """The overlap-plane artifact, BENCH_OVERLAP.json: (a) the DP
    gradient all-reduce schedule — barrier baseline vs bucketed overlap
    across bucket sizes, steps/s + devtime-measured exposed-comm
    fraction + BITWISE loss parity, on the 2-slice scripted DCN mapping
    (TPUDIST_SLICE_MAP, mesh.axis_fabric labels the data axis "dcn");
    (b) the pipeline schedule — GPipe vs interleaved-1F1B steps/s at
    S=4, M=8 with loss parity and the analytic bubble model per row.
    Headline = bucketed/barrier steps/s at the best bucketed point.

    Measurement honesty, hard-won: (1) both halves warm EVERY cell
    before timing any and interleave timed rounds across cells —
    sequential cell timing hands the first (baseline) cell the
    process's ~30% cold-start cost and manufactures phantom wins; (2)
    the DP half measures at k=1 (inside a k-step superstep scan the
    NEXT step's forward overlaps the trailing reduces in EITHER mode —
    a superstep property, not a schedule property; the superstep x
    overlap composition is pinned in tests/test_overlap.py); (3) on
    this CPU backend the two DP schedules then measure within noise —
    profiling serializes the overlapped concurrency (the capture
    cannot see what it grades) and the merged per-host track lets
    replica skew cover either schedule — so the DP rows are recorded
    diagnostics while the CI-asserted DP evidence is deterministic:
    bitwise loss parity + the lowered programs' barrier structure
    (detail.program), the property that stops the collective combiner
    re-fusing the reduces on the hardware backends where the wall win
    lives. The pipeline half IS a fair measured win (~1.1x at S=4,
    M=8)."""
    import dataclasses

    from tpudist.parallel import build_mesh
    from tpudist.parallel import mesh as mesh_lib
    from tpudist.tune import probe

    # the scripted 2-slice DCN stand-in: labeling only, program
    # unchanged (mesh.slice_assignment). Explicit env wins.
    os.environ.setdefault("TPUDIST_SLICE_MAP", "2")
    n_dev = jax.device_count()

    # ---- pipeline half: GPipe vs interleaved at S=4, M=8 ----
    # (runs FIRST: the DP half's runners hold several hundred MB of
    # state + staged epochs, and allocator pressure measurably drags
    # the pipeline cells when they run second)
    pp_rows = []
    if n_dev >= 4:
        # activation-heavy, param-light: the interleaved schedule's win
        # is the (S-1)(1-1/v) bubble slots of layer compute it removes,
        # while its cost is per-slot param traffic (chunk select + the
        # slot scan's carried layer-grad accumulation) — so tokens per
        # microbatch must dominate param bytes for the bubble cut to
        # show as wall clock on CPU (on TPU the same ratio comes free:
        # MXU compute dwarfs HBM param reads at real model sizes)
        pmodel = ModelConfig(name="transformer", vocab_size=128,
                             n_layers=8, d_model=128, n_heads=4,
                             n_kv_heads=4, d_ff=512, max_seq_len=64)
        S, M = 4, 8
        pcfg = TrainConfig(batch_size=32, lr=1e-3, seed=0, model=pmodel,
                           pp_microbatches=M,
                           data=DataConfig(n_samples=32),
                           parallel=ParallelConfig(data=1, pipe=S))
        pmesh = build_mesh(pcfg.parallel, devices=jax.devices()[:S])
        toks = data.make_synthetic_tokens(pcfg.batch_size,
                                          pmodel.max_seq_len + 1,
                                          pmodel.vocab_size, seed=0)
        from tpudist.parallel import sharding as shd
        pcells = {}
        # build + compile + warm BOTH schedules before timing either
        for v in (1, 2):
            cfg = dataclasses.replace(pcfg, pipeline_interleave=v)
            state = engine.init_state(jax.random.PRNGKey(0), cfg, pmesh)
            step = engine.make_train_step(cfg, pmesh)
            batch_t = shd.put_batch(pmesh, (toks,))
            for _ in range(2):
                state, loss = step(state, batch_t)
            jax.device_get(loss)
            # parity pin: one fresh-step loss per schedule
            fstate = engine.init_state(jax.random.PRNGKey(0), cfg, pmesh)
            _, floss = step(fstate, batch_t)
            pcells[v] = [step, state, batch_t, [],
                         float(jax.device_get(floss))]
        # timed rounds interleaved across the two schedules
        for _ in range(max(repeats, 3)):
            for v, c in pcells.items():
                t0 = time.perf_counter()
                for _ in range(4):
                    c[1], loss = c[0](c[1], c[2])
                jax.device_get(loss)
                c[3].append((time.perf_counter() - t0) * 1000 / 4)
        for v, c in pcells.items():
            ms = statistics.median(c[3])
            pp_rows.append({
                "schedule": "gpipe" if v == 1 else "interleaved",
                "interleave": v, "stages": S, "microbatches": M,
                "bubble_model": round((S - 1) / (v * M + S - 1), 4),
                "step_ms": round(ms, 4),
                "steps_per_sec": round(1000 / ms, 2),
                "first_step_loss": c[4]})
            print(json.dumps(pp_rows[-1]))
        del pcells

    # ---- DP half: param-heavy little transformer, pure-DP mesh ----
    # Shape chosen comm-forward (wide layers, short sequences, 1 row
    # per device): the gradient all-reduce must be a visible fraction
    # of the device window (~10% exposed at the barrier baseline here)
    # or the schedule comparison measures profiler noise. ~21 MB of
    # f32 grads over 8 stacked-layer leaves + embed.
    model = ModelConfig(name="transformer", vocab_size=256, n_layers=8,
                        d_model=384, n_heads=4, n_kv_heads=4, d_ff=768,
                        max_seq_len=16)
    base = TrainConfig(batch_size=n_dev, lr=1e-3, seed=0,
                       model=model,
                       data=DataConfig(n_samples=n_steps * n_dev),
                       parallel=ParallelConfig(data=-1))
    mesh = build_mesh(base.parallel)
    fabric = mesh_lib.data_fabric(mesh)
    plan = data.plan_epoch(
        (data.make_synthetic_tokens(base.batch_size * n_steps,
                                    model.max_seq_len + 1,
                                    model.vocab_size, base.data.seed),),
        batch_size=base.batch_size, seed=base.seed, epoch=0)

    cells = [("off", None)] + [("bucketed", mb) for mb in (1.0, 4.0)]
    runners = {}
    # phase 1 — build, compile, warm EVERY cell before any timing:
    # the process's first epochs run cold (allocator growth, code
    # caches — tune.probe's documented ~30% first-trial bias), and the
    # baseline cell measuring first would wear all of it
    for mode, mb in cells:
        cfg = dataclasses.replace(base, grad_overlap=mode,
                                  grad_bucket_mb=mb)
        runner = probe.EpochRunner(cfg, mesh, k, plan, n_steps)
        state = runner.init_state()
        state, loss = runner.run_epoch(state)   # trace + compile + warm
        jax.device_get(loss)
        # a fresh state for the parity pin: every cell's first-epoch
        # loss from the identical init must agree BITWISE (the overlap
        # modes are schedule-only — parallel.overlap)
        pstate = runner.init_state()
        pstate, ploss = runner.run_epoch(pstate)
        # ploss is the last superstep's per-step loss vector (n_steps is
        # a k-multiple here, so its last entry is a real step's loss)
        loss_bits = float(jax.device_get(ploss).ravel()[-1])
        runners[(mode, mb)] = [runner, state, [], loss_bits, []]
    # phase 2 — timed epochs INTERLEAVED across cells (the staging
    # sweep's drift-cancelling discipline): each round times every cell
    # back-to-back so host-load drift hits all modes of a round equally
    # instead of biasing whichever cell ran later
    for _ in range(max(repeats, 3)):
        for key in runners:
            r = runners[key]
            t0 = time.perf_counter()
            s, loss = r[0].run_epoch(r[1])
            jax.device_get(loss)
            r[1] = s
            r[2].append((time.perf_counter() - t0) * 1000 / n_steps)
    # phase 3 — capture rounds, same interleaving
    for _ in range(rounds):
        for key in runners:
            r = runners[key]

            def once(r=r):
                s, loss = r[0].run_epoch(r[1])   # donates the state
                r[1] = s
                jax.device_get(loss)
            r[4].append(_overlap_capture_exposed(
                once, f"{key[0]}_{key[1]}"))
    rows = []
    for (mode, mb), (runner, _, times, loss_bits, caps) in \
            runners.items():
        ms = statistics.median(times)
        traces = getattr(runner.dispatch_fn, "traces", None)
        fracs = [c[0] for c in caps]
        # captured exposure rides the rows as a labeled DIAGNOSTIC, not
        # the headline: profiling the CPU thunk runtime serializes the
        # very concurrency the bucketed schedule buys (measured: the
        # bucketed cell's captured window runs at the barrier cell's
        # pace while its un-profiled step time is ~1.3x faster), so
        # under the profiler the two schedules read alike. The honest
        # CPU-measurable signal is the un-profiled wall clock below;
        # per-device TPU tracks don't share the observer effect.
        per_step_ms = [1e3 * c[1] / n_steps for c in caps]
        rows.append({"mode": mode, "grad_bucket_mb": mb,
                     "fabric": fabric,
                     "step_ms": round(ms, 4),
                     "steps_per_sec": round(1000 / ms, 1),
                     "superstep_compiles": (len(traces)
                                            if traces is not None
                                            else None),
                     "first_epoch_loss": loss_bits,
                     "exposed_comm_frac": round(
                         statistics.median(fracs), 5),
                     "exposed_comm_frac_reps": [round(f, 5)
                                                for f in fracs],
                     "exposed_comm_ms_per_step": round(
                         statistics.median(per_step_ms), 4),
                     "exposed_comm_ms_per_step_reps": [
                         round(x, 4) for x in per_step_ms]})
        print(json.dumps(rows[-1]))
    off_row = rows[0]
    best = max(rows[1:], key=lambda r: r["steps_per_sec"])
    reduction = round(best["steps_per_sec"] / off_row["steps_per_sec"],
                      4)

    # the DETERMINISTIC schedule evidence (what CPU wall clock cannot
    # adjudicate — fair interleaved timing measures the two schedules
    # within ±3% here, sign unstable): the lowered programs must carry
    # the structure the modes promise — off barriers every grad leaf
    # once; bucketed threads one barrier per chain link, which is what
    # stops the collective combiner re-fusing the reduces into the
    # trailing all-reduce on hardware backends
    def _lowered_text(cfg):
        from jax.sharding import PartitionSpec as P

        from tpudist.parallel import sharding as shd
        from tpudist.utils import compat
        state = engine.init_state(jax.random.PRNGKey(0), cfg, mesh)
        body, _, _ = engine._build_step_body(cfg, mesh)

        def jitted(st, batch):
            bspecs = jax.tree.map(
                lambda x: shd.batch_spec(x.ndim), batch)
            return compat.shard_map(body, mesh=mesh,
                                    in_specs=(P(), bspecs),
                                    out_specs=(P(), P()),
                                    check_vma=False)(st, batch)
        batch = jax.tree.map(lambda a: a[0], plan.slab(0, 1))
        staged = shd.put_batch(mesh, batch)
        return jax.jit(jitted).lower(state, staged).as_text()

    def _barrier_count(mode, mb):
        return _lowered_text(dataclasses.replace(
            base, grad_overlap=mode,
            grad_bucket_mb=mb)).count("optimization_barrier")
    program = {
        "off_barriers": _barrier_count("off", None),
        "bucketed_barrier_chain": _barrier_count(
            "bucketed", best["grad_bucket_mb"]),
    }

    # ---- cross-slice half: flat vs hierarchical per slice count ----
    # Same honesty discipline as the DP half (warm all cells, then
    # interleave timed rounds), and the same division of labor: steps/s
    # rides as a no-regression diagnostic (on CPU both schedules run the
    # same reduction work; the hierarchical win is DCN byte volume, which
    # only hardware wall clock can convert to time) while the asserted
    # evidence is program-derived — per-step DCN bytes from the lowered
    # StableHLO must shrink by exactly the slice size.
    from tpudist.obs import devtime as devtime_lib
    xs_rows = []
    xs_cells = {}
    slice_counts = [s for s in (2, 4, 8)
                    if s <= n_dev and n_dev % s == 0]
    for n_slices in slice_counts:
        os.environ["TPUDIST_SLICE_MAP"] = str(n_slices)
        device_slices = mesh_lib.mesh_device_slices(mesh)
        for cross in ("flat", "hierarchical"):
            cfg = dataclasses.replace(base, grad_overlap="bucketed",
                                      grad_bucket_mb=4.0,
                                      cross_slice=cross)
            runner = probe.EpochRunner(cfg, mesh, k, plan, n_steps)
            state = runner.init_state()
            # compile + warm; the warm epoch runs from fresh init, so
            # its loss doubles as the parity value
            state, loss = runner.run_epoch(state)
            loss_bits = float(jax.device_get(loss).ravel()[-1])
            coll = devtime_lib.collective_bytes(_lowered_text(cfg),
                                                device_slices)
            print(json.dumps({"cell": [n_slices, cross],
                              "first_epoch_loss": loss_bits,
                              "dcn_bytes_per_step":
                                  coll["dcn_bytes_total"]}))
            xs_cells[(n_slices, cross)] = [runner, state, [], loss_bits,
                                           coll]
    os.environ["TPUDIST_SLICE_MAP"] = "2"   # the sweep's scripted map
    for _ in range(max(repeats, 3)):
        for key in xs_cells:
            r = xs_cells[key]
            t0 = time.perf_counter()
            s, loss = r[0].run_epoch(r[1])
            jax.device_get(loss)
            r[1] = s
            r[2].append((time.perf_counter() - t0) * 1000 / n_steps)
    for (n_slices, cross), (_, _, times, loss_bits, coll) in \
            xs_cells.items():
        ms = statistics.median(times)
        xs_rows.append({
            "n_slices": n_slices, "slice_size": n_dev // n_slices,
            "cross_slice": cross,
            "step_ms": round(ms, 4),
            "steps_per_sec": round(1000 / ms, 1),
            "first_epoch_loss": loss_bits,
            "dcn_bytes_per_step": coll["dcn_bytes_total"],
            "ici_bytes_per_step": coll["ici_bytes_total"],
            "n_collectives": coll["n_collectives"]})
        print(json.dumps(xs_rows[-1]))
    for n_slices in slice_counts:
        flat_r = next(r for r in xs_rows
                      if r["n_slices"] == n_slices
                      and r["cross_slice"] == "flat")
        hier_r = next(r for r in xs_rows
                      if r["n_slices"] == n_slices
                      and r["cross_slice"] == "hierarchical")
        slice_size = n_dev // n_slices
        if flat_r["first_epoch_loss"] != hier_r["first_epoch_loss"]:
            raise SystemExit(
                "overlap sweep: hierarchical loss must match flat "
                f"bitwise at {n_slices} slices "
                f"({hier_r['first_epoch_loss']} vs "
                f"{flat_r['first_epoch_loss']})")
        ratio = flat_r["dcn_bytes_per_step"] / hier_r["dcn_bytes_per_step"]
        # exact when slice_size divides every bucket's element count
        # (it does for this model); the loss all-reduce's 4-byte payload
        # rides both sides, hence the sliver of tolerance
        if slice_size > 1 and abs(ratio - slice_size) > 0.02 * slice_size:
            raise SystemExit(
                "overlap sweep: hierarchical DCN bytes must be "
                f"flat/slice_size at {n_slices} slices (ratio {ratio:.4f}"
                f" vs slice_size {slice_size})")
        if hier_r["steps_per_sec"] < 0.7 * flat_r["steps_per_sec"]:
            raise SystemExit(
                "overlap sweep: hierarchical steps/s regressed beyond "
                f"the CPU noise floor at {n_slices} slices "
                f"({hier_r['steps_per_sec']} vs "
                f"{flat_r['steps_per_sec']})")

    art = {
        "metric": "grad_overlap_steps_ratio",
        "value": reduction,
        "unit": "bucketed steps/s / barrier-baseline steps/s at "
                "bitwise-identical loss (4-dev CPU mesh, scripted "
                "2-slice DCN map; captured exposure rides the rows)",
        "detail": {
            "device": jax.devices()[0].device_kind,
            "n_devices": n_dev,
            "model": "transformer", "global_batch": base.batch_size,
            "k": k, "n_steps": n_steps,
            "slice_map": os.environ.get("TPUDIST_SLICE_MAP"),
            "data_axis_fabric": fabric,
            "rows": rows,
            "best_bucket_mb": best["grad_bucket_mb"],
            "program": program,
            "exposed_comm_frac_drop": round(
                off_row["exposed_comm_frac"]
                - best["exposed_comm_frac"], 5),
            "loss_bitwise_identical": all(
                r["first_epoch_loss"] == off_row["first_epoch_loss"]
                for r in rows),
            "one_compile_per_cell": all(
                r["superstep_compiles"] in (None, 1) for r in rows),
            "steps_ratio_best_vs_off": round(
                best["steps_per_sec"] / off_row["steps_per_sec"], 4),
            "cross_slice_rows": xs_rows,
            "cross_slice_loss_bitwise_identical": all(
                r["first_epoch_loss"] == off_row["first_epoch_loss"]
                for r in xs_rows),
            "pipeline_rows": pp_rows,
            **({"pipeline_interleaved_vs_gpipe_steps_ratio": round(
                    pp_rows[1]["steps_per_sec"]
                    / pp_rows[0]["steps_per_sec"], 4),
                "pipeline_loss_bitwise_identical": (
                    pp_rows[0]["first_step_loss"]
                    == pp_rows[1]["first_step_loss"])}
               if len(pp_rows) == 2 else {}),
        },
    }
    with open(out_path, "w") as f:
        json.dump(art, f, indent=1)
    print(json.dumps({k_: art[k_] for k_ in ("metric", "value", "unit")}))
    return art


# --------------------------------------------------------- collective sweep


def run_collective_sweep(out_path: str, kinds: str, min_mb: float,
                         max_mb: float, iters: int) -> dict:
    """Promote the collective sweep to a first-class artifact:
    BENCH_COLLECTIVES.json (an original BASELINE.json north-star
    artifact that never existed) — per-kind per-size bus GB/s and % of
    ring peak, each axis labeled ICI vs DCN from the mesh, on the same
    harness shape as the other BENCH_* files. ``tpudist.bench.sweep``
    does the measuring (and stays the launcher's GATE); this wrapper
    only shapes and writes the artifact, so the two never drift."""
    from tpudist.bench import sweep as sweep_mod
    records = sweep_mod.run_sweep(tuple(kinds.split(",")), "data",
                                  min_mb=min_mb, max_mb=max_mb,
                                  iters=iters)
    if jax.process_index() == 0:
        art = sweep_mod.write_collectives_artifact(records, out_path)
    else:
        art = sweep_mod.collectives_artifact(records)
    print(json.dumps({k: art[k] for k in ("metric", "value", "unit")}))
    return art


# ------------------------------------------------------------- chaos drill


def run_chaos_drill(out_path: str) -> dict:
    """The recovery-under-fault headline: drive the seeded fault matrix
    (tpudist.chaos — seven families, policy → requeue → resume against
    the real CLI) and write BENCH_CHAOS.json on the BENCH_* harness
    shape. The measurement half is the invariant checker's report: how
    many families ended green, with per-family resume/goodput facts in
    the detail block. The drill driver is jax-free; only its
    subprocesses touch devices, so this wrapper stays a thin shaper
    like the collective sweep's (chaos.verify owns the orchestration
    and the artifact shape — one source for the CLI, this flag and
    selfcheck)."""
    from tpudist.chaos import verify as chaos_verify

    art = chaos_verify.bench_artifact(chaos_verify.run_and_verify())
    with open(out_path, "w") as f:
        json.dump(art, f, indent=1)
    print(json.dumps({k: art[k] for k in ("metric", "value", "unit")}))
    return art


# ----------------------------------------------------- serve chaos drill


def run_serve_chaos_drill(out_path: str) -> dict:
    """The serve-resilience headline: drive the scripted overload +
    serve fault matrix (tpudist.serve.drill — bounded-queue shedding
    with the arrival partition checked exactly, serve_kill → policy →
    requeue → resume with in-flight slots honestly lost, garbage
    rejection, straggler stall, adapt ladder) and write
    BENCH_SERVE_RESILIENCE.json on the BENCH_* harness shape. The
    measurement half is the jax-free verifier's report: how many
    scenarios ended green, with per-scenario shed/resume facts in the
    detail block. A thin shaper like run_chaos_drill — serve.drill
    owns the orchestration and artifact shape (one source for its CLI,
    this flag and selfcheck check_serve_resilience)."""
    from tpudist.serve import drill as serve_drill

    art = serve_drill.bench_artifact(serve_drill.run_and_verify())
    with open(out_path, "w") as f:
        json.dump(art, f, indent=1)
    print(json.dumps({k: art[k] for k in ("metric", "value", "unit")}))
    return art


# ------------------------------------------------------------------ matrix

# (model, seq, head, flash, per_chip[, remat]) — meaningful cells only:
#   * per-chip batch keeps tokens/step ≈ 28k as seq grows (the measured
#     plain-path plateau), 96 for the fused head (its reason to exist),
#     24 for no-flash at 512 (dense scores OOM above).
#   * chunked head (c4) rows cover the remaining LM-head strategy.
#   * no-flash rows measure the XLA fallback (dense at 512, blockwise at
#     2048/4096) — the CPU-test reference path's on-chip cost.
#   * one MoE row (8 experts, top-2, same backbone) at the dense plateau
#     batch; group size pre-tuned via --moe-group.
MATRIX_ROWS = [
    ("transformer", 512, "plain", True, 56, False),
    ("transformer", 512, "fused", True, 96, True),
    ("transformer", 512, "c4", True, 56, False),
    ("transformer", 512, "plain", False, 24, False),
    ("transformer", 2048, "plain", True, 12, False),
    ("transformer", 2048, "c4", True, 12, False),
    ("transformer", 2048, "plain", False, 12, False),
    ("transformer", 4096, "plain", True, 6, False),
    ("transformer", 4096, "c4", True, 6, False),
    ("transformer", 4096, "plain", False, 6, False),
    ("transformer", 8192, "plain", True, 3, False),
    # long-context frontier, batch 1-2 with the chunked head. No remat at
    # 16k: activations fit one v5e and remat cost 9 MFU points (41.2% vs
    # 50.1% measured r4)
    ("transformer", 16384, "c8", True, 2, False),
    ("transformer", 32768, "c16", True, 1, False),
    # 64/chip: the GQA plateau sits higher than dense's 56 (the compact
    # kv projections free HBM) — r5 measured 56→106.0k, 64→107.2k
    # (80.6% MFU), 72→102.6k (remat pressure returns)
    ("gqa", 512, "plain", True, 64, False),
    # compact-kv advantage grows with seq: 4x fewer kv-proj FLOPs and
    # kv-block ring/DMA bytes — beats dense at every matched seq
    ("gqa", 2048, "plain", True, 12, False),
    ("gqa", 4096, "plain", True, 6, False),
    ("gqa", 8192, "plain", True, 3, False),
    ("moe", 512, "plain", True, 32, False),
    ("moe", 512, "fused", True, 32, True),
    # r5 additions: the fused premium isolated at the plain row's batch
    # (no remat, no batch confound), and MoE coverage past seq 512
    ("transformer", 512, "fused", True, 56, False),
    ("moe", 2048, "plain", True, 8, False),
    ("moe_gqa", 512, "plain", True, 32, False),
    ("moe_cf1", 512, "plain", True, 32, False),
]


def run_cell(spec: str, iters: int, moe_group: int) -> None:
    """One matrix cell (subprocess entry): prints exactly one JSON line."""
    model, seq, head, flash, per_chip, remat = spec.split(":")
    seq, per_chip = int(seq), int(per_chip)
    flash, remat = flash == "1", remat == "1"
    label = (f"{model}/seq{seq}/{head}/"
             f"{'flash' if flash else 'noflash'}/b{per_chip}")
    base = {"config": label, "model": model, "seq": seq, "lm_head": head,
            "flash": flash, "remat": remat}
    try:
        cfg = build_cfg(seq=seq, per_chip=per_chip, head=head,
                        model=model, remat=remat, moe_group=moe_group)
        rec = {**base, **measure(cfg, iters=iters)}
    except Exception as e:   # OOM/compile failure is a result, not a crash
        rec = {**base, "error": f"{type(e).__name__}: {str(e)[:200]}"}
    print("MATRIX_CELL " + json.dumps(rec), flush=True)


def run_matrix(iters: int, out_path: str, moe_group: int) -> dict:
    """Each cell runs in a fresh subprocess: (a) a cell's OOM/compile crash
    cannot kill the sweep, and (b) env that must differ per cell
    (TPUDIST_NO_FLASH; the scoped-VMEM workaround below) is snapshotted at
    first PJRT use, so it cannot be changed within one process."""
    import subprocess
    import sys
    here = os.path.abspath(__file__)
    rows = []
    for model, seq, head, flash, per_chip, remat in MATRIX_ROWS:
        spec = (f"{model}:{seq}:{head}:{int(flash)}:{per_chip}:{int(remat)}")
        env = dict(os.environ)
        if flash:
            # an inherited escape-hatch var would silently bench the XLA
            # fallback under a "flash" label in the committed artifact
            env.pop("TPUDIST_NO_FLASH", None)
        else:
            env["TPUDIST_NO_FLASH"] = "1"
        rec = None
        try:
            r = subprocess.run(
                [sys.executable, here, "--cell", spec, "--iters",
                 str(iters), "--moe-group", str(moe_group)],
                env=env, capture_output=True, text=True, timeout=3000)
            for ln in r.stdout.splitlines():
                if ln.startswith("MATRIX_CELL "):
                    rec = json.loads(ln[len("MATRIX_CELL "):])
            tail = f"rc={r.returncode}: {(r.stderr or r.stdout)[-200:]}"
        except subprocess.TimeoutExpired:
            # a wedged cell must not lose the rows already measured
            tail = "timeout after 3000s"
        if rec is None:
            rec = {"config": spec, "model": model, "seq": seq,
                   "lm_head": head, "flash": flash, "remat": remat,
                   "error": f"cell subprocess {tail}"}
        print(json.dumps(rec), flush=True)
        rows.append(rec)
    art = {"matrix_version": 1, "rows": rows}
    with open(out_path, "w") as f:
        json.dump(art, f, indent=1)
    print(markdown_table(rows))
    return art


def markdown_table(rows) -> str:
    """README-ready table, regenerated from the artifact (single source)."""
    lines = ["| model | seq | LM head | attention | batch/chip | tok/s/chip "
             "| MFU % | step ms |",
             "|---|---|---|---|---|---|---|---|"]
    for r in rows:
        att = "flash" if r.get("flash") else "XLA fallback"
        if "error" in r:
            # raw error text contains newlines/'|' that break the table
            err = " ".join(r["error"].split()).replace("|", "/")[:40]
            lines.append(f"| {r['model']} | {r['seq']} | {r['lm_head']} | "
                         f"{att} | — | — | — | {err} |")
            continue
        lines.append(
            f"| {r['model']} | {r['seq']} | {r['lm_head']} | {att} | "
            f"{r['global_batch'] // r['n_devices']} | {r['tok_s_chip']:,} | "
            f"{r['mfu_pct']} | {r['step_time_ms']} |")
    return "\n".join(lines)


def main() -> None:
    from tpudist.utils import (maybe_enable_compilation_cache,
                               maybe_force_platform, tune_tpu)
    maybe_force_platform()
    tune_tpu()
    maybe_enable_compilation_cache()

    p = argparse.ArgumentParser()
    p.add_argument("--fused-xent", action="store_true",
                   help="bench the pallas fused LM-head variant")
    p.add_argument("--batch-per-chip", type=int, default=None)
    p.add_argument("--iters", type=int, default=60)
    p.add_argument("--matrix", action="store_true",
                   help="bench the full perf surface; write BENCH_MATRIX.json")
    p.add_argument("--dispatch-sweep", action="store_true",
                   help="bench superstep dispatch overhead (tiny MLP, "
                        "k=1/8/32); write BENCH_DISPATCH.json")
    p.add_argument("--dispatch-out", type=str, default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_DISPATCH.json"))
    p.add_argument("--staging-sweep", action="store_true",
                   help="bench full-epoch vs streamed double-buffered "
                        "staging (tiny MLP, k=32, over-budget dataset); "
                        "write BENCH_STAGING.json")
    p.add_argument("--staging-out", type=str, default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_STAGING.json"))
    p.add_argument("--memory-sweep", action="store_true",
                   help="compute the HBM ledger's bucket bytes for "
                        "dense-vs-paged KV and full-vs-streamed slab "
                        "residency (tpudist.obs.memledger arithmetic, "
                        "ledger-derived headroom columns); write "
                        "BENCH_MEMORY.json")
    p.add_argument("--memory-out", type=str, default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_MEMORY.json"))
    p.add_argument("--tune-sweep", action="store_true",
                   help="bench the measured-probe autotuner against the "
                        "dispatch sweep (heuristic-pick vs autotuned "
                        "steps/s, cache re-hit); write BENCH_TUNE.json")
    p.add_argument("--tune-out", type=str, default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_TUNE.json"))
    p.add_argument("--ckpt-sweep", action="store_true",
                   help="bench checkpoint save overhead (none vs "
                        "orbax-sync vs orbax-async vs elastic sharded "
                        "manifest): enqueue/drain ms + steps/s dip; "
                        "write BENCH_CKPT.json")
    p.add_argument("--ckpt-out", type=str, default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_CKPT.json"))
    p.add_argument("--serve-sweep", action="store_true",
                   help="bench the serving engine: decode_k × KV-layout "
                        "throughput curve on the serve probe harness + "
                        "one continuous-batching run at the best point "
                        "(TTFT/ITL percentiles, SLO verdict); write "
                        "BENCH_SERVE.json")
    p.add_argument("--serve-out", type=str, default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_SERVE.json"))
    p.add_argument("--overlap-sweep", action="store_true",
                   help="bench the overlap plane: DP gradient "
                        "all-reduce barrier-vs-bucketed (steps/s + "
                        "devtime exposed-comm frac across bucket "
                        "sizes, bitwise loss parity, scripted 2-slice "
                        "DCN labels) and GPipe-vs-interleaved pipeline "
                        "steps/s at S=4, M=8; write BENCH_OVERLAP.json")
    p.add_argument("--overlap-out", type=str, default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_OVERLAP.json"))
    p.add_argument("--collective-sweep", action="store_true",
                   help="sweep the collectives over the mesh's data "
                        "axis (ICI/DCN-labeled) and write "
                        "BENCH_COLLECTIVES.json — per-kind per-size bus "
                        "GB/s + %% of ring peak")
    p.add_argument("--collective-out", type=str, default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "BENCH_COLLECTIVES.json"))
    p.add_argument("--collective-kinds", type=str,
                   default="all_reduce,all_gather,reduce_scatter,"
                           "all_to_all,ppermute")
    p.add_argument("--collective-min-mb", type=float, default=1)
    p.add_argument("--collective-max-mb", type=float, default=1024)
    p.add_argument("--collective-iters", type=int, default=10)
    p.add_argument("--chaos-drill", action="store_true",
                   help="run the seeded fault-injection matrix "
                        "(tpudist.chaos: kill/hang/slow/corrupt/torn/"
                        "fs-error/telemetry-garbage against the real "
                        "CLI) and write BENCH_CHAOS.json — headline = "
                        "fault families ending green")
    p.add_argument("--chaos-out", type=str, default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_CHAOS.json"))
    p.add_argument("--serve-chaos-drill", action="store_true",
                   help="run the serve resilience matrix "
                        "(tpudist.serve.drill: 2x-overload shedding "
                        "with exact partition + bitwise determinism, "
                        "serve_kill->requeue->resume, request_garbage "
                        "rejection, serve_slow, adapt ladder) and "
                        "write BENCH_SERVE_RESILIENCE.json — headline "
                        "= resilience scenarios ending green")
    p.add_argument("--serve-chaos-out", type=str, default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "BENCH_SERVE_RESILIENCE.json"))
    p.add_argument("--cell", type=str, default=None,
                   help="internal: run one matrix cell "
                        "(model:seq:head:flash:per_chip:remat)")
    p.add_argument("--matrix-out", type=str, default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_MATRIX.json"))
    p.add_argument("--moe-group", type=int, default=256,
                   help="MoE routing group size for the matrix's moe rows "
                        "(dispatch einsum FLOPs scale linearly with it; "
                        "256 = r4 measured optimum on v5e)")
    args = p.parse_args()

    if args.cell:
        run_cell(args.cell, args.iters, args.moe_group)
        return
    if args.dispatch_sweep:
        run_dispatch_sweep(args.dispatch_out)
        return
    if args.staging_sweep:
        run_staging_sweep(args.staging_out)
        return
    if args.memory_sweep:
        run_memory_sweep(args.memory_out)
        return
    if args.tune_sweep:
        run_tune_sweep(args.tune_out)
        return
    if args.ckpt_sweep:
        run_ckpt_sweep(args.ckpt_out)
        return
    if args.serve_sweep:
        run_serve_sweep(args.serve_out)
        return
    if args.overlap_sweep:
        run_overlap_sweep(args.overlap_out)
        return
    if args.collective_sweep:
        run_collective_sweep(args.collective_out, args.collective_kinds,
                             args.collective_min_mb,
                             args.collective_max_mb,
                             args.collective_iters)
        return
    if args.chaos_drill:
        run_chaos_drill(args.chaos_out)
        return
    if args.serve_chaos_drill:
        run_serve_chaos_drill(args.serve_chaos_out)
        return
    if args.matrix:
        run_matrix(max(20, args.iters // 2), args.matrix_out, args.moe_group)
        return

    # 56/chip: measured plateau on v5e for the plain path with the
    # round-3 kernels (single-block flash specialisation, merged dq/dk/dv
    # backward, custom xent VJP): 40→93.5k, 48→95.4k, 52→95.9k, 56→96.2k,
    # 60→94.7k, 64→91.5k tok/s/chip. Beyond 56 XLA's rematerialisation
    # (driven by the f32 logits pair the plain head materialises) grows
    # faster than the batch amortisation — measured 31 ms/step of .remat
    # fusions at 56, and every explicit alternative (chunked head, fused
    # kernel, whole-layer remat) benched slower. The fused head removes
    # the logits tensor from HBM so it runs big-batch; pairing it with
    # remat keeps the backbone activations within HBM at batch 96.
    # with TPUDIST_NO_FLASH the dense-attention path peaks ~85k (48/chip).
    no_flash = bool(os.environ.get("TPUDIST_NO_FLASH"))
    per_chip = args.batch_per_chip or (
        96 if args.fused_xent else (24 if no_flash else 56))
    cfg = build_cfg(seq=512, per_chip=per_chip,
                    head="fused" if args.fused_xent else "plain",
                    remat=args.fused_xent)
    m = measure(cfg, iters=args.iters)

    prior = best_prior_bench()
    tok_s_chip = m["tok_s_chip"]
    print(json.dumps({
        "metric": "transformer_train_tokens_per_sec_per_chip",
        "value": tok_s_chip,
        "unit": "tokens/s/chip",
        "vs_baseline": round(tok_s_chip / prior, 4) if prior else 1.0,
        "detail": {
            **{k: m[k] for k in (
                "device", "n_devices", "global_batch", "seq_len", "n_params",
                "mfu_pct", "achieved_tflops_per_chip", "peak_tflops",
                "step_time_ms", "step_time_ms_min", "step_time_ms_max")},
            "lm_head": "fused_xent" if args.fused_xent else "plain",
            "steps_per_sec_per_chip": round(
                1000 / m["step_time_ms"] / m["n_devices"], 4),
            "prior_best_tok_s_chip": prior,
        },
    }))


if __name__ == "__main__":
    main()
