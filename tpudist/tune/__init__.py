"""tpudist.tune — the measured-probe autotuner.

`config.resolve_steps_per_dispatch` and `resolve_staging_budget_bytes`
GUESS the dispatch/staging operating point by static heuristic, and
BENCH_DISPATCH.json shows an order-of-magnitude steps/s spread (~9-12x
across rounds) between the best and worst guess on the same hardware. This package replaces the guess with a
measurement: short on-device trials of the *real* compiled superstep
(:mod:`probe`) over a bounded knob space — superstep length ``k``,
staging budget, ``remat``, ``grad_accum_steps`` — walked by a
deterministic coordinate search (:mod:`search`) and persisted in a
fingerprint-keyed JSON cache (:mod:`cache`) so the SECOND run of the
same (model, topology) costs zero probe trials, exactly like a warm XLA
compilation cache costs zero recompiles. The heuristics are not gone:
they are the search's START POINT, and the search never commits a point
that measures slower than them.

:func:`autotune` is the train loop's one entry: resolve mode
(``--autotune`` / ``TPUDIST_AUTOTUNE``), consult the cache, probe on a
miss, broadcast the committed point from the coordinator (measured
times differ per host — the commit must not), persist, and report a
``kind=tune`` metrics record plus the three-valued ``tuning_status``
for the verdict stream.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from tpudist import config as config_lib
from tpudist import verdict as verdict_lib
from tpudist.tune import cache as cache_mod
from tpudist.tune import probe as probe_mod
from tpudist.tune import search as search_mod
from tpudist.tune.search import Candidate

__all__ = ["Candidate", "TuneOutcome", "autotune", "cache_mod",
           "probe_mod", "search_mod"]

DEFAULT_TRIALS = 12

# wire order for the multi-host broadcast: index+1 is the enum value
CROSS_SLICE_ENUM = ("flat", "hierarchical")


@dataclasses.dataclass(frozen=True)
class TuneOutcome:
    """What the tuner decided and how it got there."""

    cfg: Any                      # TrainConfig with the commit folded in
    tuned: Candidate
    source: str                   # cache | probe | heuristic
    status: str                   # verdict SUCCESS/FAIL/UNGATEABLE
    trials: int                   # probe trials actually run
    pruned: int
    fingerprint: str
    cache_dir: str
    steps_per_sec: Optional[float] = None
    baseline_steps_per_sec: Optional[float] = None


def _heuristic_candidate(cfg, *, state_bytes: int = 0,
                         hbm_bytes: Optional[float] = None) -> Candidate:
    """The static heuristics' pick — the search's start point and the
    floor the commit may never fall below."""
    budget = config_lib.resolve_staging_budget_bytes(
        cfg, state_bytes=state_bytes, hbm_bytes=hbm_bytes)
    mode, bucket_bytes = config_lib.resolve_grad_overlap(cfg)
    return Candidate(
        k=config_lib.resolve_steps_per_dispatch(cfg),
        staging_budget_mb=(None if budget is None
                           else round(budget / 2**20, 4)),
        remat=cfg.remat, grad_accum_steps=cfg.grad_accum_steps,
        grad_bucket_mb=(round(bucket_bytes / 2**20, 4)
                        if mode == "bucketed" else None),
        pipeline_interleave=config_lib.resolve_pipeline_interleave(cfg),
        cross_slice=config_lib.resolve_cross_slice(cfg))


def _sync_candidate(cand: Optional[Candidate],
                    hit: bool) -> tuple[Optional[Candidate], bool]:
    """Multi-host agreement: the coordinator's (cache-hit?, candidate)
    decision is broadcast so every process dispatches the same programs —
    a cache file present on one host but not another, or per-host timing
    jitter in the probes, must not fork the pod. No-op single-process."""
    import jax
    if jax.process_count() == 1:
        return cand, hit
    import numpy as np
    from jax.experimental import multihost_utils
    enc = np.asarray([
        1.0 if hit else 0.0,
        1.0 if cand is not None else 0.0,
        float(cand.k if cand else 0),
        -1.0 if (cand is None or cand.staging_budget_mb is None)
        else float(cand.staging_budget_mb),
        1.0 if (cand and cand.remat) else 0.0,
        float(cand.grad_accum_steps if cand else 0),
        -1.0 if (cand is None or cand.grad_bucket_mb is None)
        else float(cand.grad_bucket_mb),
        float(cand.pipeline_interleave if cand else 0),
        # cross_slice enum: 0 = None, 1 = flat, 2 = hierarchical
        0.0 if (cand is None or cand.cross_slice is None)
        else float(1 + CROSS_SLICE_ENUM.index(cand.cross_slice)),
    ], np.float64)
    dec = multihost_utils.broadcast_one_to_all(enc)
    if dec[1] < 0.5:
        return None, bool(dec[0] > 0.5)
    return Candidate(
        k=int(dec[2]),
        staging_budget_mb=(None if dec[3] < 0 else float(dec[3])),
        remat=bool(dec[4] > 0.5),
        grad_accum_steps=int(dec[5]),
        grad_bucket_mb=(None if dec[6] < 0 else float(dec[6])),
        pipeline_interleave=int(dec[7]),
        cross_slice=(None if int(dec[8]) == 0
                     else CROSS_SLICE_ENUM[int(dec[8]) - 1])
    ), bool(dec[0] > 0.5)


def _sync_result(res: "probe_mod.ProbeResult") -> "probe_mod.ProbeResult":
    """Multi-host agreement at TRIAL granularity: every search decision
    (plateau pick, early stop, budget count) is a threshold on measured
    numbers, and per-host wall clocks differ by enough to land on
    opposite sides of a threshold — which would fork the deterministic
    trial sequence and deadlock the next probe's collectives. Broadcast
    the coordinator's measurement so every host feeds the search
    identical inputs. No-op single-process."""
    import jax
    if jax.process_count() == 1:
        return res
    import numpy as np
    from jax.experimental import multihost_utils
    enc = np.asarray([1.0 if res.feasible else 0.0, res.steps_per_sec,
                      res.step_ms, res.spread], np.float64)
    dec = multihost_utils.broadcast_one_to_all(enc)
    return dataclasses.replace(
        res, feasible=bool(dec[0] > 0.5), steps_per_sec=float(dec[1]),
        step_ms=float(dec[2]), spread=float(dec[3]))


def autotune(cfg, mesh, plan, *, mode: str, metrics: Any = None,
             is_coordinator: bool = True, state_bytes: int = 0,
             hbm_bytes: Optional[float] = None,
             n_steps: Optional[int] = None,
             repeats: int = probe_mod.DEFAULT_PROBE_REPEATS) -> TuneOutcome:
    """Resolve this run's operating point per ``mode`` (``probe`` |
    ``cache-only``): cache hit → committed with zero trials; miss under
    ``probe`` → measured search; miss under ``cache-only`` (or a probing
    failure) → the heuristics, honestly labeled. ``plan`` is epoch 0's
    :class:`~tpudist.data.EpochPlan` — probes consume the run's own
    first batches, so trial shapes are the real shapes."""
    start = _heuristic_candidate(cfg, state_bytes=state_bytes,
                                 hbm_bytes=hbm_bytes)
    cache_dir = config_lib.resolve_autotune_cache_dir(cfg)
    fp = cache_mod.fingerprint(cfg, mesh)
    trials_budget = config_lib.resolve_autotune_trials(cfg)
    probe_steps = (probe_mod.DEFAULT_PROBE_STEPS
                   if n_steps is None else int(n_steps))

    tuned: Optional[Candidate] = None
    hit = False
    rec = cache_mod.load(cache_dir, fp) if is_coordinator else None
    if rec is not None:
        t = rec["tuned"]
        tuned = Candidate(k=int(t["k"]),
                          staging_budget_mb=t["staging_budget_mb"],
                          remat=bool(t["remat"]),
                          grad_accum_steps=int(t["grad_accum_steps"]),
                          grad_bucket_mb=t.get("grad_bucket_mb"),
                          pipeline_interleave=int(
                              t.get("pipeline_interleave") or 0),
                          cross_slice=t.get("cross_slice"))
        hit = True
    tuned, hit = _sync_candidate(tuned, hit)
    if hit and tuned is not None:
        try:   # defensive: a cached k must still satisfy the constraints
            config_lib.resolve_steps_per_dispatch(tuned.apply(cfg))
        except ValueError:
            tuned, hit = None, False
    if hit and tuned is not None:
        sps = rec.get("steps_per_sec") if rec else None
        base = rec.get("baseline_steps_per_sec") if rec else None
        out = TuneOutcome(cfg=tuned.apply(cfg), tuned=tuned,
                          source="cache",
                          status=verdict_lib.tuning_status(
                              mode, source="cache"),
                          trials=0, pruned=0, fingerprint=fp,
                          cache_dir=cache_dir, steps_per_sec=sps,
                          baseline_steps_per_sec=base)
        return _log_record(out, metrics)

    if mode != "probe":
        # cache-only miss: nothing measured, nothing to gate — run on
        # the heuristics and say so
        out = TuneOutcome(cfg=cfg, tuned=start, source="heuristic",
                          status=verdict_lib.tuning_status(
                              mode, source="heuristic"),
                          trials=0, pruned=0, fingerprint=fp,
                          cache_dir=cache_dir)
        return _log_record(out, metrics)

    try:
        outcome = _probe_search(cfg, mesh, plan, start,
                                trials_budget=trials_budget,
                                n_steps=probe_steps, repeats=repeats)
    except Exception as e:
        # probing must never kill a run the heuristics could serve
        from tpudist.metrics import log0
        log0(f"tpudist: autotune probing failed ({e!r}); "
             f"falling back to heuristics")
        out = TuneOutcome(cfg=cfg, tuned=start, source="heuristic",
                          status=verdict_lib.tuning_status(
                              mode, source="heuristic"),
                          trials=0, pruned=0, fingerprint=fp,
                          cache_dir=cache_dir)
        return _log_record(out, metrics)

    tuned, _ = _sync_candidate(outcome.best, False)
    tuned = tuned if tuned is not None else outcome.best
    status = verdict_lib.tuning_status(
        mode, source="probe", tuned_steps_per_sec=outcome.best_sps,
        baseline_steps_per_sec=outcome.baseline_sps)
    if is_coordinator:
        cache_mod.store(cache_dir, fp, {
            "tuned": tuned.as_dict(),
            "steps_per_sec": outcome.best_sps,
            "baseline_steps_per_sec": outcome.baseline_sps,
            "trials": outcome.trials,
            "pruned": outcome.pruned,
            "probe_steps": probe_steps,
            "probe_repeats": repeats,
        })
    out = TuneOutcome(cfg=tuned.apply(cfg), tuned=tuned, source="probe",
                      status=status, trials=outcome.trials,
                      pruned=outcome.pruned, fingerprint=fp,
                      cache_dir=cache_dir,
                      steps_per_sec=outcome.best_sps,
                      baseline_steps_per_sec=outcome.baseline_sps)
    return _log_record(out, metrics)


def _probe_search(cfg, mesh, plan, start: Candidate, *, trials_budget: int,
                  n_steps: int, repeats: int) -> search_mod.SearchOutcome:
    """Wire the real probe into the coordinate search, memoised on the
    EFFECTIVE program key — budget candidates the probe epoch cannot
    tell apart (all full-epoch fast path at probe scale) share one
    trial instead of re-measuring the identical program."""
    batch_ways = max(
        mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1), 1)
    # the overlap-plane axes only exist where the mesh makes them real:
    # bucket bytes on the explicit-DP mesh, virtual stages on pipe > 1
    from tpudist.parallel import mesh as mesh_lib
    from tpudist.parallel import sharding as shd
    sg = mesh_lib.data_slice_groups(mesh)
    axes = search_mod.build_space(
        cfg, batch_ways=batch_ways,
        heuristic_budget_mb=start.staging_budget_mb,
        dp_overlap=shd.pure_dp(mesh),
        pipe_stages=mesh.shape.get("pipe", 1),
        n_slices=(sg.n_slices if sg is not None else 1))
    by_key: Dict[tuple, probe_mod.ProbeResult] = {}

    def raw_probe(cand: Candidate) -> probe_mod.ProbeResult:
        return _sync_result(probe_mod.probe_candidate(
            cfg, mesh, cand, plan, n_steps=n_steps, repeats=repeats))

    def measure(cand: Candidate) -> probe_mod.ProbeResult:
        try:
            key = probe_mod.candidate_key(cfg, mesh, cand, plan, n_steps)
        except Exception as e:   # infeasible plan — pruned, not crashed
            return probe_mod.ProbeResult(
                0.0, float("inf"), n_steps, repeats, feasible=False,
                error=f"{type(e).__name__}: {str(e)[:200]}")
        prior = by_key.get(key)
        if prior is not None:
            return dataclasses.replace(prior, counted=False)
        res = raw_probe(cand)
        if res.key is not None:
            by_key[res.key] = res
        return res

    # the process's very FIRST trial runs cold (allocator growth, code
    # caches) and measured up to 30% slow on CPU — biasing the search
    # AGAINST whichever point is probed first, which is always the
    # heuristic start. Burn the cold trial on the start candidate and
    # discard it; uncounted against the budget by design.
    probe_mod.probe_candidate(cfg, mesh, start, plan, n_steps=n_steps,
                              repeats=1)
    out = search_mod.coordinate_search(start, axes, measure,
                                       trial_budget=trials_budget)
    if out.best != out.baseline:
        # measure-then-commit confirmation: re-probe the provisional
        # winner and the heuristic back-to-back (same process state, no
        # order bias between them) and fold in by best-observed — the
        # commit must survive a SECOND look before it displaces the seed
        confirm_best = raw_probe(out.best)
        confirm_base = raw_probe(out.baseline)
        out.trials += 2
        if confirm_best.feasible:
            out.best_sps = max(out.best_sps, confirm_best.steps_per_sec)
        else:
            out.best_sps = 0.0   # the winner died on re-measure: reject
        if confirm_base.feasible:
            out.baseline_sps = max(out.baseline_sps,
                                   confirm_base.steps_per_sec)
        floor = out.baseline_sps
        if (out.best.remat != out.baseline.remat
                or out.best.grad_accum_steps
                != out.baseline.grad_accum_steps):
            # a math-knob commit costs bitwise parity with the untuned
            # trajectory: it must ALSO clear the improvement margin and
            # both confirmation trials' noise floors on the re-measure,
            # not just tie the heuristic
            floor *= 1 + max(search_mod.IMPROVE_MIN,
                             confirm_best.spread, confirm_base.spread)
        if out.best_sps < floor:
            out.best, out.best_sps = out.baseline, out.baseline_sps
    return out


def _log_record(out: TuneOutcome, metrics: Any) -> TuneOutcome:
    """One ``kind=tune`` record per tuning decision — the committed
    knobs, where they came from, and what the probes measured."""
    if metrics is not None:
        metrics.log(kind="tune", status=out.status, source=out.source,
                    trials=out.trials, pruned=out.pruned,
                    fingerprint=out.fingerprint,
                    steps_per_dispatch=out.tuned.k,
                    staging_budget_mb=out.tuned.staging_budget_mb,
                    remat=out.tuned.remat,
                    grad_accum_steps=out.tuned.grad_accum_steps,
                    grad_bucket_mb=out.tuned.grad_bucket_mb,
                    pipeline_interleave=out.tuned.pipeline_interleave,
                    cross_slice=out.tuned.cross_slice,
                    steps_per_sec=out.steps_per_sec,
                    baseline_steps_per_sec=out.baseline_steps_per_sec)
    return out
