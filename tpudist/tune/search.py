"""Deterministic coordinate-descent search over the dispatch/staging/remat
knob space.

The search is MEASUREMENT-DRIVEN but measurement-agnostic: it never
touches a device itself. Callers hand it a ``measure(candidate) ->
ProbeResult-like`` function (``tpudist.tune.probe`` for real on-device
trials; ``selfcheck.check_autotune`` injects scripted fake timers) and
the search only reads three fields off the result: ``feasible``,
``steps_per_sec``, and ``counted`` (False = the measurement was served
from a memo and must not consume trial budget).

Guarantees the rest of the system leans on:

  * **Deterministic.** Axis order, candidate order within an axis, and
    every tie-break are fixed — on a multi-host pod every process walks
    the identical trial sequence, so the probes' collectives line up
    (the committed point is still broadcast from the coordinator,
    tune.autotune, because *measured times* differ per host).
  * **Bounded.** At most ``trial_budget`` counted measurements; the
    budget running out mid-axis commits the incumbent, it does not
    raise.
  * **Never regresses the seed heuristic.** The start point is measured
    first and the final commit is taken against it: if every explored
    point is slower (or infeasible), the answer IS the start point.
  * **Prunes, never crashes.** An infeasible result (HBM OOM, a staging
    budget that cannot double-buffer, a measure() that raises) removes
    that point from consideration; on ordered axes (k, grad-accum) it
    also stops the ascent — a bigger value of a monotone-memory knob
    cannot become feasible again.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from tpudist.config import SUPERSTEP_CAP, TrainConfig

# Axis walk order: the k axis carries the order-of-magnitude spread
# (BENCH_DISPATCH), so it is searched first and every later axis rides
# the committed k. The overlap-plane knobs (grad bucket bytes, pipeline
# virtual stages) sit between the dispatch knobs and the math knobs:
# both are pure SCHEDULE coordinates — bitwise-identical loss at every
# value (parallel.overlap / parallel.pipeline pin this) — so they never
# need the math-axis commit margin, just a measured win.
AXES = ("k", "staging_budget_mb", "grad_bucket_mb", "cross_slice",
        "pipeline_interleave", "remat", "grad_accum_steps")

# Axes where the knob monotonically raises memory/recompute pressure:
# an infeasible point stops the ascent instead of probing bigger ones.
ORDERED_AXES = frozenset({"k", "grad_accum_steps"})

# Math-affecting knobs (remat changes the backward schedule, grad-accum
# changes the reduction order): committed only on a MEASURED win past
# max(IMPROVE_MIN, the trials' own repeat spread), never on a tie — a
# tie keeps the trajectory-identical seed value, preserving bitwise
# parity with the untuned run, and the noise floor requirement means a
# loaded host's +-20% jitter cannot smuggle a math change in as a
# "win" (a genuine 30% remat win on quiet hardware still clears it).
MATH_AXES = frozenset({"remat", "grad_accum_steps"})

# Plateau preference: among candidates within this fraction of the axis
# best, commit the SMALLEST (shorter supersteps = tighter log/ckpt
# boundaries at indistinguishable speed). Kept tight so the committed
# point stays well inside the acceptance criterion's 10%-of-best band.
PLATEAU_TOL = 0.02

# A math knob must beat the incumbent by this fraction to be committed.
IMPROVE_MIN = 0.02

# Early stop on regression: once a later point on an ordered axis falls
# this far below the PREVIOUS point, the curve has turned down
# decisively — stop scanning the far side of the plateau.
REGRESS_STOP = 0.10


@dataclasses.dataclass(frozen=True, order=True)
class Candidate:
    """One point in the knob space. ``apply`` folds it into a TrainConfig
    as EXPLICIT settings (tuned values outrank env vars exactly like
    flags do — a tuned commit is a flag the measurement wrote)."""

    k: int = 1
    staging_budget_mb: Optional[float] = None
    remat: bool = False
    grad_accum_steps: int = 1
    # overlap-plane coordinates (None / 0 = leave cfg's setting alone —
    # the axes only enter the space when the run's mesh makes them real)
    grad_bucket_mb: Optional[float] = None
    pipeline_interleave: int = 0
    # cross-slice reduce schedule: a pure SCHEDULE coordinate like the
    # bucket size (parallel.overlap pins bitwise parity across modes),
    # gated to multi-slice DP meshes by build_space
    cross_slice: Optional[str] = None

    def apply(self, cfg: TrainConfig) -> TrainConfig:
        out = dataclasses.replace(
            cfg, steps_per_dispatch=self.k,
            staging_budget_mb=self.staging_budget_mb,
            remat=self.remat, grad_accum_steps=self.grad_accum_steps)
        if self.grad_bucket_mb is not None:
            out = dataclasses.replace(out,
                                      grad_bucket_mb=self.grad_bucket_mb)
        if self.pipeline_interleave:
            out = dataclasses.replace(
                out, pipeline_interleave=self.pipeline_interleave)
        if self.cross_slice is not None:
            out = dataclasses.replace(out, cross_slice=self.cross_slice)
        return out

    def replace(self, **kw) -> "Candidate":
        return dataclasses.replace(self, **kw)

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def k_candidates(cfg: TrainConfig) -> List[int]:
    """The superstep lengths this run may legally dispatch: divisors of
    ``--log-every``/``--ckpt-every-steps`` up to :data:`SUPERSTEP_CAP`
    (the same constraint ``config.resolve_steps_per_dispatch`` enforces),
    thinned to a geometric ladder (each kept value >= 2x the previous)
    so the trial budget buys coverage of the whole curve, with the
    largest legal value always kept — that is where the dispatch-bound
    plateau lives."""
    if cfg.profile_dir or cfg.fail_at is not None:
        return [1]   # these modes are defined in per-step terms
    valid = []
    for d in range(1, SUPERSTEP_CAP + 1):
        if cfg.log_every > 0 and cfg.log_every % d:
            continue
        if cfg.ckpt_every_steps and cfg.ckpt_every_steps % d:
            continue
        valid.append(d)
    ladder = []
    for d in valid:
        if not ladder or d >= 2 * ladder[-1]:
            ladder.append(d)
    if valid and ladder[-1] != valid[-1]:
        ladder.append(valid[-1])
    return ladder


# Bucket-size ladder for --grad-overlap bucketed, MB: geometric like the
# k ladder, spanning "reduce almost per-leaf" to "one bucket ≈ barrier".
GRAD_BUCKET_LADDER_MB = (1.0, 4.0, 16.0)

# Interleave ladder: geometric virtual-stage counts, filtered to what
# the model's layer count divides into (build_space).
PIPELINE_INTERLEAVE_LADDER = (1, 2, 4, 8)


def build_space(cfg: TrainConfig, *, batch_ways: int = 1,
                heuristic_budget_mb: Optional[float] = None,
                dp_overlap: bool = False, pipe_stages: int = 1,
                n_slices: int = 1) -> Dict[str, List[Any]]:
    """The bounded search space for this run's config.

    * ``k``: the legal divisor ladder (:func:`k_candidates`).
    * ``staging_budget_mb``: the heuristic estimate, unbounded (the
      full-epoch fast path), and 2x the estimate — only when a heuristic
      estimate exists at all.
    * ``grad_bucket_mb``: the geometric bucket ladder, led by the run's
      configured value — only when ``dp_overlap`` says the mesh has an
      explicit DP all-reduce AND ``--grad-overlap bucketed`` is on (a
      bucket size is meaningless otherwise).
    * ``cross_slice``: both reduce schedules, led by the run's resolved
      mode — only on multi-slice DP meshes (``n_slices > 1`` with
      ``dp_overlap``): a single-slice hierarchical downgrades to flat
      anyway, so the coordinate would probe the identical program twice.
    * ``pipeline_interleave``: virtual-stage counts the layer count
      divides into — only on pipeline meshes (``pipe_stages > 1``) with
      auto microbatching or an S-divisible explicit M (the interleaved
      schedule groups microbatches S at a time).
    * ``remat``: both settings for layered models; the mlp has no layers
      to checkpoint.
    * ``grad_accum_steps``: {1, 2, 4} filtered to divide the per-shard
      batch (the same divisibility train.run enforces).
    """
    from tpudist.config import (resolve_cross_slice, resolve_grad_overlap,
                                resolve_pipeline_interleave)
    budgets: List[Optional[float]] = [heuristic_budget_mb]
    if heuristic_budget_mb is not None:
        budgets += [None, round(heuristic_budget_mb * 2, 4)]
    layered = cfg.model.name in ("transformer", "moe")
    gas = [g for g in (1, 2, 4)
           if cfg.batch_size % (max(batch_ways, 1) * g) == 0]
    if cfg.grad_accum_steps not in gas:
        gas = sorted(set(gas) | {cfg.grad_accum_steps})
    buckets: List[Optional[float]] = []
    mode, bucket_bytes = resolve_grad_overlap(cfg)
    if dp_overlap and mode == "bucketed":
        lead = round(bucket_bytes / 2**20, 4)
        buckets = [lead] + [b for b in GRAD_BUCKET_LADDER_MB if b != lead]
    cross: List[Optional[str]] = []
    if dp_overlap and n_slices > 1:
        lead = resolve_cross_slice(cfg)
        cross = [lead] + [m for m in ("flat", "hierarchical")
                          if m != lead]
    interleaves: List[int] = []
    if pipe_stages > 1 and layered:
        v0 = resolve_pipeline_interleave(cfg)
        micro_ok = (cfg.pp_microbatches == 0
                    or cfg.pp_microbatches % pipe_stages == 0)
        if micro_ok:
            interleaves = [
                v for v in PIPELINE_INTERLEAVE_LADDER
                if cfg.model.n_layers % (pipe_stages * v) == 0]
            if v0 in interleaves:   # lead with the configured value
                interleaves = [v0] + [v for v in interleaves if v != v0]
    return {
        "k": k_candidates(cfg),
        "staging_budget_mb": budgets,
        "grad_bucket_mb": buckets,
        "cross_slice": cross,
        "pipeline_interleave": interleaves,
        "remat": ([cfg.remat, not cfg.remat] if layered else [cfg.remat]),
        "grad_accum_steps": gas,
    }


@dataclasses.dataclass
class SearchOutcome:
    best: Candidate
    best_sps: float
    baseline: Candidate
    baseline_sps: float
    trials: int                 # counted (device-touching) measurements
    pruned: int                 # infeasible points removed from play
    exhausted: bool             # trial budget ran out mid-search
    log: List[Tuple[Candidate, Any]] = dataclasses.field(
        default_factory=list)


def _sps(res: Any) -> float:
    return float(getattr(res, "steps_per_sec", 0.0) or 0.0)


def _spread(res: Any) -> float:
    """A trial's own repeat spread — its measured noise floor."""
    return float(getattr(res, "spread", 0.0) or 0.0)


def coordinate_search(start: Candidate, axes: Dict[str, Sequence[Any]],
                      measure: Callable[[Candidate], Any], *,
                      trial_budget: int = 12) -> SearchOutcome:
    """Coordinate descent from ``start`` over ``axes`` (walked in
    :data:`AXES` order), committing one axis before moving to the next.
    See the module docstring for the guarantees."""
    memo: Dict[Candidate, Any] = {}
    out = SearchOutcome(best=start, best_sps=0.0, baseline=start,
                        baseline_sps=0.0, trials=0, pruned=0,
                        exhausted=False)

    def run(cand: Candidate) -> Any:
        if cand in memo:
            return memo[cand]
        if out.trials >= trial_budget:
            out.exhausted = True
            return None
        try:
            res = measure(cand)
        except Exception as e:   # a crashing probe is a pruned point
            res = _Infeasible(f"{type(e).__name__}: {str(e)[:200]}")
        if res is None:
            res = _Infeasible("measure returned None")
        if getattr(res, "counted", True):
            out.trials += 1
        if not getattr(res, "feasible", False):
            out.pruned += 1
        memo[cand] = res
        out.log.append((cand, res))
        return res

    base_res = run(start)
    out.baseline_sps = _sps(base_res) if getattr(
        base_res, "feasible", False) else 0.0
    out.best_sps = out.baseline_sps

    for axis in AXES:
        values = list(axes.get(axis, []))
        if len(values) <= 1:
            continue
        incumbent_v = getattr(out.best, axis)
        measured: List[Tuple[Any, float, Any]] = []
        if getattr(memo.get(out.best), "feasible", False):
            measured.append((incumbent_v, _sps(memo[out.best]),
                             memo[out.best]))
        prev_sps: Optional[float] = None
        for v in values:
            if v == incumbent_v:
                prev_sps = _sps(memo[out.best]) if measured else prev_sps
                continue
            cand = out.best.replace(**{axis: v})
            res = run(cand)
            if res is None:          # budget exhausted mid-axis
                break
            if not res.feasible:
                if axis in ORDERED_AXES:
                    break            # bigger k / accum cannot refit HBM
                continue
            sps = _sps(res)
            measured.append((v, sps, res))
            if (axis in ORDERED_AXES and prev_sps is not None
                    and sps < prev_sps * (1 - REGRESS_STOP)):
                break                # past the plateau, curve turned down
            prev_sps = sps
        if not measured:
            continue
        axis_best_sps = max(s for _, s, _ in measured)
        if axis in MATH_AXES:
            # math knobs: move off the seed value only on a win clearing
            # BOTH trials' measured noise floors
            cur = next(((s, r) for v, s, r in measured
                        if v == incumbent_v), (0.0, None))
            winner_v, winner_sps, winner_res = max(measured,
                                                   key=lambda t: t[1])
            need = 1 + max(IMPROVE_MIN, _spread(cur[1]),
                           _spread(winner_res))
            if (winner_v != incumbent_v and winner_sps > 0
                    and winner_sps >= cur[0] * need):
                out.best = out.best.replace(**{axis: winner_v})
                out.best_sps = winner_sps
        else:
            # plateau preference: smallest value within tolerance of best
            # (ordered axes scan ascending; the budget axis keeps its
            # measurement order, which leads with the heuristic estimate)
            if axis in ORDERED_AXES:
                measured = sorted(measured, key=lambda t: t[0])
            for v, sps, _ in measured:
                if sps >= axis_best_sps * (1 - PLATEAU_TOL):
                    if v != getattr(out.best, axis):
                        out.best = out.best.replace(**{axis: v})
                    out.best_sps = sps
                    break
        if out.exhausted:
            break

    # the hard floor: NEVER commit a point slower than the measured seed
    # heuristic (selfcheck.check_autotune drills exactly this)
    if out.best != out.baseline and out.best_sps < out.baseline_sps:
        out.best, out.best_sps = out.baseline, out.baseline_sps
    return out


class _Infeasible:
    """Minimal ProbeResult stand-in for a measure() that raised."""

    feasible = False
    counted = True
    steps_per_sec = 0.0

    def __init__(self, error: str):
        self.error = error

    def __repr__(self) -> str:
        return f"_Infeasible({self.error!r})"
