"""Persisted tuning cache: measure once, reuse every run.

A tuned operating point is only valid for the exact situation it was
measured in, so cache entries are keyed by a FINGERPRINT of everything
that moves the curve: the model config, global batch, dtypes, the
log/ckpt intervals (they bound the legal k space), the mesh shape,
device kind and counts, and the jax + tpudist versions. Any of those
changing is a different workload — the lookup MUST miss and re-probe,
exactly like the XLA compilation cache misses on a changed program.

One JSON file per fingerprint under the cache dir, written ATOMICALLY
(tmp + rename) and by the COORDINATOR only — workers on a shared
filesystem must never race partial writes; readers treat any unreadable
or mismatched file as a miss, never an error. A cache hit costs zero
probe trials.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
from typing import Any, Dict, Optional

SCHEMA = 1


def config_grad_overlap_mode(cfg) -> str:
    """The resolved ``--grad-overlap`` mode for the fingerprint (env
    included — the same resolution the engine dispatches on)."""
    from tpudist.config import resolve_grad_overlap
    try:
        return resolve_grad_overlap(cfg)[0]
    except ValueError:
        return "off"


def _config_cross_slice(cfg) -> str:
    """The resolved ``--cross-slice`` mode for the fingerprint."""
    from tpudist.config import resolve_cross_slice
    try:
        return resolve_cross_slice(cfg)
    except ValueError:
        return "flat"


def _mesh_slices(mesh) -> list:
    """The mesh's slice partition (``TPUDIST_SLICE_MAP`` resolved), as a
    JSON-able list — [] when unsliced."""
    try:
        from tpudist.parallel import mesh as mesh_lib
        return [int(s) for s in mesh_lib.mesh_device_slices(mesh)]
    except Exception:
        return []


def fingerprint(cfg, mesh, *, device_kind: Optional[str] = None) -> str:
    """Hex fingerprint of the tuning situation (see module docstring)."""
    import jax

    from tpudist.version import __version__
    if device_kind is None:
        try:
            device_kind = jax.devices()[0].device_kind
        except Exception:
            device_kind = "unknown"
    payload = {
        "schema": SCHEMA,
        "model": dataclasses.asdict(cfg.model),
        "batch_size": cfg.batch_size,
        "dtype": cfg.dtype,
        "adam_nu_dtype": cfg.adam_nu_dtype,
        "log_every": cfg.log_every,
        "ckpt_every_steps": cfg.ckpt_every_steps,
        # the overlap plane changes the PROGRAM the knobs tune: a cache
        # entry measured with the barrier all-reduce must not serve a
        # bucketed run (and the search space itself differs)
        "grad_overlap": config_grad_overlap_mode(cfg),
        "cross_slice": _config_cross_slice(cfg),
        "pp_microbatches": cfg.pp_microbatches,
        "mesh": dict(zip(mesh.axis_names,
                         (int(s) for s in mesh.devices.shape))),
        # the slice partition changes which cross_slice points exist and
        # what each one lowers to — a point tuned on a 2-slice mesh must
        # not serve a 4-slice run of the same shape
        "slices": _mesh_slices(mesh),
        "n_devices": jax.device_count(),
        "n_processes": jax.process_count(),
        "device_kind": device_kind,
        "jax": jax.__version__,
        "tpudist": __version__,
    }
    blob = json.dumps(payload, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def cache_path(cache_dir: str, fp: str, prefix: str = "tune") -> str:
    return os.path.join(cache_dir, f"{prefix}-{fp}.json")


def _validate_train_tuned(tuned: Dict[str, Any]) -> bool:
    """The train tuner's knob sanity check: the knobs must all be
    present and sane — an insane value (wrong type, non-positive) is a
    MISS here, not a crash later in resolve_staging_budget_bytes. The
    overlap-plane coordinates (grad_bucket_mb, pipeline_interleave) are
    validated when present; entries written before they existed are
    already invalidated by the fingerprint's grad_overlap/pp fields."""
    if int(tuned["k"]) < 1 or int(tuned["grad_accum_steps"]) < 1:
        return False
    bool(tuned["remat"])
    budget = tuned["staging_budget_mb"]
    if budget is not None and (isinstance(budget, bool)
                               or not isinstance(budget, (int, float))
                               or budget <= 0):
        return False
    bucket = tuned.get("grad_bucket_mb")
    if bucket is not None and (isinstance(bucket, bool)
                               or not isinstance(bucket, (int, float))
                               or bucket <= 0):
        return False
    v = tuned.get("pipeline_interleave")
    if v is not None and int(v) < 0:
        return False
    cs = tuned.get("cross_slice")
    if cs is not None and cs not in ("flat", "hierarchical"):
        return False
    return True


def load(cache_dir: str, fp: str, *, prefix: str = "tune",
         validate=_validate_train_tuned) -> Optional[Dict[str, Any]]:
    """The cached record for ``fp``, or None on miss — a corrupt,
    partial, or wrong-schema file reads as a miss (re-probe), never as
    an error (a stale cache must not fail a run). ``prefix``/
    ``validate`` let other tuners (the serve engine's decode-batch/
    KV-layout search) share the one cache mechanism with their own knob
    schema; a ``validate`` that raises or returns False is a miss."""
    try:
        with open(cache_path(cache_dir, fp, prefix)) as f:
            rec = json.load(f)
        if rec.get("schema") != SCHEMA or rec.get("fingerprint") != fp:
            return None
        if not validate(rec["tuned"]):
            return None
        return rec
    except (OSError, ValueError, KeyError, TypeError):
        return None


def store(cache_dir: str, fp: str, record: Dict[str, Any], *,
          prefix: str = "tune") -> bool:
    """Atomically persist ``record`` (coordinator only — callers gate).
    Best-effort: a read-only cache dir degrades to un-cached runs, not a
    failed one."""
    try:
        os.makedirs(cache_dir, exist_ok=True)
        path = cache_path(cache_dir, fp, prefix)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({**record, "schema": SCHEMA, "fingerprint": fp,
                       "created_unix": time.time()}, f, indent=1)
        os.replace(tmp, path)
        return True
    except OSError:
        return False
