"""Measured probe trials of the REAL dispatch path.

One probe = build the exact train-step/superstep program the run would
dispatch (``engine.make_train_step`` / ``engine.make_superstep`` over
``sharding.plan_slabs`` staging — not a model of it), compile it once,
warm it with a full epoch, then time ``repeats`` epochs with host-transfer
fences and report steps/s plus the HBM watermark. The probe either
completes with a number or reports ``feasible=False`` (OOM, a staging
budget that cannot double-buffer, watermark past the device limit) — an
infeasible point is a *result* the search prunes, never a crash.

:class:`EpochRunner` is the compile-once/run-many harness itself, shared
with ``bench.py``'s sweeps (``--dispatch-sweep``/``--staging-sweep``
previously hand-rolled the same compile/warmup/time-n-steps loop twice);
the streaming path mirrors ``train._superstep_epoch`` — double-buffered
slabs, slab-boundary fences, one compiled superstep for the whole epoch,
padded tail included.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp

from tpudist import config as config_lib
from tpudist import engine
from tpudist.obs import trace as trace_lib
from tpudist.parallel import sharding as shd

# Probe length/repeats: long enough that per-epoch fixed costs (one
# staging transfer, one fence) amortise like a real epoch, short enough
# that a full search stays a startup blip next to the timed run. The
# estimator over repeats is the MIN epoch time: host-scheduler noise is
# one-sided (a load spike only ever slows an epoch down), so the fastest
# observed epoch is the least-contaminated measurement of the program —
# medians measured up to 20% apart on back-to-back identical CPU probes.
DEFAULT_PROBE_STEPS = 64
DEFAULT_PROBE_REPEATS = 5

# A probe whose HBM watermark lands above this fraction of the device
# limit is pruned even though it survived: the timed run keeps more
# alive (checkpoint snapshots, metrics, the second staged slab at epoch
# scale) and a point with no headroom is one allocator hiccup from OOM.
HBM_HEADROOM_FRACTION = 0.95


@dataclasses.dataclass(frozen=True)
class ProbeResult:
    """One candidate's measured trial (or its reason for being pruned)."""

    steps_per_sec: float
    step_ms: float
    n_steps: int
    repeats: int
    hbm_peak_bytes: Optional[int] = None
    compile_s: float = 0.0
    feasible: bool = True
    error: Optional[str] = None
    key: Optional[tuple] = None   # effective-program key (dedupe)
    counted: bool = True          # False = memo hit, no budget consumed
    spread: float = 0.0           # (max-min)/min over repeats: the trial's
    # own measured noise floor — math-knob commits must clear it


class EpochRunner:
    """Compile-once / run-many epoch harness over the real dispatch path.

    ``k == 1`` runs the per-step path — ``make_train_step`` including its
    per-step ``put_batch`` host transfer, the real thing the superstep
    replaces. ``k > 1`` stages slabs per ``plan_slabs`` (full-epoch fast
    path, or double-buffered streaming under ``budget_bytes``) and
    dispatches supersteps exactly as ``train._superstep_epoch`` does.
    ``dispatch_fn`` exposes the compiled callable (``.cost_analysis()``,
    ``.traces``) for the observability fields the sweeps record.
    """

    def __init__(self, cfg, mesh, k: int, plan, n_steps: int, *,
                 budget_bytes: Optional[int] = None):
        self.cfg, self.mesh, self.k = cfg, mesh, int(k)
        self.n_steps = min(int(n_steps), plan.n_steps)
        if self.n_steps < 1:
            raise ValueError(f"probe needs >= 1 step, got {self.n_steps}")
        self._plan = plan
        if self.k == 1:
            # one host-side gather up front; put_batch stays per-step
            self._host = plan.slab(0, self.n_steps)
            self.dispatch_fn = engine.make_train_step(cfg, mesh)
            self.splan = None
        else:
            batch_shards = max(
                mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1), 1)
            step_bytes = max(1, plan.bytes_per_step * jax.process_count()
                             // batch_shards)
            self.splan = shd.plan_slabs(self.n_steps, self.k, step_bytes,
                                        budget_bytes)
            self.dispatch_fn = engine.make_superstep(cfg, mesh, self.k)

    def init_state(self):
        """A fresh TrainState (each timed epoch donates it away)."""
        return engine.init_state(jax.random.PRNGKey(self.cfg.seed),
                                 self.cfg, self.mesh)

    def run_epoch(self, state) -> Tuple[Any, Any]:
        """Dispatch one epoch; returns ``(state, last_loss)`` with the
        device work still in flight — callers fence on the loss."""
        if self.k == 1:
            loss = None
            for i in range(self.n_steps):
                batch = jax.tree.map(lambda a: a[i], self._host)
                state, loss = self.dispatch_fn(state, batch)
            return state, loss
        splan, k = self.splan, self.k
        S = splan.slab_steps
        total = jnp.zeros((), jnp.float32)
        loss = None

        def stage(s):
            start, stop = s * S, min(self.n_steps, s * S + S)
            pad_to = -(-(stop - start) // k) * k
            return shd.put_epoch(self.mesh,
                                 self._plan.slab(start, stop, pad_to=pad_to))

        nxt = stage(0)
        for s in range(splan.n_slabs):
            cur = nxt
            if s + 1 < splan.n_slabs:
                # double buffer: next slab's H2D overlaps this compute
                nxt = stage(s + 1)
            base = s * S
            staged_len = jax.tree.leaves(cur)[0].shape[0]
            for j in range(staged_len // k):
                gstart = base + j * k
                if gstart >= self.n_steps:
                    break
                hi = min(self.n_steps - gstart, k)
                slab = (cur if staged_len == k else
                        jax.tree.map(lambda a: a[j * k:(j + 1) * k], cur))
                state, total, loss = self.dispatch_fn(state, total, slab,
                                                      0, hi)
            if s + 1 < splan.n_slabs and loss is not None:
                jax.device_get(loss)   # slab-boundary fence (train parity)
        return state, loss


def time_runner(runner: EpochRunner, *, repeats: int = DEFAULT_PROBE_REPEATS,
                state: Any = None) -> Tuple[Any, List[float], float]:
    """Warm (trace+compile+stage) one epoch, then time ``repeats`` epochs.
    Returns ``(state, ms_per_step_per_epoch, compile_s)``; fencing is a
    host transfer of the last loss (block_until_ready can return early on
    tunneled PJRT backends)."""
    state = runner.init_state() if state is None else state
    t0 = time.perf_counter()
    state, loss = runner.run_epoch(state)
    jax.device_get(loss)
    compile_s = time.perf_counter() - t0
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        state, loss = runner.run_epoch(state)
        jax.device_get(loss)
        times.append((time.perf_counter() - t0) * 1000 / runner.n_steps)
    return state, times, compile_s


def candidate_key(cfg, mesh, candidate, plan, n_steps: int) -> tuple:
    """The EFFECTIVE program a candidate dispatches, as a hashable key.
    Distinct candidates can lower to the same program at probe scale
    (every staging budget the probe epoch fits inside is the same
    full-epoch fast path) — the search memoises on this key so the trial
    budget is spent on points that can actually differ. Raises where the
    plan itself is infeasible (plan_slabs's double-buffer error), which
    the caller converts to a pruned point."""
    overlap = (candidate.grad_bucket_mb, candidate.pipeline_interleave)
    if candidate.k == 1:
        return (1, None, candidate.remat, candidate.grad_accum_steps,
                overlap)
    pcfg = candidate.apply(cfg)
    budget = config_lib.resolve_staging_budget_bytes(pcfg)
    n = min(int(n_steps), plan.n_steps)
    batch_shards = max(
        mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1), 1)
    step_bytes = max(1, plan.bytes_per_step * jax.process_count()
                     // batch_shards)
    splan = shd.plan_slabs(n, candidate.k, step_bytes, budget)
    return (candidate.k, (splan.slab_steps, splan.streamed),
            candidate.remat, candidate.grad_accum_steps, overlap)


def probe_candidate(cfg, mesh, candidate, plan, *,
                    n_steps: int = DEFAULT_PROBE_STEPS,
                    repeats: int = DEFAULT_PROBE_REPEATS) -> ProbeResult:
    """Run one candidate's measured trial; never raises — any failure
    (OOM, infeasible slab plan, compile error) comes back as a pruned
    ``feasible=False`` result carrying the error string."""
    from tpudist.obs.hbm import HbmSampler
    n = min(int(n_steps), plan.n_steps)
    try:
        key = candidate_key(cfg, mesh, candidate, plan, n)
        pcfg = candidate.apply(cfg)
        budget = (config_lib.resolve_staging_budget_bytes(pcfg)
                  if candidate.k > 1 else None)
        runner = EpochRunner(pcfg, mesh, candidate.k, plan, n,
                             budget_bytes=budget)
        sampler = HbmSampler(period_s=0)
        # the device runtime's peak_bytes_in_use is a PROCESS-lifetime
        # high-water mark: a prior trial's peak never recedes. Snapshot
        # it before this trial so the headroom prune fires only when
        # THIS candidate raised the watermark past the limit — otherwise
        # one big early trial would poison every later probe
        prior_peak = sampler.peak_in_use
        with trace_lib.span("probe_trial", cat="tune", k=candidate.k,
                            remat=candidate.remat,
                            grad_accum=candidate.grad_accum_steps):
            _, times, compile_s = time_runner(runner, repeats=repeats)
        sampler.sample()
        hbm = sampler.split()
        ms = min(times)   # one-sided noise: fastest epoch is cleanest
        spread = (max(times) - ms) / ms if ms > 0 else 0.0
        peak, limit = hbm["hbm_peak_bytes"], hbm["hbm_limit_bytes"]
        if (peak and limit and hbm["hbm_source"] == "memory_stats"
                and peak > HBM_HEADROOM_FRACTION * limit
                and peak > prior_peak):
            return ProbeResult(
                0.0, ms, n, repeats, hbm_peak_bytes=peak,
                compile_s=compile_s, feasible=False, key=key,
                error=f"hbm watermark {peak} of {limit} B leaves no "
                      f"headroom")
        return ProbeResult(1000.0 / ms, ms, n, repeats,
                           hbm_peak_bytes=peak, compile_s=compile_s,
                           key=key, spread=spread)
    except Exception as e:
        return ProbeResult(0.0, float("inf"), n, repeats, feasible=False,
                           error=f"{type(e).__name__}: {str(e)[:200]}")
