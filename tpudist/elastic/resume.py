"""Elastic resume: map a sharded manifest checkpoint onto the CURRENT mesh.

The restore contract that makes restarts elastic rather than
all-or-nothing (cf. the pjit/TPUv4 resharding primitive, PAPERS.md): the
checkpoint was written by N processes as per-worker shard files plus
slice indexes (tpudist.elastic.ckpt); the resumed run may come back on M
processes with a different device count and a different sharding of
every leaf. :func:`restore` reads the committed manifest, validates the
step/epoch/data-cursor metadata against the resuming run's config, and
assembles each leaf's locally-addressable slices directly from whichever
saved shards intersect them (``jax.make_array_from_callback`` — each
process touches only the bytes it will own). When a requested slice
exactly equals a saved shard, the saved array is handed over zero-copy —
the fast path for the common same-mesh restart, which is then
bitwise-identical; a reshaped mesh gets the same values re-laid-out, so
continuation is loss-correct (pinned in tests/test_elastic.py).

The superstep/staging realignment needs no code here: the train loop's
resume machinery already replays the epoch plan from ``(epoch,
step_in_epoch)`` (the permutation is a pure function of (seed, epoch)
and the realignment superstep masks the consumed prefix), and the epoch
plan is computed from the CURRENT process topology — so a 4→2 reshard
automatically re-cuts the same global batches across the new hosts.
"""

from __future__ import annotations

import json
import os
import sys
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from tpudist.elastic import ckpt as ckpt_mod


class ResumeError(ValueError):
    """A manifest exists but cannot drive this run's resume (structure,
    shape, dtype, or data-cursor mismatch; missing shard coverage).
    ``--resume auto`` converts this into a logged fresh start with a
    ``fail`` resume_status; ``--resume latest`` lets it propagate."""


class ShardCorruptionError(ResumeError):
    """A committed checkpoint's BYTES are bad: a shard file unreadable
    or truncated, a shard failing its recorded crc32, a missing shard
    index. Distinct from the structural :class:`ResumeError`s because
    the right response differs: a corrupt checkpoint falls back to the
    PREVIOUS committed manifest (flagged ``fallback_from``/
    ``corrupt_shard`` in ``kind=resume``) — losing one checkpoint
    interval of steps — instead of raising or fresh-starting, while a
    structure/cursor mismatch must refuse loudly (an older checkpoint
    would mismatch the same way)."""


def _shard_table(save_dir: str, manifest: Dict[str, Any]):
    """Per-leaf shard lists from every worker's index:
    ``name -> [(start, shape, npz, key, crc32), ...]`` plus the open
    npz handles (lazy per-key loads; caller closes). Unreadable shard
    files and missing/torn indexes raise :class:`ShardCorruptionError`
    — fallback-eligible, unlike structural mismatches."""
    root = ckpt_mod.elastic_root(save_dir)
    d = os.path.join(root, manifest["dir"])
    table: Dict[str, List[Tuple]] = {}
    handles = []
    try:
        for i in range(int(manifest["process_count"])):
            ipath = os.path.join(d, ckpt_mod.index_name(i))
            if not os.path.exists(ipath):
                raise ShardCorruptionError(
                    f"committed manifest step {manifest['step']} is "
                    f"missing worker {i}'s shard index ({ipath}) — torn "
                    f"tree or hand-pruned steps/ directory")
            try:
                with open(ipath) as f:
                    idx = json.load(f)
            except (OSError, ValueError) as e:
                raise ShardCorruptionError(
                    f"worker {i}'s shard index {ipath} is unreadable "
                    f"({e!r})")
            spath = os.path.join(d, ckpt_mod.shards_name(i))
            try:
                npz = np.load(spath)
            except Exception as e:
                # a truncated npz is a broken zip: np.load raises
                # anything from BadZipFile to OSError depending on
                # where the cut landed
                raise ShardCorruptionError(
                    f"worker {i}'s shard file {spath} is unreadable "
                    f"({e!r}) — corrupt or truncated")
            handles.append(npz)
            for name, rec in idx["leaves"].items():
                rows = table.setdefault(name, [])
                for sh in rec["shards"]:
                    rows.append((tuple(sh["start"]), tuple(sh["shape"]),
                                 npz, sh["key"], sh.get("crc32")))
    except Exception:
        for h in handles:
            try:
                h.close()
            except Exception:
                pass
        raise
    return table, handles


def _shard_data(npz, key: str, crc: Optional[int]) -> np.ndarray:
    """One shard's bytes off disk, verified against the crc32 the
    writer recorded from the in-memory array — the check that turns a
    bit flip or short read into a detected :class:`ShardCorruptionError`
    instead of silently-wrong resumed weights. Older indexes without a
    crc restore unverified (the pre-crc behavior)."""
    try:
        arr = np.asarray(npz[key])
    except Exception as e:
        raise ShardCorruptionError(
            f"shard {key} is unreadable ({e!r}) — corrupt or truncated "
            f"shard file")
    if crc is not None and (zlib.crc32(arr.tobytes()) & 0xFFFFFFFF) \
            != int(crc):
        raise ShardCorruptionError(
            f"shard {key} failed its crc32 check — the bytes on disk "
            f"are not the bytes the checkpoint wrote")
    return arr


def _as_dtype(arr: np.ndarray, dtype: np.dtype) -> np.ndarray:
    """Reinterpret an npz-loaded shard as the template's dtype. The npy
    format stores extension dtypes (ml_dtypes bfloat16 — the mixed-
    precision mu/nu leaves) as raw void bytes (``|V2``) and loses the
    type on read; a same-itemsize VIEW restores it bit-exactly. Other
    dtypes were validated against the manifest already, so anything
    else matching is a no-op."""
    arr = np.asarray(arr)
    if arr.dtype == dtype:
        return arr
    if arr.dtype.kind == "V" and arr.dtype.itemsize == dtype.itemsize:
        return arr.view(dtype)
    return arr.astype(dtype)


def _assemble(region: Tuple[Tuple[int, int], ...], shards, dtype
              ) -> np.ndarray:
    """Fill one requested slice of a leaf from the saved shards that
    intersect it — the per-leaf slice-assembly reshard. Exact-match
    shards return zero-copy; anything else is gathered piecewise with
    full-coverage checking (a hole means the manifest does not actually
    tile the array — refuse rather than resume from garbage). Every
    shard read is crc-verified (:func:`_shard_data`)."""
    shape = tuple(stop - start for start, stop in region)
    for start, sshape, npz, key, crc in shards:
        if (tuple((s, s + d) for s, d in zip(start, sshape)) == region):
            return _as_dtype(_shard_data(npz, key, crc), dtype)
    out = np.zeros(shape, dtype=dtype)
    filled = 0
    for start, sshape, npz, key, crc in shards:
        # intersection of [start, start+sshape) with the region
        lo = [max(s, r0) for s, (r0, _) in zip(start, region)]
        hi = [min(s + d, r1) for s, d, (_, r1)
              in zip(start, sshape, region)]
        if any(l >= h for l, h in zip(lo, hi)):
            continue
        src = _as_dtype(_shard_data(npz, key, crc), dtype)[
            tuple(slice(l - s, h - s)
                  for l, h, s in zip(lo, hi, start))]
        out[tuple(slice(l - r0, h - r0)
                  for l, h, (r0, _) in zip(lo, hi, region))] = src
        filled += int(np.prod([h - l for l, h in zip(lo, hi)],
                              dtype=np.int64))
    size = int(np.prod(shape, dtype=np.int64))
    if filled != size:
        raise ResumeError(
            f"saved shards cover {filled} of {size} elements of region "
            f"{region} — manifest does not tile the leaf (overlap or "
            f"hole); refusing to resume from a torn layout")
    return out


def validate_run_meta(manifest: Dict[str, Any],
                      expect: Optional[Dict[str, Any]]) -> None:
    """The data-cursor check: resuming with a different seed or global
    batch size silently replays a DIFFERENT epoch permutation, so the
    'resumed' trajectory would be unrelated to the one checkpointed —
    refuse loudly instead. Only keys present in both are compared (the
    manifest's ``run`` block is the writer's claim; an older manifest
    without it stays restorable)."""
    saved = manifest.get("run") or {}
    if not expect:
        return
    bad = {k: (saved[k], v) for k, v in expect.items()
           if k in saved and saved[k] != v}
    if bad:
        raise ResumeError(
            "manifest data cursor disagrees with this run's config: "
            + ", ".join(f"{k}: saved {s!r} vs current {c!r}"
                        for k, (s, c) in bad.items())
            + " — the epoch permutation would not replay; pass a "
              "matching --seed/--train-batch-size or start fresh")


def restore(save_dir: str, template: Any, *,
            run_meta: Optional[Dict[str, Any]] = None,
            details: Optional[Dict[str, Any]] = None
            ) -> Optional[Tuple[Any, int, int]]:
    """Restore the newest RESTORABLE committed manifest onto
    ``template``'s mesh layout as ``(state, epoch, step_in_epoch)``, or
    None when no manifest was ever committed. ``template`` (the
    concretely-sharded live TrainState) pins the treedef, shapes,
    dtypes and target shardings; the saved shards may come from any
    process/device count.

    A checkpoint whose BYTES are bad (crc mismatch, truncated shard
    file, torn index — :class:`ShardCorruptionError`) is skipped and
    the previous committed manifest restores instead: a bit flip must
    cost one checkpoint interval, not the whole run. When a ``details``
    dict is passed, a fallback populates ``details["fallback_from"]``
    (the corrupt step) and ``details["corrupt_shard"]`` (what failed) —
    the train loop flags both in its ``kind=resume`` record. Structural
    failures (shape/dtype/cursor mismatch) still raise immediately: an
    older checkpoint would mismatch identically, so falling back would
    only hide the real problem. Every committed manifest corrupt ⇒ the
    newest one's error propagates (``--resume auto`` then degrades to a
    flagged fresh start)."""
    manifests = ckpt_mod.committed_manifests(save_dir)
    if not manifests:
        return None
    first_corrupt: Optional[Tuple[int, Exception]] = None
    for man in manifests:
        validate_run_meta(man, run_meta)
        try:
            out = _restore_manifest(save_dir, man, template)
        except ShardCorruptionError as e:
            print(f"tpudist: elastic restore: committed step "
                  f"{man['step']} is corrupt ({e}); falling back to "
                  f"the previous committed manifest",
                  file=sys.stderr, flush=True)
            if first_corrupt is None:
                first_corrupt = (int(man["step"]), e)
            continue
        if first_corrupt is not None and details is not None:
            details["fallback_from"] = first_corrupt[0]
            details["corrupt_shard"] = str(first_corrupt[1])
        return out
    raise first_corrupt[1]


def _restore_manifest(save_dir: str, manifest: Dict[str, Any],
                      template: Any) -> Tuple[Any, int, int]:
    """One manifest's restore proper (the pre-fallback body)."""
    import jax

    table, handles = _shard_table(save_dir, manifest)
    try:
        flat, treedef = jax.tree_util.tree_flatten_with_path(template)
        out_leaves = []
        for path, leaf in flat:
            name = jax.tree_util.keystr(path)
            saved_meta = (manifest.get("leaves") or {}).get(name)
            shards = table.get(name)
            if not shards:
                raise ResumeError(
                    f"manifest has no shards for leaf {name} — the "
                    f"model/optimizer structure changed since the "
                    f"checkpoint was written")
            shape = tuple(getattr(leaf, "shape", ()))
            dtype = np.dtype(getattr(leaf, "dtype", np.float32))
            if saved_meta is not None:
                if tuple(saved_meta["shape"]) != shape:
                    raise ResumeError(
                        f"leaf {name}: saved shape "
                        f"{tuple(saved_meta['shape'])} != current "
                        f"{shape} — a reshard can change the LAYOUT, "
                        f"never the global shape")
                if np.dtype(saved_meta["dtype"]) != dtype:
                    raise ResumeError(
                        f"leaf {name}: saved dtype {saved_meta['dtype']}"
                        f" != current {dtype}")
            sharding = getattr(leaf, "sharding", None)
            if sharding is None:
                full = tuple((0, d) for d in shape)
                out_leaves.append(_assemble(full, shards, dtype))
                continue
            from tpudist.parallel.sharding import norm_shard_index
            out_leaves.append(jax.make_array_from_callback(
                shape, sharding,
                lambda idx, _sh=shards, _shape=shape, _dt=dtype:
                    _assemble(norm_shard_index(idx, _shape), _sh, _dt)))
        state = jax.tree_util.tree_unflatten(treedef, out_leaves)
    finally:
        for h in handles:
            try:
                h.close()
            except Exception:
                pass
    return state, int(manifest["epoch"]), int(manifest["step_in_epoch"])


def restore_for_resume(save_dir: str, template: Any, *,
                       run_meta: Optional[Dict[str, Any]] = None,
                       details: Optional[Dict[str, Any]] = None
                       ) -> Optional[Tuple[Any, int, int, str]]:
    """The train loop's one resume entry. The elastic tree and orbax
    step dirs can coexist in one ``--save-dir`` (e.g. a run switched
    ``--ckpt-mode``), so the pick is NEWEST-WINS by checkpoint key —
    resuming an old manifest past newer orbax steps would silently
    retrain the difference. A manifest that exists but cannot restore
    (torn tree, data-cursor mismatch) falls back to orbax when orbax
    has anything; only when no fallback exists does the manifest's
    error propagate (``--resume latest`` then raises, ``auto``
    degrades to a flagged fresh start). Returns ``(state, epoch,
    step_in_epoch, source)`` with source in ``{"manifest", "orbax"}``,
    or None for a fresh start."""
    from tpudist import checkpoint as ckpt_lib

    manifest = ckpt_mod.latest_manifest(save_dir)
    orbax_step = ckpt_lib.latest_step(save_dir)
    manifest_err: Optional[Exception] = None
    if manifest is not None and (orbax_step is None
                                 or int(manifest["step"]) >= orbax_step):
        try:
            out = restore(save_dir, template, run_meta=run_meta,
                          details=details)
            if out is not None:
                return (*out, "manifest")
        except Exception as e:
            if orbax_step is None:
                raise
            manifest_err = e
            print(f"tpudist: elastic manifest restore failed ({e!r}); "
                  f"falling back to the orbax checkpoint at step "
                  f"{orbax_step}", file=sys.stderr, flush=True)
    full = ckpt_lib.restore_latest_full(save_dir, template)
    if full is not None:
        return (*full, "orbax")
    if manifest is not None and manifest_err is None:
        # manifest is older than an orbax key that then failed to
        # restore (or vanished between peek and read): still usable
        out = restore(save_dir, template, run_meta=run_meta,
                      details=details)
        if out is not None:
            return (*out, "manifest")
    return None
