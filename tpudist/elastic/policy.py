"""Auto-requeue policy: requeue a preempted/stalled job, stop a crashed one.

The launcher (launcher/launch_tpu.sh) reruns a failed training job up to
``--max-requeues`` times with exponential backoff — but ONLY when the
evidence says rerunning can help. Blindly requeuing a deterministic
crash (bad config, NaN loss, broken kernel) burns slice-hours looping on
the same failure; never requeuing turns every spot preemption into a
human page. This module is the classifier between the two, consuming
exactly the artifacts the failure path already collects:

  * the workload's exit code (``124`` = the launcher's outer ``timeout``
    fired — a hang);
  * the flight-record dumps (``flightrec.worker<i>``, obs.flightrec):
    a ``reason: stall`` dump means the watchdog saw a wedged step —
    the signature of a peer dying mid-collective;
  * the per-worker verdict files (``job_status.txt.worker<i>``,
    verdict.write_worker_verdict): a worker that VANISHED without
    writing one died un-orderly — the signature of a preemption kill
    (an orderly Python failure always reaches the verdict chain).

Stdlib-only by design: the launcher runs this on the CI host, where
neither jax nor numpy is guaranteed.

CLI (consumed by launch_tpu.sh; also usable by hand)::

    python3 -m tpudist.elastic.policy --rc 137 --attempt 0 \
        --max-requeues 3 --flightrec-dir flightrec_artifacts

prints one shell-evalable line::

    VERDICT=preemption REQUEUE=1 BACKOFF_S=10 REASON='...'

and exits 0 to requeue, 1 to stop (any other exit = the policy itself
broke; the launcher treats that as stop).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from dataclasses import dataclass
from typing import List, Optional, Sequence

SUCCESS = "success"
PREEMPTION = "preemption"
STALL = "stall"
CRASH = "crash"

# exit codes that mean "killed by signal", not "failed by exception":
# 128+SIGKILL(9)=137 / 128+SIGTERM(15)=143 are what a preemption reaper
# or an OOM-killer deliver; 124 is GNU timeout's own hang marker; 255 is
# ssh/gcloud failing to REACH a worker VM — by the time training runs
# the launcher has ssh'd every worker repeatedly (delivery, probe,
# selfcheck), so a sudden 255 means the VM itself went away.
_SIGNAL_RCS = frozenset({124, 130, 137, 143, 255})

BACKOFF_BASE_S = 10.0
BACKOFF_MAX_S = 300.0


def flightrec_reasons(flightrec_dir: Optional[str]) -> List[str]:
    """The ``reason`` field of every parseable flight record in the
    collected-artifacts directory (recursively — the launcher's scp may
    nest per-worker subdirs). Unparseable files are skipped: a torn
    dump is not evidence."""
    if not flightrec_dir or not os.path.isdir(flightrec_dir):
        return []
    out = []
    pattern = os.path.join(flightrec_dir, "**", "flightrec.worker*")
    for path in sorted(set(glob.glob(pattern, recursive=True))):
        try:
            with open(path) as f:
                rec = json.load(f)
            reason = rec.get("reason")
            if isinstance(reason, str):
                out.append(reason)
        except (OSError, ValueError):
            continue
    return out


def missing_worker_verdicts(verdict_path: Optional[str],
                            nprocs: Optional[int]) -> Optional[int]:
    """How many of the expected per-worker verdict files never landed,
    or None when there is nothing to count against (no path / no
    process count). A worker that died orderly ALWAYS writes one
    (train.main's finally); a missing file is a vanished worker."""
    if not verdict_path or not nprocs or nprocs < 1:
        return None
    missing = 0
    for i in range(nprocs):
        if not os.path.exists(f"{verdict_path}.worker{i}"):
            missing += 1
    return missing


def _worker_ids(flightrec_dir: str, prefix: str,
                attempt: Optional[int] = None) -> set:
    ids = set()
    for path in glob.glob(os.path.join(flightrec_dir, "**",
                                       f"{prefix}.worker*"),
                          recursive=True):
        tail = os.path.basename(path).rsplit(".worker", 1)[-1]
        if not tail.isdigit():
            # archived beacons (heartbeat.worker<i>.attempt<K>) are by
            # definition another attempt's evidence — never counted
            continue
        if attempt is not None and prefix == "heartbeat":
            # attempt-scoped liveness: a stale beacon from attempt N-1
            # left by a worker that never STARTED in attempt N must not
            # make it read as alive-then-vanished; the payload stamps
            # the attempt it beat for (beacons too old to carry the
            # stamp keep the pre-namespacing behavior: counted)
            try:
                with open(path) as f:
                    stamped = json.load(f).get("requeue_attempt")
            except (OSError, ValueError):
                continue        # a torn beacon is not evidence
            if isinstance(stamped, (int, float)) \
                    and int(stamped) != attempt:
                continue
        ids.add(int(tail))
    return ids


def vanished_workers(flightrec_dir: Optional[str],
                     attempt: Optional[int] = None) -> List[int]:
    """Vanished-worker inference from the collected artifacts alone (no
    --verdict/--nprocs wiring needed): every live worker writes a
    ``heartbeat.worker<i>`` beacon within seconds of starting, and every
    ORDERLY death writes a ``job_status.txt.worker<i>`` verdict
    (train.main's finally) — a worker with a beacon but no verdict died
    un-orderly, i.e. was preempted. The launcher points the workers'
    TPUDIST_VERDICT_PATH into the same OBS_DIR it collects (and clears
    both between attempts), so the sets line up per attempt; passing
    ``attempt`` additionally scopes beacons to the attempt they were
    written FOR (the payload's ``requeue_attempt`` stamp). Empty when
    beacons are absent entirely (nothing to infer from)."""
    if not flightrec_dir or not os.path.isdir(flightrec_dir):
        return []
    expected = _worker_ids(flightrec_dir, "heartbeat", attempt)
    wrote = _worker_ids(flightrec_dir, "job_status.txt")
    return sorted(expected - wrote) if expected else []


def classify(rc: int, *, flightrec_dir: Optional[str] = None,
             verdict_path: Optional[str] = None,
             nprocs: Optional[int] = None,
             attempt: Optional[int] = None) -> str:
    """Map one failed (or succeeded) run's evidence to a verdict."""
    if rc == 0:
        return SUCCESS
    reasons = flightrec_reasons(flightrec_dir)
    if rc == 124 or "stall" in reasons:
        # the outer timeout or the in-process watchdog saw a hang: the
        # classic shape of a peer preempted mid-collective — the
        # survivors wedge, the watchdog dumps, the launcher kills.
        # This check runs BEFORE the bare-signal table below on
        # purpose: `timeout -k` escalates SIGTERM→SIGKILL, so a wedged
        # run that ignores the grace signal exits 137 — with the
        # watchdog's stall dump in evidence that is STILL a stall (the
        # requeue path with the stall diagnosis attached), never a
        # crash and not a plain preemption; the signal-rc fallback only
        # applies when no stall dump landed (pinned in
        # tests/test_elastic.py)
        return STALL
    if rc in _SIGNAL_RCS:
        return PREEMPTION
    missing = missing_worker_verdicts(verdict_path, nprocs)
    if missing:
        return PREEMPTION
    if vanished_workers(flightrec_dir, attempt):
        return PREEMPTION
    return CRASH


@dataclass(frozen=True)
class Decision:
    verdict: str
    requeue: bool
    backoff_s: float
    reason: str

    def shell_line(self) -> str:
        return (f"VERDICT={self.verdict} REQUEUE={int(self.requeue)} "
                f"BACKOFF_S={self.backoff_s:g} "
                f"REASON='{self.reason}'")


def backoff_s(attempt: int, *, base_s: float = BACKOFF_BASE_S,
              max_s: float = BACKOFF_MAX_S) -> float:
    """Exponential backoff for requeue attempt ``attempt`` (0-based):
    base, 2x, 4x, ... capped — spot capacity that just vanished tends
    to stay gone for a while; hammering the queue helps nobody."""
    return min(max_s, base_s * (2.0 ** max(attempt, 0)))


def decide(rc: int, *, attempt: int, max_requeues: int,
           flightrec_dir: Optional[str] = None,
           verdict_path: Optional[str] = None,
           nprocs: Optional[int] = None,
           base_s: float = BACKOFF_BASE_S,
           max_s: float = BACKOFF_MAX_S) -> Decision:
    verdict = classify(rc, flightrec_dir=flightrec_dir,
                       verdict_path=verdict_path, nprocs=nprocs,
                       attempt=attempt)
    if verdict == SUCCESS:
        return Decision(verdict, False, 0.0, "run succeeded")
    if verdict == CRASH:
        return Decision(
            verdict, False, 0.0,
            f"rc={rc} with every worker verdict present and no stall "
            f"dump: deterministic failure — requeueing would loop on it")
    if attempt >= max_requeues:
        return Decision(
            verdict, False, 0.0,
            f"{verdict} but requeue budget exhausted "
            f"({attempt}/{max_requeues})")
    return Decision(
        verdict, True, backoff_s(attempt, base_s=base_s, max_s=max_s),
        f"{verdict} (rc={rc}), attempt {attempt + 1}/{max_requeues}: "
        f"rerun with --resume auto from the last committed manifest")


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tpudist.elastic.policy",
        description="requeue-or-stop verdict for a failed tpudist job")
    p.add_argument("--rc", type=int, required=True,
                   help="the training job's exit code")
    p.add_argument("--attempt", type=int, default=0,
                   help="0-based requeue attempts already consumed")
    p.add_argument("--max-requeues", type=int,
                   default=int(os.environ.get("MAX_REQUEUES", "0")),
                   help="requeue budget (default $MAX_REQUEUES, else 0)")
    p.add_argument("--flightrec-dir", type=str, default=None,
                   help="collected flight-record artifacts to consult")
    p.add_argument("--verdict", type=str, default=None,
                   help="verdict file base path (per-worker files are "
                        "<path>.worker<i>)")
    p.add_argument("--nprocs", type=int, default=None,
                   help="expected worker count for the vanished-worker "
                        "check")
    p.add_argument("--backoff-base-s", type=float,
                   default=float(os.environ.get("TPUDIST_REQUEUE_BACKOFF_S",
                                                BACKOFF_BASE_S)))
    p.add_argument("--backoff-max-s", type=float, default=BACKOFF_MAX_S)
    args = p.parse_args(argv)
    d = decide(args.rc, attempt=args.attempt,
               max_requeues=args.max_requeues,
               flightrec_dir=args.flightrec_dir,
               verdict_path=args.verdict, nprocs=args.nprocs,
               base_s=args.backoff_base_s, max_s=args.backoff_max_s)
    print(d.shell_line())
    return 0 if d.requeue else 1


if __name__ == "__main__":
    sys.exit(main())
