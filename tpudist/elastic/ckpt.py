"""Sharded manifest checkpoints: each worker writes only its own shards.

Orbax (tpudist.checkpoint) coordinates a multi-host save internally;
this module is the preemption-first alternative the elastic resume path
builds on, with three properties orbax's opaque layout cannot give us:

  * **Per-worker shard files.** Worker ``i`` serialises only the
    param/opt-state shards it OWNS (dedup by sharding index: a shard
    replicated across processes is written once, by the lowest-ranked
    owner) into ``steps/<step>/worker<i>.npz`` plus a shard index
    (``worker<i>.json``: global shape, dtype, and the slice each shard
    covers, per leaf). Restore can therefore reassemble ANY slice of
    any leaf from a different process/device count — the N→M reshard
    primitive (tpudist.elastic.resume).
  * **Atomic two-phase commit.** The index json is written last
    (write-temp + ``os.replace``), so its presence marks "this worker's
    shards landed". The coordinator commits ``manifest.json`` (also
    temp + rename) only after EVERY worker's index landed — a
    filesystem rendezvous rather than a collective, so a worker dying
    mid-save can never wedge the survivors in a barrier; the commit
    just never happens and the previous manifest stays authoritative.
    A kill at ANY instant leaves either the previous or the next
    fully-consistent step, never a torn checkpoint.
  * **Transparent layout.** Everything is npz + json on a filesystem
    the whole pod shares (NFS, GCS-fuse, or a local dir in tests); the
    stale leftovers of a killed run are recognisable and reaped on the
    next open (:func:`cleanup_stale`). ``gs://`` URIs are NOT handled
    here — pods writing straight to GCS keep ``--ckpt-mode orbax``.

:class:`ShardedCheckpointer` mirrors ``checkpoint.Checkpointer``'s
interface (``save(state, epoch=, step_in_epoch=)`` / ``wait`` /
``close`` / ``last_enqueue_ms`` / ``drain_ms``) so the train loop and
``bench.py --ckpt-sweep`` treat the modes interchangeably. ``save``
returns after the device→host snapshot (donation-safe: the next step
may reuse the donated buffers); the file writes and the commit run on
a background thread unless ``use_async=False``.
"""

from __future__ import annotations

import json
import os
import queue
import shutil
import sys
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

MANIFEST_SCHEMA_VERSION = 1
DEFAULT_KEEP = 3
# How long the coordinator's commit waits for every worker's shard index
# to land before giving up (the previous manifest then stays committed).
# Generous by default — a slow NFS worker must not lose a checkpoint —
# and shrunk by tests via the env override.
COMMIT_TIMEOUT_S = 300.0
# Transient-filesystem-error policy for the shard writer: a flaky NFS
# EIO / momentary ENOSPC must cost a retry, not a checkpoint — and
# exhaustion must cost THAT STEP'S commit, never a wedged writer thread
# or a dead training run (the previous manifest stays authoritative).
# Env overrides TPUDIST_CKPT_RETRIES / TPUDIST_CKPT_RETRY_BACKOFF_S.
WRITE_RETRIES = 3
WRITE_RETRY_BACKOFF_S = 0.05

# ---------------------------------------------------- chaos fault hook
# The chaos plane (tpudist.chaos) injects write-path faults through this
# module-level hook: called at named points of ShardedCheckpointer._write
# with the save's step context. A hook may raise OSError (a scripted
# transient fs error — the retry loop above absorbs it), damage the
# just-landed file (shard corruption — restore's crc check must catch
# it), or os._exit (the torn-manifest kill between index land and
# commit). None (the default) costs one attribute read per point.
_FAULT_HOOK: Optional[Callable[..., None]] = None


def set_fault_hook(hook: Optional[Callable[..., None]]) -> None:
    global _FAULT_HOOK
    _FAULT_HOOK = hook


def _fault(point: str, **ctx: Any) -> None:
    if _FAULT_HOOK is not None:
        _FAULT_HOOK(point, **ctx)


def elastic_root(save_dir: str) -> str:
    """The sharded-manifest tree lives under ``<save_dir>/elastic`` so it
    coexists with orbax step dirs in the same ``--save-dir``."""
    return os.path.join(save_dir, "elastic")


def _steps_dir(root: str) -> str:
    return os.path.join(root, "steps")


def step_dir(root: str, step: int) -> str:
    return os.path.join(_steps_dir(root), f"{step:08d}")


def manifest_path(save_dir: str) -> str:
    return os.path.join(elastic_root(save_dir), "manifest.json")


def index_name(process_index: int) -> str:
    return f"worker{process_index}.json"


def shards_name(process_index: int) -> str:
    return f"worker{process_index}.npz"


def _atomic_json(path: str, payload: Dict[str, Any]) -> None:
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1)
    os.replace(tmp, path)


def latest_manifest(save_dir: str) -> Optional[Dict[str, Any]]:
    """The committed manifest, or None when no sharded checkpoint has
    ever been committed in ``save_dir``. Only ``manifest.json`` itself
    is consulted — a ``manifest.json.tmp`` torn off by a kill
    mid-commit is ignored (and reaped by :func:`cleanup_stale`)."""
    path = manifest_path(save_dir)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def committed_manifests(save_dir: str) -> List[Dict[str, Any]]:
    """Every committed manifest still on disk, NEWEST FIRST: the
    top-level ``manifest.json`` plus the per-step copies each commit
    leaves inside its step directory. The per-step copies are what
    restore falls back onto when the newest checkpoint's shards fail
    their crc check — without them a corrupt byte would cost ALL the
    retained history, not one step. Steps newer than the top-level
    manifest are ignored (a per-step copy whose top-level flip a kill
    tore off is not committed; :func:`cleanup_stale` reaps its dir),
    and checkpoints predating the copies simply have no fallback."""
    latest = latest_manifest(save_dir)
    if latest is None:
        return []
    out = [latest]
    seen = {int(latest["step"])}
    sdir = _steps_dir(elastic_root(save_dir))
    if not os.path.isdir(sdir):
        return out
    for name in sorted(os.listdir(sdir), reverse=True):
        if not name.isdigit():
            continue
        step = int(name)
        if step in seen or step > int(latest["step"]):
            continue
        p = os.path.join(sdir, name, "manifest.json")
        if not os.path.exists(p):
            continue          # retained but never committed (or too old)
        try:
            with open(p) as f:
                man = json.load(f)
        except (OSError, ValueError):
            continue          # a torn copy is not a fallback
        if int(man.get("step", -1)) != step:
            continue
        out.append(man)
        seen.add(step)
    return out


def state_leaves(state: Any) -> List[Tuple[str, Any]]:
    """``(path_key, leaf)`` pairs in a stable order — the name contract
    both the writer and the restorer key on (jax keystr paths)."""
    import jax
    flat, _ = jax.tree_util.tree_flatten_with_path(state)
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in flat]


def cleanup_stale(save_dir: str, *, process_index: int = 0) -> List[str]:
    """Reap the leftovers of a killed run: ``*.tmp`` files anywhere in
    the elastic tree, and (coordinator only) step directories NEWER than
    the committed manifest — those are mid-flight writes whose commit
    never happened; the resumed run will re-reach and rewrite those
    steps. Committed and retained older dirs are untouched. Returns the
    removed paths (tests pin the contract)."""
    root = elastic_root(save_dir)
    removed: List[str] = []
    if not os.path.isdir(root):
        return removed
    for dirpath, _, files in os.walk(root):
        for fn in files:
            if fn.endswith(".tmp"):
                p = os.path.join(dirpath, fn)
                try:
                    os.remove(p)
                    removed.append(p)
                except OSError:
                    pass
    if process_index != 0:
        return removed
    manifest = latest_manifest(save_dir)
    committed = -1 if manifest is None else int(manifest["step"])
    sdir = _steps_dir(root)
    if os.path.isdir(sdir):
        for name in sorted(os.listdir(sdir)):
            try:
                step = int(name)
            except ValueError:
                continue
            if step > committed:
                p = os.path.join(sdir, name)
                shutil.rmtree(p, ignore_errors=True)
                removed.append(p)
    return removed


class ShardedCheckpointer:
    """Per-worker sharded checkpoint writer with coordinator commit.

    Every process constructs one and calls ``save`` at the same train
    boundaries (the same all-ranks contract as the orbax
    ``Checkpointer``). ``run_meta`` is stored verbatim in the manifest
    — the train loop passes its data cursor (seed, global batch size)
    so resume can refuse a checkpoint whose batch order the current
    config would not reproduce.
    """

    def __init__(self, save_dir: str, *, process_index: int = 0,
                 process_count: int = 1, keep: Optional[int] = DEFAULT_KEEP,
                 use_async: bool = True,
                 run_meta: Optional[Dict[str, Any]] = None,
                 commit_timeout_s: Optional[float] = None):
        self.root = elastic_root(save_dir)
        self.save_dir = save_dir
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.keep = keep
        self.use_async = use_async
        self.run_meta = dict(run_meta or {})
        # the commit rendezvous' freshness key: a corruption-FALLBACK
        # resume re-reaches steps whose dir still holds the dead
        # attempt's indexes (the dir was committed, so cleanup_stale
        # leaves it), and a commit satisfied by a peer's STALE index
        # would flip the manifest onto the very bytes the fallback
        # rejected — indexes therefore stamp the attempt they were
        # written by, and the rendezvous only counts this attempt's
        # (None = unstamped callers/old indexes keep the old behavior)
        att = self.run_meta.get("requeue_attempt")
        self._attempt = int(att) if isinstance(att, (int, float)) else None
        if commit_timeout_s is None:
            try:
                commit_timeout_s = float(os.environ.get(
                    "TPUDIST_CKPT_COMMIT_TIMEOUT_S", COMMIT_TIMEOUT_S))
            except ValueError:
                commit_timeout_s = COMMIT_TIMEOUT_S
        self.commit_timeout_s = commit_timeout_s
        try:
            self.write_retries_max = int(os.environ.get(
                "TPUDIST_CKPT_RETRIES", WRITE_RETRIES))
        except ValueError:
            self.write_retries_max = WRITE_RETRIES
        try:
            self.write_retry_backoff_s = float(os.environ.get(
                "TPUDIST_CKPT_RETRY_BACKOFF_S", WRITE_RETRY_BACKOFF_S))
        except ValueError:
            self.write_retry_backoff_s = WRITE_RETRY_BACKOFF_S
        self.last_enqueue_ms: float = 0.0
        self.last_drain_ms: float = 0.0
        self.drain_ms: float = 0.0
        self.saves: int = 0
        self.commits: int = 0           # manifests this process committed
        self.commit_failures: int = 0   # commit waits that timed out
        self.write_errors: int = 0
        self.write_retries: int = 0     # transient fs errors retried away
        self.write_skips: int = 0       # saves abandoned after exhaustion
        # steps whose shard write was abandoned: the coordinator must
        # not sit out the full commit timeout waiting for shards that
        # will never land — that step's commit is skipped outright
        self._skip_commit_steps: set = set()
        # reap the dead run's tmp files / uncommitted step dirs BEFORE
        # the first save can collide with a half-written leftover
        cleanup_stale(save_dir, process_index=self.process_index)
        self._q: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        if use_async:
            self._thread = threading.Thread(
                target=self._worker, name="tpudist-elastic-ckpt",
                daemon=True)
            self._thread.start()

    @property
    def last_save_ms(self) -> float:
        """Alias matching ``checkpoint.Checkpointer`` (the enqueue time
        is what the old field measured under async saves)."""
        return self.last_enqueue_ms

    # ------------------------------------------------------------ save
    def save(self, state: Any, *, epoch: int, step_in_epoch: int = 0
             ) -> None:
        """Snapshot this worker's shards of ``state`` and hand the write
        (and, on the coordinator, the commit) to the background thread.
        Returns once the device→host copies are done — donation-safe."""
        t0 = time.perf_counter()
        from tpudist.obs import trace as trace_lib
        step = int(state.step)
        from tpudist.parallel import sharding as shd
        with trace_lib.span("ckpt_enqueue", cat="ckpt", step=step,
                            mode="sharded"):
            index: Dict[str, Any] = {}
            arrays: Dict[str, np.ndarray] = {}
            for li, (name, leaf) in enumerate(state_leaves(state)):
                shards = []
                for si, (span, data) in enumerate(
                        shd.owned_shard_spans(leaf, self.process_index)):
                    key = f"L{li}_S{si}"
                    arrays[key] = data
                    # crc32 of the shard's raw bytes, recorded BEFORE
                    # any file I/O: restore verifies it against what
                    # the filesystem hands back, so a corrupt or
                    # truncated shard is detected — and the manifest
                    # rejected in favor of the previous committed step
                    # — instead of resuming from garbage
                    shards.append({"key": key,
                                   "start": [s for s, _ in span],
                                   "shape": list(data.shape),
                                   "crc32": zlib.crc32(data.tobytes())
                                   & 0xFFFFFFFF})
                index[name] = {
                    "shape": list(getattr(leaf, "shape", ())),
                    "dtype": str(np.dtype(getattr(leaf, "dtype",
                                                  np.float32))),
                    "shards": shards}
            job = (step, int(epoch), int(step_in_epoch), index, arrays)
            if self.use_async:
                self._q.put(("write", job))
                if self.process_index == 0:
                    self._q.put(("commit", job[:3]))
            else:
                # sync mode shares the retry/skip discipline: a
                # transient fs error exhausting its retries skips this
                # step's commit instead of killing the training run
                if self._write_retrying(*job) and self.process_index == 0:
                    self._commit(step, int(epoch), int(step_in_epoch))
        self.last_enqueue_ms = (time.perf_counter() - t0) * 1000
        self.saves += 1

    # -------------------------------------------------- writer thread
    def _worker(self) -> None:
        while True:
            kind, payload = self._q.get()
            try:
                if kind == "stop":
                    return
                elif kind == "write":
                    self._write_retrying(*payload)
                elif kind == "commit":
                    self._commit(*payload)
            except Exception as e:
                # a failed background save must not kill training; the
                # previous manifest stays committed and the error is
                # visible in the run log + the write_errors counter
                self.write_errors += 1
                print(f"tpudist: sharded ckpt {kind} failed: {e!r}",
                      file=sys.stderr, flush=True)
            finally:
                self._q.task_done()

    def _write_retrying(self, step: int, epoch: int, step_in_epoch: int,
                        index: Dict[str, Any],
                        arrays: Dict[str, np.ndarray]) -> bool:
        """Bounded retry-with-backoff around the shard write: transient
        filesystem errors (a flaky NFS EIO, momentary ENOSPC) retry;
        exhaustion skips THIS STEP's commit — the writer thread never
        wedges and the previous manifest stays authoritative. Non-OSError
        failures keep their old path (sync raises, async is caught by
        the worker loop's generic handler)."""
        delay = self.write_retry_backoff_s
        for attempt in range(self.write_retries_max + 1):
            try:
                self._write(step, epoch, step_in_epoch, index, arrays)
                return True
            except OSError as e:
                if attempt >= self.write_retries_max:
                    self.write_errors += 1
                    self.write_skips += 1
                    self._skip_commit_steps.add(step)
                    print(f"tpudist: sharded ckpt write of step {step} "
                          f"failed {attempt + 1}x ({e!r}); skipping this "
                          f"step's commit — the previous manifest stays "
                          f"committed", file=sys.stderr, flush=True)
                    return False
                self.write_retries += 1
                time.sleep(delay)
                delay *= 2
        return False

    def _write(self, step: int, epoch: int, step_in_epoch: int,
               index: Dict[str, Any], arrays: Dict[str, np.ndarray]
               ) -> None:
        d = step_dir(self.root, step)
        os.makedirs(d, exist_ok=True)
        npz = os.path.join(d, shards_name(self.process_index))
        _fault("shard_write", step=step, epoch=epoch,
               step_in_epoch=step_in_epoch, path=npz)
        tmp = f"{npz}.tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, npz)
        _fault("shard_written", step=step, epoch=epoch,
               step_in_epoch=step_in_epoch, path=npz)
        # the index lands LAST: its presence is this worker's "shards
        # landed" marker — the commit's filesystem rendezvous
        ipath = os.path.join(d, index_name(self.process_index))
        _atomic_json(ipath, {
            "schema": MANIFEST_SCHEMA_VERSION, "step": step,
            "epoch": epoch, "step_in_epoch": step_in_epoch,
            "process_index": self.process_index,
            "requeue_attempt": self._attempt, "leaves": index})
        _fault("index_written", step=step, epoch=epoch,
               step_in_epoch=step_in_epoch, path=ipath)

    # --------------------------------------------------------- commit
    def _worker_landed(self, step: int, i: int) -> bool:
        p = os.path.join(step_dir(self.root, step), index_name(i))
        if not os.path.exists(p):
            return False
        try:
            with open(p) as f:
                idx = json.load(f)
        except (ValueError, OSError):
            return False
        if int(idx.get("step", -1)) != step:
            return False
        # freshness: a previous attempt's leftover index in a re-reached
        # step dir must not satisfy THIS attempt's rendezvous — wait for
        # the peer to rewrite (unstamped indexes keep the old behavior)
        stamped = idx.get("requeue_attempt")
        if self._attempt is not None and stamped is not None \
                and int(stamped) != self._attempt:
            return False
        return True

    def _landed(self, step: int, verified: Optional[set] = None) -> bool:
        """All workers' shard indexes landed for ``step``. ``verified``
        carries the workers already validated across the commit loop's
        polls — an index is written once, atomically, so re-parsing a
        landed worker's file 20×/s for the whole wait would hammer the
        shared filesystem the save itself is contending for (256
        workers × full per-leaf metadata per poll)."""
        if verified is None:
            verified = set()
        for i in range(self.process_count):
            if i in verified:
                continue
            if not self._worker_landed(step, i):
                return False
            verified.add(i)
        return True

    def _commit(self, step: int, epoch: int, step_in_epoch: int) -> None:
        """Coordinator only: wait (bounded) for every worker's shard
        index, then atomically flip ``manifest.json`` to this step and
        apply retention. On timeout the previous manifest simply stays
        authoritative — never a partial commit. A per-step copy of the
        manifest lands inside the step dir FIRST: that copy is what
        restore falls back onto when a newer checkpoint's shards fail
        their crc check (it only becomes meaningful once the top-level
        flip succeeds, so a kill between the two writes changes
        nothing)."""
        if step in self._skip_commit_steps:
            # this worker's own shard write was abandoned after retry
            # exhaustion: the rendezvous can never complete — don't sit
            # out the full timeout on a commit that must not happen
            return
        deadline = time.monotonic() + self.commit_timeout_s
        verified: set = set()
        while not self._landed(step, verified):
            if time.monotonic() >= deadline:
                self.commit_failures += 1
                print(f"tpudist: sharded ckpt commit of step {step} timed "
                      f"out after {self.commit_timeout_s}s waiting for "
                      f"worker shards; previous manifest stays committed",
                      file=sys.stderr, flush=True)
                return
            time.sleep(min(0.05, self.commit_timeout_s / 10 or 0.05))
        with open(os.path.join(step_dir(self.root, step),
                               index_name(0))) as f:
            leaves = {name: {"shape": rec["shape"], "dtype": rec["dtype"]}
                      for name, rec in json.load(f)["leaves"].items()}
        payload = {
            "schema": MANIFEST_SCHEMA_VERSION,
            "step": step, "epoch": epoch, "step_in_epoch": step_in_epoch,
            "process_count": self.process_count,
            "ts": time.time(), "run": self.run_meta, "leaves": leaves,
            "dir": os.path.relpath(step_dir(self.root, step), self.root)}
        _atomic_json(os.path.join(step_dir(self.root, step),
                                  "manifest.json"), payload)
        _atomic_json(manifest_path(self.save_dir), payload)
        self.commits += 1
        self._retain(step)

    def _retain(self, committed: int) -> None:
        if self.keep is None:
            return
        sdir = _steps_dir(self.root)
        if not os.path.isdir(sdir):
            return
        steps = sorted(int(n) for n in os.listdir(sdir) if n.isdigit())
        old = [s for s in steps if s <= committed]
        for s in old[:-max(self.keep, 1)]:
            shutil.rmtree(os.path.join(sdir, f"{s:08d}"),
                          ignore_errors=True)

    # ---------------------------------------------------------- drain
    def wait(self) -> None:
        t0 = time.perf_counter()
        from tpudist.obs import trace as trace_lib
        with trace_lib.span("ckpt_drain", cat="ckpt", mode="sharded"):
            if self.use_async:
                self._q.join()
        self.last_drain_ms = (time.perf_counter() - t0) * 1000
        self.drain_ms += self.last_drain_ms

    def close(self) -> None:
        t0 = time.perf_counter()
        from tpudist.obs import trace as trace_lib
        with trace_lib.span("ckpt_drain", cat="ckpt", close=True,
                            mode="sharded"):
            if self.use_async and self._thread is not None:
                self._q.join()
                self._q.put(("stop", None))
                self._thread.join(timeout=10.0)
                self._thread = None
        self.last_drain_ms = (time.perf_counter() - t0) * 1000
        self.drain_ms += self.last_drain_ms
