"""tpudist.elastic — preemption survival for pod training runs.

The flight recorder (obs.heartbeat) and pod tracer (obs.trace) *detect*
a dying run; this package *survives* one. Queued/spot TPU capacity is
preemptible by design, so the acceptance framework's production story
needs three layers (ROADMAP item 1):

  * :mod:`tpudist.elastic.ckpt` — **sharded manifest checkpoints**:
    each worker asynchronously writes only its OWN param/opt-state
    shards plus a shard index; the coordinator commits ``manifest.json``
    atomically (write-temp + rename) only after every worker's shards
    landed, so a kill at any instant leaves either the previous or the
    next fully-consistent step — never a torn checkpoint.
  * :mod:`tpudist.elastic.resume` — **elastic resume**: restore maps
    the saved shards onto the *current* mesh even when the host/device
    count changed (N→M reshard via per-leaf slice assembly, with a
    zero-copy fast path when the layout matches), validates the
    step/epoch/data-cursor metadata, and hands the train loop the
    resume position its superstep realignment already consumes —
    bitwise-identical continuation on the same mesh, loss-correct on a
    reshaped one.
  * :mod:`tpudist.elastic.policy` — **auto-requeue policy**: a jax-free
    classifier the launcher consults after a failed run — preemption /
    stall (requeue with exponential backoff, ``--resume auto``) vs
    deterministic crash (stop) — fed by the watchdog's flight-record
    verdicts and the per-worker verdict files.

Import discipline: this ``__init__`` and :mod:`policy` are stdlib-only
(the launcher runs the policy on a CI host with no jax installed);
``ckpt``/``resume`` import jax/numpy at module level and are imported
lazily by their callers.
"""

__all__ = ["ckpt", "policy", "resume"]
