"""Training engine — the DeepSpeed-engine equivalent, TPU-native.

Reference counterpart: ``deepspeed.initialize`` + ``model_engine.backward()``
/ ``.step()`` (reference ``train.py:87-93,113-114``), where the gradient
all-reduce is hidden inside the engine. Here the engine is a pytree
(``TrainState``) plus ONE compiled function:

  * **DP path (shard_map)** — when only the ``data`` mesh axis is >1, the
    train step is ``shard_map``-ped with an explicit
    ``lax.psum(grads, 'data')``: the collective under test is visible in the
    program, exactly what a fabric acceptance test wants.
  * **General path (jit + shardings)** — FSDP/tensor layouts annotate params
    with PartitionSpecs and let XLA's SPMD partitioner insert all-gathers /
    reduce-scatters / psums (the scaling-book recipe); no hand-written
    collectives to get wrong.

Context- and pipeline-parallel meshes build their loss through the
models' shard_map-based builders (make_cp_loss_fn, parallel.pipeline)
inside the general path. Both engine paths produce bitwise-identical math
on the same mesh ordering for the dense models (the MoE's group-local
routing is the documented exception, models/moe.py); tests assert
DP-vs-single-device and FSDP-vs-DP agreement.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from tpudist.config import TrainConfig
from tpudist.models import get_model
from tpudist.parallel import sharding as shd
from tpudist.utils import compat


class TrainState(NamedTuple):
    step: jax.Array          # int32 global step counter
    params: Any
    opt_state: Any


def make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    """Adam, parity with ``torch.optim.Adam(lr)`` (reference train.py:85).

    Under mixed precision the FIRST moment is stored bf16 (optax
    ``mu_dtype`` — the standard low-precision-optimizer-state trade; the
    variance stays f32 for dynamic range): at the flagship shape the mu
    buffer halves, ~0.54 GB of HBM the step no longer stores or streams.
    f32 runs keep exact parity with the reference trajectory.

    ``--adam-nu-dtype bfloat16`` additionally stores the SECOND moment
    bf16 with STOCHASTIC rounding at store (opt-in; see
    :func:`_stochastic_round_bf16` — nearest-rounding would freeze the
    EMA, whose per-step relative change is below the bf16 half-ulp).
    The win is HBM traffic on big optimizer states: ~2.7 GB/step off
    the MoE model's 674M-param nu read+write (~3 ms/step on v5e,
    DESIGN.md MoE account). The update math runs in f32 either way:
    moments are upcast at use, rounded only at store; trajectory
    agreement and EMA-decay tracking are pinned in
    tests/test_engine.py."""
    mu_dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else None
    if cfg.adam_nu_dtype == "bfloat16":
        return _adam_low_precision_nu(cfg.lr, mu_dtype=mu_dtype)
    return optax.adam(cfg.lr, mu_dtype=mu_dtype)


def _stochastic_round_bf16(x: jax.Array, count: jax.Array,
                           salt: int) -> jax.Array:
    """f32 → bf16 with STOCHASTIC rounding: add uniform dither in
    [0, ulp) to the low 16 mantissa bits, then truncate. Unbiased —
    E[sr(x)] = x — which is what makes a bf16-stored EMA work at all:
    round-to-NEAREST freezes the second moment once its per-step relative
    change (1−b2 = 1e-3) drops below the bf16 half-ulp (~2e-3), so nu
    ratchets to its historical max and the effective step size never
    recovers (r5 review finding). With SR the sub-ulp updates land with
    probability proportional to their size, so the EMA tracks in
    expectation — the same reason TPUs do hardware SR for low-precision
    accumulation.

    The dither is an integer HASH of (flat element index, step count,
    per-leaf salt) — murmur-style multiply/xor-shift mixing — NOT a
    threefry PRNG: counter-based jax.random.bits over the 674M-element
    MoE state measured ~10 ms/step, eating the ~3 ms the bf16 store
    saves (r5 measured). Rounding dither needs uniformity and
    step-decorrelation, not cryptographic strength; the EMA-decay test
    (tests/test_engine.py) pins that this hash's dither actually lets
    the moment track."""
    u32 = lambda v: jnp.uint32(v)
    bits = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    idx = jax.lax.iota(jnp.uint32, x.size).reshape(x.shape)
    h = idx * u32(0x9E3779B1) + count.astype(jnp.uint32) * u32(0x85EBCA6B) \
        + u32(salt * 0xC2B2AE35 & 0xFFFFFFFF)
    h = h ^ (h >> 15)
    h = h * u32(0x27D4EB2F)
    h = h ^ (h >> 13)
    noise = h >> 16                      # 16 uniform dither bits
    bits = (bits + noise) & u32(0xFFFF0000)
    return jax.lax.bitcast_convert_type(bits, jnp.float32).astype(
        jnp.bfloat16)


def _adam_low_precision_nu(lr: float, *, b1: float = 0.9, b2: float = 0.999,
                           eps: float = 1e-8,
                           mu_dtype=None) -> optax.GradientTransformation:
    """optax.adam with the second moment STORED bf16 (optax exposes only
    ``mu_dtype``). Same math in f32 — decay, bias correction, rsqrt —
    with nu stochastically rounded to bf16 at store (see
    :func:`_stochastic_round_bf16` for why nearest-rounding is wrong
    here) and upcast at use. The SR dither hashes (element index, step
    count, leaf index), so the update stays a pure function of
    (state, grads)."""

    def init(params):
        mu = jax.tree.map(
            lambda p: jnp.zeros_like(p, dtype=mu_dtype or p.dtype), params)
        nu = jax.tree.map(lambda p: jnp.zeros_like(p, jnp.bfloat16), params)
        return optax.ScaleByAdamState(
            count=jnp.zeros([], jnp.int32), mu=mu, nu=nu)

    def update(grads, state, params=None):
        count = state.count + 1
        f32 = lambda t: jax.tree.map(lambda x: x.astype(jnp.float32), t)
        mu = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g,
                          f32(state.mu), f32(grads))
        nu = jax.tree.map(lambda v, g: b2 * v + (1 - b2) * g * g,
                          f32(state.nu), f32(grads))
        c1 = 1 - b1 ** count.astype(jnp.float32)
        c2 = 1 - b2 ** count.astype(jnp.float32)
        updates = jax.tree.map(
            lambda m, v: -lr * (m / c1) / (jnp.sqrt(v / c2) + eps), mu, nu)
        mu_store = jax.tree.map(
            lambda x: x.astype(mu_dtype) if mu_dtype else x, mu)
        leaves, treedef = jax.tree.flatten(nu)
        nu_store = jax.tree.unflatten(treedef, [
            _stochastic_round_bf16(leaf, count, i)
            for i, leaf in enumerate(leaves)])
        return updates, optax.ScaleByAdamState(
            count=count, mu=mu_store, nu=nu_store)

    return optax.GradientTransformation(init, update)


def _compute_dtype(cfg: TrainConfig):
    return jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32


def _device_hbm_bytes() -> float:
    """Per-device accelerator memory for the head policy. Env override
    TPUDIST_HBM_BYTES (tests pin it for determinism), else the backend's
    reported limit, else a 16 GB v5e-class default (CPU backends report
    no limit; the policy then errs toward the plain head at test shapes,
    which is what the CPU reference path wants)."""
    import os
    env = os.environ.get("TPUDIST_HBM_BYTES")
    if env:
        return float(env)
    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats and stats.get("bytes_limit"):
            return float(stats["bytes_limit"])
    except Exception:
        pass
    return 16e9


def _resolve_lm_head(cfg: TrainConfig,
                     mesh: Mesh | None) -> tuple[bool, int]:
    """cfg.lm_head -> concrete (fused_xent, xent_chunks) for this run.

    ``auto`` (the default) honors an explicit --fused-xent/--xent-chunks,
    else asks models.transformer.pick_lm_head with per-DEVICE head tokens
    (the logits live batch/fsdp/context-sharded) and an analytic train-
    state estimate (f32 master + mu/nu at their configured storage
    dtypes: 12 B/param full-f32 down to 8 B with bf16 mu and nu) —
    analytic rather than memory_stats so the decision does not depend on
    whether init_state already materialised the state."""
    if cfg.lm_head != "auto":
        # a forced mode with a CONTRADICTORY explicit flag is a config
        # error (a stale --fused-xent in a launch script must not be
        # silently dropped), not a precedence question
        if cfg.lm_head == "plain" and (cfg.fused_xent or cfg.xent_chunks):
            raise ValueError(
                "--lm-head plain contradicts --fused-xent/--xent-chunks")
        if cfg.lm_head == "fused" and cfg.xent_chunks:
            raise ValueError("--lm-head fused contradicts --xent-chunks")
        if cfg.lm_head == "chunked" and cfg.fused_xent:
            raise ValueError("--lm-head chunked contradicts --fused-xent")
    if cfg.lm_head == "plain":
        return False, 0
    if cfg.lm_head == "fused":
        return True, 0
    if cfg.lm_head == "chunked":
        return False, cfg.xent_chunks or 4
    if cfg.lm_head != "auto":
        raise ValueError(f"unknown --lm-head {cfg.lm_head!r}")
    if cfg.fused_xent or cfg.xent_chunks:
        return cfg.fused_xent, cfg.xent_chunks
    return _auto_lm_head(cfg, mesh)


def _auto_lm_head(cfg: TrainConfig, mesh: Mesh | None) -> tuple[bool, int]:
    """The auto policy pick, logged at rank 0 — here, inside the single
    source of truth, not re-derived at call sites (r5 review). Dedup is
    once per resolved CHOICE per process: make_loss_fn runs at least
    twice per run (train + eval), and a repeat of the same line carries
    no information; a changed choice always prints."""
    from tpudist.models import transformer as T
    m = cfg.model
    batch_shards = 1 if mesh is None else (
        mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1))
    ctx = 1 if mesh is None else mesh.shape.get("context", 1)
    n_tok = (max(cfg.batch_size // max(batch_shards, 1), 1)
             * max(m.max_seq_len // max(ctx, 1), 1))
    hd = m.d_model // m.n_heads
    attn = 2 * m.d_model * m.d_model + 2 * m.d_model * m.n_kv_heads * hd
    ffn = 3 * m.d_model * m.d_ff
    expert_mult = m.n_experts if m.name == "moe" else 1
    # per-device state share: fsdp and tensor shard every param's storage;
    # the expert axis additionally shards the (n_experts×) FFN weights
    wshards = 1 if mesh is None else (
        mesh.shape.get("fsdp", 1) * mesh.shape.get("tensor", 1))
    eshards = 1 if mesh is None else mesh.shape.get("expert", 1)
    n_params_dev = (m.vocab_size * m.d_model
                    + m.n_layers * attn
                    + m.n_layers * ffn * expert_mult
                    / max(eshards, 1)) / max(wshards, 1)
    # f32 master (4) + mu (bf16 under mixed precision, else f32) + nu
    # (bf16 when --adam-nu-dtype says so, else f32)
    state_bytes_per_param = (4 + (2 if cfg.dtype == "bfloat16" else 4)
                             + (2 if cfg.adam_nu_dtype == "bfloat16" else 4))
    dtype_bytes = 2 if cfg.dtype == "bfloat16" else 4
    fused_xent, xent_chunks = T.pick_lm_head(
        n_tok, m.vocab_size, m.d_model, m.n_layers, dtype_bytes,
        n_params_dev * state_bytes_per_param,
        _device_hbm_bytes())
    choice = ("fused" if fused_xent
              else f"chunked({xent_chunks})" if xent_chunks else "plain")
    if choice not in _AUTO_HEAD_LOGGED:
        _AUTO_HEAD_LOGGED.add(choice)
        from tpudist.metrics import log0
        log0(f"tpudist: --lm-head auto -> {choice}")
    return fused_xent, xent_chunks


_AUTO_HEAD_LOGGED: set = set()


def make_loss_fn(cfg: TrainConfig, mesh: Mesh | None = None, *,
                 constrain_logits: bool = False) -> Callable:
    """(params, batch) -> scalar loss, for the configured model.

    With a mesh whose ``context`` axis is >1, any model providing
    ``make_cp_loss_fn`` (transformer, moe) runs context-parallel —
    sequence sharded, ring or ulysses attention per ``cfg.cp_impl``.

    ``constrain_logits`` is only legal (and only needed) under the
    jit+shardings train path — a NamedSharding constraint inside the
    fully-manual shard_map DP path is an error."""
    model = get_model(cfg.model.name)
    dt = _compute_dtype(cfg)
    if (mesh is not None and mesh.shape.get("expert", 1) > 1
            and cfg.model.name != "moe"):
        # without expert-sharded weights the axis silently replicates all
        # compute — half the slice doing duplicate work is a config error
        raise ValueError(f"--expert > 1 requires --model moe; "
                         f"{cfg.model.name!r} has no expert-sharded params")
    if cfg.model.name == "mlp":
        if mesh is not None and mesh.shape.get("pipe", 1) > 1:
            raise ValueError("pipeline parallelism requires a layered "
                             "model (transformer/moe), not mlp")
        return functools.partial(model.loss_fn, dtype=dt)

    fused_xent, xent_chunks = _resolve_lm_head(cfg, mesh)
    pp = mesh is not None and mesh.shape.get("pipe", 1) > 1
    cp = mesh is not None and mesh.shape.get("context", 1) > 1
    if pp:
        if cp:
            raise ValueError(
                "pipe and context parallelism both manualize their own "
                "mesh axis in a shard_map and do not compose; pick one")
        from tpudist.config import resolve_pipeline_interleave
        from tpudist.parallel.pipeline import make_pp_loss_fn
        pp_loss = make_pp_loss_fn(cfg.model, mesh,
                                  n_microbatches=cfg.pp_microbatches,
                                  dtype=dt, remat=cfg.remat,
                                  xent_chunks=xent_chunks,
                                  fused_xent=fused_xent,
                                  interleave=resolve_pipeline_interleave(
                                      cfg))

        def loss(params, batch):
            tokens = batch[0] if isinstance(batch, tuple) else batch
            return pp_loss(params, tokens)
        return loss
    if cp:
        if not hasattr(model, "make_cp_loss_fn"):
            raise ValueError(
                f"context parallelism is not implemented for model "
                f"{cfg.model.name!r}")
        cp_loss = model.make_cp_loss_fn(cfg.model, mesh, dtype=dt,
                                        remat=cfg.remat,
                                        xent_chunks=xent_chunks,
                                        fused_xent=fused_xent,
                                        impl=cfg.cp_impl)

        def loss(params, batch):
            tokens = batch[0] if isinstance(batch, tuple) else batch
            return cp_loss(params, tokens)
        return loss

    logits_sh = None
    if mesh is not None and constrain_logits:
        # Batch dims follow the batch layout; the vocab dim rides the tensor
        # axis so the tied-head backward (dE = dlogitsᵀ·h, vocab-sharded
        # embed grad) consumes dlogits natively — without this the
        # partitioner demands a batch→vocab reshard of the (b,s,v) cotangent
        # it can only satisfy by full rematerialisation (dp+fsdp+tensor).
        vocab_axis = ("tensor" if cfg.model.vocab_size
                      % mesh.shape.get("tensor", 1) == 0 else None)
        logits_sh = NamedSharding(
            mesh, P(("data", "fsdp"), None, vocab_axis))

    def loss(params, batch):
        tokens = batch[0] if isinstance(batch, tuple) else batch
        return model.loss_fn(params, tokens, cfg.model, dtype=dt,
                             remat=cfg.remat, xent_chunks=xent_chunks,
                             fused_xent=fused_xent,
                             logits_sharding=logits_sh)
    return loss


def init_state(key: jax.Array, cfg: TrainConfig,
               mesh: Mesh | None = None) -> TrainState:
    """Init params + opt state, placed into their sharded layout if a mesh is
    given. Init is seeded → deterministic across process counts (the
    convergence oracle depends on this; SURVEY.md §7 "hard parts")."""
    model = get_model(cfg.model.name)
    params = model.init(key, cfg.model)
    tx = make_optimizer(cfg)
    opt_state = tx.init(params)
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       opt_state=opt_state)
    if mesh is not None:
        state = jax.device_put(state, state_shardings(cfg, mesh))
    return state


def state_shardings(cfg: TrainConfig, mesh: Mesh) -> TrainState:
    """NamedShardings for the full TrainState. Opt-state moments share the
    params' layout (ZeRO-style: optimizer state lives where the shard
    lives); scalar leaves are replicated."""
    model = get_model(cfg.model.name)
    params_shape = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), cfg.model))
    # drop axes that don't divide a dim (vocab 97 over fsdp=2 → replicated)
    pspecs = shd.sanitize_specs(params_shape, model.param_specs(cfg.model),
                                mesh)
    psh = shd.named(mesh, pspecs)
    # optax adam state is a tuple of states where mu/nu are params-shaped
    # pytrees; those subtrees get the params' layout (ZeRO-style: optimizer
    # state lives with the shard), everything else is replicated.
    params_struct = jax.tree.structure(psh)
    tx = make_optimizer(cfg)
    opt_shape = jax.eval_shape(tx.init, params_shape)
    # Walk the opt-state shape; replace params-shaped subtrees with psh.
    opt_sh = _match_subtrees(opt_shape, params_struct, psh, mesh)
    return TrainState(step=NamedSharding(mesh, P()), params=psh,
                      opt_state=opt_sh)


def _match_subtrees(shape_tree, params_struct, psh, mesh):
    """Replace every params-structured subtree of an optax state shape with
    the params shardings; replicate everything else."""
    def rec(node):
        try:
            if jax.tree.structure(node) == params_struct:
                return psh
        except Exception:
            pass
        if isinstance(node, tuple) and not hasattr(node, "shape"):
            out = tuple(rec(c) for c in node)
            return type(node)(*out) if hasattr(node, "_fields") else out
        if isinstance(node, list):
            return [rec(c) for c in node]
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        return NamedSharding(mesh, P())
    return rec(shape_tree)


def _microbatch(loss_fn, params, batch, n_accum: int):
    """Gradient accumulation via lax.scan over microbatches (the reference
    configured accumulation off, train.py:80; we support it properly)."""
    if n_accum == 1:
        return jax.value_and_grad(loss_fn)(params, batch)

    def split(x):
        return x.reshape(n_accum, x.shape[0] // n_accum, *x.shape[1:])
    micro = jax.tree.map(split, batch)

    def body(carry, mb):
        loss, grads = jax.value_and_grad(loss_fn)(params, mb)
        acc_loss, acc_g = carry
        return (acc_loss + loss,
                jax.tree.map(jnp.add, acc_g, grads)), None
    zero = (jnp.zeros((), jnp.float32),
            jax.tree.map(jnp.zeros_like, params))
    (loss, grads), _ = lax.scan(body, zero, micro)
    inv = 1.0 / n_accum
    return loss * inv, jax.tree.map(lambda g: g * inv, grads)


def _build_step_body(cfg: TrainConfig, mesh: Mesh):
    """The shared single-step body behind :func:`make_train_step` and
    :func:`make_superstep`: ``(TrainState, batch) -> (TrainState, loss)``.

    Returns ``(body, dp, st_sh)``: ``dp`` True selects the explicit-psum
    shard_map path (pure-DP meshes — the body then contains the visible
    gradient all-reduce and must trace inside a fully-manual shard_map);
    otherwise the body carries the jit+shardings path's constraint
    annotations and ``st_sh`` holds the TrainState's NamedShardings.
    """
    tx = make_optimizer(cfg)
    dp = shd.pure_dp(mesh)
    # the logits constraint belongs to the jit+shardings path only — inside
    # the shard_map DP body every mesh axis is manual and a NamedSharding
    # constraint is rejected at trace time
    loss_fn = make_loss_fn(cfg, mesh, constrain_logits=not dp)
    st_sh = None if dp else state_shardings(cfg, mesh)
    from tpudist.config import resolve_cross_slice, resolve_grad_overlap
    overlap_mode, bucket_bytes = resolve_grad_overlap(cfg)
    if overlap_mode != "off" and not dp:
        if any(int(s) > 1 for s in mesh.devices.shape):
            # the bucketed schedule rewrites the PROGRAM's explicit
            # psums; on jit+shardings meshes the gradient reduction is
            # inserted by the partitioner and there is nothing
            # program-level to re-schedule — a silently-inert flag
            # would fake the acceptance signal, so refuse loudly
            raise ValueError(
                f"--grad-overlap {overlap_mode} requires the explicit-"
                f"collective pure-DP mesh (only the 'data' axis > 1); "
                f"this mesh routes gradients through the jit+shardings "
                f"partitioner")
        # a single-device mesh has no all-reduce at all: the flag is
        # inert (a laptop dry-run of a pod launch script must not crash)
        overlap_mode = "off"
    cross_mode = resolve_cross_slice(cfg)
    slice_groups = None
    if cross_mode == "hierarchical" and not dp:
        if any(int(s) > 1 for s in mesh.devices.shape):
            # same refusal logic as --grad-overlap: the ladder rewrites
            # explicit psums, and the jit+shardings partitioner owns the
            # gradient reduce on non-DP meshes
            raise ValueError(
                f"--cross-slice hierarchical requires the explicit-"
                f"collective pure-DP mesh (only the 'data' axis > 1); "
                f"this mesh routes gradients through the jit+shardings "
                f"partitioner")
        cross_mode = "flat"
    if dp:
        from tpudist.parallel import mesh as mesh_lib
        slice_groups = mesh_lib.data_slice_groups(mesh)
        if cross_mode == "hierarchical" and slice_groups is None:
            # single slice: there is no DCN phase to shard, and lowering
            # the ladder anyway would emit dead in-slice scatter/gather
            # phases. Downgrade LOUDLY — tests and operators read this
            # line to know the program is the flat one.
            from tpudist.metrics import log0
            log0("tpudist: --cross-slice hierarchical downgraded to "
                 "flat: single-slice mesh (no cross-slice DCN phase to "
                 "shard)")
            cross_mode = "flat"

    def sgd_update(state: TrainState, loss, grads):
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return TrainState(step=state.step + 1, params=new_params,
                          opt_state=new_opt), loss

    if dp:
        from tpudist.parallel import overlap as overlap_lib

        def body(state: TrainState, batch):
            loss, grads = _microbatch(loss_fn, state.params, batch,
                                      cfg.grad_accum_steps)
            # THE collective under test: gradient all-reduce over ICI/DCN
            # (reference equivalent: NCCL all-reduce inside
            # model_engine.backward(), train.py:113). The schedule is a
            # program property (parallel.overlap): "off" pins the
            # trailing-barrier baseline, "bucketed" chains size-bounded
            # per-bucket reduces behind the backward — bitwise-identical
            # math either way, only the exposed-comm fraction moves.
            grads = overlap_lib.grad_mean(grads, "data",
                                          mode=overlap_mode,
                                          bucket_bytes=bucket_bytes,
                                          cross=cross_mode,
                                          slice_groups=slice_groups)
            loss = lax.pmean(loss, "data")
            return sgd_update(state, loss, grads)
    else:
        def body(state: TrainState, batch):
            # Pin the weights to their layout *inside* the traced body: the
            # transpose of a sharding constraint constrains the cotangent,
            # so the scan-transpose gradient accumulation of the stacked
            # layer weights keeps the params' sharding instead of letting
            # the partitioner pick one it then can't reconcile
            # (spmd_partitioner "involuntary full rematerialization" on the
            # grad add_any).
            params = jax.lax.with_sharding_constraint(state.params,
                                                      st_sh.params)
            loss, grads = _microbatch(loss_fn, params, batch,
                                      cfg.grad_accum_steps)
            grads = jax.lax.with_sharding_constraint(grads, st_sh.params)
            return sgd_update(state, loss, grads)
    return body, dp, st_sh


def _arg_specs(args):
    """Shape/dtype/sharding skeletons of a call's arguments — what
    ``jit.lower`` needs, WITHOUT keeping any buffer alive (holding the
    last staged slab would break the streaming pipeline's ≤2-resident
    guarantee; donated states are deleted but their avals survive).
    Only NamedShardings are kept: host-created scalars (total, lo, hi)
    carry a SingleDeviceSharding that would contradict the mesh-wide
    state at lowering — the real call passes them uncommitted and the
    specs must reproduce that."""
    def spec(a):
        sh = getattr(a, "sharding", None)
        if not isinstance(sh, NamedSharding):
            sh = None
        return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh)
    return jax.tree.map(spec, args)


def _cost_analysis_hook(jitted, cell) -> Callable:
    """Build the ``.cost_analysis()`` accessor attached to the step /
    superstep callables: XLA's cost properties (flops, bytes accessed)
    of the EXACT program the run dispatched (tpudist.obs.mfu reads this
    for the run-end roofline record). ``cell[0]`` holds the first call's
    arg specs. Lowering + compiling here is off the step path, runs at
    most once per run, and hits the persistent compilation cache when
    one is configured; any failure degrades to None — observability
    must never fail a run."""
    def cost_analysis():
        if cell[0] is None:
            return None
        try:
            return compat.cost_analysis(jitted.lower(*cell[0]).compile())
        except Exception:
            return None
    return cost_analysis


def _memory_analysis_hook(jitted, cell) -> Callable:
    """Build the ``.memory_analysis()`` accessor attached beside
    ``.cost_analysis()``: XLA's memory plan for the EXACT program the
    run dispatched — argument/output/temp/generated-code bytes
    (tpudist.obs.memledger's program_temp bucket reads this). Same
    contract as the cost hook: lowering hits jit's trace cache after
    the first call; None before the first call, on backends without
    memory planning, or on any failure — observability must never fail
    a run."""
    def memory_analysis():
        if cell[0] is None:
            return None
        try:
            mem = compat.memory_analysis(
                jitted.lower(*cell[0]).compile())
            return mem or None
        except Exception:
            return None
    return memory_analysis


def _lowered_text_hook(jitted, cell) -> Callable:
    """Build the ``.lowered_text()`` accessor attached beside
    ``.cost_analysis()``: the StableHLO text of the EXACT program the
    run dispatched (obs.devtime.collective_bytes parses its collective
    ops into the per-fabric byte accounting the devtime record and the
    DCN-bytes gauge carry). Lowering hits jit's trace cache after the
    first call; None before the first call or on any failure —
    observability must never fail a run."""
    def lowered_text():
        if cell[0] is None:
            return None
        try:
            return jitted.lower(*cell[0]).as_text()
        except Exception:
            return None
    return lowered_text


def make_train_step(cfg: TrainConfig, mesh: Mesh) -> Callable:
    """Build the compiled train step: (TrainState, batch) -> (TrainState, loss).

    Chooses the explicit-psum shard_map path for pure-DP meshes, else the
    jit+shardings path. Loss returned is the global mean. The returned
    callable exposes ``.cost_analysis()`` (compiled-program flops/bytes,
    None before the first call) for the observability layer.
    """
    body, dp, st_sh = _build_step_body(cfg, mesh)

    if dp:
        # --- DP path: shard_map with explicit gradient all-reduce ---
        def jitted(state, batch):
            # batch specs are built per-leaf (x is 2-D, labels are 1-D);
            # re-wrapping per trace is free — jit caches by structure.
            bspecs = jax.tree.map(lambda x: shd.batch_spec(x.ndim), batch)
            spmd = compat.shard_map(body, mesh=mesh,
                                    in_specs=(P(), bspecs),
                                    out_specs=(P(), P()), check_vma=False)
            return spmd(state, batch)
        # donate the incoming state like the general path does: the update
        # writes in place instead of carrying two copies of params+opt
        # state per step
        jitted = jax.jit(jitted, donate_argnums=(0,))
    else:
        # --- general path: jit + shardings, XLA inserts collectives ---
        jitted = jax.jit(body, in_shardings=(st_sh, None),
                         out_shardings=(st_sh, NamedSharding(mesh, P())),
                         donate_argnums=(0,))

    _specs: list = [None]

    def step(state, batch):
        staged = shd.put_batch(mesh, batch)
        if _specs[0] is None:
            _specs[0] = _arg_specs((state, staged))
        return jitted(state, staged)
    step.cost_analysis = _cost_analysis_hook(jitted, _specs)
    step.lowered_text = _lowered_text_hook(jitted, _specs)
    step.memory_analysis = _memory_analysis_hook(jitted, _specs)
    return step


def state_bytes_per_device(state) -> int:
    """Largest per-device byte footprint of a (possibly sharded) pytree —
    the params/opt-state term of the staging-budget estimate
    (config.resolve_staging_budget_bytes). Counted from each leaf's
    addressable shards so FSDP/TP layouts report their true per-device
    share while replicated leaves count in full."""
    per: dict = {}
    for leaf in jax.tree.leaves(state):
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:
            n = getattr(leaf, "nbytes", 0)
            for d in jax.local_devices():
                per[d.id] = per.get(d.id, 0) + n // max(
                    jax.local_device_count(), 1)
            continue
        for sh in shards:
            per[sh.device.id] = per.get(sh.device.id, 0) + sh.data.nbytes
    return max(per.values()) if per else 0


def make_superstep(cfg: TrainConfig, mesh: Mesh, k: int) -> Callable:
    """Compiled multi-step "superstep" dispatch:
    ``(TrainState, total, slab, lo, hi) -> (TrainState, total,
    per_step_losses)``.

    Wraps the same per-step body as :func:`make_train_step` in a
    ``lax.scan`` over the slab's leading (step) axis — ONE host dispatch
    and ONE fence per ``k`` steps instead of ``k`` of each, which is the
    whole game for the paper's deliberately dispatch-bound workload. The
    slab is a device-resident ``(k, local_batch, ...)`` pytree (staged by
    ``sharding.put_epoch``, whole-epoch or streamed slab-wise per
    ``sharding.plan_slabs``).

    The slab's step axis is always EXACTLY ``k`` long; ``lo``/``hi``
    bound the valid steps inside it (``lo <= idx < hi``). Steps outside
    the bounds are MASKED out via ``lax.cond``: the skip branch passes
    the carried state/total through untouched. ``cond`` rather than a
    ``where``-select on the outputs because a select makes the carried
    state a second consumer of the update arithmetic, which changes
    XLA's fusion (FMA contraction) of the Adam update on the CPU backend
    and costs the bitwise-parity guarantee at the ULP level (measured:
    3/64 weights off by 1 ULP after 8 steps); ``cond`` isolates the body
    in its own branch computation, so valid steps lower identically to
    the unmasked scan. One compiled program then serves every slab in
    the run — the zero-padded trailing partial superstep (``hi < k``)
    and the mid-epoch-resume realignment slab (``lo > 0``) included —
    where the old variable-length tail forced a second compile per
    epoch. ``lo``/``hi`` are traced scalars, so their values never
    recompile; ``superstep.traces`` counts actual retraces (tests and
    ``bench.py --staging-sweep`` pin it to 1).

    Donation contract (audited for the staging pipeline): the incoming
    ``state`` and ``total`` are donated — the update writes in place, so
    no second copy of params+opt state sits beside the staged slabs. The
    slab argument is deliberately NOT donated: no output of the scan
    shares its ``(k, batch, ...)`` shape, so XLA could never alias it
    (donation would only emit an unusable-donation warning per compile
    and free nothing early). Slab memory is reclaimed by reference
    death instead — each k-slice dies after its dispatch, and the
    streaming loop drops each staged slab as soon as its last superstep
    is dispatched, keeping at most two slabs resident.

    The carried ``total`` accumulates each valid step's global-mean loss
    in step order (``((total+l0)+l1)+…`` — the masked select returns the
    bitwise-identical sum for valid steps), so the epoch's running loss
    sum and the stdout ``Avg loss`` stay bitwise-identical to per-step
    dispatch. Per-step losses come back as a ``k``-vector; entries
    outside ``[lo, hi)`` are meaningless and must not be read.
    """
    if k < 1:
        raise ValueError(f"superstep length must be >= 1, got {k}")
    body, dp, st_sh = _build_step_body(cfg, mesh)
    traces: list = []

    def super_body(state, total, slab, lo, hi):
        traces.append(1)   # trace-time marker: one entry per compilation

        def scan_body(carry, xs):
            state, total = carry
            batch, idx = xs
            valid = (idx >= lo) & (idx < hi)

            def run(ops):
                state, total, batch = ops
                state, loss = body(state, batch)
                return state, total + loss, loss

            def skip(ops):
                state, total, _ = ops
                # emitted loss for masked steps is a placeholder; the
                # train loop never reads outside [lo, hi)
                return state, total, jnp.float32(0)

            state, total, loss = lax.cond(valid, run, skip,
                                          (state, total, batch))
            return (state, total), loss

        n = jax.tree.leaves(slab)[0].shape[0]
        (state, total), losses = lax.scan(
            scan_body, (state, total), (slab, jnp.arange(n)))
        return state, total, losses

    if dp:
        def jitted(state, total, slab, lo, hi):
            sspecs = jax.tree.map(lambda x: shd.epoch_spec(x.ndim), slab)
            spmd = compat.shard_map(super_body, mesh=mesh,
                                    in_specs=(P(), P(), sspecs, P(), P()),
                                    out_specs=(P(), P(), P()),
                                    check_vma=False)
            return spmd(state, total, slab, lo, hi)
        jitted = jax.jit(jitted, donate_argnums=(0, 1))
    else:
        rep = NamedSharding(mesh, P())
        jitted = jax.jit(super_body,
                         in_shardings=(st_sh, rep, None, None, None),
                         out_shardings=(st_sh, rep, rep),
                         donate_argnums=(0, 1))

    _specs: list = [None]

    def superstep(state, total, slab, lo, hi):
        args = (state, total, slab, jnp.int32(lo), jnp.int32(hi))
        if _specs[0] is None:
            _specs[0] = _arg_specs(args)
        return jitted(*args)
    superstep.traces = traces
    superstep.cost_analysis = _cost_analysis_hook(jitted, _specs)
    superstep.lowered_text = _lowered_text_hook(jitted, _specs)
    superstep.memory_analysis = _memory_analysis_hook(jitted, _specs)
    return superstep


def make_eval_fn(cfg: TrainConfig, mesh: Mesh) -> Callable:
    """(state, batch) -> global mean loss, no update."""
    loss_fn = make_loss_fn(cfg, mesh)
    jitted = jax.jit(lambda state, batch: loss_fn(state.params, batch))

    def ev(state, batch):
        return jitted(state, shd.put_batch(mesh, batch))
    return ev
