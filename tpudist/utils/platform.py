"""Platform override honored by every CLI entry point.

Some hosts pin JAX to a hardware backend from a site hook at interpreter
start, which silently defeats the ``JAX_PLATFORMS`` env var (the config was
already updated by the hook). ``TPUDIST_PLATFORM=cpu`` re-overrides at the
config level; it must run before any backend is initialized.
"""

from __future__ import annotations

import os


def maybe_force_platform() -> None:
    force = os.environ.get("TPUDIST_PLATFORM")
    if force:
        import jax
        jax.config.update("jax_platforms", force)
