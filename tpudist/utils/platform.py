"""Platform override honored by every CLI entry point.

Some hosts pin JAX to a hardware backend from a site hook at interpreter
start, which silently defeats the ``JAX_PLATFORMS`` env var (the config was
already updated by the hook). ``TPUDIST_PLATFORM=cpu`` re-overrides at the
config level; it must run before any backend is initialized.
"""

from __future__ import annotations

import os


def maybe_force_platform() -> None:
    force = os.environ.get("TPUDIST_PLATFORM")
    if force:
        import jax
        jax.config.update("jax_platforms", force)


def tune_tpu(scoped_vmem_kib: int | None = None) -> None:
    """Set performance-tuning libtpu flags; call before first backend use.

    Raising the scoped-VMEM limit from its 16 MiB default lets XLA form
    larger fusions — measured +8% train tokens/s on v5e at the flagship
    transformer shape going to 48 MiB, +1% more at 80 MiB (the env
    snapshot happens at PJRT plugin dlopen, so setting it here works even
    though jax was imported earlier). Respects an operator-provided
    LIBTPU_INIT_ARGS that already carries the flag;
    ``TPUDIST_SCOPED_VMEM_KIB=0`` disables, other values override."""
    if scoped_vmem_kib is None:
        raw = os.environ.get("TPUDIST_SCOPED_VMEM_KIB", "").strip()
        try:
            scoped_vmem_kib = int(raw) if raw else 81920
        except ValueError:
            print(f"tpudist: ignoring non-integer "
                  f"TPUDIST_SCOPED_VMEM_KIB={raw!r}")
            return
    if scoped_vmem_kib <= 0:
        return
    cur = os.environ.get("LIBTPU_INIT_ARGS", "")
    if "scoped_vmem_limit" in cur:
        return
    os.environ["LIBTPU_INIT_ARGS"] = (
        cur + f" --xla_tpu_scoped_vmem_limit_kib={scoped_vmem_kib}").strip()
