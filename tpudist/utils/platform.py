"""Platform override honored by every CLI entry point.

Some hosts pin JAX to a hardware backend from a site hook at interpreter
start, which silently defeats the ``JAX_PLATFORMS`` env var (the config was
already updated by the hook). ``TPUDIST_PLATFORM=cpu`` re-overrides at the
config level; it must run before any backend is initialized.
"""

from __future__ import annotations

import os


def maybe_force_platform() -> None:
    force = os.environ.get("TPUDIST_PLATFORM")
    if force:
        import jax
        jax.config.update("jax_platforms", force)


def maybe_enable_compilation_cache(cache_dir: str | None = None) -> None:
    """Opt-in persistent XLA compilation cache.

    ``--compilation-cache-dir`` / ``TPUDIST_COMPILATION_CACHE_DIR`` point
    jax's persistent cache at a directory that survives the process, so a
    repeat run (CI re-run, restarted worker) loads compiled programs
    instead of recompiling — the startup cost the superstep path cannot
    amortise away. The min-compile-time/min-entry-size floors drop to 0:
    the acceptance workload's programs are deliberately tiny, and the
    default floors would skip caching exactly the programs this workload
    compiles.
    """
    d = cache_dir or os.environ.get("TPUDIST_COMPILATION_CACHE_DIR")
    if not d:
        return
    import jax
    os.makedirs(d, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", d)
    for opt, val in (("jax_persistent_cache_min_compile_time_secs", 0),
                     ("jax_persistent_cache_min_entry_size_bytes", 0)):
        try:
            jax.config.update(opt, val)
        except Exception:
            pass  # knob names drift across jax versions; the cache dir
            # alone still caches everything past the default floors


def tune_tpu(scoped_vmem_kib: int | None = None) -> None:
    """Set performance-tuning libtpu flags; call before first backend use.

    Raising the scoped-VMEM limit from its 16 MiB default lets XLA form
    larger fusions — measured +8% train tokens/s on v5e at the flagship
    transformer shape going to 48 MiB, +1% more at 80 MiB (the env
    snapshot happens at PJRT plugin dlopen, so setting it here works even
    though jax was imported earlier). Respects an operator-provided
    LIBTPU_INIT_ARGS that already carries the flag;
    ``TPUDIST_SCOPED_VMEM_KIB=0`` disables, other values override."""
    if scoped_vmem_kib is None:
        raw = os.environ.get("TPUDIST_SCOPED_VMEM_KIB", "").strip()
        try:
            scoped_vmem_kib = int(raw) if raw else 81920
        except ValueError:
            print(f"tpudist: ignoring non-integer "
                  f"TPUDIST_SCOPED_VMEM_KIB={raw!r}")
            return
    if scoped_vmem_kib <= 0:
        return
    cur = os.environ.get("LIBTPU_INIT_ARGS", "")
    if "scoped_vmem_limit" in cur:
        return
    os.environ["LIBTPU_INIT_ARGS"] = (
        cur + f" --xla_tpu_scoped_vmem_limit_kib={scoped_vmem_kib}").strip()
