from tpudist.utils.platform import maybe_force_platform

__all__ = ["maybe_force_platform"]
