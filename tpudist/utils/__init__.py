from tpudist.utils.platform import maybe_force_platform, tune_tpu

__all__ = ["maybe_force_platform", "tune_tpu"]
