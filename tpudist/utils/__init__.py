from tpudist.utils.platform import (maybe_enable_compilation_cache,
                                    maybe_force_platform, tune_tpu)

__all__ = ["maybe_enable_compilation_cache", "maybe_force_platform",
           "tune_tpu"]
