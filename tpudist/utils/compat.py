"""JAX cross-version compatibility shims.

The repo targets the current ``jax.shard_map`` / ``jax.sharding.AxisType``
API surface, but CI pins jax 0.4.37 where ``shard_map`` still lives in
``jax.experimental`` with the older keyword spelling (``check_rep`` /
``auto``) and mesh axis types do not exist yet. Every call site imports
these names from here instead of from ``jax`` directly, so the version
skew is handled in exactly one place:

  * :func:`shard_map` — new-style signature (``check_vma``,
    ``axis_names`` naming the MANUAL axes). On old jax it forwards to
    ``jax.experimental.shard_map.shard_map`` with ``check_rep`` and the
    complement-set ``auto`` frozenset.
  * :data:`AxisType` — ``jax.sharding.AxisType`` when it exists, else a
    no-op sentinel with the same member names (old jax behaves as
    all-Auto, so the sentinel carries no semantics).
  * :func:`make_mesh` — forwards ``axis_types`` only when the installed
    ``jax.make_mesh`` accepts it (on old jax Auto is the only behavior,
    so dropping the kwarg is exact).
"""

from __future__ import annotations

import inspect
from typing import Any

import jax

_NEW_SHARD_MAP = hasattr(jax, "shard_map")
if not _NEW_SHARD_MAP:
    from jax.experimental.shard_map import shard_map as _old_shard_map

# Old jax's SPMD partitioner cannot lower collectives (ppermute,
# all_to_all) inside a PARTIALLY-manual shard_map when any auto axis has
# size > 1 — it hard-aborts the XLA compiler (a CHECK failure, not a
# catchable trace error), which would take the whole process down.
# Features that need the combination (context/pipeline parallelism
# composed with data/fsdp sharding, ulysses all-to-alls) gate on these
# and raise a clean NotImplementedError at trace time instead. The
# companion PartitionId limitation (lax.axis_index under partial-auto)
# IS worked around — the rank rides in as a sharded-iota input (see
# models.transformer.make_cp_loss) — but the collectives have no such
# alternate spelling.
PARTIAL_AUTO_ALL_TO_ALL = _NEW_SHARD_MAP
PARTIAL_AUTO_COLLECTIVES = _NEW_SHARD_MAP


def check_partial_auto(mesh, axis: str, feature: str) -> None:
    """Raise a clean NotImplementedError when a partially-manual
    shard_map over ``axis`` would need collectives alongside auto axes of
    size > 1 on a jax version whose partitioner hard-aborts on that
    (see :data:`PARTIAL_AUTO_COLLECTIVES`)."""
    if PARTIAL_AUTO_COLLECTIVES:
        return
    big = [a for a in mesh.axis_names
           if a != axis and mesh.shape[a] > 1]
    if big:
        raise NotImplementedError(
            f"{feature} composed with sharded axes {big} needs "
            f"collectives inside a partially-manual shard_map, which "
            f"this jax version's SPMD partitioner cannot lower; use a "
            f"mesh with only the '{axis}' axis > 1, or a newer jax")


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True,
              axis_names=None):
    """New-API ``jax.shard_map`` with old-jax fallback.

    ``axis_names`` names the axes manualized in the body (new-API
    meaning); ``None`` means all mesh axes. On old jax this becomes
    ``auto = mesh.axis_names - axis_names`` and ``check_vma`` maps to
    ``check_rep`` (same semantics: static replication/varying-axes
    checking of the body's outputs).
    """
    if _NEW_SHARD_MAP:
        kw: dict[str, Any] = dict(mesh=mesh, in_specs=in_specs,
                                  out_specs=out_specs, check_vma=check_vma)
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return jax.shard_map(f, **kw)
    auto = frozenset()
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _old_shard_map(f, mesh, in_specs, out_specs,
                          check_rep=check_vma, auto=auto)


try:
    AxisType = jax.sharding.AxisType
except AttributeError:
    class AxisType:  # type: ignore[no-redef]
        """Sentinel standing in for ``jax.sharding.AxisType`` on jax
        versions that predate typed mesh axes (everything is Auto there,
        so the values are never consumed)."""
        Auto = "auto"
        Explicit = "explicit"
        Manual = "manual"


def axis_size(name):
    """``jax.lax.axis_size`` with old-jax fallback: ``psum(1, name)`` is
    the classic spelling — jax special-cases a psum of a literal into the
    static axis size at trace time, so this stays a Python int for
    control flow either way."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(name)
    return jax.lax.psum(1, name)


def cost_analysis(compiled) -> dict:
    """``Compiled.cost_analysis()`` as one flat dict: old jax returns a
    list with one properties-dict per device program, new jax returns the
    dict directly."""
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        return cost[0] if cost else {}
    return cost or {}


def memory_analysis(compiled) -> dict:
    """``Compiled.memory_analysis()`` as one flat byte-count dict, or
    ``{}`` when the backend can't say.

    New jax returns an object with ``*_size_in_bytes`` attributes; some
    versions return a per-device list of them; CPU builds may return
    ``None`` or raise (memory planning is an XLA:TPU/GPU feature). The
    ledger treats a missing analysis as zero known temp with the gap
    flagged, so this normalizer degrades to ``{}`` rather than raising.
    """
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return {}
    if isinstance(mem, (list, tuple)):
        mem = mem[0] if mem else None
    if mem is None:
        return {}
    out = {}
    for key, attr in (("argument_bytes", "argument_size_in_bytes"),
                      ("output_bytes", "output_size_in_bytes"),
                      ("temp_bytes", "temp_size_in_bytes"),
                      ("generated_code_bytes",
                       "generated_code_size_in_bytes"),
                      ("alias_bytes", "alias_size_in_bytes")):
        val = getattr(mem, attr, None)
        if val is not None:
            try:
                out[key] = int(val)
            except (TypeError, ValueError):
                pass
    return out


def tpu_compiler_params(**kw):
    """``pltpu.CompilerParams`` (new name) / ``pltpu.TPUCompilerParams``
    (old name) — same constructor kwargs either way. Lazy import: pallas
    must not load for callers that never touch the kernels."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kw)


_MAKE_MESH_PARAMS = inspect.signature(jax.make_mesh).parameters


def make_mesh(axis_shapes, axis_names, *, devices=None, axis_types=None):
    """``jax.make_mesh`` that tolerates the missing ``axis_types`` kwarg
    on old jax (where Auto — the only type we ever request — is the
    implicit behavior)."""
    kw: dict[str, Any] = {}
    if devices is not None:
        kw["devices"] = devices
    if axis_types is not None and "axis_types" in _MAKE_MESH_PARAMS:
        kw["axis_types"] = axis_types
    return jax.make_mesh(axis_shapes, axis_names, **kw)
