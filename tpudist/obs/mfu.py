"""MFU / roofline accounting from the compiled program itself.

``bench.py`` has always reported an *analytic* MFU (FLOPs counted from
the model formula). The training run can do better: the superstep is
already compiled, and XLA's cost analysis on that exact executable
(``utils.compat.cost_analysis``) reports the FLOPs and bytes the program
actually executes — remat recompute, masked padding steps, fused
epilogues and all. Divided by the ``StepTimer``'s steady-state wall
time, that yields model-FLOP utilization and achieved HBM bytes/s per
chip with no model-specific formula to drift out of date.

The per-chip convention: ``cost_analysis`` describes the per-device SPMD
program, and ``StepTimer`` wall time is the same on every host, so
``flops / k / step_s`` IS the per-chip achieved rate.

The bf16 peak table lives here (bench.py imports it — single source of
truth); ``TPUDIST_PEAK_TFLOPS`` overrides it for chips the table does
not know, and makes MFU testable on the CPU backend.
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, Optional

# bf16 peak TFLOP/s by device kind (dense); no match → MFU UNGATEABLE
PEAK_TFLOPS = [
    (re.compile(r"v5 ?lite|v5e", re.I), 197.0),
    (re.compile(r"v5p", re.I), 459.0),
    (re.compile(r"v4", re.I), 275.0),
    (re.compile(r"v6|trillium", re.I), 918.0),
]


def chip_peak_tflops(device_kind: Optional[str] = None) -> Optional[float]:
    """Peak bf16 TFLOP/s for ``device_kind`` (default: local device 0).
    ``TPUDIST_PEAK_TFLOPS`` overrides the table — required to account a
    chip generation the table predates, and how CPU tests pin MFU."""
    env = os.environ.get("TPUDIST_PEAK_TFLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass   # malformed override must not fail a finished run;
            # fall through to the table
    if device_kind is None:
        try:
            import jax
            device_kind = jax.devices()[0].device_kind
        except Exception:
            return None
    for pat, peak in PEAK_TFLOPS:
        if pat.search(device_kind):
            return peak
    return None


def dispatch_cost(fn: Any) -> Optional[Dict[str, Any]]:
    """The compiled cost-analysis dict of a train-step/superstep callable
    built by :mod:`tpudist.engine` (they expose ``.cost_analysis()``
    after their first dispatch), or None when unavailable."""
    cost_fn = getattr(fn, "cost_analysis", None)
    if cost_fn is None:
        return None
    try:
        return cost_fn()
    except Exception:
        return None


def mfu_fields(cost: Optional[Dict[str, Any]],
               step_s: float) -> Dict[str, Any]:
    """Roofline fields for the ``kind=timing`` record.

    ``cost`` is the dispatch program's cost analysis and is treated as
    covering ONE train step regardless of the superstep length k: XLA's
    HLO cost analysis visits a while/scan body ONCE (the trip count is
    not multiplied in), so the k-step ``lax.scan`` superstep reports the
    same flops as the k=1 per-step program — measured identical to
    within the scan's ~10-flop bookkeeping, and pinned by
    tests/test_obs.py so a cost-model change in a future XLA cannot
    silently skew MFU by k×. (Known undercount, same mechanism: a
    gradient-accumulation microbatch scan inside the step counts once
    too — MFU is advisory, not exit-code-bearing.)

    ``step_s`` is the steady-state seconds per step from ``StepTimer``.
    All fields are present in every record — ``None`` marks "could not
    be derived" (no cost analysis, no steady-state steps, unknown chip
    peak) so downstream parsers never key-error on a degraded run.
    """
    out: Dict[str, Any] = {
        "model_flops_per_step": None, "hbm_bytes_per_step": None,
        "achieved_tflops_per_chip": None, "achieved_gbps_per_chip": None,
        "peak_tflops": chip_peak_tflops(), "mfu": None,
    }
    if not cost or step_s <= 0:
        return out
    flops = cost.get("flops")
    nbytes = cost.get("bytes accessed")
    if flops and flops > 0:
        per_step = float(flops)
        out["model_flops_per_step"] = per_step
        achieved = per_step / step_s
        out["achieved_tflops_per_chip"] = achieved / 1e12
        peak = out["peak_tflops"]
        if peak:
            out["mfu"] = achieved / (peak * 1e12)
    if nbytes and nbytes > 0:
        per_step_b = float(nbytes)
        out["hbm_bytes_per_step"] = per_step_b
        out["achieved_gbps_per_chip"] = per_step_b / step_s / 1e9
    return out
