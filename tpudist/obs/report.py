"""Offline run report: ``python -m tpudist.obs.report --run-dir DIR``.

The acceptance-test philosophy (the container-HPC workflow of
arXiv:2208.02498) is that the run itself must emit the artifacts that
explain a failure. This CLI is the explainer: it ingests a finished
run's ``metrics.jsonl`` and merged ``pod_trace.json`` (plus an optional
baseline) and emits ``run_report.json`` + a human ``run_report.md``
with:

  * per-host, per-phase wall-time breakdown (SELF time: nested child
    spans are subtracted from their parents, so the phase totals are
    mutually exclusive and sum to the traced coverage of the run);
  * exposed-vs-overlapped staging time (``slab_wait`` spans = H2D the
    pipeline failed to hide; ``stage_slab`` = host staging work that
    overlapped compute);
  * DEVICE time (``--profile-window`` runs): the compute vs
    exposed-communication split recomputed from the device tracks
    merged into ``pod_trace.json`` (obs.devtime), exposed comm
    attributed to the host phase it occurred under, the
    ``comm_status`` verdict (``TPUDIST_COMM_EXPOSED_MAX``), and the
    delta against a baseline's exposed-comm fraction;
  * straggler attribution BY PHASE: not just "host 3 was slow" but
    which phase put it behind the pod median;
  * checkpoint-drain stalls (enqueue vs drain blocked time);
  * a regression verdict against a baseline steps/s;
  * the collective-sweep artifact (``--collectives
    BENCH_COLLECTIVES.json``): per-kind best bus bandwidth and % of
    ring peak, folded into the same report.

Offline by design: no jax import, no device touch — it runs on a
laptop against scp'd artifacts from a dead pod (obs.devtime, the only
tpudist import here, is jax-free for the same reason).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence

from tpudist import rules as rules_lib
from tpudist.obs import devtime as devtime_mod
from tpudist.obs import goodput as goodput_mod
from tpudist.obs import live as live_mod
from tpudist.obs import memledger as memledger_mod
from tpudist.serve import flight as flight_mod
from tpudist.serve import slo as slo_mod

# Schema 5: adds the "goodput" section (cross-attempt wall-clock
# partition from the goodput ledger — tpudist.obs.goodput — or the
# run-end kind=goodput record for single-attempt runs).
# Schema 6: the serving section grows the resilience plane's exact
# shed partition (arrived/admitted/shed_at_admission/expired_in_queue/
# rejected/lost, shed_fraction + the serve_shed gate) and the
# degradation ladder's adapt_level/adapt_transitions; the Alerts
# cross-check adds the serve-gate table (rules.SERVE_STATUS_RULES).
# Schema 7: adds the "flights" section (per-request flight ledger from
# tpudist.serve.flight — chain-exactness verdict, bitwise ShedLedger
# reconciliation, TTFT decomposed into queue/prefill/decode components,
# spec-acceptance trajectory, shed/evict timeline); the serving section
# grows the PR 16 paged-footprint fields (kv_page_tokens /
# kv_pages_total / kv_pages_used_peak / kv_shared_refs,
# spec_accept_rate + the spec_accept gate, speculate_k,
# shared_prefix_len, active_slots_peak, verify_compiles).
# Schema 8: adds the "memory" section (per-device HBM ledger from
# tpudist.obs.memledger — exact params/opt_state/slabs/kv_pool/
# program_temp/headroom/residue partition, the hbm_headroom grade, and
# the per-bucket delta against a baseline's memory section).
REPORT_SCHEMA_VERSION = 8

# Artifact schemas this reader KNOWS. A newer number is a warning, not
# a failure: a requeue loop can scatter attempts across tpudist
# versions (the slice is re-provisioned, images drift), and a
# mixed-version attempt directory must still fold into ONE report —
# the known fields are read, unknown ones ignored.
KNOWN_ARTIFACT_SCHEMAS = {
    # mirrors obs.trace.TRACE_SCHEMA_VERSION — the one constant that
    # CANNOT be imported here (trace.py imports jax; this CLI must run
    # with jax uninstalled). tests/test_goodput.py diffs the two.
    "trace": 1,
    "alerts": live_mod.LIVE_SCHEMA_VERSION,
    "goodput": goodput_mod.GOODPUT_SCHEMA_VERSION,
    "memledger": memledger_mod.MEMLEDGER_SCHEMA_VERSION,
    "baseline": REPORT_SCHEMA_VERSION,
}


def warn_newer_schema(doc: Any, what: str,
                      known: Optional[int] = None) -> bool:
    """Forward-compat gate for every artifact this CLI loads: an
    artifact stamped with a schema NEWER than this reader knows gets a
    stderr warning and is read anyway (known fields only). Returns
    whether it warned (tests pin the path)."""
    if known is None:
        known = KNOWN_ARTIFACT_SCHEMAS[what]
    if not isinstance(doc, dict):
        return False
    s = doc.get("schema")
    if s is None:
        s = (doc.get("metadata") or {}).get("schema") \
            if isinstance(doc.get("metadata"), dict) else None
    if isinstance(s, (int, float)) and s > known:
        print(f"tpudist.obs.report: {what} artifact carries schema "
              f"{int(s)} > known {known} — reading the fields this "
              f"version knows, ignoring the rest (a mixed-version "
              f"attempt set still folds into one report)",
              file=sys.stderr)
        return True
    return False

SUCCESS = "success"
FAIL = "fail"
UNGATEABLE = "ungateable"

# Regression gate: measured steps/s below this fraction of baseline is
# a FAIL. Same advisory three-valued shape as the staging/straggler
# gates; override via --regress-min or TPUDIST_REGRESS_MIN. The value
# lives in tpudist.rules, shared with the live alert engine's regress
# rule so mid-run and offline grading cannot drift.
REGRESS_MIN_FRACTION = rules_lib.REGRESS_MIN_FRACTION

# A host whose per-phase self time exceeds the pod median by this many
# seconds AND this factor is attributed as a straggler cause.
ATTRIB_FACTOR = 1.25
ATTRIB_MIN_S = 0.05


# ----------------------------------------------------------- ingestion


def load_metrics(path: str) -> List[Dict[str, Any]]:
    recs = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                recs.append(json.loads(line))
    return recs


def load_trace(path: str) -> Dict[str, Any]:
    with open(path) as f:
        doc = json.load(f)
    if "traceEvents" not in doc:
        raise ValueError(f"{path}: not a Chrome trace-event document "
                         f"(no traceEvents key)")
    warn_newer_schema(doc, "trace")
    return doc


def complete_events(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """The 'X' (complete) events — the spans."""
    return [e for e in doc.get("traceEvents", [])
            if e.get("ph") == "X" and "ts" in e and "dur" in e]


# -------------------------------------------------- self-time analysis


def self_times(events: List[Dict[str, Any]]) -> Dict[int, Dict[str, Any]]:
    """Per-host phase breakdown from span SELF times.

    Spans on one thread nest properly (the tracer records them from a
    stack discipline), so each span's self time is its duration minus
    the time covered by its children; summing self times per category
    yields mutually-exclusive phase totals whose sum equals the union
    of traced time on that thread. Returns, per pid::

        {"wall_s", "covered_s", "coverage", "phases": {cat: s},
         "names": {name: {"s", "count"}}, "spans"}
    """
    by_host: Dict[int, Dict[str, Any]] = {}
    by_pid_tid: Dict[tuple, List[Dict[str, Any]]] = {}
    for e in events:
        by_pid_tid.setdefault((e.get("pid", 0), e.get("tid", 0)),
                              []).append(e)

    for (pid, _tid), evs in by_pid_tid.items():
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        host = by_host.setdefault(
            pid, {"t_min": None, "t_max": None,
                  "phases": {}, "names": {}, "spans": 0})
        # stack of [end_ts, child_covered_us] for open ancestors
        stack: List[List[float]] = []
        for e in evs:
            ts, dur = float(e["ts"]), float(e["dur"])
            end = ts + dur
            host["t_min"] = ts if host["t_min"] is None else min(
                host["t_min"], ts)
            host["t_max"] = end if host["t_max"] is None else max(
                host["t_max"], end)
            host["spans"] += 1
            while stack and stack[-1][0] <= ts + 1e-9:
                stack.pop()
            if stack:
                stack[-1][1] += dur     # covered inside the parent
            stack.append([end, 0.0])
            # self time resolves when the span closes; with sorted input
            # all children arrive before the next sibling, but their
            # durations accumulate into slot [1] as they are visited —
            # record a placeholder now and fix up after the pass
            e["_self_slot"] = stack[-1]
        for e in evs:
            ts, dur = float(e["ts"]), float(e["dur"])
            self_us = max(0.0, dur - e["_self_slot"][1])
            del e["_self_slot"]
            cat = e.get("cat", "misc")
            host["phases"][cat] = host["phases"].get(cat, 0.0) + self_us
            n = host["names"].setdefault(e.get("name", "?"),
                                         {"s": 0.0, "count": 0})
            n["s"] += dur / 1e6
            n["count"] += 1

    out: Dict[int, Dict[str, Any]] = {}
    for pid, h in sorted(by_host.items()):
        wall_us = ((h["t_max"] - h["t_min"])
                   if h["t_max"] is not None else 0.0)
        phases = {c: round(us / 1e6, 6) for c, us in
                  sorted(h["phases"].items(), key=lambda kv: -kv[1])}
        covered = sum(phases.values())
        out[pid] = {
            "wall_s": round(wall_us / 1e6, 6),
            "covered_s": round(covered, 6),
            "coverage": (round(covered / (wall_us / 1e6), 4)
                         if wall_us > 0 else None),
            "phases": phases,
            "names": {k: {"s": round(v["s"], 6), "count": v["count"]}
                      for k, v in sorted(h["names"].items(),
                                         key=lambda kv: -kv[1]["s"])},
            "spans": h["spans"],
        }
    return out


def _sum_named(events: List[Dict[str, Any]], *,
               names: Optional[set] = None,
               cat: Optional[str] = None,
               pid: Optional[int] = None) -> float:
    """Total duration (s) of spans matching name/cat/pid filters."""
    tot = 0.0
    for e in events:
        if names is not None and e.get("name") not in names:
            continue
        if cat is not None and e.get("cat") != cat:
            continue
        if pid is not None and e.get("pid") != pid:
            continue
        tot += float(e["dur"]) / 1e6
    return tot


# ----------------------------------------------------------- sections


def staging_section(events, timing: Optional[Dict]) -> Dict[str, Any]:
    """Exposed vs overlapped staging: ``slab_wait`` spans are the H2D
    the pipeline failed to hide behind compute; ``stage_slab`` is the
    host-side materialise+dispatch work that DID overlap."""
    exposed = _sum_named(events, names={"slab_wait"})
    staged = _sum_named(events, names={"stage_slab"})
    sec = {
        "exposed_wait_s": round(exposed, 6),
        "stage_host_s": round(staged, 6),
        "overlapped_s": round(max(0.0, staged - exposed), 6),
        "slabs": sum(1 for e in events if e.get("name") == "stage_slab"),
    }
    if timing:
        sec["timing_stage_wait_s"] = timing.get("stage_wait_s")
        sec["staging_status"] = timing.get("staging_status")
        sec["overlap_fraction"] = timing.get("staging_overlap_fraction")
    return sec


def ckpt_section(events, metrics) -> Dict[str, Any]:
    """Checkpoint cost split: per-save enqueue (what the step path
    paid) vs drain (time blocked on serialisation at wait/close)."""
    drains = [e for e in events if e.get("cat") == "ckpt"
              and "drain" in e.get("name", "")]
    enq = _sum_named(events, names={"ckpt_enqueue"})
    drain_recs = [r for r in metrics if r.get("kind") == "ckpt_drain"]
    saves = [r for r in metrics if r.get("kind") == "ckpt"]
    worst = max((float(e["dur"]) / 1e6 for e in drains), default=0.0)
    return {
        "saves": len(saves),
        "enqueue_s": round(enq, 6),
        "drain_s": round(sum(float(e["dur"]) / 1e6 for e in drains), 6),
        "drain_spans": len(drains),
        "worst_drain_s": round(worst, 6),
        "timing_drain_ms": (drain_recs[-1].get("drain_ms")
                            if drain_recs else None),
    }


# Exposed-comm phase attribution: host span categories in priority
# order — the most specific wins (a fence is inside an epoch; exposed
# comm during it is a DISPATCH finding, not a "train" finding). The
# "profile" cat (the capture-window bracket span itself) is excluded:
# it covers the whole window by construction and would absorb
# everything.
PHASE_PRIORITY = ("dispatch", "staging", "ckpt", "eval", "tune", "sync",
                  "data", "init", "train")


def _exposed_by_phase(exposed, host_evs) -> Dict[str, float]:
    """Attribute exposed-comm intervals (µs, merged) to the host phase
    they occurred under; leftovers (no span open, or only the capture
    bracket) read as ``other``."""
    by_cat: Dict[str, list] = {}
    for e in host_evs:
        cat = e.get("cat", "misc")
        if cat == "profile":
            continue
        ts, dur = float(e["ts"]), float(e["dur"])
        by_cat.setdefault(cat, []).append((ts, ts + dur))
    remaining = devtime_mod.merge_intervals(exposed)
    out: Dict[str, float] = {}
    extras = sorted(set(by_cat) - set(PHASE_PRIORITY))
    for cat in list(PHASE_PRIORITY) + extras:
        if cat not in by_cat or not remaining:
            continue
        hit = devtime_mod.intersect_intervals(remaining, by_cat[cat])
        s = devtime_mod.measure(hit) / 1e6
        if s > 0:
            out[cat] = round(s, 6)
        remaining = devtime_mod.subtract_intervals(remaining,
                                                   by_cat[cat])
    left = devtime_mod.measure(remaining) / 1e6
    if left > 0:
        out["other"] = round(left, 6)
    return out


def devtime_section(events, metrics, baseline: Optional[Dict]
                    ) -> Dict[str, Any]:
    """The device-time split: compute vs exposed communication per
    device track, recomputed from the device events a
    ``--profile-window`` run merged into ``pod_trace.json``
    (obs.devtime's interval math — the same operator the live run
    used), plus the per-phase attribution of exposed comm against the
    host spans, the ``comm_status`` verdict, and the exposed-fraction
    delta vs baseline. Falls back to the ``kind=devtime`` metrics
    record when the trace carries no device tracks (e.g. a ``--trace
    off`` run); ungateable when neither exists."""
    dev_evs = [e for e in events
               if e.get("cat") == devtime_mod.DEVTIME_CAT]
    host_evs = [e for e in events
                if e.get("cat") != devtime_mod.DEVTIME_CAT]
    recs = [r for r in metrics if r.get("kind") == "devtime"]

    devices: Dict[str, Any] = {}
    exposed_by_phase: Dict[str, float] = {}
    pod = {"compute_s": 0.0, "comm_s": 0.0, "exposed_comm_s": 0.0,
           "window_s": 0.0, "devices": 0, "exposed_comm_frac": None}
    if dev_evs:
        # per host: rebuild each device track's class intervals from
        # the coalesced compute/comm events
        by_pid: Dict[int, Dict[str, Dict[str, list]]] = {}
        for e in dev_evs:
            pid = e.get("pid", 0)
            dev = (e.get("args") or {}).get("device", str(e.get("tid")))
            cls = e.get("name")
            if cls not in ("compute", "comm"):
                continue
            ts, dur = float(e["ts"]), float(e["dur"])
            by_pid.setdefault(pid, {}).setdefault(
                dev, {"compute": [], "comm": []})[cls].append(
                    (ts, ts + dur))
        # window_s counts wall once per HOST (the capture window), while
        # the exposed fraction divides by DEVICE-seconds (window × each
        # host's device count) — the same convention as the live
        # kind=devtime record (devtime.attribute_tracks), so the report
        # and metrics.jsonl agree on both numbers
        win_host_us = 0.0
        win_dev_us = 0.0
        for pid, tracks in sorted(by_pid.items()):
            allv = [iv for c in tracks.values()
                    for ivs in c.values() for iv in ivs]
            window = (min(lo for lo, _ in allv),
                      max(hi for _, hi in allv)) if allv else None
            if window is not None:
                win_host_us += window[1] - window[0]
            exposed_pid: list = []
            for dev, classed in sorted(tracks.items()):
                att = devtime_mod.attribute_classed(classed, window)
                devices[f"host{pid}/{dev}"] = {
                    k: (round(v, 6) if isinstance(v, float) else v)
                    for k, v in att.items()}
                for k in ("compute_s", "comm_s", "exposed_comm_s"):
                    pod[k] += att[k]
                pod["devices"] += 1
                win_dev_us += att["window_s"] * 1e6
                exposed_pid.extend(devtime_mod.subtract_intervals(
                    classed["comm"], classed["compute"]))
            host_pid_evs = [e for e in host_evs if e.get("pid") == pid]
            for cat, s in _exposed_by_phase(exposed_pid,
                                            host_pid_evs).items():
                exposed_by_phase[cat] = round(
                    exposed_by_phase.get(cat, 0.0) + s, 6)
        pod["window_s"] = round(win_host_us / 1e6, 6)
        pod["exposed_comm_frac"] = (
            round(pod["exposed_comm_s"] * 1e6 / win_dev_us, 6)
            if win_dev_us > 0 else None)
        for k in ("compute_s", "comm_s", "exposed_comm_s"):
            pod[k] = round(pod[k], 6)
    elif recs:
        rec = recs[-1]
        for d in rec.get("per_device", []):
            devices[f"host{rec.get('process_index', 0)}/"
                    f"{d.get('device')}"] = {
                k: v for k, v in d.items() if k != "device"}
        for k in ("compute_s", "comm_s", "exposed_comm_s", "window_s",
                  "devices", "exposed_comm_frac"):
            if rec.get(k) is not None:
                pod[k] = rec[k]

    # fabric-graded at fold time: the record's axis_fabric label picks
    # the ICI or DCN ceiling (tpudist.rules.resolve_comm) — same
    # dispatch the live alert engine applied mid-run
    fabric = (recs[-1].get("fabric") if recs else None)
    status = devtime_mod.comm_status(pod["exposed_comm_frac"],
                                     fabric=fabric)
    base_frac = _find_exposed_frac(baseline) if baseline else None
    delta = (round(pod["exposed_comm_frac"] - base_frac, 6)
             if (pod["exposed_comm_frac"] is not None
                 and base_frac is not None) else None)
    out = {
        "comm_status": status,
        "fabric": fabric,
        "devices": devices,
        "pod": pod,
        "exposed_by_phase": exposed_by_phase,
        "record_comm_status": (recs[-1].get("comm_status")
                               if recs else None),
        "baseline_exposed_comm_frac": base_frac,
        "exposed_comm_frac_delta": delta,
    }
    # program-derived collective byte volumes (devtime.collective_bytes
    # rows carried on the record in both cross-slice modes): the DCN
    # bytes the schedule moves per step, surfaced next to the time split
    # they explain
    if recs and recs[-1].get("dcn_bytes_total") is not None:
        rec = recs[-1]
        out["dcn_bytes_total"] = rec["dcn_bytes_total"]
        out["ici_bytes_total"] = rec.get("ici_bytes_total")
        out["collectives"] = rec.get("collectives")
    return out


def _find_exposed_frac(doc: Any) -> Optional[float]:
    """Dig an exposed-comm fraction out of a baseline document: a prior
    run_report (``devtime.pod.exposed_comm_frac``) or a bare pin."""
    if not isinstance(doc, dict):
        return None
    for path in (("exposed_comm_frac",),
                 ("devtime", "pod", "exposed_comm_frac")):
        cur: Any = doc
        for k in path:
            cur = cur.get(k) if isinstance(cur, dict) else None
        if isinstance(cur, (int, float)):
            return float(cur)
    return None


def collectives_section(doc: Optional[Dict]) -> Optional[Dict[str, Any]]:
    """Fold BENCH_COLLECTIVES.json (bench.py --collective-sweep) into
    the report: per collective kind, the best-bucket bus bandwidth and
    % of ring peak. Purely informational — the sweep gate already ran
    live; this puts the numbers next to the exposed-comm split they
    explain."""
    if not doc:
        return None
    detail = doc.get("detail", doc)
    rows = detail.get("rows", [])
    per_kind: Dict[str, Dict[str, Any]] = {}
    for r in rows:
        # tolerate truncated/hand-kept artifacts (this CLI's offline
        # contract): a row without a kind or bandwidth is skipped, not
        # a traceback
        kind = r.get("kind")
        gbps = r.get("bus_gbps")
        if kind is None or not isinstance(gbps, (int, float)):
            continue
        best = per_kind.get(kind)
        if best is None or gbps > best["bus_gbps"]:
            per_kind[kind] = {
                "bus_gbps": gbps,
                "pct_of_ring_peak": r.get("pct_of_ring_peak"),
                "message_bytes": r.get("message_bytes"),
                "fabric": r.get("fabric"),
            }
    return {
        "axis": detail.get("axis"),
        "fabric": detail.get("fabric"),
        "n_devices": detail.get("n_devices"),
        "rows": len(rows),
        "per_kind": per_kind,
    }


# At-exit fail verdicts and the live alert rule that should have fired
# for each — the Alerts section's cross-check table. The whole point of
# on-line alerting is that a run which grades fail at exit alerted
# HOURS earlier; a fail with no matching mid-run alert is a gap in the
# live engine's coverage and gets flagged as a report warning. The
# table itself lives in tpudist.rules (shared with the chaos verifier's
# end-to-end pin of the same invariant) so the two checkers cannot
# drift.
_EXIT_FAIL_TO_RULE = rules_lib.STATUS_RULES


def alerts_section(metrics: List[Dict[str, Any]],
                   alert_history: Optional[List[Dict[str, Any]]],
                   timing: Optional[Dict]) -> Dict[str, Any]:
    """The live-telemetry slice of the report: the alert fire/resolve
    history (first-fire step/time, duration, final state per
    ``(rule, host)``) plus the on-line/at-exit parity cross-check.

    ``alert_history`` comes from ``alerts.jsonl`` (the aggregator's
    append-only transition log) or ``live_status.json``; runs without
    the live bus fall back to the ``kind=alert`` records the aggregator
    mirrored into ``metrics.jsonl``; a run with neither reads as
    ``enabled: False`` and skips the cross-check (nothing was watching,
    so a miss means nothing)."""
    history = list(alert_history or [])
    live_seen = alert_history is not None
    if not history:
        history = [r for r in metrics if r.get("kind") == "alert"]
        live_seen = live_seen or bool(history)
    # fold transitions into one row per (rule, host): the FIRING event
    # pins first_step/first_ts; the latest transition wins the rest
    rows: Dict[tuple, Dict[str, Any]] = {}
    for rec in history:
        rule = rec.get("alert")
        if not rule:
            continue
        key = (rule, rec.get("host"))
        row = rows.setdefault(key, {
            "alert": rule, "host": rec.get("host"),
            "first_step": rec.get("first_step"),
            "first_ts": rec.get("first_ts"),
            "state": rec.get("state"), "duration_s": 0.0,
            "value": rec.get("value"),
            "threshold": rec.get("threshold")})
        row["state"] = rec.get("state", row["state"])
        for k in ("value", "threshold"):
            if rec.get(k) is not None:
                row[k] = rec[k]
        if rec.get("duration_s") is not None:
            row["duration_s"] = max(row["duration_s"],
                                    float(rec["duration_s"]))
    fired_rules = {r["alert"] for r in rows.values()}
    warnings = []
    if live_seen:
        for status_key, rule in _EXIT_FAIL_TO_RULE:
            if (timing or {}).get(status_key) == FAIL \
                    and rule not in fired_rules:
                warnings.append(
                    f"at-exit {status_key}=fail had NO mid-run "
                    f"{rule!r} alert — live coverage gap")
        # the serve lane's twin of the same invariant: a kind=serve
        # summary that graded a gate fail must have its mid-run alert
        # (rules.SERVE_STATUS_RULES — shared with the serve drill
        # verifier, tpudist.serve.drill)
        serve = next((r for r in reversed(metrics)
                      if r.get("kind") == "serve"), None)
        if serve is not None:
            for status_key, rule in rules_lib.SERVE_STATUS_RULES:
                if serve.get(status_key) == FAIL \
                        and rule not in fired_rules:
                    warnings.append(
                        f"at-exit serve {status_key}=fail had NO "
                        f"mid-run {rule!r} alert — live coverage gap")
        # a watchdog stall dump in the stream means the run wedged;
        # the live stall alert must have fired before the kill
        if any(r.get("kind") == "stall_dump" for r in metrics) \
                and "stall" not in fired_rules:
            warnings.append("watchdog stall dump recorded but NO "
                            "mid-run 'stall' alert fired")
    return {
        "enabled": live_seen,
        "events": len(history),
        "history": sorted(rows.values(),
                          key=lambda r: (r.get("first_ts") or 0)),
        "fired_rules": sorted(fired_rules),
        "warnings": warnings,
    }


def straggler_section(hosts: Dict[int, Dict[str, Any]],
                      metrics) -> Dict[str, Any]:
    """Straggler attribution BY PHASE: for each host, which phase's
    self time exceeds the pod median of that phase. With < 2 hosts
    there is nothing to compare — ungateable, like the live verdict."""
    import statistics
    hosts_rec = [r for r in metrics if r.get("kind") == "hosts"]
    status = (hosts_rec[-1].get("straggler_status")
              if hosts_rec else UNGATEABLE)
    if len(hosts) < 2:
        return {"status": status if hosts_rec else UNGATEABLE,
                "attribution": []}
    cats = sorted({c for h in hosts.values() for c in h["phases"]})
    attribution = []
    for cat in cats:
        vals = {pid: h["phases"].get(cat, 0.0)
                for pid, h in hosts.items()}
        med = statistics.median(vals.values())
        for pid, v in vals.items():
            if v > ATTRIB_FACTOR * med and v - med > ATTRIB_MIN_S:
                attribution.append({
                    "process": pid, "phase": cat,
                    "self_s": round(v, 6),
                    "pod_median_s": round(med, 6),
                    "excess_s": round(v - med, 6)})
    attribution.sort(key=lambda a: -a["excess_s"])
    return {"status": status, "attribution": attribution}


def regression_section(timing: Optional[Dict],
                       baseline: Optional[Dict],
                       min_fraction: float) -> Dict[str, Any]:
    """Measured steps/s vs baseline. Baseline JSON: any dict carrying
    ``steps_per_sec`` (a prior run_report.json, a BENCH row, or a
    hand-written pin). No baseline / no measurement → ungateable."""
    measured = None
    if timing and timing.get("run_s") and timing.get("steps"):
        measured = timing["steps"] / timing["run_s"]
    base = _find_steps_per_sec(baseline) if baseline else None
    if measured is None or base is None or base <= 0:
        return {"status": UNGATEABLE, "steps_per_sec": measured,
                "baseline_steps_per_sec": base, "ratio": None,
                "min_fraction": min_fraction}
    ratio = measured / base
    return {"status": SUCCESS if ratio >= min_fraction else FAIL,
            "steps_per_sec": round(measured, 4),
            "baseline_steps_per_sec": round(base, 4),
            "ratio": round(ratio, 4), "min_fraction": min_fraction}


def serving_section(metrics: List[Dict[str, Any]],
                    baseline: Optional[Dict] = None) -> Dict[str, Any]:
    """The serving slice of the report (tpudist.serve): the run's
    latency percentiles and throughput RE-GRADED through the shared SLO
    gates (tpudist.serve.slo over the rules table — same thresholds the
    serve loop's on-line alerts and exit verdict applied, env read at
    fold time), queue depth over time from the ``kind=serve_tick``
    stream, and an optional throughput comparison against a baseline
    BENCH_SERVE.json / prior report. Runs without serve records read as
    ``enabled: False`` — a training run has no SLO to grade."""
    serves = [r for r in metrics if r.get("kind") == "serve"]
    if not serves:
        return {"enabled": False}
    s = serves[-1]
    graded = slo_mod.grade(s.get("ttft_p99_s"), s.get("itl_p99_s"),
                           s.get("tokens_per_sec_per_chip"),
                           shed_fraction=s.get("shed_fraction"))
    ticks = [r for r in metrics if r.get("kind") == "serve_tick"]
    queue = [{"t_s": r.get("t_s"), "queue_depth": r.get("queue_depth"),
              "active_slots": r.get("active_slots"),
              "completed": r.get("completed")} for r in ticks]
    tunes = [r for r in metrics if r.get("kind") == "serve_tune"]
    base_tps = _find_serve_tps(baseline) if baseline else None
    tps = s.get("tokens_per_sec_per_chip")
    ratio = (round(tps / base_tps, 4)
             if isinstance(tps, (int, float)) and base_tps else None)
    return {
        "enabled": True,
        "status": graded["status"],
        "gates": {rule: graded[f"{rule}_status"]
                  for rule, _ in slo_mod.SERVE_RULES},
        "thresholds": {rule: rules_lib.resolve(rule)
                       for rule, _ in slo_mod.SERVE_RULES},
        "requests": s.get("requests"), "completed": s.get("completed"),
        "generated_tokens": s.get("generated_tokens"),
        "truncated": s.get("truncated"), "wall_s": s.get("wall_s"),
        "slots": s.get("slots"), "decode_k": s.get("decode_k"),
        "kv_layout": s.get("kv_layout"),
        "kv_cache_bytes": s.get("kv_cache_bytes"),
        "tokens_per_sec": s.get("tokens_per_sec"),
        "tokens_per_sec_per_chip": tps,
        "ttft_p50_s": s.get("ttft_p50_s"),
        "ttft_p99_s": s.get("ttft_p99_s"),
        "itl_p50_s": s.get("itl_p50_s"),
        "itl_p99_s": s.get("itl_p99_s"),
        "e2e_p99_s": s.get("e2e_p99_s"),
        "prefill_compiles": s.get("prefill_compiles"),
        "decode_compiles": s.get("decode_compiles"),
        "verify_compiles": s.get("verify_compiles"),
        "queue_depth_max": s.get("queue_depth_max"),
        "queue_over_time": queue,
        "active_slots_peak": s.get("active_slots_peak"),
        # the PR 16 paged footprint + speculation fields: what the pool
        # actually held at peak and how well the draft guessed. The
        # spec_accept gate re-grades here like every other gate (env
        # read at fold time); pre-paged artifacts read None/absent
        "kv_page_tokens": s.get("kv_page_tokens"),
        "kv_pages_total": s.get("kv_pages_total"),
        "kv_pages_used_peak": s.get("kv_pages_used_peak"),
        "spec_accept_rate": s.get("spec_accept_rate"),
        "spec_accept_status": slo_mod.rule_status(
            "spec_accept", s.get("spec_accept_rate")),
        "speculate_k": s.get("speculate_k"),
        "shared_prefix_len": s.get("shared_prefix_len"),
        # the resilience plane's exact shed partition (PR 15): absent
        # keys on pre-resilience artifacts simply read None
        "arrived": s.get("arrived"), "admitted": s.get("admitted"),
        "shed_at_admission": s.get("shed_at_admission"),
        "expired_in_queue": s.get("expired_in_queue"),
        "rejected": s.get("rejected"), "lost": s.get("lost"),
        "shed_fraction": s.get("shed_fraction"),
        "queue_cap": s.get("queue_cap"),
        "ttft_deadline_s": s.get("ttft_deadline_s"),
        "adapt_level": s.get("adapt_level"),
        "adapt_transitions": [
            {k: r.get(k) for k in ("t_s", "from_level", "to_level",
                                   "decode_k", "reason")}
            for r in metrics if r.get("kind") == "serve_adapt"],
        "tuning": ({k: tunes[-1].get(k) for k in
                    ("status", "source", "trials", "decode_k", "layout")}
                   if tunes else None),
        "baseline_tokens_per_sec_per_chip": base_tps,
        "tokens_per_chip_ratio": ratio,
    }


def flights_section(metrics: List[Dict[str, Any]],
                    trace_doc: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
    """The request-flight slice (tpudist.serve.flight): every arrived
    rid reconstructed into its lifecycle chain and verified EXACTLY —
    one admission verdict, one terminal state, TTFT equal to its own
    queue/prefill decomposition within the flight_decomp tolerance, and
    chain counts reconciled bitwise against the ShedLedger partition
    (attempt 0 only — a resumed attempt's ledger partitions only its
    own arrivals while the replayed event stream spans every attempt).
    Plus the aggregates the chains make possible: p50/p99 of each TTFT
    component, the speculative-acceptance trajectory, and the
    shed/evict timeline. Runs without ``kind=serve_request`` records
    read as ``enabled: False``."""
    if not any(r.get("kind") == "serve_request" for r in metrics):
        return {"enabled": False}
    flights = flight_mod.reconstruct(metrics, trace_doc)
    partition, attempt = flight_mod.find_partition(metrics)
    res = flight_mod.verify(flights,
                            partition if attempt == 0 else None)
    spec = [{"t_s": r.get("t_s"),
             "spec_accept_rate": r.get("spec_accept_rate")}
            for r in metrics if r.get("kind") == "serve_tick"
            and r.get("spec_accept_rate") is not None]
    return {
        "enabled": True,
        "exact": res["exact"],
        "flights": res["flights"],
        "counts": res["counts"],
        "partition_checked": res["partition_checked"],
        "trace_checked": res["trace_checked"],
        "decomposed": res["decomposed"],
        "ttft_decomp_worst_s": res["ttft_decomp_worst_s"],
        "ttft_decomp_tol_s": res["ttft_decomp_tol_s"],
        "ttft_decomp_status": res["ttft_decomp_status"],
        "decomposition": flight_mod.decomposition(flights),
        "spec_accept_over_time": spec,
        "shed_timeline": flight_mod.shed_timeline(flights),
        # bounded: a pathological run could break every chain, and the
        # report must stay readable — the flight CLI prints them all
        "problems": res["problems"][:20],
        "problem_count": len(res["problems"]),
    }


def goodput_section(metrics: List[Dict[str, Any]],
                    ledger: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
    """The goodput slice of the report (tpudist.obs.goodput): the
    cross-attempt wall-clock partition when a ledger is available
    (attempts.jsonl next to the artifacts, or a prebuilt goodput.json),
    else the run-end ``kind=goodput`` attempt-local estimate. The
    status is RE-GRADED through the shared rules table at fold time
    (env read now — same discipline as the serving section), but the
    fraction itself is the ledger's verbatim: the CLI, this section and
    the Prometheus gauges must report the identical number (the
    consumer-parity pin in tests/test_goodput.py)."""
    if ledger:
        frac = ledger.get("goodput_fraction")
        return {
            "enabled": True,
            "cross_attempt": True,
            "status": goodput_mod.goodput_status(frac),
            "fraction": frac,
            "min_fraction": rules_lib.resolve("goodput"),
            "total_wall_s": ledger.get("total_wall_s"),
            "buckets": ledger.get("totals"),
            "lost_steps": ledger.get("lost_steps"),
            "exact": ledger.get("exact"),
            "tolerance": ledger.get("tolerance"),
            "problems": ledger.get("problems") or [],
            "attempts": [
                {k: a.get(k) for k in
                 ("attempt", "wall_s", "rc", "verdict", "steps_done",
                  "lost_steps", "steps_per_sec", "buckets")}
                for a in ledger.get("attempts", [])],
        }
    recs = [r for r in metrics if r.get("kind") == "goodput"]
    if not recs:
        return {"enabled": False}
    g = recs[-1]
    return {
        "enabled": True,
        "cross_attempt": False,
        "status": goodput_mod.goodput_status(g.get("fraction")),
        "fraction": g.get("fraction"),
        "min_fraction": rules_lib.resolve("goodput"),
        "total_wall_s": g.get("wall_s"),
        "buckets": {k: g.get(f"{k}_s") for k in goodput_mod.BUCKETS
                    if g.get(f"{k}_s") is not None},
        "lost_steps": None,
        "exact": None,
        "attempts": [{"attempt": g.get("requeue_attempt"),
                      "wall_s": g.get("wall_s")}],
    }


def _find_memory_buckets(doc: Any) -> Optional[Dict[str, Any]]:
    """Dig a per-bucket byte map out of a baseline document: a raw
    memledger.json (top-level ``buckets``) or a prior run_report's
    memory section."""
    if not isinstance(doc, dict):
        return None
    for path in (("buckets",), ("memory", "buckets")):
        cur: Any = doc
        for k in path:
            cur = cur.get(k) if isinstance(cur, dict) else None
        if isinstance(cur, dict) and cur:
            return cur
    return None


def memory_section(metrics: List[Dict[str, Any]],
                   ledger: Optional[Dict[str, Any]] = None,
                   baseline: Optional[Dict] = None) -> Dict[str, Any]:
    """The HBM-ledger slice of the report (tpudist.obs.memledger): the
    exact per-bucket partition of one device's HBM, graded against the
    shared ``hbm_headroom`` floor at fold time (env read now — same
    re-grade discipline as the goodput section), plus the per-bucket
    delta when the baseline carries a memory section of its own. A run
    with neither a ``memledger.json`` artifact nor a ``kind=memledger``
    record folds to ``{"enabled": False}`` — UNGATEABLE, never a crash
    (older run dirs predate the ledger)."""
    if ledger is None:
        recs = [r for r in metrics if r.get("kind") == "memledger"]
        if recs:
            ledger = memledger_mod.from_record(recs[-1])
    if not ledger:
        return {"enabled": False, "status": UNGATEABLE}
    frac = ledger.get("headroom_fraction")
    buckets = {k: (ledger.get("buckets") or {}).get(k)
               for k in memledger_mod.BUCKETS}
    sec: Dict[str, Any] = {
        "enabled": True,
        "status": memledger_mod.hbm_headroom_status(frac),
        "headroom_fraction": frac,
        "min_fraction": rules_lib.resolve("hbm_headroom"),
        "mode": ledger.get("mode"),
        "total_hbm_bytes": ledger.get("total_hbm_bytes"),
        "buckets": buckets,
        "watermark_bytes": ledger.get("watermark_bytes"),
        "watermark_source": ledger.get("watermark_source"),
        "program_temp_complete": ledger.get("program_temp_complete"),
        "programs": sorted((ledger.get("programs") or {}).keys()),
        "exact": ledger.get("exact"),
        "problems": ledger.get("problems") or [],
        "notes": ledger.get("notes") or [],
    }
    base_buckets = _find_memory_buckets(baseline)
    if base_buckets:
        sec["bucket_delta_bytes"] = {
            k: int(buckets.get(k) or 0) - int(base_buckets.get(k) or 0)
            for k in memledger_mod.BUCKETS
            if buckets.get(k) is not None
            or base_buckets.get(k) is not None}
    return sec


def _find_serve_tps(doc: Any) -> Optional[float]:
    """Dig a serve tokens/s/chip baseline out of a document: a
    BENCH_SERVE.json (top-level ``value`` under the serve metric name),
    a prior run_report's serving section, or a bare number under
    ``tokens_per_sec_per_chip``."""
    if not isinstance(doc, dict):
        return None
    if doc.get("metric") == "serve_tokens_per_sec_per_chip" \
            and isinstance(doc.get("value"), (int, float)):
        return float(doc["value"])
    for path in (("tokens_per_sec_per_chip",),
                 ("serving", "tokens_per_sec_per_chip")):
        cur: Any = doc
        for k in path:
            cur = cur.get(k) if isinstance(cur, dict) else None
        if isinstance(cur, (int, float)) and cur > 0:
            return float(cur)
    return None


def _find_steps_per_sec(doc: Any) -> Optional[float]:
    """Dig a steps/s number out of a baseline document: top-level
    ``steps_per_sec``, a run_report's ``regression.steps_per_sec``, or
    a ``run.steps_per_sec``."""
    if not isinstance(doc, dict):
        return None
    for path in (("steps_per_sec",),
                 ("run", "steps_per_sec"),
                 ("regression", "steps_per_sec")):
        cur: Any = doc
        for k in path:
            cur = cur.get(k) if isinstance(cur, dict) else None
        if isinstance(cur, (int, float)) and cur > 0:
            return float(cur)
    return None


# -------------------------------------------------------- the report


def build_report(metrics: List[Dict[str, Any]],
                 trace_doc: Dict[str, Any], *,
                 baseline: Optional[Dict] = None,
                 regress_min: Optional[float] = None,
                 collectives: Optional[Dict] = None,
                 alert_history: Optional[List[Dict]] = None,
                 goodput: Optional[Dict] = None,
                 memledger: Optional[Dict] = None
                 ) -> Dict[str, Any]:
    if regress_min is None:
        # the shared rules table (same env knob, read at call time, as
        # the live alert engine's regress rule)
        regress_min = rules_lib.resolve("regress")
    all_events = complete_events(trace_doc)
    # the host-side analyses must not see the device tracks: a device
    # busy interval is not a host phase, and folding it into self-time
    # would double every covered second of a profiled window
    events = [e for e in all_events
              if e.get("cat") != devtime_mod.DEVTIME_CAT]
    hosts = self_times(events)
    timings = [r for r in metrics if r.get("kind") == "timing"]
    timing = timings[-1] if timings else None
    epochs = [r for r in metrics if r.get("kind") == "epoch"]
    tunes = [r for r in metrics if r.get("kind") == "tune"]
    resumes = [r for r in metrics if r.get("kind") == "resume"]
    resume = resumes[-1] if resumes else None

    regression = regression_section(timing, baseline, regress_min)
    stragglers = straggler_section(hosts, metrics)
    devtime = devtime_section(all_events, metrics, baseline)
    alerts = alerts_section(metrics, alert_history, timing)
    serving = serving_section(metrics, baseline)
    flights = flights_section(metrics, trace_doc)
    goodput_sec = goodput_section(metrics, goodput)
    memory = memory_section(metrics, memledger, baseline)
    # the correlation id: every metrics record carries it (the train
    # CLI stamps MetricsLogger.extra); older artifacts fall back to the
    # trace metadata
    run_id = next((r.get("run_id") for r in metrics if r.get("run_id")),
                  None) or trace_doc.get("metadata", {}).get("run_id")
    # pod-level phase totals (sum over hosts)
    pod_phases: Dict[str, float] = {}
    for h in hosts.values():
        for c, s in h["phases"].items():
            pod_phases[c] = pod_phases.get(c, 0.0) + s

    # a serving section whose gates all read ungateable measured
    # NOTHING — it must not count as evidence toward a success verdict
    # (the serve CLI's own exit verdict for that run is ungateable)
    serving_measured = serving["enabled"] \
        and serving["status"] != UNGATEABLE
    verdict = SUCCESS
    if regression["status"] == FAIL or stragglers["status"] == FAIL \
            or (serving["enabled"] and serving["status"] == FAIL):
        verdict = FAIL
    elif not events and not serving_measured:
        verdict = UNGATEABLE

    return {
        "schema": REPORT_SCHEMA_VERSION,
        "run": {
            "run_id": run_id,
            "steps": timing.get("steps") if timing else None,
            "run_s": timing.get("run_s") if timing else None,
            "compile_warmup_s": (timing.get("compile_warmup_s")
                                 if timing else None),
            "steps_per_sec": regression["steps_per_sec"],
            "epochs": len(epochs),
            "final_avg_loss": (epochs[-1].get("avg_loss")
                               if epochs else None),
            "staging_status": (timing.get("staging_status")
                               if timing else None),
            "tuning_status": (tunes[-1].get("status") if tunes
                              else (timing or {}).get("tuning_status")),
            "straggler_status": stragglers["status"],
            "comm_status": devtime["comm_status"],
            "trace_status": (timing.get("trace_status")
                             if timing else None),
            # elastic-resume slice of the header (tpudist.elastic): did
            # this run continue a preempted one, from where, at what cost
            "resume_status": ((resume or {}).get("status")
                              or (timing or {}).get("resume_status")),
            "resumed_from_step": (resume or {}).get("resumed_from_step"),
            "resume_steps_lost": (resume or {}).get("steps_lost"),
            "requeue_attempt": (resume or {}).get("requeue_attempt"),
        },
        "trace": {
            "hosts": trace_doc.get("metadata", {}).get("hosts", 1),
            "spans": len(events),
            "dropped": trace_doc.get("metadata", {}).get("dropped", 0),
            "clock_offsets_ns": trace_doc.get("metadata", {}).get(
                "clock_offsets_ns"),
        },
        "hosts": {str(pid): h for pid, h in hosts.items()},
        "pod_phases": {c: round(s, 6) for c, s in
                       sorted(pod_phases.items(), key=lambda kv: -kv[1])},
        "staging": staging_section(events, timing),
        "ckpt": ckpt_section(events, metrics),
        "devtime": devtime,
        "collectives": collectives_section(collectives),
        "stragglers": stragglers,
        "regression": regression,
        "serving": serving,
        "flights": flights,
        "goodput": goodput_sec,
        "memory": memory,
        "alerts": alerts,
        "verdict": verdict,
    }


def to_markdown(report: Dict[str, Any]) -> str:
    """The human half of the artifact pair."""
    r = report
    lines = ["# tpudist run report", ""]
    run = r["run"]
    if run.get("run_id"):
        att = run.get("requeue_attempt")
        lines += [f"_run {run['run_id']}"
                  + (f" · requeue attempt {att}" if att else "") + "_",
                  ""]
    lines += [f"**Verdict: {r['verdict']}** — regression "
              f"{r['regression']['status']}, stragglers "
              f"{r['stragglers']['status']}, staging "
              f"{run.get('staging_status')}, tuning "
              f"{run.get('tuning_status')}", ""]
    if run.get("run_s"):
        sps = run.get("steps_per_sec")
        warm = run.get("compile_warmup_s")
        lines += [f"- steady-state: {run['steps']} steps in "
                  f"{run['run_s']:.3f}s"
                  + (f" ({sps:.2f} steps/s)" if sps else ""),
                  f"- compile+warmup: "
                  + (f"{warm:.3f}s" if warm is not None else "—"),
                  f"- epochs: {run['epochs']}, final avg loss "
                  f"{run.get('final_avg_loss')}", ""]
    if run.get("resume_status") not in (None, UNGATEABLE):
        lost = run.get("resume_steps_lost")
        req = run.get("requeue_attempt")
        req_note = f", requeue attempt {req}" if req else ""
        if run["resume_status"] == FAIL:
            # a failed restore means the run started FRESH — saying
            # "continued from step 0" would claim a continuation that
            # never happened
            lines += [f"- resume: **fail** — restore errored, run "
                      f"started fresh{req_note}", ""]
        else:
            lines += [f"- resume: **{run['resume_status']}** — continued "
                      f"from global step {run.get('resumed_from_step')}"
                      + (f", ~{lost} step(s) lost to the preemption"
                         if lost is not None else "")
                      + req_note, ""]
    reg = r["regression"]
    if reg["status"] != UNGATEABLE:
        lines += [f"- regression gate: {reg['steps_per_sec']} vs baseline "
                  f"{reg['baseline_steps_per_sec']} steps/s (ratio "
                  f"{reg['ratio']}, floor {reg['min_fraction']}) → "
                  f"**{reg['status']}**", ""]
    lines += ["## Per-host phase breakdown (span self time)", ""]
    cats = list(r["pod_phases"].keys())
    lines += ["| host | wall s | coverage | "
              + " | ".join(cats) + " |",
              "|---|---|---|" + "---|" * len(cats)]
    for pid, h in r["hosts"].items():
        row = [f"host{pid}", f"{h['wall_s']:.3f}",
               f"{h['coverage']:.0%}" if h["coverage"] else "—"]
        row += [f"{h['phases'].get(c, 0.0):.3f}" for c in cats]
        lines.append("| " + " | ".join(row) + " |")
    lines.append("")
    st = r["staging"]
    lines += ["## Staging",
              f"- exposed H2D wait: {st['exposed_wait_s']:.3f}s over "
              f"{st['slabs']} slabs; host staging work "
              f"{st['stage_host_s']:.3f}s "
              f"(overlapped ≈ {st['overlapped_s']:.3f}s)", ""]
    ck = r["ckpt"]
    lines += ["## Checkpointing",
              f"- {ck['saves']} saves, enqueue {ck['enqueue_s']:.3f}s, "
              f"drain {ck['drain_s']:.3f}s over {ck['drain_spans']} "
              f"drain windows (worst {ck['worst_drain_s']:.3f}s)", ""]
    dt = r.get("devtime") or {}
    if dt.get("devices"):
        pod = dt["pod"]
        lines += ["## Device time (compute vs exposed communication)",
                  "",
                  f"**comm_status: {dt['comm_status']}**"
                  + (f" ({dt['fabric']}-graded)"
                     if dt.get("fabric") else "")
                  + f" — exposed "
                  f"comm {pod['exposed_comm_s']:.3f}s summed over "
                  f"{pod['devices']} device track(s), "
                  f"{100 * (pod['exposed_comm_frac'] or 0):.1f}% of "
                  f"device time in a {pod['window_s']:.3f}s window"
                  + (f", baseline "
                     f"{100 * dt['baseline_exposed_comm_frac']:.1f}% "
                     f"(delta "
                     f"{100 * dt['exposed_comm_frac_delta']:+.1f}pp)"
                     if dt.get("exposed_comm_frac_delta") is not None
                     else ""), "",
                  "| device | compute s | comm s | exposed s | idle % |",
                  "|---|---|---|---|---|"]
        for name, d in dt["devices"].items():
            idle = d.get("idle_frac")
            lines.append(
                f"| {name} | {d['compute_s']:.3f} | {d['comm_s']:.3f} "
                f"| {d['exposed_comm_s']:.3f} | "
                + (f"{100 * idle:.1f} |" if idle is not None else "— |"))
        lines.append("")
        if dt.get("exposed_by_phase"):
            lines.append("- exposed comm by host phase: " + ", ".join(
                f"{cat} {s:.3f}s"
                for cat, s in dt["exposed_by_phase"].items()))
            lines.append("")
        if dt.get("dcn_bytes_total") is not None:
            lines.append(
                f"- collective bytes per step (program-derived): "
                f"{dt['dcn_bytes_total']} B over DCN, "
                f"{dt.get('ici_bytes_total') or 0} B over ICI "
                f"({len(dt.get('collectives') or [])} op group(s))")
            lines.append("")
    co = r.get("collectives")
    if co and co.get("per_kind"):
        lines += ["## Collectives (bench sweep)", "",
                  "| kind | fabric | best bus GB/s | % ring peak | "
                  "at bytes |", "|---|---|---|---|---|"]
        for kind, k in sorted(co["per_kind"].items()):
            pct = k.get("pct_of_ring_peak")
            lines.append(
                f"| {kind} | {k.get('fabric') or co.get('fabric') or '—'}"
                f" | {k.get('bus_gbps'):.2f} | "
                + (f"{pct:.1f}" if pct is not None else "—")
                + f" | {k.get('message_bytes')} |")
        lines.append("")
    sv = r.get("serving") or {}
    if sv.get("enabled"):
        lines += ["## Serving (latency SLOs)", "",
                  f"**serve_status: {sv['status']}** — "
                  + ", ".join(f"{rule} {st}"
                              for rule, st in sv["gates"].items()), "",
                  f"- {sv['completed']}/{sv['requests']} requests, "
                  f"{sv['generated_tokens']} tokens in "
                  f"{sv['wall_s']:.3f}s "
                  f"({sv['tokens_per_sec_per_chip']} tok/s/chip"
                  + (f", {sv['tokens_per_chip_ratio']}x baseline"
                     if sv.get("tokens_per_chip_ratio") is not None
                     else "") + ")",
                  f"- TTFT p50/p99: {sv['ttft_p50_s']}/"
                  f"{sv['ttft_p99_s']}s; ITL p50/p99: "
                  f"{sv['itl_p50_s']}/{sv['itl_p99_s']}s",
                  f"- {sv['slots']} slot(s), decode_k "
                  f"{sv['decode_k']}, kv layout {sv['kv_layout']}, "
                  f"queue depth max {sv['queue_depth_max']}, compiles "
                  f"{sv['prefill_compiles']} prefill / "
                  f"{sv['decode_compiles']} decode", ""]
        if sv.get("arrived") is not None:
            lines += [f"- admission: {sv['arrived']} arrived = "
                      f"{sv['admitted']} admitted + "
                      f"{sv['shed_at_admission']} shed + "
                      f"{sv['expired_in_queue']} expired + "
                      f"{sv['rejected']} rejected "
                      f"(shed fraction {sv['shed_fraction']}"
                      + (f", queue cap {sv['queue_cap']}"
                         if sv.get("queue_cap") else "")
                      + (f", deadline {sv['ttft_deadline_s']}s"
                         if sv.get("ttft_deadline_s") else "") + ")",
                      ""]
        if sv.get("adapt_transitions"):
            lines += ["- degradation: " + "; ".join(
                f"L{t['from_level']}→L{t['to_level']} "
                f"(decode_k {t['decode_k']}) at {t['t_s']}s"
                for t in sv["adapt_transitions"]), ""]
        if sv.get("tuning"):
            t = sv["tuning"]
            lines += [f"- serve tune: {t.get('status')} "
                      f"({t.get('source')}, {t.get('trials')} trial(s)) "
                      f"→ decode_k {t.get('decode_k')}, layout "
                      f"{t.get('layout')}", ""]
    fl = r.get("flights") or {}
    if fl.get("enabled"):
        cn = fl.get("counts") or {}
        worst = fl.get("ttft_decomp_worst_s")
        lines += ["## Request flights", "",
                  "**ledger "
                  + ("exact" if fl.get("exact") else "**INEXACT**")
                  + f"** — {fl.get('flights')} flight(s): "
                  f"{cn.get('completed')} completed, "
                  f"{cn.get('evicted')} evicted, "
                  f"{cn.get('shed_at_admission')} shed, "
                  f"{cn.get('expired_in_queue')} expired, "
                  f"{cn.get('rejected')} rejected, "
                  f"{cn.get('lost')} lost"
                  + (" · partition reconciled"
                     if fl.get("partition_checked") else "")
                  + (" · trace cross-checked"
                     if fl.get("trace_checked") else ""), "",
                  f"- TTFT decomposition "
                  f"{fl.get('ttft_decomp_status')}: worst "
                  f"|ttft − (queue + prefill)| = "
                  + (f"{worst * 1e6:.2f}µs" if worst is not None
                     else "—")
                  + f" over {fl.get('decomposed')} flight(s) "
                  f"(tol {fl.get('ttft_decomp_tol_s')}s)", ""]
        dc = fl.get("decomposition") or {}
        if any((dc.get(k) or {}).get("n") for k in dc):
            lines += ["| component | n | p50 s | p99 s |",
                      "|---|---|---|---|"]
            for comp in ("queue_wait", "prefill", "ttft", "decode",
                         "e2e"):
                d = dc.get(comp) or {}
                if d.get("n"):
                    lines.append(f"| {comp} | {d['n']} | "
                                 f"{d.get('p50_s')} | "
                                 f"{d.get('p99_s')} |")
            lines.append("")
        spec_traj = fl.get("spec_accept_over_time") or []
        if spec_traj:
            first, last = spec_traj[0], spec_traj[-1]
            lines += [f"- spec accept trajectory: "
                      f"{first.get('spec_accept_rate')} @ "
                      f"{first.get('t_s')}s → "
                      f"{last.get('spec_accept_rate')} @ "
                      f"{last.get('t_s')}s "
                      f"({len(spec_traj)} tick(s))", ""]
        tl = fl.get("shed_timeline") or []
        if tl:
            shown = tl[:10]
            lines += ["- shed/evict timeline: " + "; ".join(
                f"{e.get('event')} rid={e.get('rid')} @ "
                f"{e.get('t_s')}s" for e in shown)
                + (f" … ({len(tl)} total)"
                   if len(tl) > len(shown) else ""), ""]
        for p in fl.get("problems") or []:
            lines.append(f"- ⚠️ {p}")
        if fl.get("problems"):
            lines.append("")
    gp = r.get("goodput") or {}
    if gp.get("enabled"):
        frac = gp.get("fraction")
        scope = ("across attempts" if gp.get("cross_attempt")
                 else "this attempt (run-end estimate)")
        lines += ["## Goodput (wall-clock accounting)", "",
                  f"**goodput_status: {gp['status']}** — "
                  + (f"{100 * frac:.1f}%" if frac is not None else "—")
                  + f" of {gp.get('total_wall_s') or 0:.2f}s wall was "
                    f"productive step time {scope} (floor "
                    f"{100 * gp['min_fraction']:.0f}%)"]
        if gp.get("cross_attempt"):
            lines += [f"- partition "
                      + ("exact" if gp.get("exact") else "**INEXACT**")
                      + f" (±{100 * (gp.get('tolerance') or 0):.0f}% "
                        f"pinned), {gp.get('lost_steps')} step(s) lost "
                        f"to preemption"]
        bk = gp.get("buckets") or {}
        if bk:
            lines.append("- buckets: " + ", ".join(
                f"{k} {v:.2f}s" for k, v in bk.items()
                if isinstance(v, (int, float))))
        lines.append("")
        atts = gp.get("attempts") or []
        if gp.get("cross_attempt") and atts:
            lines += ["| attempt | wall s | rc | verdict | steps | "
                      "lost | productive s | residue s |",
                      "|---|---|---|---|---|---|---|---|"]
            for a in atts:
                ab = a.get("buckets") or {}
                lines.append(
                    f"| {a.get('attempt')} | "
                    f"{a.get('wall_s') or 0:.2f} | {a.get('rc')} | "
                    f"{a.get('verdict') or '—'} | "
                    f"{a.get('steps_done') if a.get('steps_done') is not None else '—'} | "
                    f"{a.get('lost_steps') if a.get('lost_steps') is not None else '—'} | "
                    f"{ab.get('productive', 0.0):.2f} | "
                    f"{ab.get('residue', 0.0):.2f} |")
            lines.append("")
        for p in gp.get("problems") or []:
            lines.append(f"- ⚠️ {p}")
        if gp.get("problems"):
            lines.append("")
    mem = r.get("memory") or {}
    if mem.get("enabled"):
        frac = mem.get("headroom_fraction")
        total = mem.get("total_hbm_bytes") or 0
        lines += ["## Memory (per-device HBM ledger)", "",
                  f"**hbm_headroom_status: {mem['status']}** — "
                  + (f"{100 * frac:.1f}%" if frac is not None else "—")
                  + f" of {total / 2**20:.0f} MiB device HBM "
                    f"unattributed ({mem.get('mode')} lane, floor "
                    f"{100 * (mem.get('min_fraction') or 0):.0f}%)"
                  + f" · partition "
                  + ("exact" if mem.get("exact") else "**INEXACT**"), ""]
        deltas = mem.get("bucket_delta_bytes") or {}
        has_delta = bool(deltas)
        lines += ["| bucket | MiB | % of HBM |"
                  + (" Δ vs baseline MiB |" if has_delta else ""),
                  "|---|---|---|" + ("---|" if has_delta else "")]
        for b in memledger_mod.BUCKETS:
            v = (mem.get("buckets") or {}).get(b)
            row = (f"| {b} | "
                   + (f"{v / 2**20:.1f}" if v is not None else "—")
                   + " | "
                   + (f"{100 * v / total:.1f}"
                      if v is not None and total else "—") + " |")
            if has_delta:
                d = deltas.get(b)
                row += (f" {d / 2**20:+.1f} |" if d is not None
                        else " — |")
            lines.append(row)
        lines.append("")
        if mem.get("watermark_bytes") is not None:
            lines += [f"- measured watermark: "
                      f"{mem['watermark_bytes'] / 2**20:.1f} MiB "
                      f"({mem.get('watermark_source')})"]
        if mem.get("programs"):
            lines += ["- programs: " + ", ".join(mem["programs"])
                      + ("" if mem.get("program_temp_complete")
                         else " (some without memory_analysis — "
                              "program_temp under-counts)")]
        for p in mem.get("problems") or []:
            lines.append(f"- ⚠️ {p}")
        for n in mem.get("notes") or []:
            lines.append(f"- {n}")
        lines.append("")
    al = r.get("alerts") or {}
    if al.get("enabled"):
        lines += ["## Alerts (live telemetry)", ""]
        if al["history"]:
            lines += ["| rule | host | first fired | duration | state "
                      "| value vs threshold |",
                      "|---|---|---|---|---|---|"]
            for a in al["history"]:
                host = a["host"] if a.get("host") is not None else "pod"
                first = (f"step {a['first_step']}"
                         if a.get("first_step") is not None else "—")
                val = (f"{a['value']:.4g} vs {a['threshold']:.4g}"
                       if isinstance(a.get("value"), (int, float))
                       and isinstance(a.get("threshold"), (int, float))
                       else "—")
                lines.append(
                    f"| {a['alert']} | {host} | {first} | "
                    f"{a.get('duration_s', 0):.1f}s | {a.get('state')} "
                    f"| {val} |")
            lines.append("")
        else:
            lines += ["- no alerts fired", ""]
        for w in al.get("warnings", []):
            lines.append(f"- ⚠️ {w}")
        if al.get("warnings"):
            lines.append("")
    if r["stragglers"]["attribution"]:
        lines += ["## Straggler attribution", ""]
        for a in r["stragglers"]["attribution"]:
            lines.append(
                f"- host{a['process']}: **{a['phase']}** self time "
                f"{a['self_s']:.3f}s vs pod median "
                f"{a['pod_median_s']:.3f}s (+{a['excess_s']:.3f}s)")
        lines.append("")
    tr = r["trace"]
    lines += [f"_trace: {tr['spans']} spans from {tr['hosts']} host(s), "
              f"{tr['dropped']} dropped_", ""]
    return "\n".join(lines)


# -------------------------------------------------------------- CLI


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tpudist.obs.report",
        description="offline tpudist run report from metrics.jsonl + "
                    "pod_trace.json")
    p.add_argument("--run-dir", type=str, default=None,
                   help="directory holding metrics.jsonl and "
                        "pod_trace.json (a train run's --save-dir)")
    p.add_argument("--metrics", type=str, default=None,
                   help="explicit metrics.jsonl path")
    p.add_argument("--trace", type=str, default=None,
                   help="explicit pod_trace.json (or trace.worker<i>."
                        "json) path")
    p.add_argument("--baseline", type=str, default=None,
                   help="baseline JSON carrying steps_per_sec (e.g. a "
                        "prior run_report.json) for the regression gate "
                        "— a prior report also baselines the exposed-"
                        "comm fraction for the devtime delta")
    p.add_argument("--collectives", type=str, default=None,
                   help="BENCH_COLLECTIVES.json (bench.py "
                        "--collective-sweep) folded into the report's "
                        "Collectives section (default: <run-dir>/"
                        "BENCH_COLLECTIVES.json when present)")
    p.add_argument("--alerts", type=str, default=None,
                   help="alert history for the Alerts section: "
                        "alerts.jsonl (the live aggregator's transition "
                        "log) or a live_status.json (default: <run-dir>/"
                        "alerts.jsonl, else <run-dir>/live_status.json "
                        "when present)")
    p.add_argument("--goodput", type=str, default=None,
                   help="prebuilt goodput ledger JSON (python -m "
                        "tpudist.obs.goodput) for the Goodput section "
                        "(default: <run-dir>/goodput.json when "
                        "present)")
    p.add_argument("--attempts", type=str, default=None,
                   help="attempts.jsonl (launcher-written, one record "
                        "per requeue attempt): when present — or found "
                        "in <run-dir> — the cross-attempt goodput "
                        "ledger is built here and folded into the "
                        "Goodput section")
    p.add_argument("--memledger", type=str, default=None,
                   help="memledger.json (the train/serve CLIs write it, "
                        "python -m tpudist.obs.memledger rebuilds it) "
                        "for the Memory section (default: <run-dir>/"
                        "memledger.json when present; a kind=memledger "
                        "record is the in-stream fallback)")
    p.add_argument("--regress-min", type=float, default=None,
                   help=f"regression floor as a fraction of baseline "
                        f"steps/s (default $TPUDIST_REGRESS_MIN, else "
                        f"{REGRESS_MIN_FRACTION})")
    p.add_argument("--out-json", type=str, default=None,
                   help="run_report.json path (default: <run-dir>/"
                        "run_report.json)")
    p.add_argument("--out-md", type=str, default=None,
                   help="run_report.md path (default: <run-dir>/"
                        "run_report.md)")
    args = p.parse_args(argv)

    run_dir = args.run_dir or "."
    metrics_path = args.metrics or os.path.join(run_dir, "metrics.jsonl")
    trace_path = args.trace
    if trace_path is None:
        trace_path = os.path.join(run_dir, "pod_trace.json")
        if not os.path.exists(trace_path):
            # single-worker fallback: the local export is the pod trace
            alt = os.path.join(run_dir, "trace.worker0.json")
            if os.path.exists(alt):
                trace_path = alt
    for path, what in ((metrics_path, "metrics"), (trace_path, "trace")):
        if not os.path.exists(path):
            print(f"tpudist.obs.report: missing {what} file {path}",
                  file=sys.stderr)
            return 2

    metrics = load_metrics(metrics_path)
    trace_doc = load_trace(trace_path)
    baseline = None
    if args.baseline:
        with open(args.baseline) as f:
            baseline = json.load(f)
        warn_newer_schema(baseline, "baseline")
    collectives = None
    coll_path = args.collectives or os.path.join(run_dir,
                                                 "BENCH_COLLECTIVES.json")
    if os.path.exists(coll_path):
        with open(coll_path) as f:
            collectives = json.load(f)
    elif args.collectives:
        print(f"tpudist.obs.report: missing collectives file "
              f"{coll_path}", file=sys.stderr)
        return 2

    alert_history = None
    alerts_path = args.alerts
    if alerts_path is None:
        for cand in (os.path.join(run_dir, "alerts.jsonl"),
                     os.path.join(run_dir, "live_status.json")):
            if os.path.exists(cand):
                alerts_path = cand
                break
    if alerts_path:
        if not os.path.exists(alerts_path):
            print(f"tpudist.obs.report: missing alerts file "
                  f"{alerts_path}", file=sys.stderr)
            return 2
        with open(alerts_path) as f:
            if alerts_path.endswith(".jsonl"):
                alert_history = [json.loads(line)
                                 for line in f if line.strip()]
            else:
                # a live_status.json: the final snapshot's full history
                status_doc = json.load(f)
                warn_newer_schema(status_doc, "alerts")
                alert_history = (status_doc.get("alerts") or {}).get(
                    "history", [])

    # the goodput ledger: a prebuilt goodput.json wins; else an
    # attempts.jsonl (given or discovered in the run dir) builds the
    # cross-attempt ledger right here (goodput is jax-free like this
    # whole CLI); single-attempt runs fall back to the kind=goodput
    # record inside build_report
    goodput_doc = None
    gp_path = args.goodput or os.path.join(run_dir, "goodput.json")
    if args.goodput and not os.path.exists(gp_path):
        print(f"tpudist.obs.report: missing goodput file {gp_path}",
              file=sys.stderr)
        return 2
    if os.path.exists(gp_path):
        with open(gp_path) as f:
            goodput_doc = json.load(f)
        warn_newer_schema(goodput_doc, "goodput")
    else:
        attempts_path = args.attempts or os.path.join(
            run_dir, goodput_mod.ATTEMPTS_NAME)
        if args.attempts and not os.path.exists(attempts_path):
            print(f"tpudist.obs.report: missing attempts file "
                  f"{attempts_path}", file=sys.stderr)
            return 2
        if os.path.exists(attempts_path):
            goodput_doc = goodput_mod.build_from_dir(
                run_dir, attempts_path=attempts_path)

    # the memory ledger: an explicit --memledger must exist; the
    # discovered <run-dir>/memledger.json is optional — run dirs from
    # before the ledger still fold (the section reads UNGATEABLE)
    memledger_doc = None
    ml_path = args.memledger or os.path.join(run_dir,
                                             memledger_mod.LEDGER_NAME)
    if args.memledger and not os.path.exists(ml_path):
        print(f"tpudist.obs.report: missing memledger file {ml_path}",
              file=sys.stderr)
        return 2
    if os.path.exists(ml_path):
        with open(ml_path) as f:
            memledger_doc = json.load(f)
        warn_newer_schema(memledger_doc, "memledger")

    report = build_report(metrics, trace_doc, baseline=baseline,
                          regress_min=args.regress_min,
                          collectives=collectives,
                          alert_history=alert_history,
                          goodput=goodput_doc,
                          memledger=memledger_doc)
    out_json = args.out_json or os.path.join(run_dir, "run_report.json")
    out_md = args.out_md or os.path.join(run_dir, "run_report.md")
    for path, payload in ((out_json, json.dumps(report, indent=1)),
                          (out_md, to_markdown(report))):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, path)
    print(f"tpudist: run report {report['verdict']}: {out_json} "
          f"({report['trace']['spans']} spans, "
          f"{len(report['hosts'])} host(s))")
    return 0 if report["verdict"] != FAIL else 1


if __name__ == "__main__":
    sys.exit(main())
