"""Flight-record dump: the post-mortem a hung pod run leaves behind.

The dominant real-world failure mode of a pod acceptance test is not a
crash but a *hang* — one worker stalls in a collective and every other
rank blocks silently until the launcher's outer timeout, leaving zero
evidence of which host or which step died. :func:`dump_flight_record`
writes that evidence while the process is still alive: faulthandler
stacks of every thread (the wedged collective's frame is right there),
per-device ``memory_stats()``, the last progress beacon, and the tail of
the in-memory metrics history — one JSON artifact per worker
(``flightrec.worker<i>``), written atomically so a kill mid-dump cannot
leave a half-parsed file.

The writer must itself be hang-proof: it takes no locks it does not own,
touches the device runtime only through ``memory_stats()`` (a host-side
query that does not enqueue device work), and swallows per-section
failures so a broken backend cannot turn the diagnosis into a second
hang.
"""

from __future__ import annotations

import faulthandler
import json
import os
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional

FLIGHTREC_SCHEMA_VERSION = 1


def thread_stacks() -> str:
    """All threads' stacks as text, via :mod:`faulthandler` (the signal-
    safe dumper — it walks frames without allocating, so it works even
    when the main thread is wedged holding internal locks). faulthandler
    needs a real file descriptor, so route it through a TemporaryFile."""
    try:
        with tempfile.TemporaryFile(mode="w+") as tf:
            faulthandler.dump_traceback(file=tf, all_threads=True)
            tf.seek(0)
            return tf.read()
    except Exception as e:   # a diagnosis tool must not raise
        return f"<thread stack dump failed: {e!r}>"


def collect_memory_stats() -> List[Dict[str, Any]]:
    """Per-local-device ``memory_stats()`` snapshots (None entries on
    backends that report nothing, e.g. CPU)."""
    out: List[Dict[str, Any]] = []
    try:
        import jax
        for d in jax.local_devices():
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            out.append({"id": d.id, "kind": getattr(d, "device_kind", "?"),
                        "stats": stats})
    except Exception:
        pass
    return out


def dump_flight_record(path: str, *, reason: str,
                       progress: Optional[Dict[str, Any]] = None,
                       stall_s: Optional[float] = None,
                       last_metrics: Optional[List[Dict]] = None,
                       spans: Optional[List[Dict]] = None,
                       extra: Optional[Dict[str, Any]] = None) -> str:
    """Write one flight-record artifact to ``path`` and return the path.

    The artifact is a single JSON object (CI parses it) with:
    ``reason`` (why the dump fired), ``progress`` (the last beacon:
    step/epoch/phase/ts), ``thread_stacks`` (faulthandler text),
    ``memory_stats`` (per device), ``last_metrics`` (tail of the
    in-memory record history), ``spans`` (the span tracer's per-thread
    buffer tails + open-span stacks — what phase each thread was in
    when the dump fired), and any ``extra`` observer state (HBM
    watermarks). Atomic write: tmp + ``os.replace``."""
    payload: Dict[str, Any] = {
        "schema": FLIGHTREC_SCHEMA_VERSION,
        "reason": reason,
        "ts": time.time(),
        "pid": os.getpid(),
        "python": sys.version.split()[0],
        "stall_s": stall_s,
        "progress": progress or {},
        "thread_stacks": thread_stacks(),
        "memory_stats": collect_memory_stats(),
        "last_metrics": list(last_metrics or []),
        "spans": spans,
    }
    if extra:
        payload["extra"] = extra
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1, default=str)
    os.replace(tmp, path)
    return path
