"""HBM watermark sampler: "the staging budget was nearly blown" as a
number, not a guess.

A background thread polls ``device.memory_stats()`` (``bytes_in_use`` /
``peak_bytes_in_use``) every ``period_s`` and keeps the high-water mark
across the run. The poll is a host-side runtime query — it enqueues no
device work, so sampling cannot perturb the training it observes.

Backends that report no memory stats at all (the CPU test mesh) fall
back to the process's peak RSS (``ru_maxrss``) so the watermark fields
are always populated: on the CPU backend device memory IS host memory,
and the `hbm_source` field says which estimate you are reading.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional


def _rss_peak_bytes() -> Optional[int]:
    """Peak RSS of this process in bytes (Linux ru_maxrss is KiB)."""
    try:
        import resource
        import sys
        ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return int(ru) if sys.platform == "darwin" else int(ru) * 1024
    except Exception:
        return None


class HbmSampler:
    """Background high-water-mark tracker over local devices.

    ``period_s > 0`` starts a daemon thread; ``period_s == 0`` makes the
    sampler manual (callers invoke :meth:`sample` themselves — the bench
    sweeps do this so the sampling points bracket their timed windows).
    One synchronous sample is always taken at construction so short runs
    still report a watermark.
    """

    def __init__(self, period_s: float = 2.0):
        if period_s < 0:
            raise ValueError(f"period_s must be >= 0, got {period_s}")
        self.period_s = float(period_s)
        self.peak_in_use = 0        # max over time of max over devices
        self.last_in_use = 0
        self.last_reserved: Optional[int] = None  # allocator reservation
        self.limit_bytes: Optional[int] = None
        self.source = "none"        # memory_stats | rss | none
        self.samples = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.sample()
        if self.period_s > 0:
            self._thread = threading.Thread(
                target=self._loop, name="tpudist-hbm", daemon=True)
            self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.period_s):
            self.sample()

    def sample(self) -> None:
        """One poll of every local device; fold into the high-water
        mark. Never raises — a dead backend must not kill the thread."""
        in_use = 0
        peak_reported = 0
        reserved = None
        got_stats = False
        try:
            import jax
            for d in jax.local_devices():
                try:
                    stats = d.memory_stats()
                except Exception:
                    stats = None
                if not stats:
                    continue
                got_stats = True
                in_use = max(in_use, int(stats.get("bytes_in_use", 0)))
                peak_reported = max(
                    peak_reported, int(stats.get("peak_bytes_in_use", 0)))
                res = stats.get("bytes_reserved")
                if res is not None:
                    reserved = max(reserved or 0, int(res))
                limit = stats.get("bytes_limit")
                if limit:
                    self.limit_bytes = int(limit)
        except Exception:
            pass
        if got_stats:
            self.source = "memory_stats"
            self.last_in_use = in_use
            self.last_reserved = reserved
            self.peak_in_use = max(self.peak_in_use, in_use, peak_reported)
        elif self.source != "memory_stats":
            # RSS fallback ONLY on backends that never reported device
            # stats: one transient memory_stats() failure mid-run must
            # not fold host RSS (tens of GB on a TPU VM) into a device
            # watermark that can never recede
            rss = _rss_peak_bytes()
            if rss is not None:
                self.source = "rss"
                self.last_in_use = rss
                self.peak_in_use = max(self.peak_in_use, rss)
        self.samples += 1

    def split(self) -> Dict[str, Any]:
        """Watermark fields for the ``kind=timing`` record and the
        flight-record dump."""
        frac = None
        if self.limit_bytes and self.peak_in_use:
            frac = round(self.peak_in_use / self.limit_bytes, 4)
        # fragmentation: what the allocator holds beyond live buffers —
        # reserved minus in-use, only on backends whose memory_stats
        # report a reservation (RSS says nothing about the allocator)
        frag = None
        if self.last_reserved is not None \
                and self.source == "memory_stats":
            frag = max(0, self.last_reserved - self.last_in_use)
        return {"hbm_peak_bytes": self.peak_in_use or None,
                "hbm_bytes_in_use": self.last_in_use or None,
                "hbm_bytes_reserved": self.last_reserved,
                "hbm_fragmentation_bytes": frag,
                "hbm_limit_bytes": self.limit_bytes,
                "hbm_peak_fraction": frac,
                "hbm_source": self.source}

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self.sample()   # final watermark covers the run's tail
