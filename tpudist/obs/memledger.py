"""HBM memory ledger: every device byte attributed to a named bucket.

HBM is the binding resource for both halves of the framework — the
paged KV serve lane and streamed staging exist precisely to live inside
a fixed HBM budget — yet until this module the framework could only
observe memory as an opaque watermark (:mod:`tpudist.obs.hbm` samples
``memory_stats`` / RSS) and the staging resolver guessed with a
``state_bytes x 4`` margin. Footprint is a property of the *compiled
program* and of the model's own static buffers, so it should be READ,
not sampled: ``compiled.memory_analysis()`` gives argument/output/temp/
generated-code bytes per pinned program (train step/superstep, serve
prefill, each decode-ladder rung, the speculative verify program), and
the model gives its static buckets (params and optimizer state from
``engine.state_bytes_per_device``, resident staged slabs from
``sharding.plan_slabs``, the KV pool + page table from
``PagedCacheSpec.bytes``).

The ledger partitions one device's HBM EXACTLY — the same discipline as
the devtime decomposition (PR 6), the goodput ledger (PR 10) and the
shed ledger (PR 15) — into::

    params / opt_state / slabs / kv_pool / program_temp
    / headroom / residue        (sum == device HBM, by construction)

``program_temp`` is the MAX across programs of temp + generated-code
bytes (programs never run concurrently on one device, so peak scratch
is the max, not the sum). ``residue`` reconciles the derived footprint
against the measured :class:`~tpudist.obs.hbm.HbmSampler` watermark
when the backend reports real device stats: it is what the model failed
to attribute (allocator overhead, fragmentation, untracked buffers) —
flagged ``exact=False`` past the pinned :data:`TOLERANCE`. ``headroom``
is the honest remainder; a NEGATIVE headroom means the pod is
over-committed and one allocation spike from ``RESOURCE_EXHAUSTED``,
which is why the ``hbm_headroom`` rule's default floor of 0.0 breaches
on it even with no opt-in.

Four consumers:

  * the train/serve loops log a ``kind=memledger`` record
    (:func:`ledger_record`) the live aggregator turns into
    ``tpudist_hbm_bytes{bucket=...}`` gauges and grades against
    ``TPUDIST_HBM_HEADROOM_MIN``;
  * :mod:`tpudist.obs.report` renders a jax-free "Memory" section with
    the bucket table and delta-vs-baseline;
  * OOM forensics: the flight recorder embeds the last ledger, and
    ``python -m tpudist.obs.memledger --run-dir D`` reconstructs from
    artifacts alone which bucket grew before a RESOURCE_EXHAUSTED death
    and names the knob to turn (:data:`KNOBS`);
  * feed-forward: ``config.resolve_staging_budget_bytes`` and the serve
    allocator's admission bound accept the ledger's measured temp bytes
    in place of the 4x heuristic (heuristic kept as fallback, choice
    logged).

jax-free by design (the offline-tooling contract shared with
:mod:`tpudist.obs.report` and :mod:`tpudist.obs.goodput`): the CLI runs
on the CI host or a laptop against scp'd artifacts.

CLI::

    python -m tpudist.obs.memledger --run-dir DIR \
        [--out memledger.json] [--bench-out BENCH_MEMORY.json] \
        [--prom-out memledger.prom] [--baseline OLD/memledger.json]
    python -m tpudist.obs.memledger --drill --run-dir DIR   # scripted OOM
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

from tpudist import rules as rules_lib

MEMLEDGER_SCHEMA_VERSION = 1
LEDGER_NAME = "memledger.json"

# Partition exactness: the pinned tolerance (fraction of device HBM)
# past which the watermark-reconciliation residue flags the ledger
# inexact — the same ±1% discipline as devtime and goodput.
TOLERANCE = 0.01

SUCCESS = "success"     # mirrors tpudist.verdict vocabulary without the
FAIL = "fail"           # import (same pattern as obs.goodput/obs.alerts)
UNGATEABLE = "ungateable"

# The headroom floor lives in tpudist.rules with every other gate
# (TPUDIST_HBM_HEADROOM_MIN, resolved at call time); the alias is this
# module's documented surface, like goodput's.
HBM_HEADROOM_MIN = rules_lib.HBM_HEADROOM_MIN

# Bucket names, display order. The first five are attributed; headroom
# and residue close the partition (sum over BUCKETS == device HBM).
BUCKETS = ("params", "opt_state", "slabs", "kv_pool", "program_temp",
           "headroom", "residue")
ATTRIBUTED = ("params", "opt_state", "slabs", "kv_pool", "program_temp")

# Forensics: the knob that shrinks each growable bucket — what the CLI
# prints after naming the guilty bucket, so an OOM post-mortem ends
# with an action, not just a diagnosis.
KNOBS = {
    "params": "shard the model further (--fsdp-shard / --tensor-"
              "parallel) or pick a smaller --model",
    "opt_state": "optimizer state scales with params: shard further "
                 "(--fsdp-shard) or reduce the model",
    "slabs": "--staging-budget-mb (env TPUDIST_STAGING_BUDGET_MB): a "
             "smaller budget streams more, smaller slabs",
    "kv_pool": "--kv-pages / --kv-page-tokens (or fewer --slots): "
               "shrink the paged KV pool and page table",
    "program_temp": "--steps-per-dispatch (train superstep scratch) / "
                    "the decode_k ladder and --speculate-k (serve "
                    "scratch)",
}


def hbm_headroom_status(fraction: Optional[float],
                        min_fraction: Optional[float] = None) -> str:
    """Three-valued headroom verdict: UNGATEABLE with nothing derived
    (a run with no ledger must not read as a headroom pass), else
    SUCCESS/FAIL by whether the free fraction clears
    ``TPUDIST_HBM_HEADROOM_MIN``. The default floor is 0.0, so only an
    over-committed device (negative headroom) fails without opt-in —
    how much slack a pod NEEDS is a capacity-planning choice."""
    if fraction is None:
        return UNGATEABLE
    if min_fraction is None:
        min_fraction = rules_lib.resolve("hbm_headroom")
    return SUCCESS if fraction >= min_fraction else FAIL


# ------------------------------------------------------------- the ledger


def program_temp_bytes(programs: Optional[Dict[str, Dict[str, Any]]]
                       ) -> Tuple[int, bool]:
    """(peak scratch bytes, complete) across the pinned programs.

    Programs never run concurrently on one device (the two-compiled-
    programs discipline serializes them), so the resident scratch peak
    is the MAX of each program's temp + generated-code bytes, not the
    sum. ``complete`` is False when any program reported no analysis
    (CPU builds may not implement memory planning) — the bucket then
    under-counts and the ledger records the gap as a note, not a lie.
    """
    peak = 0
    complete = True
    for mem in (programs or {}).values():
        if not mem:
            complete = False
            continue
        peak = max(peak, int(mem.get("temp_bytes") or 0)
                   + int(mem.get("generated_code_bytes") or 0))
    return peak, complete


def build_ledger(*, total_hbm_bytes: float,
                 params_bytes: float = 0,
                 opt_state_bytes: float = 0,
                 slab_bytes: float = 0,
                 kv_pool_bytes: float = 0,
                 programs: Optional[Dict[str, Dict[str, Any]]] = None,
                 watermark_bytes: Optional[float] = None,
                 watermark_source: Optional[str] = None,
                 mode: str = "train",
                 run_id: Optional[str] = None,
                 tolerance: float = TOLERANCE) -> Dict[str, Any]:
    """Partition one device's HBM into the memory buckets.

    All byte inputs are PER-DEVICE numbers (the engine's
    ``state_bytes_per_device`` convention). The sum of all buckets
    equals ``total_hbm_bytes`` EXACTLY by construction: ``residue`` is
    the watermark-vs-derived reconciliation (zero when the watermark is
    not a real device measurement — RSS on the CPU mesh says nothing
    about a device partition) and ``headroom`` is the remainder.
    ``exact`` certifies the reconciliation stayed inside the pinned
    tolerance and nothing over-committed the device.
    """
    total = int(total_hbm_bytes)
    if total <= 0:
        raise ValueError(f"total_hbm_bytes must be > 0, got "
                         f"{total_hbm_bytes!r} — the device HBM size is "
                         f"the partition's spine (TPUDIST_HBM_BYTES "
                         f"pins it on backends that report none)")
    programs = dict(programs or {})
    temp, complete = program_temp_bytes(programs)
    buckets: Dict[str, int] = {
        "params": int(params_bytes),
        "opt_state": int(opt_state_bytes),
        "slabs": int(slab_bytes),
        "kv_pool": int(kv_pool_bytes),
        "program_temp": temp,
    }
    derived = sum(buckets.values())

    exact = True
    problems: List[str] = []
    notes: List[str] = []
    for k, v in buckets.items():
        if v < 0:
            exact = False
            problems.append(f"bucket {k} is negative ({v} bytes) — a "
                            f"byte count can never be")
            buckets[k] = 0
    derived = sum(buckets.values())

    # residue: what the measured watermark saw that the model did not
    # attribute (allocator overhead, fragmentation, untracked buffers)
    # — only a REAL device measurement reconciles; an RSS fallback
    # watermark measures the host, not the device partition
    reconciled = watermark_source == "memory_stats" \
        and watermark_bytes is not None
    residue = int(watermark_bytes) - derived if reconciled else 0
    if reconciled and abs(residue) > tolerance * total:
        exact = False
        if residue > 0:
            problems.append(
                f"measured watermark exceeds the derived footprint by "
                f"{residue} bytes ({residue / total:.1%} of HBM) — "
                f"unattributed allocations")
        else:
            problems.append(
                f"derived footprint exceeds the measured watermark by "
                f"{-residue} bytes ({-residue / total:.1%} of HBM) — "
                f"double counting or never-materialized buffers")
    buckets["residue"] = residue
    buckets["headroom"] = total - derived - residue
    if buckets["headroom"] < 0:
        # over-committed: not an accounting error (the partition is
        # still exact — headroom honestly negative), but the pod is one
        # allocation spike from RESOURCE_EXHAUSTED; the headroom rule's
        # default 0.0 floor breaches on exactly this
        notes.append(f"device over-committed by {-buckets['headroom']} "
                     f"bytes — headroom is negative")
    if not complete:
        missing = sorted(k for k, v in programs.items() if not v)
        notes.append("no memory_analysis for program(s) "
                     f"{', '.join(missing)} — program_temp under-counts "
                     f"(backend does not report memory planning)")

    frac = round(buckets["headroom"] / total, 6)
    return {
        "schema": MEMLEDGER_SCHEMA_VERSION,
        "mode": mode,
        "run_id": run_id,
        "total_hbm_bytes": total,
        "buckets": {k: int(buckets[k]) for k in BUCKETS},
        "programs": {k: dict(v or {}) for k, v in programs.items()},
        "program_temp_complete": complete,
        "watermark_bytes": (int(watermark_bytes)
                            if watermark_bytes is not None else None),
        "watermark_source": watermark_source,
        "headroom_fraction": frac,
        "headroom_status": hbm_headroom_status(frac),
        "headroom_min": rules_lib.resolve("hbm_headroom"),
        "exact": exact,
        "tolerance": tolerance,
        "problems": problems,
        "notes": notes,
    }


def ledger_record(ledger: Dict[str, Any]) -> Dict[str, Any]:
    """The ledger as the flat ``kind=memledger`` metrics record: one
    ``<bucket>_bytes`` field per bucket plus the headroom grade — the
    shape the live aggregator ingests and the report CLI reads back."""
    b = ledger.get("buckets") or {}
    rec: Dict[str, Any] = {
        "total_hbm_bytes": ledger.get("total_hbm_bytes"),
        "headroom_fraction": ledger.get("headroom_fraction"),
        "hbm_headroom_status": ledger.get("headroom_status"),
        "watermark_bytes": ledger.get("watermark_bytes"),
        "watermark_source": ledger.get("watermark_source"),
        "program_temp_complete": ledger.get("program_temp_complete"),
        "exact": ledger.get("exact"),
        "mode": ledger.get("mode"),
    }
    for k in BUCKETS:
        rec[f"{k}_bytes"] = b.get(k)
    return rec


def from_record(rec: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """A minimal ledger dict back out of a flat ``kind=memledger``
    record (the forensics path reads history from metrics.jsonl). None
    when the record carries no bucket bytes at all."""
    buckets = {}
    for k in BUCKETS:
        v = rec.get(f"{k}_bytes")
        if isinstance(v, (int, float)):
            buckets[k] = int(v)
    if not buckets:
        return None
    return {
        "schema": MEMLEDGER_SCHEMA_VERSION,
        "mode": rec.get("mode"),
        "run_id": rec.get("run_id"),
        "total_hbm_bytes": rec.get("total_hbm_bytes"),
        "buckets": {k: buckets.get(k, 0) for k in BUCKETS},
        "programs": {},
        "program_temp_complete": rec.get("program_temp_complete"),
        "watermark_bytes": rec.get("watermark_bytes"),
        "watermark_source": rec.get("watermark_source"),
        "headroom_fraction": rec.get("headroom_fraction"),
        "headroom_status": rec.get("hbm_headroom_status"),
        "exact": rec.get("exact"),
        "problems": [],
        "notes": [],
    }


# ----------------------------------------------------------- forensics


def load_jsonl(path: str) -> List[Dict[str, Any]]:
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue    # a torn tail line is not evidence
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out


def _find_flightrecs(run_dir: str) -> List[str]:
    paths = set(glob.glob(os.path.join(run_dir, "**", "flightrec.worker*"),
                          recursive=True))
    return sorted(p for p in paths if not p.endswith(".tmp"))


def collect_ledgers(run_dir: str) -> List[Tuple[str, Dict[str, Any]]]:
    """Every ledger snapshot the run left behind, in evidence order:
    ``kind=memledger`` metrics records first (the run's own timeline),
    then the ``memledger.json`` artifact (the run-end state), then any
    flight-record-embedded ledger LAST — a flight record is dumped at
    death, so its ledger is the final pre-mortem state. Returns
    ``(source, ledger)`` pairs."""
    out: List[Tuple[str, Dict[str, Any]]] = []
    mpaths = set(glob.glob(os.path.join(run_dir, "metrics.jsonl")))
    mpaths |= set(glob.glob(os.path.join(run_dir, "*", "metrics.jsonl")))
    for mp in sorted(mpaths):
        for rec in load_jsonl(mp):
            if rec.get("kind") != "memledger":
                continue
            led = from_record(rec)
            if led is not None:
                out.append((os.path.basename(mp), led))
    apath = os.path.join(run_dir, LEDGER_NAME)
    if os.path.exists(apath):
        try:
            with open(apath) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            doc = None
        if isinstance(doc, dict) and isinstance(doc.get("buckets"), dict):
            out.append((LEDGER_NAME, doc))
    for fp in _find_flightrecs(run_dir):
        try:
            with open(fp) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            continue
        led = (payload.get("extra") or {}).get("memledger") \
            if isinstance(payload, dict) else None
        if isinstance(led, dict) and isinstance(led.get("buckets"), dict):
            out.append((os.path.basename(fp), led))
    return out


def find_oom(run_dir: str) -> Optional[Dict[str, Any]]:
    """The death evidence: the first flight record whose ``reason``
    mentions RESOURCE_EXHAUSTED (XLA's OOM vocabulary) — returns
    ``{"source", "reason"}`` or None for a run that did not die of
    memory."""
    for fp in _find_flightrecs(run_dir):
        try:
            with open(fp) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            continue
        reason = str(payload.get("reason") or "")
        if "RESOURCE_EXHAUSTED" in reason.upper():
            return {"source": os.path.basename(fp), "reason": reason}
    return None


def diagnose(run_dir: str) -> Dict[str, Any]:
    """OOM forensics from artifacts alone: which bucket grew before the
    death, and which knob turns it. Compares the earliest ledger
    snapshot (the baseline) against the latest (the flight-record-
    embedded pre-mortem state when one exists); with a single snapshot
    the largest attributed bucket is named instead — a one-snapshot
    post-mortem can still say where the bytes went."""
    ledgers = collect_ledgers(run_dir)
    oom = find_oom(run_dir)
    if not ledgers:
        return {"oom": oom is not None,
                "reason": oom["reason"] if oom else None,
                "guilty_bucket": None, "knob": None, "growth": {},
                "baseline_source": None, "death_source": None,
                "ledgers": 0}
    death_source, death = ledgers[-1]
    base_source, base = ledgers[0]
    growth: Dict[str, int] = {}
    guilty = None
    if len(ledgers) >= 2:
        db, bb = death.get("buckets") or {}, base.get("buckets") or {}
        for k in ATTRIBUTED:
            d = int(db.get(k) or 0) - int(bb.get(k) or 0)
            if d:
                growth[k] = d
        grew = {k: v for k, v in growth.items() if v > 0}
        if grew:
            guilty = max(grew, key=lambda k: grew[k])
    if guilty is None:
        db = death.get("buckets") or {}
        sized = {k: int(db.get(k) or 0) for k in ATTRIBUTED}
        if any(sized.values()):
            guilty = max(sized, key=lambda k: sized[k])
    return {"oom": oom is not None,
            "reason": oom["reason"] if oom else None,
            "guilty_bucket": guilty,
            "knob": KNOBS.get(guilty) if guilty else None,
            "growth": growth,
            "baseline_source": base_source if len(ledgers) >= 2 else None,
            "death_source": death_source,
            "ledgers": len(ledgers)}


def _mib(b: Any) -> str:
    return f"{int(b) / 2**20:.1f} MiB" if isinstance(b, (int, float)) \
        else "?"


def forensics_lines(diag: Dict[str, Any]) -> List[str]:
    lines: List[str] = []
    if diag.get("oom"):
        lines.append(f"tpudist: memledger OOM death detected "
                     f"({diag.get('death_source')}): "
                     f"{diag.get('reason')}")
    guilty = diag.get("guilty_bucket")
    if guilty is None:
        if diag.get("oom"):
            lines.append("tpudist: memledger forensics: no ledger "
                         "snapshot survived — cannot name a bucket")
        return lines
    delta = (diag.get("growth") or {}).get(guilty)
    if delta is not None and diag.get("baseline_source"):
        lines.append(
            f"tpudist: memledger guilty bucket: {guilty} grew "
            f"{_mib(delta)} between {diag['baseline_source']} and "
            f"{diag['death_source']}")
    else:
        lines.append(
            f"tpudist: memledger guilty bucket: {guilty} (largest "
            f"attributed bucket at {diag['death_source']})")
    lines.append(f"tpudist: memledger knob: {KNOBS[guilty]}")
    return lines


# --------------------------------------------------- prometheus textfile


_PROM_HELP = {
    "tpudist_memledger_info": "Ledger identity (labels carry mode and "
                              "exactness).",
    "tpudist_hbm_bytes": "Per-device HBM bytes per ledger bucket (the "
                         "partition sums to device HBM).",
    "tpudist_hbm_total_bytes": "Device HBM size the ledger partitions.",
    "tpudist_hbm_headroom_fraction": "Unattributed free fraction of "
                                     "device HBM.",
    "tpudist_memledger_exact": "1 when the watermark reconciliation "
                               "met the pinned tolerance.",
}


def prometheus_text(ledger: Dict[str, Any]) -> str:
    """The ledger as Prometheus text exposition (0.0.4), rendered with
    the SAME escaping/number formatting as the live exporter so the
    offline ``tpudist_hbm_bytes`` family reads identically to the live
    gauges (the consumer-parity pin)."""
    from tpudist.obs.live import _prom_escape, _prom_num
    out: List[str] = []

    def metric(name, samples, mtype="gauge"):
        rows = [(lbl, v) for lbl, v in samples if v is not None]
        if not rows:
            return
        out.append(f"# HELP {name} {_PROM_HELP[name]}")
        out.append(f"# TYPE {name} {mtype}")
        for lbl, v in rows:
            label_s = ",".join(f'{k}="{_prom_escape(x)}"'
                               for k, x in lbl.items())
            out.append(f"{name}{{{label_s}}} {_prom_num(v)}"
                       if label_s else f"{name} {_prom_num(v)}")

    metric("tpudist_memledger_info",
           [({"mode": ledger.get("mode") or "",
              "exact": str(bool(ledger.get("exact"))).lower()}, 1)])
    metric("tpudist_hbm_bytes",
           [({"bucket": k}, (ledger.get("buckets") or {}).get(k))
            for k in BUCKETS])
    metric("tpudist_hbm_total_bytes",
           [({}, ledger.get("total_hbm_bytes"))])
    metric("tpudist_hbm_headroom_fraction",
           [({}, ledger.get("headroom_fraction"))])
    metric("tpudist_memledger_exact",
           [({}, 1 if ledger.get("exact") else 0)])
    return "\n".join(out) + "\n"


def bench_artifact(ledger: Dict[str, Any],
                   extra_detail: Optional[Dict[str, Any]] = None
                   ) -> Dict[str, Any]:
    """BENCH_MEMORY-style artifact on the shared BENCH_* harness shape:
    the headline value is the headroom fraction, the detail the full
    ledger (plus any sweep rows the bench driver appends)."""
    detail: Dict[str, Any] = {"ledger": ledger}
    if extra_detail:
        detail.update(extra_detail)
    return {
        "metric": "hbm_headroom_fraction",
        "value": ledger.get("headroom_fraction"),
        "unit": "unattributed free fraction of device HBM",
        "detail": detail,
    }


# ----------------------------------------------------------- the drill


DRILL_REASON = ("RESOURCE_EXHAUSTED: scripted OOM drill — allocation "
                "would exceed device HBM")


def run_drill(run_dir: str, *, grow: str = "slabs") -> str:
    """The scripted OOM drill: take the run directory's REAL ledger (a
    prior train/serve run wrote it), synthesize the pre-mortem state an
    OOM'ing run would have reached — the ``grow`` bucket inflated past
    the device's remaining headroom, partition kept exact — and dump a
    flight record with that ledger embedded and a RESOURCE_EXHAUSTED
    reason, exactly the artifact the heartbeat watchdog leaves behind.
    The forensics path must then reconstruct the guilty bucket from the
    artifacts alone. Returns the flight-record path. jax-free."""
    from tpudist.obs import flightrec

    if grow not in ATTRIBUTED:
        raise ValueError(f"--drill-grow must be one of {ATTRIBUTED}, "
                         f"got {grow!r}")
    apath = os.path.join(run_dir, LEDGER_NAME)
    try:
        with open(apath) as f:
            base = json.load(f)
    except (OSError, ValueError):
        raise RuntimeError(
            f"no baseline ledger at {apath} — run the train/serve CLI "
            f"into --run-dir first (the drill grows a REAL ledger)")
    buckets = dict(base.get("buckets") or {})
    total = int(base.get("total_hbm_bytes") or 0)
    if total <= 0:
        raise RuntimeError(f"baseline ledger at {apath} carries no "
                           f"total_hbm_bytes")
    # grow the bucket past everything the device had left: headroom
    # goes negative by one page-ish margin — the allocation that died
    delta = max(int(buckets.get("headroom") or 0), 0) + (1 << 20)
    death = {k: dict(v) if isinstance(v, dict) else v
             for k, v in base.items()}
    death["buckets"] = dict(buckets)
    death["buckets"][grow] = int(buckets.get(grow) or 0) + delta
    death["buckets"]["headroom"] = int(buckets.get("headroom") or 0) \
        - delta
    frac = round(death["buckets"]["headroom"] / total, 6)
    death["headroom_fraction"] = frac
    death["headroom_status"] = hbm_headroom_status(frac)
    death["notes"] = list(base.get("notes") or []) + [
        f"scripted OOM drill grew {grow} by {delta} bytes"]
    path = os.path.join(run_dir, "flightrec.worker0")
    flightrec.dump_flight_record(
        path, reason=DRILL_REASON,
        progress={"drill": "memledger-oom", "grew": grow},
        extra={"memledger": death})
    return path


# -------------------------------------------------------------- the CLI


def _summary_lines(ledger: Dict[str, Any]) -> List[str]:
    b = ledger.get("buckets") or {}
    frac = ledger.get("headroom_fraction")
    lines = [
        f"tpudist: memledger [{ledger.get('mode')}] hbm_headroom "
        f"{ledger.get('headroom_status')}: "
        + (f"{100 * frac:.1f}% free" if frac is not None
           else "nothing derived")
        + f" of {_mib(ledger.get('total_hbm_bytes'))} device HBM",
        "tpudist: memledger buckets: " + ", ".join(
            f"{k} {_mib(b.get(k, 0))}" for k in BUCKETS),
        f"tpudist: memledger partition "
        f"{'exact' if ledger.get('exact') else 'INEXACT'} "
        f"(tolerance {ledger.get('tolerance', TOLERANCE):.0%})",
    ]
    for p in ledger.get("problems") or []:
        lines.append(f"tpudist: memledger problem: {p}")
    for n in ledger.get("notes") or []:
        lines.append(f"tpudist: memledger note: {n}")
    return lines


def _atomic_write(path: str, payload: str) -> None:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        f.write(payload)
    os.replace(tmp, path)


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tpudist.obs.memledger",
        description="per-device HBM ledger + OOM forensics from run "
                    "artifacts (memledger.json, kind=memledger "
                    "records, flight records) — jax-free")
    p.add_argument("--run-dir", type=str, default=".",
                   help="directory holding memledger.json, "
                        "metrics.jsonl and/or flightrec.worker* dumps")
    p.add_argument("--out", type=str, default=None,
                   help=f"write the latest ledger back as JSON "
                        f"(default: <run-dir>/{LEDGER_NAME} only when "
                        f"absent — never clobbers the run's own "
                        f"artifact)")
    p.add_argument("--bench-out", type=str, default=None,
                   help="also write a BENCH_MEMORY-shaped artifact "
                        "(headline = headroom fraction)")
    p.add_argument("--prom-out", type=str, default=None,
                   help="also write tpudist_hbm_* gauges as a "
                        "Prometheus textfile-collector file")
    p.add_argument("--baseline", type=str, default=None,
                   help="a prior run's memledger.json: print the "
                        "per-bucket delta against it")
    p.add_argument("--drill", action="store_true",
                   help="first run the scripted OOM drill into "
                        "--run-dir (grows a bucket of the dir's REAL "
                        "ledger past headroom and dumps the flight "
                        "record an OOM death leaves), then run the "
                        "forensics over it")
    p.add_argument("--drill-grow", type=str, default="slabs",
                   choices=sorted(ATTRIBUTED),
                   help="which bucket the drill grows (default slabs)")
    args = p.parse_args(argv)

    if args.drill:
        run_drill(args.run_dir, grow=args.drill_grow)

    ledgers = collect_ledgers(args.run_dir)
    if not ledgers:
        print(f"tpudist.obs.memledger: no ledger evidence under "
              f"{args.run_dir} — the train/serve CLIs write "
              f"{LEDGER_NAME} and kind=memledger records",
              file=sys.stderr)
        return 2
    source, ledger = ledgers[-1]
    print(f"tpudist: memledger latest snapshot from {source} "
          f"({len(ledgers)} snapshot(s))")
    for line in _summary_lines(ledger):
        print(line)

    if args.baseline:
        try:
            with open(args.baseline) as f:
                baseline = json.load(f)
        except (OSError, ValueError):
            print(f"tpudist.obs.memledger: unreadable --baseline "
                  f"{args.baseline}", file=sys.stderr)
            return 2
        bb = baseline.get("buckets") or {}
        lb = ledger.get("buckets") or {}
        deltas = ", ".join(
            f"{k} {'+' if int(lb.get(k) or 0) >= int(bb.get(k) or 0) else '-'}"
            f"{_mib(abs(int(lb.get(k) or 0) - int(bb.get(k) or 0)))}"
            for k in BUCKETS)
        print(f"tpudist: memledger delta vs baseline: {deltas}")

    diag = diagnose(args.run_dir)
    for line in forensics_lines(diag):
        print(line)

    out = args.out
    if out is None:
        default = os.path.join(args.run_dir, LEDGER_NAME)
        out = default if not os.path.exists(default) else None
    if out:
        _atomic_write(out, json.dumps(ledger, indent=1))
    if args.bench_out:
        _atomic_write(args.bench_out,
                      json.dumps(bench_artifact(ledger), indent=1))
    if args.prom_out:
        _atomic_write(args.prom_out, prometheus_text(ledger))
    # the headroom grade is advisory (opt-in floor); a broken PARTITION
    # is a real failure — the whole point is exact accounting
    return 0 if ledger.get("exact") else 1


if __name__ == "__main__":
    sys.exit(main())
