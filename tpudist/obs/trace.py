"""Host-side span tracing: a merged Perfetto timeline of the pod.

PR 3's flight recorder says where a worker is stuck *right now* and
``kind=timing`` says how fast the run was *on average* — but neither
records *when* each host-side phase (staging H2D, superstep dispatch
fences, checkpoint enqueue/drain, tune trials) happened on each host, so
cross-host effects ("worker 3's checkpoint drain serialized behind
worker 0's staging") are invisible. This module closes that gap with a
low-overhead span tracer:

  * :class:`Tracer` — preallocated per-thread ring buffers of
    ``(name, cat, t0, t1, args)`` span tuples stamped with
    ``time.perf_counter_ns`` (monotonic; NTP cannot rewrite history).
    Recording a span is two clock reads plus one list-slot store —
    measured ~1 µs/span on CPU — and the ring bounds memory, so the
    tracer is ALWAYS ON by default (``--trace off`` / ``TPUDIST_TRACE=off``
    is the escape hatch, and the disabled path performs no clock reads
    at all — pinned in tests).
  * Chrome trace-event export (:meth:`Tracer.export_local`): one
    ``trace.worker<i>.json`` per process, loadable in Perfetto as-is.
    The stall watchdog exports it too, so even a HUNG run leaves its
    timeline behind.
  * pod merge (:func:`export_pod_trace`): per-host clock offsets from a
    barrier-bracketed probe (every host stamps its monotonic clock at
    the same barrier release and allgathers the stamps — the collective
    path the verdict chain already uses), then the coordinator folds
    every worker's spans into ONE ``pod_trace.json`` with one Perfetto
    track (pid) per host. Cross-host alignment error is bounded by
    barrier-release skew (~collective latency), far below the
    phase-length scales the timeline exists to explain.

``python -m tpudist.obs.report`` (:mod:`tpudist.obs.report`) turns the
merged trace plus ``metrics.jsonl`` into an offline run report.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

TRACE_SCHEMA_VERSION = 1

# Default ring capacity (spans per thread). A span tuple is ~100 B of
# host memory, so 65536 ≈ 6.5 MB/thread bounds the recorder while
# holding hours of fence-granular spans (the train loop records a few
# spans per dispatch group, not per step). Env: TPUDIST_TRACE_CAPACITY.
DEFAULT_CAPACITY = 65536

# Clock indirection: tests monkeypatch this to count reads and pin the
# "disabled tracer performs zero timed-window syscalls" contract.
_now_ns = time.perf_counter_ns


class _NullSpan:
    """The disabled path: a shared no-op context manager. No clock
    reads, no allocation — ``with span(...)`` costs one attribute call
    and one identity return."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class _ThreadBuf:
    """One thread's preallocated span ring + open-span stack."""

    __slots__ = ("ring", "capacity", "count", "tid", "thread_name", "open")

    def __init__(self, capacity: int, tid: int, thread_name: str):
        self.ring: List[Any] = [None] * capacity
        self.capacity = capacity
        self.count = 0          # total spans ever recorded (ring wraps)
        self.tid = tid          # small stable int for the export
        self.thread_name = thread_name
        self.open: List[str] = []   # names of currently-open spans

    def record(self, name: str, cat: str, t0: int, t1: int,
               args: Optional[Dict[str, Any]]) -> None:
        self.ring[self.count % self.capacity] = (name, cat, t0, t1, args)
        self.count += 1

    @property
    def dropped(self) -> int:
        return max(0, self.count - self.capacity)

    def spans(self) -> List[tuple]:
        """Chronological snapshot of the surviving (un-overwritten)
        spans."""
        n = min(self.count, self.capacity)
        lo = self.count - n
        return [self.ring[i % self.capacity] for i in range(lo, self.count)]


class _Span:
    """A single timed window; context-manager AND begin/end handle."""

    __slots__ = ("_buf", "name", "cat", "args", "t0")

    def __init__(self, buf: _ThreadBuf, name: str, cat: str,
                 args: Optional[Dict[str, Any]]):
        self._buf = buf
        self.name = name
        self.cat = cat
        self.args = args
        self.t0 = 0

    def __enter__(self) -> "_Span":
        self._buf.open.append(self.name)
        self.t0 = _now_ns()
        return self

    def __exit__(self, *exc) -> bool:
        t1 = _now_ns()
        buf = self._buf
        if buf.open and buf.open[-1] == self.name:
            buf.open.pop()
        buf.record(self.name, self.cat, self.t0, t1, self.args)
        return False


class Tracer:
    """The per-process span recorder.

    Thread-safe by construction: each thread records into its own ring
    (created on first span from that thread), and the registry of rings
    is the only shared state (guarded by a lock taken once per thread,
    never per span). ``enabled=False`` makes every recording entry point
    a constant-time no-op with no clock reads.
    """

    def __init__(self, *, enabled: bool = True,
                 capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError(f"trace capacity must be >= 1, got {capacity}")
        self.enabled = enabled
        self.capacity = capacity
        self._tls = threading.local()
        self._bufs: List[_ThreadBuf] = []
        self._lock = threading.Lock()
        self.exported = False      # run-end export happened (any form)
        # run identity stamped into every exported document's metadata
        # (run_id / requeue_attempt — the train CLI sets it once the
        # coordinator has broadcast the id), so a trace scp'd off a
        # dead pod names the attempt it came from
        self.run_info: Dict[str, Any] = {}
        # wall↔monotonic correspondence, sampled back-to-back: lets the
        # offline report align metrics.jsonl (wall ts + mono) with span
        # timestamps without trusting NTP for intervals
        self.wall_at_start = time.time()
        self.mono_ns_at_start = _now_ns()

    # ------------------------------------------------------- recording
    def _thread_buf(self) -> _ThreadBuf:
        buf = getattr(self._tls, "buf", None)
        if buf is None:
            t = threading.current_thread()
            with self._lock:
                buf = _ThreadBuf(self.capacity, len(self._bufs), t.name)
                self._bufs.append(buf)
            self._tls.buf = buf
        return buf

    def span(self, name: str, cat: str = "misc", **args: Any):
        """Context manager timing one window. ~1 µs/span enabled;
        a shared no-op (zero clock reads) when disabled."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self._thread_buf(), name, cat, args or None)

    def begin(self, name: str, cat: str = "misc", **args: Any):
        """Open a span; pair with :meth:`end`. For windows that cannot
        be a lexical ``with`` block (e.g. spanning loop iterations)."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self._thread_buf(), name, cat,
                     args or None).__enter__()

    def end(self, span) -> None:
        if span is not _NULL_SPAN:
            span.__exit__(None, None, None)

    def instant(self, name: str, cat: str = "misc", **args: Any) -> None:
        """Zero-duration marker (exports as a dur=0 slice)."""
        if not self.enabled:
            return
        t = _now_ns()
        self._thread_buf().record(name, cat, t, t, args or None)

    # ------------------------------------------------------ inspection
    @property
    def span_count(self) -> int:
        with self._lock:
            bufs = list(self._bufs)
        return sum(min(b.count, b.capacity) for b in bufs)

    @property
    def dropped(self) -> int:
        with self._lock:
            bufs = list(self._bufs)
        return sum(b.dropped for b in bufs)

    def tail(self, per_thread: int = 64) -> List[Dict[str, Any]]:
        """Last ``per_thread`` spans of every thread plus its open-span
        stack — the flight-record slice: *what phase was each thread in
        when the run hung*. Safe to call from the watchdog thread while
        the main thread records (a torn read costs at most one
        garbled span, never a crash)."""
        with self._lock:
            bufs = list(self._bufs)
        out = []
        for b in bufs:
            spans = [{"name": s[0], "cat": s[1],
                      "ts_us": s[2] / 1e3, "dur_us": (s[3] - s[2]) / 1e3,
                      **({"args": s[4]} if s[4] else {})}
                     for s in b.spans()[-per_thread:] if s is not None]
            out.append({"tid": b.tid, "thread": b.thread_name,
                        "open": list(b.open), "spans": spans,
                        "dropped": b.dropped})
        return out

    # ---------------------------------------------------------- export
    def events(self, *, process_index: int = 0) -> List[Dict[str, Any]]:
        """Surviving spans as Chrome trace-event complete ('X') events,
        ts/dur in microseconds on this process's monotonic clock."""
        with self._lock:
            bufs = list(self._bufs)
        out: List[Dict[str, Any]] = []
        for b in bufs:
            for s in b.spans():
                if s is None:
                    continue
                name, cat, t0, t1, args = s
                ev: Dict[str, Any] = {
                    "name": name, "cat": cat, "ph": "X",
                    "ts": t0 / 1e3, "dur": (t1 - t0) / 1e3,
                    "pid": process_index, "tid": b.tid}
                if args:
                    ev["args"] = args
                out.append(ev)
        out.sort(key=lambda e: e["ts"])
        return out

    def _thread_meta(self, process_index: int) -> List[Dict[str, Any]]:
        with self._lock:
            bufs = list(self._bufs)
        return [{"ph": "M", "name": "thread_name", "pid": process_index,
                 "tid": b.tid, "args": {"name": b.thread_name}}
                for b in bufs]

    def to_doc(self, *, process_index: int = 0) -> Dict[str, Any]:
        """One worker's full Chrome-trace JSON document."""
        events = ([{"ph": "M", "name": "process_name",
                    "pid": process_index,
                    "args": {"name": f"host{process_index}"}}]
                  + self._thread_meta(process_index)
                  + self.events(process_index=process_index))
        return {
            "displayTimeUnit": "ms",
            "traceEvents": events,
            "metadata": {
                "schema": TRACE_SCHEMA_VERSION,
                "process_index": process_index,
                "spans": self.span_count,
                "dropped": self.dropped,
                "clock_sync": {"wall_ts": self.wall_at_start,
                               "mono_us": self.mono_ns_at_start / 1e3},
                **self.run_info,
            },
        }

    def export_local(self, path: str, *, process_index: int = 0) -> str:
        """Write this worker's trace atomically; returns the path.
        Perfetto/chrome://tracing load it directly."""
        doc = self.to_doc(process_index=process_index)
        _atomic_write_json(path, doc)
        self.exported = True
        return path


# ------------------------------------------------------ module singleton

_TRACER: Optional[Tracer] = None
_TRACER_LOCK = threading.Lock()


def _env_enabled() -> bool:
    return os.environ.get("TPUDIST_TRACE", "on").lower() not in (
        "off", "0", "false", "no")


def _env_capacity() -> int:
    try:
        return max(1, int(os.environ.get("TPUDIST_TRACE_CAPACITY",
                                         DEFAULT_CAPACITY)))
    except ValueError:
        return DEFAULT_CAPACITY


def get() -> Tracer:
    """The process-wide tracer (created on first use; enabled unless
    ``TPUDIST_TRACE`` says otherwise)."""
    global _TRACER
    if _TRACER is None:
        with _TRACER_LOCK:
            if _TRACER is None:
                _TRACER = Tracer(enabled=_env_enabled(),
                                 capacity=_env_capacity())
    return _TRACER


def configure(*, enabled: Optional[bool] = None,
              capacity: Optional[int] = None) -> Tracer:
    """Install a FRESH process-wide tracer (the train CLI calls this at
    run start so back-to-back runs in one process never mix spans)."""
    global _TRACER
    with _TRACER_LOCK:
        _TRACER = Tracer(
            enabled=_env_enabled() if enabled is None else enabled,
            capacity=_env_capacity() if capacity is None else capacity)
    return _TRACER


def span(name: str, cat: str = "misc", **args: Any):
    """Module-level convenience: ``with trace.span("stage_slab",
    cat="staging"): ...`` against the process-wide tracer."""
    return get().span(name, cat, **args)


def instant(name: str, cat: str = "misc", **args: Any) -> None:
    get().instant(name, cat, **args)


def enabled() -> bool:
    return get().enabled


# --------------------------------------------------- pod merge + export


def worker_trace_name(process_index: int) -> str:
    return f"trace.worker{process_index}.json"


POD_TRACE_NAME = "pod_trace.json"


def estimate_clock_offsets(process_count: int,
                           rounds: int = 2) -> List[int]:
    """Per-host monotonic-clock offsets (ns) relative to host 0.

    Barrier-bracketed probe: every host stamps ``perf_counter_ns``
    immediately after the same barrier release, then allgathers the
    stamps — at that instant true time is equal across hosts to within
    barrier-release skew, so ``stamp_i - stamp_0`` IS host i's clock
    offset. Averaged over ``rounds`` barriers to shave skew noise.
    Single-process: ``[0]`` with no collective.
    """
    if process_count <= 1:
        return [0]
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import multihost_utils

    sums = np.zeros(process_count, np.int64)
    for r in range(rounds):
        multihost_utils.sync_global_devices(f"tpudist_trace_clock_{r}")
        stamp = _now_ns()
        # ship the stamp as (seconds, nanos) int32 pairs: without x64
        # mode jax silently downgrades int64/float64 payloads, and a
        # float32 perf_counter_ns has ~2 ms granularity — worse than
        # the barrier skew this probe exists to beat
        pair = jnp.asarray([stamp // 1_000_000_000,
                            stamp % 1_000_000_000], jnp.int32)
        rows = np.asarray(multihost_utils.process_allgather(pair),
                          np.int64).reshape(process_count, 2)
        stamps = rows[:, 0] * 1_000_000_000 + rows[:, 1]
        sums += stamps - stamps[0]
    return [int(round(s / rounds)) for s in sums]


def _allgather_bytes(payload: bytes, process_count: int) -> List[bytes]:
    """Every worker's ``payload`` on every worker (variable-length:
    lengths gather first, then zero-padded uint8 rows)."""
    if process_count <= 1:
        return [payload]
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import multihost_utils

    data = np.frombuffer(payload, np.uint8)
    lens = np.asarray(multihost_utils.process_allgather(
        jnp.asarray([len(data)], jnp.int32))).reshape(-1)
    maxlen = int(lens.max())
    padded = np.zeros(maxlen, np.uint8)
    padded[:len(data)] = data
    rows = np.asarray(multihost_utils.process_allgather(
        jnp.asarray(padded))).reshape(process_count, maxlen)
    return [rows[i, :int(lens[i])].tobytes()
            for i in range(process_count)]


def merge_traces(worker_docs: Sequence[Dict[str, Any]],
                 offsets_ns: Sequence[int]) -> Dict[str, Any]:
    """Fold per-worker trace docs into one Perfetto-loadable document:
    worker ``i``'s track is pid ``i`` (named ``host<i>``), and every
    event timestamp shifts by ``-offsets_ns[i]`` onto host 0's
    monotonic timeline. Pure function — the deterministic-merge tests
    feed it scripted offsets."""
    events: List[Dict[str, Any]] = []
    clock_sync = {}
    spans = dropped = device_tracks = counter_events = 0
    for i, doc in enumerate(worker_docs):
        off_us = offsets_ns[i] / 1e3 if i < len(offsets_ns) else 0.0
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = i
            if "ts" in ev:
                ev["ts"] = ev["ts"] - off_us
            events.append(ev)
        meta = doc.get("metadata", {})
        spans += int(meta.get("spans", 0))
        dropped += int(meta.get("dropped", 0))
        device_tracks += int(meta.get("device_tracks", 0))
        counter_events += int(meta.get("counter_events", 0))
        clock_sync[str(i)] = meta.get("clock_sync")
    events.sort(key=lambda e: (e.get("ts", -1.0)))
    metadata = {
        "schema": TRACE_SCHEMA_VERSION,
        "hosts": len(worker_docs),
        "clock_offsets_ns": [int(o) for o in offsets_ns],
        "clock_sync": clock_sync,
        "spans": spans,
        "dropped": dropped,
        "device_tracks": device_tracks,
        "counter_events": counter_events,
    }
    # run identity: every worker stamped the same broadcast id; the
    # first doc that carries one names the merged artifact too
    for key in ("run_id", "requeue_attempt"):
        for doc in worker_docs:
            v = doc.get("metadata", {}).get(key)
            if v is not None:
                metadata[key] = v
                break
    return {
        "displayTimeUnit": "ms",
        "traceEvents": events,
        "metadata": metadata,
    }


def export_pod_trace(out_dir: str, *, process_index: int = 0,
                     process_count: int = 1,
                     tracer: Optional[Tracer] = None,
                     extra_events: Optional[List[Dict[str, Any]]] = None
                     ) -> Dict[str, Any]:
    """Run-end export: write this worker's ``trace.worker<i>.json``,
    probe clock offsets, gather every worker's spans, and (coordinator
    only) write the merged ``pod_trace.json``.

    ``extra_events`` are pre-built Chrome events appended to this
    worker's document before the gather — the device-timeline tracks
    from a ``--profile-window`` capture (obs.devtime) ride the same
    gather/merge/clock-shift path as the host spans, so they land
    under this host's row in ``pod_trace.json``. Their timestamps must
    already be on this host's monotonic (``perf_counter``) timebase.

    CONTAINS COLLECTIVES on multi-host runs — call it only at a point
    every process reaches (the success path after the epoch loop; a
    dying run falls back to the watchdog's local-only export). Returns
    a summary dict for the ``kind=timing`` record.
    """
    tracer = get() if tracer is None else tracer
    local_path = os.path.join(out_dir, worker_trace_name(process_index))
    # ONE document snapshot serves both the local file and the gather:
    # building it twice would walk/sort the rings twice and let spans
    # recorded in between make the two copies disagree
    doc = tracer.to_doc(process_index=process_index)
    if extra_events:
        doc["traceEvents"].extend(extra_events)
        doc["metadata"]["device_tracks"] = sum(
            1 for e in extra_events if e.get("ph") == "M")
        # ph="C" counter samples (the serve lane's KV-pool occupancy
        # track): counted in metadata so consumers can assert the
        # track's presence without scanning the event stream
        doc["metadata"]["counter_events"] = sum(
            1 for e in extra_events if e.get("ph") == "C")
    _atomic_write_json(local_path, doc)
    tracer.exported = True
    offsets = estimate_clock_offsets(process_count)
    payloads = _allgather_bytes(
        json.dumps(doc, default=str).encode(), process_count)
    merged_path = None
    if process_index == 0:
        docs = [json.loads(p) for p in payloads]
        merged = merge_traces(docs, offsets)
        merged_path = os.path.join(out_dir, POD_TRACE_NAME)
        _atomic_write_json(merged_path, merged)
    return {
        "spans": tracer.span_count,
        "dropped": tracer.dropped,
        "hosts": process_count,
        "clock_offsets_ns": offsets,
        "local_path": local_path,
        "merged_path": merged_path,
    }


def _atomic_write_json(path: str, doc: Dict[str, Any]) -> None:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, default=str)
    os.replace(tmp, path)
