"""Goodput ledger: cross-attempt wall-clock accounting for preempted runs.

The framework *survives* preemption (tpudist.elastic's requeue loop) and
*observes* single attempts in depth (flight recorder, tracer, devtime,
live bus) — but nothing answered the question an operator of
preemptible capacity actually asks: of the total wall-clock this run
consumed across ALL its requeue attempts, what fraction was productive
training?  This module is that answer: a ledger that ingests every
attempt of one ``run_id`` and partitions the run's total wall into
mutually exclusive buckets:

  * ``productive``       — steady-state step time that survived (kept
    steps; the goodput numerator);
  * ``compile``          — attempt 0's trace+compile warmup
    (``compile_warmup_s``);
  * ``rewarmup``         — the SAME cost paid AGAIN by requeued
    attempts (re-trace/re-compile after resume);
  * ``staging_exposed``  — H2D waits the staging pipeline failed to
    hide (``stage_wait_s``; they sit inside the timed windows, so they
    are carved OUT of productive);
  * ``ckpt``             — checkpoint enqueue cost on the step path
    plus drain stalls at wait/close;
  * ``eval``             — per-epoch held-out eval forwards;
  * ``lost``             — step time a kill threw away: steps computed
    AFTER the last committed checkpoint of a killed attempt, recovered
    from the dead attempt's heartbeat beacon vs the next attempt's
    ``kind=resume`` record;
  * ``startup``          — process spawn + imports + distributed/model
    init, from the attempt's launcher start stamp to its first metrics
    record;
  * ``off_pod``          — time with NO attempt running at all: requeue
    backoff + re-provisioning, from consecutive ``attempts.jsonl``
    deltas;
  * ``residue``          — the honest remainder (what a dead attempt
    never got to report, run-end export/verdict tails).

The partition is EXACT by the same discipline as the devtime
decomposition (PR 6): every attempt's buckets sum to that attempt's
wall because ``residue`` is defined as the remainder — and the ledger
FLAGS (``exact=False``) any attempt whose *measured* buckets exceed its
wall by more than the pinned :data:`TOLERANCE` (double counting), any
overlapping attempt stamps, and any global drift.  Dead attempts are
accounted from what actually survived the kill: the flushed
step/ckpt records (rate + progress), the final heartbeat beacon
(how far training really got), and the resuming attempt's ``kind=
resume`` record (what was committed) — everything unmeasurable lands
in ``residue``, never in a guessed bucket.

Inputs (all of them artifacts the framework already writes):

  * ``attempts.jsonl`` — NEW, launcher-written (launch_tpu.sh appends
    one record per workload invocation: attempt index, start/end
    epoch-seconds, rc, the requeue policy's verdict); also written by
    the scripted drill below;
  * ``metrics.jsonl``  — every record carries ``requeue_attempt``
    (stamped since the live-telemetry PR), so one file holds all
    attempts and splits cleanly;
  * heartbeat beacons  — ``heartbeat.worker<i>`` (current attempt) and
    ``heartbeat.worker<i>.attempt<K>`` (archived by the NEXT attempt's
    flight recorder — obs.heartbeat), the dead attempts' last progress
    counters;
  * ``alerts.jsonl`` / ``kind=resume`` records ride along in the same
    metrics stream.

jax-free by design (the offline-tooling contract shared with
:mod:`tpudist.obs.report`): the CLI runs on the CI host or a laptop
against scp'd artifacts.  The scripted ``--drill`` runs the real train
CLI in subprocesses (kill → requeue-policy → resume), writes
``attempts.jsonl`` exactly as the launcher would, and produces
``BENCH_GOODPUT.json`` — the acceptance artifact CI uploads.

CLI::

    python -m tpudist.obs.goodput --run-dir DIR \
        [--bench-out BENCH_GOODPUT.json] [--prom-out goodput.prom]
    python -m tpudist.obs.goodput --drill --run-dir DIR ...
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from tpudist import rules as rules_lib

GOODPUT_SCHEMA_VERSION = 1
ATTEMPTS_NAME = "attempts.jsonl"
LEDGER_NAME = "goodput.json"

# Partition exactness: the pinned tolerance (fraction of the wall being
# partitioned) past which the ledger flags itself inexact — the same
# ±1% discipline the devtime decomposition pins (compute + exposed_comm
# + idle == window).
TOLERANCE = 0.01

SUCCESS = "success"     # mirrors tpudist.verdict vocabulary without the
FAIL = "fail"           # import (same pattern as obs.alerts)
UNGATEABLE = "ungateable"

# The goodput floor lives in tpudist.rules with every other gate
# (TPUDIST_GOODPUT_MIN, resolved at call time); the alias is this
# module's documented surface, like verdict's.
GOODPUT_MIN = rules_lib.GOODPUT_MIN

# Cross-attempt bucket names, display order. Per-attempt rows carry all
# but ``off_pod`` (time between attempts belongs to no attempt).
BUCKETS = ("productive", "compile", "rewarmup", "staging_exposed",
           "ckpt", "eval", "lost", "startup", "off_pod", "residue")
ATTEMPT_BUCKETS = tuple(b for b in BUCKETS if b != "off_pod")


def goodput_status(fraction: Optional[float],
                   min_fraction: Optional[float] = None) -> str:
    """Three-valued goodput verdict: UNGATEABLE with nothing measured
    (an empty ledger must not read as a goodput pass), else
    SUCCESS/FAIL by whether the productive fraction clears
    ``TPUDIST_GOODPUT_MIN``. Advisory, like the comm/staging gates — a
    run that completed with bad goodput is a capacity-efficiency
    finding, not a correctness failure."""
    if fraction is None:
        return UNGATEABLE
    if min_fraction is None:
        min_fraction = rules_lib.resolve("goodput")
    return SUCCESS if fraction >= min_fraction else FAIL


# ------------------------------------------------------------- ingestion


def load_jsonl(path: str) -> List[Dict[str, Any]]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue    # a torn tail line is not evidence
            if isinstance(rec, dict):
                out.append(rec)
    return out


def load_attempts(path: str) -> List[Dict[str, Any]]:
    """The launcher's per-invocation records, sorted by attempt index.
    Only parseable records with both timestamps count — the ledger's
    spine must be well-formed or absent, never guessed."""
    recs = [r for r in load_jsonl(path)
            if isinstance(r.get("start_ts"), (int, float))
            and isinstance(r.get("end_ts"), (int, float))]
    return sorted(recs, key=lambda r: int(r.get("attempt", 0)))


def find_metrics(run_dir: str) -> List[str]:
    """Every metrics.jsonl under the run directory: the top-level one
    (records self-identify by ``requeue_attempt``, so one appended file
    holds every attempt) plus per-attempt collection subdirs
    (``attempt<N>/metrics.jsonl``, the launcher's failure-path
    layout)."""
    paths = set(glob.glob(os.path.join(run_dir, "metrics.jsonl")))
    paths |= set(glob.glob(os.path.join(run_dir, "*", "metrics.jsonl")))
    return sorted(paths)


def find_beacons(run_dir: str) -> Dict[int, Dict[int, Dict[str, Any]]]:
    """``{attempt: {worker: beacon payload}}`` from every heartbeat
    file under the run dir (recursively — collection may nest
    per-attempt subdirs). The attempt comes from the payload's own
    ``requeue_attempt`` stamp (the archived ``.attempt<K>`` filename
    suffix is a fallback for beacons too old to carry it); duplicate
    (attempt, worker) pairs keep the furthest-progressed payload."""
    out: Dict[int, Dict[int, Dict[str, Any]]] = {}
    pattern = os.path.join(run_dir, "**", "heartbeat.worker*")
    for path in sorted(set(glob.glob(pattern, recursive=True))):
        tail = os.path.basename(path).rsplit(".worker", 1)[-1]
        suffix_attempt = None
        if "." in tail:
            tail, _, suffix = tail.partition(".")
            if suffix.startswith("attempt") and suffix[7:].isdigit():
                suffix_attempt = int(suffix[7:])
            else:
                continue        # .tmp or foreign suffix: not a beacon
        if not tail.isdigit():
            continue
        worker = int(tail)
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            continue
        att = payload.get("requeue_attempt")
        att = int(att) if isinstance(att, (int, float)) else suffix_attempt
        if att is None:
            att = 0
        cur = out.setdefault(att, {}).get(worker)
        if cur is None or _progress_key(payload) > _progress_key(cur):
            out[att][worker] = payload
    return out


def _progress_key(payload: Dict[str, Any]) -> Tuple[int, int]:
    """Beacon ordering: (epoch, step_in_epoch) LEXICOGRAPHIC — a stale
    epoch-0/step-7 beacon must never beat a fresher epoch-1/step-2 one
    (step resets every epoch)."""
    epoch = payload.get("epoch")
    step = payload.get("step")
    return (int(epoch) if isinstance(epoch, (int, float)) else -1,
            int(step) if isinstance(step, (int, float)) else -1)


# ------------------------------------------------- per-attempt buckets


def _kind(recs: List[Dict[str, Any]], kind: str) -> List[Dict[str, Any]]:
    return [r for r in recs if r.get("kind") == kind]


def _ckpt_seconds(recs: List[Dict[str, Any]]) -> float:
    """Checkpoint cost: per-save enqueue (what the step path paid,
    ``kind=ckpt``) plus the run-total drain stall (``kind=ckpt_drain``
    — the honest enqueue/drain split from the checkpointing work)."""
    s = sum(float(r.get("enqueue_ms") or 0.0) for r in _kind(recs, "ckpt"))
    drains = _kind(recs, "ckpt_drain")
    if drains:
        s += float(drains[-1].get("drain_ms") or 0.0)
    return s / 1e3


def _eval_seconds(recs: List[Dict[str, Any]]) -> float:
    return sum(float(r.get("eval_s") or 0.0) for r in _kind(recs, "epoch"))


def attempt_record(history: Sequence[Dict[str, Any]], *,
                   wall_s: float, requeue_attempt: int = 0
                   ) -> Optional[Dict[str, Any]]:
    """The ATTEMPT-local goodput estimate the train loop logs at run
    end (``kind=goodput``): the same bucket math the cross-attempt
    ledger applies to a completed attempt, over this process's own
    record history and wall clock. The live aggregator observes its
    ``fraction`` against the goodput rule, so a badput-heavy run alerts
    mid-fleet — the offline ledger then refines it with startup/off-pod
    time only the launcher can see. None when nothing was measured."""
    timings = [r for r in history if r.get("kind") == "timing"]
    if not timings or wall_s <= 0:
        return None
    t = timings[-1]
    warm = float(t.get("compile_warmup_s") or 0.0)
    wait = float(t.get("stage_wait_s") or 0.0)
    productive = max(0.0, float(t.get("run_s") or 0.0) - wait)
    buckets = {
        ("compile" if requeue_attempt == 0 else "rewarmup"): warm,
        "staging_exposed": wait,
        "productive": productive,
        "ckpt": _ckpt_seconds(list(history)),
        "eval": _eval_seconds(list(history)),
    }
    frac = round(productive / wall_s, 6)
    return {"fraction": frac, "status": goodput_status(frac),
            "wall_s": round(wall_s, 6),
            "requeue_attempt": requeue_attempt,
            **{f"{k}_s": round(v, 6) for k, v in buckets.items()}}


def _completed_into(buckets: Dict[str, float], recs, timing,
                    first_attempt: bool) -> Dict[str, Any]:
    warm = float(timing.get("compile_warmup_s") or 0.0)
    buckets["compile" if first_attempt else "rewarmup"] += warm
    wait = float(timing.get("stage_wait_s") or 0.0)
    buckets["staging_exposed"] += wait
    run_s = float(timing.get("run_s") or 0.0)
    buckets["productive"] += max(0.0, run_s - wait)
    steps = timing.get("steps")
    sps = (steps / run_s) if steps and run_s > 0 else None
    return {"steps_done": steps, "lost_steps": 0,
            "steps_per_sec": round(sps, 4) if sps else None}


def _beacon_progress(beacons: Optional[Dict[int, Dict[str, Any]]]
                     ) -> Tuple[Optional[int], Optional[int]]:
    """(step_in_epoch, epoch) of the furthest-progressed worker beacon
    for one attempt — how far the attempt REALLY got before dying.
    Ordered by (epoch, step): step resets per epoch, so a straggler's
    epoch-0/step-7 beacon must not outrank a peer's epoch-1/step-2."""
    best = None
    for payload in (beacons or {}).values():
        step = payload.get("step")
        if not isinstance(step, (int, float)) or step < 0:
            continue
        if best is None or _progress_key(payload) > _progress_key(best):
            best = payload
    if best is None:
        return None, None
    return int(best["step"]), best.get("epoch")


def _dead_into(buckets: Dict[str, float], recs, *, first_ts,
               next_resume, beacons, first_attempt: bool,
               wall: float = float("inf")) -> Dict[str, Any]:
    """Bucket a KILLED attempt from what survived: flushed step/ckpt
    records give the rate and committed progress, the final beacon the
    true progress, the resuming attempt's record what was kept.
    Unmeasurable remainder (the kill's whole point) stays residue."""
    steps = _kind(recs, "step")
    ckpts = _kind(recs, "ckpt")
    resumes = [r for r in _kind(recs, "resume")
               if r.get("status") == SUCCESS]
    sps = None
    for r in reversed(steps):
        v = r.get("steps_per_sec")
        if isinstance(v, (int, float)) and v > 0:
            sps = float(v)
            break
    g0 = int(resumes[-1].get("resumed_from_step") or 0) if resumes else 0
    b_step, b_epoch = _beacon_progress(beacons)
    # final global step: the last flushed record's global step, extended
    # by the beacon's in-epoch progress when both sit in the same epoch
    g1 = None
    if ckpts:
        base = ckpts[-1]
        g1 = int(base.get("step") or 0)
        if b_step is not None and b_epoch == base.get("epoch"):
            g1 += max(0, b_step - int(base.get("step_in_epoch") or 0))
    elif steps:
        g1 = int(steps[-1].get("step") or 0)
        if b_step is not None and b_epoch == 0 and g0 == 0:
            g1 = max(g1, b_step)    # fresh epoch-0 run: global == in-epoch
    elif b_step is not None and b_epoch == 0 and g0 == 0:
        g1 = b_step
    steps_done = max(0, g1 - g0) if g1 is not None else None

    # lost steps: the resuming attempt's own accounting first (it read
    # the SAME beacon at restore time), the beacon-vs-resume-point diff
    # as the independent cross-check the acceptance drill pins
    lost_beacon = None
    if next_resume is not None and b_step is not None \
            and next_resume.get("epoch") == b_epoch:
        lost_beacon = max(0, b_step - int(
            next_resume.get("step_in_epoch") or 0))
    if next_resume is not None and next_resume.get("status") == SUCCESS:
        lost = next_resume.get("steps_lost")
        lost = int(lost) if isinstance(lost, (int, float)) else lost_beacon
    else:
        # no successful restore: EVERYTHING this attempt computed was
        # thrown away (a fresh start redoes it all)
        lost = steps_done
    lost = int(lost or 0)
    if steps_done is not None:
        lost = min(lost, steps_done)
    if sps:
        buckets["lost"] += lost / sps
        kept = max(0, (steps_done if steps_done is not None else lost)
                   - lost)
        buckets["productive"] += kept / sps
        if steps and first_ts is not None:
            # compile estimate: the gap from the first metrics record to
            # the first logged step, minus the step time that interval
            # covered — the trace+compile cost a dead attempt's missing
            # timing record never reported
            t1 = steps[0].get("ts")
            n1 = max(0, int(steps[0].get("step") or 0) - g0)
            if isinstance(t1, (int, float)):
                est = (float(t1) - first_ts) - n1 / sps
                # the estimate is a timestamp inference, and inferring
                # MORE than the attempt's unaccounted wall is by
                # definition overcounting — clamp to the remaining
                # headroom, so estimator noise on a dead attempt cannot
                # flag the partition inexact (measured buckets keep
                # their own double-counting check)
                est = min(max(0.0, est),
                          max(0.0, wall - sum(buckets.values())))
                buckets["compile" if first_attempt
                        else "rewarmup"] += est
    return {"steps_done": steps_done, "lost_steps": lost,
            "lost_steps_beacon": lost_beacon,
            "beacon_step": b_step,
            "steps_per_sec": round(sps, 4) if sps else None}


# ------------------------------------------------------------ the ledger


def build_ledger(attempts: List[Dict[str, Any]],
                 records: List[Dict[str, Any]], *,
                 beacons: Optional[Dict[int, Dict[int, Dict]]] = None,
                 tolerance: float = TOLERANCE,
                 run_id: Optional[str] = None) -> Dict[str, Any]:
    """Partition the run's total wall-clock (first attempt start →
    last attempt end, from ``attempts.jsonl``) into the goodput
    buckets. The sum of all buckets equals the total EXACTLY by
    construction (residue is the remainder); ``exact`` certifies the
    measured buckets never exceeded any attempt's wall (no double
    counting) and the attempt stamps never overlapped, within the
    pinned tolerance."""
    attempts = [dict(a) for a in attempts]
    if not attempts:
        raise ValueError("no attempt records — attempts.jsonl is the "
                         "ledger's spine (the launcher and the drill "
                         "both write it)")
    if run_id is None:
        # the NEWEST stamped launch is the run being accounted: a retry
        # from the same artifacts dir appends a fresh run_id, and stale
        # runs' evidence must not fold into this ledger
        run_id = next((a.get("run_id") for a in reversed(attempts)
                       if a.get("run_id")), None) \
            or next((r.get("run_id") for r in reversed(records)
                     if r.get("run_id")), None)

    def _ours(rec: Dict[str, Any]) -> bool:
        # unstamped evidence stays (scripted/old artifacts); a DIFFERENT
        # run_id is another launch's leftovers
        rid = rec.get("run_id")
        return run_id is None or not rid or rid == run_id

    attempts = sorted((a for a in attempts if _ours(a)),
                      key=lambda a: int(a.get("attempt", 0)))
    if not attempts:
        raise ValueError(f"no attempt records for run_id {run_id!r}")
    beacons = {att: {w: p for w, p in workers.items() if _ours(p)}
               for att, workers in (beacons or {}).items()}
    by_att: Dict[int, List[Dict[str, Any]]] = {}
    for r in records:
        if not _ours(r):
            continue
        a = r.get("requeue_attempt")
        by_att.setdefault(int(a) if isinstance(a, (int, float)) else 0,
                          []).append(r)
    for recs in by_att.values():
        recs.sort(key=lambda r: r.get("ts") or 0)

    t0 = float(attempts[0]["start_ts"])
    t1 = float(attempts[-1]["end_ts"])
    total_wall = max(0.0, t1 - t0)
    scale = max(total_wall, 1e-9)
    totals = {k: 0.0 for k in BUCKETS}
    rows: List[Dict[str, Any]] = []
    exact = True
    problems: List[str] = []
    prev_end: Optional[float] = None

    for i, a in enumerate(attempts):
        att = int(a.get("attempt", i))
        start, end = float(a["start_ts"]), float(a["end_ts"])
        wall = max(0.0, end - start)
        if prev_end is not None:
            gap = start - prev_end
            if gap < -tolerance * scale:
                exact = False
                problems.append(f"attempt {att} overlaps the previous "
                                f"attempt by {-gap:.3f}s")
            totals["off_pod"] += max(0.0, gap)
        prev_end = end

        recs = by_att.get(att, [])
        buckets = {k: 0.0 for k in ATTEMPT_BUCKETS}
        first_ts = None
        ts_vals = [float(r["ts"]) for r in recs
                   if isinstance(r.get("ts"), (int, float))]
        if ts_vals:
            first_ts = min(ts_vals)
            buckets["startup"] = min(max(0.0, first_ts - start), wall)
        timings = _kind(recs, "timing")
        next_resumes = [r for r in by_att.get(att + 1, [])
                        if r.get("kind") == "resume"]
        # measured ckpt/eval land FIRST so the dead-attempt estimator
        # sees the true remaining headroom when it clamps
        buckets["ckpt"] += _ckpt_seconds(recs)
        buckets["eval"] += _eval_seconds(recs)
        info: Dict[str, Any] = {}
        if timings:
            info = _completed_into(buckets, recs, timings[-1],
                                   first_attempt=(i == 0))
        else:
            info = _dead_into(
                buckets, recs, first_ts=first_ts,
                next_resume=next_resumes[-1] if next_resumes else None,
                beacons=beacons.get(att), first_attempt=(i == 0),
                wall=wall)
        measured = sum(v for k, v in buckets.items() if k != "residue")
        buckets["residue"] = wall - measured
        if buckets["residue"] < -tolerance * max(wall, 1e-9):
            exact = False
            problems.append(
                f"attempt {att}: measured buckets exceed its "
                f"{wall:.3f}s wall by {-buckets['residue']:.3f}s — "
                f"double counting")
        for k, v in buckets.items():
            totals[k] += v
        rows.append({
            "attempt": att, "start_ts": start, "end_ts": end,
            "wall_s": round(wall, 6), "rc": a.get("rc"),
            "verdict": a.get("verdict"), "records": len(recs),
            "buckets": {k: round(v, 6) for k, v in buckets.items()},
            **info})

    drift = abs(sum(totals.values()) - total_wall)
    if drift > tolerance * scale:
        exact = False
        problems.append(f"bucket sum drifts {drift:.3f}s from the "
                        f"{total_wall:.3f}s total wall")
    lost_steps = sum(int(r.get("lost_steps") or 0) for r in rows)
    frac = (round(totals["productive"] / total_wall, 6)
            if total_wall > 0 else None)
    return {
        "schema": GOODPUT_SCHEMA_VERSION,
        "run_id": run_id,
        "attempts": rows,
        "totals": {k: round(v, 6) for k, v in totals.items()},
        "total_wall_s": round(total_wall, 6),
        "goodput_fraction": frac,
        "goodput_status": goodput_status(frac),
        "goodput_min": rules_lib.resolve("goodput"),
        "lost_steps": lost_steps,
        "exact": exact,
        "tolerance": tolerance,
        "problems": problems,
    }


def build_from_dir(run_dir: str, *,
                   attempts_path: Optional[str] = None,
                   tolerance: float = TOLERANCE
                   ) -> Optional[Dict[str, Any]]:
    """Discover a run directory's artifacts (attempts.jsonl, every
    metrics.jsonl, all beacon generations) and build the ledger; None
    when there is no attempts.jsonl to anchor wall-clock to."""
    path = attempts_path or os.path.join(run_dir, ATTEMPTS_NAME)
    if not os.path.exists(path):
        return None
    attempts = load_attempts(path)
    if not attempts:
        return None
    records: List[Dict[str, Any]] = []
    for mp in find_metrics(run_dir):
        records.extend(load_jsonl(mp))
    return build_ledger(attempts, records, beacons=find_beacons(run_dir),
                        tolerance=tolerance)


# --------------------------------------------------- prometheus textfile


_PROM_HELP = {
    "tpudist_goodput_info": "Ledger identity (labels carry run_id and "
                            "attempt count).",
    "tpudist_goodput_fraction": "Productive training fraction of the "
                                "cross-attempt wall clock.",
    "tpudist_goodput_total_wall_seconds": "Total wall from first "
                                          "attempt start to last "
                                          "attempt end.",
    "tpudist_goodput_bucket_seconds": "Wall seconds per badput bucket "
                                      "(the partition sums to total).",
    "tpudist_goodput_lost_steps": "Steps recomputed after preemption "
                                  "kills (beacon vs resume point).",
    "tpudist_goodput_exact": "1 when the partition met the pinned "
                             "tolerance.",
}


def prometheus_text(ledger: Dict[str, Any]) -> str:
    """The ledger as Prometheus text exposition (0.0.4) — the textfile-
    collector shape for CI/dashboards, rendered with the SAME escaping
    and number formatting as the live exporter so the two tpudist_*
    families read identically. Pure function, golden-tested; the value
    of ``tpudist_goodput_fraction`` is byte-identical to the ledger's
    (the consumer-parity pin)."""
    from tpudist.obs.live import _prom_escape, _prom_num
    out: List[str] = []

    def metric(name, samples, mtype="gauge"):
        rows = [(lbl, v) for lbl, v in samples if v is not None]
        if not rows:
            return
        out.append(f"# HELP {name} {_PROM_HELP[name]}")
        out.append(f"# TYPE {name} {mtype}")
        for lbl, v in rows:
            label_s = ",".join(f'{k}="{_prom_escape(x)}"'
                               for k, x in lbl.items())
            out.append(f"{name}{{{label_s}}} {_prom_num(v)}"
                       if label_s else f"{name} {_prom_num(v)}")

    metric("tpudist_goodput_info",
           [({"run_id": ledger.get("run_id") or "",
              "attempts": str(len(ledger.get("attempts", [])))}, 1)])
    metric("tpudist_goodput_fraction",
           [({}, ledger.get("goodput_fraction"))])
    metric("tpudist_goodput_total_wall_seconds",
           [({}, ledger.get("total_wall_s"))])
    metric("tpudist_goodput_bucket_seconds",
           [({"bucket": k}, (ledger.get("totals") or {}).get(k))
            for k in BUCKETS])
    metric("tpudist_goodput_lost_steps",
           [({}, ledger.get("lost_steps"))])
    metric("tpudist_goodput_exact",
           [({}, 1 if ledger.get("exact") else 0)])
    return "\n".join(out) + "\n"


def bench_artifact(ledger: Dict[str, Any]) -> Dict[str, Any]:
    """BENCH_GOODPUT.json on the shared BENCH_* harness shape: the
    headline value is the goodput fraction, the detail is the full
    ledger."""
    return {
        "metric": "goodput_fraction",
        "value": ledger.get("goodput_fraction"),
        "unit": "productive wall / total wall across requeue attempts",
        "detail": ledger,
    }


def append_attempt(path: str, *, attempt: int, start_ts: float,
                   end_ts: float, rc: int, verdict: str,
                   run_id: Optional[str] = None,
                   mode: str = "train") -> None:
    """One attempts.jsonl record — the same shape launch_tpu.sh's
    ``append_attempt`` shell function writes, so drill- and
    launcher-produced ledgers are interchangeable."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    rec = {"kind": "attempt", "run_id": run_id, "mode": mode,
           "attempt": int(attempt), "start_ts": start_ts,
           "end_ts": end_ts, "rc": int(rc), "verdict": verdict}
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")


# ----------------------------------------------------------- the drill


# Same workload shape as the elastic acceptance drills
# (tests/test_elastic.py): 8 steps/epoch, a sharded-manifest save every
# 3 steps, per-step dispatch (log_every 2 and ckpt_every 3 share no
# divisor > 1), kill at step 5 — so the committed step is 3 and the
# beacon-recorded progress 5: exactly 2 steps lost, deterministically.
DRILL_FLAGS = ["--epochs", "1", "--train-batch-size", "8",
               "--n-samples", "64", "--log-every", "2", "--lr", "1e-2",
               "--seed", "3", "--ckpt-mode", "sharded", "--ckpt-sync",
               "--ckpt-every-steps", "3"]
DRILL_KILL = "0:5"
DRILL_RUN_ID = "goodput-drill"


def run_drill(run_dir: str, *, python: Optional[str] = None,
              backoff_base_s: float = 0.2,
              timeout_s: float = 600.0) -> List[Dict[str, Any]]:
    """The scripted kill→requeue→resume drill: run the REAL train CLI
    twice in subprocesses (attempt 0 dies to a scripted preemption at
    step 5 after the step-3 manifest committed; the requeue policy
    classifies it; attempt 1 runs ``--resume auto``), writing
    ``attempts.jsonl`` around each invocation exactly as the launcher
    does. Returns the attempt records. The subprocesses need jax; this
    process stays jax-free."""
    import subprocess

    from tpudist.elastic import policy

    os.makedirs(run_dir, exist_ok=True)
    attempts_path = os.path.join(run_dir, ATTEMPTS_NAME)
    if os.path.exists(attempts_path):
        os.remove(attempts_path)    # a re-run starts a fresh ledger
    python = python or sys.executable

    def run_attempt(extra_flags, env_extra):
        env = dict(os.environ)
        env.setdefault("TPUDIST_PLATFORM", "cpu")
        env["TPUDIST_RUN_ID"] = DRILL_RUN_ID
        env.update(env_extra)
        start = time.time()
        proc = subprocess.run(
            [python, "-m", "tpudist.train", "--save-dir", run_dir,
             *DRILL_FLAGS, *extra_flags],
            env=env, capture_output=True, text=True, timeout=timeout_s)
        return proc, start, time.time()

    p0, s0, e0 = run_attempt([], {"TPUDIST_TEST_KILL": DRILL_KILL})
    if p0.returncode != 113:
        raise RuntimeError(
            f"drill attempt 0 exited {p0.returncode}, expected the "
            f"scripted kill's 113:\n{p0.stdout}\n{p0.stderr}")
    decision = policy.decide(p0.returncode, attempt=0, max_requeues=2,
                             flightrec_dir=run_dir,
                             base_s=backoff_base_s)
    append_attempt(attempts_path, attempt=0, start_ts=s0, end_ts=e0,
                   rc=p0.returncode, verdict=decision.verdict,
                   run_id=DRILL_RUN_ID)
    if not decision.requeue:
        raise RuntimeError(f"drill policy refused to requeue: "
                           f"{decision.shell_line()}")
    time.sleep(decision.backoff_s)    # the measured off-pod gap
    p1, s1, e1 = run_attempt(["--resume", "auto",
                              "--requeue-attempt", "1"], {})
    append_attempt(attempts_path, attempt=1, start_ts=s1, end_ts=e1,
                   rc=p1.returncode,
                   verdict=SUCCESS if p1.returncode == 0 else "crash",
                   run_id=DRILL_RUN_ID)
    if p1.returncode != 0:
        raise RuntimeError(
            f"drill attempt 1 exited {p1.returncode}:\n"
            f"{p1.stdout}\n{p1.stderr}")
    if "tpudist: resume success" not in p1.stdout:
        raise RuntimeError(
            f"drill attempt 1 did not resume from the manifest:\n"
            f"{p1.stdout}")
    return load_attempts(attempts_path)


# -------------------------------------------------------------- the CLI


def _summary_lines(ledger: Dict[str, Any]) -> List[str]:
    frac = ledger.get("goodput_fraction")
    totals = ledger.get("totals") or {}
    lines = [
        f"tpudist: goodput {ledger['goodput_status']}: "
        + (f"{100 * frac:.1f}% productive" if frac is not None
           else "nothing measured")
        + f" of {ledger['total_wall_s']:.2f}s wall across "
          f"{len(ledger['attempts'])} attempt(s), "
          f"{ledger['lost_steps']} step(s) lost to preemption",
        "tpudist: goodput buckets: " + ", ".join(
            f"{k} {totals.get(k, 0.0):.2f}s" for k in BUCKETS),
        f"tpudist: goodput partition "
        f"{'exact' if ledger['exact'] else 'INEXACT'} "
        f"(tolerance {ledger['tolerance']:.0%})",
    ]
    for p in ledger.get("problems", []):
        lines.append(f"tpudist: goodput problem: {p}")
    return lines


def _atomic_write(path: str, payload: str) -> None:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        f.write(payload)
    os.replace(tmp, path)


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tpudist.obs.goodput",
        description="cross-attempt goodput ledger from attempts.jsonl "
                    "+ metrics.jsonl + heartbeat beacons (jax-free)")
    p.add_argument("--run-dir", type=str, default=".",
                   help="directory holding attempts.jsonl, "
                        "metrics.jsonl (top level or attempt<N>/ "
                        "subdirs) and heartbeat beacons")
    p.add_argument("--attempts", type=str, default=None,
                   help="explicit attempts.jsonl path (default: "
                        "<run-dir>/attempts.jsonl)")
    p.add_argument("--out", type=str, default=None,
                   help=f"ledger JSON path (default: <run-dir>/"
                        f"{LEDGER_NAME})")
    p.add_argument("--bench-out", type=str, default=None,
                   help="also write BENCH_GOODPUT.json (BENCH_* "
                        "harness shape, headline = goodput fraction)")
    p.add_argument("--prom-out", type=str, default=None,
                   help="also write tpudist_goodput_* gauges as a "
                        "Prometheus textfile-collector file")
    p.add_argument("--tolerance", type=float, default=TOLERANCE,
                   help=f"partition-exactness tolerance as a fraction "
                        f"of total wall (default {TOLERANCE})")
    p.add_argument("--drill", action="store_true",
                   help="first run the scripted kill->requeue->resume "
                        "drill into --run-dir (real train CLI in "
                        "subprocesses, attempts.jsonl written like the "
                        "launcher's), then build the ledger from it")
    args = p.parse_args(argv)

    if args.drill:
        run_drill(args.run_dir)

    ledger = build_from_dir(args.run_dir, attempts_path=args.attempts,
                            tolerance=args.tolerance)
    if ledger is None:
        path = args.attempts or os.path.join(args.run_dir, ATTEMPTS_NAME)
        print(f"tpudist.obs.goodput: no attempt records at {path} — "
              f"the launcher (or --drill) writes attempts.jsonl",
              file=sys.stderr)
        return 2

    _atomic_write(args.out or os.path.join(args.run_dir, LEDGER_NAME),
                  json.dumps(ledger, indent=1))
    if args.bench_out:
        _atomic_write(args.bench_out,
                      json.dumps(bench_artifact(ledger), indent=1))
    if args.prom_out:
        _atomic_write(args.prom_out, prometheus_text(ledger))
    for line in _summary_lines(ledger):
        print(line)
    # advisory gate (the fraction's status never flips the exit code);
    # a broken PARTITION is a real failure — the whole point is exact
    # accounting
    return 0 if ledger["exact"] else 1


if __name__ == "__main__":
    sys.exit(main())
