"""Per-process heartbeat beacon + stall watchdog.

The reference's only liveness signal was CI's 10-second poll of a
``job_status.txt`` that is written *after* the job ends (SURVEY.md
§5.1/§5.5) — a hung worker produced nothing at all until the launcher's
outer timeout. The :class:`FlightRecorder` closes that gap from inside
the process:

  * the train loop calls :meth:`note_progress` at step boundaries (an
    attribute assignment — nanoseconds, nothing fenced, no device work);
  * a daemon thread writes a small JSON **beacon**
    (``heartbeat.worker<i>``: step, epoch, phase, ts) every few seconds —
    an operator ssh'd into any worker can see where it is *right now*;
  * the same thread watches the progress counter: no step progress for
    ``stall_timeout_s`` ⇒ it dumps a flight record (thread stacks,
    memory stats, last-N metrics — :mod:`tpudist.obs.flightrec`) and
    flushes the buffered metrics stream, all *before* the launcher kills
    the job.

The watchdog thread runs even while the main thread is wedged inside a
blocked collective: JAX blocks in C with the GIL released, so the timer
keeps ticking — which is the entire point.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, Optional

from tpudist.obs import flightrec

# beacon/watchdog wake period is derived from the stall window (a 0.5 s
# test window needs sub-second checks; a production 300 s window does
# not) and clamped to these bounds
_MIN_PERIOD_S = 0.05
_MAX_PERIOD_S = 2.0


class FlightRecorder:
    """Heartbeat beacon + stall watchdog for one process.

    Parameters:
      * ``out_dir`` — where ``heartbeat.worker<i>`` and
        ``flightrec.worker<i>`` land (the launcher collects this
        directory when a run times out).
      * ``stall_timeout_s`` — no step progress for this long ⇒ dump a
        flight record. ``0`` disables the watchdog (the beacon still
        beats).
      * ``process_index`` — names the artifacts; cached at construction
        so the watchdog thread never calls into jax.
      * ``metrics`` — a ``MetricsLogger``; the stall dump embeds the
        tail of its history and flushes its buffer (the records matter
        most in exactly the runs that die).
      * ``extra_state`` — optional callable returning a dict folded into
        the dump (the HBM sampler's watermarks ride along here).
      * ``tracer`` — an ``obs.trace.Tracer``; the stall dump embeds the
        tail of its span buffers (what phase each thread was in when
        the run hung) and exports the worker's local Chrome trace next
        to the flight record — a hung run leaves its TIMELINE, not
        just its stacks.
      * ``stall_hook`` — optional callable fired at dump time, BEFORE
        the record is written; returns a path (or None) recorded as
        ``profile_capture`` in the flight record. The windowed device
        profiler (obs.devtime.WindowProfiler.emergency_stop) hangs off
        this: a run that stalls with a capture window open still stops
        the profiler cleanly and keeps the partial device timeline
        next to the flight record.
      * ``emitter`` — a live-telemetry emitter (obs.live
        ``TelemetryEmitter``): every beacon ALSO ships as a
        ``kind=heartbeat`` record to the coordinator's aggregator (the
        per-host liveness signal the on-line stall alert keys off),
        and the stall dump ships a ``kind=stall_dump`` record FIRST —
        before the slow stack/memory collection below — so the firing
        alert reaches the Prometheus exporter while the launcher's
        outer timeout is still minutes away. ``emit`` is a lock-free
        bounded put (same discipline as ``WindowProfiler.
        emergency_stop``): a wedged run cannot wedge its own telemetry.
      * ``beacon_extra`` — optional callable whose dict folds into
        every beacon (live staging/HBM counters ride along; failures
        are swallowed — the beacon is best-effort by contract).
    """

    def __init__(self, out_dir: str, *, stall_timeout_s: float = 300.0,
                 process_index: int = 0, metrics: Any = None,
                 extra_state: Optional[Callable[[], Dict]] = None,
                 tracer: Any = None, last_n_metrics: int = 50,
                 last_n_spans: int = 64,
                 stall_hook: Optional[Callable[[], Optional[str]]] = None,
                 emitter: Any = None,
                 beacon_extra: Optional[Callable[[], Dict]] = None,
                 requeue_attempt: int = 0):
        if stall_timeout_s < 0:
            raise ValueError(
                f"stall_timeout_s must be >= 0, got {stall_timeout_s}")
        self.out_dir = out_dir
        self.stall_timeout_s = float(stall_timeout_s)
        self.process_index = process_index
        self.metrics = metrics
        self.extra_state = extra_state
        self.tracer = tracer
        self.stall_hook = stall_hook
        self.emitter = emitter
        self.beacon_extra = beacon_extra
        self.last_n_metrics = last_n_metrics
        self.last_n_spans = last_n_spans
        self.requeue_attempt = int(requeue_attempt)
        self.beacon_path = os.path.join(
            out_dir, f"heartbeat.worker{process_index}")
        self.flightrec_path = os.path.join(
            out_dir, f"flightrec.worker{process_index}")
        self.dumps = 0          # flight records written (tests read this)
        self.beacons = 0        # beacon writes (tests read this)
        # beacon namespacing across requeue attempts: an earlier
        # attempt's beacon left in a shared obs dir must never read as
        # THIS attempt's progress (the goodput ledger and the launcher's
        # vanished-worker inference both key off beacons per attempt) —
        # archive it under its own attempt suffix before the first
        # write. The dead attempt's progress counters survive under
        # heartbeat.worker<i>.attempt<K>, where the cross-attempt
        # ledger finds them.
        self._archive_stale_beacon()
        # progress is replaced wholesale (never mutated) so the watchdog
        # thread always reads a consistent snapshot without a lock
        self._progress: Dict[str, Any] = {
            "phase": "init", "step": -1, "epoch": -1, "ts": time.time(),
            "process_index": process_index, "pid": os.getpid(),
            "requeue_attempt": self.requeue_attempt}
        self._count = 0
        self._stop = threading.Event()
        period = _MAX_PERIOD_S
        if self.stall_timeout_s > 0:
            period = min(_MAX_PERIOD_S,
                         max(_MIN_PERIOD_S, self.stall_timeout_s / 4.0))
        self._period_s = period
        self._thread = threading.Thread(
            target=self._loop, name="tpudist-flightrec", daemon=True)
        self._thread.start()

    def _archive_stale_beacon(self) -> None:
        """Move a previous attempt's beacon aside (best-effort): the
        payload names its own attempt, so the archive keeps the attempt
        the data belongs to — NOT the one that found it."""
        try:
            with open(self.beacon_path) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            return     # absent or torn: this attempt's writes overwrite
        stale = payload.get("requeue_attempt")
        stale = int(stale) if isinstance(stale, (int, float)) else 0
        if stale == self.requeue_attempt:
            return     # same attempt restarted in place: just overwrite
        try:
            os.replace(self.beacon_path,
                       f"{self.beacon_path}.attempt{stale}")
        except OSError:
            try:
                os.remove(self.beacon_path)
            except OSError:
                pass   # unremovable beats unreadable: first write wins

    # ------------------------------------------------------- hot path
    def note_progress(self, **kv: Any) -> None:
        """Record step progress. Called from the train loop's hot path:
        two attribute assignments, no I/O, no locks, no device work."""
        kv["ts"] = time.time()
        self._progress = {**self._progress, **kv}
        self._count += 1

    @property
    def progress(self) -> Dict[str, Any]:
        return self._progress

    def beacon_now(self) -> None:
        """Write one beacon synchronously, off the watchdog cadence.
        The scripted preemption (train._maybe_test_kill) calls this
        before ``os._exit``: at production step rates the periodic
        beacon is at most a step or two stale when a reaper lands, but
        a CPU drill runs its whole epoch inside one beacon period —
        this stamp reproduces the realistic ~fresh beacon a real kill
        leaves, so the lost-step accounting stays deterministic."""
        self._write_beacon()

    # ------------------------------------------------- watchdog thread
    def _loop(self) -> None:
        last_count = self._count
        last_change = time.monotonic()
        dumped_this_stall = False
        while not self._stop.wait(self._period_s):
            self._write_beacon()
            now = time.monotonic()
            if self._count != last_count:
                last_count = self._count
                last_change = now
                dumped_this_stall = False   # progress resumed; re-arm
                continue
            if (self.stall_timeout_s > 0 and not dumped_this_stall
                    and now - last_change >= self.stall_timeout_s):
                self.dump(reason="stall",
                          stall_s=round(now - last_change, 3))
                dumped_this_stall = True

    def _write_beacon(self) -> None:
        # progress_n is the note_progress call counter — the SAME
        # signal this watchdog's own stall detection keys off (any
        # progress re-arms it: phase flips during long eval/ckpt
        # included, not just step advances), shipped so the live
        # aggregator's stall-age accounting agrees with the watchdog
        payload = {**self._progress, "beacon_ts": time.time(),
                   "progress_n": self._count}
        if self.beacon_extra is not None:
            try:
                payload.update(self.beacon_extra())
            except Exception:
                pass   # extras are a bonus; the beacon core still beats
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            tmp = f"{self.beacon_path}.tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, self.beacon_path)
            self.beacons += 1
        except Exception:
            # the beacon is best-effort; a full disk must not kill the
            # watchdog (the flight record is the part that matters)
            pass
        if self.emitter is not None:
            # the live bus's per-host liveness signal: the aggregator's
            # progress-age accounting (and so the on-line stall alert)
            # keys off these, so they flow even when the FILE write
            # above failed — a full disk must not blind the exporter
            self.emitter.emit({"kind": "heartbeat", **payload})

    # ----------------------------------------------------------- dump
    def dump(self, reason: str = "manual",
             stall_s: Optional[float] = None) -> str:
        """Write the flight record now (the watchdog calls this on
        stall; the launcher-facing contract is the artifact's existence,
        so it is also callable directly for drills/tests)."""
        if self.emitter is not None:
            # FIRST, before the slow stack/memory collection below: the
            # measured stall rides to the aggregator so the stall alert
            # is firing — on disk in live_status.json and scrapeable at
            # /metrics — before the launcher's kill, not after
            self.emitter.emit({"kind": "stall_dump", "reason": reason,
                               "stall_s": stall_s, **self._progress})
        history = []
        if self.metrics is not None:
            # the stall dump also lands in the metrics stream itself —
            # the offline report's Alerts cross-check ("a watchdog dump
            # with no mid-run stall alert is a live-coverage gap") reads
            # metrics.jsonl, so the evidence must exist there too, not
            # only on the live bus
            try:
                self.metrics.log(kind="stall_dump", reason=reason,
                                 stall_s=stall_s,
                                 **{k: self._progress.get(k)
                                    for k in ("phase", "step", "epoch",
                                              "process_index")})
            except Exception:
                pass
            try:
                history = list(self.metrics.history)[-self.last_n_metrics:]
            except Exception:
                pass
        extra = None
        if self.extra_state is not None:
            try:
                extra = self.extra_state()
            except Exception:
                extra = None
        if self.stall_hook is not None:
            # e.g. stop an open device-profiler window so the partial
            # capture survives next to this record (a hung run still
            # yields a device timeline); fired before the write so the
            # record can name the capture path
            try:
                capture = self.stall_hook()
            except Exception:
                capture = None
            if capture:
                extra = {**(extra or {}), "profile_capture": capture}
        spans = None
        if self.tracer is not None and getattr(self.tracer, "enabled",
                                               False):
            # the span-buffer tail: WHAT PHASE each thread was in when
            # the run hung (the open-span stack is the live answer) —
            # and the full local timeline as a Chrome trace next to the
            # flight record, since a wedged pod never reaches the
            # run-end merged export (its collectives would hang too)
            try:
                spans = self.tracer.tail(per_thread=self.last_n_spans)
            except Exception:
                spans = None
            try:
                from tpudist.obs import trace as trace_mod
                self.tracer.export_local(
                    os.path.join(self.out_dir, trace_mod.worker_trace_name(
                        self.process_index)),
                    process_index=self.process_index)
            except Exception:
                pass
        path = flightrec.dump_flight_record(
            self.flightrec_path, reason=reason, progress=self._progress,
            stall_s=stall_s, last_metrics=history, spans=spans,
            extra=extra)
        if self.metrics is not None:
            # the buffered JSONL stream would otherwise die with the run
            # — these are the records that matter most (satellite:
            # crash-safety for buffered metrics). Flushed before the
            # dumps counter ticks: the counter is the "dump complete"
            # signal watchers key off.
            try:
                self.metrics.flush()
            except Exception:
                pass
        self.dumps += 1
        return path

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._write_beacon()   # final beacon: phase as of shutdown
