"""Live pod telemetry: streaming metrics bus, on-line status, Prometheus.

Everything the framework produced before this module was post-mortem:
the verdict grades at exit, the flight recorder dumps on a stall, the
run report runs after ``pod_trace.json`` lands. An operator watching a
multi-hour pod job had no view of it *while it runs*. This module is
that view, in four pieces:

  * :class:`TelemetryEmitter` — per-worker, NON-BLOCKING: records go
    into a bounded queue (``put_nowait``; full queue = dropped record +
    counter, never a blocked step loop — the PR 5 tracer's
    zero-overhead discipline, pinned by the bitwise live-on/off parity
    test) and a background thread ships them as length-prefixed JSON
    frames over TCP (or UDP) to the coordinator. A wedged socket costs
    the sender thread, not the train loop.
  * :class:`LiveAggregator` — coordinator-side: ingests every worker's
    stream (heartbeat beacons + the rank-0 metrics fan-out), keeps
    rolling windows (pod steps/s, per-host rates and progress ages,
    staging overlap, HBM watermarks, exposed-comm fraction, ckpt drain
    stalls), drives the on-line :class:`~tpudist.obs.alerts.AlertEngine`
    over the SAME thresholds the exit verdict applies
    (:mod:`tpudist.rules`), and atomically rewrites
    ``live_status.json`` + appends ``alerts.jsonl``.
  * :class:`LiveHttpServer` — a stdlib ``http.server`` exposing the
    aggregator as Prometheus text format (``/metrics``), JSON
    (``/status.json``) and a liveness probe (``/healthz``). Handlers
    read the aggregator's last snapshot — a wholesale-replaced dict,
    so serving a scrape takes NO lock shared with ingest (the
    ``note_progress`` discipline): a firing stall alert reaches the
    exporter even while the run is wedged.
  * ``python -m tpudist.obs.live tail`` — a terminal dashboard over
    ``/status.json`` or the ``live_status.json`` file: per-host rates,
    active phase, firing alerts.

The exporter, tail CLI, frame codec and aggregator are jax-free (the
offline-tooling contract shared with :mod:`tpudist.obs.report`); only
:func:`resolve_run_id`'s multi-host broadcast imports jax, at call
time. ``--live off`` (the default) constructs NONE of this — no
sockets, no threads, no queue, zero added syscalls.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from tpudist import rules as rules_lib
from tpudist.obs import alerts as alerts_lib

LIVE_SCHEMA_VERSION = 1
STATUS_NAME = "live_status.json"
ALERTS_NAME = "alerts.jsonl"

# Emitter queue depth: at the train loop's record rate (a few records
# per logging boundary plus one beacon every couple of seconds) this
# holds minutes of backlog; past it the emitter DROPS — the step loop
# never blocks on telemetry.
DEFAULT_QUEUE_SLOTS = 1024
# A frame longer than this is a corrupt length prefix, not a record —
# the decoder resynchronises by dropping its buffer.
MAX_FRAME_BYTES = 1 << 20


# ------------------------------------------------------------ wire format

_LEN = struct.Struct(">I")
_CRC = struct.Struct(">I")
# Frame marker: the decoder's resynchronisation anchor. A naked length
# prefix cannot recover from garbage on the stream (any 4 bytes read as
# a length), so each frame leads with this magic and carries a payload
# crc32 — garbage between frames is skipped by scanning to the next
# marker, and a frame whose bytes were torn mid-stream (truncation, a
# chance marker inside garbage) fails its crc and costs ONLY itself:
# the decoder rescans the very bytes it tentatively consumed, so
# buffered and subsequent valid frames still decode (pinned by the
# fuzz test in tests/test_live.py — the chaos plane's
# telemetry_garbage drill injects exactly this).
FRAME_MAGIC = b"TPLF"
# header layout: magic + payload length + header crc32 (over the magic
# and length bytes — a TORN length field is rejected the moment the
# header arrives, instead of stalling the stream on a phantom payload
# that never comes) + payload crc32
_HEADER = len(FRAME_MAGIC) + _LEN.size + 2 * _CRC.size


def encode_frame(rec: Dict[str, Any]) -> bytes:
    """One record as a framed JSON message: 4-byte magic + big-endian
    payload length + header crc32 + payload crc32 + UTF-8 payload. The
    same framing rides TCP streams and UDP datagrams, so both
    transports share one codec."""
    import zlib
    payload = json.dumps(rec, separators=(",", ":"),
                         default=str).encode("utf-8")
    head = FRAME_MAGIC + _LEN.pack(len(payload))
    return (head + _CRC.pack(zlib.crc32(head) & 0xFFFFFFFF)
            + _CRC.pack(zlib.crc32(payload) & 0xFFFFFFFF) + payload)


class FrameDecoder:
    """Incremental, SELF-RESYNCHRONISING frame parser for one TCP
    connection (or one UDP datagram). Tolerates partial reads; garbage
    bytes, corrupt length prefixes and torn frames bump ``bad`` and the
    decoder scans forward to the next frame marker — one bad peer (or a
    chaos-injected garbage burst) can neither wedge the aggregator nor
    cost the valid frames around the damage."""

    def __init__(self) -> None:
        self._buf = b""
        self.bad = 0

    def _discard_to_marker(self) -> bool:
        """Drop bytes that cannot start a frame; keep a possible marker
        prefix at the tail. True when a full marker heads the buffer."""
        i = self._buf.find(FRAME_MAGIC)
        if i == 0:
            return True
        if i > 0:
            self.bad += 1             # garbage before the marker
            self._buf = self._buf[i:]
            return True
        keep = 0
        for k in range(min(len(FRAME_MAGIC) - 1, len(self._buf)), 0, -1):
            if self._buf.endswith(FRAME_MAGIC[:k]):
                keep = k
                break
        if len(self._buf) > keep:
            self.bad += 1             # pure garbage discarded
            self._buf = self._buf[len(self._buf) - keep:] if keep else b""
        return False

    def feed(self, data: bytes) -> List[Dict[str, Any]]:
        import zlib
        self._buf += data
        out: List[Dict[str, Any]] = []
        while self._buf:
            if not self._discard_to_marker():
                break                 # no full marker buffered yet
            if len(self._buf) < _HEADER:
                break
            (n,) = _LEN.unpack_from(self._buf, len(FRAME_MAGIC))
            (hcrc,) = _CRC.unpack_from(self._buf,
                                       len(FRAME_MAGIC) + _LEN.size)
            head = self._buf[:len(FRAME_MAGIC) + _LEN.size]
            if n > MAX_FRAME_BYTES \
                    or zlib.crc32(head) & 0xFFFFFFFF != hcrc:
                # torn/corrupt header (or a chance marker inside
                # garbage): reject NOW — waiting out a phantom length
                # would stall the stream — skip just this marker and
                # rescan what follows
                self.bad += 1
                self._buf = self._buf[1:]
                continue
            if len(self._buf) < _HEADER + n:
                break                 # wait for the rest of the frame
            (crc,) = _CRC.unpack_from(
                self._buf, len(FRAME_MAGIC) + _LEN.size + _CRC.size)
            raw = self._buf[_HEADER:_HEADER + n]
            if zlib.crc32(raw) & 0xFFFFFFFF != crc:
                # torn frame (truncated sender, garbage with a chance
                # marker): the bytes we tentatively framed may CONTAIN
                # the next valid frame — skip only the marker and
                # rescan them instead of discarding
                self.bad += 1
                self._buf = self._buf[1:]
                continue
            self._buf = self._buf[_HEADER + n:]
            try:
                rec = json.loads(raw)
                if isinstance(rec, dict):
                    out.append(rec)
                else:
                    self.bad += 1
            except Exception:
                self.bad += 1         # well-framed but unparseable
        return out


def parse_endpoint(endpoint: str) -> Tuple[str, Tuple[str, int]]:
    """``[tcp://|udp://]host:port`` → ``(transport, (host, port))``."""
    transport = "tcp"
    rest = endpoint
    if "://" in endpoint:
        scheme, rest = endpoint.split("://", 1)
        if scheme not in ("tcp", "udp"):
            raise ValueError(
                f"live endpoint transport must be tcp or udp, got "
                f"{scheme!r} in {endpoint!r}")
        transport = scheme
    host, sep, port_s = rest.rpartition(":")
    if not sep or not port_s.isdigit():
        raise ValueError(
            f"live endpoint must be [tcp://|udp://]host:port, got "
            f"{endpoint!r}")
    return transport, (host or "127.0.0.1", int(port_s))


# --------------------------------------------------------------- emitter


class TelemetryEmitter:
    """Per-worker non-blocking record shipper.

    ``emit()`` is the ONLY entry point the train loop (and the beacon
    thread) touches: a ``put_nowait`` onto a bounded queue — a full
    queue drops the record and bumps ``dropped``, it never waits. The
    sender thread owns every socket operation; connect/send timeouts
    plus a reconnect backoff mean a dead or wedged coordinator costs
    dropped records, never a blocked caller. Same posture as the span
    tracer: telemetry must not be able to slow the thing it observes.
    """

    def __init__(self, endpoint: str, *,
                 queue_slots: int = DEFAULT_QUEUE_SLOTS,
                 connect_timeout_s: float = 2.0,
                 send_timeout_s: float = 2.0,
                 retry_s: float = 0.5):
        import queue as queue_mod
        self.transport, self.addr = parse_endpoint(endpoint)
        self.endpoint = endpoint
        self.connect_timeout_s = connect_timeout_s
        self.send_timeout_s = send_timeout_s
        self.retry_s = retry_s
        self._q: Any = queue_mod.Queue(maxsize=max(1, queue_slots))
        self._full = queue_mod.Full
        self._empty = queue_mod.Empty
        self.sent = 0
        self.dropped = 0
        self.errors = 0
        self._sock: Optional[socket.socket] = None
        self._next_connect = 0.0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="tpudist-live-emit", daemon=True)
        self._thread.start()

    # ------------------------------------------------------- hot path
    def emit(self, rec: Dict[str, Any]) -> None:
        """Enqueue one record; never blocks, never raises."""
        if self._stop.is_set():
            return
        try:
            self._q.put_nowait(rec)
        except self._full:
            self.dropped += 1

    def inject_garbage(self, data: bytes) -> None:
        """Chaos-plane hook (tpudist.chaos ``telemetry_garbage``):
        enqueue raw UNFRAMED bytes that the sender ships verbatim —
        scripted stream damage the aggregator's FrameDecoder must
        resynchronise through. Same non-blocking discipline as emit."""
        if self._stop.is_set():
            return
        try:
            self._q.put_nowait(bytes(data))
        except self._full:
            self.dropped += 1

    # --------------------------------------------------- sender thread
    def _loop(self) -> None:
        while True:
            try:
                rec = self._q.get(timeout=0.1)
            except self._empty:
                if self._stop.is_set():
                    return
                continue
            self._send(rec)

    def _send(self, rec: Any) -> None:
        try:
            # raw bytes = chaos-injected garbage, shipped unframed
            frame = (bytes(rec) if isinstance(rec, (bytes, bytearray))
                     else encode_frame(rec))
            if self.transport == "udp":
                if self._sock is None:
                    self._sock = socket.socket(socket.AF_INET,
                                               socket.SOCK_DGRAM)
                self._sock.sendto(frame, self.addr)
            else:
                if self._sock is None:
                    if time.monotonic() < self._next_connect:
                        raise ConnectionError("reconnect backoff")
                    s = socket.create_connection(
                        self.addr, timeout=self.connect_timeout_s)
                    s.settimeout(self.send_timeout_s)
                    self._sock = s
                self._sock.sendall(frame)
            self.sent += 1
        except Exception:
            # drop-not-block: the record is lost, counted, and the
            # sender moves on; the NEXT connect attempt is rate-limited
            self.errors += 1
            self.dropped += 1
            if self._sock is not None:
                try:
                    self._sock.close()
                except Exception:
                    pass
                self._sock = None
            self._next_connect = time.monotonic() + self.retry_s

    def close(self, drain_s: float = 1.0) -> None:
        """Bounded drain then stop — run exit must not hang on a dead
        coordinator (whatever is still queued past the deadline is
        counted as dropped by omission)."""
        deadline = time.monotonic() + max(0.0, drain_s)
        while (not self._q.empty() and self._thread.is_alive()
               and time.monotonic() < deadline):
            time.sleep(0.02)
        self._stop.set()
        self._thread.join(timeout=2.0)
        if self._sock is not None:
            try:
                self._sock.close()
            except Exception:
                pass
            self._sock = None

    def stats(self) -> Dict[str, Any]:
        return {"endpoint": self.endpoint, "sent": self.sent,
                "dropped": self.dropped, "errors": self.errors,
                "queued": self._q.qsize()}


# ------------------------------------------------------- rolling windows


class RollingWindow:
    """Monotone counter samples within the last ``window_s`` seconds;
    ``rate()`` is the counter's slope over the surviving span."""

    def __init__(self, window_s: float = 30.0):
        self.window_s = float(window_s)
        self._pts: deque = deque()

    def add(self, t: float, v: float) -> None:
        self._pts.append((t, v))
        cutoff = t - self.window_s
        while len(self._pts) > 1 and self._pts[0][0] < cutoff:
            self._pts.popleft()

    def rate(self) -> Optional[float]:
        if len(self._pts) < 2:
            return None
        (t0, v0), (t1, v1) = self._pts[0], self._pts[-1]
        return (v1 - v0) / (t1 - t0) if t1 > t0 else None

    def last(self) -> Optional[float]:
        return self._pts[-1][1] if self._pts else None


# ------------------------------------------------------------ aggregator


class LiveAggregator:
    """Coordinator-side rolling view of the pod + the on-line alerts.

    ``ingest(rec)`` accepts any record from the bus — heartbeat beacons
    from every worker, the rank-0 metrics fan-out (``kind=step/epoch/
    hosts/timing/ckpt/devtime/resume``), and the watchdog's last-gasp
    ``kind=stall_dump`` — updates the rolling windows, and feeds the
    alert engine. ``tick()`` evaluates the time-based rules (stall ages,
    live straggler ratios from beacon-derived rates). Both rebuild
    ``self._status``, a plain dict REPLACED WHOLESALE so the exporter
    and the flight recorder's stall dump read it without any lock
    (:meth:`snapshot`), and write ``live_status.json`` atomically
    (rate-limited; alert transitions force a write so a breach is on
    disk and scrapeable before any launcher kill).

    Scripted tests pass ``start_ticker=False`` plus explicit ``now=``
    values, and a fake ``wall`` clock into the engine, making windows
    and alert durations deterministic.
    """

    def __init__(self, *, out_dir: str, run_id: Optional[str] = None,
                 requeue_attempt: int = 0,
                 stall_timeout_s: Optional[float] = None,
                 window_s: float = 30.0,
                 regress_baseline_sps: Optional[float] = None,
                 metrics: Any = None,
                 status_min_interval_s: float = 0.25,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time,
                 start_ticker: bool = True):
        self.out_dir = out_dir
        os.makedirs(out_dir, exist_ok=True)
        self.status_path = os.path.join(out_dir, STATUS_NAME)
        self.alerts_path = os.path.join(out_dir, ALERTS_NAME)
        self.run_id = run_id
        self.requeue_attempt = requeue_attempt
        self.stall_timeout_s = (rules_lib.resolve("stall")
                                if stall_timeout_s is None
                                else float(stall_timeout_s))
        self.window_s = window_s
        if regress_baseline_sps is None:
            raw = os.environ.get("TPUDIST_LIVE_BASELINE_SPS")
            try:
                regress_baseline_sps = float(raw) if raw else None
            except ValueError:
                regress_baseline_sps = None
        self.regress_baseline_sps = regress_baseline_sps
        self.metrics = metrics
        self.clock = clock
        self.wall = wall
        self.engine = alerts_lib.AlertEngine(on_event=self._on_event,
                                             clock=wall)
        self._lock = threading.RLock()
        self._hosts: Dict[int, Dict[str, Any]] = {}
        self._pod: Dict[str, Any] = {
            "step": None, "epoch": None, "loss": None,
            "steps_per_sec": None, "straggler_ratio": None,
            "staging_overlap_fraction": None, "exposed_comm_frac": None,
            "dcn_bytes_total": None,
            "ckpt_last_enqueue_ms": None, "ckpt_drain_ms": None,
            "ckpt_saves": 0, "resume": None, "timing_seen": False}
        self._pod_window = RollingWindow(window_s)
        self.records = 0
        self.bad_frames = 0
        self._alerts_fh = None
        self._last_write = 0.0
        # serialises the throttle check + tmp-file write/rename: ingest
        # threads, the ticker, and forced alert writes all land here,
        # and two writers sharing one .tmp path would tear the file
        self._write_lock = threading.Lock()
        self.status_min_interval_s = status_min_interval_s
        self._servers: List[Any] = []
        self._conns: List[socket.socket] = []
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._status: Dict[str, Any] = {}
        self._rebuild(force_write=False)
        if start_ticker:
            period = 0.5
            if self.stall_timeout_s > 0:
                period = min(1.0, max(0.05, self.stall_timeout_s / 4.0))
            t = threading.Thread(target=self._tick_loop, args=(period,),
                                 name="tpudist-live-agg", daemon=True)
            t.start()
            self._threads.append(t)

    # ---------------------------------------------------------- ingest
    def ingest(self, rec: Dict[str, Any],
               now: Optional[float] = None) -> None:
        now = self.clock() if now is None else now
        with self._lock:
            self._ingest_locked(rec, now)
        self._rebuild()

    def _ingest_locked(self, rec: Dict[str, Any], now: float) -> None:
        self.records += 1
        if self.run_id is None and rec.get("run_id"):
            self.run_id = str(rec["run_id"])
        kind = rec.get("kind")
        if kind == "heartbeat":
            self._ingest_heartbeat(rec, now)
        elif kind == "step":
            step = rec.get("step")
            if isinstance(step, (int, float)):
                self._pod["step"] = int(step)
                self._pod_window.add(now, float(step))
            for k in ("epoch", "loss"):
                if rec.get(k) is not None:
                    self._pod[k] = rec[k]
            self._observe_rate(rec.get("steps_per_sec"))
        elif kind == "epoch":
            for k in ("epoch", "steps_per_sec"):
                if rec.get(k) is not None:
                    self._pod[k] = rec[k]
            if rec.get("avg_loss") is not None:
                self._pod["loss"] = rec["avg_loss"]
            self._observe_rate(rec.get("steps_per_sec"))
        elif kind == "hosts":
            ratio = rec.get("straggler_ratio")
            self._pod["straggler_ratio"] = ratio
            self.engine.observe("straggler", ratio,
                                step=self._pod.get("step"))
        elif kind == "timing":
            self._pod["timing_seen"] = True
            ov = rec.get("staging_overlap_fraction")
            if ov is not None:
                self._pod["staging_overlap_fraction"] = ov
                self.engine.observe("staging", ov,
                                    step=self._pod.get("step"))
        elif kind == "devtime":
            frac = rec.get("exposed_comm_frac")
            self._pod["exposed_comm_frac"] = frac
            fabric = rec.get("fabric")
            if fabric is not None:
                self._pod["comm_fabric"] = fabric
            if rec.get("dcn_bytes_total") is not None:
                # program-derived per-step DCN byte volume (cross-slice
                # schedule telemetry) — a gauge, not a counter: the
                # program is fixed for the run
                self._pod["dcn_bytes_total"] = rec["dcn_bytes_total"]
            # fabric-graded: a DCN-labeled record substitutes the DCN
            # ceiling but keeps the ONE "comm" rule key, so the at-exit
            # comm_status cross-check still finds its matching alert
            self.engine.observe("comm", frac,
                                threshold=rules_lib.resolve_comm(fabric),
                                step=self._pod.get("step"))
        elif kind == "ckpt":
            self._pod["ckpt_saves"] += 1
            if rec.get("enqueue_ms") is not None:
                self._pod["ckpt_last_enqueue_ms"] = rec["enqueue_ms"]
        elif kind == "ckpt_drain":
            if rec.get("drain_ms") is not None:
                self._pod["ckpt_drain_ms"] = rec["drain_ms"]
        elif kind == "resume":
            self._pod["resume"] = {
                k: rec.get(k) for k in ("status", "source",
                                        "resumed_from_step",
                                        "requeue_attempt")}
        elif kind in ("serve_tick", "serve"):
            # the serving loop's periodic SLO observations (and its
            # final summary): latest values win the status doc, and the
            # three serve gates ride the SAME alert engine the training
            # rules do — an SLO breach fires mid-run, not at exit
            sv = self._pod.setdefault("serve", {})
            for k in ("queue_depth", "active_slots", "completed",
                      "generated_tokens", "ttft_p99_s", "itl_p99_s",
                      "tokens_per_sec_per_chip", "status",
                      "shed_total", "shed_fraction", "adapt_level",
                      "decode_k", "kv_pages_used", "kv_pages_total",
                      "kv_shared_refs", "spec_accept_rate",
                      "ttft_hist", "itl_hist"):
                if rec.get(k) is not None:
                    sv[k] = rec[k]
            step = sv.get("completed")
            self.engine.observe("ttft", rec.get("ttft_p99_s"),
                                step=step)
            self.engine.observe("itl", rec.get("itl_p99_s"), step=step)
            self.engine.observe("tokens_per_chip",
                                rec.get("tokens_per_sec_per_chip"),
                                step=step)
            self.engine.observe("serve_shed", rec.get("shed_fraction"),
                                step=step)
        elif kind == "serve_adapt":
            # the pressure controller's ladder transitions, mirrored
            # into the live view: latest level wins the status doc and
            # the tpudist_serve_adapt_level gauge; the full transition
            # history stays in metrics.jsonl for the report/verifier
            sv = self._pod.setdefault("serve", {})
            for k in ("adapt_level", "decode_k"):
                src = "to_level" if k == "adapt_level" else k
                if rec.get(src) is not None:
                    sv[k] = rec[src]
        elif kind == "goodput":
            # the run-end attempt-local goodput estimate
            # (obs.goodput.attempt_record): the same observable the
            # offline cross-attempt ledger refines, graded against the
            # same rules-table floor
            frac = rec.get("fraction")
            self._pod["goodput_fraction"] = frac
            self.engine.observe("goodput", frac,
                                step=self._pod.get("step"))
        elif kind == "memledger":
            # the run-end HBM ledger (obs.memledger.ledger_record):
            # per-bucket bytes become the tpudist_hbm_bytes{bucket=...}
            # gauge family and the headroom fraction is graded live
            # against the TPUDIST_HBM_HEADROOM_MIN floor — an
            # over-committed device alerts before the allocation spike
            # that would kill it
            ml = self._pod.setdefault("memledger", {})
            for k in ("total_hbm_bytes", "headroom_fraction",
                      "hbm_headroom_status", "exact", "mode",
                      "watermark_source"):
                if rec.get(k) is not None:
                    ml[k] = rec[k]
            for k in ("params", "opt_state", "slabs", "kv_pool",
                      "program_temp", "headroom", "residue"):
                if rec.get(f"{k}_bytes") is not None:
                    ml.setdefault("buckets", {})[k] = rec[f"{k}_bytes"]
            self.engine.observe("hbm_headroom",
                                rec.get("headroom_fraction"),
                                step=self._pod.get("step"))
        elif kind == "stall_dump":
            # the watchdog's last gasp: the worker MEASURED this many
            # seconds without step progress before dumping — observe it
            # directly so the alert is firing (and scrapeable) without
            # waiting for this side's age accounting to catch up
            pi = int(rec.get("process_index", 0) or 0)
            stall_s = rec.get("stall_s")
            if isinstance(stall_s, (int, float)) \
                    and self.stall_timeout_s > 0:
                self.engine.observe("stall", float(stall_s), host=pi,
                                    step=rec.get("step"),
                                    threshold=self.stall_timeout_s)
        # kind == "alert" (our own loopback echo) and unknown kinds:
        # counted, otherwise ignored

    def _ingest_heartbeat(self, rec: Dict[str, Any], now: float) -> None:
        pi = int(rec.get("process_index", 0) or 0)
        h = self._hosts.setdefault(pi, {
            "window": RollingWindow(self.window_s),
            "last_progress": now, "last_seen": now, "step": None,
            "epoch": None, "phase": None, "progress_n": None,
            "hbm_peak_bytes": None,
            "staging_overlap_fraction": None})
        step = rec.get("step")
        stepped = (isinstance(step, (int, float)) and step >= 0
                   and h["step"] != int(step))
        # stall re-arm: prefer the beacon's note_progress counter — the
        # SAME any-progress signal the watchdog re-arms on (phase flips
        # during a long eval or ckpt drain count, so those phases don't
        # read as stalls) — falling back to step advances for scripted
        # or older beacons that don't carry it
        pn = rec.get("progress_n")
        if pn is not None:
            if h["progress_n"] != pn:
                h["last_progress"] = now
            h["progress_n"] = pn
        elif stepped:
            h["last_progress"] = now
        if isinstance(step, (int, float)) and step >= 0:
            if stepped:
                h["window"].add(now, float(step))
            h["step"] = int(step)
        for k in ("epoch", "phase"):
            if rec.get(k) is not None:
                h[k] = rec[k]
        if rec.get("hbm_peak_bytes") is not None:
            h["hbm_peak_bytes"] = rec["hbm_peak_bytes"]
        h["last_seen"] = now
        # live staging overlap from the beacon's cheap counters: the
        # SAME observable the exit verdict grades, available mid-run
        run_s = rec.get("run_s")
        wait_s = rec.get("staging_wait_s")
        if (rec.get("staging_streamed")
                and isinstance(run_s, (int, float)) and run_s > 0
                and isinstance(wait_s, (int, float))):
            ov = max(0.0, min(1.0, 1.0 - wait_s / run_s))
            h["staging_overlap_fraction"] = ov
            self.engine.observe("staging", ov, host=pi, step=h["step"])

    def _observe_rate(self, sps: Any) -> None:
        if not isinstance(sps, (int, float)) or sps <= 0:
            return   # warmup/empty timer: nothing measured yet
        self._pod["steps_per_sec"] = sps
        if self.regress_baseline_sps:
            self.engine.observe("regress",
                                sps / self.regress_baseline_sps,
                                step=self._pod.get("step"))

    # ------------------------------------------------------------ tick
    def tick(self, now: Optional[float] = None) -> None:
        """Time-based rule evaluation: per-host progress ages (stall)
        and the live straggler ratio from beacon-derived rates."""
        now = self.clock() if now is None else now
        with self._lock:
            import statistics
            step_times = []
            for pi, h in self._hosts.items():
                age = max(0.0, now - h["last_progress"])
                h["age_s"] = age
                if self.stall_timeout_s > 0:
                    # the per-RUN stall window (--stall-timeout-s), not
                    # the env-only rules resolve: live and the watchdog
                    # must agree on when a host counts as wedged (0 =
                    # disabled, same contract as the watchdog)
                    self.engine.observe("stall", age, host=pi,
                                        step=h.get("step"),
                                        threshold=self.stall_timeout_s)
                r = h["window"].rate()
                if r and r > 0 and now - h["last_seen"] < self.window_s:
                    step_times.append(1.0 / r)
            if len(step_times) >= 2:
                med = statistics.median(step_times)
                if med > 0:
                    self.engine.observe("straggler",
                                        max(step_times) / med,
                                        step=self._pod.get("step"))
        self._rebuild()

    def _tick_loop(self, period: float) -> None:
        while not self._stop.wait(period):
            try:
                self.tick()
            except Exception:
                pass   # the view must never take down the run

    # ---------------------------------------------------------- status
    def _on_event(self, rec: Dict[str, Any]) -> None:
        """Alert transition fan-out: alerts.jsonl + the metrics stream
        (rank 0's buffered JSONL — the report CLI's Alerts section reads
        both) + an immediate forced status rewrite so the breach is on
        disk and scrapeable NOW, not at the next throttled write."""
        try:
            if self._alerts_fh is None:
                self._alerts_fh = open(self.alerts_path, "a")
            self._alerts_fh.write(json.dumps(rec, default=str) + "\n")
            self._alerts_fh.flush()
        except Exception:
            pass
        if self.metrics is not None:
            try:
                self.metrics.log(**rec)
            except Exception:
                pass
        self._rebuild(force_write=True)

    def _rebuild(self, force_write: bool = False) -> None:
        with self._lock:
            hosts = {}
            for pi, h in sorted(self._hosts.items()):
                hosts[str(pi)] = {
                    "step": h["step"], "epoch": h["epoch"],
                    "phase": h["phase"],
                    "steps_per_sec": h["window"].rate(),
                    "age_s": round(h.get("age_s", 0.0), 3),
                    "hbm_peak_bytes": h["hbm_peak_bytes"],
                    "staging_overlap_fraction":
                        h["staging_overlap_fraction"]}
            alerts = self.engine.snapshot()
            doc = {
                "schema": LIVE_SCHEMA_VERSION,
                "run_id": self.run_id,
                "requeue_attempt": self.requeue_attempt,
                "ts": self.wall(),
                "status": "alert" if alerts["firing"] else "ok",
                "pod": dict(self._pod,
                            steps_per_sec_window=self._pod_window.rate()),
                "hosts": hosts,
                "alerts": alerts,
                "counters": {"records": self.records,
                             "bad_frames": self.bad_frames},
            }
        self._status = doc
        now = self.clock()
        with self._write_lock:
            if force_write or now - self._last_write >= \
                    self.status_min_interval_s:
                self._last_write = now
                self._write_status(doc)

    def _write_status(self, doc: Dict[str, Any]) -> None:
        try:
            tmp = f"{self.status_path}.tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, default=str)
            os.replace(tmp, self.status_path)
        except Exception:
            pass   # a full disk must not kill the aggregator

    def snapshot(self) -> Dict[str, Any]:
        """The last built status doc. LOCK-FREE by design (a wholesale-
        replaced reference): the exporter's scrape handler and the
        flight recorder's stall dump both read it while the run may be
        wedged — neither can afford to wait on the ingest lock."""
        return self._status

    # ------------------------------------------------------ networking
    def serve_ingest(self, host: str = "127.0.0.1",
                     port: int = 0) -> int:
        """Bind the ingest listener (TCP stream + UDP datagrams on the
        same port number) and start the accept/receive threads; returns
        the bound port (``port=0`` picks an ephemeral one)."""
        tcp = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        tcp.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        tcp.bind((host, port))
        tcp.listen(32)
        bound = tcp.getsockname()[1]
        udp = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            udp.bind((host, bound))
        except OSError:
            udp = None
        self._servers += [s for s in (tcp, udp) if s is not None]
        t = threading.Thread(target=self._accept_loop, args=(tcp,),
                             name="tpudist-live-tcp", daemon=True)
        t.start()
        self._threads.append(t)
        if udp is not None:
            tu = threading.Thread(target=self._udp_loop, args=(udp,),
                                  name="tpudist-live-udp", daemon=True)
            tu.start()
            self._threads.append(tu)
        return bound

    def _accept_loop(self, tcp: socket.socket) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = tcp.accept()
            except OSError:
                return   # listener closed
            self._conns.append(conn)
            t = threading.Thread(target=self._conn_loop, args=(conn,),
                                 name="tpudist-live-conn", daemon=True)
            t.start()
            self._threads.append(t)

    def _conn_loop(self, conn: socket.socket) -> None:
        dec = FrameDecoder()
        try:
            while not self._stop.is_set():
                data = conn.recv(65536)
                if not data:
                    break
                for rec in dec.feed(data):
                    self.ingest(rec)
                self.bad_frames += dec.bad
                dec.bad = 0
        except Exception:
            pass
        finally:
            try:
                conn.close()
            except Exception:
                pass

    def _udp_loop(self, udp: socket.socket) -> None:
        while not self._stop.is_set():
            try:
                data, _ = udp.recvfrom(65536)
            except OSError:
                return
            dec = FrameDecoder()
            for rec in dec.feed(data):
                self.ingest(rec)
            self.bad_frames += dec.bad

    def close(self) -> None:
        """Final status write + teardown. Deliberately NO stall
        evaluation here: a run in orderly shutdown is not stalled."""
        self._stop.set()
        for s in self._servers:
            try:
                s.close()
            except Exception:
                pass
        # unblock the per-connection reader threads too: a thread parked
        # in recv() on a still-open worker connection would otherwise
        # eat its full join timeout below — shutdown must stay O(1), not
        # O(workers)
        for c in self._conns:
            try:
                c.close()
            except Exception:
                pass
        self._rebuild(force_write=True)
        if self._alerts_fh is not None:
            try:
                self._alerts_fh.close()
            except Exception:
                pass
            self._alerts_fh = None
        for t in self._threads:
            t.join(timeout=1.0)


# --------------------------------------------------- prometheus text

_PROM_HELP = {
    "tpudist_up": "Live aggregator is running.",
    "tpudist_info": "Run identity (labels carry run_id and attempt).",
    "tpudist_run_info": "Info-style run/attempt identity: join scrapes "
                        "from different requeue attempts of one run_id "
                        "on these labels.",
    "tpudist_step": "Last global step seen on the metrics stream.",
    "tpudist_epoch": "Last epoch seen on the metrics stream.",
    "tpudist_steps_per_sec": "Pod steps/s (last measured).",
    "tpudist_steps_per_sec_window": "Pod steps/s over the rolling "
                                    "window.",
    "tpudist_loss": "Last training loss.",
    "tpudist_staging_overlap_fraction": "Staging overlap fraction "
                                        "(1.0 = all H2D hidden).",
    "tpudist_exposed_comm_fraction": "Exposed-communication fraction "
                                     "of the device window.",
    "tpudist_dcn_bytes_total": "Per-step cross-slice (DCN) collective "
                               "bytes, derived from the lowered "
                               "program.",
    "tpudist_straggler_ratio": "Worst host step time over pod median.",
    "tpudist_goodput_fraction": "Attempt-local productive fraction of "
                                "wall clock (run-end estimate; the "
                                "cross-attempt ledger refines it).",
    "tpudist_hbm_bytes": "Per-device HBM bytes per memory-ledger "
                         "bucket (the partition sums to device HBM).",
    "tpudist_hbm_total_bytes": "Device HBM size the memory ledger "
                               "partitions.",
    "tpudist_hbm_headroom_fraction": "Unattributed free fraction of "
                                     "device HBM (obs.memledger).",
    "tpudist_memledger_exact": "1 when the ledger's watermark "
                               "reconciliation met the pinned "
                               "tolerance.",
    "tpudist_ckpt_last_enqueue_ms": "Last checkpoint enqueue cost.",
    "tpudist_ckpt_drain_ms": "Run-total checkpoint drain cost.",
    "tpudist_host_step": "Per-host last step from its heartbeat.",
    "tpudist_host_steps_per_sec": "Per-host rolling step rate.",
    "tpudist_host_progress_age_seconds": "Seconds since the host's "
                                         "step last advanced.",
    "tpudist_host_hbm_peak_bytes": "Per-host HBM high-water mark.",
    "tpudist_serve_queue_depth": "Requests waiting for a slot.",
    "tpudist_serve_active_slots": "Slots holding a live sequence.",
    "tpudist_serve_completed_total": "Requests completed so far.",
    "tpudist_serve_generated_tokens_total": "Tokens generated so far.",
    "tpudist_serve_ttft_p99_seconds": "p99 time-to-first-token.",
    "tpudist_serve_itl_p99_seconds": "p99 inter-token latency.",
    "tpudist_serve_tokens_per_sec_per_chip": "Decode throughput per "
                                             "chip.",
    "tpudist_serve_shed_total": "Arrivals turned away without service "
                                "(shed at admission + expired in "
                                "queue + rejected malformed).",
    "tpudist_serve_shed_fraction": "Shed share of all arrivals (the "
                                   "serve_shed gate's observable).",
    "tpudist_serve_adapt_level": "Graceful-degradation ladder level "
                                 "(0 = full service).",
    "tpudist_serve_kv_pages_used": "KV cache pages currently held "
                                   "(slots + shared-prefix registry).",
    "tpudist_serve_kv_pages_total": "KV cache pool capacity in pages.",
    "tpudist_serve_kv_shared_refs": "Refcounts currently held on the "
                                    "shared-prefix pages.",
    "tpudist_serve_spec_accept_rate": "Fraction of drafted tokens the "
                                      "target model accepted.",
    "tpudist_serve_ttft_seconds": "Time-to-first-token distribution "
                                  "(native histogram, fixed buckets).",
    "tpudist_serve_itl_seconds": "Inter-token latency distribution "
                                 "(native histogram, fixed buckets).",
    "tpudist_alert_firing": "1 while the named alert rule fires.",
    "tpudist_alerts_total": "Alert fire/resolve transitions so far.",
    "tpudist_records_total": "Telemetry records ingested.",
    "tpudist_bad_frames_total": "Undecodable frames dropped.",
}


def _prom_escape(v: Any) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace(
        "\n", r"\n")


def _prom_num(v: Any) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return f"{f:.10g}"


def prometheus_text(status: Dict[str, Any]) -> str:
    """Render a status doc (:meth:`LiveAggregator.snapshot`) as
    Prometheus text exposition format (version 0.0.4). Pure function —
    the golden test pins the exact output for a scripted status."""
    out: List[str] = []

    def metric(name: str, samples: List[Tuple[Dict[str, str], Any]],
               mtype: str = "gauge") -> None:
        rows = [(lbl, v) for lbl, v in samples if v is not None]
        if not rows:
            return
        out.append(f"# HELP {name} {_PROM_HELP[name]}")
        out.append(f"# TYPE {name} {mtype}")
        for lbl, v in rows:
            label_s = ",".join(f'{k}="{_prom_escape(x)}"'
                               for k, x in lbl.items())
            out.append(f"{name}{{{label_s}}} {_prom_num(v)}"
                       if label_s else f"{name} {_prom_num(v)}")

    def hist(name: str, h: Any) -> None:
        # a native histogram family from the self-describing hist
        # record the serve loop ships on every tick (per-bucket counts
        # + overflow bin; cumulated HERE into le= rows, the exposition
        # format's convention). A malformed or absent record renders
        # nothing — same None-skipping posture as metric()
        if not isinstance(h, dict):
            return
        buckets, counts = h.get("buckets"), h.get("counts")
        if (not isinstance(buckets, list) or not isinstance(counts, list)
                or len(counts) != len(buckets) + 1):
            return
        out.append(f"# HELP {name} {_PROM_HELP[name]}")
        out.append(f"# TYPE {name} histogram")
        cum = 0
        for ub, c in zip(buckets, counts):
            cum += int(c)
            out.append(f'{name}_bucket{{le="{_prom_num(ub)}"}} {cum}')
        cum += int(counts[-1])
        out.append(f'{name}_bucket{{le="+Inf"}} {cum}')
        out.append(f"{name}_sum {_prom_num(h.get('sum', 0.0))}")
        out.append(f"{name}_count {cum}")

    pod = status.get("pod", {})
    hosts = status.get("hosts", {})
    alerts = status.get("alerts", {})
    counters = status.get("counters", {})
    metric("tpudist_up", [({}, 1)])
    metric("tpudist_info", [({"run_id": status.get("run_id") or "",
                              "requeue_attempt":
                                  str(status.get("requeue_attempt", 0))},
                             1)])
    # the info-style join key for cross-attempt dashboards: scrapes
    # from different requeue attempts of one run_id join on exactly
    # these labels (tpudist_info predates it and stays for compat)
    metric("tpudist_run_info",
           [({"run_id": status.get("run_id") or "",
              "requeue_attempt":
                  str(status.get("requeue_attempt", 0))}, 1)])
    metric("tpudist_step", [({}, pod.get("step"))])
    metric("tpudist_epoch", [({}, pod.get("epoch"))])
    metric("tpudist_steps_per_sec", [({}, pod.get("steps_per_sec"))])
    metric("tpudist_steps_per_sec_window",
           [({}, pod.get("steps_per_sec_window"))])
    metric("tpudist_loss", [({}, pod.get("loss"))])
    metric("tpudist_staging_overlap_fraction",
           [({}, pod.get("staging_overlap_fraction"))])
    metric("tpudist_exposed_comm_fraction",
           [({}, pod.get("exposed_comm_frac"))])
    metric("tpudist_dcn_bytes_total",
           [({}, pod.get("dcn_bytes_total"))])
    metric("tpudist_straggler_ratio",
           [({}, pod.get("straggler_ratio"))])
    metric("tpudist_goodput_fraction",
           [({}, pod.get("goodput_fraction"))])
    ml = pod.get("memledger") or {}
    metric("tpudist_hbm_bytes",
           [({"bucket": b}, (ml.get("buckets") or {}).get(b))
            for b in ("params", "opt_state", "slabs", "kv_pool",
                      "program_temp", "headroom", "residue")])
    metric("tpudist_hbm_total_bytes", [({}, ml.get("total_hbm_bytes"))])
    metric("tpudist_hbm_headroom_fraction",
           [({}, ml.get("headroom_fraction"))])
    metric("tpudist_memledger_exact",
           [({}, (1 if ml.get("exact") else 0) if ml else None)])
    metric("tpudist_ckpt_last_enqueue_ms",
           [({}, pod.get("ckpt_last_enqueue_ms"))])
    metric("tpudist_ckpt_drain_ms", [({}, pod.get("ckpt_drain_ms"))])
    metric("tpudist_host_step",
           [({"host": pi}, h.get("step")) for pi, h in hosts.items()])
    metric("tpudist_host_steps_per_sec",
           [({"host": pi}, h.get("steps_per_sec"))
            for pi, h in hosts.items()])
    metric("tpudist_host_progress_age_seconds",
           [({"host": pi}, h.get("age_s")) for pi, h in hosts.items()])
    metric("tpudist_host_hbm_peak_bytes",
           [({"host": pi}, h.get("hbm_peak_bytes"))
            for pi, h in hosts.items()])
    sv = pod.get("serve") or {}
    metric("tpudist_serve_queue_depth", [({}, sv.get("queue_depth"))])
    metric("tpudist_serve_active_slots",
           [({}, sv.get("active_slots"))])
    metric("tpudist_serve_completed_total", [({}, sv.get("completed"))],
           mtype="counter")
    metric("tpudist_serve_generated_tokens_total",
           [({}, sv.get("generated_tokens"))], mtype="counter")
    metric("tpudist_serve_ttft_p99_seconds",
           [({}, sv.get("ttft_p99_s"))])
    metric("tpudist_serve_itl_p99_seconds", [({}, sv.get("itl_p99_s"))])
    metric("tpudist_serve_tokens_per_sec_per_chip",
           [({}, sv.get("tokens_per_sec_per_chip"))])
    metric("tpudist_serve_shed_total", [({}, sv.get("shed_total"))],
           mtype="counter")
    metric("tpudist_serve_shed_fraction",
           [({}, sv.get("shed_fraction"))])
    metric("tpudist_serve_adapt_level", [({}, sv.get("adapt_level"))])
    metric("tpudist_serve_kv_pages_used",
           [({}, sv.get("kv_pages_used"))])
    metric("tpudist_serve_kv_pages_total",
           [({}, sv.get("kv_pages_total"))])
    metric("tpudist_serve_kv_shared_refs",
           [({}, sv.get("kv_shared_refs"))])
    metric("tpudist_serve_spec_accept_rate",
           [({}, sv.get("spec_accept_rate"))])
    hist("tpudist_serve_ttft_seconds", sv.get("ttft_hist"))
    hist("tpudist_serve_itl_seconds", sv.get("itl_hist"))
    # one series per alert RULE: 1 when any (rule, host) key fires —
    # a fixed label set scrapers can alert on without knowing hosts
    firing_rules = {a["alert"] for a in alerts.get("firing", [])}
    metric("tpudist_alert_firing",
           [({"alert": r.name}, 1 if r.name in firing_rules else 0)
            for r in rules_lib.ALERT_RULES])
    metric("tpudist_alerts_total", [({}, alerts.get("events", 0))],
           mtype="counter")
    metric("tpudist_records_total", [({}, counters.get("records", 0))],
           mtype="counter")
    metric("tpudist_bad_frames_total",
           [({}, counters.get("bad_frames", 0))], mtype="counter")
    return "\n".join(out) + "\n"


# -------------------------------------------------------- http exporter


class LiveHttpServer:
    """Stdlib HTTP front of the aggregator: ``/metrics`` (Prometheus
    text format), ``/status.json`` (the raw snapshot — the tail CLI's
    source), ``/healthz``. Handlers read only
    :meth:`LiveAggregator.snapshot` — no lock shared with ingest."""

    def __init__(self, aggregator: LiveAggregator, *, port: int = 0,
                 host: str = "127.0.0.1"):
        import http.server

        agg = aggregator

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):   # noqa: N802 (stdlib API name)
                if self.path.split("?")[0] in ("/metrics", "/metrics/"):
                    body = prometheus_text(agg.snapshot()).encode()
                    ctype = ("text/plain; version=0.0.4; "
                             "charset=utf-8")
                elif self.path.split("?")[0] == "/status.json":
                    body = json.dumps(agg.snapshot(),
                                      default=str).encode()
                    ctype = "application/json"
                elif self.path.split("?")[0] == "/healthz":
                    body = b'{"ok": true}'
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):   # scrapes must not spam stdout
                pass

        self._server = http.server.ThreadingHTTPServer((host, port),
                                                       Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self.host = host
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="tpudist-live-http", daemon=True)
        self._thread.start()

    def close(self) -> None:
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:
            pass
        self._thread.join(timeout=2.0)


# ------------------------------------------------------------- run id


def resolve_run_id(process_count: int = 1) -> str:
    """The run's correlation id: ``$TPUDIST_RUN_ID`` when the launcher
    set one (it does — the SAME id then spans every requeue attempt),
    else coordinator-generated and broadcast at init so every worker
    stamps identical artifacts. Lazy jax import: the single-process and
    env paths stay usable from jax-free tooling."""
    rid = os.environ.get("TPUDIST_RUN_ID")
    if rid:
        return rid.strip()[:64]
    import uuid
    rid = uuid.uuid4().hex[:12]
    if process_count <= 1:
        return rid
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import multihost_utils
    row = np.frombuffer(rid.encode("ascii"), np.uint8)
    rows = np.asarray(multihost_utils.process_allgather(
        jnp.asarray(row))).reshape(process_count, -1)
    return rows[0].tobytes().decode("ascii")


# ------------------------------------------------------------ run facade


class LiveRun:
    """The train loop's one live-telemetry handle: the coordinator gets
    the aggregator + HTTP exporter, every process gets an emitter back
    to the coordinator. ``--live off`` never constructs one — the
    disabled path is the absence of this object."""

    def __init__(self, *, aggregator: Optional[LiveAggregator] = None,
                 exporter: Optional[LiveHttpServer] = None,
                 emitter: Optional[TelemetryEmitter] = None,
                 endpoint: Optional[str] = None):
        self.aggregator = aggregator
        self.exporter = exporter
        self.emitter = emitter
        self.endpoint = endpoint

    @classmethod
    def start(cls, *, is_coordinator: bool, process_index: int,
              out_dir: str, run_id: Optional[str] = None,
              requeue_attempt: int = 0, port: int = 0,
              endpoint: Optional[str] = None,
              stall_timeout_s: Optional[float] = None,
              metrics: Any = None) -> "LiveRun":
        """Wire this process's live pieces. With no explicit endpoint
        (single-host runs, CI) the coordinator binds loopback on an
        ephemeral port and talks to itself — the SAME socket path a pod
        exercises, not a shortcut. The launcher passes
        ``TPUDIST_LIVE_ENDPOINT=<coordinator>:<port>`` so workers on
        other hosts reach the aggregator; the coordinator then binds
        all interfaces on that port."""
        aggregator = exporter = emitter = None
        if is_coordinator:
            aggregator = LiveAggregator(
                out_dir=out_dir, run_id=run_id,
                requeue_attempt=requeue_attempt,
                stall_timeout_s=stall_timeout_s, metrics=metrics)
            bind_host, bind_port = "127.0.0.1", 0
            if endpoint:
                _, (_, bind_port) = parse_endpoint(endpoint)
                bind_host = "0.0.0.0"
            actual = aggregator.serve_ingest(host=bind_host,
                                             port=bind_port)
            exporter = LiveHttpServer(
                aggregator, port=port,
                host="0.0.0.0" if endpoint else "127.0.0.1")
            if not endpoint:
                endpoint = f"127.0.0.1:{actual}"
        if endpoint:
            emitter = TelemetryEmitter(endpoint)
        return cls(aggregator=aggregator, exporter=exporter,
                   emitter=emitter, endpoint=endpoint)

    def emit(self, rec: Dict[str, Any]) -> None:
        if self.emitter is not None:
            self.emitter.emit(rec)

    def snapshot_fields(self) -> Optional[Dict[str, Any]]:
        """The aggregator's last rolling-window snapshot, for the
        flight recorder's pre-kill dump (lock-free — see
        :meth:`LiveAggregator.snapshot`); None off-coordinator."""
        if self.aggregator is None:
            return None
        return self.aggregator.snapshot()

    def close(self, drain_s: float = 1.0) -> None:
        """Emitter drain first (its tail records must reach the
        aggregator), then a short settle for in-flight frames, then the
        aggregator's final status write. Every wait is bounded: run
        exit must not hang on telemetry."""
        if self.emitter is not None:
            self.emitter.close(drain_s=drain_s)
        if self.aggregator is not None:
            deadline = time.monotonic() + drain_s
            seen = -1
            while time.monotonic() < deadline:
                n = self.aggregator.records
                if n == seen:
                    break
                seen = n
                time.sleep(0.05)
            self.aggregator.close()
        if self.exporter is not None:
            self.exporter.close()


# ------------------------------------------------------------- tail CLI


def render_status(status: Dict[str, Any]) -> str:
    """The terminal dashboard body for one status doc. Pure text (the
    tail loop adds the screen-clear), pinned by the CLI e2e test."""
    import datetime
    pod = status.get("pod", {})
    alerts = status.get("alerts", {})
    ts = status.get("ts")
    when = (datetime.datetime.fromtimestamp(ts).strftime(
        "%Y-%m-%d %H:%M:%S") if ts else "-")
    lines = [
        f"tpudist live · run {status.get('run_id') or '?'} · attempt "
        f"{status.get('requeue_attempt', 0)} · status "
        f"{(status.get('status') or '?').upper()} · {when}"]

    def fmt(v, spec="{:.2f}", none="-"):
        return spec.format(v) if isinstance(v, (int, float)) else none

    lines.append(
        f"pod: step {pod.get('step') if pod.get('step') is not None else '-'}"
        f" epoch {pod.get('epoch') if pod.get('epoch') is not None else '-'}"
        f" · {fmt(pod.get('steps_per_sec'))} steps/s"
        f" · loss {fmt(pod.get('loss'), '{:.4f}')}"
        f" · staging overlap {fmt(pod.get('staging_overlap_fraction'))}"
        f" · exposed comm {fmt(pod.get('exposed_comm_frac'), '{:.1%}')}")
    hosts = status.get("hosts", {})
    if hosts:
        lines.append(f"{'host':>4}  {'step':>8}  {'epoch':>5}  "
                     f"{'phase':<10} {'steps/s':>8}  {'age':>6}")
        for pi, h in sorted(hosts.items(), key=lambda kv: int(kv[0])):
            lines.append(
                f"{pi:>4}  "
                f"{h.get('step') if h.get('step') is not None else '-':>8}  "
                f"{h.get('epoch') if h.get('epoch') is not None else '-':>5}"
                f"  {h.get('phase') or '-':<10} "
                f"{fmt(h.get('steps_per_sec')):>8}  "
                f"{fmt(h.get('age_s'), '{:.1f}s'):>6}")
    sv = pod.get("serve")
    if sv:
        # the serving pod's vitals, one row (plus KV/spec detail only
        # when the paged plane reported it): a serve run tailed with
        # this dashboard previously rendered as an idle TRAIN pod
        line = (f"serve: {fmt(sv.get('tokens_per_sec_per_chip'))} "
                f"tok/s/chip"
                f" · queue {sv.get('queue_depth') if sv.get('queue_depth') is not None else '-'}"
                f" · active {sv.get('active_slots') if sv.get('active_slots') is not None else '-'}"
                f" · done {sv.get('completed') if sv.get('completed') is not None else '-'}"
                f" · shed {fmt(sv.get('shed_fraction'), '{:.1%}')}"
                f" · ttft p99 {fmt(sv.get('ttft_p99_s'), '{:.3f}s')}"
                f" · itl p99 {fmt(sv.get('itl_p99_s'), '{:.3f}s')}")
        if sv.get("kv_pages_total") is not None:
            used = sv.get("kv_pages_used")
            line += (f" · kv pages "
                     f"{used if used is not None else '-'}"
                     f"/{sv.get('kv_pages_total')}")
        if sv.get("spec_accept_rate") is not None:
            line += (f" · spec accept "
                     f"{fmt(sv.get('spec_accept_rate'), '{:.1%}')}")
        lines.append(line)
    firing = alerts.get("firing", [])
    if firing:
        lines.append("ALERTS FIRING:")
        for a in firing:
            host = f" host{a['host']}" if a.get("host") is not None else ""
            lines.append(
                f"  [{a['alert']}]{host} value {a.get('value'):.4g} vs "
                f"threshold {a.get('threshold'):.4g} "
                f"(for {a.get('duration_s', 0):.1f}s, since step "
                f"{a.get('first_step')})")
    else:
        lines.append("alerts: none firing")
    resolved = [a for a in alerts.get("history", [])
                if a.get("state") == alerts_lib.RESOLVED]
    for a in resolved[-3:]:
        host = f" host{a['host']}" if a.get("host") is not None else ""
        lines.append(f"  [resolved] {a['alert']}{host}: fired at step "
                     f"{a.get('first_step')}, lasted "
                     f"{a.get('duration_s', 0):.1f}s")
    return "\n".join(lines)


def _fetch_status(status_path: Optional[str],
                  url: Optional[str]) -> Optional[Dict[str, Any]]:
    if url:
        import urllib.request
        try:
            with urllib.request.urlopen(url, timeout=2.0) as r:
                return json.loads(r.read())
        except Exception:
            return None
    try:
        with open(status_path or STATUS_NAME) as f:
            return json.load(f)
    except Exception:
        return None


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import sys
    p = argparse.ArgumentParser(
        prog="python -m tpudist.obs.live",
        description="live pod telemetry tools (jax-free)")
    sub = p.add_subparsers(dest="cmd", required=True)
    tail = sub.add_parser(
        "tail", help="terminal dashboard over live_status.json or the "
                     "aggregator's /status.json")
    tail.add_argument("--status", type=str, default=None,
                      help=f"status file to render (default: "
                           f"./{STATUS_NAME})")
    tail.add_argument("--url", type=str, default=None,
                      help="poll the aggregator instead, e.g. "
                           "http://coordinator:9109/status.json")
    tail.add_argument("--interval", type=float, default=2.0,
                      help="refresh period in seconds (default 2)")
    tail.add_argument("--once", action="store_true",
                      help="render one frame and exit (scripts/tests)")
    args = p.parse_args(argv)

    if args.cmd == "tail":
        while True:
            status = _fetch_status(args.status, args.url)
            if status is None:
                src = args.url or args.status or STATUS_NAME
                print(f"tpudist.obs.live: no status at {src}",
                      file=sys.stderr)
                if args.once:
                    return 2
            else:
                if not args.once and sys.stdout.isatty():
                    print("\x1b[2J\x1b[H", end="")
                print(render_status(status), flush=True)
                if args.once:
                    return 0
            time.sleep(args.interval)
    return 2


if __name__ == "__main__":
    import sys
    sys.exit(main())
