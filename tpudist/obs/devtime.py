"""Device-time attribution: compute vs exposed communication.

Everything the observability stack records so far is HOST wall-clock:
the tracer's ``fence`` span lumps device compute, collective time, and
straggler wait into one number, so "exposed-communication time per
phase must drop" (ROADMAP item 3's acceptance signal) cannot be
measured. This module is the first layer that sees what the CHIP did:

  * a **jax-free parser** for the Chrome trace-event JSON that
    ``jax.profiler`` already writes per worker
    (``plugins/profile/*/*.trace.json.gz`` — stdlib ``gzip`` + ``json``,
    no TensorBoard, no xprof): device-timeline ops are classified into
    compute vs collective communication by HLO op name;
  * **interval math** that computes *exposed* communication — comm time
    NOT overlapped by compute on the same device track — by interval
    subtraction. The decomposition is exact and mutually exclusive:
    ``compute + exposed_comm + idle == window`` per device (comm that
    overlaps compute is *hidden* and counts as compute time, which is
    precisely what overlap optimisations buy);
  * a :class:`WindowProfiler` capture mode (``--profile-window N`` /
    ``TPUDIST_PROFILE_WINDOW``): N mid-run supersteps captured on every
    worker into ``profile/worker<i>`` and ingested automatically at run
    end — cheap enough to leave on for acceptance runs, unlike the
    full-run ``--profile-dir`` which stays a manual debug tool (and is
    the only capture mode that still disables autotuning);
  * the three consumers: a ``kind=devtime`` metrics record, device
    tracks merged under each host's row in ``pod_trace.json`` (the
    capture's timestamps share ``perf_counter``'s timebase, so PR 5's
    clock-offset machinery aligns them across hosts for free), and the
    run report's "Device time" section with per-phase exposed-comm
    attribution and a ``comm_status`` verdict
    (``TPUDIST_COMM_EXPOSED_MAX``).

The parser half of this module MUST stay importable without jax —
``tpudist.obs.report`` runs on a laptop against scp'd artifacts. All
jax use lives inside :class:`WindowProfiler` methods (lazy imports).
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

# tpudist.verdict is import-safe on the jax-free offline path (its jax
# uses are lazy), so the status vocabulary has one home
from tpudist import rules as rules_lib
from tpudist.verdict import FAIL, SUCCESS, UNGATEABLE

Interval = Tuple[float, float]

# ------------------------------------------------------- classification

# Collective-communication HLO ops (async -start/-done variants and
# fusions embedding them match too): the names XLA gives the device
# timeline on TPU ("all-reduce.3"), GPU ("ncclAllReduce...") and the
# CPU thunk runtime ("all-reduce.1"). "megascale" covers the TPU
# multi-slice DCN transfer ops.
_COMM_RE = re.compile(
    r"(?:^|[^a-z])(all-reduce|all-gather|all-to-all|reduce-scatter|"
    r"collective-permute|collective-broadcast|ragged-all-to-all|"
    r"send|recv|megascale|nccl)", re.IGNORECASE)

# Runtime/infra timeline entries that are neither compute nor comm:
# C++ scopes ("ThunkExecutor::Execute"), the profiler's python tracer
# ("$builtins isinstance"), and dispatch bookkeeping. An HLO op name is
# a bare identifier — letters/digits/_/-/. only.
_OP_NAME_RE = re.compile(r"^[A-Za-z][A-Za-z0-9_.\-]*$")


def classify(name: str) -> Optional[str]:
    """``"comm"`` / ``"compute"`` for device ops, ``None`` for runtime
    noise that must not count toward device busy time."""
    if not name or not _OP_NAME_RE.match(name):
        return None
    return "comm" if _COMM_RE.search(name) else "compute"


# ------------------------------------- program-derived collective bytes
#
# CPU wall-clock cannot honestly measure a DCN-byte win (PR 12's
# observer-effect lesson), but the LOWERED PROGRAM states it exactly:
# every collective op carries its payload tensor type and its replica
# groups, and the mesh knows which device ids share a slice. Parsing
# the StableHLO text (engine's ``.lowered_text()`` hook) therefore
# yields per-collective byte volumes per fabric as program facts — the
# hierarchical schedule's bytes-over-DCN cut is asserted from these
# rows, never from timing. Stays jax-free like the rest of the parser
# half: tests and the offline report feed it saved text.

# StableHLO collective ops (MLIR spelling — underscores, unlike the
# device-timeline HLO names above).
_COLLECTIVE_OPS = ("all_reduce", "reduce_scatter", "all_gather",
                   "all_to_all", "collective_permute",
                   "collective_broadcast")
_COLLECTIVE_RE = re.compile(
    r"stablehlo\.(" + "|".join(_COLLECTIVE_OPS) + r")\b")
_DENSE_RE = {
    attr: re.compile(attr + r"\s*=\s*dense<(.*?)>\s*:\s*tensor<([0-9x]*)",
                     re.DOTALL)
    for attr in ("replica_groups", "source_target_pairs")
}
# an op's type signature: "(operands) -> result" — on the op's own line
# for region-free ops, on the "}) : (...)" closing line for the
# region-carrying reduces
_SIG_RE = re.compile(r":\s*\(([^()]*)\)\s*->\s*\(?\s*(tensor<[^>]*>)")
_TENSOR_RE = re.compile(r"tensor<([^>]*)>")

_MLIR_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "i64": 8, "ui64": 8, "i32": 4, "ui32": 4, "i16": 2, "ui16": 2,
    "i8": 1, "ui8": 1, "i1": 1,
    "f8E4M3FN": 1, "f8E5M2": 1, "f8E4M3": 1, "f8E3M4": 1,
}


def _tensor_bytes(ty: str) -> Tuple[int, str]:
    """``"2x11xf32"`` -> (88, "f32"); scalar ``"f32"`` -> (4, "f32")."""
    parts = ty.strip().split("x")
    dtype = parts[-1]
    n = 1
    for p in parts[:-1]:
        n *= int(p)
    return n * _MLIR_DTYPE_BYTES.get(dtype, 4), dtype


def _parse_dense(attr: str, window: str) -> Optional[List[List[int]]]:
    """An MLIR dense int attribute -> list of rows. Handles the
    explicit nested-list form and the splat form (``dense<0>`` with
    the row shape taken from the tensor type)."""
    m = _DENSE_RE[attr].search(window)
    if not m:
        return None
    body, shape = m.group(1).strip(), m.group(2)
    dims = [int(d) for d in shape.split("x") if d]
    if body.startswith("["):
        rows = re.findall(r"\[([^\[\]]*)\]", body)
        return [[int(v) for v in r.split(",") if v.strip()] for r in rows]
    # splat: one value repeated over the whole shape
    v = int(body)
    n_rows = dims[0] if dims else 1
    n_cols = dims[1] if len(dims) > 1 else 1
    return [[v] * n_cols for _ in range(n_rows)]


def collective_bytes(text: str, device_slices: Sequence[int]
                     ) -> Dict[str, Any]:
    """Per-collective byte accounting of a lowered StableHLO module.

    ``device_slices[i]`` is the slice of device id ``i`` in the
    program's device assignment (``mesh.mesh_device_slices`` — the id
    space ``replica_groups``/``source_target_pairs`` index into).

    Returns ``{"ops": [row...], "dcn_bytes_total", "ici_bytes_total",
    "n_collectives"}``. Each row aggregates identical ops: ``op``,
    ``dtype``, ``bytes`` (payload per instance — the larger of operand
    and result tensors, i.e. the full vector a reduce-scatter consumes
    or an all-gather produces), ``count``, ``fabric`` (``dcn`` when any
    replica group spans slices, ``mixed`` for a permute with both kinds
    of edge), and ``dcn_bytes`` (total over ``count``: payload × the
    number of participants whose traffic crosses slices — for group
    collectives every member of a slice-spanning group, for a permute
    each slice-crossing source→target edge). The convention prices a
    participant's payload once per instance, so the hierarchical ladder's
    cross-slice all-reduce (1/slice_size shard, all N participants)
    lands at exactly 1/slice_size of the flat schedule's — the relation
    the acceptance tests pin. A ``lax.scan`` body lowers once, so rows
    approximate per-step volumes regardless of superstep length."""
    slices = list(device_slices)
    agg: Dict[tuple, Dict[str, Any]] = {}
    lines = text.splitlines()
    for i, line in enumerate(lines):
        m = _COLLECTIVE_RE.search(line)
        if not m:
            continue
        op = m.group(1)
        sig = _SIG_RE.search(line)
        j = i
        while sig is None and j + 1 < len(lines) and j - i < 50:
            # region-carrying op (all_reduce / reduce_scatter): the
            # type signature lives on the "}) : (...)" closing line
            j += 1
            if "}) :" in lines[j]:
                sig = _SIG_RE.search(lines[j])
                break
        if sig is None:
            continue
        operand_tys = _TENSOR_RE.findall(sig.group(1))
        result_ty = _TENSOR_RE.search(sig.group(2))
        tys = operand_tys + ([result_ty.group(1)] if result_ty else [])
        if not tys:
            continue
        sized = [_tensor_bytes(t) for t in tys]
        payload, dtype = max(sized, key=lambda s: s[0])
        groups = _parse_dense("replica_groups", line)
        pairs = _parse_dense("source_target_pairs", line)
        fabric = "ici"
        dcn_participants = 0
        if pairs is not None:
            crossing = sum(1 for p in pairs if len(p) == 2
                           and _crosses(p, slices))
            dcn_participants = crossing
            if crossing == len(pairs) and pairs:
                fabric = "dcn"
            elif crossing:
                fabric = "mixed"
        elif groups is not None:
            for g in groups:
                if _crosses(g, slices):
                    dcn_participants += len(g)
            if dcn_participants:
                fabric = "dcn"
        key = (op, dtype, payload, fabric, dcn_participants)
        row = agg.setdefault(key, {
            "op": op, "dtype": dtype, "bytes": payload, "count": 0,
            "fabric": fabric, "dcn_bytes": 0})
        row["count"] += 1
        row["dcn_bytes"] += payload * dcn_participants
    ops = sorted(agg.values(),
                 key=lambda r: (-r["dcn_bytes"], -r["bytes"], r["op"]))
    dcn_total = sum(r["dcn_bytes"] for r in ops)
    ici_total = sum(r["bytes"] * r["count"] for r in ops
                    if r["fabric"] == "ici")
    return {"ops": ops, "dcn_bytes_total": dcn_total,
            "ici_bytes_total": ici_total,
            "n_collectives": sum(r["count"] for r in ops)}


def _crosses(ids: Sequence[int], slices: List[int]) -> bool:
    """True when the id group spans more than one slice (out-of-range
    ids — a program lowered for a larger world than the slice table —
    read as slice 0, the conservative single-slice answer)."""
    seen = set()
    for d in ids:
        seen.add(slices[d] if 0 <= d < len(slices) else 0)
        if len(seen) > 1:
            return True
    return False


# -------------------------------------------------------- interval math


def merge_intervals(intervals: Sequence[Interval]) -> List[Interval]:
    """Sorted disjoint union of ``intervals`` (zero-length dropped)."""
    ivs = sorted((lo, hi) for lo, hi in intervals if hi > lo)
    out: List[Interval] = []
    for lo, hi in ivs:
        if out and lo <= out[-1][1]:
            if hi > out[-1][1]:
                out[-1] = (out[-1][0], hi)
        else:
            out.append((lo, hi))
    return out


def measure(intervals: Sequence[Interval]) -> float:
    """Total length of a DISJOINT interval list."""
    return sum(hi - lo for lo, hi in intervals)


def subtract_intervals(a: Sequence[Interval],
                       b: Sequence[Interval]) -> List[Interval]:
    """``a \\ b`` — the parts of ``a`` not covered by ``b`` (both are
    union-normalised first). This IS the exposed-communication
    operator: ``subtract(comm, compute)``."""
    a = merge_intervals(a)
    b = merge_intervals(b)
    out: List[Interval] = []
    j = 0
    for lo, hi in a:
        cur = lo
        while j < len(b) and b[j][1] <= cur:
            j += 1
        k = j
        while k < len(b) and b[k][0] < hi:
            blo, bhi = b[k]
            if blo > cur:
                out.append((cur, blo))
            cur = max(cur, bhi)
            if cur >= hi:
                break
            k += 1
        if cur < hi:
            out.append((cur, hi))
    return out


def intersect_intervals(a: Sequence[Interval],
                        b: Sequence[Interval]) -> List[Interval]:
    """``a ∩ b`` (union-normalised)."""
    a = merge_intervals(a)
    b = merge_intervals(b)
    out: List[Interval] = []
    i = j = 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            out.append((lo, hi))
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return out


# ------------------------------------------------------ capture parsing


def find_captures(capture_dir: str) -> List[str]:
    """The trace-event JSON files under a ``jax.profiler`` capture dir
    (``plugins/profile/<session>/<host>.trace.json.gz``)."""
    pats = (os.path.join(capture_dir, "**", "*.trace.json.gz"),
            os.path.join(capture_dir, "**", "*.trace.json"))
    out: List[str] = []
    for p in pats:
        out.extend(glob.glob(p, recursive=True))
    return sorted(out)


def load_capture_doc(path: str) -> Dict[str, Any]:
    """One capture file → the Chrome trace-event document (stdlib gzip +
    json; no protobuf, no TensorBoard)."""
    if path.endswith(".gz"):
        with gzip.open(path, "rb") as f:
            return json.load(f)
    with open(path) as f:
        return json.load(f)


def device_op_tracks(doc: Dict[str, Any]
                     ) -> Dict[str, List[Tuple[float, float, str]]]:
    """Device-timeline op intervals per device track:
    ``{device_name: [(t0_us, t1_us, op_name), ...]}``.

    On TPU/GPU the profiler emits one PROCESS per device
    (``/device:TPU:0``) whose "XLA Ops" thread carries the op events —
    each such pid is one track. The CPU backend has no device
    processes; its op events land on the PJRT client's pool threads
    (``tf_XLATfrtCpuClient/*`` — and, under the thunk runtime newer
    jaxlibs use, the Eigen compute pool ``tf_XLAEigen/*``, where the
    HLO op events actually live) inside the ``/host:CPU`` process, so
    all of them fold into ONE synthetic track per host process (the
    virtual devices share the pool — per-device attribution is a
    hardware concept; the CPU track exists so the plumbing is testable
    end-to-end without a TPU).
    """
    proc_names: Dict[Any, str] = {}
    thread_names: Dict[Tuple[Any, Any], str] = {}
    for e in doc.get("traceEvents", []):
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            proc_names[e.get("pid")] = e.get("args", {}).get("name", "")
        elif e.get("name") == "thread_name":
            thread_names[(e.get("pid"), e.get("tid"))] = \
                e.get("args", {}).get("name", "")

    device_pids = {pid: name.split("/device:", 1)[1]
                   for pid, name in proc_names.items()
                   if name.startswith("/device:")}
    # device pids with an "XLA Ops" thread: only those threads are op
    # executions (the "Steps"/"XLA Modules" threads carry step numbers
    # and whole-module windows that would double-count)
    xla_ops_pids = {pid for (pid, tid), tn in thread_names.items()
                    if pid in device_pids and "XLA Ops" in tn}

    tracks: Dict[str, List[Tuple[float, float, str]]] = {}
    for e in doc.get("traceEvents", []):
        if e.get("ph") != "X" or "ts" not in e or "dur" not in e:
            continue
        pid, tid = e.get("pid"), e.get("tid")
        name = e.get("name", "")
        if pid in device_pids:
            tn = thread_names.get((pid, tid), "")
            if pid in xla_ops_pids and "XLA Ops" not in tn:
                continue
            if classify(name) is None:
                continue
            track = device_pids[pid]
        else:
            tn = thread_names.get((pid, tid), "")
            if not tn.startswith(("tf_XLATfrtCpuClient", "tf_XLAEigen")):
                continue
            if classify(name) is None:
                continue
            track = proc_names.get(pid, "/host:CPU").lstrip("/") or "host"
        t0 = float(e["ts"])
        tracks.setdefault(track, []).append((t0, t0 + float(e["dur"]),
                                             name))
    return tracks


# ---------------------------------------------------------- attribution


def attribute_classed(classed: Dict[str, List[Interval]],
                      window: Optional[Interval] = None) -> Dict[str, Any]:
    """One device track's compute/comm interval unions → the exact,
    mutually exclusive decomposition (all times in SECONDS, inputs µs):

        compute_s + exposed_comm_s + idle_s == window_s

    ``comm_s`` is the TOTAL collective time (for "how much comm is
    there"); ``exposed_comm_s = comm \\ compute`` is the part the
    schedule failed to hide — the number overlap work must drive down.
    """
    compute = merge_intervals(classed.get("compute", []))
    comm = merge_intervals(classed.get("comm", []))
    if window is None:
        allv = compute + comm
        window = ((min(lo for lo, _ in allv), max(hi for _, hi in allv))
                  if allv else (0.0, 0.0))
    win_us = max(0.0, window[1] - window[0])
    exposed = subtract_intervals(comm, compute)
    busy = merge_intervals(compute + comm)
    compute_us = measure(compute)
    comm_us = measure(comm)
    exposed_us = measure(exposed)
    idle_us = max(0.0, win_us - measure(busy))
    out = {
        "window_s": win_us / 1e6,
        "compute_s": compute_us / 1e6,
        "comm_s": comm_us / 1e6,
        "exposed_comm_s": exposed_us / 1e6,
        "idle_s": idle_us / 1e6,
    }
    if win_us > 0:
        out["compute_frac"] = compute_us / win_us
        out["exposed_comm_frac"] = exposed_us / win_us
        out["idle_frac"] = idle_us / win_us
    else:
        out["compute_frac"] = out["exposed_comm_frac"] = None
        out["idle_frac"] = None
    return out


def attribute_tracks(tracks: Dict[str, List[Tuple[float, float, str]]]
                     ) -> Dict[str, Any]:
    """All device tracks of one capture → per-device attribution plus
    the per-class interval unions (the merged-trace export reuses
    them). The idle window is the CAPTURE-wide op extent, shared by
    every track, so a device idling while its peers compute reads as
    idle — the straggler signature."""
    classed: Dict[str, Dict[str, List[Interval]]] = {}
    lo = hi = None
    for name, ops in tracks.items():
        c = classed.setdefault(name, {"compute": [], "comm": []})
        for t0, t1, op in ops:
            cls = classify(op)
            if cls is None:
                continue
            c[cls].append((t0, t1))
            lo = t0 if lo is None else min(lo, t0)
            hi = t1 if hi is None else max(hi, t1)
    window = (lo, hi) if lo is not None else None
    devices = {name: attribute_classed(c, window)
               for name, c in sorted(classed.items())}
    intervals = {name: {cls: merge_intervals(iv)
                        for cls, iv in c.items()}
                 for name, c in classed.items()}
    pod = {
        "devices": len(devices),
        "window_s": (max(0.0, (window[1] - window[0]) / 1e6)
                     if window else 0.0),
        "compute_s": sum(d["compute_s"] for d in devices.values()),
        "comm_s": sum(d["comm_s"] for d in devices.values()),
        "exposed_comm_s": sum(d["exposed_comm_s"]
                              for d in devices.values()),
    }
    denom = pod["window_s"] * max(len(devices), 1)
    pod["exposed_comm_frac"] = (pod["exposed_comm_s"] / denom
                                if denom > 0 else None)
    return {"devices": devices, "intervals": intervals, "pod": pod,
            "window_us": window}


def analyze_capture(capture_dir: str) -> Dict[str, Any]:
    """Parse every capture file under ``capture_dir`` and attribute
    device time. Raises ``FileNotFoundError`` when the dir holds no
    trace-event JSON (an aborted capture)."""
    paths = find_captures(capture_dir)
    if not paths:
        raise FileNotFoundError(
            f"no *.trace.json(.gz) under {capture_dir}")
    tracks: Dict[str, List[Tuple[float, float, str]]] = {}
    for p in paths:
        for name, ops in device_op_tracks(load_capture_doc(p)).items():
            tracks.setdefault(name, []).extend(ops)
    out = attribute_tracks(tracks)
    out["capture_files"] = paths
    return out


# ------------------------------------------------------------ verdict

# Exposed-communication gate: above this fraction of the device window
# spent on UN-hidden collectives, the run is flagged — the pod is
# paying for its fabric in steps/s. Advisory, like the staging and
# straggler gates; env override TPUDIST_COMM_EXPOSED_MAX (call time).
# The threshold itself lives in tpudist.rules, shared with the live
# alert engine so mid-run and at-exit grading cannot drift. DCN-labeled
# rows (a data axis crossing slices) grade against their own ceiling.
COMM_EXPOSED_MAX = rules_lib.COMM_EXPOSED_MAX
COMM_EXPOSED_MAX_DCN = rules_lib.COMM_EXPOSED_MAX_DCN


def comm_status(exposed_frac: Optional[float],
                max_frac: Optional[float] = None,
                fabric: Optional[str] = None) -> str:
    """Three-valued exposed-communication verdict: UNGATEABLE when no
    device window was measured (capture off or empty), else
    SUCCESS/FAIL by whether the exposed-comm fraction of the device
    window stays under the threshold. ``fabric`` selects the per-fabric
    default (``tpudist.rules.resolve_comm``): a data axis crossing
    slices grades against the DCN ceiling
    (``TPUDIST_COMM_EXPOSED_MAX_DCN``) — a slower fabric honestly costs
    more exposure before the run is flagged — while ICI rows keep
    ``TPUDIST_COMM_EXPOSED_MAX``. An explicit ``max_frac`` wins."""
    if max_frac is None:
        max_frac = rules_lib.resolve_comm(fabric)
    if exposed_frac is None:
        return UNGATEABLE
    return SUCCESS if exposed_frac <= max_frac else FAIL


# --------------------------------------------- merged-trace device rows

# Device tracks ride under each host's pid in pod_trace.json on
# synthetic tids far above the tracer's per-thread ids.
DEVICE_TID_BASE = 1000
DEVTIME_CAT = "devtime"


def device_events(analysis: Dict[str, Any], *, process_index: int,
                  anchor_us: float) -> List[Dict[str, Any]]:
    """The capture's per-class busy intervals as Chrome trace events for
    the pod merge: one synthetic thread per device track under the
    host's pid, events named ``compute``/``comm`` over the merged
    interval unions (coalesced — per-op events would bloat
    ``pod_trace.json`` by orders of magnitude and add nothing the
    report's interval math needs).

    ``anchor_us`` is the host's ``perf_counter_ns()/1e3`` sampled
    immediately before ``start_trace``: the profiler stamps event
    timestamps relative to session start on the same monotonic clock,
    so ``anchor_us + ts`` lands the device ops on the host tracer's
    timebase and the existing clock-offset merge aligns them pod-wide.
    """
    out: List[Dict[str, Any]] = []
    for i, (name, classed) in enumerate(sorted(
            analysis["intervals"].items())):
        tid = DEVICE_TID_BASE + i
        out.append({"ph": "M", "name": "thread_name",
                    "pid": process_index, "tid": tid,
                    "args": {"name": f"device:{name}"}})
        for cls in ("compute", "comm"):
            for lo, hi in classed.get(cls, []):
                out.append({"name": cls, "cat": DEVTIME_CAT, "ph": "X",
                            "ts": anchor_us + lo, "dur": hi - lo,
                            "pid": process_index, "tid": tid,
                            "args": {"device": name}})
    return out


# ------------------------------------------------------ window capture


class WindowProfiler:
    """``--profile-window N``: capture N mid-run supersteps with
    ``jax.profiler`` into ``<out_dir>/worker<i>`` and hand the capture
    to :func:`analyze_capture` at run end.

    Unlike full-run ``--profile-dir`` (a manual debug tool that forces
    per-step dispatch and disables autotuning), the window is cheap and
    composes with everything: it arms at the MIDDLE epoch's first
    dispatch (steady state — compile and staging fill are over), counts
    dispatches, fences once, and stops. The only perturbation is the
    capture overhead inside the window plus that one fence; device math
    is untouched, so step losses stay bitwise-identical to an
    uncaptured run (pinned in tests).

    Thread-safety: the stall watchdog calls :meth:`emergency_stop` from
    its own thread when a run hangs with the window open — the partial
    capture is kept next to the flight record, so even a hung run
    yields a device timeline. ``_stop`` is guarded by a lock and never
    fences (the fence happens in :meth:`note_dispatch` BEFORE the lock,
    so a wedged device cannot deadlock the watchdog against the main
    thread).
    """

    def __init__(self, out_dir: str, n_dispatches: int, *,
                 process_index: int = 0, trigger_epoch: int = 0):
        if n_dispatches < 1:
            raise ValueError(
                f"profile window must be >= 1 dispatch, got {n_dispatches}")
        self.capture_dir = os.path.join(out_dir,
                                        f"worker{process_index}")
        self.n = n_dispatches
        self.trigger_epoch = trigger_epoch
        self.process_index = process_index
        self.state = "armed"            # armed -> open -> done
        self.seen = 0
        self.captured = False
        self.anchor_ns: Optional[int] = None
        self._lock = threading.Lock()
        self._span = None

    @classmethod
    def from_config(cls, cfg, *, out_dir: str,
                    process_index: int = 0) -> Optional["WindowProfiler"]:
        """``None`` when the window is off (the train loop's calls all
        no-op through a plain ``if win is not None``)."""
        from tpudist.config import resolve_profile_window
        n = resolve_profile_window(cfg)
        if n <= 0:
            return None
        # mid-run: the middle epoch's first dispatches are steady state
        # (past compile, past the first epoch's staging fill)
        return cls(os.path.join(out_dir, "profile"), n,
                   process_index=process_index,
                   trigger_epoch=max(0, cfg.epochs // 2))

    # ------------------------------------------------------ train hooks
    def maybe_start(self, epoch: int) -> None:
        """Epoch-top hook: open the capture at the trigger epoch."""
        if self.state != "armed" or epoch < self.trigger_epoch:
            return
        import jax

        from tpudist.obs import trace as trace_lib
        os.makedirs(self.capture_dir, exist_ok=True)
        self._span = trace_lib.get().begin("profile_window",
                                           cat="profile", n=self.n)
        # the anchor must be read BEFORE start_trace: the profiler
        # stamps its session epoch (the ts origin) during the call
        self.anchor_ns = time.perf_counter_ns()
        jax.profiler.start_trace(self.capture_dir)
        self.state = "open"

    def note_dispatch(self, result: Any = None) -> None:
        """Per-dispatch hook; closes the window after ``n`` dispatches.
        The fence (one host transfer) makes the captured supersteps'
        device execution actually land inside the capture — stopping
        behind async dispatch would truncate the timeline."""
        if self.state != "open":
            return
        self.seen += 1
        if self.seen < self.n:
            return
        if result is not None:
            import jax
            try:
                jax.device_get(result)
            except Exception:
                pass
        self._stop()

    def close(self) -> None:
        """Run-end backstop: a window larger than the run still stops
        cleanly (partial capture). Idempotent."""
        self._stop()

    def emergency_stop(self) -> Optional[str]:
        """Watchdog hook: stop an open capture WITHOUT fencing (the
        device may be the thing that hung) and report the capture path
        for the flight record; ``None`` when no window was open."""
        if self.state != "open":
            return None
        self._stop()
        return self.capture_dir if self.captured else None

    def _stop(self) -> None:
        with self._lock:
            if self.state != "open":
                return
            self.state = "done"
            import jax
            try:
                jax.profiler.stop_trace()
                self.captured = True
            except Exception:
                pass
            if self._span is not None:
                from tpudist.obs import trace as trace_lib
                trace_lib.get().end(self._span)
                self._span = None
