"""tpudist.obs — the pod flight recorder.

Four pieces that turn a hung or slow pod run into a diagnosis instead
of a timeout (DESIGN.md "Observability"):

  * :mod:`heartbeat` — per-process progress beacon + stall watchdog
    that dumps a flight record *before* the launcher kills the job;
  * :mod:`flightrec` — the dump itself: thread stacks, memory stats,
    last-N metrics, one JSON artifact per worker;
  * :mod:`hbm` — background HBM high-water-mark sampler;
  * :mod:`hoststats` — epoch-end per-host step-time aggregation and
    the three-valued straggler verdict;
  * :mod:`mfu` — MFU/roofline accounting from the compiled program's
    own cost analysis;
  * :mod:`trace` — host-side span tracer (ring buffers, Chrome
    trace-event export, pod-merged Perfetto timeline);
  * :mod:`devtime` — device-time attribution: the jax-free parser for
    ``jax.profiler`` captures (compute vs exposed-communication split)
    plus the ``--profile-window`` capture mode;
  * :mod:`report` — the offline run-report CLI over the merged trace
    plus ``metrics.jsonl`` (``python -m tpudist.obs.report``);
  * :mod:`goodput` — the cross-attempt goodput ledger: productive vs
    badput wall-clock across every requeue attempt of a ``run_id``
    (``python -m tpudist.obs.goodput``);
  * :mod:`memledger` — the per-device HBM ledger: program-derived
    exact bucket partition, headroom grading, and the OOM-forensics
    CLI (``python -m tpudist.obs.memledger``).

:class:`PodObserver` is the facade the train loop wires through: one
object to start, feed progress, ask for record fields, and close.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from tpudist.obs import (devtime, flightrec, hbm, heartbeat, hoststats,
                         mfu, trace)
from tpudist.obs.flightrec import dump_flight_record
from tpudist.obs.hbm import HbmSampler
from tpudist.obs.heartbeat import FlightRecorder
from tpudist.obs.hoststats import HostStepStats

__all__ = ["FlightRecorder", "HbmSampler", "HostStepStats", "PodObserver",
           "devtime", "dump_flight_record", "flightrec", "hbm",
           "heartbeat", "hoststats", "mfu", "trace"]


class PodObserver:
    """The train loop's one observability handle: flight recorder
    (beacon + watchdog), HBM watermark sampler, and per-host straggler
    tracking, started together and closed together.

    Every sub-piece is optional (``stall window 0`` / ``sample period
    0`` disable their threads) and every method degrades to a no-op
    when its piece is off — callers never branch.
    """

    def __init__(self, *, out_dir: str, stall_timeout_s: float = 300.0,
                 hbm_sample_s: float = 2.0, metrics: Any = None,
                 process_index: int = 0, process_count: int = 1,
                 stall_hook: Any = None, live: Any = None,
                 live_fields: Any = None, requeue_attempt: int = 0):
        self.hbm = (HbmSampler(period_s=hbm_sample_s)
                    if hbm_sample_s > 0 else None)
        self.hosts = HostStepStats(process_index=process_index,
                                   process_count=process_count)
        self.live = live
        # the last assembled HBM ledger (obs.memledger): the train and
        # serve loops store it here so a pre-kill flight record carries
        # the final bucket partition — the OOM forensics CLI's
        # reconstruct-from-artifacts input
        self.last_memledger: Optional[Dict[str, Any]] = None

        def _extra_state() -> Dict[str, Any]:
            # the flight-record extras: HBM watermarks, plus — on the
            # coordinator of a live run — the aggregator's last
            # rolling-window snapshot (lock-free wholesale-replaced
            # dict, obs.live), so a pre-kill dump says what the POD
            # looked like, not just this process
            out = dict(self.hbm.split()) if self.hbm is not None else {}
            if self.last_memledger is not None:
                out["memledger"] = self.last_memledger
            if live is not None:
                snap = live.snapshot_fields()
                if snap is not None:
                    out["live_status"] = snap
            return out

        def _beacon_extra() -> Dict[str, Any]:
            # live slice of the heartbeat beacon: the SAME observables
            # the exit verdict grades (staging overlap inputs, HBM
            # peak), cheap counter reads only — no fences, no jax
            out: Dict[str, Any] = {}
            if self.hbm is not None:
                out["hbm_peak_bytes"] = self.hbm.peak_in_use or None
            if live_fields is not None:
                try:
                    out.update(live_fields())
                except Exception:
                    pass
            return out

        self.recorder = FlightRecorder(
            out_dir, stall_timeout_s=stall_timeout_s,
            process_index=process_index, metrics=metrics,
            extra_state=_extra_state,
            tracer=trace.get(), stall_hook=stall_hook,
            emitter=(live.emitter if live is not None else None),
            beacon_extra=_beacon_extra,
            requeue_attempt=requeue_attempt)
        self._closed = False

    @classmethod
    def from_config(cls, cfg, *, metrics=None, process_index: int = 0,
                    process_count: int = 1,
                    stall_hook: Any = None, live: Any = None,
                    live_fields: Any = None) -> "PodObserver":
        from tpudist.config import resolve_obs, resolve_requeue_attempt
        stall_s, out_dir, hbm_s = resolve_obs(cfg)
        return cls(out_dir=out_dir, stall_timeout_s=stall_s,
                   hbm_sample_s=hbm_s, metrics=metrics,
                   process_index=process_index,
                   process_count=process_count, stall_hook=stall_hook,
                   live=live, live_fields=live_fields,
                   requeue_attempt=resolve_requeue_attempt(cfg))

    def note_progress(self, **kv: Any) -> None:
        self.recorder.note_progress(**kv)

    def beacon_now(self) -> None:
        """One synchronous beacon write (the scripted-kill stamp —
        FlightRecorder.beacon_now)."""
        self.recorder.beacon_now()

    def epoch_end(self, epoch: int, timer, metrics) -> str:
        """Per-host step-stat aggregation (collective on multi-host —
        every process must call this at every epoch end)."""
        return self.hosts.epoch_end(epoch, timer, metrics)

    def hbm_fields(self) -> Dict[str, Any]:
        if self.hbm is None:
            # same schema as HbmSampler.split: every hbm_* key present
            # in every timing record, None = not derived (parsers must
            # not key-error on degraded runs)
            return {"hbm_peak_bytes": None, "hbm_bytes_in_use": None,
                    "hbm_bytes_reserved": None,
                    "hbm_fragmentation_bytes": None,
                    "hbm_limit_bytes": None, "hbm_peak_fraction": None,
                    "hbm_source": "off"}
        self.hbm.sample()   # final watermark before the record is cut
        return self.hbm.split()

    def timing_fields(self, timer, dispatch_fn: Any) -> Dict[str, Any]:
        """The observability slice of the run-end ``kind=timing``
        record: MFU/roofline from the compiled dispatch, HBM
        watermarks, and the last epoch's straggler verdict."""
        step_s = (timer.elapsed / timer.steps) if timer.steps else 0.0
        fields = mfu.mfu_fields(mfu.dispatch_cost(dispatch_fn), step_s)
        fields.update(self.hbm_fields())
        fields["straggler_status"] = self.hosts.status
        return fields

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.recorder.close()
        if self.hbm is not None:
            self.hbm.close()
