"""On-line alert engine: the at-exit gates, evaluated mid-run.

Every verdict the run grades at exit (straggler, staging overlap,
exposed comm, regression, stall — :mod:`tpudist.rules`) is a number the
run already produces *while it runs*; this engine watches those numbers
continuously and turns threshold breaches into **alerts** with a
fire/resolve lifecycle, so an operator (or the launcher's requeue
policy) learns about a sick pod hours before the exit verdict would
say so. The thresholds come from the same :mod:`tpudist.rules` table
the exit graders read — on-line and at-exit grading CANNOT drift,
which is pinned by a tier-1 test diffing the two consumers.

jax-free and clock-injectable by design: the engine runs inside the
coordinator's aggregator thread on a pod, but also under the Prometheus
exporter's test harness and the scripted drills, where a fake clock
makes durations deterministic.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from tpudist import rules as rules_lib

SUCCESS = "success"   # mirrors tpudist.verdict vocabulary without the
FAIL = "fail"         # import (verdict is jax-lazy but heavier)

FIRING = "firing"
RESOLVED = "resolved"


class AlertEngine:
    """Threshold-breach tracker over the live observation stream.

    ``observe(rule, value)`` evaluates one observation against the
    rule's CURRENT threshold (env read at call time —
    :func:`tpudist.rules.resolve`) and manages the alert keyed by
    ``(rule, host)``: a clear→breach transition FIRES it, breach→clear
    RESOLVES it, repeated breaches update its value/duration. Each
    transition produces a ``kind=alert`` record (returned, appended to
    ``history``, and passed to ``on_event`` — the aggregator fans it
    into ``alerts.jsonl``, the metrics stream and ``live_status.json``).

    ``host=None`` is a pod-level alert (straggler ratio, regression);
    per-host rules (stall, staging) pass the host index so one wedged
    worker cannot mask another's recovery.
    """

    def __init__(self, *, on_event: Optional[Callable[[Dict], None]] = None,
                 clock: Callable[[], float] = time.time):
        self.on_event = on_event
        self.clock = clock
        self.active: Dict[Tuple[str, Optional[int]], Dict[str, Any]] = {}
        self.history: List[Dict[str, Any]] = []
        self.events = 0

    def observe(self, rule: str, value: Optional[float], *,
                host: Optional[int] = None, step: Optional[int] = None,
                ts: Optional[float] = None, detail: Optional[str] = None,
                threshold: Optional[float] = None
                ) -> Optional[Dict[str, Any]]:
        """Feed one observation; returns the transition record when the
        alert fired or resolved, else None. ``value=None`` never fires
        (no measurement is ungateable, not bad) and never resolves (a
        gap in the signal is not evidence of recovery). ``threshold``
        overrides the rules-table resolution for callers holding a
        per-run value (the aggregator's stall window comes from the
        ``--stall-timeout-s`` FLAG, which the env-only resolve cannot
        see)."""
        if value is None:
            return None
        if threshold is None:
            threshold = rules_lib.resolve(rule)
        breach = rules_lib.breached(rule, value, threshold)
        now = self.clock() if ts is None else ts
        key = (rule, host)
        alert = self.active.get(key)
        if breach and alert is None:
            alert = {
                "kind": "alert", "alert": rule, "state": FIRING,
                "host": host, "value": value, "threshold": threshold,
                "sense": rules_lib.get(rule).sense,
                "first_ts": now, "first_step": step, "last_ts": now,
                "last_step": step, "duration_s": 0.0, "detail": detail,
            }
            self.active[key] = alert
            self.history.append(alert)
            return self._event(alert)
        if breach:
            alert["value"] = value
            alert["last_ts"] = now
            alert["last_step"] = step if step is not None else alert[
                "last_step"]
            alert["duration_s"] = max(0.0, now - alert["first_ts"])
            return None
        if alert is not None:
            del self.active[key]
            alert["state"] = RESOLVED
            alert["last_ts"] = now
            alert["last_step"] = step if step is not None else alert[
                "last_step"]
            alert["duration_s"] = max(0.0, now - alert["first_ts"])
            return self._event(alert)
        return None

    def _event(self, alert: Dict[str, Any]) -> Dict[str, Any]:
        self.events += 1
        rec = dict(alert)
        if self.on_event is not None:
            try:
                self.on_event(rec)
            except Exception:
                pass   # alerting must never take down the aggregator
        return rec

    def firing(self) -> List[Dict[str, Any]]:
        """Currently-firing alerts (copies, stable order)."""
        return [dict(a) for a in self.active.values()]

    def snapshot(self) -> Dict[str, Any]:
        """The alert slice of ``live_status.json``: what fires now plus
        the full fire/resolve history with first-fire step/time and
        duration — the shape the report CLI's Alerts section ingests."""
        return {"firing": self.firing(),
                "history": [dict(a) for a in self.history],
                "events": self.events}
