"""Per-host step-time aggregation: straggler detection at epoch ends.

On a pod, one slow host drags every collective down to its pace — the
job's steps/s quietly becomes the straggler's steps/s and nothing in a
global aggregate says which host it was. At each epoch end every process
contributes its steady-state step-wall stats for that epoch via
``process_allgather`` (the epoch end is already a synchronization point
— all hosts arrive together, so the collective adds no new hang risk
beyond the watchdog's coverage), and rank 0 emits a ``kind=hosts``
record listing every host's mean step time plus a three-valued
``straggler_status`` (SUCCESS / FAIL / UNGATEABLE — the
:mod:`tpudist.verdict` pattern): FAIL when any host's step time exceeds
the pod median by ``TPUDIST_STRAGGLER_FACTOR``.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from tpudist import verdict as verdict_lib


class HostStepStats:
    """Epoch-over-epoch per-host step-time tracker.

    Holds the last epoch's straggler verdict in ``status`` (folded into
    the run-end ``kind=timing`` record) and the deltas needed to turn
    the run-long ``StepTimer`` aggregate into per-epoch means.
    """

    def __init__(self, process_index: int = 0, process_count: int = 1):
        self.process_index = process_index
        self.process_count = process_count
        self.status = verdict_lib.UNGATEABLE
        self.last_hosts: List[Dict[str, Any]] = []
        self._last_steps = 0
        self._last_elapsed = 0.0

    def _local_epoch_stats(self, timer) -> tuple[int, float]:
        """This epoch's (steps, mean step seconds) from the run-long
        timer aggregate; warmup-only epochs report (0, 0)."""
        d_steps = timer.steps - self._last_steps
        d_elapsed = timer.elapsed - self._last_elapsed
        self._last_steps = timer.steps
        self._last_elapsed = timer.elapsed
        mean = d_elapsed / d_steps if d_steps > 0 else 0.0
        return d_steps, mean

    def _gather(self, steps: int, mean: float) -> np.ndarray:
        """(n_hosts, 3) rows of [process_index, steps, step_s_mean]."""
        local = np.asarray(
            [float(self.process_index), float(steps), mean], np.float32)
        if self.process_count == 1:
            return local[None, :]
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(local))

    def epoch_end(self, epoch: int, timer, metrics) -> str:
        """Aggregate this epoch's per-host step stats; log the
        ``kind=hosts`` record (rank 0 — MetricsLogger gates itself) and
        update ``status``. ALL processes must call this (it contains a
        collective on multi-host runs)."""
        steps, mean = self._local_epoch_stats(timer)
        try:
            rows = self._gather(steps, mean)
        except Exception:
            # observability must never fail a run: a backend whose
            # cross-process collectives are broken will fail training on
            # its own terms — degrade to the local row (status stays
            # UNGATEABLE with a single reporter)
            rows = np.asarray(
                [[float(self.process_index), float(steps), mean]],
                np.float32)
        hosts = [{"process": int(r[0]), "steps": int(r[1]),
                  "step_s_mean": float(r[2])} for r in rows]
        means = [h["step_s_mean"] for h in hosts if h["steps"] > 0]
        median = float(np.median(means)) if means else 0.0
        self.status = verdict_lib.straggler_status(means)
        self.last_hosts = hosts
        worst = max(means) if means else 0.0
        metrics.log(kind="hosts", epoch=epoch, hosts=hosts,
                    median_step_s=median, worst_step_s=worst,
                    straggler_ratio=(worst / median if median > 0
                                     else None),
                    straggler_status=self.status)
        return self.status
