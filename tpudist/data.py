"""Deterministic synthetic data + sharded batching.

Parity target: reference ``train.py:19-24`` (``make_synthetic_data``) and the
``DistributedSampler`` + ``DataLoader`` pipeline at ``train.py:63-74``.

TPU-first differences:
  * jax PRNG keys instead of a global torch seed — determinism is explicit
    and independent of call order.
  * The "sampler" is a pure function producing a permutation from
    ``(seed, epoch)``; every process computes the SAME global permutation and
    slices out its own shard by ``process_index`` — no inter-process
    coordination needed (the reference needed ``sampler.set_epoch`` state).
  * Batches are materialised as a single ``(steps, batch, ...)`` array so the
    epoch can run under ``lax.scan`` with static shapes (XLA-friendly), rather
    than a Python DataLoader yielding tensors one at a time.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def make_synthetic_data(n_samples: int = 2000, n_features: int = 20,
                        seed: int = 42) -> Tuple[jax.Array, jax.Array]:
    """Linearly separable binary task: ``y = 1[sum of first n_features//2
    columns > 0]`` on ``x ~ N(0, 1)``.

    Deterministic by seed — this is the convergence oracle (loss must fall
    fast), matching reference ``train.py:19-24``.
    """
    key = jax.random.PRNGKey(seed)
    x = jax.random.normal(key, (n_samples, n_features), dtype=jnp.float32)
    y = (jnp.sum(x[:, : n_features // 2], axis=1) > 0).astype(jnp.float32)
    return x, y


def make_synthetic_tokens(n_samples: int, seq_len: int, vocab_size: int,
                          seed: int = 42) -> jax.Array:
    """Synthetic token stream for the transformer workload (BASELINE.json
    config #5). Deterministic next-token structure: token[t+1] depends on
    token[t] via a fixed affine map mod vocab, so a causal LM can learn it
    and loss decreases — the sequence-shaped convergence oracle."""
    rng = np.random.default_rng(seed)
    first = rng.integers(0, vocab_size, size=(n_samples, 1), dtype=np.int32)
    toks = np.empty((n_samples, seq_len), dtype=np.int32)
    toks[:, :1] = first
    for t in range(1, seq_len):
        toks[:, t] = (toks[:, t - 1] * 7 + 3) % vocab_size
    return jnp.asarray(toks)


def pad_steps(arrays, to_steps: int):
    """Zero-pad ``(steps, batch, ...)`` arrays along the step axis to
    ``to_steps``. The padded steps are MASKED out of the superstep's loss
    accumulation and state updates (engine.make_superstep's ``lo``/``hi``
    bounds), so the pad value never reaches the trajectory — zeros keep
    every model's forward finite (token id 0 is always in-vocab)."""
    def pad(a):
        a = np.asarray(a)
        if a.shape[0] >= to_steps:
            return a
        fill = np.zeros((to_steps - a.shape[0],) + a.shape[1:], a.dtype)
        return np.concatenate([a, fill], axis=0)
    return jax.tree.map(pad, arrays)


class EpochPlan:
    """Lazy per-slab materialisation of one epoch's batches.

    Holds the epoch's permutation (a pure function of ``(seed, epoch)``)
    and the source arrays; ``slab(start, stop)`` gathers only that step
    range into host ``(steps, local_batch, ...)`` arrays. This replaces
    the one-shot whole-epoch materialisation: the streaming train loop
    stages bounded slabs into device memory while compute runs, so epochs
    larger than the staging budget — or than HBM — run fine.
    """

    def __init__(self, arrays, idx: np.ndarray):
        self.arrays = tuple(np.asarray(a) for a in arrays)
        self.idx = idx

    @property
    def n_steps(self) -> int:
        return self.idx.shape[0]

    @property
    def bytes_per_step(self) -> int:
        """Host bytes of one materialised ``(local_batch, ...)`` step."""
        bs = self.idx.shape[1]
        return sum(bs * int(np.prod(a.shape[1:], dtype=np.int64))
                   * a.dtype.itemsize for a in self.arrays)

    def slab(self, start: int, stop: int, pad_to: int = 0):
        """Materialise steps ``[start, stop)`` as ``(steps, local_batch,
        ...)`` host arrays, zero-padded along the step axis to ``pad_to``
        when that exceeds the true length (see :func:`pad_steps`)."""
        sl = self.idx[start:stop]
        out = tuple(a[sl] for a in self.arrays)
        if pad_to > sl.shape[0]:
            out = pad_steps(out, pad_to)
        return out


def plan_epoch(arrays, *, batch_size: int, seed: int, epoch: int,
               process_index: int = 0, process_count: int = 1) -> EpochPlan:
    """Build this process's :class:`EpochPlan` for one epoch — the lazy
    (slab-wise) counterpart of :func:`shard_epoch`, sharing its contract:
    global ``batch_size``, global batch ``b`` is ``perm[b*batch_size:
    (b+1)*batch_size]``, each process owns a contiguous ``local_batch``
    slice of every global batch, trailing samples are dropped."""
    n = int(np.asarray(arrays[0]).shape[0])
    idx = _epoch_index(n, batch_size=batch_size, seed=seed, epoch=epoch,
                       process_index=process_index,
                       process_count=process_count)
    return EpochPlan(arrays, idx)


def _epoch_index(n: int, *, batch_size: int, seed: int, epoch: int,
                 process_index: int, process_count: int) -> np.ndarray:
    """(steps, local_batch) gather indices for this process's epoch."""
    if batch_size % process_count:
        raise ValueError(
            f"global batch_size={batch_size} not divisible by "
            f"process_count={process_count}")
    local_bs = batch_size // process_count
    steps = n // batch_size
    if steps == 0:
        raise ValueError(
            f"n_samples={n} < global batch_size={batch_size}: zero steps")
    perm = epoch_permutation(seed, epoch, n)[: steps * batch_size]
    return perm.reshape(steps, process_count, local_bs)[:, process_index, :]


def epoch_permutation(seed: int, epoch: int, n: int) -> np.ndarray:
    """Global shuffle for an epoch, identical on every process.

    Replaces ``DistributedSampler(shuffle=True)`` + ``set_epoch`` (reference
    ``train.py:68-69,101``): the permutation is a pure function of
    ``(seed, epoch)`` so no state or broadcast is required.
    """
    return np.asarray(jax.random.permutation(
        jax.random.fold_in(jax.random.PRNGKey(seed), epoch), n))


def shard_epoch(x: jax.Array, y: jax.Array, *, batch_size: int, seed: int,
                epoch: int, process_index: int = 0,
                process_count: int = 1) -> Tuple[jax.Array, jax.Array]:
    """Produce this process's batches for one epoch.

    Returns ``(steps, local_batch, ...)`` arrays where
    ``local_batch = batch_size // process_count``. ``batch_size`` is the
    GLOBAL batch size (fixing the reference's three-way batch-size conflict,
    SURVEY.md §2.7). Trailing samples that don't fill a global batch are
    dropped (static shapes for XLA).
    """
    # Global batch b is perm[b*batch_size:(b+1)*batch_size]; this process owns
    # the contiguous slice [process_index*local_bs : (process_index+1)*local_bs)
    # of every global batch — the DistributedSampler-equivalent contract.
    idx = _epoch_index(x.shape[0], batch_size=batch_size, seed=seed,
                       epoch=epoch, process_index=process_index,
                       process_count=process_count)
    return x[idx], y[idx]
