"""Measured-probe autotuner for the serving engine's knobs.

The PR-4 train tuner generalised: the decode superstep length
(``decode_k`` — tokens per dispatch per slot) and the KV cache's
physical storage layout (``st`` | ``hs``, :mod:`tpudist.serve.kvcache`)
both move decode throughput, and the right answer depends on the model
shape, mesh and device kind — exactly the situation the train tuner
replaced static heuristics with measurement for. This module reuses that
machinery wholesale: the same persisted fingerprint-keyed JSON cache
(:mod:`tpudist.tune.cache`, ``prefix="serve"`` so the two knob schemas
never collide in one file), the same deterministic walk discipline
(ordered-axis ascent with plateau preference and regress early-stop,
:mod:`tpudist.tune.search` constants), and the same contract: the
search NEVER commits a point that measures slower than the heuristic
start, a second run of the same (model, topology, serve shape) costs
zero probe trials, and a probing failure degrades to the heuristics,
never to a dead run.

The probe is closed-loop decode throughput: build the candidate's
engine, prefill every slot, then time whole decode supersteps with all
slots active — tokens/s at full occupancy, the number the
``tokens_per_chip`` SLO gate grades.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional

from tpudist import verdict as verdict_lib
from tpudist.parallel.sharding import KV_CACHE_LAYOUTS
from tpudist.tune import cache as cache_mod
from tpudist.tune import search as search_mod

# Decode-k ladder: geometric like the train tuner's k axis — the curve's
# knee is what matters, not every integer. Capped where per-dispatch
# latency starts to dominate ITL attribution (slo: ITL = wall / k).
DECODE_K_LADDER = (1, 2, 4, 8, 16, 32)
# Paged-axis ladders (serve mode only): page sizes worth probing (0 —
# the dense arena — is always the walk's committed fallback) and verify
# window widths (window includes the pending last token, so 2 is the
# smallest real speculation).
KV_PAGE_TOKENS_LADDER = (8, 16, 32)
SPECULATE_K_LADDER = (2, 4, 8)

DEFAULT_PROBE_DISPATCHES = 8
DEFAULT_PROBE_REPEATS = 3
DEFAULT_TRIALS = 8


@dataclasses.dataclass(frozen=True)
class ServeCandidate:
    """One point in the serve knob space. ``kv_page_tokens = 0`` is the
    dense arena; > 0 selects the paged engine at that page size.
    ``speculate_k = 0`` is plain decode; >= 2 is the draft+verify
    window (meaningful only with paging — the walk gates it so)."""

    decode_k: int = 8
    layout: str = "st"
    kv_page_tokens: int = 0
    speculate_k: int = 0

    def replace(self, **kw) -> "ServeCandidate":
        return dataclasses.replace(self, **kw)

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def validate_serve_tuned(tuned: Dict[str, Any]) -> bool:
    """Knob sanity for a cached serve record (the ``validate`` hook of
    :func:`tpudist.tune.cache.load`): an insane decode_k, unknown
    layout, or a pre-paging record missing the paged knobs is a cache
    MISS (re-probe), never a crash in the engine."""
    if "kv_page_tokens" not in tuned or "speculate_k" not in tuned:
        return False              # pre-paging schema: re-probe
    if int(tuned["decode_k"]) < 1:
        return False
    pt, sk = int(tuned["kv_page_tokens"]), int(tuned["speculate_k"])
    if pt < 0 or sk < 0 or sk == 1:
        return False
    if sk >= 2 and pt == 0:
        return False              # speculation needs the paged engine
    return tuned["layout"] in KV_CACHE_LAYOUTS


def fingerprint(model_cfg, mesh, *, slots: int, max_seq: int,
                prompt_pad: int,
                device_kind: Optional[str] = None) -> str:
    """Fingerprint of the serve tuning situation — everything that moves
    the decode-throughput curve: model shape, cache geometry, mesh,
    device kind/counts, software versions. Same recipe as the train
    tuner's (tune.cache.fingerprint); distinct payload because the knob
    space is distinct."""
    import hashlib
    import json

    import jax

    from tpudist.version import __version__
    if device_kind is None:
        try:
            device_kind = jax.devices()[0].device_kind
        except Exception:
            device_kind = "unknown"
    payload = {
        "schema": cache_mod.SCHEMA,
        "what": "serve",
        # knob-space generation: bumped when the candidate schema grows
        # (paged knobs joined at 2) so records from an older walk never
        # alias a fingerprint whose search space they never saw
        "knobs": 2,
        "model": dataclasses.asdict(model_cfg),
        "slots": int(slots),
        "max_seq": int(max_seq),
        "prompt_pad": int(prompt_pad),
        "mesh": dict(zip(mesh.axis_names,
                         (int(s) for s in mesh.devices.shape))),
        "n_devices": jax.device_count(),
        "n_processes": jax.process_count(),
        "device_kind": device_kind,
        "jax": jax.__version__,
        "tpudist": __version__,
    }
    blob = json.dumps(payload, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


@dataclasses.dataclass(frozen=True)
class ServeProbeResult:
    """One candidate's measured decode-throughput trial."""

    tokens_per_sec: float
    dispatch_ms: float
    feasible: bool = True
    error: Optional[str] = None
    spread: float = 0.0      # (max-min)/min over repeats: noise floor
    tokens: int = 0          # tokens actually generated per timed run


def probe_candidate(model_cfg, mesh, params, cand: ServeCandidate, *,
                    slots: int, max_seq: int, prompt_pad: int,
                    n_dispatches: int = DEFAULT_PROBE_DISPATCHES,
                    repeats: int = DEFAULT_PROBE_REPEATS
                    ) -> ServeProbeResult:
    """Measure one candidate: build its engine, prefill every slot, time
    ``repeats`` runs of ``n_dispatches`` decode supersteps at full
    occupancy. Estimator over repeats is the MIN elapsed (one-sided host
    noise, same reasoning as tune.probe). A paged candidate probes the
    paged engine (default full-capacity pool: the probe measures the
    program, not an artificial page famine); a speculative one times
    draft+verify dispatches and counts the tokens the verifies actually
    emitted — fenced ``lengths`` deltas, not ``k × dispatches``, since
    acceptance is workload-dependent and crediting rejected drafts
    would let speculation look free. Never raises — any failure (OOM,
    bad layout lowering) is a pruned ``feasible=False`` result."""
    import jax
    import numpy as np

    from tpudist.serve.engine import PagedServeEngine, ServeEngine
    try:
        paged = cand.kv_page_tokens > 0
        spec_k = cand.speculate_k if paged else 0
        if paged:
            engine = PagedServeEngine(
                model_cfg, mesh, slots=slots, max_seq=max_seq,
                prompt_pad=prompt_pad, decode_k=cand.decode_k,
                page_tokens=cand.kv_page_tokens, speculate_k=spec_k)
        else:
            engine = ServeEngine(model_cfg, mesh, slots=slots,
                                 max_seq=max_seq, prompt_pad=prompt_pad,
                                 decode_k=cand.decode_k,
                                 layout=cand.layout)
        # per-slot decode budget must cover every timed dispatch so the
        # whole probe runs at full occupancy (an emptying batch would
        # flatter small decode_k); shrink the dispatch count if the
        # cache pages cannot hold that many tokens
        width = spec_k if spec_k >= 2 else cand.decode_k
        room = (max_seq - prompt_pad - 1) // width
        n_disp = max(1, min(int(n_dispatches), room))
        budget = n_disp * width + 2
        prompt = np.arange(prompt_pad, dtype=np.int32) \
            % model_cfg.vocab_size

        def fill() -> Any:
            state = engine.init_state()
            if paged:
                engine.new_allocator()
            outs = []
            for s in range(slots):
                if paged:
                    engine.alloc.admit(s, prompt_pad)  # full-capacity
                    # pool: cannot fail at probe shapes
                state, first = engine.prefill(
                    params, state, prompt[None, :], prompt_pad, s,
                    budget)
                outs.append([int(x) for x in prompt] + [int(first)])
            if paged:
                # map every page up front: the probe times dispatch
                # compute, not incremental host allocation
                for s in range(slots):
                    engine.alloc.ensure(s, max_seq - 1)
            return state, outs

        def dispatch(state, outs):
            if spec_k >= 2:
                from tpudist.serve.scheduler import ngram_draft
                draft = np.zeros((slots, spec_k - 1), np.int32)
                for s in range(slots):
                    draft[s] = ngram_draft(outs[s], spec_k - 1)
                state, toks, valid, _ = engine.verify(params, state,
                                                      draft)
                tv, vv = np.asarray(toks), np.asarray(valid)  # fence —
                # the NEXT draft needs these tokens; part of the cost
                for s in range(slots):
                    outs[s].extend(int(x) for x in tv[vv[:, s], s])
                return state, toks
            state, toks, _ = engine.decode(params, state)
            return state, toks

        # warm: compile every program off the timed path
        state, outs = fill()
        state, toks = dispatch(state, outs)
        np.asarray(toks)
        times: List[float] = []
        tokens = 0
        for _ in range(repeats):
            state, outs = fill()
            len0 = int(np.asarray(state.lengths).sum())  # fence too
            t0 = time.perf_counter()
            toks = None
            for _ in range(n_disp):
                state, toks = dispatch(state, outs)
            np.asarray(toks)                 # fence on the tokens
            times.append(time.perf_counter() - t0)
            # honest token count from the device's own ledger: every
            # emitted token advanced a slot's length by exactly one, a
            # frozen slot's by zero — so an oversized decode_k or a
            # rejected draft can never inflate the estimate
            tokens = int(np.asarray(state.lengths).sum()) - len0
        best = min(times)
        spread = (max(times) - best) / best if best > 0 else 0.0
        return ServeProbeResult(
            tokens_per_sec=tokens / best if best > 0 else 0.0,
            dispatch_ms=best * 1000.0 / n_disp, spread=spread,
            tokens=tokens)
    except Exception as e:
        return ServeProbeResult(
            0.0, float("inf"), feasible=False,
            error=f"{type(e).__name__}: {str(e)[:200]}")


@dataclasses.dataclass(frozen=True)
class ServeTuneOutcome:
    """What the serve tuner decided and how it got there."""

    tuned: ServeCandidate
    source: str                   # cache | probe | heuristic
    status: str                   # verdict SUCCESS/FAIL/UNGATEABLE
    trials: int
    pruned: int
    fingerprint: str
    cache_dir: str
    tokens_per_sec: Optional[float] = None
    baseline_tokens_per_sec: Optional[float] = None


def _search(measure, start: ServeCandidate, *, max_decode_k: int,
            trial_budget: int,
            max_page_tokens: int = 0) -> Dict[str, Any]:
    """Deterministic axis walk sharing the train search's discipline:
    decode_k first (ordered ascent, regress early-stop,
    plateau-prefers-smallest within PLATEAU_TOL — shorter supersteps
    mean honester ITL at indistinguishable throughput), then layout at
    the committed decode_k (best wins; ties keep the start's layout),
    then the paged axes: ``kv_page_tokens`` (a real win over the
    committed point switches storage discipline; a tie keeps it — the
    dense arena is the simpler program) and, only at a committed page
    size, ``speculate_k`` (same real-win bar: acceptance-rate-dependent
    speedups must MEASURE, never be assumed). The committed point NEVER
    measures slower than the start."""
    memo: Dict[ServeCandidate, ServeProbeResult] = {}
    out = {"best": start, "best_tps": 0.0, "baseline_tps": 0.0,
           "trials": 0, "pruned": 0}

    def run(cand: ServeCandidate) -> Optional[ServeProbeResult]:
        if cand in memo:
            return memo[cand]
        if out["trials"] >= trial_budget:
            return None
        res = measure(cand)
        out["trials"] += 1
        if not res.feasible:
            out["pruned"] += 1
        memo[cand] = res
        return res

    base = run(start)
    if base is not None and base.feasible:
        out["baseline_tps"] = out["best_tps"] = base.tokens_per_sec

    ladder = [k for k in DECODE_K_LADDER if k <= max_decode_k]
    if start.decode_k not in ladder:
        ladder = sorted(set(ladder) | {start.decode_k})
    measured = [(start.decode_k, out["best_tps"])] \
        if out["best_tps"] > 0 else []
    prev: Optional[float] = None   # previous LADDER point, scan order —
    # comparing each k against the (possibly mid-ladder) start would
    # false-trigger the regress stop on the very first rung
    for k in ladder:
        if k == start.decode_k:
            prev = out["best_tps"] or prev
            continue
        res = run(start.replace(decode_k=k))
        if res is None:
            break
        if not res.feasible:
            break                # bigger pages cannot refit HBM
        measured.append((k, res.tokens_per_sec))
        if prev is not None and res.tokens_per_sec \
                < prev * (1 - search_mod.REGRESS_STOP):
            break                # past the plateau, curve turned down
        prev = res.tokens_per_sec
    if measured:
        axis_best = max(t for _, t in measured)
        for k, tps in sorted(measured):
            if tps >= axis_best * (1 - search_mod.PLATEAU_TOL):
                out["best"] = out["best"].replace(decode_k=k)
                out["best_tps"] = tps
                break

    for layout in KV_CACHE_LAYOUTS:
        if layout == out["best"].layout:
            continue
        res = run(out["best"].replace(layout=layout))
        if res is None or not res.feasible:
            continue
        if res.tokens_per_sec > out["best_tps"] * (
                1 + search_mod.PLATEAU_TOL):
            out["best"] = out["best"].replace(layout=layout)
            out["best_tps"] = res.tokens_per_sec

    # ---- paged axes (serve-mode coordinates, PR 16) ----
    if max_page_tokens > 0:
        for pt in KV_PAGE_TOKENS_LADDER:
            if pt > max_page_tokens \
                    or pt == out["best"].kv_page_tokens:
                continue
            # page size probes without speculation: one axis at a time
            res = run(out["best"].replace(kv_page_tokens=pt,
                                          speculate_k=0))
            if res is None or not res.feasible:
                continue
            if res.tokens_per_sec > out["best_tps"] * (
                    1 + search_mod.PLATEAU_TOL):
                out["best"] = out["best"].replace(kv_page_tokens=pt,
                                                  speculate_k=0)
                out["best_tps"] = res.tokens_per_sec
        if out["best"].kv_page_tokens > 0:
            for sk in SPECULATE_K_LADDER:
                if sk == out["best"].speculate_k:
                    continue
                res = run(out["best"].replace(speculate_k=sk))
                if res is None or not res.feasible:
                    continue
                if res.tokens_per_sec > out["best_tps"] * (
                        1 + search_mod.PLATEAU_TOL):
                    out["best"] = out["best"].replace(speculate_k=sk)
                    out["best_tps"] = res.tokens_per_sec

    # the hard floor: never commit a point slower than the measured start
    if out["best"] != start and out["best_tps"] < out["baseline_tps"]:
        out["best"], out["best_tps"] = start, out["baseline_tps"]
    return out


def autotune_serve(model_cfg, mesh, params, *, slots: int, max_seq: int,
                   prompt_pad: int, mode: str, cache_dir: str,
                   start: Optional[ServeCandidate] = None,
                   trials: int = DEFAULT_TRIALS,
                   n_dispatches: int = DEFAULT_PROBE_DISPATCHES,
                   repeats: int = DEFAULT_PROBE_REPEATS,
                   metrics: Any = None) -> ServeTuneOutcome:
    """Resolve the serve operating point per ``mode`` (``off`` |
    ``probe`` | ``cache-only``), exactly like tune.autotune: cache hit →
    zero trials; miss under ``probe`` → measured search + persist; miss
    under ``cache-only`` (or a probing failure) → the heuristic start,
    honestly labeled. Single-process by design — the serve loop is one
    host driving one mesh (multi-host serving would broadcast the commit
    exactly as tune._sync_candidate does)."""
    start = start or ServeCandidate()
    fp = fingerprint(model_cfg, mesh, slots=slots, max_seq=max_seq,
                     prompt_pad=prompt_pad)
    if mode == "off":
        return _log(ServeTuneOutcome(
            tuned=start, source="heuristic",
            status=verdict_lib.tuning_status("off"), trials=0, pruned=0,
            fingerprint=fp, cache_dir=cache_dir), metrics)

    rec = cache_mod.load(cache_dir, fp, prefix="serve",
                         validate=validate_serve_tuned)
    if rec is not None:
        t = rec["tuned"]
        tuned = ServeCandidate(decode_k=int(t["decode_k"]),
                               layout=t["layout"],
                               kv_page_tokens=int(t["kv_page_tokens"]),
                               speculate_k=int(t["speculate_k"]))
        if tuned.decode_k <= max_seq - prompt_pad:
            return _log(ServeTuneOutcome(
                tuned=tuned, source="cache",
                status=verdict_lib.tuning_status(mode, source="cache"),
                trials=0, pruned=0, fingerprint=fp, cache_dir=cache_dir,
                tokens_per_sec=rec.get("tokens_per_sec"),
                baseline_tokens_per_sec=rec.get(
                    "baseline_tokens_per_sec")), metrics)

    if mode != "probe":
        return _log(ServeTuneOutcome(
            tuned=start, source="heuristic",
            status=verdict_lib.tuning_status(mode, source="heuristic"),
            trials=0, pruned=0, fingerprint=fp, cache_dir=cache_dir),
            metrics)

    def measure(cand: ServeCandidate) -> ServeProbeResult:
        return probe_candidate(model_cfg, mesh, params, cand,
                               slots=slots, max_seq=max_seq,
                               prompt_pad=prompt_pad,
                               n_dispatches=n_dispatches,
                               repeats=repeats)

    try:
        out = _search(measure, start,
                      max_decode_k=max(1, max_seq - prompt_pad - 1),
                      trial_budget=trials,
                      max_page_tokens=max_seq)
    except Exception as e:
        from tpudist.metrics import log0
        log0(f"tpudist: serve autotune probing failed ({e!r}); "
             f"falling back to heuristics")
        return _log(ServeTuneOutcome(
            tuned=start, source="heuristic",
            status=verdict_lib.tuning_status(mode, source="heuristic"),
            trials=0, pruned=0, fingerprint=fp, cache_dir=cache_dir),
            metrics)

    status = verdict_lib.tuning_status(
        mode, source="probe", tuned_steps_per_sec=out["best_tps"],
        baseline_steps_per_sec=out["baseline_tps"])
    cache_mod.store(cache_dir, fp, {
        "tuned": out["best"].as_dict(),
        "tokens_per_sec": out["best_tps"],
        "baseline_tokens_per_sec": out["baseline_tps"],
        "trials": out["trials"], "pruned": out["pruned"],
    }, prefix="serve")
    return _log(ServeTuneOutcome(
        tuned=out["best"], source="probe", status=status,
        trials=out["trials"], pruned=out["pruned"], fingerprint=fp,
        cache_dir=cache_dir, tokens_per_sec=out["best_tps"],
        baseline_tokens_per_sec=out["baseline_tps"]), metrics)


def _log(out: ServeTuneOutcome, metrics: Any) -> ServeTuneOutcome:
    """One ``kind=serve_tune`` record per tuning decision."""
    if metrics is not None:
        metrics.log(kind="serve_tune", status=out.status,
                    source=out.source, trials=out.trials,
                    pruned=out.pruned, fingerprint=out.fingerprint,
                    decode_k=out.tuned.decode_k, layout=out.tuned.layout,
                    kv_page_tokens=out.tuned.kv_page_tokens,
                    speculate_k=out.tuned.speculate_k,
                    tokens_per_sec=out.tokens_per_sec,
                    baseline_tokens_per_sec=out.baseline_tokens_per_sec)
    return out
