"""Per-request flight ledger: reconstruct and EXACTLY verify serve
timelines from the artifacts a run leaves behind.

The serve lane records three views of every request:

  * lifecycle instants/spans on the ring tracer (``cat=serve``, keyed
    by ``rid``): ``arrive -> kv_admit -> admit -> prefill ->
    decode_emit ... -> {done|evicted|...}``;
  * the flushed ``kind=serve_request`` outcome stream (the same
    spellings — :mod:`tpudist.serve.resilience` owns the vocabulary);
  * the ShedLedger's exact partition in the ``kind=serve`` summary.

This module is the auditor that folds them back together. For every
arrived ``rid`` it reconstructs ONE flight and asserts the chain
grammar exactly: exactly one admission-stage event
(``admitted | shed_admission | expired_queue | rejected``); a
non-admitted verdict IS terminal (no further events); an admitted
flight ends in exactly one outcome (``done | evicted | lost``). The
admitted event's TTFT must equal its own decomposition
(``waited_s == queue_wait_s + prefill_s`` within the pinned
``flight_decomp`` rules-table tolerance), and the aggregate chain
counts must reconcile BITWISE with the ShedLedger partition — the two
accountings derive from the same scheduler but through different code
paths, so a drift here is a real bookkeeping bug, never noise. When a
trace document is supplied (and its ring dropped nothing) the ledger
additionally pins the span view against the event view: one prefill
span per admitted rid, and the per-rid sum of ``decode_emit`` tokens
equal to the terminal event's ``generated`` count minus the prefill
token.

Also home to the pod-trace presentation helpers: the per-slot track
copies and the ph="C" KV-pool occupancy counter events the serve CLI
appends to ``pod_trace.json`` via ``export_pod_trace(extra_events=)``.

Stdlib-only by design (same contract as :mod:`tpudist.serve.slo`): the
report CLI folds the "Request flights" section, and the
``python -m tpudist.serve.flight`` verifier exits 0/1, with jax
uninstalled.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

from tpudist import rules as rules_lib
from tpudist.serve import resilience as res_lib
from tpudist.serve import slo as slo_lib

SERVE_CAT = "serve"             # lifecycle spans/instants, keyed by rid
COUNTER_CAT = "serve_counter"   # KV-pool occupancy samples

# Per-slot Perfetto tracks: slot i's copies land on tid BASE+i — far
# above the tracer's small per-thread tid enumeration, so the slot rows
# sort below the host threads and never collide with them.
SLOT_TID_BASE = 1000

# Flight-stage instant names that are NOT serve_request outcomes (the
# outcome spellings come from the resilience vocabulary).
ARRIVE = "arrive"

_COUNT_KEYS = ("arrived", "admitted", "shed_at_admission",
               "expired_in_queue", "rejected", "completed", "evicted",
               "lost")

_ADMISSION_TO_KEY = {
    res_lib.ADMITTED: "admitted",
    res_lib.SHED: "shed_at_admission",
    res_lib.EXPIRED: "expired_in_queue",
    res_lib.REJECTED: "rejected",
}

_OUTCOME_TO_KEY = {
    res_lib.DONE: "completed",
    res_lib.EVICTED: "evicted",
    res_lib.LOST: "lost",
}


# --------------------------------------------------- pod-trace presentation

def slot_track_events(events: List[Dict[str, Any]], *,
                      process_index: int = 0) -> List[Dict[str, Any]]:
    """Per-slot track copies of the serve lifecycle events.

    Every ``cat=serve`` event whose args carry a ``slot`` is duplicated
    onto tid ``SLOT_TID_BASE + slot`` (with a ``thread_name`` metadata
    row naming the track ``slot<i>``), so Perfetto shows one row per
    serving slot with that slot's admissions, prefills, decode
    emissions and terminals in arrival order. Copies are tagged
    ``args.track = "slot"`` so the ledger's span accounting can skip
    them (they are presentation, not new evidence)."""
    out: List[Dict[str, Any]] = []
    slots = set()
    for e in events:
        if e.get("cat") != SERVE_CAT:
            continue
        args = e.get("args") or {}
        slot = args.get("slot")
        if slot is None or args.get("track"):
            continue
        ev = dict(e)
        ev["pid"] = process_index
        ev["tid"] = SLOT_TID_BASE + int(slot)
        ev["args"] = dict(args, track="slot")
        out.append(ev)
        slots.add(int(slot))
    meta = [{"ph": "M", "name": "thread_name", "pid": process_index,
             "tid": SLOT_TID_BASE + s, "args": {"name": f"slot{s}"}}
            for s in sorted(slots)]
    return meta + out


def kv_counter_events(events: List[Dict[str, Any]], *,
                      process_index: int = 0) -> List[Dict[str, Any]]:
    """ph="C" Chrome counter events from the scheduler's ``kv_pages``
    occupancy samples (``cat=serve_counter`` instants, one per decode
    dispatch). Emitted as a stacked used/free pair (the stack height IS
    the pool size) plus a separate shared-prefix refcount series, on
    the same timestamps as the request spans."""
    out: List[Dict[str, Any]] = []
    for e in events:
        if e.get("cat") != COUNTER_CAT or e.get("name") != "kv_pages":
            continue
        a = e.get("args") or {}
        used = int(a.get("used") or 0)
        total = int(a.get("total") or 0)
        base = {"cat": COUNTER_CAT, "ph": "C", "ts": e.get("ts", 0.0),
                "pid": process_index, "tid": 0}
        out.append(dict(base, name="kv_pages",
                        args={"used": used,
                              "free": max(total - used, 0)}))
        out.append(dict(base, name="kv_shared_refs",
                        args={"refs": int(a.get("shared_refs") or 0)}))
    return out


def build_extra_events(events: List[Dict[str, Any]], *,
                       process_index: int = 0) -> List[Dict[str, Any]]:
    """Everything the serve CLI appends to its worker trace doc before
    the pod merge: per-slot request tracks + KV occupancy counters."""
    return (slot_track_events(events, process_index=process_index)
            + kv_counter_events(events, process_index=process_index))


# -------------------------------------------------------- reconstruction

def reconstruct(records: List[Dict[str, Any]],
                trace_doc: Optional[Dict[str, Any]] = None
                ) -> Dict[int, Dict[str, Any]]:
    """Fold the ``kind=serve_request`` stream (and optionally a trace
    document) into one flight dict per rid. File order is preserved per
    rid — the scheduler emits events in lifecycle order, so order IS
    the chain."""
    flights: Dict[int, Dict[str, Any]] = {}
    for rec in records:
        if rec.get("kind") != "serve_request" or rec.get("rid") is None:
            continue
        rid = int(rec["rid"])
        f = flights.setdefault(rid, {"rid": rid, "events": []})
        f["events"].append({k: v for k, v in rec.items()
                            if k != "kind"})
    if trace_doc is not None:
        _attach_trace(flights, trace_doc)
    return flights


def _attach_trace(flights: Dict[int, Dict[str, Any]],
                  trace_doc: Dict[str, Any]) -> None:
    """Per-rid span accounting from a (worker or merged pod) trace doc.

    Only host 0's original thread events count as evidence: the merge
    re-pids every worker, per-slot track copies are tagged, and on a
    multi-process run every process records the same SPMD scheduler —
    counting more than one view would double every span."""
    meta = trace_doc.get("metadata") or {}
    dropped = int(meta.get("dropped") or 0)
    for e in trace_doc.get("traceEvents", []):
        if e.get("ph") != "X" or e.get("cat") != SERVE_CAT:
            continue
        if e.get("pid") not in (0, None):
            continue
        args = e.get("args") or {}
        if args.get("track"):
            continue
        rid = args.get("rid")
        if rid is None:
            continue
        rid = int(rid)
        f = flights.setdefault(rid, {"rid": rid, "events": [],
                                     "trace_only": True})
        spans = f.setdefault("spans", {})
        name = e.get("name")
        spans[name] = spans.get(name, 0) + 1
        if name == "decode_emit":
            f["decode_tokens"] = (f.get("decode_tokens", 0)
                                  + int(args.get("tokens") or 0))
    for f in flights.values():
        if "spans" in f:
            f["trace_dropped"] = dropped


# ------------------------------------------------------------ verification

def verify(flights: Dict[int, Dict[str, Any]],
           partition: Optional[Dict[str, Any]] = None, *,
           tol: Optional[float] = None) -> Dict[str, Any]:
    """The exactness pass. Returns a summary dict whose ``exact`` field
    is True iff every chain parsed, every decomposition met the
    tolerance, every trace cross-check held, and (when given) the
    chain-count partition reconciled bitwise with the ShedLedger."""
    if tol is None:
        tol = rules_lib.resolve("flight_decomp")
    problems: List[str] = []
    counts = {k: 0 for k in _COUNT_KEYS}
    worst = 0.0
    decomposed = 0
    trace_checked = 0
    for rid in sorted(flights):
        f = flights[rid]
        evs = [e.get("event") for e in f["events"]]
        if not evs:
            problems.append(f"rid {rid}: trace spans but no "
                            f"serve_request events")
            continue
        counts["arrived"] += 1
        unknown = [e for e in evs if e not in _ADMISSION_TO_KEY
                   and e not in _OUTCOME_TO_KEY]
        if unknown:
            problems.append(f"rid {rid}: unknown events {unknown}")
        adm = [e for e in evs if e in _ADMISSION_TO_KEY]
        outs = [e for e in evs if e in _OUTCOME_TO_KEY]
        if len(adm) != 1:
            problems.append(f"rid {rid}: {len(adm)} admission-stage "
                            f"events {adm} (want exactly 1)")
            continue
        counts[_ADMISSION_TO_KEY[adm[0]]] += 1
        if adm[0] != res_lib.ADMITTED:
            # a non-admitted verdict IS the terminal state
            if len(evs) != 1:
                problems.append(f"rid {rid}: events after terminal "
                                f"admission verdict {adm[0]}: {evs}")
            continue
        if len(outs) != 1:
            problems.append(f"rid {rid}: {len(outs)} outcome events "
                            f"{outs} after admission (want exactly 1)")
            continue
        counts[_OUTCOME_TO_KEY[outs[0]]] += 1
        if evs.index(adm[0]) > evs.index(outs[0]):
            problems.append(f"rid {rid}: outcome {outs[0]} precedes "
                            f"admission")
        adm_ev = f["events"][evs.index(res_lib.ADMITTED)]
        err = _decomp_error(adm_ev)
        if err is not None:
            decomposed += 1
            worst = max(worst, err)
            if err > tol:
                problems.append(
                    f"rid {rid}: ttft decomposition off by {err:.2e} s "
                    f"(waited_s={adm_ev.get('waited_s')} vs "
                    f"queue_wait_s+prefill_s, tol {tol:.2e})")
        tp = _trace_problems(rid, f, outs[0])
        if tp is not None:
            trace_checked += 1
            problems.extend(tp)
    if partition is not None:
        for k in _COUNT_KEYS:
            want = partition.get(k)
            if want is None or int(want) == counts[k]:
                continue
            problems.append(f"partition mismatch: {k} reconstructed "
                            f"{counts[k]} != ledger {int(want)}")
    return {
        "flights": len(flights),
        "counts": counts,
        "exact": not problems,
        "problems": problems,
        "decomposed": decomposed,
        "ttft_decomp_worst_s": round(worst, 9),
        "ttft_decomp_tol_s": tol,
        "ttft_decomp_status": (slo_lib.FAIL if rules_lib.breached(
            "flight_decomp", worst, tol) else slo_lib.SUCCESS),
        "partition_checked": partition is not None,
        "trace_checked": trace_checked,
    }


def _decomp_error(adm_ev: Dict[str, Any]) -> Optional[float]:
    """|ttft - (queue_wait + prefill)| when the ADMITTED event carries
    the decomposition; None on pre-flight-tracing artifacts."""
    waited = adm_ev.get("waited_s")
    q = adm_ev.get("queue_wait_s")
    p = adm_ev.get("prefill_s")
    if waited is None or q is None or p is None:
        return None
    return abs(float(waited) - (float(q) + float(p)))


def _trace_problems(rid: int, f: Dict[str, Any],
                    outcome: str) -> Optional[List[str]]:
    """Span-vs-event cross-checks for one ADMITTED flight; None when no
    trace evidence was attached or the ring dropped spans (an overrun
    ring under-counts exactly the oldest flights — skipping is honest,
    silently passing would not be)."""
    spans = f.get("spans")
    if spans is None or f.get("trace_dropped", 0) > 0:
        return None
    out: List[str] = []
    n_pre = spans.get("prefill", 0)
    if n_pre != 1:
        out.append(f"rid {rid}: {n_pre} prefill spans in trace "
                   f"(want exactly 1)")
    if outcome in (res_lib.DONE, res_lib.EVICTED):
        term = [e for e in f["events"] if e.get("event") == outcome]
        gen = term[-1].get("generated")
        got = f.get("decode_tokens", 0)
        if gen is not None and got != int(gen) - 1:
            out.append(f"rid {rid}: decode_emit tokens {got} != "
                       f"generated-1 ({int(gen) - 1})")
    return out


# ------------------------------------------------------------- aggregates

def decomposition(flights: Dict[int, Dict[str, Any]]
                  ) -> Dict[str, Dict[str, Any]]:
    """p50/p99 of each TTFT/e2e component across the reconstructed
    flights (nearest-rank, same percentile the SLO grader uses)."""
    comps: Dict[str, List[float]] = {
        "ttft": [], "queue_wait": [], "prefill": [], "decode": [],
        "e2e": []}
    for f in flights.values():
        for e in f["events"]:
            ev = e.get("event")
            if ev == res_lib.ADMITTED:
                for key, field in (("ttft", "waited_s"),
                                   ("queue_wait", "queue_wait_s"),
                                   ("prefill", "prefill_s")):
                    if e.get(field) is not None:
                        comps[key].append(float(e[field]))
            elif ev in (res_lib.DONE, res_lib.EVICTED):
                for key, field in (("decode", "decode_s"),
                                   ("e2e", "e2e_s")):
                    if e.get(field) is not None:
                        comps[key].append(float(e[field]))
    out: Dict[str, Dict[str, Any]] = {}
    for key, vals in comps.items():
        p50 = slo_lib.percentile(vals, 50)
        p99 = slo_lib.percentile(vals, 99)
        out[key] = {"n": len(vals),
                    "p50_s": round(p50, 6) if p50 is not None else None,
                    "p99_s": round(p99, 6) if p99 is not None else None}
    return out


def shed_timeline(flights: Dict[int, Dict[str, Any]], *,
                  limit: int = 100) -> List[Dict[str, Any]]:
    """The non-completion terminals in time order (when sheds, expiries
    and evictions clustered tells the capacity story): up to ``limit``
    of them, each ``{t_s, rid, event}``."""
    rows: List[Dict[str, Any]] = []
    for f in flights.values():
        for e in f["events"]:
            ev = e.get("event")
            if ev in (res_lib.SHED, res_lib.EXPIRED, res_lib.REJECTED,
                      res_lib.EVICTED, res_lib.LOST):
                rows.append({"t_s": e.get("t_s"), "rid": f["rid"],
                             "event": ev})
    rows.sort(key=lambda r: (r["t_s"] is None, r["t_s"], r["rid"]))
    return rows[:limit]


# --------------------------------------------------------------- loading

def load_metrics(path: str) -> List[Dict[str, Any]]:
    """metrics.jsonl as a record list; malformed lines are skipped (a
    crash mid-write leaves at most one torn tail line)."""
    out: List[Dict[str, Any]] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out


def load_trace(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None


def find_partition(records: List[Dict[str, Any]]
                   ) -> Tuple[Optional[Dict[str, Any]], int]:
    """(partition, requeue_attempt) from the last ``kind=serve``
    summary record. Bitwise reconciliation is only sound on attempt 0:
    a resumed attempt's ledger partitions only ITS OWN arrivals while
    the replayed event stream spans every attempt."""
    part: Optional[Dict[str, Any]] = None
    attempt = 0
    for rec in records:
        if rec.get("kind") == "serve" and rec.get("partition"):
            part = rec["partition"]
            attempt = int(rec.get("requeue_attempt") or 0)
    return part, attempt


# -------------------------------------------------------------------- CLI

def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpudist.serve.flight",
        description="Reconstruct and exactly verify per-request serve "
                    "flights from a run directory (jax-free).")
    ap.add_argument("--run-dir", default=None,
                    help="directory holding metrics.jsonl (+ optional "
                         "pod_trace.json / trace.worker0.json)")
    ap.add_argument("--metrics", default=None,
                    help="explicit metrics.jsonl path")
    ap.add_argument("--trace", default=None,
                    help="explicit trace json path")
    args = ap.parse_args(argv)
    metrics_path = args.metrics or (
        os.path.join(args.run_dir, "metrics.jsonl") if args.run_dir
        else None)
    if not metrics_path or not os.path.exists(metrics_path):
        print("flight: no metrics.jsonl "
              f"({metrics_path or '--run-dir/--metrics required'})",
              file=sys.stderr)
        return 2
    records = load_metrics(metrics_path)
    trace_doc = None
    trace_path = args.trace
    if trace_path is None and args.run_dir:
        for name in ("pod_trace.json", "trace.worker0.json"):
            cand = os.path.join(args.run_dir, name)
            if os.path.exists(cand):
                trace_path = cand
                break
    if trace_path:
        trace_doc = load_trace(trace_path)
    flights = reconstruct(records, trace_doc)
    if not flights:
        print("flight: no serve_request events in "
              f"{metrics_path}", file=sys.stderr)
        return 2
    partition, attempt = find_partition(records)
    if attempt != 0:
        # see find_partition: cross-attempt reconciliation is the
        # drill verifier's job, not a bitwise identity
        partition = None
    res = verify(flights, partition)
    c = res["counts"]
    print(f"flight: {res['flights']} flights reconstructed — "
          f"admitted {c['admitted']} (done {c['completed']}, evicted "
          f"{c['evicted']}, lost {c['lost']}), shed "
          f"{c['shed_at_admission']}, expired {c['expired_in_queue']}, "
          f"rejected {c['rejected']}")
    print(f"flight: ttft decomposition worst "
          f"{res['ttft_decomp_worst_s']:.2e} s over "
          f"{res['decomposed']} admitted flights "
          f"(tol {res['ttft_decomp_tol_s']:.2e}, "
          f"{res['ttft_decomp_status']}); partition "
          f"{'reconciled' if res['partition_checked'] else 'not checked'}"
          f"; trace cross-checked {res['trace_checked']} flights")
    for p in res["problems"]:
        print(f"flight: PROBLEM: {p}", file=sys.stderr)
    print(f"flight: {'EXACT' if res['exact'] else 'INEXACT'}")
    return 0 if res["exact"] else 1


if __name__ == "__main__":
    sys.exit(main())
