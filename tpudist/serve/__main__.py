import sys

from tpudist.serve.cli import main

sys.exit(main())
