"""Serve resilience: admission control, deadline shedding, degradation.

The serving loop (PR 9) *measured* SLOs but could not defend them: an
open-loop Poisson stream past the engine's capacity queued unboundedly,
every request's TTFT grew with the backlog, and the percentile summary
dutifully reported a pod that was 100% busy and 100% useless. This
module is the host-side control plane that makes overload a bounded,
exactly-accounted event instead of a poisoned histogram:

* **Admission control** — a bounded request queue (``queue_cap``) with
  a per-request TTFT deadline (``ttft_deadline_s``). Arrivals past the
  cap are shed AT ADMISSION; accepted requests that age past their
  deadline while still queued are EXPIRED before they ever touch a
  slot. Both decisions read ONE clock sample per scheduler boundary
  (no wall-clock reads inside the decision path), so the same seeded
  arrival schedule sheds the same requests every run.
* **Exact accounting** — :class:`ShedLedger` partitions every arrival
  into mutually exclusive buckets and checks the partition exactly
  (the PR 10 goodput discipline)::

      arrived  == admitted + shed_admission + expired_queue + rejected
      admitted == completed + evicted + lost

  ``admitted`` means *reached a slot* (prefilled); ``rejected`` is the
  ``request_garbage`` chaos family's bucket (malformed requests turned
  away at validation, never crashing the engine); ``lost`` is an
  in-flight slot a kill took — classified honestly by the resumed
  attempt, never re-served.
* **Graceful degradation** — :class:`PressureController`: when rolling
  queue depth or inter-token latency crosses its trip thresholds for
  ``trip_ticks`` consecutive observations, the scheduler downshifts
  ``decode_k`` one rung of the engine's pre-compiled ladder (and
  optionally truncates ``max_new`` at admission); pressure clearing
  below the (lower) clear thresholds for ``clear_ticks`` observations
  restores one rung. Dual thresholds + consecutive-tick counters +
  reset-on-transition are the hysteresis that keeps a scripted load
  step from oscillating the ladder.
* **Virtual time** — :class:`VirtualClock`/:class:`VirtualTiming`: the
  drill mode where the request clock is a deterministic function of
  the schedule (fixed per-prefill / per-dispatch costs advance it, the
  real engine still computes every token). Two runs of the same seed
  produce bitwise-identical SLO summaries — the property the jax-free
  overload verifier (:mod:`tpudist.serve.drill`) pins.

Stdlib-only by design, like :mod:`tpudist.rules` and
:mod:`tpudist.serve.slo`: the drill driver and verifier import this on
launcher/CI hosts with no accelerator stack.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# serve_request event vocabulary (the ``event`` field of the flushed
# ``kind=serve_request`` records the scheduler writes; the drill
# verifier replays these to re-derive the partition cross-attempt)
ADMITTED = "admitted"          # reached a slot (prefill dispatched)
SHED = "shed_admission"        # bounced: queue at cap when it arrived
EXPIRED = "expired_queue"      # aged past its TTFT deadline in queue
REJECTED = "rejected"          # malformed (request_garbage) at admission
DONE = "done"                  # completed its generation budget
EVICTED = "evicted"            # truncated at a full cache page
LOST = "lost"                  # in-flight slot a kill took (classified
#                                by the resumed attempt)

TERMINAL_EVENTS = (SHED, EXPIRED, REJECTED, DONE, EVICTED, LOST)

# The two chain stages of a request flight, used by the flight ledger
# (serve/flight.py) to assert the span-chain grammar: every arrival gets
# EXACTLY ONE admission-stage event; ADMITTED flights get EXACTLY ONE
# outcome-stage event; the other admission verdicts ARE the terminal.
# Kept here beside the vocabulary so the grammar and the spellings
# cannot drift apart.
ADMISSION_EVENTS = (ADMITTED, SHED, EXPIRED, REJECTED)
OUTCOME_EVENTS = (DONE, EVICTED, LOST)


@dataclass
class ShedLedger:
    """Mutually-exclusive outcome buckets for every arrival, checked
    exactly — a request that is double-counted or dropped on the floor
    flips ``exact`` to False, and the drill verifier exits nonzero."""

    arrived: int = 0
    admitted: int = 0           # reached a slot
    shed_admission: int = 0
    expired_queue: int = 0
    rejected: int = 0
    completed: int = 0          # finished: full budget (why=done)
    evicted: int = 0            # finished: truncated at a full page
    lost: int = 0               # in-flight at a kill (resumed attempt)

    def admission_exact(self) -> bool:
        return self.arrived == (self.admitted + self.shed_admission
                                + self.expired_queue + self.rejected)

    def outcome_exact(self) -> bool:
        return self.admitted == self.completed + self.evicted + self.lost

    @property
    def exact(self) -> bool:
        return self.admission_exact() and self.outcome_exact()

    def shed_total(self) -> int:
        """Arrivals turned away without service — the Prometheus
        ``tpudist_serve_shed_total`` counter."""
        return self.shed_admission + self.expired_queue + self.rejected

    def shed_fraction(self) -> Optional[float]:
        """Shed share of all arrivals; None before the first arrival
        (nothing measured is ungateable, not a clean 0.0)."""
        if self.arrived <= 0:
            return None
        return self.shed_total() / self.arrived

    def as_dict(self) -> Dict[str, Any]:
        return {
            "arrived": self.arrived, "admitted": self.admitted,
            "shed_at_admission": self.shed_admission,
            "expired_in_queue": self.expired_queue,
            "rejected": self.rejected, "completed": self.completed,
            "evicted": self.evicted, "lost": self.lost,
            "shed_total": self.shed_total(),
            "shed_fraction": self.shed_fraction(),
            "admission_exact": self.admission_exact(),
            "outcome_exact": self.outcome_exact(),
        }


@dataclass(frozen=True)
class ResilienceConfig:
    """The admission/degradation knobs one serve run applies.

    Zero values mean OFF and reproduce the pre-resilience scheduler
    exactly (unbounded queue, no deadlines, fixed decode_k) — the
    default serve lane's behavior is unchanged until an operator opts
    in with ``--queue-cap``/``--ttft-deadline-ms``/``--adapt``.
    """

    queue_cap: int = 0              # 0 = unbounded
    ttft_deadline_s: float = 0.0    # 0 = no deadline
    adapt: bool = False             # pressure-driven decode_k downshift
    max_new_cap: int = 0            # adapted admission truncation (0=off)
    validate: bool = False          # reject malformed requests
    # pressure thresholds (adapt=True): rolling queue depth and mean
    # per-token latency trip/clear levels, in the controller's units
    depth_high: float = 8.0
    depth_low: float = 2.0
    itl_high_s: float = 0.0         # 0 = depth-only pressure
    itl_low_s: float = 0.0
    trip_ticks: int = 2
    clear_ticks: int = 4
    window: int = 8

    @property
    def enabled(self) -> bool:
        return bool(self.queue_cap or self.ttft_deadline_s
                    or self.adapt or self.validate)


def default_ladder(decode_k: int, levels: int = 3) -> Tuple[int, ...]:
    """The degradation ladder for ``decode_k``: each rung halves the
    superstep (shorter dispatches drain the queue sooner and cut the
    per-token amortised stall under pressure), floored at 1 and
    deduplicated — ``(8, 4, 2)``, ``(2, 1)``, ``(1,)``."""
    out: List[int] = []
    k = max(int(decode_k), 1)
    for _ in range(max(levels, 1)):
        if not out or out[-1] != k:
            out.append(k)
        if k == 1:
            break
        k = max(1, (k + 1) // 2)
    return tuple(out)


class PressureController:
    """Hysteretic level controller over (queue depth, inter-token
    latency) observations.

    ``observe()`` is called on the scheduler's SLO tick cadence; it
    returns a ``(from_level, to_level, reason)`` transition exactly
    when the ladder moves, else None. Level 0 is full service; higher
    levels are deeper degradation (the scheduler maps them onto the
    engine's decode_k ladder and the admission-time ``max_new`` cap).

    Hysteresis, spelled out: a downshift needs ``trip_ticks``
    CONSECUTIVE observations past the high thresholds; an upshift
    needs ``clear_ticks`` consecutive observations below the (strictly
    lower) low thresholds; any transition resets both counters. A load
    step that parks pressure between the two thresholds therefore
    holds the current level forever instead of oscillating.
    """

    def __init__(self, cfg: ResilienceConfig, *, max_level: int):
        self.cfg = cfg
        self.max_level = max(int(max_level), 0)
        self.level = 0
        self._hot = 0
        self._cool = 0
        self._depths: List[float] = []
        self.transitions: List[Dict[str, Any]] = []

    def _rolling_depth(self, depth: float) -> float:
        self._depths.append(float(depth))
        if len(self._depths) > max(self.cfg.window, 1):
            self._depths.pop(0)
        return sum(self._depths) / len(self._depths)

    def observe(self, depth: float, itl_s: Optional[float] = None
                ) -> Optional[Tuple[int, int, str]]:
        mean_depth = self._rolling_depth(depth)
        itl = itl_s if (itl_s is not None and self.cfg.itl_high_s > 0) \
            else None
        hot = mean_depth > self.cfg.depth_high \
            or (itl is not None and itl > self.cfg.itl_high_s)
        cool = mean_depth <= self.cfg.depth_low \
            and (itl is None or itl <= (self.cfg.itl_low_s
                                        or self.cfg.itl_high_s))
        self._hot = self._hot + 1 if hot else 0
        self._cool = self._cool + 1 if cool else 0
        if hot and self.level < self.max_level \
                and self._hot >= max(self.cfg.trip_ticks, 1):
            return self._move(self.level + 1,
                              f"pressure: rolling depth "
                              f"{mean_depth:.2f} / itl {itl}")
        if cool and self.level > 0 \
                and self._cool >= max(self.cfg.clear_ticks, 1):
            return self._move(self.level - 1,
                              f"cleared: rolling depth "
                              f"{mean_depth:.2f} / itl {itl}")
        return None

    def _move(self, to_level: int, reason: str
              ) -> Tuple[int, int, str]:
        frm, self.level = self.level, to_level
        self._hot = self._cool = 0       # reset: the hysteresis anchor
        t = (frm, to_level, reason)
        self.transitions.append({"from_level": frm, "to_level": to_level,
                                 "reason": reason})
        return t


class VirtualClock:
    """A deterministic request clock the scheduler advances by scripted
    costs instead of reading wall time. Callable (drop-in for the
    scheduler's ``clock=``), monotone, and shared by every decision in
    the run — the whole serve summary becomes a pure function of
    (seed, schedule, costs)."""

    def __init__(self, t0: float = 0.0):
        self.t = float(t0)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += max(float(dt), 0.0)
        return self.t

    def wait_until(self, t: float) -> float:
        self.t = max(self.t, float(t))
        return self.t


@dataclass
class VirtualTiming:
    """Virtual-time mode for :func:`tpudist.serve.scheduler.run_serve`:
    each prefill advances the clock ``prefill_s``, each decode dispatch
    ``decode_s`` (plus whatever stall the chaos runtime injected). The
    engine still runs for real — only the latency accounting is
    scripted, which is exactly what makes the overload drill's shed
    decisions and percentiles bitwise reproducible."""

    clock: VirtualClock = field(default_factory=VirtualClock)
    prefill_s: float = 0.002
    decode_s: float = 0.004
