"""tpudist.serve — batched inference engine with latency-SLO verdicts.

The fourth subsystem beside ``elastic/``, ``tune/`` and ``obs/``: the
"millions of users" half of the north star. A prefill/decode-split
engine over the training models (``models.transformer`` / ``models.moe``
grow a cache-aware incremental path — serve does not fork the model),
with

* an incremental KV cache sharded on the existing mesh machinery
  (per-sequence slots, GQA-compact head layout,
  ``parallel.sharding.kv_cache_specs``) — :mod:`tpudist.serve.kvcache`;
* exactly TWO compiled programs per run — one prefill, one ``lax.scan``
  decode superstep over the whole slot batch — :mod:`tpudist.serve.engine`;
* a continuous-batching scheduler: Poisson arrivals, admission into
  free slots, mid-scan completion — :mod:`tpudist.serve.scheduler`;
* latency-SLO verdicts (p50/p99 TTFT, inter-token latency, tokens/s/chip)
  through the shared :mod:`tpudist.rules` table —
  :mod:`tpudist.serve.slo`;
* a measured-probe autotuner for decode batch size and KV layout on the
  PR-4 fingerprint-cache machinery — :mod:`tpudist.serve.tune`;
* the resilience plane (PR 15): admission control with deadline-based
  load shedding and an exactly-checked arrival partition, a hysteretic
  pressure controller over a pre-compiled decode_k ladder, and honest
  lost-slot accounting under the launcher's requeue loop —
  :mod:`tpudist.serve.resilience`;
* the jax-free overload + serve fault drill and its invariant verifier
  (``python -m tpudist.serve.drill``) — :mod:`tpudist.serve.drill`.

Entry point: ``python -m tpudist.serve`` (:mod:`tpudist.serve.cli`).

This ``__init__`` stays jax-free (only :mod:`tpudist.serve.slo` is
imported eagerly): the offline report CLI imports the SLO math on
machines with no accelerator stack installed.
"""

from tpudist.serve.slo import (LatencyStats, grade, percentile,  # noqa: F401
                               serve_status)

__all__ = ["LatencyStats", "grade", "percentile", "serve_status"]
