"""The prefill/decode-split serving engine: exactly TWO compiled programs.

The pjit/TPUv4 discipline that keeps the training loop honest (one
compiled program per run, traced scalars for everything that varies)
applies doubly to serving, where continuous batching changes the live
request set every few milliseconds: a recompile per admission would
bury the latency SLO. So the engine compiles exactly two programs and
pins it (``prefill.traces`` / ``decode.traces``, asserted in tests and
the CI lane):

* **prefill** — one request into one slot: full causal forward over the
  padded prompt (the model's cache-aware path — ``hidden_states(...,
  kv_cache=)`` — seeds the slot's KV columns), first token by greedy
  argmax at the prompt's true last position. Slot index, prompt length
  and the generation budget are traced scalars; the prompt is padded to
  a fixed ``prompt_pad`` so every admission reuses the one program.
* **decode** — a ``lax.scan`` superstep of ``decode_k`` steps over the
  WHOLE slot batch. Per-slot active masks (``jnp.where`` on every state
  update) keep finished/empty slots frozen, and a ``lax.cond`` skips an
  iteration outright when NO slot is active (mid-scan completion of the
  last request — the same masking discipline that kept PR 2's padded
  superstep bitwise) — so one compiled program serves every batch
  occupancy from full to empty.

With graceful degradation on (``adapt_ladder``), the contract
generalises to one decode program PER LADDER RUNG, all compiled at
warmup: a pressure downshift switches programs, it never traces one.


Greedy decoding is a pure function of (params, state), so runs are
bitwise reproducible; decode-with-cache logits are pinned ULP-close to
the full forward (tests/test_serve.py).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tpudist.config import ModelConfig
from tpudist.engine import _arg_specs
from tpudist.models import get_model
from tpudist.parallel import sharding as shd
from tpudist.serve import kvcache
from tpudist.utils import compat


class ServeState(NamedTuple):
    """Device-resident serving state — the scan carry of the decode
    superstep and the donation target of both programs."""

    cache_k: jax.Array       # (L, slots, ...) in the storage layout
    cache_v: jax.Array
    lengths: jax.Array       # (slots,) int32: tokens in cache per slot
    last_token: jax.Array    # (slots,) int32: newest token, not yet cached
    active: jax.Array        # (slots,) bool: slot holds a live sequence
    remaining: jax.Array     # (slots,) int32: generation budget left


def init_params(model_cfg: ModelConfig, mesh, seed: int = 0):
    """Seeded model params placed to their sanitised param_specs layout
    — the same init + sharding recipe the training engine uses, minus
    the optimizer state serving has no use for."""
    model = get_model(model_cfg.name)
    params = model.init(jax.random.PRNGKey(seed), model_cfg)
    shape = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), model_cfg))
    pspecs = shd.sanitize_specs(shape, model.param_specs(model_cfg), mesh)
    return jax.device_put(params, shd.named(mesh, pspecs))


class ServeEngine:
    """Builds and owns the two compiled programs plus the state layout.

    ``prompt_pad`` is the static prompt width every admission pads to;
    ``decode_k`` the superstep length (tokens per dispatch per slot);
    ``layout`` the KV storage layout (:mod:`tpudist.serve.kvcache`).

    ``adapt_ladder`` is the graceful-degradation rung set
    (:func:`tpudist.serve.resilience.default_ladder`): ONE decode
    program is compiled per distinct ``k`` at warmup, so the pressure
    controller downshifting mid-run switches to an already-compiled
    program — the latency SLO never pays a recompile for degrading.
    The default ladder is ``(decode_k,)``, which keeps the original
    two-program contract bit-for-bit.
    """

    paged = False          # the scheduler branches on this, not on type
    speculate_k = 0        # speculation is a paged-engine feature

    def __init__(self, model_cfg: ModelConfig, mesh, *, slots: int,
                 max_seq: int, prompt_pad: int, decode_k: int = 8,
                 layout: str = "st", dtype=jnp.float32,
                 adapt_ladder: Optional[Sequence[int]] = None):
        if slots < 1:
            raise ValueError(f"--slots must be >= 1, got {slots}")
        if decode_k < 1:
            raise ValueError(
                f"--decode-steps-per-dispatch must be >= 1, got {decode_k}")
        if not 0 < prompt_pad <= max_seq:
            raise ValueError(
                f"prompt_pad {prompt_pad} must be in (0, max_seq "
                f"{max_seq}]")
        self.model_cfg = model_cfg
        self.model = get_model(model_cfg.name)
        self.mesh = mesh
        self.slots, self.max_seq = int(slots), int(max_seq)
        self.prompt_pad, self.decode_k = int(prompt_pad), int(decode_k)
        ladder = tuple(int(k) for k in (adapt_ladder or (decode_k,)))
        if not ladder or ladder[0] != self.decode_k:
            raise ValueError(
                f"adapt_ladder {ladder} must start at decode_k "
                f"{self.decode_k} (level 0 = full service)")
        if any(k < 1 for k in ladder) \
                or any(a <= b for a, b in zip(ladder, ladder[1:])):
            raise ValueError(
                f"adapt_ladder {ladder} must be strictly descending "
                f"positive superstep lengths")
        self.ladder = ladder
        self.layout, self.dtype = layout, dtype
        self.spec = kvcache.CacheSpec.from_model(
            model_cfg, slots=slots, max_seq=max_seq, dtype=dtype,
            layout=layout)
        self.prefill_traces: list = []
        self.decode_traces: list = []
        # per-program lowering skeletons, captured at each program's
        # first call (program_memory / the memledger's per-program
        # memory_analysis reads these off the request clock)
        self._programs: dict = {}
        self._prefill = jax.jit(self._prefill_body, donate_argnums=(1,))
        # k is STATIC (it is the lax.scan length): one compiled decode
        # program per ladder rung, all traced at warmup
        self._decode = jax.jit(self._decode_body, static_argnums=(2,),
                               donate_argnums=(1,))

    # ----------------------------------------------------------- state

    def init_state(self) -> ServeState:
        cache = kvcache.init_cache(self.spec, self.mesh)
        rep = shd.replicated(self.mesh)
        vec = lambda v: jax.device_put(v, rep)
        s = self.slots
        return ServeState(
            cache_k=cache["k"], cache_v=cache["v"],
            lengths=vec(jnp.zeros((s,), jnp.int32)),
            last_token=vec(jnp.zeros((s,), jnp.int32)),
            active=vec(jnp.zeros((s,), bool)),
            remaining=vec(jnp.zeros((s,), jnp.int32)))

    # --------------------------------------------------------- prefill

    def _tied_logits(self, params, h):
        emb = params["embed"].astype(self.dtype)
        return (h @ emb.T).astype(jnp.float32)

    def _prefill_body(self, params, state: ServeState, tokens,
                      prompt_len, slot, max_new
                      ) -> Tuple[ServeState, jax.Array]:
        self.prefill_traces.append(1)   # trace-time compile marker
        # the slot's cache page, in canonical layout for the model
        ck = lax.dynamic_slice_in_dim(state.cache_k, slot, 1, axis=1)
        cv = lax.dynamic_slice_in_dim(state.cache_v, slot, 1, axis=1)
        cache = {"k": kvcache.to_canonical(ck, self.layout),
                 "v": kvcache.to_canonical(cv, self.layout)}
        h, cache = self.model.hidden_states(
            params, tokens, self.model_cfg, dtype=self.dtype,
            kv_cache=cache, cur_index=None)
        # greedy first token from the prompt's true last position — the
        # padded tail's hidden states exist but are never consulted
        h_last = lax.dynamic_index_in_dim(h, prompt_len - 1, axis=1,
                                          keepdims=False)
        first = jnp.argmax(self._tied_logits(params, h_last),
                           axis=-1).astype(jnp.int32)[0]
        zeros = (0,) * (state.cache_k.ndim - 2)
        ck = lax.dynamic_update_slice(
            state.cache_k, kvcache.from_canonical(cache["k"], self.layout),
            (0, slot) + zeros)
        cv = lax.dynamic_update_slice(
            state.cache_v, kvcache.from_canonical(cache["v"], self.layout),
            (0, slot) + zeros)
        rem = max_new - 1            # the prefill itself produced token 1
        active = (rem > 0) & (prompt_len < self.max_seq)
        return ServeState(
            cache_k=ck, cache_v=cv,
            lengths=state.lengths.at[slot].set(prompt_len),
            last_token=state.last_token.at[slot].set(first),
            active=state.active.at[slot].set(active),
            remaining=state.remaining.at[slot].set(
                jnp.where(active, rem, 0))), first

    def _note_program(self, name: str, jitted, args,
                      static_idx: Tuple[int, ...] = ()) -> None:
        """Remember how to ``.lower()`` one pinned program: shape/
        dtype/sharding skeletons of its first call's traced arguments
        (``engine._arg_specs`` — no buffer kept alive, the donation
        contract survives) with static arguments kept verbatim in
        place. A dict-membership check per call on the hot path,
        nothing more."""
        if name in self._programs:
            return
        statics = set(static_idx)
        dyn = iter(_arg_specs(tuple(
            a for i, a in enumerate(args) if i not in statics)))
        lower_args = tuple(a if i in statics else next(dyn)
                           for i, a in enumerate(args))
        self._programs[name] = (jitted, lower_args)

    def program_memory(self) -> dict:
        """``{program_name: memory_analysis dict}`` for every pinned
        program the run has called — prefill, each decode-ladder rung,
        the speculative verify. An empty dict per program on backends
        without memory planning (the memledger records the gap as a
        note); lowering hits jit's trace cache, so this is cheap and
        off the request clock."""
        out: dict = {}
        for name, (jitted, lower_args) in sorted(self._programs.items()):
            try:
                out[name] = compat.memory_analysis(
                    jitted.lower(*lower_args).compile())
            except Exception:
                out[name] = {}
        return out

    def prefill(self, params, state: ServeState, tokens, prompt_len: int,
                slot: int, max_new: int) -> Tuple[ServeState, jax.Array]:
        """Admit one request into ``slot``. ``tokens`` is the padded
        (1, prompt_pad) prompt; scalars go in as traced int32 so every
        admission reuses the one compiled program. Returns the updated
        state and the request's FIRST generated token (a device scalar
        — ``int()`` it to fence)."""
        tokens = jnp.asarray(tokens, jnp.int32).reshape(1, self.prompt_pad)
        args = (params, state, tokens, jnp.int32(prompt_len),
                jnp.int32(slot), jnp.int32(max_new))
        self._note_program("prefill", self._prefill, args)
        return self._prefill(*args)

    # ---------------------------------------------------------- decode

    def _decode_body(self, params, state: ServeState, k: int
                     ) -> Tuple[ServeState, jax.Array, jax.Array]:
        self.decode_traces.append(k)    # trace-time compile marker
        slots = self.slots

        def step(st: ServeState, _):
            def run(st: ServeState):
                # write position per slot; inactive slots' (discarded)
                # junk write is clamped in-bounds so a completed full
                # slot can never scatter out of range
                pos = jnp.minimum(st.lengths, self.max_seq - 1)
                cache = {"k": kvcache.to_canonical(st.cache_k,
                                                   self.layout),
                         "v": kvcache.to_canonical(st.cache_v,
                                                   self.layout)}
                h, cache = self.model.hidden_states(
                    params, st.last_token[:, None], self.model_cfg,
                    dtype=self.dtype, kv_cache=cache, cur_index=pos)
                nxt = jnp.argmax(self._tied_logits(params, h[:, 0]),
                                 axis=-1).astype(jnp.int32)
                act = st.active
                new_len = jnp.where(act, st.lengths + 1, st.lengths)
                new_rem = jnp.where(act, st.remaining - 1, st.remaining)
                new_state = ServeState(
                    cache_k=kvcache.from_canonical(cache["k"],
                                                   self.layout),
                    cache_v=kvcache.from_canonical(cache["v"],
                                                   self.layout),
                    lengths=new_len,
                    last_token=jnp.where(act, nxt, st.last_token),
                    # a slot completes on budget exhaustion or a full
                    # cache page (forced eviction at max_seq)
                    active=act & (new_rem > 0) & (new_len < self.max_seq),
                    remaining=new_rem)
                return new_state, jnp.where(act, nxt, -1), act

            def skip(st: ServeState):
                # nothing active (the batch emptied mid-scan): pass the
                # state through untouched — same cond discipline that
                # kept the training superstep's padded tail bitwise
                return (st, jnp.full((slots,), -1, jnp.int32),
                        jnp.zeros((slots,), bool))

            st, tok, valid = lax.cond(st.active.any(), run, skip, st)
            return st, (tok, valid)

        state, (toks, valid) = lax.scan(step, state, None, length=k)
        return state, toks, valid

    def decode(self, params, state: ServeState, k: Optional[int] = None
               ) -> Tuple[ServeState, jax.Array, jax.Array]:
        """One decode superstep: up to ``k`` (default ``decode_k``)
        tokens for every active slot. ``k`` must be a warmed ladder
        rung — any other value would trace a new program mid-run and
        break the program-budget pin. Returns ``(state, tokens (k,
        slots), valid (k, slots))`` — entries with ``valid=False`` are
        placeholders (-1) and must not be read. Async: fence on the
        returned tokens."""
        k = self.decode_k if k is None else int(k)
        if k not in self.ladder:
            # fail at the fault site: a foreign k would silently trace
            # a NEW program mid-run — charging XLA compilation to
            # exactly the latency a downshift is trying to relieve —
            # and only surface at the end-of-run program pin, if ever
            raise ValueError(
                f"decode k={k} is not a warmed ladder rung "
                f"{self.ladder}")
        self._note_program(f"decode_k{k}", self._decode,
                           (params, state, k), static_idx=(2,))
        return self._decode(params, state, k)

    # ---------------------------------------------------------- warmup

    def warmup(self, params) -> None:
        """Compile every program OFF the request clock: a cold first
        admission would charge XLA compilation to that request's TTFT,
        and a cold ladder rung would charge a recompile to the very
        overload the downshift is trying to relieve. Runs a dummy
        prefill + one decode superstep PER LADDER RUNG on a throwaway
        state (donated away), fences, and leaves the jit caches warm —
        after this, a whole serve run (adapt transitions included)
        compiles nothing (``assert_two_programs``)."""
        state = self.init_state()
        dummy = jnp.zeros((1, self.prompt_pad), jnp.int32)
        state, first = self.prefill(params, state, dummy, 1, 0, 2)
        jax.device_get(first)
        for k in self.ladder:
            state, toks, valid = self.decode(params, state, k)
            jax.device_get((toks, valid))

    def compile_counts(self) -> Tuple[int, int]:
        return len(self.prefill_traces), len(self.decode_traces)

    def assert_two_programs(self) -> None:
        """The compiled-program pin: one prefill + one decode trace PER
        LADDER RUNG for the whole run, warmup included — exactly two
        programs on the default single-rung ladder, and never a trace
        the warmup didn't already pay."""
        p, d = self.compile_counts()
        want = (1, len(self.ladder))
        if (p, d) != want:
            raise AssertionError(
                f"serve engine compiled {p} prefill / {d} decode "
                f"program(s), expected {want[0]}/{want[1]} for ladder "
                f"{self.ladder}; the two-program contract is broken")


class PagedServeState(NamedTuple):
    """Device-resident PAGED serving state. Unlike :class:`ServeState`
    there is no per-slot cache arena: K/V live in one shared pool of
    fixed-size pages (+1 trash page) and the slot→page mapping is HOST
    state (``PageAllocator.table``), passed into every dispatch as a
    small traced int32 array."""

    pool_k: jax.Array        # (L, pages+1, page_tokens, kv, head_dim)
    pool_v: jax.Array
    lengths: jax.Array       # (slots,) int32: tokens in cache per slot
    last_token: jax.Array    # (slots,) int32: newest token, not yet cached
    active: jax.Array        # (slots,) bool: slot holds a live sequence
    remaining: jax.Array     # (slots,) int32: generation budget left


class PagedServeEngine(ServeEngine):
    """The paged + shared-prefix + speculative serving engine.

    Same compiled-program discipline as the dense engine — ONE prefill
    program, one decode program per ladder rung — generalised by one
    more pinned program when speculation is on: the VERIFY forward, a
    single batched target forward over a ``speculate_k``-token window
    per slot that scores a whole host-proposed draft at once. Page
    table and per-dispatch active mask ride as small traced arrays
    (fixed shapes → no retrace); admission, eviction, page exhaustion
    and drafting are pure host decisions between dispatches.

    ``speculate_k`` is the verify WINDOW width: the window carries the
    slot's pending ``last_token`` plus ``speculate_k - 1`` draft tokens,
    so ``speculate_k >= 2`` turns speculation on (a window of 1 is
    plain decode) and ``0`` turns it off. Greedy token output is
    bitwise-identical to non-speculative greedy decode by construction:
    every emitted token is the argmax after a verified-correct token,
    and rejected drafts' junk KV sits at positions beyond the new
    length, where write-then-attend overwrites it before any query can
    attend it.
    """

    paged = True

    def __init__(self, model_cfg: ModelConfig, mesh, *, slots: int,
                 max_seq: int, prompt_pad: int, decode_k: int = 8,
                 page_tokens: int = 8, pages: int = 0,
                 speculate_k: int = 0, dtype=jnp.float32,
                 adapt_ladder: Optional[Sequence[int]] = None):
        super().__init__(model_cfg, mesh, slots=slots, max_seq=max_seq,
                         prompt_pad=prompt_pad, decode_k=decode_k,
                         layout="st", dtype=dtype,
                         adapt_ladder=adapt_ladder)
        if speculate_k == 1 or speculate_k < 0:
            raise ValueError(
                f"--speculate-k must be 0 (off) or >= 2 (window of "
                f"last_token + drafts), got {speculate_k}")
        self.speculate_k = int(speculate_k)
        self.spec = kvcache.PagedCacheSpec.from_model(
            model_cfg, slots=slots, max_seq=max_seq,
            page_tokens=page_tokens, pages=pages, dtype=dtype)
        self.page_tokens = self.spec.page_tokens
        self.alloc = kvcache.PageAllocator(self.spec)
        self.verify_traces: list = []
        self._prefill = jax.jit(self._paged_prefill_body,
                                donate_argnums=(1,))
        self._decode = jax.jit(self._paged_decode_body,
                               static_argnums=(2,), donate_argnums=(1,))
        self._verify = jax.jit(self._paged_verify_body,
                               donate_argnums=(1,))

    def new_allocator(self) -> kvcache.PageAllocator:
        """Fresh page bookkeeping (drops any shared-prefix registry) —
        one allocator per serve run, like one state per run."""
        self.alloc = kvcache.PageAllocator(self.spec)
        return self.alloc

    # ----------------------------------------------------------- state

    def init_state(self) -> PagedServeState:
        cache = kvcache.init_paged_cache(self.spec, self.mesh)
        rep = shd.replicated(self.mesh)
        vec = lambda v: jax.device_put(v, rep)
        s = self.slots
        return PagedServeState(
            pool_k=cache["k"], pool_v=cache["v"],
            lengths=vec(jnp.zeros((s,), jnp.int32)),
            last_token=vec(jnp.zeros((s,), jnp.int32)),
            active=vec(jnp.zeros((s,), bool)),
            remaining=vec(jnp.zeros((s,), jnp.int32)))

    # --------------------------------------------------------- prefill

    def _paged_prefill_body(self, params, state: PagedServeState,
                            tokens, prompt_len, slot, max_new, page_row,
                            shared_len
                            ) -> Tuple[PagedServeState, jax.Array]:
        self.prefill_traces.append(1)   # trace-time compile marker
        spec = self.spec
        pt = spec.page_tokens
        # dense prefill into a throwaway scratch row — the model's
        # existing cache-aware full forward, so the K/V bytes are
        # BITWISE the ones the dense engine would store — then scatter
        # the slot's true positions into its pages. Positions below
        # ``shared_len`` are skipped (their pages are the shared prefix,
        # already holding bitwise-identical content); the padded tail
        # and the skipped prefix route to the trash page.
        scratch_shape = (spec.n_layers, 1, self.prompt_pad,
                         spec.n_kv_heads, spec.head_dim)
        scratch = {"k": jnp.zeros(scratch_shape, self.dtype),
                   "v": jnp.zeros(scratch_shape, self.dtype)}
        h, scratch = self.model.hidden_states(
            params, tokens, self.model_cfg, dtype=self.dtype,
            kv_cache=scratch, cur_index=None)
        h_last = lax.dynamic_index_in_dim(h, prompt_len - 1, axis=1,
                                          keepdims=False)
        first = jnp.argmax(self._tied_logits(params, h_last),
                           axis=-1).astype(jnp.int32)[0]
        t = jnp.arange(self.prompt_pad)
        write = (t >= shared_len) & (t < prompt_len)
        pg = page_row[t // pt]
        pg = jnp.where(write & (pg >= 0), pg, spec.pages)  # else: trash
        off = t % pt
        pk = state.pool_k.at[:, pg, off].set(scratch["k"][:, 0])
        pv = state.pool_v.at[:, pg, off].set(scratch["v"][:, 0])
        rem = max_new - 1            # the prefill itself produced token 1
        active = (rem > 0) & (prompt_len < self.max_seq)
        return PagedServeState(
            pool_k=pk, pool_v=pv,
            lengths=state.lengths.at[slot].set(prompt_len),
            last_token=state.last_token.at[slot].set(first),
            active=state.active.at[slot].set(active),
            remaining=state.remaining.at[slot].set(
                jnp.where(active, rem, 0))), first

    def prefill(self, params, state: PagedServeState, tokens,
                prompt_len: int, slot: int, max_new: int,
                page_row=None, shared_len: int = 0
                ) -> Tuple[PagedServeState, jax.Array]:
        """Admit one request into ``slot``: the dense contract plus the
        slot's page-table ROW (defaults to the allocator's current row
        for ``slot``) and the shared-prefix watermark ``shared_len``
        (``alloc.admit_shared_len``) — both traced, one program."""
        tokens = jnp.asarray(tokens, jnp.int32).reshape(1, self.prompt_pad)
        if page_row is None:
            page_row = self.alloc.row(slot)
        page_row = jnp.asarray(page_row, jnp.int32).reshape(
            self.spec.max_pages_per_slot)
        args = (params, state, tokens, jnp.int32(prompt_len),
                jnp.int32(slot), jnp.int32(max_new), page_row,
                jnp.int32(shared_len))
        self._note_program("prefill", self._prefill, args)
        return self._prefill(*args)

    def register_prefix(self, params, state: PagedServeState,
                        prefix_tokens, prefix_len: int
                        ) -> PagedServeState:
        """Cache a shared system-prompt prefix ONCE, for every future
        admission: reserve its full pages (registry-held, refcounted)
        and fill them by running the ONE compiled prefill program —
        width ``prompt_pad``, ``max_new=1`` so the probe slot comes
        back inactive and its scalar entries are overwritten by the
        slot's real admission later. Causal masking makes the stored
        K/V bitwise-identical to what any full prompt starting with
        this prefix would compute for those positions. The partial tail
        page (``prefix_len % page_tokens`` positions) routes to trash
        here; admissions recompute it into their first private page —
        the copy-on-write fork, done eagerly by recomputation."""
        pages = self.alloc.register_shared(prefix_len)
        if not pages:
            return state
        row = np.full((self.spec.max_pages_per_slot,), -1, np.int32)
        row[:len(pages)] = pages
        padded = np.zeros((self.prompt_pad,), np.int32)
        padded[:prefix_len] = np.asarray(prefix_tokens)[:prefix_len]
        state, first = self.prefill(params, state, padded,
                                    prefix_len, 0, 1,
                                    page_row=row, shared_len=0)
        jax.device_get(first)
        return state

    # ---------------------------------------------------------- decode

    def _paged_decode_body(self, params, state: PagedServeState, k: int,
                           page_table, dispatch_active
                           ) -> Tuple[PagedServeState, jax.Array,
                                      jax.Array]:
        self.decode_traces.append(k)    # trace-time compile marker
        slots = self.slots

        def step(st: PagedServeState, _):
            def run(st: PagedServeState):
                act = st.active & dispatch_active
                pos = jnp.minimum(st.lengths, self.max_seq - 1)
                h, pk, pv = self.model.paged_hidden_states(
                    params, st.last_token[:, None], self.model_cfg,
                    dtype=self.dtype, pool_k=st.pool_k, pool_v=st.pool_v,
                    page_table=page_table, positions=pos[:, None],
                    write_ok=(act & (st.lengths < self.max_seq))[:, None],
                    page_tokens=self.spec.page_tokens)
                nxt = jnp.argmax(self._tied_logits(params, h[:, 0]),
                                 axis=-1).astype(jnp.int32)
                new_len = jnp.where(act, st.lengths + 1, st.lengths)
                new_rem = jnp.where(act, st.remaining - 1, st.remaining)
                # slots OUTSIDE this dispatch (their page rows may be
                # stale) keep their activity untouched
                new_active = jnp.where(
                    dispatch_active,
                    act & (new_rem > 0) & (new_len < self.max_seq),
                    st.active)
                new_state = PagedServeState(
                    pool_k=pk, pool_v=pv, lengths=new_len,
                    last_token=jnp.where(act, nxt, st.last_token),
                    active=new_active, remaining=new_rem)
                return new_state, jnp.where(act, nxt, -1), act

            def skip(st: PagedServeState):
                return (st, jnp.full((slots,), -1, jnp.int32),
                        jnp.zeros((slots,), bool))

            st, tok, valid = lax.cond(
                (st.active & dispatch_active).any(), run, skip, st)
            return st, (tok, valid)

        state, (toks, valid) = lax.scan(step, state, None, length=k)
        return state, toks, valid

    def decode(self, params, state: PagedServeState,
               k: Optional[int] = None, dispatch_active=None
               ) -> Tuple[PagedServeState, jax.Array, jax.Array]:
        """One paged decode superstep. The CURRENT page table (the host
        allocator's) and the dispatch's slot mask go in as small traced
        int32/bool arrays — fixed shapes, so every dispatch reuses the
        rung's one compiled program."""
        k = self.decode_k if k is None else int(k)
        if k not in self.ladder:
            raise ValueError(
                f"decode k={k} is not a warmed ladder rung "
                f"{self.ladder}")
        table = jnp.asarray(self.alloc.table, jnp.int32)
        if dispatch_active is None:
            da = jnp.ones((self.slots,), bool)
        else:
            da = jnp.asarray(dispatch_active, bool).reshape(self.slots)
        self._note_program(f"decode_k{k}", self._decode,
                           (params, state, k, table, da), static_idx=(2,))
        return self._decode(params, state, k, table, da)

    # ---------------------------------------------------------- verify

    def _paged_verify_body(self, params, state: PagedServeState, draft,
                           page_table, dispatch_active):
        self.verify_traces.append(1)    # trace-time compile marker
        w = self.speculate_k
        act = state.active & dispatch_active
        # window w=0 is the slot's pending last_token (always correct);
        # w>=1 are the host proposer's draft tokens
        toks_in = jnp.concatenate([state.last_token[:, None], draft],
                                  axis=1)                      # (S, W)
        offs = jnp.arange(w, dtype=jnp.int32)[None, :]
        raw_pos = state.lengths[:, None] + offs
        pos = jnp.minimum(raw_pos, self.max_seq - 1)
        write_ok = act[:, None] & (raw_pos < self.max_seq)
        h, pk, pv = self.model.paged_hidden_states(
            params, toks_in, self.model_cfg, dtype=self.dtype,
            pool_k=state.pool_k, pool_v=state.pool_v,
            page_table=page_table, positions=pos, write_ok=write_ok,
            page_tokens=self.spec.page_tokens)
        g = jnp.argmax(self._tied_logits(params, h),
                       axis=-1).astype(jnp.int32)              # (S, W)
        # draft token w-1 is correct iff all earlier drafts matched the
        # target's greedy choice — cumprod counts the accepted run
        match = (draft == g[:, :-1]).astype(jnp.int32)
        a = jnp.cumprod(match, axis=1).sum(axis=1)             # (S,)
        # emit the accepted run + the target's one bonus token, clamped
        # to the generation budget and the cache capacity (>= 1 for any
        # active slot: active implies remaining > 0 and lengths <
        # max_seq)
        e = jnp.minimum(a + 1, jnp.minimum(
            state.remaining, self.max_seq - state.lengths))
        e = jnp.where(act, e, 0)
        valid = act[:, None] & (offs < e[:, None])             # (S, W)
        toks = jnp.where(valid, g, -1)
        new_last = jnp.take_along_axis(
            g, jnp.maximum(e - 1, 0)[:, None], axis=1)[:, 0]
        new_len = jnp.where(act, state.lengths + e, state.lengths)
        new_rem = jnp.where(act, state.remaining - e, state.remaining)
        new_active = jnp.where(
            dispatch_active,
            act & (new_rem > 0) & (new_len < self.max_seq),
            state.active)
        new_state = PagedServeState(
            pool_k=pk, pool_v=pv, lengths=new_len,
            last_token=jnp.where(act, new_last, state.last_token),
            active=new_active, remaining=new_rem)
        return new_state, toks.T, valid.T, e

    def verify(self, params, state: PagedServeState, draft,
               dispatch_active=None):
        """Score a ``(slots, speculate_k - 1)`` host draft in ONE
        batched target forward (the speculative-decoding verify).
        Returns ``(state, tokens (speculate_k, slots), valid
        (speculate_k, slots), emitted (slots,))`` — the same
        ``(tokens, valid)`` orientation as :meth:`decode`, so the
        scheduler consumes both identically; ``emitted`` counts each
        slot's accepted-run + bonus tokens this dispatch. Rejected
        drafts' junk K/V lands beyond the new length and is overwritten
        (write-then-attend) before any query can reach it, which is
        what makes greedy output bitwise speculation-free."""
        if self.speculate_k < 2:
            raise ValueError("verify() requires speculate_k >= 2")
        draft = jnp.asarray(draft, jnp.int32).reshape(
            self.slots, self.speculate_k - 1)
        table = jnp.asarray(self.alloc.table, jnp.int32)
        if dispatch_active is None:
            da = jnp.ones((self.slots,), bool)
        else:
            da = jnp.asarray(dispatch_active, bool).reshape(self.slots)
        args = (params, state, draft, table, da)
        self._note_program("verify", self._verify, args)
        return self._verify(*args)

    # ---------------------------------------------------------- warmup

    def warmup(self, params) -> None:
        """Compile prefill + every decode rung (+ verify when
        speculating) off the request clock, on a throwaway state and a
        junk page table (compilation only sees shapes; the junk writes
        route to the trash page)."""
        state = self.init_state()
        dummy = jnp.zeros((1, self.prompt_pad), jnp.int32)
        row = np.full((self.spec.max_pages_per_slot,), -1, np.int32)
        state, first = self.prefill(params, state, dummy, 1, 0, 2,
                                    page_row=row)
        jax.device_get(first)
        for k in self.ladder:
            state, toks, valid = self.decode(params, state, k)
            jax.device_get((toks, valid))
        if self.speculate_k >= 2:
            draft = np.zeros((self.slots, self.speculate_k - 1),
                             np.int32)
            state, toks, valid, e = self.verify(params, state, draft)
            jax.device_get((toks, valid, e))

    def assert_two_programs(self) -> None:
        """The dense pin (1 prefill + 1 decode per rung) plus exactly
        one verify program when speculation is on."""
        super().assert_two_programs()
        want = 1 if self.speculate_k >= 2 else 0
        v = len(self.verify_traces)
        if v != want:
            raise AssertionError(
                f"paged serve engine compiled {v} verify program(s), "
                f"expected {want} (speculate_k={self.speculate_k}); "
                f"the program-budget pin is broken")
