"""``python -m tpudist.serve`` — the serving acceptance lane.

One command drives the whole serve stack end to end on whatever mesh
the platform gives it (the scripted CPU mesh in CI, a pod slice under
``launch_tpu.sh MODE=serve``): build the model and its sharded KV
cache, warm the compiled programs (one prefill + one decode per adapt
rung), optionally let the serve autotuner pick ``decode_k``/layout by
measured probe, run the continuous-batching loop — with admission
control, deadline shedding and graceful degradation when the
resilience knobs are on (:mod:`tpudist.serve.resilience`) — over a
seeded Poisson request stream, and grade the latency SLOs plus the
shed gate. Under the launcher's requeue loop (``--requeue-attempt``),
a restarted attempt replays the still-live requests from the seeded
schedule and classifies the dead attempt's in-flight slots as lost.

Artifacts mirror the train lane's: ``metrics.jsonl`` (``kind=serve`` /
``serve_tick`` / ``serve_tune`` / per-request ``serve_request``
records) under ``--save-dir``, the span trace — on by default, same
``--trace``/``TPUDIST_TRACE`` resolution as training — exported as
``trace.worker<i>.json`` plus the merged ``pod_trace.json`` with
per-request flight timelines, per-slot tracks and a KV-pool occupancy
counter track (verify offline with ``python -m tpudist.serve.flight``),
an optional ``BENCH_SERVE.json`` (``--bench-out``),
a Prometheus exporter while the run lives (``--live-port``), and the
machine-readable verdict file (``TPUDIST_VERDICT_PATH``) carrying the
three-valued SLO verdict. Exit code: 0 unless an SLO gate FAILED — an
ungateable run (nothing measured) is not a latency regression.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from typing import Any, Dict, Optional, Sequence

from tpudist.serve import slo as slo_lib

DEFAULT_SLOTS = 4
DEFAULT_MAX_SEQ = 64
DEFAULT_PROMPT_PAD = 16
DEFAULT_DECODE_K = 8


def parse_args(argv: Optional[Sequence[str]] = None
               ) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="python -m tpudist.serve",
        description="tpudist serving acceptance lane: continuous "
                    "batching + sharded KV cache + latency-SLO verdict")
    p.add_argument("--model", choices=("transformer", "moe"),
                   default="transformer")
    p.add_argument("--vocab-size", type=int, default=256)
    p.add_argument("--n-layers", type=int, default=2)
    p.add_argument("--d-model", type=int, default=64)
    p.add_argument("--n-heads", type=int, default=4)
    p.add_argument("--n-kv-heads", type=int, default=2,
                   help="GQA: compact kv heads stored in the cache")
    p.add_argument("--d-ff", type=int, default=128)
    p.add_argument("--n-experts", type=int, default=4)
    p.add_argument("--expert-top-k", type=int, default=2)
    p.add_argument("--slots", type=int, default=DEFAULT_SLOTS,
                   help="concurrent sequences (KV cache pages)")
    p.add_argument("--max-seq", type=int, default=DEFAULT_MAX_SEQ,
                   help="per-slot cache page length")
    p.add_argument("--prompt-pad", type=int, default=DEFAULT_PROMPT_PAD,
                   help="static prompt width every admission pads to "
                        "(one compiled prefill program)")
    p.add_argument("--decode-steps-per-dispatch", type=int,
                   default=DEFAULT_DECODE_K, dest="decode_k",
                   help="decode superstep length (tokens per dispatch "
                        "per slot)")
    p.add_argument("--kv-layout", choices=("st", "hs"), default="st",
                   help="KV cache physical storage layout "
                        "(tpudist.serve.kvcache)")
    # ---- the paged plane (PagedServeEngine) ----
    p.add_argument("--kv-page-tokens", type=int,
                   default=_env_int("TPUDIST_SERVE_KV_PAGE_TOKENS")
                   or 0,
                   help="PAGED KV cache: fixed page length in "
                        "positions; 0 keeps the dense per-slot arena "
                        "($TPUDIST_SERVE_KV_PAGE_TOKENS)")
    p.add_argument("--kv-pages", type=int,
                   default=_env_int("TPUDIST_SERVE_KV_PAGES") or 0,
                   help="paged pool size in pages (+1 trash page is "
                        "added internally); 0 = full dense capacity "
                        "slots*ceil(max_seq/page_tokens) "
                        "($TPUDIST_SERVE_KV_PAGES)")
    p.add_argument("--shared-prefix", type=int,
                   default=_env_int("TPUDIST_SERVE_SHARED_PREFIX")
                   or 0,
                   help="every request starts with this many shared "
                        "system-prompt tokens; the paged engine stores "
                        "their full pages ONCE (refcounted, "
                        "copy-on-write fork of the partial tail) "
                        "($TPUDIST_SERVE_SHARED_PREFIX)")
    p.add_argument("--speculate-k", type=int,
                   default=_env_int("TPUDIST_SERVE_SPECULATE_K") or 0,
                   help="speculative decoding verify-window width: "
                        "last token + k-1 n-gram draft tokens scored "
                        "in ONE batched target forward; 0 = off, "
                        "needs --kv-page-tokens "
                        "($TPUDIST_SERVE_SPECULATE_K)")
    p.add_argument("--requests", type=int, default=32,
                   help="synthetic request count")
    p.add_argument("--request-rate", type=float, default=0.0,
                   help="Poisson arrival rate in requests/s "
                        "(<= 0: closed loop, all present at t=0)")
    p.add_argument("--max-new-tokens", type=int, default=16)
    p.add_argument("--seed", type=int, default=0)
    # ---- the resilience plane (tpudist.serve.resilience) ----
    p.add_argument("--queue-cap", type=int,
                   default=_env_int("TPUDIST_SERVE_QUEUE_CAP") or 0,
                   help="bounded admission queue: arrivals past this "
                        "many waiting requests are SHED "
                        "($TPUDIST_SERVE_QUEUE_CAP; 0 = unbounded)")
    p.add_argument("--ttft-deadline-ms", type=float,
                   default=_env_float("TPUDIST_SERVE_TTFT_DEADLINE_MS")
                   or 0.0,
                   help="per-request TTFT deadline: accepted requests "
                        "still queued past this age are EXPIRED "
                        "($TPUDIST_SERVE_TTFT_DEADLINE_MS; 0 = off)")
    p.add_argument("--adapt", choices=("off", "on"),
                   default=os.environ.get("TPUDIST_SERVE_ADAPT", "off"),
                   help="graceful degradation: downshift decode_k on "
                        "the pre-compiled ladder when rolling queue "
                        "depth/ITL crosses the pressure thresholds, "
                        "restore when it clears ($TPUDIST_SERVE_ADAPT)")
    p.add_argument("--adapt-max-new-cap", type=int, default=0,
                   help="under degradation, truncate admitted "
                        "requests' generation budget to this many "
                        "tokens (0 = no truncation)")
    p.add_argument("--requeue-attempt", type=int, default=None,
                   help="requeue loop attempt index (the launcher "
                        "passes it whenever MAX_REQUEUES > 0): its "
                        "PRESENCE arms supervision — per-request "
                        "outcome events get boundary flushes so a "
                        "preemption cannot eat them — and attempt > 0 "
                        "replays the seeded stream MINUS requests a "
                        "prior attempt already finished, classifying "
                        "its in-flight slots as lost")
    p.add_argument("--chaos", type=str,
                   default=os.environ.get("TPUDIST_CHAOS"),
                   help="scripted serve-surface fault plan "
                        "(tpudist.chaos: serve_kill@0:<dispatch>, "
                        "serve_slow, request_garbage; $TPUDIST_CHAOS)")
    p.add_argument("--virtual-clock", action="store_true",
                   default=os.environ.get(
                       "TPUDIST_SERVE_VIRTUAL_CLOCK", "").lower()
                   in ("on", "1", "true"),
                   help="deterministic drill mode: the request clock "
                        "advances by scripted per-prefill/per-dispatch "
                        "costs instead of wall time — two runs of one "
                        "seed produce bitwise-identical SLO summaries "
                        "($TPUDIST_SERVE_VIRTUAL_CLOCK)")
    p.add_argument("--virtual-prefill-ms", type=float, default=2.0)
    p.add_argument("--virtual-decode-ms", type=float, default=4.0)
    p.add_argument("--serve-tune", choices=("off", "probe", "cache-only"),
                   default=os.environ.get("TPUDIST_SERVE_TUNE", "off"),
                   help="autotune decode_k/kv-layout by measured probe "
                        "(tpudist.serve.tune; $TPUDIST_SERVE_TUNE)")
    p.add_argument("--tune-cache-dir", type=str, default=None,
                   help="serve tuner cache dir (default "
                        "$TPUDIST_AUTOTUNE_CACHE_DIR, else "
                        "<save-dir>/tune — shared with the train tuner, "
                        "distinct file prefix)")
    p.add_argument("--save-dir", type=str, default="ckpt",
                   help="metrics.jsonl destination")
    p.add_argument("--bench-out", type=str, default=None,
                   help="write the run summary as BENCH_SERVE.json here")
    p.add_argument("--trace", choices=("on", "off"), default=None,
                   help="span tracing (request flight timelines + KV "
                        "occupancy counters); default on — same "
                        "resolution as the train lane: flag > "
                        "$TPUDIST_TRACE > on")
    p.add_argument("--trace-dir", type=str,
                   default=os.environ.get("TPUDIST_TRACE_DIR"),
                   help="span-trace export dir ($TPUDIST_TRACE_DIR, "
                        "else --save-dir)")
    p.add_argument("--live-port", type=int, default=_env_int(
        "TPUDIST_LIVE_PORT"),
        help="serve Prometheus /metrics + /status.json on this port "
             "while the run lives ($TPUDIST_LIVE_PORT)")
    return p.parse_args(argv)


def _env_int(name: str) -> Optional[int]:
    raw = os.environ.get(name)
    try:
        return int(raw) if raw else None
    except ValueError:
        return None


def _env_float(name: str) -> Optional[float]:
    raw = os.environ.get(name)
    try:
        return float(raw) if raw else None
    except ValueError:
        return None


def _prior_outcomes(path: str):
    """Replay a dead attempt's flushed ``kind=serve_request`` events:
    returns ``(accounted_rids, lost_rids)`` — rids with a terminal
    outcome in ANY prior attempt, and admitted-to-slot rids with none
    (the in-flight slots the kill took, which THIS attempt classifies
    as lost rather than silently re-serving half-generated work)."""
    import json as json_mod

    from tpudist.serve import resilience as res_lib
    admitted, terminal = set(), set()
    try:
        with open(path) as f:
            for line in f:
                try:
                    rec = json_mod.loads(line)
                except ValueError:
                    continue          # a torn tail line is not evidence
                if rec.get("kind") != "serve_request":
                    continue
                rid, ev = rec.get("rid"), rec.get("event")
                if rid is None:
                    continue
                if ev == res_lib.ADMITTED:
                    admitted.add(int(rid))
                elif ev in res_lib.TERMINAL_EVENTS:
                    terminal.add(int(rid))
    except OSError:
        return set(), set()
    return terminal, admitted - terminal


class _LoopbackEmitter:
    """MetricsLogger→LiveAggregator fan-out without a socket: the serve
    CLI is single-process, so the coordinator IS the worker and records
    can be ingested directly (same record shapes the TCP bus carries)."""

    def __init__(self, agg):
        self.agg = agg

    def emit(self, rec: Dict[str, Any]) -> None:
        try:
            self.agg.ingest(rec)
        except Exception:
            pass   # telemetry must never take down the serve loop


def run(args: argparse.Namespace) -> Dict[str, Any]:
    import jax

    from tpudist.config import ModelConfig, ParallelConfig, resolve_trace
    from tpudist.metrics import MetricsLogger, log0
    from tpudist.obs import live as live_lib
    from tpudist.obs import trace as trace_lib
    from tpudist.parallel.mesh import build_mesh
    from tpudist.serve import flight as flight_lib
    from tpudist.serve import scheduler as sched
    from tpudist.serve import tune as serve_tune
    from tpudist.serve.engine import (PagedServeEngine, ServeEngine,
                                      init_params)

    model_cfg = ModelConfig(
        name=args.model, vocab_size=args.vocab_size,
        n_layers=args.n_layers, d_model=args.d_model,
        n_heads=args.n_heads, n_kv_heads=args.n_kv_heads,
        d_ff=args.d_ff, max_seq_len=args.max_seq,
        n_experts=args.n_experts, expert_top_k=args.expert_top_k)
    mesh = build_mesh(ParallelConfig())
    # same resolver as the train lane (flag > $TPUDIST_TRACE > on for
    # the switch; --trace-dir > $TPUDIST_TRACE_DIR > --save-dir for the
    # destination): serve tracing was previously gated on --trace-dir
    # alone, which made the pod-wide TPUDIST_TRACE=off escape hatch —
    # and default-on flight timelines — silently train-only
    trace_on, trace_dir = resolve_trace(args)
    tracer = trace_lib.configure(enabled=trace_on)

    # --requeue-attempt's PRESENCE (any value, 0 included) means the
    # launcher's supervision loop owns this run: outcome events must
    # reach disk at boundaries, because a preemption may kill us and
    # the NEXT attempt classifies from what survived
    supervised = args.requeue_attempt is not None
    attempt = args.requeue_attempt or 0
    os.makedirs(args.save_dir, exist_ok=True)
    metrics_path = os.path.join(args.save_dir, "metrics.jsonl")
    # a resumed attempt reads the DEAD attempt's flushed outcome events
    # before this attempt appends its own
    prior_done, prior_lost = (set(), set())
    if attempt > 0:
        prior_done, prior_lost = _prior_outcomes(metrics_path)
    metrics = MetricsLogger(path=metrics_path)
    run_id = live_lib.resolve_run_id(jax.process_count())
    metrics.extra["run_id"] = run_id
    metrics.extra["requeue_attempt"] = attempt
    # name the trace artifact like every other artifact of the attempt
    tracer.run_info.update(run_id=run_id, requeue_attempt=attempt)

    # the live bus: the aggregator (alert engine + alerts.jsonl +
    # live_status.json) runs whenever live is ON — $TPUDIST_LIVE=on
    # without a port keeps it exporter-less (the drills' mode); a port
    # additionally serves Prometheus /metrics
    live_on = bool(args.live_port) or os.environ.get(
        "TPUDIST_LIVE", "").lower() in ("on", "1", "true")
    agg = server = None
    if live_on:
        agg = live_lib.LiveAggregator(out_dir=args.save_dir,
                                      run_id=run_id, metrics=None,
                                      stall_timeout_s=0)
        if args.live_port:
            server = live_lib.LiveHttpServer(agg, port=args.live_port)
            log0(f"tpudist: serve live exporter on "
                 f":{server.port}/metrics")
        metrics.emitter = _LoopbackEmitter(agg)

    # the chaos plane's serve surface (tpudist.chaos, --chaos /
    # $TPUDIST_CHAOS): serve_kill / serve_slow fire at decode-dispatch
    # boundaries via the scheduler's hook; request_garbage folds seeded
    # malformed requests into the arrival stream below. Off constructs
    # nothing, same as the train CLI.
    chaos_rt = None
    if args.chaos:
        from tpudist import chaos as chaos_lib
        chaos_rt = chaos_lib.ChaosRuntime(
            chaos_lib.ChaosPlan.parse(args.chaos),
            process_index=jax.process_index(), metrics=metrics)
        log0(f"tpudist: chaos on: {chaos_rt.plan.describe()}")

    from tpudist.serve import resilience as res_lib
    resilience = res_lib.ResilienceConfig(
        queue_cap=max(args.queue_cap, 0),
        ttft_deadline_s=max(args.ttft_deadline_ms, 0.0) / 1e3,
        adapt=args.adapt == "on",
        max_new_cap=max(args.adapt_max_new_cap, 0),
        # malformed-request rejection is on whenever ANY resilience or
        # chaos knob is: the garbage family's contract is an admission
        # rejection, never an engine crash
        validate=bool(args.chaos or args.queue_cap
                      or args.ttft_deadline_ms or args.adapt == "on"))

    params = init_params(model_cfg, mesh, seed=args.seed)

    if args.speculate_k and not args.kv_page_tokens:
        raise SystemExit("tpudist: --speculate-k needs the paged KV "
                         "cache (--kv-page-tokens > 0)")
    cand = serve_tune.ServeCandidate(
        decode_k=args.decode_k, layout=args.kv_layout,
        kv_page_tokens=max(args.kv_page_tokens, 0),
        speculate_k=max(args.speculate_k, 0))
    if args.serve_tune != "off":
        cache_dir = (args.tune_cache_dir
                     or os.environ.get("TPUDIST_AUTOTUNE_CACHE_DIR")
                     or os.path.join(args.save_dir, "tune"))
        with trace_lib.span("serve_tune", cat="tune",
                            mode=args.serve_tune):
            out = serve_tune.autotune_serve(
                model_cfg, mesh, params, slots=args.slots,
                max_seq=args.max_seq, prompt_pad=args.prompt_pad,
                mode=args.serve_tune, cache_dir=cache_dir, start=cand,
                metrics=metrics)
        cand = out.tuned
        log0(f"tpudist: serve tune {out.status} ({out.source}): "
             f"decode_k={cand.decode_k} layout={cand.layout} "
             f"kv_page_tokens={cand.kv_page_tokens} "
             f"speculate_k={cand.speculate_k} "
             f"[{out.trials} trial(s)]")

    ladder = (res_lib.default_ladder(cand.decode_k)
              if resilience.adapt else None)
    if cand.kv_page_tokens > 0:
        engine = PagedServeEngine(
            model_cfg, mesh, slots=args.slots, max_seq=args.max_seq,
            prompt_pad=args.prompt_pad, decode_k=cand.decode_k,
            page_tokens=cand.kv_page_tokens,
            pages=max(args.kv_pages, 0),
            speculate_k=max(cand.speculate_k, 0),
            adapt_ladder=ladder)
    else:
        engine = ServeEngine(model_cfg, mesh, slots=args.slots,
                             max_seq=args.max_seq,
                             prompt_pad=args.prompt_pad,
                             decode_k=cand.decode_k, layout=cand.layout,
                             adapt_ladder=ladder)
    with trace_lib.span("serve_warmup", cat="serve"):
        engine.warmup(params)

    # program memory (obs.memledger): warmup just compiled every pinned
    # program, so their memory_analysis is readable off the request
    # clock. For the paged plane it also feeds the allocator's memory
    # bound: admission maps only the pages device HBM can afford beside
    # the params and the programs' MEASURED scratch (falling back to the
    # 4x-params heuristic on backends without memory planning — the
    # choice is logged, and a shrunk cap backpressures at admission
    # instead of dying in RESOURCE_EXHAUSTED)
    from tpudist import engine as engine_lib
    from tpudist.obs import memledger as memledger_lib
    program_mem = engine.program_memory()
    params_bytes = engine_lib.state_bytes_per_device(params)
    hbm_bytes = int(engine_lib._device_hbm_bytes())
    if getattr(engine, "paged", False):
        temp, temp_complete = memledger_lib.program_temp_bytes(
            program_mem)
        cap = engine.alloc.set_memory_bound(
            hbm_bytes=hbm_bytes, params_bytes=params_bytes,
            program_temp_bytes=temp if temp_complete else None)
        log0(f"tpudist: serve kv memory bound "
             f"({engine.alloc.bound_source}): {cap}/{engine.spec.pages} "
             f"pages mappable in {hbm_bytes / 2**20:.0f} MB HBM")

    prefix_len = max(args.shared_prefix, 0)
    shared_prefix = (sched.shared_prefix_tokens(
        min(prefix_len, args.prompt_pad), args.vocab_size, args.seed)
        if prefix_len else None)
    requests = sched.make_requests(
        args.requests, prompt_pad=args.prompt_pad,
        vocab_size=args.vocab_size, max_new=args.max_new_tokens,
        rate=args.request_rate, seed=args.seed,
        prefix_len=prefix_len)
    if chaos_rt is not None:
        # request_garbage: the fault IS the malformed requests — fold
        # them into the (deterministic) schedule; admission rejects
        span = max((r.arrival_s for r in requests), default=0.0)
        rid_base = len(requests)
        for ev in chaos_rt.consume_request_garbage():
            garbage = sched.make_garbage_requests(
                chaos_rt.plan, ev, rid_base=rid_base,
                prompt_pad=args.prompt_pad, vocab_size=args.vocab_size,
                span_s=span)
            requests.extend(garbage)
            rid_base += len(garbage)

    n_lost = 0
    if attempt > 0:
        # honest supervision accounting: a prior attempt's in-flight
        # slots are LOST (their KV state died with the engine — a
        # half-generated answer is not resumable), its queued/unserved
        # requests are replayed from the deterministic schedule
        for rid in sorted(prior_lost):
            metrics.log(kind="serve_request", rid=rid,
                        event=res_lib.LOST)
            tracer.instant(res_lib.LOST, cat="serve", rid=rid)
            n_lost += 1
        remaining = [r for r in requests
                     if r.rid not in prior_done
                     and r.rid not in prior_lost]
        shift = min((r.arrival_s for r in remaining), default=0.0)
        requests = [dataclasses.replace(r, arrival_s=r.arrival_s - shift)
                    for r in remaining]
        metrics.log(kind="serve_resume",
                    completed_prior=len(prior_done), lost=n_lost,
                    replayed=len(requests))
        metrics.flush()
        log0(f"tpudist: serve resume (attempt {attempt}): "
             f"{len(prior_done)} done in prior attempt(s), {n_lost} "
             f"in-flight lost, replaying {len(requests)}")

    virtual = None
    if args.virtual_clock:
        virtual = res_lib.VirtualTiming(
            prefill_s=args.virtual_prefill_ms / 1e3,
            decode_s=args.virtual_decode_ms / 1e3)
    summary = sched.run_serve(engine, params, requests, metrics=metrics,
                              resilience=resilience, chaos=chaos_rt,
                              virtual=virtual,
                              flush_events=True if supervised else None,
                              shared_prefix=shared_prefix)
    engine.assert_two_programs()

    summary["run_id"] = run_id
    summary["model"] = args.model
    summary["requeue_attempt"] = attempt
    if attempt > 0:
        # the summary-level ``lost`` is everything THIS attempt knows
        # was lost: in-process losses are impossible (a kill that takes
        # slots never writes a summary), so the resumed attempt's
        # classification of the dead attempt's in-flight slots IS the
        # number — lifted here so the report/bench lanes surface it
        # (the ``partition`` block stays the attempt-local checked
        # ledger, where lost is 0 by construction)
        summary["lost"] = n_lost
        summary["completed_prior"] = len(prior_done)
    cache_bytes = engine.spec.bytes
    summary["kv_cache_bytes"] = cache_bytes
    metrics.log(kind="serve",
                **{k: v for k, v in summary.items()
                   if k not in ("results", "alert_events", "thresholds")})
    metrics.flush()

    # the serve lane's HBM ledger (obs.memledger): params + KV pool
    # (paged: pool pages incl. the trash page + page table — the
    # PagedCacheSpec.bytes number the bench lane reports) + the pinned
    # programs' scratch, partitioned exactly against device HBM and
    # persisted as <save-dir>/memledger.json for the forensics CLI and
    # the next run's feed-forward margin. Advisory: never fails serve.
    try:
        ledger = memledger_lib.build_ledger(
            total_hbm_bytes=hbm_bytes, params_bytes=params_bytes,
            kv_pool_bytes=cache_bytes, programs=program_mem,
            mode="serve", run_id=run_id)
        metrics.log(kind="memledger",
                    **memledger_lib.ledger_record(ledger))
        metrics.flush()
        memledger_lib._atomic_write(
            os.path.join(args.save_dir, memledger_lib.LEDGER_NAME),
            json.dumps(ledger, indent=1))
        log0(f"tpudist: memledger {ledger['headroom_status']}: "
             f"{100 * ledger['headroom_fraction']:.1f}% headroom of "
             f"{ledger['total_hbm_bytes'] / 2**20:.0f} MB HBM "
             f"(params {params_bytes / 2**20:.1f} MB, kv_pool "
             f"{cache_bytes / 2**20:.2f} MB, temp "
             f"{ledger['buckets']['program_temp'] / 2**20:.1f} MB, "
             f"{'exact' if ledger['exact'] else 'INEXACT'})")
    except Exception as e:
        log0(f"tpudist: memledger skipped ({e!r})")

    log0(f"tpudist: serve {summary['status']}: "
         f"{summary['completed']}/{summary['requests']} requests, "
         f"{summary['generated_tokens']} tokens in "
         f"{summary['wall_s']:.3f}s "
         f"({summary['tokens_per_sec_per_chip']} tok/s/chip), "
         f"ttft p99 {summary['ttft_p99_s']}s, "
         f"itl p99 {summary['itl_p99_s']}s "
         f"[{summary['prefill_compiles']} prefill / "
         f"{summary['decode_compiles']} decode / "
         f"{summary['verify_compiles']} verify compile(s), "
         f"kv cache {cache_bytes / 2**20:.2f} MB"
         + (f", {summary['kv_pages_used_peak']}"
            f"/{summary['kv_pages_total']} pages peak, "
            f"spec accept {summary['spec_accept_rate']}"
            if getattr(engine, "paged", False) else "") + "]")

    if args.bench_out:
        _write_bench(args.bench_out, args, summary)
        log0(f"tpudist: serve bench -> {args.bench_out}")

    if tracer.enabled:
        # full pod export, like the train lane: trace.worker<i>.json
        # per process plus the merged pod_trace.json on the
        # coordinator — with the serve-specific presentation appended
        # (per-slot request tracks, ph="C" KV occupancy counters)
        pi, pc = jax.process_index(), jax.process_count()
        extra = flight_lib.build_extra_events(
            tracer.events(process_index=pi), process_index=pi)
        tinfo = trace_lib.export_pod_trace(
            trace_dir, process_index=pi, process_count=pc,
            tracer=tracer, extra_events=extra)
        log0(f"tpudist: serve trace -> {tinfo['local_path']} "
             f"({tinfo['spans']} spans, {len(extra)} slot-track/"
             f"counter events"
             + (f", merged {tinfo['merged_path']}"
                if tinfo["merged_path"] else "") + ")")
    if server is not None:
        server.close()
    if agg is not None:
        agg.close()
    metrics.close()
    return summary


def _write_bench(path: str, args: argparse.Namespace,
                 summary: Dict[str, Any]) -> None:
    """BENCH_SERVE.json — same harness shape as the other BENCH_*
    artifacts: one metric headline, per-gate detail, thresholds."""
    import jax
    doc = {
        "metric": "serve_tokens_per_sec_per_chip",
        "value": summary["tokens_per_sec_per_chip"],
        "unit": "tokens/s/chip",
        "detail": {k: summary.get(k) for k in (
            "run_id", "model", "requests", "completed",
            "generated_tokens", "truncated", "wall_s", "dispatches",
            "slots", "decode_k", "kv_layout", "kv_cache_bytes",
            "tokens_per_sec", "queue_depth_max", "queue_depth_mean",
            "ttft_p50_s", "ttft_p99_s", "itl_p50_s", "itl_p99_s",
            "e2e_p50_s", "e2e_p99_s", "prefill_compiles",
            "decode_compiles", "verify_compiles", "n_chips",
            "arrived", "admitted", "shed_at_admission",
            "expired_in_queue", "rejected", "lost", "completed_prior",
            "shed_fraction", "queue_cap", "ttft_deadline_s",
            "adapt_level", "decode_k_ladder", "requeue_attempt",
            "kv_page_tokens", "kv_pages_total", "kv_pages_used_peak",
            "active_slots_peak", "spec_accept_rate", "speculate_k",
            "shared_prefix_len")},
        "slo": slo_lib.slo_block(summary),
        "device": jax.devices()[0].device_kind,
    }
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, path)


def main(argv: Optional[Sequence[str]] = None) -> int:
    from tpudist.utils import (maybe_enable_compilation_cache,
                               maybe_force_platform, tune_tpu)
    maybe_force_platform()
    tune_tpu()
    maybe_enable_compilation_cache()
    args = parse_args(argv)
    verdict_path = os.environ.get("TPUDIST_VERDICT_PATH")
    status = slo_lib.FAIL
    try:
        summary = run(args)
        status = summary["status"]
    except Exception as e:
        print(f"tpudist: serve failed: {e!r}", file=sys.stderr,
              flush=True)
    if verdict_path:
        try:
            from tpudist import verdict as verdict_lib
            verdict_lib.write_final_status(verdict_path, status)
        except Exception as e:
            print(f"tpudist: verdict plumbing failed: {e!r}",
                  file=sys.stderr, flush=True)
    # an UNGATEABLE run (nothing measured) is not a latency regression
    return 1 if status == slo_lib.FAIL else 0


if __name__ == "__main__":
    sys.exit(main())
