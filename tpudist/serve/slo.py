"""Latency accounting + SLO verdicts for the serving engine.

Stdlib-only by design, like :mod:`tpudist.rules`: the offline report CLI
(:mod:`tpudist.obs.report`) folds the serving section with jax
uninstalled, and the thresholds themselves live in the shared rules
table so the serve loop's on-line alerts, the exit verdict line, and the
offline report all grade the SAME numbers against the SAME gates.

The three serving observables:

* **TTFT** — time-to-first-token per request: arrival → the prefill
  dispatch that produced its first token (queue wait included — an
  admission-starved pod must read as a TTFT problem, not disappear into
  engine-only timing).
* **ITL** — inter-token latency: decode tokens come k-per-dispatch
  (the compiled superstep), so each token in a dispatch is attributed
  ``dispatch_wall / k`` — the honest amortised figure at superstep
  granularity (``k=1`` recovers true per-token timing).
* **tokens/s/chip** — generated tokens (first tokens included) over the
  serving wall clock, per chip.
* **shed fraction** — the resilience plane's admission gate (PR 15):
  (shed + expired + rejected) / arrived, graded against
  ``TPUDIST_SERVE_SHED_MAX`` — admitted-traffic latency stays honest
  only because overload is shed, so the shed share is itself gated.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from tpudist import rules as rules_lib

SUCCESS = "success"      # mirrors tpudist.verdict vocabulary without
FAIL = "fail"            # the import (same pattern as obs.alerts)
UNGATEABLE = "ungateable"

# The serve gates, in grading order; each is (rule name, summary key).
# serve_shed (the resilience plane's admission gate) grades the shed
# share of all arrivals: a pod turning away more than the ceiling is
# under-provisioned even when every ADMITTED request met its latency
# SLO — bounded TTFT bought by unbounded shedding is not a pass.
SERVE_RULES = (("ttft", "ttft_p99_s"),
               ("itl", "itl_p99_s"),
               ("tokens_per_chip", "tokens_per_sec_per_chip"),
               ("serve_shed", "shed_fraction"))

# Fixed Prometheus-native histogram buckets (upper bounds, seconds).
# Pinned here — NOT configurable — because bucket bounds are part of the
# metric contract: a scrape-side PromQL histogram_quantile() over two
# runs is only comparable when both used the same edges. TTFT spans
# queue wait + prefill (hundreds of ms under load), ITL is a per-token
# share of one decode dispatch (single-digit ms on real hardware).
TTFT_BUCKETS_S = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)
ITL_BUCKETS_S = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0)


def hist_block(samples: List[float],
               buckets: tuple) -> Dict[str, Any]:
    """A self-describing histogram record for one latency family:
    per-bucket (NOT cumulative) counts with one overflow bin, plus
    sum/count. Carried on ``kind=serve_tick`` records so the live
    Prometheus exporter can emit native ``_bucket{le=...}`` series
    without holding raw samples; the bucket edges ride along so every
    consumer renders the same edges the producer counted against."""
    counts = [0] * (len(buckets) + 1)
    total = 0.0
    for s in samples:
        total += s
        for j, ub in enumerate(buckets):
            if s <= ub:
                counts[j] += 1
                break
        else:
            counts[-1] += 1
    return {"buckets": [float(b) for b in buckets], "counts": counts,
            "sum": round(total, 6), "count": len(samples)}


def percentile(xs: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile (q in [0, 100]); None on no samples.
    Deterministic and interpolation-free — two graders computing p99 of
    the same samples must get the same number bit-for-bit."""
    if not xs:
        return None
    s = sorted(xs)
    rank = max(1, math.ceil(q / 100.0 * len(s)))
    return s[min(rank, len(s)) - 1]


@dataclass
class LatencyStats:
    """Per-run latency sample sink; all samples in seconds."""

    ttft_s: List[float] = field(default_factory=list)
    itl_s: List[float] = field(default_factory=list)
    e2e_s: List[float] = field(default_factory=list)

    def note_ttft(self, s: float) -> None:
        self.ttft_s.append(float(s))

    def note_itl(self, s: float, n: int = 1) -> None:
        self.itl_s.extend([float(s)] * max(int(n), 0))

    def note_e2e(self, s: float) -> None:
        self.e2e_s.append(float(s))

    def summary(self) -> Dict[str, Any]:
        return {
            "ttft_p50_s": percentile(self.ttft_s, 50),
            "ttft_p99_s": percentile(self.ttft_s, 99),
            "itl_p50_s": percentile(self.itl_s, 50),
            "itl_p99_s": percentile(self.itl_s, 99),
            "e2e_p50_s": percentile(self.e2e_s, 50),
            "e2e_p99_s": percentile(self.e2e_s, 99),
        }

    def ttft_hist(self) -> Dict[str, Any]:
        return hist_block(self.ttft_s, TTFT_BUCKETS_S)

    def itl_hist(self) -> Dict[str, Any]:
        return hist_block(self.itl_s, ITL_BUCKETS_S)


def rule_status(rule: str, value: Optional[float]) -> str:
    """Three-valued per-gate verdict: no measurement is UNGATEABLE (the
    convention every tpudist gate follows — an empty run must not read
    as an SLO pass), else SUCCESS/FAIL by the shared rules table (env
    overrides read at call time)."""
    if value is None:
        return UNGATEABLE
    return FAIL if rules_lib.breached(rule, value) else SUCCESS


def grade(ttft_p99_s: Optional[float], itl_p99_s: Optional[float],
          tokens_per_sec_per_chip: Optional[float],
          shed_fraction: Optional[float] = None) -> Dict[str, str]:
    """All four serve gates + the fold: overall ``status`` is FAIL if
    any gate fails, UNGATEABLE if nothing was measurable, else
    SUCCESS. ``shed_fraction`` is None on pre-resilience artifacts (and
    empty runs) — the serve_shed gate reads UNGATEABLE there, never a
    retroactive fail."""
    vals = {"ttft_p99_s": ttft_p99_s, "itl_p99_s": itl_p99_s,
            "tokens_per_sec_per_chip": tokens_per_sec_per_chip,
            "shed_fraction": shed_fraction}
    out = {f"{rule}_status": rule_status(rule, vals[key])
           for rule, key in SERVE_RULES}
    statuses = list(out.values())
    if FAIL in statuses:
        overall = FAIL
    elif all(s == UNGATEABLE for s in statuses):
        overall = UNGATEABLE
    else:
        overall = SUCCESS
    out["status"] = overall
    return out


def serve_status(ttft_p99_s: Optional[float], itl_p99_s: Optional[float],
                 tokens_per_sec_per_chip: Optional[float]) -> str:
    """The folded serving verdict alone (what ``verdict.serve_status``
    delegates to)."""
    return grade(ttft_p99_s, itl_p99_s, tokens_per_sec_per_chip)["status"]


def slo_block(summary: Dict[str, Any]) -> Dict[str, Any]:
    """The BENCH_SERVE.json ``slo`` block from a ``run_serve`` summary —
    ONE producer shared by the serve CLI and ``bench.py --serve-sweep``
    so the two artifact writers cannot drift (same reason
    ``write_collectives_artifact`` exists). Thresholds resolve through
    the rules table at call time, like every other gate."""
    return {
        "status": summary["status"],
        **{f"{rule}_status": summary[f"{rule}_status"]
           for rule, _ in SERVE_RULES},
        "thresholds": {rule: rules_lib.resolve(rule)
                       for rule, _ in SERVE_RULES},
    }
