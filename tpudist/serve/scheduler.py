"""Continuous batching: Poisson arrivals, slot admission, SLO accounting.

The host half of the serving engine. Requests arrive on an open-loop
Poisson schedule (a synthetic stand-in for "millions of users" — rate,
prompt lengths and generation budgets are all seeded, so a serve run is
reproducible end to end), queue until a slot frees, prefill into the
free slot, and decode continuously: every dispatch is one compiled
superstep over the WHOLE slot batch, with completed slots freed and
refilled between dispatches — no draining, no batch reshaping, no
recompiles.

Latency accounting happens here because only the host sees the request
clock: TTFT spans arrival → the fenced prefill that produced the first
token (queue wait included); ITL attributes each token in a decode
dispatch ``dispatch_wall / decode_k`` (see :mod:`tpudist.serve.slo`).
The loop feeds every observation to an :class:`~tpudist.obs.alerts.
AlertEngine` over the shared rules table, so an SLO breach FIRES as an
alert mid-run — same numbers, same thresholds as the exit verdict.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from tpudist import rules as rules_lib
from tpudist.obs import trace as trace_lib
from tpudist.obs.alerts import AlertEngine
from tpudist.serve import slo as slo_lib
from tpudist.serve.engine import ServeEngine


@dataclasses.dataclass(frozen=True)
class Request:
    """One synthetic inference request."""

    rid: int
    arrival_s: float          # offset from run start
    tokens: np.ndarray        # (prompt_pad,) int32, padded prompt
    prompt_len: int
    max_new: int


def make_requests(n: int, *, prompt_pad: int, vocab_size: int,
                  max_new: int, rate: float, seed: int,
                  prompt_min: int = 0) -> List[Request]:
    """Seeded synthetic request stream.

    Arrivals: Poisson process at ``rate`` requests/s (exponential
    inter-arrival gaps); ``rate <= 0`` means every request is present at
    t=0 — the closed-loop mode benchmarks and probes use. Prompts reuse
    the training data's deterministic next-token structure (the affine
    map of data.make_synthetic_tokens) with per-request lengths drawn
    from [prompt_min, prompt_pad]."""
    rng = np.random.default_rng(seed)
    if rate > 0:
        arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n))
    else:
        arrivals = np.zeros(n)
    prompt_min = min(max(1, prompt_min or prompt_pad // 2), prompt_pad)
    lens = rng.integers(prompt_min, prompt_pad + 1, size=n)
    first = rng.integers(0, vocab_size, size=(n, 1)).astype(np.int32)
    toks = np.empty((n, prompt_pad), np.int32)
    toks[:, :1] = first
    for t in range(1, prompt_pad):
        toks[:, t] = (toks[:, t - 1] * 7 + 3) % vocab_size
    out = []
    for i in range(n):
        padded = toks[i].copy()
        padded[lens[i]:] = 0     # pad-token tail, masked by prompt_len
        out.append(Request(rid=i, arrival_s=float(arrivals[i]),
                           tokens=padded, prompt_len=int(lens[i]),
                           max_new=int(max_new)))
    return out


@dataclasses.dataclass
class _Slot:
    req: Request
    generated: int
    first_token_s: float
    output: List[int]


def run_serve(engine: ServeEngine, params, requests: List[Request], *,
              metrics: Any = None, tick_every: int = 8,
              clock: Callable[[], float] = time.perf_counter,
              n_chips: Optional[int] = None) -> Dict[str, Any]:
    """Drive the engine over the request stream; returns the run summary
    (percentiles, throughput, per-gate SLO statuses, compile counts).

    The engine must already be warmed (:meth:`ServeEngine.warmup`) so
    the request clock never pays XLA compilation. ``metrics`` (a
    MetricsLogger) receives periodic ``kind=serve_tick`` records; the
    caller logs the final ``kind=serve`` summary so it can stamp its own
    fields in."""
    import jax
    if n_chips is None:
        n_chips = max(jax.device_count(), 1)
    tracer = trace_lib.get()
    stats = slo_lib.LatencyStats()
    alerts = AlertEngine()
    queue = deque(sorted(requests, key=lambda r: (r.arrival_s, r.rid)))
    slots: List[Optional[_Slot]] = [None] * engine.slots
    state = engine.init_state()
    results: Dict[int, Dict[str, Any]] = {}
    generated = truncated = dispatches = 0
    queue_depths: List[int] = []
    t0 = clock()

    def now() -> float:
        return clock() - t0

    def finish(i: int, why: str) -> None:
        nonlocal truncated
        s = slots[i]
        results[s.req.rid] = {
            "tokens": list(s.output), "prompt_len": s.req.prompt_len,
            "generated": s.generated, "why": why,
            "e2e_s": now() - s.req.arrival_s}
        stats.note_e2e(now() - s.req.arrival_s)
        if why == "evicted":
            truncated += 1
        slots[i] = None

    def admit() -> None:
        nonlocal generated, state
        t = now()
        for i in range(engine.slots):
            if slots[i] is not None or not queue \
                    or queue[0].arrival_s > t:
                continue
            req = queue.popleft()
            with tracer.span("admit", cat="serve", rid=req.rid, slot=i):
                pass   # the admission decision itself is host-trivial
            with tracer.span("prefill", cat="serve", rid=req.rid,
                             slot=i, prompt_len=req.prompt_len):
                state, first = engine.prefill(
                    params, state, req.tokens[None, :], req.prompt_len,
                    i, req.max_new)
                first = int(first)           # fence: the token exists NOW
            t_first = now()
            stats.note_ttft(t_first - req.arrival_s)
            generated += 1
            slots[i] = _Slot(req=req, generated=1, first_token_s=t_first,
                             output=[first])
            if req.max_new <= 1 or req.prompt_len >= engine.max_seq:
                finish(i, "done" if req.max_new <= 1 else "evicted")
            t = now()

    def arrived_depth() -> int:
        # ONLY requests whose arrival time has passed: the deque holds
        # the whole future synthetic schedule, and "queued" must mean
        # waiting-for-a-slot, not not-yet-generated (the Prometheus
        # gauge and the report's queue_over_time both promise that)
        t = now()
        n = 0
        for r in queue:            # arrival-sorted: break at the future
            if r.arrival_s > t:
                break
            n += 1
        return n

    def observe_slos(summ: Dict[str, Any]) -> None:
        alerts.observe("ttft", summ["ttft_p99_s"])
        alerts.observe("itl", summ["itl_p99_s"])
        wall = now()
        if wall > 0 and generated:
            alerts.observe("tokens_per_chip",
                           generated / wall / n_chips)

    while len(results) < len(requests):
        admit()
        occupied = [i for i in range(engine.slots) if slots[i] is not None]
        if not occupied:
            # nothing running and nothing arrived yet: wait out the gap
            # to the next scheduled arrival (bounded — the generator's
            # schedule is finite)
            if queue:
                time.sleep(min(0.002, max(0.0,
                                          queue[0].arrival_s - now())))
                continue
            break
        # depth sampled once per DISPATCH (not per idle busy-wait pass:
        # a sparse schedule would drown the mean in idle-gap zeros and
        # grow the sample list unboundedly)
        queue_depths.append(arrived_depth())
        t_dispatch = clock()
        with tracer.span("decode_step", cat="serve",
                         active=len(occupied)):
            state, toks, valid = engine.decode(params, state)
            toks = np.asarray(toks)          # fence: tokens on host
            valid = np.asarray(valid)
        dt = clock() - t_dispatch
        dispatches += 1
        per_tok = dt / engine.decode_k
        for i in occupied:
            col_valid = valid[:, i]
            n_new = int(col_valid.sum())
            if n_new:
                slots[i].output.extend(
                    int(t) for t in toks[col_valid, i])
                slots[i].generated += n_new
                generated += n_new
                stats.note_itl(per_tok, n_new)
            s = slots[i]
            if s.generated >= s.req.max_new:
                finish(i, "done")
            elif s.req.prompt_len + s.generated > engine.max_seq:
                # aligned with the DEVICE freeze (lengths >= max_seq,
                # i.e. prompt + generated - 1 tokens cached): the slot
                # is evicted exactly when its page filled, so truncated
                # output length does not depend on decode_k and a freed
                # slot is never still device-active
                finish(i, "evicted")
        # SLO grading on the tick cadence, not per dispatch: summary()
        # sorts every accumulated sample, and that host work would land
        # in the inter-dispatch gap — inflating the very ITL it grades
        if dispatches % max(tick_every, 1) != 0:
            continue
        summ = stats.summary()
        observe_slos(summ)
        if metrics is not None:
            wall = now()
            metrics.log(kind="serve_tick", t_s=round(wall, 4),
                        queue_depth=arrived_depth(),
                        active_slots=sum(s is not None for s in slots),
                        completed=len(results),
                        generated_tokens=generated,
                        ttft_p99_s=summ["ttft_p99_s"],
                        itl_p99_s=summ["itl_p99_s"],
                        tokens_per_sec_per_chip=(
                            round(generated / wall / n_chips, 3)
                            if wall > 0 else None))

    wall_s = now()
    # an empty run measured NOTHING: throughput is None (→ the gate
    # grades UNGATEABLE, the three-valued contract every tpudist gate
    # follows), not a 0.0 that would read as an SLO fail
    tps = (generated / wall_s) if generated and wall_s > 0 else None
    tps_chip = tps / n_chips if tps is not None else None
    summ = stats.summary()
    if requests:
        observe_slos(summ)   # runs shorter than a tick still fire
    grade = slo_lib.grade(summ["ttft_p99_s"], summ["itl_p99_s"],
                          tps_chip)
    return {
        "requests": len(requests), "completed": len(results),
        "generated_tokens": generated, "truncated": truncated,
        "wall_s": round(wall_s, 4), "dispatches": dispatches,
        "slots": engine.slots, "decode_k": engine.decode_k,
        "kv_layout": engine.layout,
        "tokens_per_sec": round(tps, 3) if tps is not None else None,
        "tokens_per_sec_per_chip": (round(tps_chip, 3)
                                    if tps_chip is not None else None),
        "n_chips": n_chips,
        "queue_depth_max": max(queue_depths, default=0),
        "queue_depth_mean": (round(float(np.mean(queue_depths)), 3)
                             if queue_depths else 0.0),
        **{k: (round(v, 6) if v is not None else None)
           for k, v in summ.items()},
        **grade,
        "alert_events": alerts.events,
        "prefill_compiles": engine.compile_counts()[0],
        "decode_compiles": engine.compile_counts()[1],
        "results": results,
        "thresholds": {rule: rules_lib.resolve(rule)
                       for rule, _ in slo_lib.SERVE_RULES},
    }
