"""Continuous batching: Poisson arrivals, slot admission, SLO accounting.

The host half of the serving engine. Requests arrive on an open-loop
Poisson schedule (a synthetic stand-in for "millions of users" — rate,
prompt lengths and generation budgets are all seeded, so a serve run is
reproducible end to end), pass ADMISSION CONTROL (bounded queue,
per-request TTFT deadlines, malformed-request rejection —
:mod:`tpudist.serve.resilience`), queue until a slot frees, prefill
into the free slot, and decode continuously: every dispatch is one
compiled superstep over the WHOLE slot batch, with completed slots
freed and refilled between dispatches — no draining, no batch
reshaping, no recompiles.

Under overload the queue does NOT grow unboundedly: arrivals past
``queue_cap`` are shed at admission, and accepted requests that age
past ``ttft_deadline_s`` while still queued are expired before they
ever touch a slot — so the requests the pod DOES serve keep a bounded
TTFT instead of every percentile inheriting the backlog. Every arrival
lands in exactly one ledger bucket (``arrived == admitted +
shed_at_admission + expired_in_queue + rejected``, checked exactly),
and every shed/expiry decision reads ONE monotonic clock sample per
scheduler boundary — no wall-clock reads inside the decision path, so
the seeded schedule sheds the same requests every run (bitwise, under
the drill's virtual clock).

Latency accounting happens here because only the host sees the request
clock: TTFT spans arrival → the fenced prefill that produced the first
token (queue wait included); ITL attributes each token in a decode
dispatch ``dispatch_wall / decode_k`` (see :mod:`tpudist.serve.slo`).
The loop feeds every observation to an :class:`~tpudist.obs.alerts.
AlertEngine` over the shared rules table, so an SLO breach FIRES as an
alert mid-run — same numbers, same thresholds as the exit verdict.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from tpudist import rules as rules_lib
from tpudist.obs import trace as trace_lib
from tpudist.obs.alerts import AlertEngine
from tpudist.serve import resilience as res_lib
from tpudist.serve import slo as slo_lib
from tpudist.serve.engine import ServeEngine


@dataclasses.dataclass(frozen=True)
class Request:
    """One synthetic inference request."""

    rid: int
    arrival_s: float          # offset from run start
    tokens: np.ndarray        # (prompt_pad,) int32, padded prompt
    prompt_len: int
    max_new: int


def shared_prefix_tokens(prefix_len: int, vocab_size: int,
                         seed: int) -> np.ndarray:
    """The run's shared system-prompt prefix: ``prefix_len`` seeded
    tokens every ``--shared-prefix`` request starts with. One function,
    used by both the request generator and the engine's prefix
    registration, so the two can never disagree about the bytes."""
    rng = np.random.default_rng([int(seed), 17])
    return rng.integers(0, vocab_size,
                        size=(int(prefix_len),)).astype(np.int32)


def make_requests(n: int, *, prompt_pad: int, vocab_size: int,
                  max_new: int, rate: float, seed: int,
                  prompt_min: int = 0,
                  prefix_len: int = 0) -> List[Request]:
    """Seeded synthetic request stream.

    Arrivals: Poisson process at ``rate`` requests/s (exponential
    inter-arrival gaps); ``rate <= 0`` means every request is present at
    t=0 — the closed-loop mode benchmarks and probes use. Prompts reuse
    the training data's deterministic next-token structure (the affine
    map of data.make_synthetic_tokens) with per-request lengths drawn
    from [prompt_min, prompt_pad]. ``prefix_len > 0`` gives every
    request the same :func:`shared_prefix_tokens` system-prompt prefix
    (per-request tails stay distinct; prompt lengths never undercut the
    prefix) — the paged engine's shared-prefix workload. ``prefix_len
    = 0`` is bit-for-bit the original stream (identical rng draws)."""
    rng = np.random.default_rng(seed)
    if rate > 0:
        arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n))
    else:
        arrivals = np.zeros(n)
    prompt_min = min(max(1, prompt_min or prompt_pad // 2), prompt_pad)
    if prefix_len > 0:
        prefix_len = min(int(prefix_len), prompt_pad)
        prompt_min = max(prompt_min, prefix_len)
    lens = rng.integers(prompt_min, prompt_pad + 1, size=n)
    first = rng.integers(0, vocab_size, size=(n, 1)).astype(np.int32)
    toks = np.empty((n, prompt_pad), np.int32)
    toks[:, :1] = first
    for t in range(1, prompt_pad):
        toks[:, t] = (toks[:, t - 1] * 7 + 3) % vocab_size
    if prefix_len > 0:
        # overwrite the head with the shared prefix; the tail keeps
        # each request's own chain (seeded from its own first token)
        toks[:, :prefix_len] = shared_prefix_tokens(
            prefix_len, vocab_size, seed)[None, :]
    out = []
    for i in range(n):
        padded = toks[i].copy()
        padded[lens[i]:] = 0     # pad-token tail, masked by prompt_len
        out.append(Request(rid=i, arrival_s=float(arrivals[i]),
                           tokens=padded, prompt_len=int(lens[i]),
                           max_new=int(max_new)))
    return out


def ngram_draft(history, k: int) -> List[int]:
    """The cheap host-side draft proposer for speculative decoding:
    repeat what followed the last occurrence of the current token in
    the sequence so far (classic n-gram lookup, n=1), falling back to
    repeating the token itself. Deterministic and model-free — the
    verify forward accepts exactly the prefix of the draft that matches
    the target's own greedy choices, so a bad draft costs nothing but
    its acceptance rate."""
    h = [int(t) for t in history]
    out: List[int] = []
    for _ in range(int(k)):
        last = h[-1]
        nxt = last
        for i in range(len(h) - 2, -1, -1):
            if h[i] == last:
                nxt = h[i + 1]
                break
        out.append(nxt)
        h.append(nxt)
    return out


def validate_request(req: Request, *, prompt_pad: int,
                     vocab_size: int) -> Optional[str]:
    """Admission-time request validation: the reason a malformed
    request is rejected, or None for a well-formed one. The engine's
    compiled prefill assumes a (prompt_pad,) int32 prompt with an
    in-range true length and a positive budget — anything else must be
    turned away HERE (the ``request_garbage`` chaos family's contract:
    garbage costs itself a rejection, never the engine)."""
    pl, mn = req.prompt_len, req.max_new
    if not isinstance(pl, (int, np.integer)) or not (0 < pl <= prompt_pad):
        return "bad_prompt_len"
    if not isinstance(mn, (int, np.integer)) or mn < 1:
        return "bad_max_new"
    try:
        toks = np.asarray(req.tokens)
    except Exception:
        return "bad_tokens"
    if toks.shape != (prompt_pad,):
        return "bad_shape"
    if not np.issubdtype(toks.dtype, np.integer):
        return "bad_dtype"
    if ((toks[:pl] < 0) | (toks[:pl] >= vocab_size)).any():
        return "bad_token"
    return None


def make_garbage_requests(plan, event, *, rid_base: int, prompt_pad: int,
                          vocab_size: int, span_s: float
                          ) -> List[Request]:
    """The ``request_garbage`` chaos family's payload: ``n`` seeded
    malformed requests spread over the arrival window, each broken a
    deterministically-chosen way (out-of-range tokens, zero/oversized
    prompt_len, dead budget, wrong shape, float tokens). Derived from
    the plan's keyed byte stream, so the same spec injects the same
    garbage every run and the fuzz drill is replayable."""
    from tpudist.chaos import plan as plan_mod
    n = int(event.args.get("n", 4))
    raw = plan_mod.garbage_bytes(plan, event, n=8 * max(n, 1))
    modes = ("bad_token", "zero_len", "over_len", "bad_max_new",
             "bad_shape", "bad_dtype")
    out: List[Request] = []
    for i in range(n):
        chunk = raw[8 * i:8 * i + 8]
        arrival = (int.from_bytes(chunk[:4], "big") / 0xFFFFFFFF) \
            * max(span_s, 0.0)
        mode = modes[chunk[4] % len(modes)]
        tokens = np.zeros((prompt_pad,), np.int32)
        prompt_len, max_new = max(1, prompt_pad // 2), 4
        if mode == "bad_token":
            tokens[0] = vocab_size + 1 + chunk[5]
        elif mode == "zero_len":
            prompt_len = 0
        elif mode == "over_len":
            prompt_len = prompt_pad + 1 + chunk[5] % 8
        elif mode == "bad_max_new":
            max_new = -int(chunk[5])
        elif mode == "bad_shape":
            tokens = np.zeros((prompt_pad + 3,), np.int32)
        elif mode == "bad_dtype":
            tokens = np.zeros((prompt_pad,), np.float64) + 0.5
        out.append(Request(rid=rid_base + i, arrival_s=float(arrival),
                           tokens=tokens, prompt_len=prompt_len,
                           max_new=max_new))
    return out


@dataclasses.dataclass
class _Slot:
    req: Request
    generated: int
    first_token_s: float
    output: List[int]
    budget: int               # max_new after any adapt-time truncation


def run_serve(engine: ServeEngine, params, requests: List[Request], *,
              metrics: Any = None, tick_every: int = 8,
              clock: Callable[[], float] = time.perf_counter,
              n_chips: Optional[int] = None,
              resilience: Optional[res_lib.ResilienceConfig] = None,
              chaos: Any = None,
              virtual: Optional[res_lib.VirtualTiming] = None,
              flush_events: Optional[bool] = None,
              shared_prefix: Optional[np.ndarray] = None
              ) -> Dict[str, Any]:
    """Drive the engine over the request stream; returns the run summary
    (percentiles, throughput, per-gate SLO statuses, the exact shed
    partition, compile counts).

    The engine must already be warmed (:meth:`ServeEngine.warmup`) so
    the request clock never pays XLA compilation. ``metrics`` (a
    MetricsLogger) receives periodic ``kind=serve_tick`` records plus
    per-request ``kind=serve_request`` outcome events; the caller logs
    the final ``kind=serve`` summary so it can stamp its own fields in.

    ``resilience`` turns on admission control / degradation
    (:class:`~tpudist.serve.resilience.ResilienceConfig`; None keeps
    the pre-resilience open-loop behavior bit-for-bit). ``chaos`` is a
    :class:`~tpudist.chaos.inject.ChaosRuntime` whose serve surface
    (``on_serve_dispatch``) fires at every decode-dispatch boundary.
    ``virtual`` switches the request clock to deterministic virtual
    time (:class:`~tpudist.serve.resilience.VirtualTiming`) — the
    overload drill's bitwise mode. ``flush_events`` arms BOUNDARY
    flushes of the buffered per-request outcome events — before every
    chaos dispatch hook and on the tick cadence — so a kill cannot eat
    the evidence the resumed attempt classifies from (default: on when
    chaos or resilience is armed; the CLI also arms it under the
    launcher's requeue supervision).

    A PAGED engine (``engine.paged``) changes admission and dispatch,
    never the accounting: slot admission additionally asks the page
    allocator (a pool too full leaves the request WAITING —
    backpressure, not shedding — while a request too big to EVER fit
    this pool is ``rejected`` with reason ``kv_pages_exhausted``, in
    the same exact ledger partition); each dispatch first grows every
    live slot's page mapping to cover the positions it will write
    (growth failure evicts, freeing the pages); finishing a slot
    returns its pages. ``shared_prefix`` (token array) registers a
    refcounted shared system-prompt prefix once, served from the same
    pages to every admission that starts with it. With
    ``engine.speculate_k >= 2`` decode dispatches become draft+verify:
    the host :func:`ngram_draft` proposes, the engine's ONE batched
    verify forward accepts — greedy output stays bitwise identical to
    plain decode, only the tokens-per-dispatch changes. Speculation
    runs at adapt level 0 only (the degradation ladder's rungs are
    plain decode programs)."""
    import jax
    if n_chips is None:
        n_chips = max(jax.device_count(), 1)
    res = resilience or res_lib.ResilienceConfig()
    if virtual is not None:
        clock = virtual.clock
    if flush_events is None:
        flush_events = chaos is not None or res.enabled
    tracer = trace_lib.get()
    stats = slo_lib.LatencyStats()
    alerts = AlertEngine()
    led = res_lib.ShedLedger()
    controller = None
    if res.adapt and len(engine.ladder) > 1:
        controller = res_lib.PressureController(
            res, max_level=len(engine.ladder) - 1)
    cur_level = 0
    cur_k = engine.ladder[0]
    pending = deque(sorted(requests, key=lambda r: (r.arrival_s, r.rid)))
    waiting: deque = deque()         # accepted, not yet slotted
    slots: List[Optional[_Slot]] = [None] * engine.slots
    state = engine.init_state()
    paged = bool(getattr(engine, "paged", False))
    alloc = engine.new_allocator() if paged else None
    prefix_len = 0
    prefix_arr: Optional[np.ndarray] = None
    if paged and shared_prefix is not None and len(shared_prefix) > 0:
        prefix_arr = np.asarray(shared_prefix, np.int32)
        prefix_len = int(min(len(prefix_arr), engine.prompt_pad))
        prefix_arr = prefix_arr[:prefix_len]
        # fills the prefix's full pages via the ONE prefill program;
        # a pool that cannot hold the prefix is a config error, raised
        state = engine.register_prefix(params, state, prefix_arr,
                                       prefix_len)
    spec_k = int(getattr(engine, "speculate_k", 0))
    results: Dict[int, Dict[str, Any]] = {}
    generated = truncated = dispatches = 0
    drafted = accepted = 0          # speculative-draft acceptance
    active_peak = pages_peak = 0
    queue_depths: List[int] = []
    recent_tok: deque = deque(maxlen=max(res.window, 1))
    t0 = clock()

    def is_shared(req: Request) -> bool:
        # a request rides the shared prefix iff its prompt literally
        # starts with it — byte-checked, never assumed
        return (prefix_arr is not None
                and req.prompt_len >= prefix_len
                and np.array_equal(np.asarray(req.tokens)[:prefix_len],
                                   prefix_arr))

    def now() -> float:
        return clock() - t0

    def event(rid: int, ev: str, **kw: Any) -> None:
        # the per-request outcome stream the drill verifier (and a
        # resumed attempt's lost-slot classification) replays. Buffered
        # here — durability comes from the BOUNDARY flushes below (per
        # dispatch ahead of the chaos hook, per tick otherwise), not a
        # write+flush per outcome on the serving host path.
        # Every outcome is ALSO a lifecycle instant on the flight
        # timeline (cat=serve, keyed by rid, same spellings as the
        # resilience vocabulary) — the flight ledger cross-checks the
        # two streams, so they are emitted from the same call site
        tracer.instant(ev, cat="serve", rid=rid, **kw)
        if metrics is None:
            return
        metrics.log(kind="serve_request", rid=rid, event=ev,
                    t_s=round(now(), 6), **kw)

    def shared_refs() -> int:
        # refcounts currently held on the shared-prefix pages
        # (includes the registry's own keep-cached hold)
        if alloc is None or not alloc.shared_pages:
            return 0
        return int(sum(int(alloc.refcount[p])
                       for p in alloc.shared_pages))

    def finish(i: int, why: str) -> None:
        nonlocal truncated
        s = slots[i]
        t_done = now()     # ONE sample: results/stats/event agree
        results[s.req.rid] = {
            "tokens": list(s.output), "prompt_len": s.req.prompt_len,
            "generated": s.generated, "why": why,
            "adapt_truncated": s.budget < s.req.max_new,
            "e2e_s": t_done - s.req.arrival_s}
        stats.note_e2e(t_done - s.req.arrival_s)
        if why == "evicted":
            truncated += 1
            led.evicted += 1
        else:
            led.completed += 1
        event(s.req.rid, res_lib.DONE if why == "done" else
              res_lib.EVICTED, slot=i, generated=s.generated,
              e2e_s=round(t_done - s.req.arrival_s, 6),
              decode_s=round(t_done - s.first_token_s, 6))
        slots[i] = None
        if paged:
            # pages return to the pool (shared prefix pages drop one
            # refcount; the registry hold keeps them cached). Safe: the
            # device slot is frozen (budget/capacity) or masked out of
            # every future dispatch until its next prefill
            alloc.free_slot(i)

    def expire(t: float) -> None:
        # the accepted queue's head is always the oldest (FIFO in
        # arrival order), so deadline expiry only ever pops from there
        while waiting and t - waiting[0].arrival_s \
                > res.ttft_deadline_s:
            r = waiting.popleft()
            led.expired_queue += 1
            event(r.rid, res_lib.EXPIRED,
                  waited_s=round(t - r.arrival_s, 6))

    def pump(t: float) -> None:
        """Admission control at ONE sampled time ``t``: first expire
        the deadline-aged queue heads, THEN process arrivals against
        the post-expiry queue — a fresh arrival must never be shed at
        the cap by requests that are already dead at the same sampled
        instant. An arrival whose own deadline passed while it sat in
        the schedule backlog counts expired, not shed (it was never
        servable). No clock reads in here: determinism under the
        seeded schedule is exactly this function never asking twice."""
        if res.ttft_deadline_s > 0:
            expire(t)
        while pending and pending[0].arrival_s <= t:
            req = pending.popleft()
            led.arrived += 1
            # the flight chain's opening marker: every arrived rid gets
            # exactly one, whatever admission then decides
            tracer.instant("arrive", cat="serve", rid=req.rid,
                           arrival_s=round(req.arrival_s, 6),
                           prompt_len=req.prompt_len)
            why = validate_request(
                req, prompt_pad=engine.prompt_pad,
                vocab_size=engine.model_cfg.vocab_size) \
                if res.validate else None
            if why is not None:
                led.rejected += 1
                event(req.rid, res_lib.REJECTED, reason=why)
            elif res.ttft_deadline_s > 0 \
                    and t - req.arrival_s > res.ttft_deadline_s:
                led.expired_queue += 1
                event(req.rid, res_lib.EXPIRED,
                      waited_s=round(t - req.arrival_s, 6))
            elif res.queue_cap and len(waiting) >= res.queue_cap:
                led.shed_admission += 1
                event(req.rid, res_lib.SHED,
                      queue_depth=len(waiting))
            else:
                waiting.append(req)

    def admit() -> None:
        nonlocal generated, state
        t = now()
        pump(t)
        for i in range(engine.slots):
            if slots[i] is not None or not waiting:
                continue
            shared = False
            if paged:
                # peek-then-pop: a denied admission must leave the
                # request at the queue head, not shed it
                req = waiting[0]
                shared = is_shared(req)
                if not alloc.can_ever_admit(req.prompt_len, shared):
                    # structurally unservable at this pool size: even
                    # an empty pool could not hold the prompt. Reject
                    # (exact-partition bucket) instead of wedging the
                    # queue head forever
                    waiting.popleft()
                    led.rejected += 1
                    event(req.rid, res_lib.REJECTED,
                          reason="kv_pages_exhausted")
                    continue
                pt = engine.spec.page_tokens
                need = -(-req.prompt_len // pt)
                reused = min(need, len(alloc.shared_pages)) \
                    if shared else 0
                if not alloc.admit(i, req.prompt_len, shared=shared):
                    # pool full RIGHT NOW: backpressure, not shedding —
                    # running slots will finish and free pages
                    tracer.instant("kv_backpressure", cat="serve",
                                   rid=req.rid, slot=i, pages=need)
                    break
                tracer.instant("kv_admit", cat="serve", rid=req.rid,
                               slot=i, pages=need,
                               pages_granted=need - reused,
                               shared_pages_reused=reused)
            req = waiting.popleft()
            budget = req.max_new
            if cur_level > 0 and res.max_new_cap:
                budget = min(budget, res.max_new_cap)
            with tracer.span("admit", cat="serve", rid=req.rid, slot=i):
                pass   # the admission decision itself is host-trivial
            with tracer.span("prefill", cat="serve", rid=req.rid,
                             slot=i, prompt_len=req.prompt_len):
                if paged:
                    state, first = engine.prefill(
                        params, state, req.tokens[None, :],
                        req.prompt_len, i, budget,
                        shared_len=alloc.admit_shared_len(shared))
                else:
                    state, first = engine.prefill(
                        params, state, req.tokens[None, :],
                        req.prompt_len, i, budget)
                first = int(first)           # fence: the token exists NOW
            if virtual is not None:
                virtual.clock.advance(virtual.prefill_s)
            t_first = now()
            led.admitted += 1
            # waited_s is the TTFT; its exact decomposition rides along
            # (queue wait up to the sampled admission instant ``t``,
            # then prefill+fence up to ``t_first``) so the flight
            # ledger can assert ttft == queue_wait + prefill without
            # any extra clock reads on the decision path
            event(req.rid, res_lib.ADMITTED, slot=i,
                  waited_s=round(t_first - req.arrival_s, 6),
                  queue_wait_s=round(t - req.arrival_s, 6),
                  prefill_s=round(t_first - t, 6))
            stats.note_ttft(t_first - req.arrival_s)
            generated += 1
            slots[i] = _Slot(req=req, generated=1, first_token_s=t_first,
                             output=[first], budget=budget)
            if budget <= 1 or req.prompt_len >= engine.max_seq:
                finish(i, "done" if budget <= 1 else "evicted")
            t = now()
            pump(t)        # arrivals that landed during the prefill

    def observe_slos(summ: Dict[str, Any]) -> None:
        alerts.observe("ttft", summ["ttft_p99_s"])
        alerts.observe("itl", summ["itl_p99_s"])
        alerts.observe("serve_shed", led.shed_fraction())
        wall = now()
        if wall > 0 and generated:
            alerts.observe("tokens_per_chip",
                           generated / wall / n_chips)

    while len(results) + led.shed_total() < len(requests):
        admit()
        occupied = [i for i in range(engine.slots) if slots[i] is not None]
        if not occupied:
            if waiting:
                # accepted work and free slots, but every slot FINISHED
                # inside this admit pass (an instant budget<=1 / full-
                # prompt completion): loop straight back into admit.
                # This check must come BEFORE the next-arrival wait —
                # warping the clock past queued servable requests would
                # expire (or TTFT-inflate) them with slots sitting free
                continue
            # nothing running and nothing queued: wait out the gap to
            # the next scheduled arrival (bounded — the generator's
            # schedule is finite)
            if pending:
                if virtual is not None:
                    virtual.clock.wait_until(t0 + pending[0].arrival_s)
                else:
                    time.sleep(min(0.002, max(
                        0.0, pending[0].arrival_s - now())))
                continue
            break
        # depth sampled once per DISPATCH (not per idle busy-wait pass:
        # a sparse schedule would drown the mean in idle-gap zeros and
        # grow the sample list unboundedly)
        queue_depths.append(len(waiting))
        # speculation only at full service: the degradation ladder's
        # rungs are plain decode programs, and a downshifted pod wants
        # its smallest dispatch, not a wider verify window
        spec_on = paged and spec_k >= 2 and cur_level == 0
        if paged:
            # grow each live slot's mapping to cover every position
            # this dispatch can write; a slot the pool cannot grow for
            # is evicted (truncated output, pages fund the others)
            width = spec_k if spec_on else cur_k
            for i in occupied:
                s = slots[i]
                last = min(s.req.prompt_len + s.generated + width - 2,
                           engine.max_seq - 1)
                if not alloc.ensure(i, last):
                    finish(i, "evicted")
            occupied = [i for i in range(engine.slots)
                        if slots[i] is not None]
            if not occupied:
                continue
        # the chaos serve surface: serve_kill dies HERE (a dispatch
        # boundary — the compiled program is never torn mid-flight),
        # serve_slow returns the stall it injected so virtual time can
        # account it. Flush the buffered outcome events FIRST: a kill
        # at this boundary must not eat the evidence the resumed
        # attempt's lost-slot classification replays.
        stall_s = 0.0
        if chaos is not None:
            if flush_events and metrics is not None:
                metrics.flush()
            stall_s = float(chaos.on_serve_dispatch(dispatches) or 0.0)
        t_dispatch = clock()
        if spec_on:
            occ_mask = np.array([s is not None for s in slots])
            draft = np.zeros((engine.slots, spec_k - 1), np.int32)
            for i in occupied:
                s = slots[i]
                draft[i] = ngram_draft(
                    list(s.req.tokens[:s.req.prompt_len]) + s.output,
                    spec_k - 1)
            with tracer.span("verify_step", cat="serve",
                             active=len(occupied), window=spec_k):
                state, toks, valid, _emitted = engine.verify(
                    params, state, draft, dispatch_active=occ_mask)
                toks = np.asarray(toks)      # fence: tokens on host
                valid = np.asarray(valid)
        elif paged:
            occ_mask = np.array([s is not None for s in slots])
            with tracer.span("decode_step", cat="serve",
                             active=len(occupied), decode_k=cur_k):
                state, toks, valid = engine.decode(
                    params, state, cur_k, dispatch_active=occ_mask)
                toks = np.asarray(toks)      # fence: tokens on host
                valid = np.asarray(valid)
        else:
            with tracer.span("decode_step", cat="serve",
                             active=len(occupied), decode_k=cur_k):
                state, toks, valid = engine.decode(params, state, cur_k)
                toks = np.asarray(toks)      # fence: tokens on host
                valid = np.asarray(valid)
        if virtual is not None:
            dt = virtual.decode_s + stall_s
            virtual.clock.advance(dt)
        else:
            dt = (clock() - t_dispatch) + stall_s
        dispatches += 1
        active_peak = max(active_peak, len(occupied))
        if paged:
            pages_peak = max(pages_peak, alloc.pages_used())
            if tracer.enabled:
                # KV-pool occupancy sample, one per dispatch: becomes
                # the ph="C" counter track in pod_trace.json so cache
                # pressure sits on the same timeline as the request
                # spans causing it. Guarded: the refcount walk (and
                # the clock read inside instant) must cost nothing
                # when tracing is off
                tracer.instant("kv_pages", cat="serve_counter",
                               used=alloc.pages_used(),
                               total=engine.spec.pages,
                               shared_refs=shared_refs())
        if spec_on:
            # a verify dispatch emits a VARIABLE token count per slot:
            # ITL attributes the dispatch wall over each slot's own
            # accepted run (that is speculation's whole win)
            tot_new = int(valid.sum())
            mean_new = tot_new / max(len(occupied), 1)
            recent_tok.append(dt / mean_new if mean_new > 0 else dt)
            per_tok = None
        else:
            per_tok = dt / cur_k
            recent_tok.append(per_tok)
        for i in occupied:
            col_valid = valid[:, i]
            n_new = int(col_valid.sum())
            if n_new:
                slots[i].output.extend(
                    int(t) for t in toks[col_valid, i])
                slots[i].generated += n_new
                generated += n_new
                stats.note_itl(dt / n_new if spec_on else per_tok,
                               n_new)
                if spec_on:
                    accepted += n_new - 1    # minus the bonus token
                    drafted += spec_k - 1
            s = slots[i]
            # per-slot decode attribution on the flight timeline: the
            # ledger sums these per rid and pins the total against the
            # terminal event's generated count (first token excluded)
            if spec_on:
                tracer.instant("decode_emit", cat="serve",
                               rid=s.req.rid, slot=i, tokens=n_new,
                               dispatch=dispatches,
                               drafted=spec_k - 1,
                               accepted=max(n_new - 1, 0))
            else:
                tracer.instant("decode_emit", cat="serve",
                               rid=s.req.rid, slot=i, tokens=n_new,
                               dispatch=dispatches)
            if s.generated >= s.budget:
                finish(i, "done")
            elif s.req.prompt_len + s.generated > engine.max_seq:
                # aligned with the DEVICE freeze (lengths >= max_seq,
                # i.e. prompt + generated - 1 tokens cached): the slot
                # is evicted exactly when its page filled, so truncated
                # output length does not depend on decode_k and a freed
                # slot is never still device-active
                finish(i, "evicted")
        # SLO grading on the tick cadence, not per dispatch: summary()
        # sorts every accumulated sample, and that host work would land
        # in the inter-dispatch gap — inflating the very ITL it grades
        if dispatches % max(tick_every, 1) != 0:
            continue
        if flush_events and metrics is not None:
            # amortised durability for the supervised-but-unchaosed
            # path (a REAL preemption can land anywhere): at most one
            # tick window of outcome events is at risk, not the run
            metrics.flush()
        summ = stats.summary()
        observe_slos(summ)
        if controller is not None:
            recent_itl = (sum(recent_tok) / len(recent_tok)
                          if recent_tok else None)
            trans = controller.observe(len(waiting), recent_itl)
            if trans is not None:
                frm, to, reason = trans
                cur_level = to
                cur_k = engine.ladder[min(to, len(engine.ladder) - 1)]
                if metrics is not None:
                    # every ladder move is a flushed, auditable record:
                    # the drill verifier and the live view both read it
                    metrics.log(kind="serve_adapt",
                                t_s=round(now(), 4), from_level=frm,
                                to_level=to, decode_k=cur_k,
                                queue_depth=len(waiting),
                                reason=reason)
                    metrics.flush()
        if metrics is not None:
            wall = now()
            extra: Dict[str, Any] = {}
            if paged:
                # the PAGED footprint — what is actually allocated
                # (pool + table), not the dense slots×max_seq formula
                extra = {"kv_pages_used": alloc.pages_used(),
                         "kv_pages_total": engine.spec.pages,
                         "kv_cache_bytes": engine.spec.bytes,
                         "kv_shared_refs": shared_refs(),
                         "spec_accept_rate": (
                             round(accepted / drafted, 4)
                             if drafted else None)}
            metrics.log(kind="serve_tick", t_s=round(wall, 4),
                        queue_depth=len(waiting),
                        active_slots=sum(s is not None for s in slots),
                        completed=len(results),
                        generated_tokens=generated,
                        shed_total=led.shed_total(),
                        shed_fraction=led.shed_fraction(),
                        adapt_level=cur_level,
                        decode_k=cur_k,
                        ttft_p99_s=summ["ttft_p99_s"],
                        itl_p99_s=summ["itl_p99_s"],
                        tokens_per_sec_per_chip=(
                            round(generated / wall / n_chips, 3)
                            if wall > 0 else None),
                        # self-describing fixed-bucket histograms: the
                        # live Prometheus exporter renders native
                        # _bucket{le=...} series straight from these —
                        # raw samples never leave the serving host
                        ttft_hist=stats.ttft_hist(),
                        itl_hist=stats.itl_hist(),
                        **extra)

    wall_s = now()
    # an empty run measured NOTHING: throughput is None (→ the gate
    # grades UNGATEABLE, the three-valued contract every tpudist gate
    # follows), not a 0.0 that would read as an SLO fail
    tps = (generated / wall_s) if generated and wall_s > 0 else None
    tps_chip = tps / n_chips if tps is not None else None
    summ = stats.summary()
    if requests:
        observe_slos(summ)   # runs shorter than a tick still fire
    grade = slo_lib.grade(summ["ttft_p99_s"], summ["itl_p99_s"],
                          tps_chip, shed_fraction=led.shed_fraction())
    return {
        "requests": len(requests), "completed": len(results),
        "generated_tokens": generated, "truncated": truncated,
        "wall_s": round(wall_s, 4), "dispatches": dispatches,
        "slots": engine.slots, "decode_k": engine.decode_k,
        "kv_layout": engine.layout,
        "tokens_per_sec": round(tps, 3) if tps is not None else None,
        "tokens_per_sec_per_chip": (round(tps_chip, 3)
                                    if tps_chip is not None else None),
        "n_chips": n_chips,
        "queue_depth_max": max(queue_depths, default=0),
        "queue_depth_mean": (round(float(np.mean(queue_depths)), 3)
                             if queue_depths else 0.0),
        # the exact shed partition (headline fields lifted for the
        # bench/report consumers; the full checked block under
        # "partition")
        "arrived": led.arrived, "admitted": led.admitted,
        "shed_at_admission": led.shed_admission,
        "expired_in_queue": led.expired_queue,
        "rejected": led.rejected, "lost": led.lost,
        "shed_total": led.shed_total(),
        "shed_fraction": led.shed_fraction(),
        "partition": led.as_dict(),
        "queue_cap": res.queue_cap,
        "ttft_deadline_s": res.ttft_deadline_s,
        "adapt_level": cur_level, "decode_k_current": cur_k,
        "decode_k_ladder": list(engine.ladder),
        "adapt_transitions": (list(controller.transitions)
                              if controller is not None else []),
        **{k: (round(v, 6) if v is not None else None)
           for k, v in summ.items()},
        **grade,
        "alert_events": alerts.events,
        "prefill_compiles": engine.compile_counts()[0],
        "decode_compiles": engine.compile_counts()[1],
        "verify_compiles": len(getattr(engine, "verify_traces", [])),
        "active_slots_peak": active_peak,
        "kv_page_tokens": (engine.spec.page_tokens if paged else 0),
        "kv_pages_total": (engine.spec.pages if paged else 0),
        "kv_pages_used_peak": pages_peak,
        "spec_accept_rate": (round(accepted / drafted, 4)
                             if drafted else None),
        "speculate_k": spec_k,
        "shared_prefix_len": prefix_len,
        "ttft_hist": stats.ttft_hist(),
        "itl_hist": stats.itl_hist(),
        "results": results,
        "thresholds": {rule: rules_lib.resolve(rule)
                       for rule, _ in slo_lib.SERVE_RULES},
    }
