"""Sharded KV cache for the serving engine: dense arena OR paged pool.

Two storage disciplines, one module:

* **Dense arena** (:class:`CacheSpec`, the original): one K and one V
  array of canonical shape ``(n_layers, slots, max_seq, n_kv_heads,
  head_dim)`` — one private ``max_seq``-long row per slot. Simple, but
  HBM scales with ``slots × max_seq`` even when most slots hold short
  sequences, and an identical system-prompt prefix is stored once per
  concurrent request.
* **Paged pool** (:class:`PagedCacheSpec` + :class:`PageAllocator`,
  vLLM-style): fixed-size pages of ``page_tokens`` positions in a pool
  of ``pages`` (+1 sacrificial TRASH page), mapped to slots through a
  host-owned slot→page table. A slot only holds pages for positions it
  has actually written, so the pool can be sized well below
  ``slots × max_seq`` — the freed HBM becomes sustained concurrency.
  Full prefix pages of a common system prompt are REFCOUNTED and shared
  across every slot (``register_shared``); the partial tail page is
  "forked" copy-on-write at admission (the prefill recomputes those
  positions into the slot's first private page — bitwise-identical
  content, same tokens at the same absolute positions), so no slot ever
  writes a shared page. Invalid/masked writes are routed to the trash
  page (pool index ``pages``), which no page table ever references and
  the ownership mask therefore never reads.

The page table itself never lives on device state: the HOST allocator
owns it and each dispatch passes the current table in as a small traced
int32 array — the compiled programs stay exactly the programs the
two-program discipline pinned (tpudist.serve.engine), and admission /
eviction / page exhaustion are pure host decisions between dispatches.

GQA-aware by construction either way: the cache stores the COMPACT kv
heads (the same layout the models' ``wk``/``wv`` produce) and expansion
to the query head count happens inside the attention math — an
8×-grouped model's cache is 8× smaller than a naive full-head cache,
which is the difference between fitting long contexts in HBM or not.

Sharding rides the existing mesh machinery: ``parallel.sharding.
kv_cache_specs`` / ``paged_kv_cache_specs`` are the ``param_specs``-
style single sources for the PartitionSpecs (slots — or pages — over
the batch axes, kv heads over tensor), sanitised per-mesh exactly like
model params.

``layout`` is a PHYSICAL storage knob the serve autotuner probes for
the dense arena: ``"st"`` (canonical, seq-major) or ``"hs"``
(heads-major). The models' cache API always sees canonical;
:func:`to_canonical` / :func:`from_canonical` transpose inside the
compiled program, so the layout's real cost/benefit is exactly what
the probe measures. The paged pool has one physical layout (pages are
already the placement unit).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpudist.config import ModelConfig
from tpudist.parallel import sharding as shd
from tpudist.parallel.sharding import KV_CACHE_LAYOUTS  # noqa: F401


@dataclasses.dataclass(frozen=True)
class CacheSpec:
    """Static shape/dtype/layout of one serving run's KV cache."""

    n_layers: int
    slots: int
    max_seq: int
    n_kv_heads: int
    head_dim: int
    dtype: Any = jnp.float32
    layout: str = "st"

    @classmethod
    def from_model(cls, cfg: ModelConfig, *, slots: int, max_seq: int,
                   dtype=jnp.float32, layout: str = "st") -> "CacheSpec":
        return cls(n_layers=cfg.n_layers, slots=slots, max_seq=max_seq,
                   n_kv_heads=cfg.n_kv_heads,
                   head_dim=cfg.d_model // cfg.n_heads,
                   dtype=dtype, layout=layout)

    @property
    def canonical_shape(self) -> tuple:
        return (self.n_layers, self.slots, self.max_seq,
                self.n_kv_heads, self.head_dim)

    @property
    def storage_shape(self) -> tuple:
        l, s, t, h, d = self.canonical_shape
        return (l, s, t, h, d) if self.layout == "st" else (l, s, h, t, d)

    @property
    def bytes(self) -> int:
        """Total cache footprint (K + V) — the number an operator sizes
        slots × max_seq against HBM with."""
        n = 1
        for d in self.canonical_shape:
            n *= d
        return 2 * n * jnp.dtype(self.dtype).itemsize


def to_canonical(arr: jax.Array, layout: str) -> jax.Array:
    """Storage layout → canonical (L, slots, seq, kv_heads, head_dim).
    A no-op for ``"st"``; ``"hs"`` transposes (the swap is its own
    inverse, so one permutation serves both directions)."""
    if layout == "st":
        return arr
    if layout == "hs":
        return jnp.transpose(arr, (0, 1, 3, 2, 4))
    raise ValueError(f"unknown kv-cache layout {layout!r}: "
                     f"{' | '.join(KV_CACHE_LAYOUTS)}")


def from_canonical(arr: jax.Array, layout: str) -> jax.Array:
    """Canonical → storage layout (see :func:`to_canonical`)."""
    return to_canonical(arr, layout)


def cache_shardings(spec: CacheSpec, mesh) -> Any:
    """NamedSharding for the K/V arrays on ``mesh``, sanitised like
    model params (a slot count the batch axes don't divide falls back
    to replicated instead of erroring)."""
    shape = jax.ShapeDtypeStruct(spec.storage_shape, spec.dtype)
    pspec = shd.sanitize_specs(
        shape, shd.kv_cache_specs(spec.layout), mesh)
    return shd.named(mesh, pspec)


def init_cache(spec: CacheSpec, mesh=None) -> Dict[str, jax.Array]:
    """Zero-initialised ``{"k", "v"}`` cache in the storage layout,
    placed to its mesh sharding when one is given. Zeros are never read
    (the length mask guards every slot), but a deterministic initial
    value keeps the whole serve run a pure function of (params, seed)."""
    k = jnp.zeros(spec.storage_shape, spec.dtype)
    v = jnp.zeros(spec.storage_shape, spec.dtype)
    if mesh is not None:
        sh = cache_shardings(spec, mesh)
        k = jax.device_put(k, sh)
        v = jax.device_put(v, sh)
    return {"k": k, "v": v}


# ------------------------------------------------------------------ #
# paged pool                                                          #
# ------------------------------------------------------------------ #


@dataclasses.dataclass(frozen=True)
class PagedCacheSpec:
    """Static shape/dtype of one serving run's PAGED KV pool.

    ``pages`` is the usable pool size; the physical pool carries one
    extra sacrificial TRASH page at index ``pages`` where every
    masked/invalid write is routed (a page table never references it,
    so the ownership mask never reads it — the paged twin of the dense
    arena's clamped junk writes). ``page_tokens`` is the fixed page
    length in positions; ``max_pages_per_slot`` is the page-table row
    width (``ceil(max_seq / page_tokens)``)."""

    n_layers: int
    slots: int
    max_seq: int
    n_kv_heads: int
    head_dim: int
    page_tokens: int
    pages: int
    dtype: Any = jnp.float32

    @classmethod
    def from_model(cls, cfg: ModelConfig, *, slots: int, max_seq: int,
                   page_tokens: int, pages: int = 0,
                   dtype=jnp.float32) -> "PagedCacheSpec":
        if not 0 < page_tokens <= max_seq:
            raise ValueError(
                f"--kv-page-tokens {page_tokens} must be in (0, "
                f"max_seq {max_seq}]")
        maxp = -(-max_seq // page_tokens)
        if pages <= 0:
            # default pool = full dense capacity: correctness-neutral
            # sizing (admission can never be denied); operators shrink
            # it to trade capacity for sustained concurrency
            pages = slots * maxp
        return cls(n_layers=cfg.n_layers, slots=slots, max_seq=max_seq,
                   n_kv_heads=cfg.n_kv_heads,
                   head_dim=cfg.d_model // cfg.n_heads,
                   page_tokens=int(page_tokens), pages=int(pages),
                   dtype=dtype)

    @property
    def max_pages_per_slot(self) -> int:
        return -(-self.max_seq // self.page_tokens)

    @property
    def pool_shape(self) -> tuple:
        # +1: the trash page
        return (self.n_layers, self.pages + 1, self.page_tokens,
                self.n_kv_heads, self.head_dim)

    @property
    def table_bytes(self) -> int:
        return self.slots * self.max_pages_per_slot * 4   # int32 table

    @property
    def bytes(self) -> int:
        """The PAGED footprint: pool pages (trash included — it is
        real HBM) × page bytes for K and V, plus the page-table
        overhead. This is the number serve_tick / BENCH_SERVE report,
        so the fixed-HBM-budget acceptance claim is measured against
        what is actually allocated, not the dense formula."""
        n = 1
        for d in self.pool_shape:
            n *= d
        return 2 * n * jnp.dtype(self.dtype).itemsize + self.table_bytes


def paged_cache_shardings(spec: PagedCacheSpec, mesh) -> Any:
    """NamedSharding for the paged K/V pools: pages ride the batch axes
    (the pool's embarrassingly-parallel dim, like slots in the dense
    arena), kv heads ride tensor — sanitised like model params."""
    shape = jax.ShapeDtypeStruct(spec.pool_shape, spec.dtype)
    pspec = shd.sanitize_specs(shape, shd.paged_kv_cache_specs(), mesh)
    return shd.named(mesh, pspec)


def init_paged_cache(spec: PagedCacheSpec, mesh=None
                     ) -> Dict[str, jax.Array]:
    """Zero-initialised paged ``{"k", "v"}`` pool (trash page included),
    placed to its mesh sharding when one is given."""
    k = jnp.zeros(spec.pool_shape, spec.dtype)
    v = jnp.zeros(spec.pool_shape, spec.dtype)
    if mesh is not None:
        sh = paged_cache_shardings(spec, mesh)
        k = jax.device_put(k, sh)
        v = jax.device_put(v, sh)
    return {"k": k, "v": v}


class PageAllocatorError(RuntimeError):
    """An allocator invariant broke (refcount underflow, double free) —
    a HOST bug, raised loudly rather than silently corrupting the
    slot→page mapping the compiled programs trust."""


class PageAllocator:
    """Host-side page bookkeeping for one paged serve run.

    Owns the slot→page table (``table``, int32 ``(slots,
    max_pages_per_slot)``, -1 = unmapped) and the free list. Pages are
    REFCOUNTED: private pages hold refcount 1 (their slot); shared
    prefix pages hold one count per using slot PLUS one registry hold
    (``register_shared``) so the cached prefix survives every slot
    freeing. All methods are deterministic (free list is FIFO in page
    order) so a seeded serve run admits the same pages every run.
    """

    def __init__(self, spec: PagedCacheSpec):
        self.spec = spec
        self.free: List[int] = list(range(spec.pages))
        self.refcount = np.zeros((spec.pages,), np.int64)
        self.table = np.full(
            (spec.slots, spec.max_pages_per_slot), -1, np.int32)
        # shared prefix registry: logical page index -> page id, plus
        # how many leading POSITIONS those full pages cover
        self.shared_pages: Tuple[int, ...] = ()
        self.shared_len = 0
        # admission page ceiling: the whole pool by default; a memory
        # bound (set_memory_bound) lowers it when device HBM cannot
        # actually afford every configured page beside the params and
        # the compiled programs' scratch
        self.page_cap = spec.pages
        self.bound_source = "none"     # none | ledger | heuristic

    def set_memory_bound(self, *, hbm_bytes: float,
                         params_bytes: float = 0,
                         program_temp_bytes: Optional[int] = None
                         ) -> int:
        """Cap admissions to the pages device HBM can actually afford.

        The pool array is allocated in full either way; what this bounds
        is how many pages admission will ever MAP — so a pool configured
        past the device's real capacity backpressures at admission
        (requests wait or are structurally rejected) instead of letting
        the next allocation spike die in RESOURCE_EXHAUSTED. The margin
        reserved beside the pool is ledger-informed when a prior run's
        memory ledger measured the compiled programs' real scratch
        (``program_temp_bytes``, obs.memledger); without a ledger it
        falls back to the staging resolver's conservative
        ``STAGING_STATE_HEADROOM x params`` guess. ``bound_source``
        records which path won (the serve CLI logs it). Returns the
        resulting page cap, clamped to [0, spec.pages] — shared-prefix
        registry pages always stay admissible."""
        from tpudist.config import STAGING_STATE_HEADROOM
        if program_temp_bytes is not None and program_temp_bytes >= 0:
            margin = float(params_bytes) + float(program_temp_bytes)
            self.bound_source = "ledger"
        else:
            margin = STAGING_STATE_HEADROOM * float(params_bytes)
            self.bound_source = "heuristic"
        page_bytes = 2 * self.spec.n_layers * self.spec.page_tokens \
            * self.spec.n_kv_heads * self.spec.head_dim \
            * jnp.dtype(self.spec.dtype).itemsize
        avail = float(hbm_bytes) - margin - self.spec.table_bytes
        cap = int(avail // page_bytes) if page_bytes > 0 else 0
        cap = max(cap, len(self.shared_pages))
        self.page_cap = min(max(cap, 0), self.spec.pages)
        return self.page_cap

    # ------------------------------------------------------- internal

    def _take(self) -> Optional[int]:
        # the memory bound caps LIVE pages, not just the free list: a
        # pool configured past what HBM affords backpressures here
        if not self.free or self.pages_used() >= self.page_cap:
            return None
        pg = self.free.pop(0)
        self.refcount[pg] += 1
        return pg

    def _drop(self, pg: int) -> None:
        if self.refcount[pg] <= 0:
            raise PageAllocatorError(
                f"page {pg} refcount underflow: freed more times than "
                f"held — the slot→page bookkeeping is corrupt")
        self.refcount[pg] -= 1
        if self.refcount[pg] == 0:
            self.free.append(pg)

    # --------------------------------------------------------- shared

    def register_shared(self, prefix_len: int) -> Tuple[int, ...]:
        """Reserve the FULL pages of a ``prefix_len``-token shared
        prefix (the partial tail page is never shared — admission forks
        it into the slot's first private page by recomputation). Each
        reserved page takes a registry hold so it survives all slots
        freeing. Returns the reserved page ids, in logical order."""
        if self.shared_pages:
            raise PageAllocatorError("shared prefix already registered")
        pt = self.spec.page_tokens
        n_full = max(int(prefix_len), 0) // pt
        pages: List[int] = []
        for _ in range(n_full):
            pg = self._take()
            if pg is None:
                for p in pages:        # rollback: nothing half-shared
                    self._drop(p)
                raise PageAllocatorError(
                    f"pool of {self.spec.pages} pages cannot hold the "
                    f"{n_full}-page shared prefix")
            pages.append(pg)
        self.shared_pages = tuple(pages)
        self.shared_len = n_full * pt
        return self.shared_pages

    # ------------------------------------------------------ lifecycle

    def admit(self, slot: int, prompt_len: int,
              shared: bool = False) -> bool:
        """Map pages for one admission: shared full prefix pages (when
        ``shared``) plus private pages covering positions
        ``[shared_len, prompt_len)``. All-or-nothing — a pool too empty
        rolls back and returns False (the request stays WAITING, it is
        not shed: admission denial by page exhaustion is backpressure,
        not overload shedding)."""
        if (self.table[slot] >= 0).any():
            raise PageAllocatorError(
                f"slot {slot} admitted while still holding pages")
        pt = self.spec.page_tokens
        need = -(-int(prompt_len) // pt)            # pages [0, need)
        row = np.full((self.spec.max_pages_per_slot,), -1, np.int32)
        taken: List[int] = []
        for j in range(need):
            if shared and j < len(self.shared_pages):
                pg = self.shared_pages[j]
                self.refcount[pg] += 1              # one hold per slot
            else:
                got = self._take()
                if got is None:
                    for p in taken:
                        self._drop(p)
                    if shared:
                        for jj in range(min(j, len(self.shared_pages))):
                            self._drop(self.shared_pages[jj])
                    return False
                pg = got
                taken.append(pg)
            row[j] = pg
        self.table[slot] = row
        return True

    def admit_shared_len(self, shared: bool) -> int:
        """The prefill's ``shared_len`` traced scalar for an admission:
        positions below it are NOT written (their pages are the shared
        prefix, already holding bitwise-identical content)."""
        return self.shared_len if shared else 0

    def ensure(self, slot: int, last_pos: int) -> bool:
        """Grow a live slot's mapping to cover positions up to
        ``last_pos`` (inclusive, clamped to the cache capacity) before
        a dispatch writes them. All-or-nothing like :meth:`admit`."""
        pt = self.spec.page_tokens
        upto = min(int(last_pos), self.spec.max_seq - 1) // pt
        taken: List[Tuple[int, int]] = []
        for j in range(upto + 1):
            if self.table[slot, j] >= 0:
                continue
            pg = self._take()
            if pg is None:
                for jj, p in taken:
                    self._drop(p)
                    self.table[slot, jj] = -1
                return False
            taken.append((j, pg))
            self.table[slot, j] = pg
        return True

    def free_slot(self, slot: int) -> None:
        """Return a finished/evicted slot's pages. Shared prefix pages
        drop ONE count (the registry hold keeps them cached for the
        next admission); private pages return to the free list."""
        for j in range(self.spec.max_pages_per_slot):
            pg = int(self.table[slot, j])
            if pg >= 0:
                self._drop(pg)
            self.table[slot, j] = -1

    # ------------------------------------------------------- queries

    def row(self, slot: int) -> np.ndarray:
        return self.table[slot].copy()

    def pages_used(self) -> int:
        return self.spec.pages - len(self.free)

    def can_ever_admit(self, prompt_len: int, shared: bool) -> bool:
        """Could this admission EVER succeed, even with every slot
        freed? False means the request is structurally unservable at
        this pool size — or at the memory bound's ledger/heuristic page
        cap when one is set — (reject it: waiting forever would wedge
        the run); the shared-prefix registry holds are the only
        permanent reservation."""
        pt = self.spec.page_tokens
        need = -(-int(prompt_len) // pt)
        if shared:
            need = max(need - len(self.shared_pages), 0)
        usable = min(self.spec.pages, self.page_cap)
        return need <= usable - len(self.shared_pages)
