"""Sharded per-sequence KV cache for the serving engine.

The cache is the serving engine's whole working state: one K and one V
array of canonical shape ``(n_layers, slots, max_seq, n_kv_heads,
head_dim)``. Slots are per-SEQUENCE pages — a request is admitted into a
free slot, decodes in place, and frees the slot on completion; stale
rows beyond a slot's current length are never read (the decode mask is
``key_pos <= cur_index``), so admission never needs to zero anything.

GQA-aware by construction: the cache stores the COMPACT kv heads (the
same layout the models' ``wk``/``wv`` produce) and expansion to the
query head count happens inside the attention math — an 8×-grouped
model's cache is 8× smaller than a naive full-head cache, which is the
difference between fitting long contexts in HBM or not.

Sharding rides the existing mesh machinery: ``parallel.sharding.
kv_cache_specs`` is the ``param_specs``-style single source for the
PartitionSpec (slots over the batch axes, kv heads over tensor),
sanitised per-mesh exactly like model params.

``layout`` is a PHYSICAL storage knob the serve autotuner probes:
``"st"`` (canonical, seq-major) or ``"hs"`` (heads-major). The models'
cache API always sees canonical; :func:`to_canonical` /
:func:`from_canonical` transpose inside the compiled program, so the
layout's real cost/benefit is exactly what the probe measures.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from tpudist.config import ModelConfig
from tpudist.parallel import sharding as shd
from tpudist.parallel.sharding import KV_CACHE_LAYOUTS  # noqa: F401


@dataclasses.dataclass(frozen=True)
class CacheSpec:
    """Static shape/dtype/layout of one serving run's KV cache."""

    n_layers: int
    slots: int
    max_seq: int
    n_kv_heads: int
    head_dim: int
    dtype: Any = jnp.float32
    layout: str = "st"

    @classmethod
    def from_model(cls, cfg: ModelConfig, *, slots: int, max_seq: int,
                   dtype=jnp.float32, layout: str = "st") -> "CacheSpec":
        return cls(n_layers=cfg.n_layers, slots=slots, max_seq=max_seq,
                   n_kv_heads=cfg.n_kv_heads,
                   head_dim=cfg.d_model // cfg.n_heads,
                   dtype=dtype, layout=layout)

    @property
    def canonical_shape(self) -> tuple:
        return (self.n_layers, self.slots, self.max_seq,
                self.n_kv_heads, self.head_dim)

    @property
    def storage_shape(self) -> tuple:
        l, s, t, h, d = self.canonical_shape
        return (l, s, t, h, d) if self.layout == "st" else (l, s, h, t, d)

    @property
    def bytes(self) -> int:
        """Total cache footprint (K + V) — the number an operator sizes
        slots × max_seq against HBM with."""
        n = 1
        for d in self.canonical_shape:
            n *= d
        return 2 * n * jnp.dtype(self.dtype).itemsize


def to_canonical(arr: jax.Array, layout: str) -> jax.Array:
    """Storage layout → canonical (L, slots, seq, kv_heads, head_dim).
    A no-op for ``"st"``; ``"hs"`` transposes (the swap is its own
    inverse, so one permutation serves both directions)."""
    if layout == "st":
        return arr
    if layout == "hs":
        return jnp.transpose(arr, (0, 1, 3, 2, 4))
    raise ValueError(f"unknown kv-cache layout {layout!r}: "
                     f"{' | '.join(KV_CACHE_LAYOUTS)}")


def from_canonical(arr: jax.Array, layout: str) -> jax.Array:
    """Canonical → storage layout (see :func:`to_canonical`)."""
    return to_canonical(arr, layout)


def cache_shardings(spec: CacheSpec, mesh) -> Any:
    """NamedSharding for the K/V arrays on ``mesh``, sanitised like
    model params (a slot count the batch axes don't divide falls back
    to replicated instead of erroring)."""
    shape = jax.ShapeDtypeStruct(spec.storage_shape, spec.dtype)
    pspec = shd.sanitize_specs(
        shape, shd.kv_cache_specs(spec.layout), mesh)
    return shd.named(mesh, pspec)


def init_cache(spec: CacheSpec, mesh=None) -> Dict[str, jax.Array]:
    """Zero-initialised ``{"k", "v"}`` cache in the storage layout,
    placed to its mesh sharding when one is given. Zeros are never read
    (the length mask guards every slot), but a deterministic initial
    value keeps the whole serve run a pure function of (params, seed)."""
    k = jnp.zeros(spec.storage_shape, spec.dtype)
    v = jnp.zeros(spec.storage_shape, spec.dtype)
    if mesh is not None:
        sh = cache_shardings(spec, mesh)
        k = jax.device_put(k, sh)
        v = jax.device_put(v, sh)
    return {"k": k, "v": v}
