"""The serve resilience drill: overload + serve fault matrix, verified.

The serve-side twin of :mod:`tpudist.chaos.drill`/``verify``: a jax-free
driver runs the REAL serve CLI (``python -m tpudist.serve``) in
subprocesses on a 4-device CPU mesh under scripted overload and the
serve-surface chaos families, replaying the launcher's requeue loop for
the fatal one (serve_kill → exit code → the jax-free requeue policy →
backoff → ``--requeue-attempt 1`` rerun, with ``attempts.jsonl``
written like ``launch_tpu.sh``), and a jax-free verifier replays the
artifacts and asserts the resilience contract end to end:

  * **overload** (2x sustained capacity, virtual clock): the admitted
    traffic's p99 TTFT stays bounded by the deadline (+ one scheduler
    boundary of slack), the shed partition of ALL arrivals is exact
    (``arrived == admitted + shed + expired + rejected``), both shed
    mechanisms actually fired, and two runs of the same seed produced
    BITWISE-identical SLO summaries (the virtual clock's whole point);
  * **shed_breach**: a tightened ``TPUDIST_SERVE_SHED_MAX`` makes the
    same overload grade FAIL — the exit code goes 1 and every failed
    gate has its matching mid-run alert (``rules.SERVE_STATUS_RULES``,
    the table the report CLI's cross-check shares);
  * **serve_kill**: a hard kill at a dispatch boundary is classified
    (preemption), requeued, and the resumed attempt replays the
    still-live queued requests while classifying the dead attempt's
    in-flight slots as LOST — every rid ends in exactly one terminal
    bucket across attempts, and the restarted engine compiled exactly
    its warmup budget (1 prefill + 1 decode per ladder rung);
  * **request_garbage**: every seeded malformed request is rejected at
    admission with a named reason — the engine never crashes;
  * **serve_slow**: the per-dispatch stall is visible in the (virtual,
    deterministic) ITL percentiles and the run still completes;
  * **adapt**: sustained pressure downshifts the decode_k ladder
    (logged ``kind=serve_adapt``) with zero recompiles past warmup.

jax-free AND numpy-free by design (the launcher-host contract shared
with policy/goodput/chaos.verify); only the subprocesses need jax.
``python -m tpudist.serve.drill drill|verify`` is the CLI;
``tpudist.selfcheck check_serve_resilience`` runs the whole matrix as
an acceptance gate and ``bench.py --serve-chaos-drill`` shapes the
report into BENCH_SERVE_RESILIENCE.json.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from tpudist import rules as rules_lib
from tpudist.elastic import policy
from tpudist.obs import goodput as goodput_mod
from tpudist.serve import resilience as res_lib

RESULTS_NAME = "serve_resilience_results.json"
REPORT_NAME = "serve_resilience_report.json"
DEVICES = 4
MAX_REQUEUES = 2
BACKOFF_BASE_S = 0.2

# The drill workload: a tiny transformer on the 4-device CPU mesh,
# virtual-clock timing (prefill 2 ms, decode dispatch 4 ms) so every
# scenario's shed decisions and percentiles are a pure function of the
# seed. Measured capacity of this shape is ~250 admitted requests/s;
# the overload scenarios arrive at 500/s — sustained 2x.
ENGINE_FLAGS = ("--model", "transformer", "--vocab-size", "64",
                "--n-layers", "2", "--d-model", "32", "--n-heads", "4",
                "--n-kv-heads", "2", "--d-ff", "64",
                "--slots", "4", "--max-seq", "32", "--prompt-pad", "8",
                "--seed", "3", "--virtual-clock")
OVERLOAD_FLAGS = ENGINE_FLAGS + (
    "--requests", "80", "--request-rate", "500",
    "--max-new-tokens", "8", "--decode-steps-per-dispatch", "4",
    "--queue-cap", "16", "--ttft-deadline-ms", "40")
OVERLOAD_DEADLINE_S = 0.040
# one scheduler boundary of TTFT slack past the deadline: a request can
# clear the expiry check and still wait out the in-flight dispatch
# (4 ms) plus a slot-refill round of prefills (4 x 2 ms) before its own
# prefill lands
OVERLOAD_SLACK_S = 0.020

SCENARIOS: Dict[str, Dict[str, Any]] = {
    "overload": dict(
        flags=OVERLOAD_FLAGS, runs=2, expect_rc=0,
        bitwise=True, shed_admission=True, expired=True,
        ttft_bound_s=OVERLOAD_DEADLINE_S + OVERLOAD_SLACK_S,
        min_shed_fraction=0.2),
    "shed_breach": dict(
        flags=OVERLOAD_FLAGS, expect_rc=1,
        env={"TPUDIST_SERVE_SHED_MAX": "0.05"},
        fail_gates=("serve_shed_status",), alert_parity=True),
    "serve_kill": dict(
        flags=ENGINE_FLAGS + (
            "--requests", "24", "--request-rate", "300",
            "--max-new-tokens", "8", "--decode-steps-per-dispatch", "4",
            "--queue-cap", "40"),
        chaos="serve_kill@0:6,rc=137",
        expect_rc=137, policy="preemption", resume=True, min_lost=1),
    "request_garbage": dict(
        flags=ENGINE_FLAGS + (
            "--requests", "12", "--request-rate", "300",
            "--max-new-tokens", "6", "--decode-steps-per-dispatch", "4"),
        chaos="request_garbage@0:0,n=6",
        expect_rc=0, rejected=6, reject_reasons_min=2),
    "serve_slow": dict(
        flags=ENGINE_FLAGS + (
            "--requests", "16", "--request-rate", "300",
            "--max-new-tokens", "8", "--decode-steps-per-dispatch", "4"),
        chaos="serve_slow@0:2,s=0.02,steps=4",
        expect_rc=0, itl_inflated=True),
    "adapt": dict(
        flags=ENGINE_FLAGS + (
            "--requests", "100", "--request-rate", "600",
            "--max-new-tokens", "12",
            "--decode-steps-per-dispatch", "8", "--adapt", "on"),
        expect_rc=0, adapt_transitions=True, ladder_len=3),
}


class ServeDrillError(RuntimeError):
    """A drill attempt did not follow its script (distinct from an
    INVARIANT violation, which verify reports rather than raises)."""


def _attempt(python: str, save_dir: str, flags: Sequence[str], *,
             env_extra: Optional[Dict[str, str]] = None,
             log_name: str = "attempt.log", timeout_s: float = 600.0
             ) -> Tuple[subprocess.CompletedProcess, float, float]:
    """One serve-CLI invocation on the 4-device CPU mesh with a clean
    TPUDIST_* environment (outer chaos/live/threshold knobs must not
    leak into a drill), the live bus on exporter-less (alerts.jsonl for
    the parity checks), and load-decoupled gates: the virtual clock
    makes TTFT/ITL deterministic, so the ceilings can be TIGHT in
    virtual seconds without grading this host's load."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    keep = {"TPUDIST_PLATFORM", "TPUDIST_COMPILATION_CACHE_DIR"}
    for k in list(env):
        if k.startswith("TPUDIST_") and k not in keep:
            env.pop(k)
    env.setdefault("TPUDIST_PLATFORM", "cpu")
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={DEVICES}"
    env["TPUDIST_LIVE"] = "on"
    env["TPUDIST_TTFT_P99_MAX"] = "0.5"
    env["TPUDIST_ITL_P99_MAX"] = "0.1"
    env["TPUDIST_TOKENS_PER_CHIP_MIN"] = "0.001"
    env.update(env_extra or {})
    start = time.time()
    proc = subprocess.run(
        [python, "-m", "tpudist.serve", "--save-dir", save_dir, *flags],
        env=env, capture_output=True, text=True, timeout=timeout_s)
    end = time.time()
    try:
        with open(os.path.join(save_dir, log_name), "w") as f:
            f.write(proc.stdout)
            if proc.stderr:
                f.write("\n--- stderr ---\n" + proc.stderr)
    except OSError:
        pass
    return proc, start, end


def _tail(proc: subprocess.CompletedProcess, n: int = 30) -> str:
    lines = (proc.stdout + "\n" + proc.stderr).splitlines()
    return "\n".join(lines[-n:])


def run_scenario(run_dir: str, name: str, *,
                 python: Optional[str] = None) -> Dict[str, Any]:
    """One scenario's scripted drill. Fatal scenarios (expect_rc != 0
    with a ``policy`` expectation) replay the launcher's loop: fault →
    jax-free policy classification → backoff → ``--requeue-attempt 1``
    rerun, with attempts.jsonl stamped around every invocation."""
    cfg = SCENARIOS[name]
    python = python or sys.executable
    out: Dict[str, Any] = {"scenario": name, "dir": name,
                           "expect": {k: v for k, v in cfg.items()
                                      if k not in ("flags", "env")},
                           "rcs": [], "dirs": []}
    runs = int(cfg.get("runs", 1))
    env_extra = dict(cfg.get("env") or {})
    if cfg.get("chaos"):
        env_extra["TPUDIST_CHAOS"] = cfg["chaos"]
        out["chaos"] = cfg["chaos"]
    for r in range(runs):
        d = os.path.join(run_dir, name if runs == 1 else f"{name}{r}")
        shutil.rmtree(d, ignore_errors=True)
        os.makedirs(d, exist_ok=True)
        out["dirs"].append(os.path.basename(d))
        run_id = f"serve-drill-{name}"
        attempts_path = os.path.join(d, goodput_mod.ATTEMPTS_NAME)
        env_extra["TPUDIST_RUN_ID"] = run_id
        p0, s0, e0 = _attempt(python, d, cfg["flags"],
                              env_extra=env_extra,
                              log_name="attempt0.log")
        out["rcs"].append(p0.returncode)
        if p0.returncode != cfg["expect_rc"]:
            raise ServeDrillError(
                f"{name}: attempt 0 exited {p0.returncode}, the script "
                f"expected {cfg['expect_rc']}:\n{_tail(p0)}")
        if "policy" not in cfg:
            goodput_mod.append_attempt(
                attempts_path, attempt=0, start_ts=s0, end_ts=e0,
                rc=p0.returncode,
                verdict="success" if p0.returncode == 0 else "crash",
                run_id=run_id, mode="serve")
            continue
        # the launcher's requeue-or-stop call, verbatim (rc + this
        # attempt's collected evidence — the serve lane classifies from
        # the exit code alone, there are no beacons to consult)
        decision = policy.decide(p0.returncode, attempt=0,
                                 max_requeues=MAX_REQUEUES,
                                 flightrec_dir=d, base_s=BACKOFF_BASE_S)
        out["policy"] = {"verdict": decision.verdict,
                         "requeue": decision.requeue,
                         "backoff_s": decision.backoff_s,
                         "reason": decision.reason}
        goodput_mod.append_attempt(
            attempts_path, attempt=0, start_ts=s0, end_ts=e0,
            rc=p0.returncode, verdict=decision.verdict, run_id=run_id,
            mode="serve")
        if not decision.requeue:
            raise ServeDrillError(
                f"{name}: policy refused to requeue — "
                f"{decision.shell_line()}")
        time.sleep(decision.backoff_s)       # the measured off-pod gap
        env1 = {k: v for k, v in env_extra.items()
                if k != "TPUDIST_CHAOS"}
        p1, s1, e1 = _attempt(python, d,
                              (*cfg["flags"], "--requeue-attempt", "1"),
                              env_extra=env1, log_name="attempt1.log")
        out["rcs"].append(p1.returncode)
        goodput_mod.append_attempt(
            attempts_path, attempt=1, start_ts=s1, end_ts=e1,
            rc=p1.returncode,
            verdict="success" if p1.returncode == 0 else "crash",
            run_id=run_id, mode="serve")
        if p1.returncode != 0:
            raise ServeDrillError(
                f"{name}: resume attempt exited {p1.returncode}:\n"
                f"{_tail(p1)}")
    return out


def run_matrix(run_dir: str, *, python: Optional[str] = None,
               scenarios: Optional[Sequence[str]] = None
               ) -> Dict[str, Any]:
    """The whole matrix; results persisted as
    ``serve_resilience_results.json`` so verify can replay offline."""
    os.makedirs(run_dir, exist_ok=True)
    python = python or sys.executable
    results: Dict[str, Any] = {"schema": 1, "scenarios": {}}
    for name in (scenarios or SCENARIOS):
        results["scenarios"][name] = run_scenario(run_dir, name,
                                                  python=python)
        print(f"tpudist: serve drill {name}: scripted outcome held "
              f"(rcs {results['scenarios'][name]['rcs']})", flush=True)
    path = os.path.join(run_dir, RESULTS_NAME)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(results, f, indent=1)
    os.replace(tmp, path)
    return results


# ------------------------------------------------------------- verifier


def _load_jsonl(path: str) -> List[Dict[str, Any]]:
    return goodput_mod.load_jsonl(path) if os.path.exists(path) else []


def _serve_summaries(recs: List[Dict[str, Any]]
                     ) -> List[Dict[str, Any]]:
    return [r for r in recs if r.get("kind") == "serve"]


_VOLATILE = ("ts", "mono")     # wall-clock stamps: the ONLY fields a
#                                virtual-clock rerun may legitimately vary


def _canonical_summary(rec: Dict[str, Any]) -> Dict[str, Any]:
    return {k: v for k, v in rec.items() if k not in _VOLATILE}


def _terminal_events(recs: List[Dict[str, Any]]
                     ) -> Dict[int, List[str]]:
    out: Dict[int, List[str]] = {}
    for r in recs:
        if r.get("kind") != "serve_request":
            continue
        if r.get("event") in res_lib.TERMINAL_EVENTS:
            out.setdefault(int(r["rid"]), []).append(r["event"])
    return out


def verify_scenario(run_dir: str, result: Dict[str, Any]
                    ) -> Dict[str, Any]:
    """One scenario's invariants against its artifacts. Returns
    ``{"ok", "problems", "facts"}`` — problems name exactly which leg
    of the resilience contract broke."""
    name = result["scenario"]
    expect = result.get("expect", {})
    dirs = [os.path.join(run_dir, d) for d in result.get("dirs", [name])]
    problems: List[str] = []
    facts: Dict[str, Any] = {"rcs": result.get("rcs")}

    recs_per_dir = [_load_jsonl(os.path.join(d, "metrics.jsonl"))
                    for d in dirs]
    if not any(recs_per_dir):
        problems.append("no metrics.jsonl survived the drill")
        return {"ok": False, "problems": problems, "facts": facts}
    recs = recs_per_dir[0]
    summaries = _serve_summaries(recs)
    summ = summaries[-1] if summaries else {}

    # -- scheduled chaos fired (flushed kind=chaos evidence)
    if result.get("chaos"):
        from tpudist.chaos import plan as plan_mod
        want = {e.kind for e in
                plan_mod.ChaosPlan.parse(result["chaos"]).events}
        fired = {r.get("fault") for r in recs if r.get("kind") == "chaos"}
        if want - fired:
            problems.append(f"scheduled fault(s) never fired: "
                            f"{sorted(want - fired)}")
        facts["fired"] = sorted(k for k in fired if k)

    # -- exact shed partition, recomputed two ways: the summary's own
    # checked ledger AND the replayed per-request event stream
    part = summ.get("partition") or {}
    facts["partition"] = {k: part.get(k) for k in
                          ("arrived", "admitted", "shed_at_admission",
                           "expired_in_queue", "rejected", "completed",
                           "evicted", "lost", "shed_fraction")}
    if not summaries:
        problems.append("no kind=serve summary record")
    else:
        if not (part.get("admission_exact")
                and part.get("outcome_exact")):
            problems.append(f"shed partition INEXACT: {part}")
        n_arrived = part.get("arrived") or 0
        n_events = sum(
            1 for r in recs if r.get("kind") == "serve_request"
            and r.get("event") in (res_lib.SHED, res_lib.EXPIRED,
                                   res_lib.REJECTED, res_lib.DONE,
                                   res_lib.EVICTED))
        if result.get("rcs", [None])[0] == 0 and "policy" not in expect \
                and n_events != n_arrived:
            problems.append(
                f"event stream accounts {n_events} arrivals, the "
                f"ledger says {n_arrived} — the two books diverged")

    # -- overload: bounded admitted-traffic TTFT, both shed mechanisms,
    # bitwise determinism across the same-seed rerun
    if expect.get("ttft_bound_s") is not None and summaries:
        facts["ttft_p99_s"] = summ.get("ttft_p99_s")
        if not summ.get("ttft_p99_s") \
                or summ["ttft_p99_s"] > expect["ttft_bound_s"]:
            problems.append(
                f"admitted-traffic p99 TTFT {summ.get('ttft_p99_s')}s "
                f"exceeded the deadline bound "
                f"{expect['ttft_bound_s']}s under 2x overload — "
                f"admission control failed its one job")
        if summ.get("ttft_status") != "success":
            problems.append(f"ttft gate graded "
                            f"{summ.get('ttft_status')!r} on the "
                            f"admitted traffic")
    if expect.get("shed_admission") and not (summ.get(
            "shed_at_admission") or 0) > 0:
        problems.append("the bounded queue never shed at admission")
    if expect.get("expired") and not (summ.get(
            "expired_in_queue") or 0) > 0:
        problems.append("no queued request expired past its deadline")
    if expect.get("min_shed_fraction") is not None:
        sf = summ.get("shed_fraction") or 0.0
        facts["shed_fraction"] = sf
        if sf < expect["min_shed_fraction"]:
            problems.append(
                f"shed fraction {sf} under {expect['min_shed_fraction']}"
                f" — the scripted 2x overload never materialised")
    if expect.get("bitwise") and len(dirs) > 1:
        canon = []
        for rs in recs_per_dir:
            ss = _serve_summaries(rs)
            canon.append(_canonical_summary(ss[-1]) if ss else None)
        if any(c is None for c in canon):
            problems.append("a rerun left no kind=serve summary")
        elif any(c != canon[0] for c in canon[1:]):
            diff = [k for k in canon[0]
                    if any(c.get(k) != canon[0][k] for c in canon[1:])]
            problems.append(
                f"same-seed virtual-clock reruns were NOT bitwise "
                f"identical (diverging keys: {diff})")
        else:
            facts["bitwise_identical_runs"] = len(canon)

    # -- SLO-fail ↔ mid-run-alert parity (rules.SERVE_STATUS_RULES —
    # the same table the report CLI's cross-check reads)
    alerts = _load_jsonl(os.path.join(dirs[0], "alerts.jsonl"))
    fired_rules = {a.get("alert") for a in alerts}
    facts["alert_rules"] = sorted(r for r in fired_rules if r)
    for status_key, rule in rules_lib.SERVE_STATUS_RULES:
        if summ.get(status_key) == "fail" and rule not in fired_rules:
            problems.append(f"at-exit {status_key}=fail had no mid-run "
                            f"{rule!r} alert")
    for gate in expect.get("fail_gates", ()):
        if summ.get(gate) != "fail":
            problems.append(f"expected {gate}=fail, got "
                            f"{summ.get(gate)!r}")
        facts[gate] = summ.get(gate)

    # -- serve_kill: classification, requeue, honest lost accounting,
    # every rid terminal exactly once ACROSS attempts, engine restart
    # within its compiled-program budget
    if "policy" in expect:
        got = (result.get("policy") or {}).get("verdict")
        facts["policy"] = got
        if got != expect["policy"]:
            problems.append(f"policy classified the fault as {got!r}, "
                            f"expected {expect['policy']!r}")
        if not (result.get("policy") or {}).get("requeue"):
            problems.append("policy did not requeue a recoverable "
                            "serve fault")
        resumes = [r for r in recs if r.get("kind") == "serve_resume"]
        res = resumes[-1] if resumes else None
        if res is None:
            problems.append("no kind=serve_resume record from the "
                            "requeued attempt")
        else:
            facts["resume"] = {k: res.get(k) for k in
                               ("completed_prior", "lost", "replayed")}
            if (res.get("lost") or 0) < expect.get("min_lost", 1):
                problems.append(
                    f"resume classified {res.get('lost')} in-flight "
                    f"slot(s) as lost, expected >= "
                    f"{expect.get('min_lost', 1)}")
            if summ.get("completed") != res.get("replayed"):
                problems.append(
                    f"resumed attempt completed {summ.get('completed')}"
                    f" of its {res.get('replayed')} replayed requests")
        term = _terminal_events(recs)
        doubles = {r: evs for r, evs in term.items() if len(evs) > 1}
        if doubles:
            problems.append(f"rid(s) with more than one terminal "
                            f"outcome across attempts: {doubles}")
        total = summ.get("requests", 0) + (res or {}).get(
            "completed_prior", 0) + (res or {}).get("lost", 0)
        if total and len(term) != total:
            problems.append(
                f"{len(term)} rid(s) ended terminal across attempts, "
                f"expected every one of {total}")
        facts["terminal_rids"] = len(term)
        if summaries and (summ.get("prefill_compiles"),
                          summ.get("decode_compiles")) != (
                1, len(summ.get("decode_k_ladder") or [1])):
            problems.append(
                f"restarted engine compiled "
                f"{summ.get('prefill_compiles')} prefill / "
                f"{summ.get('decode_compiles')} decode program(s) — "
                f"past its warmup budget")
        attempts = _load_jsonl(os.path.join(
            dirs[0], goodput_mod.ATTEMPTS_NAME))
        facts["attempts"] = [(a.get("attempt"), a.get("rc"),
                              a.get("verdict")) for a in attempts]
        if [a.get("verdict") for a in attempts] != \
                [expect["policy"], "success"]:
            problems.append(f"attempts.jsonl verdicts "
                            f"{facts['attempts']} != "
                            f"[{expect['policy']}, success]")

    # -- request_garbage: every malformed request rejected, with seeded
    # variety in the reasons; the engine survived (rc 0, all valid
    # requests completed)
    if "rejected" in expect:
        rej = [r for r in recs if r.get("kind") == "serve_request"
               and r.get("event") == res_lib.REJECTED]
        reasons = {r.get("reason") for r in rej}
        facts["rejected"] = {"n": len(rej),
                             "reasons": sorted(r for r in reasons if r)}
        if len(rej) != expect["rejected"]:
            problems.append(f"{len(rej)} garbage request(s) rejected, "
                            f"expected {expect['rejected']}")
        if len(reasons) < expect.get("reject_reasons_min", 1):
            problems.append(f"rejection reasons {sorted(reasons)} show "
                            f"no seeded variety")
        if summaries and summ.get("completed") != (
                summ.get("requests", 0) - expect["rejected"]):
            problems.append(
                f"completed {summ.get('completed')} != the "
                f"{summ.get('requests', 0) - expect['rejected']} "
                f"well-formed requests — garbage cost the engine more "
                f"than its own rejection")

    # -- serve_slow: the stall is visible in the deterministic ITL
    if expect.get("itl_inflated") and summaries:
        facts["itl_p99_s"] = summ.get("itl_p99_s")
        # the un-stalled virtual per-token cost is decode_s / k = 1 ms;
        # four stalled dispatches must push the p99 above it
        if not summ.get("itl_p99_s") or summ["itl_p99_s"] <= 0.001:
            problems.append(
                f"serve_slow stall invisible in itl_p99 "
                f"{summ.get('itl_p99_s')} (expected > the 0.001s "
                f"un-stalled virtual per-token cost)")
        if summ.get("completed") != summ.get("requests"):
            problems.append("a straggler stall must not cost "
                            "completions")

    # -- adapt: the ladder moved under pressure, without a recompile
    if expect.get("adapt_transitions"):
        trans = [r for r in recs if r.get("kind") == "serve_adapt"]
        facts["adapt_transitions"] = [
            (r.get("from_level"), r.get("to_level"), r.get("decode_k"))
            for r in trans]
        if not any(r.get("to_level", 0) > r.get("from_level", 0)
                   for r in trans):
            problems.append("sustained pressure produced no downshift "
                            "transition")
        ladder = summ.get("decode_k_ladder") or []
        if len(ladder) != expect.get("ladder_len", len(ladder)):
            problems.append(f"ladder {ladder} has "
                            f"{len(ladder)} rung(s), expected "
                            f"{expect.get('ladder_len')}")
        if (summ.get("prefill_compiles"),
                summ.get("decode_compiles")) != (1, len(ladder)):
            problems.append(
                f"adapt run compiled {summ.get('prefill_compiles')} "
                f"prefill / {summ.get('decode_compiles')} decode "
                f"program(s), expected (1, {len(ladder)}) — a "
                f"downshift paid a recompile")
        if summ.get("completed") != summ.get("requests"):
            problems.append("degraded service must still complete the "
                            "(uncapped) stream")

    return {"ok": not problems, "problems": problems, "facts": facts}


def verify_matrix(run_dir: str,
                  results: Optional[Dict[str, Any]] = None
                  ) -> Dict[str, Any]:
    """Verify every scenario of a drill run; write
    ``serve_resilience_report.json`` next to the artifacts (the CI
    lane's uploaded acceptance record)."""
    if results is None:
        path = os.path.join(run_dir, RESULTS_NAME)
        try:
            with open(path) as f:
                results = json.load(f)
        except (OSError, ValueError):
            raise FileNotFoundError(
                f"no {RESULTS_NAME} under {run_dir} — run the drill "
                f"first (python -m tpudist.serve.drill drill)")
    scenarios = {name: verify_scenario(run_dir, res)
                 for name, res in results.get("scenarios", {}).items()}
    report = {
        "schema": 1,
        "ok": all(s["ok"] for s in scenarios.values())
        and bool(scenarios),
        "scenarios": scenarios,
    }
    path = os.path.join(run_dir, REPORT_NAME)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=1)
    os.replace(tmp, path)
    return report


def bench_artifact(report: Dict[str, Any]) -> Dict[str, Any]:
    """BENCH_SERVE_RESILIENCE.json on the shared BENCH_* harness shape:
    headline = resilience scenarios ending green, detail = the full
    report. The ONE shaper behind ``python -m tpudist.serve.drill``,
    ``bench.py --serve-chaos-drill`` and the CI lane."""
    sc = report.get("scenarios", {})
    return {
        "metric": "serve_resilience_scenarios_green",
        "value": sum(1 for s in sc.values() if s.get("ok")),
        "unit": f"resilience scenarios ending green of {len(sc)} "
                f"drilled",
        "detail": report,
    }


def run_and_verify(run_dir: Optional[str] = None, *,
                   scenarios=None) -> Dict[str, Any]:
    """The whole acceptance sequence in one call — drill the matrix,
    replay the invariants, persist the report — shared by the CLI,
    ``bench.py --serve-chaos-drill`` and ``selfcheck
    check_serve_resilience``. ``run_dir`` defaults to
    ``$TPUDIST_SERVE_DRILL_DIR`` (CI uploads it), else a temp dir."""
    import tempfile

    if run_dir is None:
        run_dir = os.environ.get("TPUDIST_SERVE_DRILL_DIR") \
            or tempfile.mkdtemp(prefix="tpudist_serve_drill_")
    results = run_matrix(run_dir, scenarios=scenarios)
    report = verify_matrix(run_dir, results)
    report["run_dir"] = run_dir
    return report


def _summarise(report: Dict[str, Any]) -> None:
    for name, sc in sorted(report.get("scenarios", {}).items()):
        status = "green" if sc.get("ok") else "RED"
        print(f"tpudist: serve drill {name}: {status}"
              + ("" if sc.get("ok")
                 else " — " + "; ".join(sc.get("problems", []))))
    print(f"tpudist: serve resilience matrix "
          f"{'green' if report.get('ok') else 'RED'} "
          f"({len(report.get('scenarios', {}))} scenarios)")


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        prog="python -m tpudist.serve.drill",
        description="serve resilience drills (overload + serve fault "
                    "matrix) + the invariant checker (jax-free driver)")
    sub = p.add_subparsers(dest="cmd", required=True)
    d = sub.add_parser("drill", help="run the matrix then verify")
    d.add_argument("--run-dir", type=str, required=True)
    d.add_argument("--scenario", action="append", default=None,
                   choices=sorted(SCENARIOS),
                   help="drill only these scenarios (repeatable; "
                        "default: all)")
    d.add_argument("--bench-out", type=str, default=None,
                   help="also write BENCH_SERVE_RESILIENCE.json")
    v = sub.add_parser("verify", help="re-check an existing drill dir")
    v.add_argument("--run-dir", type=str, required=True)
    args = p.parse_args(argv)

    if args.cmd == "drill":
        report = run_and_verify(args.run_dir, scenarios=args.scenario)
        if args.bench_out:
            tmp = f"{args.bench_out}.tmp"
            os.makedirs(os.path.dirname(args.bench_out) or ".",
                        exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(bench_artifact(report), f, indent=1)
            os.replace(tmp, args.bench_out)
    else:
        try:
            report = verify_matrix(args.run_dir)
        except FileNotFoundError as e:
            print(f"tpudist.serve.drill: {e}", file=sys.stderr)
            return 2
    _summarise(report)
    return 0 if report.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
