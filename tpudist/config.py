"""Configuration system for the workload.

The reference scattered configuration over four ad-hoc surfaces with broken
precedence (three conflicting batch sizes — reference ``train.py:44,74,79``,
SURVEY.md §2.7). Here there is exactly ONE config object with explicit
precedence: defaults < CLI flags. The CLI remains tolerant of unknown flags
for parity with the reference's ``parse_known_args`` contract
(reference ``train.py:49``).
"""

from __future__ import annotations

import argparse
import dataclasses
import os
from dataclasses import dataclass
from typing import Any, Optional, Sequence

from tpudist import rules as rules_lib


# context-parallel attention implementations (single source of truth;
# tpudist.models.transformer imports this for its validation/errors)
CP_IMPLS = ("ring", "ulysses")


@dataclass(frozen=True)
class DataConfig:
    """Synthetic dataset shape (parity: reference ``train.py:19-24,63``)."""

    n_samples: int = 2000
    n_features: int = 20
    seed: int = 42


@dataclass(frozen=True)
class ModelConfig:
    """Model selection. ``mlp`` is the parity model (reference
    ``train.py:26-36``); ``transformer`` is the north-star synthetic
    Llama-block model (BASELINE.json config #5)."""

    name: str = "mlp"
    n_features: int = 20
    hidden: int = 64
    # transformer-only fields
    vocab_size: int = 32000
    n_layers: int = 4
    d_model: int = 2048
    n_heads: int = 16
    n_kv_heads: int = 16
    d_ff: int = 5504
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    # moe-only fields (model name "moe": transformer blocks with a
    # mixture-of-experts FFN, experts sharded over the mesh's expert axis)
    n_experts: int = 8
    expert_top_k: int = 2
    capacity_factor: float = 1.25
    router_aux_weight: float = 0.01
    moe_group_size: int = 4096    # routing group (bounds dispatch memory)


@dataclass(frozen=True)
class ParallelConfig:
    """Mesh axis sizes. ``-1`` on the data axis means "all remaining
    devices". A size of 1 disables that axis (it still exists in the mesh so
    shardings are uniform across configurations)."""

    data: int = -1
    pipe: int = 1
    fsdp: int = 1
    expert: int = 1
    tensor: int = 1
    context: int = 1


@dataclass(frozen=True)
class TrainConfig:
    """Top-level workload config (parity: reference ``train.py:42-49`` flags
    plus the ds_config dict at ``train.py:78-83``, unified)."""

    batch_size: int = 64          # GLOBAL batch size (one source of truth)
    epochs: int = 5
    lr: float = 1e-3
    seed: int = 42
    save_dir: str = "ckpt"
    resume: Any = False           # False = off; True/"latest" = resume and
    # RAISE if the newest checkpoint cannot drive this run; "auto" = the
    # launcher-requeue mode — resume when a committed checkpoint exists,
    # fall back to a fresh start (flagged resume_status=fail) when the
    # restore errors, never crash-loop (resolve_resume)
    ckpt_every_steps: int = 0     # also save mid-epoch every N steps (0=off)
    ckpt_sync: bool = False       # disable async checkpointing (debugging)
    ckpt_mode: Optional[str] = None  # orbax | sharded (elastic/ckpt.py:
    # per-worker shard files + atomically committed manifest — the
    # reshardable layout elastic resume consumes). None =
    # $TPUDIST_CKPT_MODE, else orbax (resolve_ckpt_mode)
    requeue_attempt: int = 0      # which auto-requeue rerun this is (the
    # launcher passes it; 0 = first attempt / not requeued). Rides into
    # the kind=resume record / resume_status line
    # ($TPUDIST_REQUEUE_ATTEMPT when 0)
    grad_accum_steps: int = 1
    dtype: str = "float32"        # compute dtype: float32 | bfloat16
    adam_nu_dtype: str = "float32"  # Adam second-moment storage dtype
    # (bfloat16 = opt-in HBM saving for big optimizer states, engine.py)
    remat: bool = False           # checkpoint transformer layers
    xent_chunks: int = 0          # stream LM head+loss over N seq chunks
    fused_xent: bool = False      # pallas fused LM head+loss (no HBM logits)
    lm_head: str = "auto"         # auto | plain | chunked | fused — auto
    # defers to fused_xent/xent_chunks when set, else picks by the memory
    # policy (models.transformer.pick_lm_head)
    pp_microbatches: int = 0      # pipeline microbatches (0 = pipe size)
    pipeline_interleave: int = 0  # virtual stages per pipeline device
    # (parallel.pipeline interleaved schedule): v>1 cuts the bubble from
    # (S-1)/(M+S-1) to (S-1)/(v*M+S-1) by giving each device v
    # round-robin layer chunks. 0 = $TPUDIST_PIPELINE_INTERLEAVE, else 1
    # (the GPipe parity oracle)
    cp_impl: str = "ring"         # context parallelism: ring | ulysses
    grad_overlap: Optional[str] = None  # off | bucketed — DP gradient
    # all-reduce schedule (parallel.overlap): off pins the trailing-
    # barrier baseline (reduce after the whole backward), bucketed
    # splits the reduce into size-bounded buckets dispatched as the
    # backward produces each bucket's grads, hidden behind the
    # remaining backward compute (the multi-slice DCN recipe). None =
    # $TPUDIST_GRAD_OVERLAP, else off. Bitwise-identical loss either
    # way; only the schedule (and the exposed-comm fraction) moves
    grad_bucket_mb: Optional[float] = None  # bucket size bound in MB for
    # --grad-overlap bucketed. None = $TPUDIST_GRAD_BUCKET_MB, else 4
    cross_slice: Optional[str] = None  # flat | hierarchical — how the DP
    # gradient reduce crosses slice boundaries (parallel.overlap): flat
    # moves the FULL gradient bytes over DCN (in-slice reduce, then
    # cross-slice reduce on the whole vector), hierarchical
    # reduce-scatters in-slice over ICI, all-reduces the 1/slice_size
    # shard over DCN, all-gathers in-slice — DCN bytes drop by the
    # slice size. Bitwise-identical loss either way (both modes pin the
    # same slice-structured association); single-slice meshes downgrade
    # hierarchical to flat with a logged notice. None =
    # $TPUDIST_CROSS_SLICE, else flat
    fail_at: Optional[int] = None  # fault injection: exit(1) after this epoch
    chaos: Optional[str] = None   # scripted fault-injection plan
    # (tpudist.chaos): ";"-separated <fault>@<epoch>:<step>[:<rank>]
    # [,k=v...] events — kill | hang | slow | corrupt_shard |
    # torn_manifest | fs_error | telemetry_garbage. None =
    # $TPUDIST_CHAOS, else off (resolve_chaos). Deterministic by
    # construction: the same spec replays the same faults
    log_every: int = 100
    profile_dir: Optional[str] = None  # write jax.profiler traces here
    profile_window: int = 0       # capture N mid-run supersteps with
    # jax.profiler into <trace-dir>/profile/worker<i> and ingest the
    # device timeline at run end (obs.devtime: kind=devtime record,
    # device tracks in pod_trace.json, comm_status). 0 = off
    # ($TPUDIST_PROFILE_WINDOW). Unlike --profile-dir this is cheap,
    # keeps superstep dispatch, and composes with --autotune probe
    steps_per_dispatch: int = 0   # superstep length k: one compiled
    # lax.scan dispatch covers k train steps (engine.make_superstep).
    # 0 = auto (resolve_steps_per_dispatch); 1 = per-step dispatch.
    compilation_cache_dir: Optional[str] = None  # persistent XLA
    # compilation cache (also via TPUDIST_COMPILATION_CACHE_DIR); repeat
    # runs skip recompiles entirely
    staging_budget_mb: Optional[float] = None  # per-device MB of batch
    # staging memory (sharding.plan_slabs). None = $TPUDIST_STAGING_BUDGET_MB,
    # else auto from device memory stats minus the train-state estimate
    # (resolve_staging_budget_bytes); epochs over budget stream in
    # double-buffered slabs instead of staging whole
    stall_timeout_s: Optional[float] = None  # flight-recorder watchdog: no
    # step progress for this long -> dump stacks/memory/last-metrics to
    # flightrec.worker<i> (obs.heartbeat). None = $TPUDIST_STALL_TIMEOUT_S,
    # else 300; 0 disables the watchdog (the heartbeat beacon still beats)
    heartbeat_dir: Optional[str] = None  # where heartbeat.worker<i> /
    # flightrec.worker<i> land. None = $TPUDIST_HEARTBEAT_DIR, else save_dir
    hbm_sample_s: Optional[float] = None  # HBM watermark sampler period
    # (obs.hbm). None = $TPUDIST_HBM_SAMPLE_S, else 2.0; 0 disables
    autotune: Optional[str] = None  # off | probe | cache-only
    # (tpudist.tune): measure the dispatch/staging/remat operating point
    # with short on-device trials before the timed run, or reuse a
    # cached measurement. None = $TPUDIST_AUTOTUNE, else off.
    autotune_cache_dir: Optional[str] = None  # tuning-cache directory.
    # None = $TPUDIST_AUTOTUNE_CACHE_DIR, else <save_dir>/tune
    autotune_trials: int = 0      # probe-trial budget; 0 = auto
    # ($TPUDIST_AUTOTUNE_TRIALS, else 12)
    trace: Optional[str] = None   # on | off — host-side span tracing
    # (obs.trace): ALWAYS ON by default; None = $TPUDIST_TRACE, else on.
    # Run end exports trace.worker<i>.json per process and a merged
    # pod_trace.json on the coordinator (one Perfetto track per host)
    trace_dir: Optional[str] = None  # where trace artifacts land.
    # None = $TPUDIST_TRACE_DIR, else save_dir (next to metrics.jsonl)
    live: Optional[str] = None    # on | off — live telemetry bus
    # (obs.live): per-worker non-blocking emitters stream records +
    # heartbeats to a coordinator aggregator that keeps rolling
    # windows, runs the on-line alert engine over the SAME thresholds
    # the exit verdict applies (tpudist.rules), rewrites
    # live_status.json, and serves Prometheus /metrics.
    # None = $TPUDIST_LIVE, else off (resolve_live)
    live_port: int = 0            # Prometheus exporter port on the
    # coordinator (/metrics, /status.json, /healthz). 0 =
    # $TPUDIST_LIVE_PORT, else an ephemeral port
    live_endpoint: Optional[str] = None  # ingest endpoint workers ship
    # records to ([tcp://|udp://]host:port). None =
    # $TPUDIST_LIVE_ENDPOINT, else the coordinator binds loopback on an
    # ephemeral port (single-host runs); the launcher passes the
    # coordinator's reachable address on pods
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    parallel: ParallelConfig = dataclasses.field(default_factory=ParallelConfig)


# auto superstep cap: past ~32 steps per dispatch the per-dispatch
# overhead is already amortised to noise and longer scans only delay
# log/fence boundaries (bench.py --dispatch-sweep measures the curve)
SUPERSTEP_CAP = 32


def resolve_steps_per_dispatch(cfg: TrainConfig) -> int:
    """Resolve/validate ``--steps-per-dispatch`` to the concrete superstep
    length ``k`` for this run.

    The train loop only fences and logs at superstep edges, so ``k`` must
    divide ``--log-every`` and ``--ckpt-every-steps`` (when enabled) —
    boundaries then land exactly on superstep edges and the logged
    loss/step stream is indistinguishable from per-step dispatch. An
    explicit ``k`` violating that is a config error, as is ``k > 1``
    combined with ``--fail-at`` (fault-injection timing is defined in
    per-step terms; a k-step scan would glide past the injection point).

    Auto (``0``) picks 1 under ``--log-every 1``, profiling, or fault
    injection (each wants true per-step dispatch), else the largest
    divisor of the log/ckpt intervals ≤ :data:`SUPERSTEP_CAP`. The
    epoch's trailing partial superstep is NOT a config concern: it is
    zero-padded to ``k`` with the pad steps masked out of the loss and
    state updates, so ONE compiled program serves the whole run
    (engine.make_superstep).
    """
    k = cfg.steps_per_dispatch
    if k < 0:
        raise ValueError(
            f"--steps-per-dispatch must be >= 1 (or 0 = auto), got {k}")
    if k == 0:
        if cfg.profile_dir or cfg.fail_at is not None or cfg.log_every == 1:
            return 1
        cap = SUPERSTEP_CAP if cfg.log_every <= 0 else min(cfg.log_every,
                                                           SUPERSTEP_CAP)
        best = 1
        for d in range(1, cap + 1):
            if cfg.log_every > 0 and cfg.log_every % d:
                continue
            if cfg.ckpt_every_steps and cfg.ckpt_every_steps % d:
                continue
            best = d
        return best
    if k > 1:
        if cfg.fail_at is not None:
            raise ValueError(
                f"--steps-per-dispatch {k} with --fail-at: fault injection "
                f"must observe per-step/epoch boundaries; use "
                f"--steps-per-dispatch 1")
        if cfg.log_every > 0 and cfg.log_every % k:
            raise ValueError(
                f"--steps-per-dispatch {k} must divide --log-every "
                f"{cfg.log_every} so logging boundaries land on superstep "
                f"edges")
        if cfg.ckpt_every_steps and cfg.ckpt_every_steps % k:
            raise ValueError(
                f"--steps-per-dispatch {k} must divide --ckpt-every-steps "
                f"{cfg.ckpt_every_steps} so checkpoint boundaries land on "
                f"superstep edges")
    return k


# Auto staging budget: leave the train state (params + opt moments) plus
# this multiple of it for grads / activations / XLA workspace, then stage
# batches into half of what remains (the other half is slack for the
# allocator — device memory stats are an estimate, not a reservation).
# The floor keeps the budget positive when the conservative 4x estimate
# exceeds the device estimate: a zero budget would make plan_slabs
# reject EVERY epoch, failing runs that used to stage fine.
STAGING_STATE_HEADROOM = 4.0
STAGING_FREE_FRACTION = 0.5
STAGING_FLOOR_FRACTION = 0.05


def resolve_staging_budget_bytes(cfg: TrainConfig, *, state_bytes: int = 0,
                                 hbm_bytes: Optional[float] = None,
                                 program_temp_bytes: Optional[int] = None
                                 ) -> Optional[int]:
    """Resolve ``--staging-budget-mb`` to a per-device byte budget for
    epoch staging (``sharding.plan_slabs``), or ``None`` for "unbounded"
    (always the full-epoch fast path).

    Precedence: explicit flag > ``TPUDIST_STAGING_BUDGET_MB`` > auto.
    Auto derives from the device's reported memory minus the train
    state and its working margin — ledger-informed when a prior run's
    memory ledger measured the compiled programs' real scratch
    (``program_temp_bytes``, obs.memledger): the margin is then
    ``state + measured temp`` instead of the conservative
    ``STAGING_STATE_HEADROOM x state`` guess (the 4x heuristic stays
    the fallback; the train loop logs which path won). The budget only
    moves slab CUT points, which the superstep's lo/hi masking keeps
    loss-invariant — so a ledger-informed budget is bitwise
    loss-neutral by construction (pinned in tests). On backends that
    report no limit (CPU tests) the 16 GB default makes small epochs
    take the fast path, which is exactly the seed behavior.
    """
    mb = cfg.staging_budget_mb
    if mb is None:
        env = os.environ.get("TPUDIST_STAGING_BUDGET_MB")
        if env:
            mb = float(env)
    if mb is not None:
        if mb <= 0:
            raise ValueError(
                f"--staging-budget-mb must be > 0, got {mb}")
        return int(mb * 2**20)
    if hbm_bytes is None:
        return None
    if program_temp_bytes is not None and program_temp_bytes >= 0:
        margin = state_bytes + program_temp_bytes
    else:
        margin = STAGING_STATE_HEADROOM * state_bytes
    free = max(hbm_bytes - margin, hbm_bytes * STAGING_FLOOR_FRACTION)
    return int(free * STAGING_FREE_FRACTION)


# Autotune (tpudist.tune): the measured-probe search that replaces the
# two resolve_* heuristics above with a measurement when enabled. The
# heuristics stay as the search's START point and its never-regress
# floor.
AUTOTUNE_MODES = ("off", "probe", "cache-only")
AUTOTUNE_DEFAULT_TRIALS = 12


def resolve_autotune(cfg: TrainConfig) -> str:
    """Resolve ``--autotune`` / ``TPUDIST_AUTOTUNE`` to a concrete mode.

    ``probe`` measures on a cache miss; ``cache-only`` reuses a prior
    measurement but never probes (pod launches where N workers probing
    at startup is unwanted). Fault injection and FULL-RUN profiling
    (``--profile-dir``) force ``off``: both are defined in
    per-step-dispatch terms, so every knob the tuner searches is
    already pinned. The windowed capture (``--profile-window``) does
    NOT force off — it profiles whatever operating point the run
    actually uses, tuned or not, and runs long after the probes are
    done (pinned in tests/test_devtime.py).
    """
    mode = cfg.autotune
    if mode is None:
        mode = os.environ.get("TPUDIST_AUTOTUNE") or "off"
    if mode not in AUTOTUNE_MODES:
        raise ValueError(
            f"--autotune must be one of {AUTOTUNE_MODES}, got {mode!r}")
    if mode != "off" and (cfg.fail_at is not None or cfg.profile_dir):
        return "off"
    return mode


def resolve_profile_window(cfg: TrainConfig) -> int:
    """Resolve ``--profile-window`` / ``TPUDIST_PROFILE_WINDOW`` to the
    number of mid-run supersteps to capture (0 = off).

    Precedence: explicit flag > env > 0. Full-run ``--profile-dir``
    wins over the window (profiler sessions cannot nest — the whole
    run is already inside one), so the window resolves to 0 there.
    """
    n = cfg.profile_window
    if n < 0:
        raise ValueError(
            f"--profile-window must be >= 0, got {n}")
    if n == 0:
        env = _env_float("TPUDIST_PROFILE_WINDOW")
        n = int(env) if env and env > 0 else 0
    if cfg.profile_dir:
        return 0
    return n


def resolve_autotune_cache_dir(cfg: TrainConfig) -> str:
    """Precedence: flag > ``TPUDIST_AUTOTUNE_CACHE_DIR`` > a ``tune/``
    subdir of ``save_dir`` (next to metrics.jsonl — one directory to
    persist across runs, same shape as the heartbeat default)."""
    return (cfg.autotune_cache_dir
            or os.environ.get("TPUDIST_AUTOTUNE_CACHE_DIR")
            or os.path.join(cfg.save_dir, "tune"))


def resolve_autotune_trials(cfg: TrainConfig) -> int:
    """Probe-trial budget: flag > ``TPUDIST_AUTOTUNE_TRIALS`` > 12."""
    if cfg.autotune_trials < 0:
        raise ValueError(
            f"--autotune-trials must be >= 0, got {cfg.autotune_trials}")
    if cfg.autotune_trials:
        return cfg.autotune_trials
    env = _env_float("TPUDIST_AUTOTUNE_TRIALS")
    return int(env) if env and env > 0 else AUTOTUNE_DEFAULT_TRIALS


# Gradient-overlap plane (tpudist.parallel.overlap): the DP all-reduce
# schedule knob and its bucket bound. The default bucket mirrors
# overlap.DEFAULT_BUCKET_MB (kept as a literal here so config stays
# importable before jax — the two are pinned equal in tests).
GRAD_OVERLAP_MODES = ("off", "bucketed")
GRAD_BUCKET_MB_DEFAULT = 4.0


def resolve_grad_overlap(cfg: TrainConfig) -> tuple[str, int]:
    """Resolve ``--grad-overlap`` / ``--grad-bucket-mb`` to the concrete
    ``(mode, bucket_bytes)`` pair the engine's DP path dispatches on.

    Precedence per knob: explicit flag > env (``TPUDIST_GRAD_OVERLAP``,
    ``TPUDIST_GRAD_BUCKET_MB``) > default (off, 4 MB). The mode applies
    to the explicit-collective DP shard_map path only — the engine
    raises on meshes that route gradients through the jit+shardings
    partitioner (there is no program-level reduce there to schedule)."""
    mode = cfg.grad_overlap
    if mode is None:
        mode = os.environ.get("TPUDIST_GRAD_OVERLAP") or "off"
    if mode not in GRAD_OVERLAP_MODES:
        raise ValueError(
            f"--grad-overlap must be one of {GRAD_OVERLAP_MODES}, "
            f"got {mode!r}")
    mb = cfg.grad_bucket_mb
    if mb is None:
        mb = _env_float("TPUDIST_GRAD_BUCKET_MB")
    if mb is None:
        mb = GRAD_BUCKET_MB_DEFAULT
    if mb <= 0:
        raise ValueError(f"--grad-bucket-mb must be > 0, got {mb}")
    return mode, int(mb * 2**20)


# --cross-slice vocabulary, mirrored from overlap.CROSS_SLICE_MODES
# (kept as a literal so config stays importable before jax — pinned
# equal in tests, like GRAD_OVERLAP_MODES above).
CROSS_SLICE_MODES = ("flat", "hierarchical")


def resolve_cross_slice(cfg: TrainConfig) -> str:
    """Resolve ``--cross-slice`` to the concrete cross-slice reduce
    schedule. Precedence: explicit flag > ``TPUDIST_CROSS_SLICE`` >
    flat. Like --grad-overlap, the mode applies to the explicit-
    collective pure-DP path; the engine refuses hierarchical on meshes
    that route gradients through the jit+shardings partitioner and
    downgrades it (with a logged notice) on single-slice meshes, where
    there is no DCN phase to split."""
    mode = cfg.cross_slice
    if mode is None:
        mode = os.environ.get("TPUDIST_CROSS_SLICE") or "flat"
    if mode not in CROSS_SLICE_MODES:
        raise ValueError(
            f"--cross-slice must be one of {CROSS_SLICE_MODES}, "
            f"got {mode!r}")
    return mode


def resolve_pipeline_interleave(cfg: TrainConfig) -> int:
    """Resolve ``--pipeline-interleave`` to the virtual-stage count v
    (1 = GPipe). Precedence: explicit flag > env > 1. Divisibility
    against the layer/stage/microbatch shape is validated where those
    are known (parallel.pipeline.make_pp_loss_fn)."""
    v = cfg.pipeline_interleave
    if v < 0:
        raise ValueError(
            f"--pipeline-interleave must be >= 1 (or 0 = default), "
            f"got {v}")
    if v == 0:
        env = _env_float("TPUDIST_PIPELINE_INTERLEAVE")
        v = int(env) if env and env > 0 else 1
    return v


# Elastic checkpoint/resume (tpudist.elastic): the checkpoint layout and
# the resume semantics are separate knobs — the layout decides what a
# kill can lose, the resume mode decides what a restart does about it.
CKPT_MODES = ("orbax", "sharded")
RESUME_MODES = ("latest", "auto")


def resolve_ckpt_mode(cfg: TrainConfig) -> str:
    """Resolve ``--ckpt-mode`` / ``TPUDIST_CKPT_MODE`` to the concrete
    checkpoint layout: ``orbax`` (step-keyed CheckpointManager — the
    default, and the only mode that writes ``gs://`` URIs natively) or
    ``sharded`` (tpudist.elastic.ckpt: per-worker shard files + an
    atomically committed manifest on a pod-shared filesystem — the
    layout elastic N→M resume reshards from). ``--ckpt-sync`` composes
    with either: it selects synchronous writes within the mode."""
    mode = cfg.ckpt_mode
    if mode is None:
        mode = os.environ.get("TPUDIST_CKPT_MODE") or "orbax"
    if mode not in CKPT_MODES:
        raise ValueError(
            f"--ckpt-mode must be one of {CKPT_MODES}, got {mode!r}")
    if mode == "sharded" and "://" in cfg.save_dir:
        raise ValueError(
            f"--ckpt-mode sharded writes plain files on a pod-shared "
            f"filesystem and cannot target {cfg.save_dir!r}; keep "
            f"--ckpt-mode orbax for remote URIs (or mount the bucket)")
    return mode


def resolve_resume(cfg: TrainConfig) -> Optional[str]:
    """Resolve ``--resume`` to a concrete mode or None (off). ``True``
    (the pre-elastic boolean spelling, kept for compat) means
    ``latest``. ``latest`` raises when the newest checkpoint cannot
    drive this run; ``auto`` — what the launcher's requeue loop passes —
    degrades a failed restore to a flagged fresh start, because a
    requeued job must make progress, not crash-loop on a torn dir."""
    r = cfg.resume
    if not r:
        return None
    if r is True:
        return "latest"
    if r not in RESUME_MODES:
        raise ValueError(
            f"--resume must be one of {RESUME_MODES}, got {r!r}")
    return r


def resolve_chaos(cfg: TrainConfig) -> Optional[str]:
    """Resolve ``--chaos`` / ``TPUDIST_CHAOS`` to the raw fault-plan
    spec, or None (the default: no chaos plane constructed, zero hooks
    installed). The spec itself is parsed — and validated loudly — by
    ``tpudist.chaos.ChaosPlan.parse`` at run start, not here: config
    must stay importable without the chaos package resolved."""
    return cfg.chaos or os.environ.get("TPUDIST_CHAOS") or None


def resolve_requeue_attempt(cfg: TrainConfig) -> int:
    """Which auto-requeue rerun this is: explicit flag, else
    ``TPUDIST_REQUEUE_ATTEMPT``, else 0."""
    if cfg.requeue_attempt:
        return int(cfg.requeue_attempt)
    env = _env_float("TPUDIST_REQUEUE_ATTEMPT")
    return int(env) if env and env > 0 else 0


# Span tracing (tpudist.obs.trace): always-on observability, like the
# flight recorder — the escape hatch exists for runs measuring the last
# microsecond of host overhead, not as the default posture.
TRACE_MODES = ("on", "off")


def resolve_trace(cfg: TrainConfig) -> tuple[bool, str]:
    """Resolve the span-tracer knobs to ``(enabled, trace_dir)``.

    Precedence per knob: explicit flag > env var > default (on,
    ``save_dir``). ``TPUDIST_TRACE`` accepts the usual falsy spellings
    (off/0/false/no) so launchers can disable tracing pod-wide without
    touching per-worker argv."""
    mode = cfg.trace
    if mode is None:
        # single source of truth for the accepted falsy spellings: the
        # ambient tracer (obs.trace.get, used by bench/selfcheck paths
        # that never call this resolver) parses the same env the same
        # way. Lazy import: config must stay importable before jax.
        from tpudist.obs.trace import _env_enabled
        mode = "on" if _env_enabled() else "off"
    if mode not in TRACE_MODES:
        raise ValueError(
            f"--trace must be one of {TRACE_MODES}, got {mode!r}")
    out_dir = (cfg.trace_dir or os.environ.get("TPUDIST_TRACE_DIR")
               or cfg.save_dir)
    return mode == "on", out_dir


# Flight-recorder defaults: the stall window must comfortably exceed any
# legitimate quiet period (a cold compile of the flagship superstep is
# ~1-2 min on TPU) while still firing well inside the launcher's outer
# TIMEOUT_S (default 1800) — the dump has to land BEFORE the kill. The
# value itself lives in tpudist.rules: the live alert engine fires the
# stall alert on the SAME window the watchdog dumps on.
OBS_STALL_TIMEOUT_S = rules_lib.STALL_TIMEOUT_S
OBS_HBM_SAMPLE_S = 2.0


def _env_float(name: str) -> Optional[float]:
    """Optional float env var; a malformed value reads as unset (an
    advisory observability knob must never kill a run at startup —
    same swallow-and-default semantics as verdict._env_float). An
    explicit FLAG, by contrast, still raises below: typos on the
    command line should fail fast."""
    raw = os.environ.get(name)
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        return None


def resolve_obs(cfg: TrainConfig) -> tuple[float, str, float]:
    """Resolve the flight-recorder knobs to concrete values:
    ``(stall_timeout_s, out_dir, hbm_sample_s)``.

    Precedence per knob: explicit flag > env var > default. The beacon /
    flight-record directory defaults to ``save_dir`` so the artifacts
    land next to ``metrics.jsonl`` — one directory to collect when a run
    dies.
    """
    stall = cfg.stall_timeout_s
    if stall is None:
        stall = _env_float("TPUDIST_STALL_TIMEOUT_S")
    if stall is None:
        stall = OBS_STALL_TIMEOUT_S
    if stall < 0:
        raise ValueError(f"--stall-timeout-s must be >= 0, got {stall}")
    out_dir = (cfg.heartbeat_dir or os.environ.get("TPUDIST_HEARTBEAT_DIR")
               or cfg.save_dir)
    hbm_s = cfg.hbm_sample_s
    if hbm_s is None:
        hbm_s = _env_float("TPUDIST_HBM_SAMPLE_S")
    if hbm_s is None:
        hbm_s = OBS_HBM_SAMPLE_S
    if hbm_s < 0:
        raise ValueError(f"--hbm-sample-s must be >= 0, got {hbm_s}")
    return stall, out_dir, hbm_s


# Live telemetry (tpudist.obs.live): OFF by default — unlike the span
# tracer it opens sockets and threads, which a bare acceptance run
# should not do unless an operator (or the launcher) asked for the view.
LIVE_MODES = ("on", "off")


def resolve_live(cfg: TrainConfig) -> tuple[bool, int, Optional[str]]:
    """Resolve the live-telemetry knobs to ``(enabled, exporter_port,
    ingest_endpoint)``.

    Precedence per knob: explicit flag > env var > default (off, 0 =
    ephemeral exporter port, no endpoint). ``TPUDIST_LIVE`` accepts the
    usual truthy/falsy spellings so launchers can switch the bus
    pod-wide without touching per-worker argv; ``TPUDIST_LIVE_ENDPOINT``
    is how the launcher tells every worker where the coordinator's
    aggregator listens (``[tcp://|udp://]host:port``) — without it a
    single-host run loops back over an ephemeral loopback port, which
    exercises the same socket path a pod does."""
    mode = cfg.live
    if mode is None:
        raw = (os.environ.get("TPUDIST_LIVE") or "off").lower()
        mode = "off" if raw in ("", "off", "0", "false", "no") else "on"
    if mode not in LIVE_MODES:
        raise ValueError(
            f"--live must be one of {LIVE_MODES}, got {mode!r}")
    port = cfg.live_port
    if port < 0:
        raise ValueError(f"--live-port must be >= 0, got {port}")
    if port == 0:
        env = _env_float("TPUDIST_LIVE_PORT")
        port = int(env) if env and env > 0 else 0
    endpoint = (cfg.live_endpoint
                or os.environ.get("TPUDIST_LIVE_ENDPOINT") or None)
    return mode == "on", port, endpoint


def flagship_model_config(max_seq_len: int = 512) -> ModelConfig:
    """BASELINE.json config #5: the synthetic Llama-block transformer
    (4 layers, 2048 hidden, 16 heads, SwiGLU 5504). Single source of truth
    for the headline benchmark and the driver compile-check entry."""
    return ModelConfig(name="transformer", vocab_size=32000, n_layers=4,
                       d_model=2048, n_heads=16, n_kv_heads=16, d_ff=5504,
                       max_seq_len=max_seq_len)


def parse_args(argv: Optional[Sequence[str]] = None) -> TrainConfig:
    """CLI → TrainConfig. Unknown flags are tolerated (parity with the
    reference's ``parse_known_args()[0]``), so launchers may pass extra
    flags without breaking the workload."""
    p = argparse.ArgumentParser(description="tpudist synthetic training workload")
    p.add_argument("--train-batch-size", type=int, default=64,
                   help="global batch size across all data-parallel replicas")
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--save-dir", type=str, default="ckpt")
    p.add_argument("--resume", nargs="?", const="latest", default=False,
                   choices=list(RESUME_MODES),
                   help="resume from the latest checkpoint in --save-dir: "
                        "bare/latest raises when the checkpoint cannot "
                        "drive this run; auto (the launcher's requeue "
                        "mode) prefers the committed elastic manifest, "
                        "falls back to orbax, and degrades a failed "
                        "restore to a flagged fresh start")
    p.add_argument("--ckpt-every-steps", type=int, default=0,
                   help="also checkpoint mid-epoch every N steps (0 = "
                        "epoch-end only); a preemption then loses at most "
                        "N steps")
    p.add_argument("--ckpt-sync", action="store_true",
                   help="synchronous checkpoint writes (async overlap is "
                        "the default)")
    p.add_argument("--ckpt-mode", type=str, default=None,
                   choices=list(CKPT_MODES),
                   help="checkpoint layout: orbax step dirs (default; "
                        "native gs:// support) or sharded — per-worker "
                        "shard files + an atomically committed "
                        "manifest.json (tpudist.elastic), resumable onto "
                        "a DIFFERENT process/device count (default: "
                        "$TPUDIST_CKPT_MODE, else orbax)")
    p.add_argument("--requeue-attempt", type=int, default=0,
                   help="which auto-requeue rerun this is (the launcher "
                        "passes it; lands in the kind=resume record and "
                        "the tpudist: resume line; default: "
                        "$TPUDIST_REQUEUE_ATTEMPT, else 0)")
    p.add_argument("--model", type=str, default="mlp",
                   choices=["mlp", "transformer", "moe"])
    p.add_argument("--dtype", type=str, default="float32",
                   choices=["float32", "bfloat16"])
    p.add_argument("--grad-accum-steps", type=int, default=1)
    p.add_argument("--adam-nu-dtype", type=str, default="float32",
                   choices=("float32", "bfloat16"),
                   help="Adam second-moment storage dtype; bfloat16 trades "
                        "~1e-3-relative update noise for halved nu HBM "
                        "traffic (big MoE optimizer states)")
    p.add_argument("--remat", action="store_true",
                   help="rematerialise transformer layers in backward")
    p.add_argument("--xent-chunks", type=int, default=0,
                   help="stream the LM head + cross-entropy over N sequence "
                        "chunks instead of materialising full logits")
    p.add_argument("--lm-head", type=str, default="auto",
                   choices=("auto", "plain", "chunked", "fused"),
                   help="LM-head strategy; auto picks from the logits-pair"
                        " + activation HBM estimate (the default: the "
                        "operator never needs to know this flag exists)")
    p.add_argument("--fused-xent", action="store_true",
                   help="compute the LM head + cross-entropy with the fused "
                        "pallas kernel (logits never reach HBM); runs in "
                        "the pallas interpreter off-TPU")
    p.add_argument("--n-samples", type=int, default=2000)
    p.add_argument("--n-features", type=int, default=20)
    # transformer shape (defaults = BASELINE.json config #5: 4 layers, 2k hidden)
    p.add_argument("--vocab-size", type=int, default=32000)
    p.add_argument("--n-layers", type=int, default=4)
    p.add_argument("--d-model", type=int, default=2048)
    p.add_argument("--n-heads", type=int, default=16)
    p.add_argument("--n-kv-heads", type=int, default=None)
    p.add_argument("--d-ff", type=int, default=5504)
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--fsdp", type=int, default=1, help="fsdp mesh axis size")
    p.add_argument("--tensor", type=int, default=1, help="tensor mesh axis size")
    p.add_argument("--context", type=int, default=1, help="context mesh axis size")
    p.add_argument("--pipe", type=int, default=1,
                   help="pipeline mesh axis size (GPipe schedule over "
                        "transformer layer stages)")
    p.add_argument("--expert", type=int, default=1,
                   help="expert mesh axis size (MoE expert parallelism)")
    p.add_argument("--pp-microbatches", type=int, default=0,
                   help="pipeline microbatches per step (0 = pipe size)")
    p.add_argument("--pipeline-interleave", type=int, default=0,
                   help="virtual pipeline stages per device: v>1 runs "
                        "the interleaved schedule (each device holds v "
                        "round-robin layer chunks), cutting the bubble "
                        "from (S-1)/(M+S-1) to (S-1)/(v*M+S-1); "
                        "requires n-layers divisible by pipe*v and "
                        "microbatches divisible by pipe (default: "
                        "$TPUDIST_PIPELINE_INTERLEAVE, else 1 = GPipe)")
    p.add_argument("--grad-overlap", type=str, default=None,
                   choices=list(GRAD_OVERLAP_MODES),
                   help="DP gradient all-reduce schedule "
                        "(tpudist.parallel.overlap): off = trailing-"
                        "barrier baseline (reduce after the whole "
                        "backward), bucketed = size-bounded buckets "
                        "dispatched as backward produces them, hidden "
                        "behind the remaining backward compute; "
                        "bitwise-identical loss either way (default: "
                        "$TPUDIST_GRAD_OVERLAP, else off)")
    p.add_argument("--grad-bucket-mb", type=float, default=None,
                   help="bucket size bound for --grad-overlap bucketed "
                        "(default: $TPUDIST_GRAD_BUCKET_MB, else 4)")
    p.add_argument("--cross-slice", type=str, default=None,
                   choices=list(CROSS_SLICE_MODES),
                   help="cross-slice DP reduce schedule "
                        "(tpudist.parallel.overlap): flat = full "
                        "gradient bytes over DCN, hierarchical = "
                        "reduce-scatter in-slice (ICI) + all-reduce of "
                        "the 1/slice_size shard across slices (DCN) + "
                        "all-gather in-slice — cuts DCN bytes by the "
                        "slice size; bitwise-identical loss either way "
                        "(default: $TPUDIST_CROSS_SLICE, else flat)")
    p.add_argument("--cp-impl", type=str, default="ring",
                   choices=list(CP_IMPLS),
                   help="context-parallel attention: kv ring rotation "
                        "(zigzag causal balance, scales past head count) "
                        "or ulysses all-to-all head resharding")
    # moe shape
    p.add_argument("--n-experts", type=int, default=8)
    p.add_argument("--expert-top-k", type=int, default=2)
    p.add_argument("--capacity-factor", type=float, default=1.25)
    p.add_argument("--router-aux-weight", type=float, default=0.01)
    p.add_argument("--moe-group-size", type=int, default=4096,
                   help="tokens per routing group (bounds dispatch-tensor "
                        "memory; must divide batch*seq or routing falls "
                        "back to one global group)")
    p.add_argument("--fail-at", type=int, default=None,
                   help="fault injection: fail after this epoch (replaces the "
                        "reference's commented-out sys.exit(1), train.py:129)")
    p.add_argument("--chaos", type=str, default=None,
                   help="scripted fault-injection plan (tpudist.chaos): "
                        "';'-separated <fault>@<epoch>:<step>[:<rank>]"
                        "[,k=v...] events, fault one of kill | hang | "
                        "slow | corrupt_shard | torn_manifest | fs_error "
                        "| telemetry_garbage — e.g. "
                        "'corrupt_shard@0:6,mode=flip;kill@0:7' "
                        "(default: $TPUDIST_CHAOS, else off)")
    p.add_argument("--log-every", type=int, default=100)
    p.add_argument("--steps-per-dispatch", type=int, default=0,
                   help="superstep length: compile k train steps into one "
                        "lax.scan dispatch (one host fence per k steps). "
                        "0 = auto: largest divisor of --log-every/"
                        "--ckpt-every-steps up to 32, or 1 under "
                        "profiling/--fail-at/--log-every 1")
    p.add_argument("--staging-budget-mb", type=float, default=None,
                   help="per-device MB of device memory for staging epoch "
                        "batches; epochs over budget stream in "
                        "double-buffered k-step slabs overlapped with "
                        "compute (default: $TPUDIST_STAGING_BUDGET_MB, "
                        "else auto from device memory stats minus the "
                        "params/opt-state estimate)")
    p.add_argument("--compilation-cache-dir", type=str,
                   default=None,
                   help="persistent XLA compilation cache directory "
                        "(default: $TPUDIST_COMPILATION_CACHE_DIR); repeat "
                        "runs reuse compiled programs instead of retracing")
    p.add_argument("--stall-timeout-s", type=float, default=None,
                   help="flight-recorder watchdog: no step progress for "
                        "this long dumps thread stacks + memory stats + "
                        "last-N metrics to flightrec.worker<i> before the "
                        "launcher kills the job (default: "
                        "$TPUDIST_STALL_TIMEOUT_S, else 300; 0 disables "
                        "the watchdog, beacon stays on)")
    p.add_argument("--heartbeat-dir", type=str, default=None,
                   help="directory for heartbeat.worker<i> beacons and "
                        "flightrec.worker<i> dumps (default: "
                        "$TPUDIST_HEARTBEAT_DIR, else --save-dir)")
    p.add_argument("--hbm-sample-s", type=float, default=None,
                   help="HBM watermark sampler period in seconds; the "
                        "high-water mark lands in the kind=timing record "
                        "(default: $TPUDIST_HBM_SAMPLE_S, else 2.0; "
                        "0 disables)")
    p.add_argument("--autotune", type=str, default=None,
                   choices=list(AUTOTUNE_MODES),
                   help="measured-probe autotuning of the dispatch/"
                        "staging/remat operating point (tpudist.tune): "
                        "probe = short on-device trials before the timed "
                        "run (cached by workload fingerprint; the second "
                        "run costs zero probes), cache-only = reuse a "
                        "prior measurement but never probe (default: "
                        "$TPUDIST_AUTOTUNE, else off)")
    p.add_argument("--autotune-cache-dir", type=str, default=None,
                   help="tuning-cache directory (default: "
                        "$TPUDIST_AUTOTUNE_CACHE_DIR, else "
                        "<save-dir>/tune)")
    p.add_argument("--autotune-trials", type=int, default=0,
                   help="probe-trial budget for the autotune search "
                        "(0 = $TPUDIST_AUTOTUNE_TRIALS, else 12)")
    p.add_argument("--profile-dir", type=str, default=None,
                   help="write jax.profiler traces (tensorboard format) "
                        "here — EVERY worker captures, into "
                        "profile/worker<i> subdirs, so multi-host "
                        "traces are complete; the reference had no "
                        "profiling at all (SURVEY.md §5.1)")
    p.add_argument("--profile-window", type=int, default=0,
                   help="capture N mid-run supersteps with jax.profiler "
                        "on every worker (profile/worker<i> under "
                        "--trace-dir) and ingest the device timeline at "
                        "run end: kind=devtime record, device tracks in "
                        "pod_trace.json, comm_status verdict (default: "
                        "$TPUDIST_PROFILE_WINDOW, else 0 = off; "
                        "--profile-dir wins when both are set)")
    p.add_argument("--trace", type=str, default=None,
                   choices=list(TRACE_MODES),
                   help="host-side span tracing (obs.trace): on by "
                        "default (~1 µs/span); run end writes "
                        "trace.worker<i>.json per process and a merged "
                        "Perfetto pod_trace.json on the coordinator "
                        "(default: $TPUDIST_TRACE, else on)")
    p.add_argument("--trace-dir", type=str, default=None,
                   help="directory for trace.worker<i>.json / "
                        "pod_trace.json (default: $TPUDIST_TRACE_DIR, "
                        "else --save-dir)")
    p.add_argument("--live", type=str, default=None,
                   choices=list(LIVE_MODES),
                   help="live telemetry bus (obs.live): non-blocking "
                        "per-worker emitters stream records + heartbeats "
                        "to a coordinator aggregator that runs the "
                        "on-line alert engine over the SAME thresholds "
                        "as the exit verdict (tpudist.rules), rewrites "
                        "live_status.json, and serves Prometheus "
                        "/metrics (default: $TPUDIST_LIVE, else off)")
    p.add_argument("--live-port", type=int, default=0,
                   help="Prometheus exporter port on the coordinator "
                        "(/metrics, /status.json, /healthz; default: "
                        "$TPUDIST_LIVE_PORT, else an ephemeral port)")
    p.add_argument("--live-endpoint", type=str, default=None,
                   help="ingest endpoint workers ship records to "
                        "([tcp://|udp://]host:port; default: "
                        "$TPUDIST_LIVE_ENDPOINT, else the coordinator "
                        "binds loopback on an ephemeral port — the "
                        "launcher passes the coordinator's reachable "
                        "address on pods)")
    args = p.parse_known_args(argv)[0]

    return TrainConfig(
        batch_size=args.train_batch_size,
        epochs=args.epochs,
        lr=args.lr,
        seed=args.seed,
        save_dir=args.save_dir,
        resume=args.resume,
        ckpt_every_steps=args.ckpt_every_steps,
        ckpt_sync=args.ckpt_sync,
        ckpt_mode=args.ckpt_mode,
        requeue_attempt=args.requeue_attempt,
        grad_accum_steps=args.grad_accum_steps,
        adam_nu_dtype=args.adam_nu_dtype,
        dtype=args.dtype,
        remat=args.remat,
        xent_chunks=args.xent_chunks,
        fused_xent=args.fused_xent,
        lm_head=args.lm_head,
        pp_microbatches=args.pp_microbatches,
        pipeline_interleave=args.pipeline_interleave,
        cp_impl=args.cp_impl,
        grad_overlap=args.grad_overlap,
        grad_bucket_mb=args.grad_bucket_mb,
        cross_slice=args.cross_slice,
        fail_at=args.fail_at,
        chaos=args.chaos,
        log_every=args.log_every,
        profile_dir=args.profile_dir,
        profile_window=args.profile_window,
        steps_per_dispatch=args.steps_per_dispatch,
        compilation_cache_dir=args.compilation_cache_dir,
        staging_budget_mb=args.staging_budget_mb,
        stall_timeout_s=args.stall_timeout_s,
        heartbeat_dir=args.heartbeat_dir,
        hbm_sample_s=args.hbm_sample_s,
        autotune=args.autotune,
        autotune_cache_dir=args.autotune_cache_dir,
        autotune_trials=args.autotune_trials,
        trace=args.trace,
        trace_dir=args.trace_dir,
        live=args.live,
        live_port=args.live_port,
        live_endpoint=args.live_endpoint,
        data=DataConfig(n_samples=args.n_samples, n_features=args.n_features,
                        seed=args.seed),
        model=ModelConfig(name=args.model, n_features=args.n_features,
                          vocab_size=args.vocab_size, n_layers=args.n_layers,
                          d_model=args.d_model, n_heads=args.n_heads,
                          n_kv_heads=(args.n_kv_heads if args.n_kv_heads
                                      is not None else args.n_heads),
                          d_ff=args.d_ff, max_seq_len=args.seq_len,
                          n_experts=args.n_experts,
                          expert_top_k=args.expert_top_k,
                          capacity_factor=args.capacity_factor,
                          router_aux_weight=args.router_aux_weight,
                          moe_group_size=args.moe_group_size),
        parallel=ParallelConfig(pipe=args.pipe, fsdp=args.fsdp,
                                expert=args.expert, tensor=args.tensor,
                                context=args.context),
    )
