"""The gate thresholds, in ONE place: at-exit grading and live alerts.

Before this module existed every threshold lived next to its consumer —
``STAGING_OVERLAP_MIN``/``STRAGGLER_FACTOR`` in :mod:`tpudist.verdict`,
``COMM_EXPOSED_MAX`` in :mod:`tpudist.obs.devtime`,
``REGRESS_MIN_FRACTION`` in :mod:`tpudist.obs.report`, the stall window
in :mod:`tpudist.config` — which was fine while each gate had exactly
one consumer. The live alert engine (:mod:`tpudist.obs.alerts`) is a
SECOND consumer of every one of them, and the whole point of on-line
alerting is that a run that will grade ``fail`` at exit must have
alerted mid-run: the two graders evaluating *different* thresholds
would silently break that contract. So the thresholds live here, both
graders import them, and a tier-1 test diffs the two consumers against
this table so they cannot drift apart again.

Stdlib-only by design: this module sits under every jax-free offline
path (verdict ← hoststats ← obs.report; obs.live's exporter and tail
CLI) — it must import on a laptop with nothing installed.

Each threshold's env override is read at CALL time (``resolve()``), not
import time, so per-run overrides and tests take effect without a
module reload — the discipline every gate already followed. A malformed
env value reads as the default (an advisory observability knob must
never kill a run at startup).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Tuple

# The canonical defaults (moved verbatim from their original homes —
# the rationale comments stay with the consumers that explain them).
STRAGGLER_FACTOR = 1.25     # verdict.straggler_status
STAGING_OVERLAP_MIN = 0.5   # verdict.staging_status
COMM_EXPOSED_MAX = 0.25     # obs.devtime.comm_status (ICI rows)
# DCN rows grade against their own ceiling: a cross-slice data axis is
# an order of magnitude slower than the ICI torus, so the same schedule
# honestly exposes more of it — flagging a DCN run at the ICI ceiling
# would read every multi-slice pod as broken, while a DCN pod clearing
# the ICI bar would mean the overlap plane is idle. Selected per row by
# resolve_comm() from the devtime record's axis_fabric label.
COMM_EXPOSED_MAX_DCN = 0.4  # obs.devtime.comm_status (DCN rows)
REGRESS_MIN_FRACTION = 0.8  # obs.report regression gate
STALL_TIMEOUT_S = 300.0     # obs.heartbeat watchdog / live stall alert
TRACE_DROP_MAX = 0.5        # verdict.trace_status (no live alert: a
#                             dropped-span ratio is an artifact-quality
#                             finding, not a mid-run health signal)

# Serving SLOs (tpudist.serve): latency is where a serving pod is won
# or lost, so the gates are latency-percentile bounds plus a throughput
# floor. The defaults are deliberately loose enough for the CI CPU-mesh
# acceptance lane (a warmed tiny-model engine decodes in milliseconds);
# production deployments tighten them per model via the env overrides.
TTFT_P99_MAX = 2.0          # serve: p99 time-to-first-token (seconds)
ITL_P99_MAX = 1.0           # serve: p99 inter-token latency (seconds)
TOKENS_PER_CHIP_MIN = 1.0   # serve: decode throughput floor (tok/s/chip)
# Serve admission shedding (tpudist.serve.resilience): the fraction of
# arrivals turned away (shed at admission + expired in queue + rejected
# garbage) — admission control keeps the ADMITTED percentiles honest
# under overload, so the shed share itself must be gated or a pod could
# "pass" its latency SLOs by serving almost nobody. The default tolerates
# transient 2x bursts (~half the arrivals shed at sustained 2x) without
# flagging; capacity-planned deployments tighten it via the env override.
SERVE_SHED_MAX = 0.6        # serve: max shed fraction of arrivals
# Speculative-decoding acceptance (tpudist.serve): the fraction of
# drafted tokens the target model confirmed. A low rate means the
# n-gram proposer is guessing badly for this workload and the verify
# passes are burning flops for nothing — an efficiency finding, not a
# correctness one (speculation is bitwise-exact at any rate), so the
# default floor is 0.0 (never breaches) and the rule never alerts
# mid-run; deployments that care opt in via the env override.
SPEC_ACCEPT_MIN = 0.0       # serve: min speculative acceptance rate
# Flight-ledger TTFT decomposition tolerance (tpudist.serve.flight):
# the ADMITTED event carries waited_s (the TTFT) AND its decomposition
# (queue_wait_s + prefill_s), all independently rounded to 1 µs — so
# the exact identity ttft == queue_wait + prefill survives as an
# inequality with a pinned bound (3 roundings at ±0.5 µs plus one float
# ulp). A reconstruction outside the bound means the scheduler's
# decomposition drifted from its own headline number, not noise.
FLIGHT_DECOMP_TOL_S = 5e-6  # serve: max |ttft - (queue+prefill)| (s)

# Goodput (tpudist.obs.goodput): productive training time as a fraction
# of the run's total wall-clock — cross-attempt in the offline ledger,
# attempt-local in the run-end kind=goodput record the live engine
# watches. The default is deliberately loose (spot capacity routinely
# eats half a run in requeues before anyone calls it broken); CI drills
# and production deployments tighten it via the env override.
GOODPUT_MIN = 0.5           # obs.goodput.goodput_status

# HBM headroom (tpudist.obs.memledger): the unattributed free fraction
# of device HBM after the ledger's static buckets (params, opt state,
# staged slabs, KV pool) and the compiled programs' peak temp are
# carved out. The default floor is 0.0 — like SPEC_ACCEPT_MIN, the rule
# never breaches unless a deployment opts in: how much headroom a pod
# NEEDS is a capacity-planning choice (fragmentation slack, burst
# admission, future growth), not a universal constant, and a fresh
# checkout must not flag every snug-but-working configuration. CI lanes
# and production pods pin their floor via the env override, and a
# breach means the next allocation spike is an OOM, not a slowdown.
HBM_HEADROOM_MIN = 0.0      # obs.memledger.hbm_headroom_status


@dataclass(frozen=True)
class Threshold:
    """One gate: its env knob, default, and breach direction.

    ``sense`` is the direction the threshold bounds: ``"max"`` means
    the observed value must stay **at or below** it (breach when
    ``value > threshold``); ``"min"`` means the value must stay **at or
    above** it (breach when ``value < threshold``). ``alert`` marks the
    rules the live engine evaluates mid-run; ``observable`` documents
    the number fed to :func:`breached` so the two graders agree on
    units, not just on the constant.
    """

    name: str
    env: str
    default: float
    sense: str              # "max" | "min"
    alert: bool
    observable: str
    description: str


THRESHOLDS: Tuple[Threshold, ...] = (
    Threshold(
        name="straggler", env="TPUDIST_STRAGGLER_FACTOR",
        default=STRAGGLER_FACTOR, sense="max", alert=True,
        observable="worst host mean step time / pod median",
        description="a host slower than the pod median by this factor "
                    "drags every collective to its pace"),
    Threshold(
        name="staging", env="TPUDIST_STAGING_OVERLAP_MIN",
        default=STAGING_OVERLAP_MIN, sense="min", alert=True,
        observable="fraction of steady-state wall NOT exposed to "
                   "staging waits",
        description="below this, host->device transfer is not hiding "
                    "behind compute and the pod is input-bound"),
    Threshold(
        name="comm", env="TPUDIST_COMM_EXPOSED_MAX",
        default=COMM_EXPOSED_MAX, sense="max", alert=True,
        observable="exposed-communication fraction of the device "
                   "window",
        description="communication the schedule failed to overlap "
                    "with compute"),
    Threshold(
        name="comm_dcn", env="TPUDIST_COMM_EXPOSED_MAX_DCN",
        default=COMM_EXPOSED_MAX_DCN, sense="max", alert=False,
        observable="exposed-communication fraction of the device "
                   "window, when the graded axis crosses slices (DCN)",
        description="the DCN ceiling for the comm gate — not its own "
                    "alert: the live engine observes rule 'comm' with "
                    "this threshold substituted (resolve_comm), so "
                    "mid-run alerts and the at-exit comm_status stay "
                    "one (rule, host) key per fabric-graded breach"),
    Threshold(
        name="regress", env="TPUDIST_REGRESS_MIN",
        default=REGRESS_MIN_FRACTION, sense="min", alert=True,
        observable="measured steps/s / baseline steps/s",
        description="throughput below this fraction of baseline is a "
                    "regression"),
    Threshold(
        name="stall", env="TPUDIST_STALL_TIMEOUT_S",
        default=STALL_TIMEOUT_S, sense="max", alert=True,
        observable="seconds since the last step-progress signal",
        description="no step progress for this long means a wedged "
                    "host (the watchdog dumps, the alert fires)"),
    Threshold(
        name="trace_drop", env="TPUDIST_TRACE_DROP_MAX",
        default=TRACE_DROP_MAX, sense="max", alert=False,
        observable="fraction of recorded spans the ring overwrote",
        description="a trace with more holes than this under-counts "
                    "exactly the longest runs"),
    Threshold(
        name="ttft", env="TPUDIST_TTFT_P99_MAX",
        default=TTFT_P99_MAX, sense="max", alert=True,
        observable="p99 time-to-first-token in seconds (queue wait + "
                   "prefill)",
        description="users feel the first token; past this the serving "
                    "pod is admission- or prefill-bound"),
    Threshold(
        name="itl", env="TPUDIST_ITL_P99_MAX",
        default=ITL_P99_MAX, sense="max", alert=True,
        observable="p99 inter-token latency in seconds (decode "
                   "superstep wall / steps)",
        description="token streaming stutters past this; the decode "
                    "program or batch shape is mis-sized"),
    Threshold(
        name="tokens_per_chip", env="TPUDIST_TOKENS_PER_CHIP_MIN",
        default=TOKENS_PER_CHIP_MIN, sense="min", alert=True,
        observable="generated tokens per second per chip",
        description="below this floor the pod serves fewer users than "
                    "its chip count should carry"),
    Threshold(
        name="serve_shed", env="TPUDIST_SERVE_SHED_MAX",
        default=SERVE_SHED_MAX, sense="max", alert=True,
        observable="fraction of arrived requests shed at admission, "
                   "expired in queue, or rejected as malformed",
        description="past this the admission controller is the only "
                    "thing meeting the latency SLO — the pod is "
                    "under-provisioned for its offered load"),
    Threshold(
        name="spec_accept", env="TPUDIST_SERVE_SPEC_ACCEPT_MIN",
        default=SPEC_ACCEPT_MIN, sense="min", alert=False,
        observable="fraction of drafted tokens the target model "
                   "accepted across the run",
        description="below this the n-gram draft is a poor fit for the "
                    "workload and the verify passes waste flops — an "
                    "efficiency gate (speculation is bitwise-exact at "
                    "any rate), off by default (floor 0.0) and never a "
                    "mid-run alert"),
    Threshold(
        name="flight_decomp", env="TPUDIST_SERVE_FLIGHT_TOL_S",
        default=FLIGHT_DECOMP_TOL_S, sense="max", alert=False,
        observable="worst |ttft - (queue_wait + prefill)| across "
                   "reconstructed request flights, in seconds",
        description="the flight ledger's TTFT-decomposition bound: "
                    "past the rounding budget the per-request timeline "
                    "no longer sums to its own headline TTFT — an "
                    "artifact-integrity gate (offline reconstruction), "
                    "never a mid-run alert"),
    Threshold(
        name="goodput", env="TPUDIST_GOODPUT_MIN",
        default=GOODPUT_MIN, sense="min", alert=True,
        observable="productive training fraction of wall clock "
                   "(cross-attempt in the ledger, attempt-local live)",
        description="below this the pod burns its wall-clock on "
                    "compile, exposed transfer, lost progress and "
                    "requeue gaps instead of training"),
    Threshold(
        name="hbm_headroom", env="TPUDIST_HBM_HEADROOM_MIN",
        default=HBM_HEADROOM_MIN, sense="min", alert=True,
        observable="unattributed free fraction of device HBM after the "
                   "ledger's buckets (params, opt state, slabs, KV "
                   "pool, program temp) are carved out",
        description="below the opted-in floor the pod is one "
                    "allocation spike from RESOURCE_EXHAUSTED — the "
                    "ledger names which bucket to shrink; off by "
                    "default (floor 0.0) since needed headroom is a "
                    "capacity-planning choice"),
)

ALERT_RULES: Tuple[Threshold, ...] = tuple(
    t for t in THRESHOLDS if t.alert)

# At-exit verdict fields (kind=timing) and the alert rule that grades
# the same observable — the single source behind BOTH the report CLI's
# Alerts cross-check and the chaos verifier's end-to-end invariant
# ("every fail verdict had its matching mid-run alert",
# tpudist.chaos.verify). A new gate extends THIS table so the two
# checkers cannot drift; fields whose rule is not alertable
# (trace_status) deliberately stay off it.
STATUS_RULES: Tuple[Tuple[str, str], ...] = (
    ("staging_status", "staging"),
    ("straggler_status", "straggler"),
    ("comm_status", "comm"),
)

# The serve-side twin of STATUS_RULES: the ``kind=serve`` summary's
# per-gate status fields and the alert rule that grades the same
# observable mid-run. ONE table shared by the report CLI's Alerts
# cross-check and the serve drill verifier's end-to-end invariant
# ("every SLO fail verdict had its matching mid-run alert",
# tpudist.serve.drill) — same cannot-drift discipline as STATUS_RULES.
SERVE_STATUS_RULES: Tuple[Tuple[str, str], ...] = (
    ("ttft_status", "ttft"),
    ("itl_status", "itl"),
    ("tokens_per_chip_status", "tokens_per_chip"),
    ("serve_shed_status", "serve_shed"),
)

_BY_NAME = {t.name: t for t in THRESHOLDS}


def get(name: str) -> Threshold:
    """The rule named ``name``; KeyError on unknown names (a typo'd
    rule must fail loudly, not grade vacuously)."""
    return _BY_NAME[name]


def resolve(name: str) -> float:
    """The effective threshold: env override (read NOW) else default."""
    rule = get(name)
    raw = os.environ.get(rule.env)
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    return rule.default


def resolve_comm(fabric: Optional[str] = None) -> float:
    """The exposed-comm ceiling for a fabric-labeled row: ``"dcn"``
    resolves the ``comm_dcn`` rule (its own env + default), anything
    else — ``"ici"``, None, an unknown label — the ``comm`` rule. The
    single fabric-dispatch point every comm-gate consumer (devtime,
    verdict, live alerts, report) routes through, so ICI and DCN rows
    cannot drift onto different tables."""
    return resolve("comm_dcn" if fabric == "dcn" else "comm")


def breached(name: str, value: Optional[float],
             threshold: Optional[float] = None) -> bool:
    """Whether ``value`` breaches the rule. ``None`` never breaches
    (no measurement = ungateable, the three-valued-verdict convention —
    an alert must mean an observed bad number, not a missing one)."""
    if value is None:
        return False
    if threshold is None:
        threshold = resolve(name)
    if get(name).sense == "max":
        return value > threshold
    return value < threshold
