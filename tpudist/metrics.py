"""Observability: step timing, throughput, rank-0 structured logging.

The reference had NO timing at all (SURVEY.md §5.1 — its only clock was CI's
10-second job poll) and print-only logging (§5.5). Here: a StepTimer with
proper ``block_until_ready`` fencing (XLA is async — wall-clocking a
dispatched-but-unfinished step measures nothing), steps/sec/chip (the
BASELINE.json headline metric), and JSONL metrics next to the human log.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import IO, Any, Dict, List, Optional

import jax


def log0(msg: str) -> None:
    """Rank-0-gated print (parity: reference ``train.py:120-121,128``)."""
    if jax.process_index() == 0:
        print(msg, flush=True)


@dataclass
class StepTimer:
    """Wall-clock over completed device work.

    ``stop(result)`` blocks on ``result`` before reading the clock so the
    measurement covers actual execution, not async dispatch. The first
    ``warmup`` stops (default 1: the trace+compile step) are excluded from
    the throughput aggregate — compile time would otherwise dominate short
    runs and corrupt the steps/sec headline metric.
    """
    warmup: int = 1
    t0: float = 0.0
    elapsed: float = 0.0
    steps: int = 0
    warmup_s: float = 0.0
    _seen: int = 0

    def start(self) -> None:
        self.t0 = time.perf_counter()

    def stop(self, result: Any = None) -> float:
        return self.stop_many(result, 1)

    @property
    def warming(self) -> bool:
        """Still inside the warmup stops (i.e. compile not yet absorbed)."""
        return self._seen < self.warmup

    def stop_many(self, result: Any, n: int) -> float:
        """One fence covering ``n`` dispatched steps (the train loop fences
        at logging boundaries, not per step — a per-step fence serializes
        host and device and costs a full pipeline drain on tunneled
        backends). The first group absorbs compile and counts as warmup."""
        if n <= 0:
            return 0.0
        if result is not None:
            # fence via host TRANSFER, not block_until_ready: on tunneled
            # PJRT backends the latter can return before execution completes
            jax.device_get(result)
        dt = time.perf_counter() - self.t0
        self._seen += 1
        if self._seen <= self.warmup:
            self.warmup_s += dt
        else:
            self.elapsed += dt
            self.steps += n
        return dt

    def split(self) -> Dict[str, Any]:
        """Compile-vs-run wall split for the metrics stream: the warmup
        fence group absorbs trace+compile (near-zero when the persistent
        compilation cache hits — the pair makes cache effectiveness and
        steady-state dispatch separately visible), ``run_s`` covers the
        counted steady-state steps."""
        return {"compile_warmup_s": round(self.warmup_s, 3),
                "run_s": round(self.elapsed, 3), "steps": self.steps}

    def steps_per_sec(self) -> float:
        return self.steps / self.elapsed if self.elapsed > 0 else 0.0

    def steps_per_sec_per_chip(self) -> float:
        return self.steps_per_sec() / max(jax.device_count(), 1)


@dataclass
class MetricsLogger:
    """JSONL metrics stream, rank-0 only (structured logging the reference
    lacked — its observability was stdout through SLURM log files,
    SURVEY.md §5.5)."""
    path: Optional[str] = None
    _fh: Optional[IO] = None
    history: List[Dict] = field(default_factory=list)

    def log(self, **kv) -> None:
        if jax.process_index() != 0:
            return
        rec = dict(ts=time.time(), **kv)
        self.history.append(rec)
        if self.path:
            if self._fh is None:
                d = os.path.dirname(self.path)
                if d:
                    os.makedirs(d, exist_ok=True)
                self._fh = open(self.path, "a")
            self._fh.write(json.dumps(rec) + "\n")
            self._fh.flush()

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None


def device_kind() -> str:
    try:
        return jax.devices()[0].device_kind
    except Exception:
        return "unknown"
