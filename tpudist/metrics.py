"""Observability: step timing, throughput, rank-0 structured logging.

The reference had NO timing at all (SURVEY.md §5.1 — its only clock was CI's
10-second job poll) and print-only logging (§5.5). Here: a StepTimer with
proper ``block_until_ready`` fencing (XLA is async — wall-clocking a
dispatched-but-unfinished step measures nothing), steps/sec/chip (the
BASELINE.json headline metric), and JSONL metrics next to the human log.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import IO, Any, Dict, List, Optional

import jax

from tpudist.obs import trace as trace_lib


def log0(msg: str) -> None:
    """Rank-0-gated print (parity: reference ``train.py:120-121,128``)."""
    if jax.process_index() == 0:
        print(msg, flush=True)


@dataclass
class StepTimer:
    """Wall-clock over completed device work.

    ``stop(result)`` blocks on ``result`` before reading the clock so the
    measurement covers actual execution, not async dispatch. The first
    ``warmup`` stops (default 1: the trace+compile step) are excluded from
    the throughput aggregate — compile time would otherwise dominate short
    runs and corrupt the steps/sec headline metric.
    """
    warmup: int = 1
    t0: float = 0.0
    elapsed: float = 0.0
    steps: int = 0
    warmup_s: float = 0.0
    _seen: int = 0

    def start(self) -> None:
        self.t0 = time.perf_counter()

    def stop(self, result: Any = None) -> float:
        return self.stop_many(result, 1)

    @property
    def warming(self) -> bool:
        """Still inside the warmup stops (i.e. compile not yet absorbed)."""
        return self._seen < self.warmup

    def stop_many(self, result: Any, n: int) -> float:
        """One fence covering ``n`` dispatched steps (the train loop fences
        at logging boundaries, not per step — a per-step fence serializes
        host and device and costs a full pipeline drain on tunneled
        backends). The first group absorbs compile and counts as warmup."""
        if n <= 0:
            return 0.0
        if result is not None:
            # fence via host TRANSFER, not block_until_ready: on tunneled
            # PJRT backends the latter can return before execution completes
            with trace_lib.span("fence", cat="dispatch", steps=n):
                jax.device_get(result)
        dt = time.perf_counter() - self.t0
        self._seen += 1
        if self._seen <= self.warmup:
            self.warmup_s += dt
        else:
            self.elapsed += dt
            self.steps += n
        return dt

    def split(self) -> Dict[str, Any]:
        """Compile-vs-run wall split for the metrics stream: the warmup
        fence group absorbs trace+compile (near-zero when the persistent
        compilation cache hits — the pair makes cache effectiveness and
        steady-state dispatch separately visible), ``run_s`` covers the
        counted steady-state steps. FULL precision: downstream MFU math
        divides by ``run_s``, and 3-decimal rounding quantized fast CPU
        test runs to zero — round only for human display."""
        return {"compile_warmup_s": self.warmup_s,
                "run_s": self.elapsed, "steps": self.steps}

    def steps_per_sec(self) -> float:
        return self.steps / self.elapsed if self.elapsed > 0 else 0.0

    def steps_per_sec_per_chip(self) -> float:
        return self.steps_per_sec() / max(jax.device_count(), 1)


@dataclass
class MetricsLogger:
    """JSONL metrics stream, rank-0 only (structured logging the reference
    lacked — its observability was stdout through SLURM log files,
    SURVEY.md §5.5).

    Writes are BUFFERED: ``log()`` on the step path only serialises the
    record into memory; file I/O happens at explicit ``flush()`` points
    (the train loop flushes at epoch ends) and on ``close()``. A
    per-record ``write()+flush()`` put filesystem latency — NFS-mounted
    save dirs are the norm on pods — inside the step loop's timed fence
    windows, where it read as training slowdown in ``StepTimer``.

    CRASH SAFETY: buffering must not mean "lost on death" — the runs
    where metrics matter most are exactly the ones that die between
    flushes. An ``atexit`` hook flushes the tail on any interpreter exit
    (unhandled exception included), and the flight-recorder watchdog
    flushes from its stall dump; a lock makes that cross-thread flush
    safe against the main thread's concurrent ``log()``.

    ``extra`` is stamped into EVERY record (under the record's own
    keys — a record naming ``requeue_attempt`` itself wins): the run
    correlation id + requeue attempt land on every line, so artifacts
    from different attempts of one requeue loop stay correlatable.
    ``emitter`` is the live-telemetry fan-out (obs.live
    ``TelemetryEmitter``): when set, every record ALSO goes onto the
    emitter's bounded non-blocking queue — one ``is not None`` check
    when unset, so ``--live off`` costs nothing.
    """
    path: Optional[str] = None
    _fh: Optional[IO] = None
    history: List[Dict] = field(default_factory=list)
    _buf: List[str] = field(default_factory=list)
    extra: Dict[str, Any] = field(default_factory=dict)
    emitter: Any = None

    def __post_init__(self) -> None:
        import atexit
        import threading
        self._lock = threading.Lock()
        # bound method identity is stable, so close() can unregister it
        atexit.register(self.flush)

    def log(self, **kv) -> None:
        if jax.process_index() != 0:
            return
        # both clocks on every record: wall ``ts`` for humans/dashboards,
        # monotonic ``mono`` (same perf_counter timebase as the span
        # tracer's microsecond stamps) so the offline report CLI aligns
        # metrics with trace spans without trusting NTP
        rec = {"ts": time.time(), "mono": time.perf_counter(),
               **self.extra, **kv}
        with self._lock:
            self.history.append(rec)
            if self.path:
                self._buf.append(json.dumps(rec))
        if self.emitter is not None:
            # live fan-out, OUTSIDE the lock: emit() is a put_nowait
            # that never blocks or raises (obs.live drop-not-block)
            self.emitter.emit(rec)

    def flush(self) -> None:
        """Write buffered records out — called off the step path (epoch
        ends, run end), from the watchdog's stall dump, and from the
        atexit hook, so JSONL I/O never lands inside a timed window and
        a dying run never loses its buffered tail."""
        with self._lock:
            if not (self.path and self._buf):
                return
            if self._fh is None:
                d = os.path.dirname(self.path)
                if d:
                    os.makedirs(d, exist_ok=True)
                self._fh = open(self.path, "a")
            self._fh.write("\n".join(self._buf) + "\n")
            self._fh.flush()
            self._buf.clear()

    def close(self) -> None:
        import atexit
        self.flush()
        with self._lock:
            if self._fh:
                self._fh.close()
                self._fh = None
        # a closed logger must not be re-flushed at interpreter exit
        # (the file handle is gone; long-lived processes would also leak
        # one registration per run otherwise)
        try:
            atexit.unregister(self.flush)
        except Exception:
            pass


@dataclass
class StagingStats:
    """Host-side accounting of the epoch staging pipeline
    (train._superstep_epoch): how many bytes were staged, the peak
    resident staging footprint, and how much wall time the host spent
    BLOCKED on a slab that compute was already waiting for.

    ``wait_s`` is the honest exposure metric: the streaming loop fences
    compute at slab boundaries, so by the time it blocks on the next
    slab's readiness the device is idle — any time spent there is
    host→device transfer the pipeline failed to hide behind the previous
    slab's compute. ``overlap_fraction`` folds that into one number for
    the verdict/metrics stream: 1.0 = all steady-state H2D hidden.
    """
    streamed: bool = False
    slabs: int = 0
    staged_bytes: int = 0      # cumulative per-device H2D bytes
    resident_bytes: int = 0
    peak_bytes: int = 0
    stage_host_s: float = 0.0  # host time materialising + dispatching slabs
    wait_s: float = 0.0        # host blocked on an un-arrived slab

    def note_staged(self, nbytes: int, host_s: float) -> None:
        self.slabs += 1
        self.staged_bytes += nbytes
        self.resident_bytes += nbytes
        self.peak_bytes = max(self.peak_bytes, self.resident_bytes)
        self.stage_host_s += host_s

    def note_released(self, nbytes: int) -> None:
        self.resident_bytes = max(0, self.resident_bytes - nbytes)

    def note_wait(self, slab) -> float:
        """Block until ``slab``'s transfer lands; account the exposed
        time. Called with the previous slab's compute already drained."""
        t0 = time.perf_counter()
        with trace_lib.span("slab_wait", cat="staging"):
            jax.block_until_ready(slab)
        dt = time.perf_counter() - t0
        self.wait_s += dt
        return dt

    def overlap_fraction(self, run_s: float) -> Optional[float]:
        """Fraction of steady-state wall time NOT exposed to staging
        waits; None when nothing streamed (fast path: one slab, whose
        transfer overlaps trace+compile by construction)."""
        if not self.streamed or run_s <= 0:
            return None
        return max(0.0, min(1.0, 1.0 - self.wait_s / run_s))

    def split(self) -> Dict[str, Any]:
        """Staging-vs-compute fields for the ``kind=timing`` record."""
        return {"staging_streamed": self.streamed,
                "staging_slabs": self.slabs,
                "staged_bytes": self.staged_bytes,
                "staged_bytes_peak": self.peak_bytes,
                "stage_host_s": round(self.stage_host_s, 3),
                "stage_wait_s": round(self.wait_s, 3)}


def device_kind() -> str:
    try:
        return jax.devices()[0].device_kind
    except Exception:
        return "unknown"
