from tpudist.bench.sweep import run_sweep, sweep_sizes

__all__ = ["run_sweep", "sweep_sizes"]
