"""Step profiler: where does the train step's time actually go?

The reference has no profiling at all (SURVEY.md §5.1 — its only timing is
CI's 10-second job polling); ``--profile-dir`` already captures raw
``jax.profiler`` traces for TensorBoard. This tool closes the loop ON the
TPU host with no UI: it traces a few steps of the configured workload,
parses the XLA op stats out of the xplane protobuf, and prints a
per-category and per-op table with achieved FLOP rates and memory
bandwidths — the exact analysis that found the RoPE HBM round-trip this
framework's flash kernels now avoid.

Run:  python -m tpudist.bench.profile [--model transformer] [--steps 5]
          [any tpudist.train model/shape flags] [--out profile.json]

Requires the ``xprof`` package (ships with the tensorboard profiler
plugin) for trace parsing; exits with a clear message when absent. The
trace itself always lands in ``--trace-dir`` for TensorBoard regardless.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
from collections import defaultdict
from typing import Optional


def parse_hlo_stats(trace_dir: str):
    """xplane.pb files under ``trace_dir`` → list of per-op dicts."""
    try:
        from xprof.convert import raw_to_tool_data
    except ImportError as e:
        raise RuntimeError(
            "trace parsing needs the 'xprof' package (tensorboard profiler "
            "plugin); the raw trace is in "
            f"{trace_dir} for TensorBoard") from e
    paths = glob.glob(os.path.join(trace_dir, "plugins/profile/*/*.xplane.pb"))
    if not paths:
        raise RuntimeError(f"no xplane.pb found under {trace_dir}")
    data, _ = raw_to_tool_data.xspace_to_tool_data(paths, "hlo_stats", {})
    table = json.loads(data.decode() if isinstance(data, bytes) else data)
    cols = [c["id"] for c in table["cols"]]
    return [dict(zip(cols, (c.get("v") for c in row["c"])))
            for row in table["rows"]]


def summarize(ops, n_steps: int, top: int = 15) -> dict:
    """Aggregate op stats into per-category and top-op tables (µs/step)."""
    by_cat = defaultdict(float)
    total = 0.0
    for op in ops:
        t = float(op.get("total_self_time") or 0) / n_steps
        by_cat[op.get("category")] += t
        total += t
    top_ops = sorted(ops, key=lambda o: -float(o.get("total_self_time")
                                               or 0))[:top]
    return {
        "total_us_per_step": round(total, 1),
        "by_category_us": {k: round(v, 1) for k, v in
                           sorted(by_cat.items(), key=lambda kv: -kv[1])},
        "top_ops": [{
            "us_per_step": round(float(o.get("total_self_time") or 0)
                                 / n_steps, 1),
            "category": o.get("category"),
            "name": o.get("hlo_op_name"),
            "bound_by": o.get("bound_by"),
            "gflops_per_sec": o.get("model_flop_rate"),
            "mem_bw_gbps": o.get("measured_memory_bw"),
        } for o in top_ops],
    }


def profile_step(cfg, trace_dir: str, n_steps: int = 5):
    """Trace ``n_steps`` steady-state train steps of ``cfg``'s workload."""
    import jax

    from tpudist import data as data_lib
    from tpudist import engine
    from tpudist.parallel import build_mesh
    from tpudist.parallel import sharding as shd

    mesh = build_mesh(cfg.parallel)
    state = engine.init_state(jax.random.PRNGKey(cfg.seed), cfg, mesh)
    step = engine.make_train_step(cfg, mesh)
    if cfg.model.name == "mlp":
        x, y = data_lib.make_synthetic_data(
            cfg.batch_size, cfg.data.n_features, cfg.data.seed)
        batch = shd.put_batch(mesh, (x, y))
    else:
        toks = data_lib.make_synthetic_tokens(
            cfg.batch_size, cfg.model.max_seq_len + 1,
            cfg.model.vocab_size, cfg.data.seed)
        batch = shd.put_batch(mesh, (toks,))
    for _ in range(3):                       # compile + warm
        state, loss = step(state, batch)
    float(loss)
    jax.profiler.start_trace(trace_dir)
    for _ in range(n_steps):
        state, loss = step(state, batch)
    float(loss)                              # fence inside the trace
    jax.profiler.stop_trace()


def main(argv: Optional[list] = None) -> int:
    from tpudist.config import parse_args
    from tpudist.utils import maybe_force_platform, tune_tpu
    maybe_force_platform()
    tune_tpu()

    p = argparse.ArgumentParser(add_help=False)
    p.add_argument("--steps", type=int, default=5)
    p.add_argument("--top", type=int, default=15)
    p.add_argument("--trace-dir", type=str, default=None)
    p.add_argument("--out", type=str, default=None,
                   help="also write the summary as JSON here")
    own, rest = p.parse_known_args(argv)
    if own.steps < 1:
        p.error("--steps must be >= 1")
    cfg = parse_args(rest)

    trace_dir = own.trace_dir or tempfile.mkdtemp(prefix="tpudist_prof_")
    profile_step(cfg, trace_dir, n_steps=own.steps)
    try:
        ops = parse_hlo_stats(trace_dir)
    except RuntimeError as e:
        print(f"tpudist.bench.profile: {e}", file=sys.stderr)
        return 1
    s = summarize(ops, own.steps, top=own.top)

    print(f"trace: {trace_dir}")
    print(f"total: {s['total_us_per_step']:.0f} us/step")
    print(f"{'us/step':>9}  {'%':>5}  category")
    denom = s["total_us_per_step"] or 1.0   # all-zero times: CPU xplanes
    for cat, us in s["by_category_us"].items():
        print(f"{us:9.0f}  {100 * us / denom:5.1f}  {cat}")
    print(f"\n{'us/step':>9}  {'bound':>8}  {'GF/s':>8}  {'GB/s':>7}  op")
    for o in s["top_ops"]:
        print(f"{o['us_per_step']:9.0f}  {str(o['bound_by'])[:8]:>8}  "
              f"{str(o['gflops_per_sec'])[:8]:>8}  "
              f"{str(o['mem_bw_gbps'])[:7]:>7}  {o['name']}")
    if own.out:
        with open(own.out, "w") as f:
            json.dump(s, f, indent=1)
        print(f"\nwrote {own.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
