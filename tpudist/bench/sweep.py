"""Collective bandwidth sweep (BASELINE.json config #4).

The measured analogue of "did NCCL work" — the reference only ever observed
its collectives as pass/fail through the training job; this sweeps message
sizes 1MB→1GB per collective kind and reports bus bandwidth and % of the
hardware's theoretical ring peak.

Run:  python -m tpudist.bench.sweep [--kinds all_reduce,...] [--axis data]
                                    [--min-mb 1] [--max-mb 1024]
                                    [--min-pct-peak 90] [--verdict-path p]
                                    [--out sweep.jsonl]

The sweep is a GATE, not just a measurement (the reference turns every
signal into a hard pass/fail, ci:152-181): each collective kind's BEST
bucket must reach ``--min-pct-peak`` percent of the ICI ring peak
(latency-bound small messages are informational), else exit 1 and write
``fail`` to ``--verdict-path`` for the launcher/CI poller. ``--out`` writes
the records as clean JSONL to a file, so launcher stdout noise (ssh/gcloud
banners) never pollutes the artifact.
"""

from __future__ import annotations

import argparse
import json
from typing import List, Optional

import jax

from tpudist.config import ParallelConfig
from tpudist.metrics import device_kind, log0
from tpudist.ops import collectives
from tpudist.parallel import build_mesh

# Approximate per-chip ICI ring peaks, GB/s of bus bandwidth along a 1-D
# bidirectional ring (2 links active). Public figures: v4 ≈ 2×45, v5e ≈
# 2×50, v5p ≈ 2×100 GB/s per link-direction. Used only to report % of
# peak; absolute GB/s is always printed.
RING_PEAK_GBPS = {
    "TPU v4": 90.0,
    "TPU v5 lite": 100.0,
    "TPU v5e": 100.0,
    "TPU v5": 200.0,
    "TPU v5p": 200.0,
    "TPU v6 lite": 180.0,
}


def ring_peak_gbps(kind_name: Optional[str] = None) -> Optional[float]:
    name = kind_name or device_kind()
    for k, v in sorted(RING_PEAK_GBPS.items(), key=lambda kv: -len(kv[0])):
        if name.startswith(k):
            return v
    return None


def sweep_sizes(min_mb: float = 1, max_mb: float = 1024) -> List[int]:
    """1MB → 1GB in ×4 steps (6 buckets at defaults)."""
    sizes, s = [], int(min_mb * 2**20)
    top = int(max_mb * 2**20)
    while s <= top:
        sizes.append(s)
        s *= 4
    return sizes


def axis_fabric(mesh, axis: str) -> str:
    """Label a mesh axis ``ici`` or ``dcn``. The implementation moved to
    :func:`tpudist.parallel.mesh.axis_fabric` (an axis's fabric is a
    mesh property, now also consumed by the devtime per-fabric comm
    grading and the overlap bench — and it honors the scripted
    ``TPUDIST_SLICE_MAP`` 2-slice DCN stand-in); this alias keeps the
    sweep's documented surface."""
    from tpudist.parallel import mesh as mesh_lib
    return mesh_lib.axis_fabric(mesh, axis)


def collectives_artifact(records: List[dict]) -> dict:
    """BENCH_COLLECTIVES.json on the same harness shape as the other
    BENCH_* artifacts: one headline metric — the best all-reduce bus
    bandwidth, the fabric-acceptance number BASELINE.json names — and
    the full per-kind per-size rows in ``detail``. When the sweep did
    not include all_reduce, the headline names the kind it actually
    measured instead of mislabeling another kind's bandwidth. Axis and
    fabric come from the records themselves (every row carries them),
    so there is exactly one derivation."""
    kind = "all_reduce"
    if not any(r["kind"] == kind for r in records):
        kind = max(records, key=lambda r: r["bus_gbps"])["kind"] \
            if records else "all_reduce"
    best = max((r["bus_gbps"] for r in records if r["kind"] == kind),
               default=0.0)
    return {
        "metric": f"collective_{kind}_best_bus_gbps",
        "value": round(best, 4),
        "unit": f"GB/s bus bandwidth (best {kind} bucket)",
        "detail": {
            "device": device_kind(),
            "n_devices": records[0]["n_devices"] if records else 0,
            "axis": records[0]["axis"] if records else None,
            "fabric": records[0]["fabric"] if records else None,
            "kinds": sorted({r["kind"] for r in records}),
            "rows": records,
        },
    }


def write_collectives_artifact(records: List[dict], path: str) -> dict:
    """The ONE writer of BENCH_COLLECTIVES.json — `bench.py
    --collective-sweep` (CI/dev) and this module's ``--bench-out``
    (pods, where bench.py is not shipped) both land here, so the two
    artifacts cannot drift."""
    art = collectives_artifact(records)
    with open(path, "w") as f:
        json.dump(art, f, indent=1)
    return art


def run_sweep(kinds=("all_reduce",), axis: str = "data", *,
              min_mb: float = 1, max_mb: float = 1024, iters: int = 10,
              peak_gbps: Optional[float] = None) -> List[dict]:
    """Returns one record per (kind, size): message size, time, algo/bus
    GB/s, % of ring peak (None off-TPU or unknown chip). ``peak_gbps``
    overrides the built-in chip table — the operator escape hatch for a
    chip generation RING_PEAK_GBPS doesn't know yet."""
    mesh = build_mesh(ParallelConfig())
    n = mesh.shape[axis]
    peak = peak_gbps or ring_peak_gbps()
    fabric = axis_fabric(mesh, axis)
    out = []
    for kind in kinds:
        for size in sweep_sizes(min_mb, max_mb):
            t = collectives.time_collective(kind, mesh, axis,
                                            message_bytes=size, iters=iters)
            rec = {
                "kind": kind, "n_devices": n,
                "axis": axis, "fabric": fabric,
                "message_bytes": t.message_bytes,
                "mean_s": t.mean_s, "min_s": t.min_s,
                "algo_gbps": t.algo_gbps, "bus_gbps": t.bus_gbps,
                "pct_of_ring_peak": (100 * t.bus_gbps / peak
                                     if peak and n > 1 else None),
            }
            out.append(rec)
            log0(json.dumps(rec))
    return out


def gate(records: List[dict], min_pct_peak: float) -> dict:
    """Apply the bandwidth acceptance gate: per collective kind, the best
    bucket's ``pct_of_ring_peak`` must reach ``min_pct_peak``.

    Returns {"ok": bool|None, "per_kind": {kind: best_pct}, "reason": str}.
    ``ok`` is None (gate not applicable, NOT a pass) when nothing could be
    measured against a peak — single-device mesh or unknown chip."""
    per_kind: dict = {}
    for r in records:
        if r["pct_of_ring_peak"] is None:
            continue
        best = per_kind.get(r["kind"])
        if best is None or r["pct_of_ring_peak"] > best:
            per_kind[r["kind"]] = r["pct_of_ring_peak"]
    if not per_kind:
        return {"ok": None, "per_kind": {},
                "reason": "no gateable records (single device or unknown "
                          "chip peak)"}
    bad = {k: v for k, v in per_kind.items() if v < min_pct_peak}
    if bad:
        return {"ok": False, "per_kind": per_kind,
                "reason": f"below {min_pct_peak}% of ring peak: " + ", ".join(
                    f"{k}={v:.1f}%" for k, v in sorted(bad.items()))}
    return {"ok": True, "per_kind": per_kind,
            "reason": f"all kinds ≥ {min_pct_peak}% of ring peak"}


def main(argv=None) -> int:
    from tpudist.utils import maybe_force_platform, tune_tpu
    maybe_force_platform()
    tune_tpu()
    # multi-host slices need distributed init (all workers run the sweep;
    # the collectives span the full pod); single-host this is a no-op
    from tpudist.parallel import distributed
    distributed.initialize()
    p = argparse.ArgumentParser()
    p.add_argument("--kinds", type=str, default="all_reduce")
    p.add_argument("--axis", type=str, default="data")
    p.add_argument("--min-mb", type=float, default=1)
    p.add_argument("--max-mb", type=float, default=1024)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--min-pct-peak", type=float, default=90.0,
                   help="acceptance threshold: best bucket per kind must "
                        "reach this %% of the ICI ring peak (BASELINE.md); "
                        "<=0 disables the gate")
    p.add_argument("--peak-gbps", type=float, default=None,
                   help="operator override for the ICI ring peak (GB/s) — "
                        "gates against this instead of the built-in chip "
                        "table; required to gate on a chip kind the table "
                        "doesn't know")
    p.add_argument("--verdict-path", type=str, default=None,
                   help="write success/fail here (local path or gs://) — "
                        "the reference's job_status.txt protocol")
    p.add_argument("--out", type=str, default=None,
                   help="also write records as clean JSONL to this file")
    p.add_argument("--bench-out", type=str, default=None,
                   help="also write the BENCH_COLLECTIVES.json artifact "
                        "here (the BASELINE.json harness shape: headline "
                        "metric + per-kind per-size rows with ICI/DCN "
                        "fabric labels; bench.py --collective-sweep and "
                        "the launcher share this path)")
    # strict: a mistyped flag must error, not silently run a full 1GB sweep
    args = p.parse_args(argv)
    records = run_sweep(tuple(args.kinds.split(",")), args.axis,
                        min_mb=args.min_mb, max_mb=args.max_mb,
                        iters=args.iters, peak_gbps=args.peak_gbps)
    if args.out and jax.process_index() == 0:
        with open(args.out, "w") as f:
            for r in records:
                f.write(json.dumps(r) + "\n")
    if args.bench_out and jax.process_index() == 0:
        write_collectives_artifact(records, args.bench_out)

    if args.min_pct_peak <= 0:
        return 0
    g = gate(records, args.min_pct_peak)
    log0(json.dumps({"sweep_gate": g}))
    from tpudist import verdict
    if g["ok"] is None:
        # Nothing could be compared against a peak (unknown chip kind with
        # no --peak-gbps override, or a single-device mesh). Absolute GB/s
        # was still measured and recorded; publish the distinct UNGATEABLE
        # status (exit 3) so the first run on a new TPU generation doesn't
        # read as a bandwidth regression — a real below-threshold result
        # stays a hard fail. Still nonzero: absent evidence must not
        # publish success (the reference's missing-status-file stance).
        if args.verdict_path:
            verdict.write_final_status(args.verdict_path, verdict.UNGATEABLE)
        return 3
    if args.verdict_path:
        verdict.write_final_verdict(args.verdict_path, g["ok"] is True)
    return 0 if g["ok"] is True else 1


if __name__ == "__main__":
    import sys
    sys.exit(main())
