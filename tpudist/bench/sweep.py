"""Collective bandwidth sweep (BASELINE.json config #4).

The measured analogue of "did NCCL work" — the reference only ever observed
its collectives as pass/fail through the training job; this sweeps message
sizes 1MB→1GB per collective kind and reports bus bandwidth and % of the
hardware's theoretical ring peak.

Run:  python -m tpudist.bench.sweep [--kinds all_reduce,...] [--axis data]
                                    [--min-mb 1] [--max-mb 1024]
"""

from __future__ import annotations

import argparse
import json
from typing import List, Optional

import jax

from tpudist.config import ParallelConfig
from tpudist.metrics import device_kind, log0
from tpudist.ops import collectives
from tpudist.parallel import build_mesh

# Approximate per-chip ICI ring peaks, GB/s of bus bandwidth along a 1-D
# bidirectional ring (2 links active). Public figures: v4 ≈ 2×45, v5e ≈
# 2×50, v5p ≈ 2×100 GB/s per link-direction. Used only to report % of
# peak; absolute GB/s is always printed.
RING_PEAK_GBPS = {
    "TPU v4": 90.0,
    "TPU v5 lite": 100.0,
    "TPU v5e": 100.0,
    "TPU v5": 200.0,
    "TPU v5p": 200.0,
    "TPU v6 lite": 180.0,
}


def ring_peak_gbps(kind_name: Optional[str] = None) -> Optional[float]:
    name = kind_name or device_kind()
    for k, v in sorted(RING_PEAK_GBPS.items(), key=lambda kv: -len(kv[0])):
        if name.startswith(k):
            return v
    return None


def sweep_sizes(min_mb: float = 1, max_mb: float = 1024) -> List[int]:
    """1MB → 1GB in ×4 steps (6 buckets at defaults)."""
    sizes, s = [], int(min_mb * 2**20)
    top = int(max_mb * 2**20)
    while s <= top:
        sizes.append(s)
        s *= 4
    return sizes


def run_sweep(kinds=("all_reduce",), axis: str = "data", *,
              min_mb: float = 1, max_mb: float = 1024, iters: int = 10
              ) -> List[dict]:
    """Returns one record per (kind, size): message size, time, algo/bus
    GB/s, % of ring peak (None off-TPU or unknown chip)."""
    mesh = build_mesh(ParallelConfig())
    n = mesh.shape[axis]
    peak = ring_peak_gbps()
    out = []
    for kind in kinds:
        for size in sweep_sizes(min_mb, max_mb):
            t = collectives.time_collective(kind, mesh, axis,
                                            message_bytes=size, iters=iters)
            rec = {
                "kind": kind, "n_devices": n,
                "message_bytes": t.message_bytes,
                "mean_s": t.mean_s, "min_s": t.min_s,
                "algo_gbps": t.algo_gbps, "bus_gbps": t.bus_gbps,
                "pct_of_ring_peak": (100 * t.bus_gbps / peak
                                     if peak and n > 1 else None),
            }
            out.append(rec)
            log0(json.dumps(rec))
    return out


def main(argv=None) -> int:
    from tpudist.utils import maybe_force_platform
    maybe_force_platform()
    p = argparse.ArgumentParser()
    p.add_argument("--kinds", type=str, default="all_reduce")
    p.add_argument("--axis", type=str, default="data")
    p.add_argument("--min-mb", type=float, default=1)
    p.add_argument("--max-mb", type=float, default=1024)
    p.add_argument("--iters", type=int, default=10)
    # strict: a mistyped flag must error, not silently run a full 1GB sweep
    args = p.parse_args(argv)
    run_sweep(tuple(args.kinds.split(",")), args.axis,
              min_mb=args.min_mb, max_mb=args.max_mb, iters=args.iters)
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
