from tpudist.ops import collectives, ring_attention, ulysses

__all__ = ["collectives", "ring_attention", "ulysses"]
