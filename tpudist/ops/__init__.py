from tpudist.ops import collectives, ring_attention

__all__ = ["collectives", "ring_attention"]
