"""Pallas TPU kernel: flash attention (fwd + custom-VJP bwd), fused RoPE.

The measured compute hot spot of the transformer workload after the LM head
is attention: the dense path (models/transformer.py::_attention)
materialises the (batch, heads, seq, seq) score tensor in HBM — ~8.5 ms of
the 151 ms bench step per layer on v5e at batch 24/seq 512, against ~0.8 ms
of ideal matmul FLOPs. This kernel streams kv blocks through VMEM with an
online softmax (scores never touch HBM) and recomputes them in the backward
pass (two kernels: dq with kv innermost, dk/dv with q innermost) — the
standard flash-attention schedule, written for the MXU.

Three TPU-specific schedule choices:
  * Pallas grid programs execute **sequentially** on the TensorCore, so
    per-program overhead is paid ``grid-size`` times. A (batch·heads)-sized
    grid dimension at seq 512 means ~1500 programs doing ~0.2 µs of matmul
    each — measured slower than the dense path. Instead, ``block_b``
    batch·head slices are folded into every program as one batched matmul
    on the MXU (``dot_general`` with a batch dimension).
  * Causal masking skips fully-masked blocks: the kv grid dimension is
    innermost, and a block is computed only when its kv columns intersect
    the causal triangle of the q rows (j·block_k ≤ (i+1)·block_q − 1).
  * RoPE is applied INSIDE the kernels (pass ``cos``/``sin``): rotating
    q/k blocks in VMEM removes the rotated tensors' HBM round-trip AND
    their storage as VJP residuals — profiled at ~10 ms/step of loop
    fusions at bench shapes. The backward kernels re-rotate q/k for the
    score recompute and counter-rotate the dq/dk accumulators on the way
    out (the rotation is orthogonal: Rᵀ = R(−θ)).

The reference has no attention anywhere (its model is a 20-feature MLP,
reference train.py:26-36); this kernel serves the north-star transformer
(BASELINE.json config #5). All reductions and accumulations run in f32
regardless of input dtype; matmul operands are cast to the input dtype so
the contractions run native on the MXU with f32 accumulators.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpudist.utils import compat

NEG = -1e30

# The kernels' working set (double-buffered q/k/v/out blocks + f32
# accumulators) exceeds the 16 MiB default scoped-VMEM budget at the
# default block sizes (measured 18 MB at block_b 8, blocks 512). Carrying
# the limit on the pallas_call itself makes the kernels self-contained —
# they compile whether or not the process set
# --xla_tpu_scoped_vmem_limit_kib (tpudist.utils.tune_tpu); v5e VMEM is
# 128 MiB total.
_COMPILER_PARAMS = compat.tpu_compiler_params(
    dimension_semantics=("parallel", "arbitrary", "arbitrary"),
    vmem_limit_bytes=100 * 1024 * 1024,
)

# dot_general dimension numbers for (nb, m, k) x (nb, n, k) -> (nb, m, n)
_BMM_NT = (((2,), (2,)), ((0,), (0,)))
# (nb, m, k) x (nb, k, n) -> (nb, m, n)
_BMM_NN = (((2,), (1,)), ((0,), (0,)))
# (nb, k, m) x (nb, k, n) -> (nb, m, n)
_BMM_TN = (((1,), (1,)), ((0,), (0,)))


def _cdiv(a: int, b: int) -> int:
    return (a + b - 1) // b


def _expand_rep(x, rep: int):
    """Expand a compact (nb/rep, t, d) kv block to the q-head layout
    (nb, t, d) — inside VMEM, where the copy is registers, not the HBM
    round-trip the old pre-kernel ``jnp.repeat`` paid (r2 advisor
    finding). Consecutive q-head slices share one kv head, matching the
    (batch, head)-flattened index order."""
    if rep == 1:
        return x
    return jnp.repeat(x, rep, axis=0)


def _group_sum(x, rep: int):
    """(nb, t, d) f32 per-q-head partials → compact (nb/rep, t, d) kv-head
    sums: the transpose of :func:`_expand_rep` (exact dk/dv group-sum)."""
    if rep == 1:
        return x
    nb, t, d = x.shape
    return x.reshape(nb // rep, rep, t, d).sum(axis=1)


def _needed(i, j, block_q: int, block_k: int, causal: bool):
    """Does kv block j intersect the causal triangle of q block i?"""
    if not causal:
        return jnp.bool_(True)
    return j * block_k <= i * block_q + block_q - 1


def _last_j(i, nj, block_q: int, block_k: int, causal: bool):
    """Last kv block q block i consumes (the causal diagonal's block)."""
    if not causal:
        return nj - 1
    return jnp.minimum((i * block_q + block_q - 1) // block_k, nj - 1)


def _rot(x, cos_ref, sin_ref):
    """RoPE-rotate a (nb, t, d) block; cos/sin refs hold (t, d/2)."""
    d2 = x.shape[-1] // 2
    c = cos_ref[:][None].astype(x.dtype)
    s = sin_ref[:][None].astype(x.dtype)
    x1, x2 = x[..., :d2], x[..., d2:]
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)


def _rot_t(x, cos_ref, sin_ref):
    """Transpose (inverse) rotation, for dq/dk cotangents (f32)."""
    d2 = x.shape[-1] // 2
    c = cos_ref[:][None].astype(x.dtype)
    s = sin_ref[:][None].astype(x.dtype)
    x1, x2 = x[..., :d2], x[..., d2:]
    return jnp.concatenate([x1 * c + x2 * s, x2 * c - x1 * s], axis=-1)


def _block_scores(q, k, scale, i, j, block_q, block_k, causal):
    """(nb, block_q, block_k) f32 scaled scores, causally masked.

    The mask is applied UNCONDITIONALLY even though only diagonal-
    straddling blocks need it: a scalar ``lax.cond`` skipping it on
    interior blocks was tried (r5) and measured a 16% step REGRESSION at
    seq 8192 (421→489 ms) — the branch materialises ``s`` and breaks
    Mosaic's fusion of the iota/compare/select into the matmul's output
    pipeline, costing far more than the masked elementwise work saves."""
    s = jax.lax.dot_general(q, k, _BMM_NT,
                            preferred_element_type=jnp.float32) * scale
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) \
            + i * block_q
        cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2) \
            + j * block_k
        s = jnp.where(cols <= rows, s, NEG)
    return s


# ---------------------------------------------------------------- forward


def _fwd_kernel(*refs, scale: float, block_q: int, block_k: int,
                causal: bool, rope: bool, single: bool, rep: int):
    if rope:
        (q_ref, k_ref, v_ref, cq_ref, sq_ref, ck_ref, sk_ref,
         o_ref, lse_ref, *scratch) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, *scratch = refs
    i, j = pl.program_id(1), pl.program_id(2)
    nj = pl.num_programs(2)

    if single:
        # One kv block per q block (the grid's kv dim is 1): plain softmax,
        # no online-rescale bookkeeping and no f32 accumulator scratch —
        # measured meaningfully faster than the general path at seq 512
        # (no zero-init pass, no acc read-modify-write, no rescale VPU work)
        q = q_ref[:]
        k = _expand_rep(k_ref[:], rep)
        v = _expand_rep(v_ref[:], rep)
        if rope:
            q = _rot(q, cq_ref, sq_ref)
            k = _rot(k, ck_ref, sk_ref)
        s = _block_scores(q, k, scale, i, j, block_q, block_k, causal)
        m = jnp.max(s, axis=2, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=2, keepdims=True)
        acc = jax.lax.dot_general(p.astype(v.dtype), v, _BMM_NN,
                                  preferred_element_type=jnp.float32)
        o_ref[:] = (acc / l).astype(o_ref.dtype)
        lse_ref[:] = m + jnp.log(l)
        return

    m_ref, l_ref, acc_ref = scratch

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(_needed(i, j, block_q, block_k, causal))
    def _compute():
        q = q_ref[:]
        k = _expand_rep(k_ref[:], rep)
        v = _expand_rep(v_ref[:], rep)
        if rope:
            q = _rot(q, cq_ref, sq_ref)
            k = _rot(k, ck_ref, sk_ref)
        s = _block_scores(q, k, scale, i, j, block_q, block_k, causal)
        m_prev = m_ref[:]                              # (nb, block_q, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
        p = jnp.exp(s - m_new)                         # masked cells → 0
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=2, keepdims=True)
        m_ref[:] = m_new
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, _BMM_NN,
            preferred_element_type=jnp.float32)        # (nb, block_q, d)

    # Writing mid-revisit is fine — the out block stays in VMEM until the
    # q index advances.
    @pl.when(j == _last_j(i, nj, block_q, block_k, causal))
    def _finish():
        l = l_ref[:]
        o_ref[:] = (acc_ref[:] / l).astype(o_ref.dtype)
        lse_ref[:] = m_ref[:] + jnp.log(l)


def _rope_specs(d: int, block_q: int, block_k: int, transposed: bool):
    """cos/sin blockspecs for the q-row and k-row tables: (block, d/2)
    slices of the (s, d/2) tables, indexed by the q (resp. kv) grid dim."""
    d2 = d // 2
    if transposed:      # grid (b, j, i)
        qrow = pl.BlockSpec((block_q, d2), lambda b, j, i: (i, 0),
                            memory_space=pltpu.VMEM)
        krow = pl.BlockSpec((block_k, d2), lambda b, j, i: (j, 0),
                            memory_space=pltpu.VMEM)
    else:               # grid (b, i, j)
        qrow = pl.BlockSpec((block_q, d2), lambda b, i, j: (i, 0),
                            memory_space=pltpu.VMEM)
        krow = pl.BlockSpec((block_k, d2), lambda b, i, j: (j, 0),
                            memory_space=pltpu.VMEM)
    return [qrow, qrow, krow, krow]


def _fwd(q, k, v, cos, sin, *, scale, block_b, block_q, block_k, causal,
         interpret) -> Tuple[jax.Array, jax.Array]:
    bh, s, d = q.shape
    sk = k.shape[1]
    rep = bh // k.shape[0]          # grouped-query factor (1 = MHA)
    rope = cos is not None
    grid = (_cdiv(bh, block_b), _cdiv(s, block_q), _cdiv(sk, block_k))

    qspec = pl.BlockSpec((block_b, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM)
    kspec = pl.BlockSpec((block_b // rep, block_k, d),
                         lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM)
    in_specs = [qspec, kspec, kspec]
    args = [q, k, v]
    if rope:
        in_specs += _rope_specs(d, block_q, block_k, transposed=False)
        args += [cos, sin, cos, sin]
    single = grid[2] == 1
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, causal=causal, rope=rope,
                          single=single, rep=rep),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            qspec,
            pl.BlockSpec((block_b, block_q, 1), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, s, 1), jnp.float32),
        ],
        scratch_shapes=[] if single else [
            pltpu.VMEM((block_b, block_q, 1), jnp.float32),
            pltpu.VMEM((block_b, block_q, 1), jnp.float32),
            pltpu.VMEM((block_b, block_q, d), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=_COMPILER_PARAMS,
    )(*args)
    return o, lse


# --------------------------------------------------------------- backward


def _p_and_ds(q, k, v, do, lse, delta, scale, i, j, block_q, block_k,
              causal):
    """Recompute the softmax block p and its cotangent ds (both f32).

    ds = p ⊙ (dp − delta) with dp = do·vᵀ — the softmax-jacobian
    contraction folded into the row constant delta = rowsum(do ⊙ o).
    """
    s = _block_scores(q, k, scale, i, j, block_q, block_k, causal)
    p = jnp.exp(s - lse)                               # exact softmax
    dp = jax.lax.dot_general(do, v, _BMM_NT,
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta)
    return p, ds


def _dq_kernel(*refs, scale: float, block_q: int, block_k: int,
               causal: bool, rope: bool, single: bool, rep: int):
    if rope:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         cq_ref, sq_ref, ck_ref, sk_ref, dq_ref, *scratch) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, *scratch) = refs
    i, j = pl.program_id(1), pl.program_id(2)
    nj = pl.num_programs(2)

    if single:
        # one kv block per q block: dq in one shot, no accumulator scratch
        q = q_ref[:]
        k = _expand_rep(k_ref[:], rep)
        if rope:
            q = _rot(q, cq_ref, sq_ref)
            k = _rot(k, ck_ref, sk_ref)
        _, ds = _p_and_ds(q, k, _expand_rep(v_ref[:], rep), do_ref[:],
                          lse_ref[:], delta_ref[:], scale, i, j, block_q,
                          block_k, causal)
        dq = jax.lax.dot_general(ds.astype(k.dtype), k, _BMM_NN,
                                 preferred_element_type=jnp.float32) * scale
        if rope:
            dq = _rot_t(dq, cq_ref, sq_ref)
        dq_ref[:] = dq.astype(dq_ref.dtype)
        return

    acc_ref, = scratch

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(_needed(i, j, block_q, block_k, causal))
    def _compute():
        q = q_ref[:]
        k = _expand_rep(k_ref[:], rep)
        if rope:
            q = _rot(q, cq_ref, sq_ref)
            k = _rot(k, ck_ref, sk_ref)
        _, ds = _p_and_ds(q, k, _expand_rep(v_ref[:], rep), do_ref[:],
                          lse_ref[:], delta_ref[:], scale, i, j, block_q,
                          block_k, causal)
        acc_ref[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, _BMM_NN,
            preferred_element_type=jnp.float32)        # (nb, block_q, d)

    @pl.when(j == _last_j(i, nj, block_q, block_k, causal))
    def _finish():
        dq = acc_ref[:] * scale
        if rope:
            # dq was accumulated against rotated k: counter-rotate back to
            # the unrotated-q frame (Rᵀ of the q-row rotation)
            dq = _rot_t(dq, cq_ref, sq_ref)
        dq_ref[:] = dq.astype(dq_ref.dtype)


def _dkv_kernel(*refs, scale: float, block_q: int, block_k: int,
                causal: bool, rope: bool, single: bool, rep: int):
    if rope:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         cq_ref, sq_ref, ck_ref, sk_ref,
         dk_ref, dv_ref, *scratch) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, *scratch) = refs
    j, i = pl.program_id(1), pl.program_id(2)   # kv outer, q inner
    ni = pl.num_programs(2)

    if single:
        # one q block per kv block: dk/dv in one shot, no accumulators
        q, do = q_ref[:], do_ref[:]
        k = _expand_rep(k_ref[:], rep)
        if rope:
            q = _rot(q, cq_ref, sq_ref)
            k = _rot(k, ck_ref, sk_ref)
        p, ds = _p_and_ds(q, k, _expand_rep(v_ref[:], rep), do, lse_ref[:],
                          delta_ref[:], scale, i, j, block_q, block_k,
                          causal)
        dv_ref[:] = _group_sum(jax.lax.dot_general(
            p.astype(do.dtype), do, _BMM_TN,
            preferred_element_type=jnp.float32), rep).astype(dv_ref.dtype)
        dk = _group_sum(jax.lax.dot_general(
            ds.astype(q.dtype), q, _BMM_TN,
            preferred_element_type=jnp.float32), rep) * scale
        if rope:
            dk = _rot_t(dk, ck_ref, sk_ref)
        dk_ref[:] = dk.astype(dk_ref.dtype)
        return

    dk_acc, dv_acc = scratch

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(_needed(i, j, block_q, block_k, causal))
    def _compute():
        q, do = q_ref[:], do_ref[:]
        k = _expand_rep(k_ref[:], rep)
        if rope:
            q = _rot(q, cq_ref, sq_ref)
            k = _rot(k, ck_ref, sk_ref)
        p, ds = _p_and_ds(q, k, _expand_rep(v_ref[:], rep), do, lse_ref[:],
                          delta_ref[:], scale, i, j, block_q, block_k,
                          causal)
        # accumulate COMPACT (nb/rep, block_k, d): the group-sum over the
        # rep q-head slices happens here, not as an XLA transpose-of-repeat
        dv_acc[:] += _group_sum(jax.lax.dot_general(
            p.astype(do.dtype), do, _BMM_TN,
            preferred_element_type=jnp.float32), rep)
        dk_acc[:] += _group_sum(jax.lax.dot_general(
            ds.astype(q.dtype), q, _BMM_TN,
            preferred_element_type=jnp.float32), rep)

    # the final q block always attends to every kv block under causality
    @pl.when(i == ni - 1)
    def _finish():
        dk = dk_acc[:] * scale
        if rope:
            dk = _rot_t(dk, ck_ref, sk_ref)
        dk_ref[:] = dk.astype(dk_ref.dtype)
        dv_ref[:] = dv_acc[:].astype(dv_ref.dtype)


def _dqkv_kernel(*refs, scale: float, block_q: int, block_k: int,
                 causal: bool, rope: bool, rep: int):
    """Merged single-block backward: when one (q, kv) block pair covers the
    whole sequence, dq/dk/dv come out of ONE p/ds recompute instead of the
    two the split kernels pay (one score matmul, one exp sweep and one
    q/k/v/do block fetch fewer per program) — measured faster at seq 512,
    the headline-bench shape."""
    if rope:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         cq_ref, sq_ref, ck_ref, sk_ref, dq_ref, dk_ref, dv_ref) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dk_ref, dv_ref) = refs
    q, do = q_ref[:], do_ref[:]
    k = _expand_rep(k_ref[:], rep)
    if rope:
        q = _rot(q, cq_ref, sq_ref)
        k = _rot(k, ck_ref, sk_ref)
    p, ds = _p_and_ds(q, k, _expand_rep(v_ref[:], rep), do, lse_ref[:],
                      delta_ref[:], scale, 0, 0, block_q, block_k, causal)
    dq = jax.lax.dot_general(ds.astype(k.dtype), k, _BMM_NN,
                             preferred_element_type=jnp.float32) * scale
    if rope:
        dq = _rot_t(dq, cq_ref, sq_ref)
    dq_ref[:] = dq.astype(dq_ref.dtype)
    dv_ref[:] = _group_sum(jax.lax.dot_general(
        p.astype(do.dtype), do, _BMM_TN,
        preferred_element_type=jnp.float32), rep).astype(dv_ref.dtype)
    dk = _group_sum(jax.lax.dot_general(
        ds.astype(q.dtype), q, _BMM_TN,
        preferred_element_type=jnp.float32), rep) * scale
    if rope:
        dk = _rot_t(dk, ck_ref, sk_ref)
    dk_ref[:] = dk.astype(dk_ref.dtype)


def _bwd(scale, block_b, block_q, block_k, causal, interpret, res, ct):
    q, k, v, o, lse, cos, sin = res
    do, dlse = ct
    rope = cos is not None
    bh, s, d = q.shape
    bkv, sk = k.shape[0], k.shape[1]
    rep = bh // bkv                 # grouped-query factor (1 = MHA)
    # softmax-jacobian row constant, cheap elementwise fuse outside pallas.
    # An lse cotangent (callers that consume the log-sum-exp, e.g. a ring
    # merge of per-hop partials) folds in exactly here: d lse_i / d s_ij =
    # p_ij, so its score-space contribution is p·dlse — the same shape as
    # the −p·delta term, absorbed as delta − dlse.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)            # (bh, s, 1)
    delta = delta - dlse.astype(jnp.float32)

    if _cdiv(s, block_q) == 1 and _cdiv(sk, block_k) == 1:
        qspec1 = pl.BlockSpec((block_b, block_q, d), lambda b: (b, 0, 0),
                              memory_space=pltpu.VMEM)
        kspec1 = pl.BlockSpec((block_b // rep, block_k, d),
                              lambda b: (b, 0, 0),
                              memory_space=pltpu.VMEM)
        rowspec1 = pl.BlockSpec((block_b, block_q, 1), lambda b: (b, 0, 0),
                                memory_space=pltpu.VMEM)
        args1 = [q, k, v, do, lse, delta]
        in_specs1 = [qspec1, kspec1, kspec1, qspec1, rowspec1, rowspec1]
        if rope:
            d2 = d // 2
            rspec = pl.BlockSpec((block_q, d2), lambda b: (0, 0),
                                 memory_space=pltpu.VMEM)
            in_specs1 += [rspec, rspec, rspec, rspec]
            args1 += [cos, sin, cos, sin]
        dq, dk, dv = pl.pallas_call(
            functools.partial(_dqkv_kernel, scale=scale, block_q=block_q,
                              block_k=block_k, causal=causal, rope=rope,
                              rep=rep),
            grid=(_cdiv(bh, block_b),),
            in_specs=in_specs1,
            out_specs=[qspec1, kspec1, kspec1],
            out_shape=[
                jax.ShapeDtypeStruct((bh, s, d), q.dtype),
                jax.ShapeDtypeStruct((bkv, sk, d), k.dtype),
                jax.ShapeDtypeStruct((bkv, sk, d), v.dtype),
            ],
            interpret=interpret,
            compiler_params=compat.tpu_compiler_params(
                dimension_semantics=("parallel",),
                vmem_limit_bytes=100 * 1024 * 1024),
        )(*args1)
        dcos = None if cos is None else jnp.zeros_like(cos)
        dsin = None if sin is None else jnp.zeros_like(sin)
        return dq, dk, dv, dcos, dsin

    qspec = pl.BlockSpec((block_b, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM)
    kspec = pl.BlockSpec((block_b // rep, block_k, d),
                         lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM)
    rowspec = pl.BlockSpec((block_b, block_q, 1),
                           lambda b, i, j: (b, i, 0),
                           memory_space=pltpu.VMEM)
    args = [q, k, v, do, lse, delta]
    in_specs = [qspec, kspec, kspec, qspec, rowspec, rowspec]
    if rope:
        in_specs += _rope_specs(d, block_q, block_k, transposed=False)
        args += [cos, sin, cos, sin]

    single_q = _cdiv(sk, block_k) == 1
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, causal=causal, rope=rope,
                          single=single_q, rep=rep),
        grid=(_cdiv(bh, block_b), _cdiv(s, block_q), _cdiv(sk, block_k)),
        in_specs=in_specs,
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        scratch_shapes=[] if single_q else [
            pltpu.VMEM((block_b, block_q, d), jnp.float32)],
        interpret=interpret,
        compiler_params=_COMPILER_PARAMS,
    )(*args)

    # q innermost: the (nb, block_k, d) accumulators are revisited across
    # all q blocks before the kv index advances
    qspec_t = pl.BlockSpec((block_b, block_q, d), lambda b, j, i: (b, i, 0),
                           memory_space=pltpu.VMEM)
    kspec_t = pl.BlockSpec((block_b // rep, block_k, d),
                           lambda b, j, i: (b, j, 0),
                           memory_space=pltpu.VMEM)
    rowspec_t = pl.BlockSpec((block_b, block_q, 1),
                             lambda b, j, i: (b, i, 0),
                             memory_space=pltpu.VMEM)
    args_t = [q, k, v, do, lse, delta]
    in_specs_t = [qspec_t, kspec_t, kspec_t, qspec_t, rowspec_t, rowspec_t]
    if rope:
        in_specs_t += _rope_specs(d, block_q, block_k, transposed=True)
        args_t += [cos, sin, cos, sin]
    kvout = kspec_t
    single_kv = _cdiv(s, block_q) == 1
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, causal=causal, rope=rope,
                          single=single_kv, rep=rep),
        grid=(_cdiv(bh, block_b), _cdiv(sk, block_k), _cdiv(s, block_q)),
        in_specs=in_specs_t,
        out_specs=[kvout, kvout],
        out_shape=[
            jax.ShapeDtypeStruct((bkv, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bkv, sk, d), v.dtype),
        ],
        scratch_shapes=[] if single_kv else [
            pltpu.VMEM((block_b // rep, block_k, d), jnp.float32),
            pltpu.VMEM((block_b // rep, block_k, d), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=_COMPILER_PARAMS,
    )(*args_t)
    dcos = None if cos is None else jnp.zeros_like(cos)
    dsin = None if sin is None else jnp.zeros_like(sin)
    return dq, dk, dv, dcos, dsin


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash(q, k, v, cos, sin, scale, block_b, block_q, block_k, causal,
           interpret):
    """Returns (o, lse): BOTH differentiable outputs — lse's cotangent
    folds into the backward's delta constant (see _bwd). Callers that
    ignore lse get a zero dlse from autodiff, which subtracts away."""
    return _fwd(q, k, v, cos, sin, scale=scale, block_b=block_b,
                block_q=block_q, block_k=block_k, causal=causal,
                interpret=interpret)


def _flash_fwd(q, k, v, cos, sin, scale, block_b, block_q, block_k,
               causal, interpret):
    o, lse = _fwd(q, k, v, cos, sin, scale=scale, block_b=block_b,
                  block_q=block_q, block_k=block_k, causal=causal,
                  interpret=interpret)
    return (o, lse), (q, k, v, o, lse, cos, sin)


_flash.defvjp(_flash_fwd, _bwd)


def _pick_block(s: int, preferred: int) -> int | None:
    """Largest MXU-aligned block ≤ preferred that divides s."""
    for b in (preferred, 512, 256, 128):
        if b <= preferred and s % b == 0:
            return b
    return None


def _pick_block_b(bh: int, preferred: int, rep: int = 1) -> int:
    """Largest batch·head fold ≤ preferred dividing bh — and a multiple of
    the grouped-query factor, so every program's q slice covers whole kv
    groups (the compact-kv BlockSpec maps q block b to kv block b).
    ``rep`` always divides bh (rep | h | b·h), so ``rep`` itself is the
    floor."""
    nb = max(preferred, rep)
    while bh % nb or nb % rep:
        nb -= 1
    return nb


def supports(q_shape, k_shape, *, causal: bool = True, block_q: int = 512,
             block_k: int = 512) -> bool:
    """Can flash_attention handle these (b, s, h, hd) shapes? Mirrors every
    ValueError the kernel raises (call sites gate on this and fall back to
    the dense/blockwise paths), including the causal seq_q == seq_k
    requirement — the kernel's mask has no kv-offset notion."""
    _, s, h, hd = q_shape
    _, sk, kv, _ = k_shape
    return (hd % 128 == 0 and h % kv == 0
            and (not causal or s == sk)
            and _pick_block(s, block_q) is not None
            and _pick_block(sk, block_k) is not None)


def _prepare(q, k, v, causal, block_b, block_q, block_k, interpret,
             api_name: str):
    """Shared validation + (b, s, h, hd) → (b·h, s, hd) folding for both
    public entry points (one copy: the shape rules must not drift between
    them). Returns (q3, k3, v3, nb, bq, bk, interpret)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, s, h, hd = q.shape
    sk = k.shape[1]
    bq = _pick_block(s, block_q)
    bk = _pick_block(sk, block_k)
    if bq is None or bk is None or hd % 128:
        raise ValueError(
            f"{api_name} needs seq multiples of 128 and head_dim "
            f"multiples of 128, got q {q.shape}, k {k.shape}; gate call "
            f"sites on flash_attention.supports()")
    if causal and s != sk:
        # The causal mask compares unoffset absolute row/col indices, which
        # is wrong for kv-cache/cross-attention offsets (q row i should see
        # kv cols <= i + sk - s). No caller passes such shapes today; fail
        # loudly rather than mask silently wrong (r2 advisor finding).
        raise ValueError(
            f"causal=True requires seq_q == seq_k (got {s} vs {sk}): the "
            f"kernel has no notion of a kv offset")
    if h % k.shape[2]:
        raise ValueError(
            f"heads {h} not divisible by kv_heads {k.shape[2]}")
    rep = h // k.shape[2]
    nb = _pick_block_b(b * h, block_b, rep)

    def to3(x):
        nh = x.shape[2]
        return x.transpose(0, 2, 1, 3).reshape(b * nh, x.shape[1], hd)

    return to3(q), to3(k), to3(v), nb, bq, bk, interpret


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    cos: jax.Array | None = None,
                    sin: jax.Array | None = None,
                    causal: bool = True, block_b: int = 8,
                    block_q: int = 512, block_k: int = 512,
                    interpret: bool | None = None) -> jax.Array:
    """Attention without the (b, h, s, s) score tensor in HBM.

    q: (batch, seq, heads, head_dim); k/v: (batch, seq_k, kv_heads,
    head_dim) — grouped-query k/v stay COMPACT all the way into the
    kernels: the kv BlockSpecs map each q-head block to its kv-head block
    (the q-head fold is constrained to whole kv groups), the expansion
    happens in VMEM, and the dk/dv kernels group-sum back to the compact
    shape — no heads/kv_heads-times copies of k and v ever touch HBM (the
    r2 advisor finding against the old pre-kernel ``jnp.repeat``). Layout
    matches models/transformer.py::_attention, which this replaces on TPU.
    ``cos``/``sin``: optional (seq, head_dim/2) RoPE tables — when given,
    q and k are rotated inside the kernels (see module docstring); the
    tables are positional constants, their cotangent is zero.
    ``block_b`` batch·head slices share one program (sequential-grid
    amortisation); ``interpret=None`` auto-selects the pallas interpreter
    off-TPU so the same code path is CPU-testable.
    """
    b, s, h, hd = q.shape
    sk = k.shape[1]
    if cos is not None and (s != sk or cos.shape != (s, hd // 2)
                            or sin.shape != cos.shape):
        raise ValueError(
            f"rope tables must be (seq, head_dim/2) = ({s}, {hd // 2}) "
            f"with seq == seq_k, got cos {cos.shape}, sin {sin.shape}, "
            f"seq_k {sk}")
    q3, k3, v3, nb, bq, bk, interpret = _prepare(
        q, k, v, causal, block_b, block_q, block_k, interpret,
        "flash_attention")
    cosf = None if cos is None else cos.astype(jnp.float32)
    sinf = None if sin is None else sin.astype(jnp.float32)
    o, _ = _flash(q3, k3, v3, cosf, sinf, 1.0 / (hd ** 0.5),
                  nb, bq, bk, causal, interpret)
    return o.reshape(b, h, s, hd).transpose(0, 2, 1, 3)


def flash_attention_with_lse(q: jax.Array, k: jax.Array, v: jax.Array, *,
                             causal: bool = True, block_b: int = 8,
                             block_q: int = 512, block_k: int = 512,
                             interpret: bool | None = None):
    """:func:`flash_attention` that also returns the per-row log-sum-exp.

    Returns (o (b, s, h, hd), lse (b, h, s) f32). lse is DIFFERENTIABLE —
    its cotangent folds into the backward's delta row constant at zero
    extra kernel work — which is what a partial-attention merge needs:
    combining per-hop results (o_i, lse_i) with
    ``lse = logaddexp(...); o = Σ exp(lse_i − lse)·o_i`` backpropagates
    correctly through each hop's kernel. This is the building block for
    ring attention consuming each hop through the flash kernel (future
    work, DESIGN.md); no RoPE fusion here — rotate q/k before calling.
    """
    b, s, h, hd = q.shape
    q3, k3, v3, nb, bq, bk, interpret = _prepare(
        q, k, v, causal, block_b, block_q, block_k, interpret,
        "flash_attention_with_lse")
    o, lse = _flash(q3, k3, v3, None, None, 1.0 / (hd ** 0.5),
                    nb, bq, bk, causal, interpret)
    return (o.reshape(b, h, s, hd).transpose(0, 2, 1, 3),
            lse.reshape(b, h, s))
