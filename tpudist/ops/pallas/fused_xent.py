"""Pallas TPU kernel: fused LM-head + cross-entropy.

The measured memory hot spot of the transformer workload is the tied-head
projection: ``logits = h @ E^T`` materialises a (tokens, vocab) f32 tensor
(0.5-2 GB at bench shapes) that exists only to be reduced by logsumexp and
a gather. This kernel streams vocab blocks through VMEM with an online
logsumexp — logits never touch HBM — and a custom VJP recomputes each
block ONCE in the backward pass (one merged kernel emitting both dh and
dE).

Forward math per token i:  loss_i = logsumexp_v(h_i·E_v) − h_i·E_{t_i}
Backward:                  dlogits_iv = (softmax_iv − 1[v = t_i]) · ct_i
                           dh = dlogits @ E ;  dE = dlogitsᵀ @ h

FLOP accounting (r3 judge finding — the old split dh/dq kernels
recomputed every logits block twice, 5 block-matmuls total): the unfused
path is 3 matmuls (fwd logits, stored as the VJP residual; dh; dE); any
fused path that keeps logits out of HBM must recompute them once in
backward — a hard floor of 4 matmuls (fwd logits, bwd logits, dh, dE).
The merged backward kernel reaches that floor: grid (token-supergroup ig
OUTER, vocab block j inner); per step the dl block feeds BOTH products —
dh accumulates in a (block_t_bwd, d) f32 scratch across the j sweep
(consecutive revisits), dE is emitted as per-supergroup HBM partials
(written once per (ig, j) — Mosaic's out-block pipelining is only
correct for consecutive revisits, measured on-chip: a vocab-keyed out
block revisited across ig reads back stale double-buffered state) and
summed outside the kernel. Supergroups also cut the dominant re-stream:
the old dh pass re-read the full (vocab, d) embedding per 512-token
block (56 sweeps = 7.3 GB at bench shape); now once per supergroup.

All reductions/accumulations run in f32 regardless of input dtype.
Shapes need no special alignment: vocab/token remainders are masked with
broadcasted iota against the true sizes.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpudist.utils import compat

NEG = -1e30

# Self-contained VMEM budget (see flash_attention._COMPILER_PARAMS): the
# kernels pick blocks far beyond the 16 MiB default scoped-VMEM limit —
# block size is the dominant perf lever here because every vocab sweep
# re-streams the full (tokens, d) h (dE pass) or (vocab, d) embedding
# (fwd/dh passes) through HBM: at the pre-tune block_t=256 that re-read
# traffic alone was ~15 GB (≈18 ms) per kernel at bench shapes.
_COMPILER_PARAMS = compat.tpu_compiler_params(
    dimension_semantics=("parallel", "arbitrary"),
    vmem_limit_bytes=100 * 1024 * 1024,
)


def _cdiv(a: int, b: int) -> int:
    return (a + b - 1) // b


def _col_ids(tb: int, vb: int, j: int, block_v: int):
    """Global vocab column index of each cell in a (tb, vb) logits block."""
    return jax.lax.broadcasted_iota(jnp.int32, (tb, vb), 1) + j * block_v


# ---------------------------------------------------------------- forward


def _fwd_kernel(h_ref, emb_ref, tgt_ref, loss_ref, lse_ref,
                m_ref, s_ref, g_ref, *, vocab: int, block_v: int):
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG)
        s_ref[:] = jnp.zeros_like(s_ref)
        g_ref[:] = jnp.zeros_like(g_ref)

    h = h_ref[:]
    logits = jax.lax.dot_general(
        h, emb_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)          # (tb, vb)
    tb, vb = logits.shape
    cols = _col_ids(tb, vb, j, block_v)
    valid = cols < vocab
    logits = jnp.where(valid, logits, NEG)

    m_prev = m_ref[:]                                 # (tb, 1)
    blk_max = jnp.max(logits, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, blk_max)
    p = jnp.exp(logits - m_new)
    s_ref[:] = s_ref[:] * jnp.exp(m_prev - m_new) + jnp.sum(
        p, axis=1, keepdims=True)
    m_ref[:] = m_new

    tgt = tgt_ref[:]                                  # (tb, 1) int32
    is_gold = (cols == tgt) & valid
    g_ref[:] += jnp.sum(jnp.where(is_gold, logits, 0.0), axis=1,
                        keepdims=True)

    @pl.when(j == nj - 1)
    def _finish():
        lse = m_ref[:] + jnp.log(s_ref[:])
        lse_ref[:] = lse
        loss_ref[:] = lse - g_ref[:]


def _fwd(h: jax.Array, emb: jax.Array, targets: jax.Array, *,
         block_t: int, block_v: int, interpret: bool
         ) -> Tuple[jax.Array, jax.Array]:
    t, d = h.shape
    v = emb.shape[0]
    tgt2 = targets.reshape(t, 1).astype(jnp.int32)
    grid = (_cdiv(t, block_t), _cdiv(v, block_v))

    loss, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, vocab=v, block_v=block_v),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_t, d), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_v, d), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_t, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((block_t, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_t, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, 1), jnp.float32),
            jax.ShapeDtypeStruct((t, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_t, 1), jnp.float32),
            pltpu.VMEM((block_t, 1), jnp.float32),
            pltpu.VMEM((block_t, 1), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=_COMPILER_PARAMS,
    )(h, emb, tgt2)
    return loss[:, 0], lse


# --------------------------------------------------------------- backward


def _dlogits(h, emb_blk, tgt, lse, ct, cols, vocab):
    logits = jax.lax.dot_general(
        h, emb_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    p = jnp.exp(logits - lse)                         # softmax block
    valid = cols < vocab
    is_gold = (cols == tgt) & valid
    d = (p - is_gold.astype(jnp.float32)) * ct
    return jnp.where(valid, d, 0.0)


def _bwd_kernel(h_ref, emb_ref, tgt_ref, lse_ref, ct_ref,
                dh_ref, dep_ref, acc_ref, *, vocab: int, block_v: int,
                tokens: int, block_t: int):
    """Merged backward: grid (token-supergroup ig, vocab block j). The dl
    block is computed ONCE and feeds both contractions — dh accumulates
    across the j sweep in the f32 scratch (consecutive out revisits), dE
    is written as the (ig, j) partial of the per-supergroup sum (each out
    block written exactly once; the host-side sum over ig finishes it)."""
    j = pl.program_id(1)
    nj = pl.num_programs(1)
    ig = pl.program_id(0)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    tb = h_ref.shape[0]
    vb = emb_ref.shape[0]
    cols = _col_ids(tb, vb, j, block_v)
    dl = _dlogits(h_ref[:], emb_ref[:], tgt_ref[:], lse_ref[:], ct_ref[:],
                  cols, vocab)                        # (tb, vb)
    h = h_ref[:]
    if tokens % block_t:
        # Mask padded token rows (trace-time guard: aligned shapes skip it):
        # the last supergroup's rows of h/ct/lse beyond the true token
        # count are undefined on real TPU (only interpret mode zero-fills)
        # and must not be contracted into either accumulator. dl is zeroed
        # via select (not multiply — the garbage may be inf/nan) and h
        # likewise, mirroring the vocab-col mask.
        rows_valid = (jax.lax.broadcasted_iota(jnp.int32, (tb, 1), 0)
                      + ig * block_t) < tokens
        dl = jnp.where(rows_valid, dl, 0.0)
        h = jnp.where(rows_valid, h, jnp.zeros_like(h))
    emb = emb_ref[:]
    if vocab % block_v:
        # zero the out-of-vocab padded rows of the emb block (trace-time
        # guard: aligned vocab skips it): the matching dl columns are zero,
        # but 0 × garbage would still poison the contraction. Zeroed in the
        # native dtype — an f32 copy of the block doubles its VMEM.
        row_valid = (jax.lax.broadcasted_iota(jnp.int32, (vb, 1), 0)
                     + j * block_v) < vocab
        emb = jnp.where(row_valid, emb, jnp.zeros_like(emb))
    # dl is cast to the operand dtype so the contractions run native on the
    # MXU with f32 accumulators — the same schedule XLA derives for the
    # unfused bf16 head (d/dh of a bf16 matmul casts the f32 cotangent down)
    acc_ref[:] += jax.lax.dot_general(
        dl.astype(emb.dtype), emb, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)           # (tb, d)
    dep_ref[:] = jax.lax.dot_general(
        dl.astype(h.dtype), h, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(
            dep_ref.dtype)[None]                      # (1, vb, d)

    @pl.when(j == nj - 1)
    def _finish():
        dh_ref[:] = acc_ref[:].astype(dh_ref.dtype)


# Largest dE-partials buffer one merged-backward kernel call may emit, in
# supergroups (r4 review: unbounded, the (nig, v, d) partials at batch 96
# match the byte size of the logits tensor the fused head exists to keep
# out of HBM). 8 × (32000, 2048) bf16 ≈ 1.0 GB at the bench shape; token
# ranges beyond it run additional kernel calls accumulated in f32.
_MAX_PARTIALS = 8


def _bwd_call(h, emb, tgt2, lse, ct2, *, block_v_bwd, block_t_bwd,
              interpret):
    """One merged-backward kernel call over a token range: returns
    (dh (t, d), dep (nig, v, d) per-supergroup dE partials)."""
    t, d = h.shape
    v = emb.shape[0]
    bt = min(block_t_bwd, t)
    nig = _cdiv(t, bt)
    col_i = lambda i, j: (i, 0)
    return pl.pallas_call(
        functools.partial(_bwd_kernel, vocab=v, block_v=block_v_bwd,
                          tokens=t, block_t=bt),
        grid=(nig, _cdiv(v, block_v_bwd)),
        in_specs=[
            pl.BlockSpec((bt, d), col_i, memory_space=pltpu.VMEM),
            pl.BlockSpec((block_v_bwd, d), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bt, 1), col_i, memory_space=pltpu.VMEM),
            pl.BlockSpec((bt, 1), col_i, memory_space=pltpu.VMEM),
            pl.BlockSpec((bt, 1), col_i, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bt, d), col_i, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_v_bwd, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, d), h.dtype),
            # partials keep the embedding dtype: f32 runs stay exact; bf16
            # runs round each supergroup's f32-accumulated partial once —
            # within the unfused bf16 head's own rounding (its dE matmul
            # consumes a bf16 dlogits cotangent)
            jax.ShapeDtypeStruct((nig, v, d), emb.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bt, d), jnp.float32)],
        interpret=interpret,
        compiler_params=_COMPILER_PARAMS,
    )(h, emb, tgt2, lse, ct2)


def _bwd(block_t, block_v, block_v_bwd, block_t_bwd, interpret, res,
         ct_loss):
    # Backward block geometry is independent of the forward's: the vocab
    # block is smaller (the kernel carries a (block_t_bwd, d) f32 dh
    # scratch + an f32 dl block), the token block BIGGER — each supergroup
    # re-streams the whole embedding once, so fewer supergroups divide the
    # dominant HBM traffic (and the per-call dE-partials buffer is capped
    # at _MAX_PARTIALS supergroups, outer chunks accumulated in f32).
    h, emb, tgt2, lse = res
    t, d = h.shape
    ct2 = ct_loss.reshape(t, 1).astype(jnp.float32)
    rows = min(block_t_bwd, t) * _MAX_PARTIALS

    de_acc = None
    dh_parts = []
    for start in range(0, t, rows):
        stop = min(start + rows, t)
        dh_c, dep = _bwd_call(h[start:stop], emb, tgt2[start:stop],
                              lse[start:stop], ct2[start:stop],
                              block_v_bwd=block_v_bwd,
                              block_t_bwd=block_t_bwd, interpret=interpret)
        dh_parts.append(dh_c)
        part = (jnp.sum(dep.astype(jnp.float32), axis=0)
                if dep.shape[0] > 1 else dep[0].astype(jnp.float32))
        de_acc = part if de_acc is None else de_acc + part
    dh = dh_parts[0] if len(dh_parts) == 1 else jnp.concatenate(dh_parts)
    return dh, de_acc.astype(emb.dtype), None


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _fused(h, emb, targets, block_t, block_v, block_v_bwd, block_t_bwd,
           interpret):
    loss, _ = _fwd(h, emb, targets, block_t=block_t, block_v=block_v,
                   interpret=interpret)
    return loss


def _fused_fwd(h, emb, targets, block_t, block_v, block_v_bwd, block_t_bwd,
               interpret):
    loss, lse = _fwd(h, emb, targets, block_t=block_t, block_v=block_v,
                     interpret=interpret)
    t = h.shape[0]
    tgt2 = targets.reshape(t, 1).astype(jnp.int32)
    return loss, (h, emb, tgt2, lse.reshape(t, 1))


_fused.defvjp(_fused_fwd, _bwd)


def fused_lm_head_xent(h: jax.Array, emb: jax.Array, targets: jax.Array, *,
                       block_t: int = 512, block_v: int = 2048,
                       block_v_bwd: int = 1024, block_t_bwd: int = 2048,
                       interpret: bool = False) -> jax.Array:
    """Mean cross-entropy of a tied LM head, logits never materialised.

    h: (tokens, d_model) hidden states (bf16 or f32)
    emb: (vocab, d_model) embedding matrix (tied head)
    targets: (tokens,) int32 gold token ids
    Differentiable w.r.t. h and emb. ``interpret=True`` runs the kernels in
    the pallas interpreter (CPU-testable). Backward block geometry:
    ``block_v_bwd`` (vocab) is smaller than the forward's because the
    merged kernel carries a (block_t_bwd, d) f32 dh scratch plus an f32 dl
    block; ``block_t_bwd`` (token supergroup) is BIGGER than the forward's
    because each supergroup re-streams the whole embedding once and emits
    one (vocab, d) dE partial — fewer supergroups divide both."""
    t = h.shape[0]
    block_t = min(block_t, t)
    block_v = min(block_v, emb.shape[0])
    block_v_bwd = min(block_v_bwd, emb.shape[0])
    loss = _fused(h, emb, targets, block_t, block_v, block_v_bwd,
                  block_t_bwd, interpret)
    return jnp.mean(loss)
