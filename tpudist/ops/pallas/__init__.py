from tpudist.ops.pallas.fused_xent import fused_lm_head_xent

__all__ = ["fused_lm_head_xent"]
