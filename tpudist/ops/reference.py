"""Plain-XLA reference implementations the kernels are checked against.

ONE copy, importable by both the CPU test lane (tests/) and the on-chip
acceptance gate (tpudist.selfcheck): if these lived in each, a semantic
fix to one (mask constant, GQA repeat order, xent reduction dtype) could
silently leave the other checking different math. Deliberately the naive
formulation — materialised scores, f32 reductions — because obviousness
is the point of a reference.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def dense_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True) -> jax.Array:
    """Materialised-scores attention. q: (b, s, h, hd); k/v may carry
    fewer (grouped-query) heads. Softmax in f32, output in q's dtype."""
    h, kv = q.shape[2], k.shape[2]
    if kv != h:
        k = jnp.repeat(k, h // kv, axis=2)
        v = jnp.repeat(v, h // kv, axis=2)
    hd = q.shape[-1]
    sc = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(hd)
    if causal:
        s_q, s_k = sc.shape[-2], sc.shape[-1]
        # top-left-aligned tril is wrong for rectangular (decode-style)
        # shapes; refuse rather than silently mis-mask
        assert s_q == s_k, f"causal reference needs s_q == s_k, got {q.shape} {k.shape}"
        mask = jnp.tril(jnp.ones((s_q, s_k), bool))
        sc = jnp.where(mask, sc, -1e30)
    p = jax.nn.softmax(sc.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def lm_head_xent(h: jax.Array, emb: jax.Array,
                 targets: jax.Array) -> jax.Array:
    """Tied-head mean cross-entropy with whole f32 logits.
    h: (tokens, d); emb: (vocab, d); targets: (tokens,) int."""
    logits = h.astype(jnp.float32) @ emb.astype(jnp.float32).T
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[:, None], axis=-1)[:, 0]
    return jnp.mean(logz - gold)
