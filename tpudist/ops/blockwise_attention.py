"""Blockwise causal attention — the long-context local attention path.

The plain einsum attention materialises the full (b, h, s, s) score tensor;
at seq 4096 that is gigabytes and fails to compile on one chip. This is the
standard blockwise/flash decomposition expressed in plain XLA ops: the
query sequence is cut into chunks and each chunk folds key/value chunks
through an online softmax — only lower-triangle (qi >= kj) blocks are
computed, the diagonal gets the intra-chunk causal mask, and nothing bigger
than a (b, h, chunk, chunk) block ever exists.

Role: the long-context path for everything that is not the pallas flash
kernel — the CPU test lane (bit-identical, no Mosaic), shapes the kernel
rejects (seq/head_dim alignment), and the TPUDIST_NO_FLASH escape. On TPU
the flash kernel now wins at every long-context shape (v5e, b2·h16·hd128:
seq 4096 fwd 3.1 ms flash vs 8.2 ms here, fwd+bwd 8.6 vs 20.3 ms) and is
the default; an earlier environment's minutes-long Mosaic compile at seq
4096 no longer reproduces (~5 s). The context-parallel ring path has its
own per-hop consume and does not call this.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from tpudist.ops.gqa import expand_gqa

NEG = -1e30


def blockwise_causal_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                               *, chunk: int = 1024) -> jax.Array:
    """Causal attention, O(s·chunk) memory. q/k/v: (batch, seq, heads, hd);
    k/v may carry fewer (grouped-query) heads. Returns (b, s, heads, hd) in
    q's dtype. ``seq`` must divide by ``chunk`` (callers fall back to the
    dense path otherwise)."""
    b, s, hq, dq = q.shape
    if s % chunk:
        raise ValueError(f"seq {s} not divisible by chunk {chunk}")
    k, v = expand_gqa(q, k, v)
    # (b, h, s, d) layout: chunk slices are contiguous in the matmul dims
    qT = q.transpose(0, 2, 1, 3)
    kT = k.transpose(0, 2, 1, 3)
    vT = v.transpose(0, 2, 1, 3)
    scale = dq ** -0.5
    nc = s // chunk
    tri = (jnp.arange(chunk)[:, None] >= jnp.arange(chunk)[None, :])[None,
                                                                    None]

    # Checkpointed per q chunk: without it, autodiff saves every block's
    # scores/probs as residuals and backward memory is O(s²) again —
    # measured as an HBM OOM training seq 2048 at batch 12 on one v5e.
    # Recomputing each chunk's blocks in backward keeps this path
    # O(s·chunk) in both directions (it is the memory-bound fallback; the
    # flash kernel is the fast path).
    @functools.partial(jax.checkpoint, static_argnums=(3,))
    def q_chunk_out(qc, kTc, vTc, qi) -> jax.Array:
        num = jnp.zeros((b, hq, chunk, dq), jnp.float32)
        den = jnp.zeros((b, hq, chunk), jnp.float32)
        mx = jnp.full((b, hq, chunk), NEG, jnp.float32)
        for kj in range(qi + 1):             # lower triangle only
            kc = kTc[:, :, kj * chunk:(kj + 1) * chunk]
            vc = vTc[:, :, kj * chunk:(kj + 1) * chunk]
            scores = jnp.einsum(
                "bhqd,bhkd->bhqk", qc, kc,
                preferred_element_type=jnp.float32) * scale
            if kj == qi:                      # diagonal block: intra mask
                scores = jnp.where(tri, scores, NEG)
            bm = scores.max(-1)
            nm = jnp.maximum(mx, bm)
            corr = jnp.exp(mx - nm)
            p = jnp.exp(scores - nm[..., None])
            num = num * corr[..., None] + jnp.einsum(
                "bhqk,bhkd->bhqd", p.astype(q.dtype), vc,
                preferred_element_type=jnp.float32)
            den = den * corr + p.sum(-1)
            mx = nm
        return (num / den[..., None]).astype(q.dtype)   # (b, h, chunk, d)

    out = jnp.concatenate(
        [q_chunk_out(qT[:, :, i * chunk:(i + 1) * chunk],
                     kT[:, :, :(i + 1) * chunk], vT[:, :, :(i + 1) * chunk],
                     i) for i in range(nc)], axis=2)
    return out.transpose(0, 2, 1, 3)
