"""Measured collectives: the fabric-acceptance core.

The reference exercised its collective stack (NCCL all-reduce) implicitly
inside DeepSpeed and never measured it (SURVEY.md §5.8, §6). Here the
collective layer is a first-class, *measured* component: explicit shard_map
wrappers around the XLA collectives plus correct bus-bandwidth accounting —
the BASELINE.json headline metric is ≥90% of ICI peak all-reduce bus
bandwidth on a real slice.

Bus-bandwidth convention (nccl-tests / ring-algorithm):
    reported size S = the logical message (see each kind below)
    all_reduce      busBW = 2(n-1)/n × S / t
    all_gather      busBW =  (n-1)/n × S / t   (S = full gathered buffer)
    reduce_scatter  busBW =  (n-1)/n × S / t   (S = full input buffer)
    all_to_all      busBW =  (n-1)/n × S / t   (S = per-rank send buffer)
    ppermute        busBW =            S / t   (S = per-hop message; pure
                                                point-to-point ICI probe)

Every input is laid out so each device holds DISTINCT data — a replicated
input could legally be constant-folded by XLA (psum of known-replicated x
is just n·x), which would time nothing (the fusion hazard in SURVEY.md §7).
shard_map pins the collective in the program.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from tpudist.utils import compat

KINDS = ("all_reduce", "all_gather", "reduce_scatter", "all_to_all",
         "ppermute")

BUS_FACTOR: Dict[str, Callable[[int], float]] = {
    "all_reduce": lambda n: 2 * (n - 1) / n,
    "all_gather": lambda n: (n - 1) / n,
    "reduce_scatter": lambda n: (n - 1) / n,
    "all_to_all": lambda n: (n - 1) / n,
    "ppermute": lambda n: 1.0,
}


def _ring_perm(n: int):
    return [(i, (i + 1) % n) for i in range(n)]


def build_op(kind: str, mesh: Mesh, axis: str, *, message_bytes: int,
             dtype=jnp.float32) -> Tuple[Callable, jax.Array, int]:
    """Build (jitted op, input array, actual message bytes) for one
    collective at one message size.

    ``message_bytes`` is the logical message size S per the convention in
    the module docstring; rounded down so shapes tile evenly over the axis.
    """
    if kind not in KINDS:
        raise ValueError(f"unknown collective {kind!r}; one of {KINDS}")
    n = mesh.shape[axis]
    item = jnp.dtype(dtype).itemsize
    elems = max(message_bytes // item, n)
    elems = (elems // n) * n

    def _sharded_iota(total, spec, shape=None):
        """Generate the input directly in its sharded layout — each device
        materialises only its own shard (a host-side arange would land on
        one device first and OOM at GB sizes × slice width)."""
        def gen():
            v = jnp.arange(total, dtype=dtype)
            return v.reshape(shape) if shape else v
        return jax.jit(gen, out_shardings=NamedSharding(mesh, spec))()

    if kind in ("all_reduce", "reduce_scatter"):
        # each device holds a DISTINCT full buffer: global (n, E), P(axis)
        x = _sharded_iota(n * elems, P(axis, None), shape=(n, elems))

        if kind == "all_reduce":
            def body(v):
                return lax.psum(v[0], axis)
            out_spec = P(None)
        else:
            def body(v):
                return lax.psum_scatter(v[0], axis, tiled=True)
            out_spec = P(axis)
        fn = compat.shard_map(body, mesh=mesh, in_specs=P(axis, None),
                           out_specs=out_spec, check_vma=False)
    elif kind == "all_gather":
        # shards of E/n gather into the full E buffer on every device
        x = _sharded_iota(elems, P(axis))

        def body(v):
            return lax.all_gather(v, axis, tiled=True)
        fn = compat.shard_map(body, mesh=mesh, in_specs=P(axis),
                           out_specs=P(None), check_vma=False)
    elif kind == "all_to_all":
        # each device's send buffer is E (global n·E), exchanged n-ways
        x = _sharded_iota(n * elems, P(axis))

        def body(v):
            return lax.all_to_all(v, axis, split_axis=0, concat_axis=0,
                                  tiled=True)
        fn = compat.shard_map(body, mesh=mesh, in_specs=P(axis),
                           out_specs=P(axis), check_vma=False)
    else:  # ppermute: each device passes its E-buffer one hop around the ring
        x = _sharded_iota(n * elems, P(axis))

        def body(v):
            return lax.ppermute(v, axis, perm=_ring_perm(n))
        fn = compat.shard_map(body, mesh=mesh, in_specs=P(axis),
                           out_specs=P(axis), check_vma=False)

    return jax.jit(fn), x, elems * item


@dataclass
class CollectiveTiming:
    kind: str
    n_devices: int
    message_bytes: int
    mean_s: float
    min_s: float
    algo_gbps: float       # message_bytes / min_s
    bus_gbps: float        # algo × bus factor


def time_collective(kind: str, mesh: Mesh, axis: str, *,
                    message_bytes: int, dtype=jnp.float32,
                    iters: int = 10, warmup: int = 3) -> CollectiveTiming:
    """Time one collective at one message size with block_until_ready
    fencing; warmup reps absorb compile + first-touch."""
    n = mesh.shape[axis]
    op, x, actual_bytes = build_op(kind, mesh, axis,
                                   message_bytes=message_bytes, dtype=dtype)
    for _ in range(warmup):
        jax.block_until_ready(op(x))
    times: List[float] = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(op(x))
        times.append(time.perf_counter() - t0)
    mean_s = sum(times) / len(times)
    min_s = min(times)
    algo = actual_bytes / min_s / 1e9
    return CollectiveTiming(kind=kind, n_devices=n,
                            message_bytes=actual_bytes, mean_s=mean_s,
                            min_s=min_s, algo_gbps=algo,
                            bus_gbps=algo * BUS_FACTOR[kind](n))
