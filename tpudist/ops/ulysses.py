"""Ulysses-style sequence parallelism: all-to-all head↔sequence reshard.

The second standard long-context strategy next to ring attention
(tpudist.ops.ring_attention): instead of rotating key/value blocks around
a ring, TWO ``lax.all_to_all`` collectives reshard the activations so each
device sees the FULL sequence for a slice of the heads —

    (batch, s/n, heads, hd)  --all_to_all-->  (batch, s, heads/n, hd)
        attention over the full sequence, local heads only
    (batch, s, heads/n, hd)  --all_to_all-->  (batch, s/n, heads, hd)

Attention math is then exactly the single-device kernel (dense, blockwise,
or the pallas flash kernel — whatever ``_attention`` routes to), with no
masking games and perfect causal load balance; sequence shards stay
CONTIGUOUS (no zigzag permutation), so RoPE uses plain offset positions.

Trade-off vs ring: Ulysses moves activations twice per layer in two
all-to-alls (volume ~4·b·s·d/n per device) regardless of causality, and
its parallelism is capped by the head count; ring moves k/v blocks n-1
times but overlaps transfers with compute and scales past the head count.
Both are first-class here: ``--cp-impl ulysses|ring``.

The reference has no sequence dimension at all (SURVEY.md §5.7) — this is
TPU-first long-context design, not parity.
"""

from __future__ import annotations

import jax
from jax import lax

from tpudist.utils import compat


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis: str, *, causal: bool = True,
                      attn_impl=None) -> jax.Array:
    """Attention under sequence sharding via head↔sequence all-to-alls.

    q: (batch, s_local, heads, hd); k/v may carry fewer (grouped-query)
    kv heads. Both head counts must be divisible by the ``axis`` size.
    Must run inside a shard_map region where ``axis`` is a manual axis and
    the inputs are sequence-sharded over it (callers: the context-parallel
    loss path, transformer.make_cp_loss_fn with cp_impl="ulysses").
    """
    if attn_impl is None:
        from tpudist.models.transformer import _attention
        attn_impl = _attention
    n = compat.axis_size(axis)
    for name, x in (("q heads", q.shape[2]), ("kv heads", k.shape[2])):
        if x % n:
            raise ValueError(
                f"ulysses needs {name} ({x}) divisible by the context "
                f"axis size ({n}); use --cp-impl ring when the head "
                f"count doesn't factor over the axis")
    if not compat.PARTIAL_AUTO_ALL_TO_ALL:
        # raise BEFORE building the all_to_all program: the old SPMD
        # partitioner hard-aborts the process on it (uncatchable), which
        # would take the whole test run down with it
        raise NotImplementedError(
            "ulysses context parallelism needs lax.all_to_all inside a "
            "partially-manual shard_map, which this jax version's SPMD "
            "partitioner cannot lower; use --cp-impl ring")

    def seq_to_heads(x):
        # (b, s/n, h, hd) -> (b, s, h/n, hd)
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                              tiled=True)

    o = attn_impl(seq_to_heads(q), seq_to_heads(k), seq_to_heads(v),
                  causal=causal)
    # (b, s, h/n, hd) -> (b, s/n, h, hd)
    return lax.all_to_all(o, axis, split_axis=1, concat_axis=2, tiled=True)
