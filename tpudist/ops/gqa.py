"""Grouped-query attention head expansion, shared by every attention path.

One definition (rather than a copy per kernel) so a future change — e.g.
broadcast-reshape instead of ``jnp.repeat`` to keep expanded k/v out of
HBM — lands everywhere at once.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def expand_gqa(q: jax.Array, k: jax.Array,
               v: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Repeat grouped kv heads up to q's head count.

    q: (..., heads, hd); k/v: (..., kv_heads, hd) with heads % kv_heads
    == 0. Heads live on axis 2 in every caller's (batch, seq, heads, hd)
    layout. Differentiable — the repeat's transpose group-sums dk/dv.
    """
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return k, v
