"""Grouped-query attention head expansion for the XLA attention paths
(dense fallback, blockwise) — one definition rather than a copy each.

The pallas flash kernels do NOT use this: they take compact kv into the
kernels via BlockSpec indexing and expand inside VMEM
(flash_attention._expand_rep / _group_sum), precisely to avoid the HBM
expansion this function performs. Ring attention likewise expands
per-hop. A GQA semantic change must visit those sites too.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def expand_gqa(q: jax.Array, k: jax.Array,
               v: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Repeat grouped kv heads up to q's head count.

    q: (..., heads, hd); k/v: (..., kv_heads, hd) with heads % kv_heads
    == 0. Heads live on axis 2 in every caller's (batch, seq, heads, hd)
    layout. Differentiable — the repeat's transpose group-sums dk/dv.
    """
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return k, v
