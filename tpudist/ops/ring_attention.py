"""Ring attention — context/sequence parallelism over the ``context`` axis.

Long-context extension (the reference has no sequence dimension at all,
SURVEY.md §5.7; this is TPU-first design for scale): the sequence is sharded
over the mesh's ``context`` axis; each device computes flash-style online
softmax for its local query chunks while key/value blocks rotate around the
ring via ``lax.ppermute``. Memory per device is O(S/n) and no device ever
materialises the full S×S score matrix. Each hop's ppermute is issued
*before* the current block is consumed, so the neighbour ICI transfer has no
data dependence on the hop's compute and XLA's scheduler can overlap them.

Causal load balance — the ``zigzag`` layout (default): contiguous sequence
sharding under a causal mask is pathologically imbalanced (rank 0's queries
mask out every remote block; rank n-1 needs them all — and the synchronous
ring makes everyone wait for the busiest rank). Instead the sequence is
split into 2n chunks and rank r holds the PAIR (r, 2n-1-r) — one early
chunk, one late chunk. Under causality exactly two of the four chunk-pairs
per remote hop are live, and both are *fully* unmasked:

  * q_high × k_low — always (the high chunk 2n-1-r is later than every low
    chunk src < n).
  * q_low × k_low(src)  when src < r, else  q_high × k_high(src) — the
    "diagonal" pair, strictly ordered either way.

Only the local block needs masks (intra-chunk causal triangles). Every rank
therefore computes the same 2 chunk-matmuls per hop (3 locally) — ~2× fewer
attention FLOPs than consume-everything and perfectly balanced. The loss is
a token-mean, so the zigzag permutation needs no inverse on the loss path;
callers that need outputs in sequence order apply ``zigzag_inverse``.

Math: standard online-softmax accumulation (numerator, denominator, running
max) in f32; a block fully masked by causality contributes exp(-1e30)=0
rather than -inf arithmetic (NaN-safe).
"""

from __future__ import annotations

import functools
from typing import List

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

NEG = -1e30


# ------------------------------------------------------------- zigzag layout


def zigzag_order(n: int) -> List[int]:
    """Chunk ids (of 2n sequence chunks) in on-device order: rank r holds
    [r, 2n-1-r], concatenated over ranks."""
    out: List[int] = []
    for r in range(n):
        out += [r, 2 * n - 1 - r]
    return out


def zigzag_permute(x: jax.Array, n: int, axis: int = 1) -> jax.Array:
    """Reorder a sequence axis into zigzag layout: after a contiguous
    n-way shard, rank r's slice holds chunks (r, 2n-1-r) of the original."""
    s = x.shape[axis]
    if s % (2 * n):
        raise ValueError(f"sequence length {s} not divisible by 2*n={2 * n} "
                         "(zigzag context layout)")
    chunks = jnp.split(x, 2 * n, axis=axis)
    return jnp.concatenate([chunks[i] for i in zigzag_order(n)], axis=axis)


def zigzag_inverse(x: jax.Array, n: int, axis: int = 1) -> jax.Array:
    """Inverse of :func:`zigzag_permute`."""
    order = zigzag_order(n)
    inv = [0] * len(order)
    for pos, cid in enumerate(order):
        inv[cid] = pos
    chunks = jnp.split(x, 2 * n, axis=axis)
    return jnp.concatenate([chunks[i] for i in inv], axis=axis)


def zigzag_positions(me, s_local: int, n: int) -> jax.Array:
    """Absolute token positions of rank ``me``'s local zigzag slice
    (chunks me and 2n-1-me), for RoPE. ``me`` may be traced
    (``lax.axis_index``)."""
    c = s_local // 2
    ar = jnp.arange(c)
    return jnp.concatenate([me * c + ar, (2 * n - 1 - me) * c + ar])


# --------------------------------------------------------- online softmax


def _update(scores, vf, num, den, mx):
    """Fold one (b,h,q,k) score block into the (num, den, mx) state."""
    blk_max = jnp.max(scores, axis=-1)                    # (b,h,q)
    new_mx = jnp.maximum(mx, blk_max)
    corr = jnp.exp(mx - new_mx)
    p = jnp.exp(scores - new_mx[..., None])               # (b,h,q,k)
    num = num * corr[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, vf)
    den = den * corr + jnp.sum(p, axis=-1)
    return num, den, new_mx


def ring_attention_local(q: jax.Array, k: jax.Array, v: jax.Array,
                         axis: str, *, causal: bool = True,
                         layout: str = "zigzag",
                         unroll: int | bool = False) -> jax.Array:
    """Per-shard ring attention; call INSIDE shard_map.

    q: local block ``(batch, s_local, heads, head_dim)``; k, v may have
    fewer (grouped-query) kv heads — GQA expansion happens inside the block
    compute, so only the COMPACT kv blocks travel the ring. The sequence
    dim is sharded over ``axis``; with ``layout="zigzag"`` (causal only)
    the caller must have permuted the sequence with :func:`zigzag_permute`.
    Returns the local output block ``(batch, s_local, heads, head_dim)``.
    """
    if layout not in ("zigzag", "contig"):
        raise ValueError(f"unknown ring layout {layout!r}")
    # n=1 is a degenerate ring (no remote hops): the zigzag schedule's
    # peeled final hop would re-consume the local block, so fall back to
    # the contig path, which handles it as a single masked local consume
    if layout == "zigzag" and causal and lax.axis_size(axis) > 1:
        return _ring_zigzag(q, k, v, axis, unroll=unroll)
    return _ring_contig(q, k, v, axis, causal=causal, unroll=unroll)


def _expand_gqa(x: jax.Array, rep: int) -> jax.Array:
    xf = x.astype(jnp.float32)
    return jnp.repeat(xf, rep, axis=2) if rep != 1 else xf


def _ring_contig(q, k, v, axis: str, *, causal: bool,
                 unroll: int | bool = False) -> jax.Array:
    """Contiguous-shard ring: every rank consumes every kv block (the only
    option without causality; under causality prefer zigzag)."""
    n = lax.axis_size(axis)
    me = lax.axis_index(axis)
    b, s, h, d = q.shape
    rep = h // k.shape[2]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    qf = q.astype(jnp.float32)
    q_pos = me * s + jnp.arange(s)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def consume(k_cur, v_cur, src, num, den, mx):
        kf = _expand_gqa(k_cur, rep)
        vf = _expand_gqa(v_cur, rep)
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * scale
        if causal:
            k_pos = src * s + jnp.arange(s)
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask[None, None], scores, NEG)
        return _update(scores, vf, num, den, mx)

    num = jnp.zeros((b, h, s, d), jnp.float32)
    den = jnp.zeros((b, h, s), jnp.float32)
    mx = jnp.full((b, h, s), NEG, jnp.float32)

    def step(i, carry):
        k_cur, v_cur, num, den, mx = carry
        # issue the rotation FIRST: the transfer of the NEXT block has no
        # dependence on this hop's compute, so they overlap
        k_nxt = lax.ppermute(k_cur, axis, perm=perm)
        v_nxt = lax.ppermute(v_cur, axis, perm=perm)
        num, den, mx = consume(k_cur, v_cur, (me - i) % n, num, den, mx)
        return k_nxt, v_nxt, num, den, mx

    k_l, v_l, num, den, mx = lax.fori_loop(0, n - 1, step,
                                           (k, v, num, den, mx),
                                           unroll=unroll)
    # last block: consume only, nothing left to rotate
    num, den, _ = consume(k_l, v_l, (me - (n - 1)) % n, num, den, mx)

    out = num / jnp.maximum(den, 1e-30)[..., None]            # (b,h,q,d)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)          # (b,q,h,d)


def _ring_zigzag(q, k, v, axis: str, *,
                 unroll: int | bool = False) -> jax.Array:
    """Zigzag-layout causal ring (see module docstring for the schedule)."""
    n = lax.axis_size(axis)
    me = lax.axis_index(axis)
    b, s, h, d = q.shape
    if s % 2:
        raise ValueError("zigzag layout needs an even local sequence length")
    c = s // 2
    rep = h // k.shape[2]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    qf = q.astype(jnp.float32)
    q_lo, q_hi = qf[:, :c], qf[:, c:]
    perm = [(j, (j + 1) % n) for j in range(n)]
    tri = (jnp.arange(c)[:, None] >= jnp.arange(c)[None, :])[None, None]

    def scores_of(q_chunk, k_chunk, mask=None):
        sc = jnp.einsum("bqhd,bkhd->bhqk", q_chunk, k_chunk) * scale
        if mask is not None:
            sc = jnp.where(mask, sc, NEG)
        return sc

    def zero_state():
        return (jnp.zeros((b, h, c, d), jnp.float32),
                jnp.zeros((b, h, c), jnp.float32),
                jnp.full((b, h, c), NEG, jnp.float32))

    # --- local block (the only masked hop): 3 live chunk pairs ---
    kf = _expand_gqa(k, rep)
    vf = _expand_gqa(v, rep)
    k_lo, k_hi = kf[:, :c], kf[:, c:]
    v_lo, v_hi = vf[:, :c], vf[:, c:]
    lo = _update(scores_of(q_lo, k_lo, tri), v_lo, *zero_state())
    hi = _update(scores_of(q_hi, k_lo), v_lo, *zero_state())
    hi = _update(scores_of(q_hi, k_hi, tri), v_hi, *hi)

    def consume_remote(src, k_cur, v_cur, lo, hi):
        """Two unmasked chunk pairs: q_hi×k_lo always; the diagonal pair
        goes to q_lo (src < me) or q_hi (src > me) — chunk operands and the
        target state are selected by predicate, the matmuls run once."""
        kf = _expand_gqa(k_cur, rep)
        vf = _expand_gqa(v_cur, rep)
        k_lo, k_hi = kf[:, :c], kf[:, c:]
        v_lo, v_hi = vf[:, :c], vf[:, c:]
        hi = _update(scores_of(q_hi, k_lo), v_lo, *hi)

        pred = src < me
        q_sel = jnp.where(pred, q_lo, q_hi)
        k_sel = jnp.where(pred, k_lo, k_hi)
        v_sel = jnp.where(pred, v_lo, v_hi)
        st = jax.tree.map(lambda a, b: jnp.where(pred, a, b), lo, hi)
        st = _update(scores_of(q_sel, k_sel), v_sel, *st)
        lo = jax.tree.map(lambda new, old: jnp.where(pred, new, old), st, lo)
        hi = jax.tree.map(lambda new, old: jnp.where(pred, old, new), st, hi)
        return lo, hi

    def step(i, carry):
        k_cur, v_cur, lo, hi = carry
        k_nxt = lax.ppermute(k_cur, axis, perm=perm)   # overlaps consume
        v_nxt = lax.ppermute(v_cur, axis, perm=perm)
        lo, hi = consume_remote((me - i) % n, k_cur, v_cur, lo, hi)
        return k_nxt, v_nxt, lo, hi

    # hops 1..n-1; the local block was consumed above, so rotate first and
    # peel the last hop (consume only, nothing left to forward)
    k1 = lax.ppermute(k, axis, perm=perm)
    v1 = lax.ppermute(v, axis, perm=perm)
    k_l, v_l, lo, hi = lax.fori_loop(1, n - 1, step, (k1, v1, lo, hi),
                                     unroll=unroll)
    lo, hi = consume_remote((me - (n - 1)) % n, k_l, v_l, lo, hi)

    def finish(num, den, mx):
        out = num / jnp.maximum(den, 1e-30)[..., None]        # (b,h,c,d)
        return out.transpose(0, 2, 1, 3)                      # (b,c,h,d)

    return jnp.concatenate([finish(*lo), finish(*hi)],
                           axis=1).astype(q.dtype)


def make_ring_attention(mesh: Mesh, axis: str = "context", *,
                        causal: bool = True, layout: str = "zigzag"):
    """Standalone jitted ring attention on globally (seq-)sharded arrays.

    q, k, v: ``(batch, seq, heads, head_dim)`` with seq sharded over
    ``axis``. With the zigzag layout the permutation/inverse are applied
    here, so inputs and outputs are in natural sequence order. Used
    directly by tests and by context-parallel model code.
    """
    n = mesh.shape[axis]
    spec = P(None, axis, None, None)
    zig = layout == "zigzag" and causal and n > 1

    @functools.partial(jax.shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    def f(q, k, v):
        return ring_attention_local(q, k, v, axis, causal=causal,
                                    layout=layout)

    jf = jax.jit(f)

    def apply(q, k, v):
        if zig:
            q, k, v = (zigzag_permute(x, n) for x in (q, k, v))
        sh = NamedSharding(mesh, spec)
        out = jf(jax.device_put(q, sh), jax.device_put(k, sh),
                 jax.device_put(v, sh))
        return zigzag_inverse(out, n) if zig else out
    return apply
