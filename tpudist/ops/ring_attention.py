"""Ring attention — context/sequence parallelism over the ``context`` axis.

Long-context extension (the reference has no sequence dimension at all,
SURVEY.md §5.7; this is TPU-first design for scale): the sequence is sharded
over the mesh's ``context`` axis; each device computes flash-style online
softmax for its local query block while key/value blocks rotate around the
ring via ``lax.ppermute`` — n_ctx hops overlap compute with neighbour ICI
transfers, memory per device is O(S/n), and no device ever materialises the
full S×S score matrix.

Math: standard online-softmax accumulation (numerator, denominator, running
max) in f32; a block fully masked by causality contributes exp(-1e30)=0
rather than -inf arithmetic (NaN-safe).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

NEG = -1e30


def ring_attention_local(q: jax.Array, k: jax.Array, v: jax.Array,
                         axis: str, *, causal: bool = True) -> jax.Array:
    """Per-shard ring attention; call INSIDE shard_map.

    q: local block ``(batch, s_local, heads, head_dim)``; k, v may have
    fewer (grouped-query) kv heads — GQA expansion happens inside the block
    compute, so only the COMPACT kv blocks travel the ring. The sequence
    dim is sharded over ``axis``. n-1 hops total: the local block is
    consumed before the first rotation and the last block is not forwarded.
    Returns the local output block ``(batch, s_local, heads, head_dim)``.
    """
    n = lax.axis_size(axis)
    me = lax.axis_index(axis)
    b, s, h, d = q.shape
    kv = k.shape[2]
    rep = h // kv
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    qf = q.astype(jnp.float32)

    q_pos = me * s + jnp.arange(s)  # absolute positions of local queries
    perm = [(j, (j + 1) % n) for j in range(n)]

    def consume(k_cur, v_cur, src, num, den, mx):
        """Online-softmax update with the block whose global index is src."""
        kf = k_cur.astype(jnp.float32)
        vf = v_cur.astype(jnp.float32)
        if rep != 1:
            kf = jnp.repeat(kf, rep, axis=2)
            vf = jnp.repeat(vf, rep, axis=2)
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * scale
        if causal:
            k_pos = src * s + jnp.arange(s)
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask[None, None], scores, NEG)
        blk_max = jnp.max(scores, axis=-1)                    # (b,h,q)
        new_mx = jnp.maximum(mx, blk_max)
        corr = jnp.exp(mx - new_mx)
        p = jnp.exp(scores - new_mx[..., None])               # (b,h,q,k)
        num = num * corr[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, vf)
        den = den * corr + jnp.sum(p, axis=-1)
        return num, den, new_mx

    num0 = jnp.zeros((b, h, s, d), jnp.float32)
    den0 = jnp.zeros((b, h, s), jnp.float32)
    mx0 = jnp.full((b, h, s), NEG, jnp.float32)
    # hop 0: the local block, no transfer
    num, den, mx = consume(k, v, me, num0, den0, mx0)

    def step(i, carry):
        k_cur, v_cur, num, den, mx = carry
        # rotate FIRST (ICI neighbour transfer of compact kv), then consume
        k_cur = lax.ppermute(k_cur, axis, perm=perm)
        v_cur = lax.ppermute(v_cur, axis, perm=perm)
        num, den, mx = consume(k_cur, v_cur, (me - i) % n, num, den, mx)
        return k_cur, v_cur, num, den, mx

    _, _, num, den, _ = lax.fori_loop(1, n, step, (k, v, num, den, mx))

    out = num / jnp.maximum(den, 1e-30)[..., None]            # (b,h,q,d)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)          # (b,q,h,d)


def make_ring_attention(mesh: Mesh, axis: str = "context", *,
                        causal: bool = True):
    """Standalone jitted ring attention on globally (seq-)sharded arrays.

    q, k, v: ``(batch, seq, heads, head_dim)`` with seq sharded over
    ``axis``. Used directly by tests and by context-parallel model code.
    """
    spec = P(None, axis, None, None)

    @functools.partial(jax.shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    def f(q, k, v):
        return ring_attention_local(q, k, v, axis, causal=causal)

    jf = jax.jit(f)

    def apply(q, k, v):
        sh = NamedSharding(mesh, spec)
        return jf(jax.device_put(q, sh), jax.device_put(k, sh),
                  jax.device_put(v, sh))
    return apply
