"""Ring attention — context/sequence parallelism over the ``context`` axis.

Long-context extension (the reference has no sequence dimension at all,
SURVEY.md §5.7; this is TPU-first design for scale): the sequence is sharded
over the mesh's ``context`` axis; each device computes flash-style online
softmax for its local query chunks while key/value blocks rotate around the
ring via ``lax.ppermute``. Memory per device is O(S/n) and no device ever
materialises the full S×S score matrix. Each hop's ppermute is issued
*before* the current block is consumed, so the neighbour ICI transfer has no
data dependence on the hop's compute and XLA's scheduler can overlap them.

Causal load balance — the ``zigzag`` layout (default): contiguous sequence
sharding under a causal mask is pathologically imbalanced (rank 0's queries
mask out every remote block; rank n-1 needs them all — and the synchronous
ring makes everyone wait for the busiest rank). Instead the sequence is
split into 2n chunks and rank r holds the PAIR (r, 2n-1-r) — one early
chunk, one late chunk. Under causality exactly two of the four chunk-pairs
per remote hop are live, and both are *fully* unmasked:

  * q_high × k_low — always (the high chunk 2n-1-r is later than every low
    chunk src < n).
  * q_low × k_low(src)  when src < r, else  q_high × k_high(src) — the
    "diagonal" pair, strictly ordered either way.

Only the local block needs masks (intra-chunk causal triangles). Every rank
therefore computes the same 2 chunk-matmuls per hop (3 locally) — ~2× fewer
attention FLOPs than consume-everything and perfectly balanced. The loss is
a token-mean, so the zigzag permutation needs no inverse on the loss path;
callers that need outputs in sequence order apply ``zigzag_inverse``.

Math: standard online-softmax accumulation (numerator, denominator, running
max) in f32; a block fully masked by causality contributes exp(-1e30)=0
rather than -inf arithmetic (NaN-safe).

Hop compute has two implementations, selected by ``use_flash``:

  * **flash** (TPU default when shapes qualify): every hop's chunk
    attention runs in the pallas flash kernel via
    ``flash_attention_with_lse`` and per-hop partials ``(o_i, lse_i)``
    merge with ``lse = logaddexp(...)``, ``o = Σ exp(lse_i − lse)·o_i`` —
    the kernel's lse output is differentiable (its cotangent folds into
    the backward's delta constant), so autodiff through the merge
    backpropagates correctly into each hop's kernel. Scores never touch
    HBM and kv stays compact (GQA) on the ring. Measured on v5e this is
    the difference between kernel speed and XLA-fallback speed in exactly
    the long-context regime CP exists for (README flash-vs-fallback:
    1.5–1.7× at seq 2048–4096).
  * **einsum** (CPU reference + unaligned shapes): f32 einsum hops with
    explicit online-softmax state — the oracle the flash path is tested
    against.
"""

from __future__ import annotations

import functools
import os
from typing import List

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from tpudist.utils import compat

NEG = -1e30


# ------------------------------------------------------------- zigzag layout


def zigzag_order(n: int) -> List[int]:
    """Chunk ids (of 2n sequence chunks) in on-device order: rank r holds
    [r, 2n-1-r], concatenated over ranks."""
    out: List[int] = []
    for r in range(n):
        out += [r, 2 * n - 1 - r]
    return out


def zigzag_permute(x: jax.Array, n: int, axis: int = 1) -> jax.Array:
    """Reorder a sequence axis into zigzag layout: after a contiguous
    n-way shard, rank r's slice holds chunks (r, 2n-1-r) of the original."""
    s = x.shape[axis]
    if s % (2 * n):
        raise ValueError(f"sequence length {s} not divisible by 2*n={2 * n} "
                         "(zigzag context layout)")
    chunks = jnp.split(x, 2 * n, axis=axis)
    return jnp.concatenate([chunks[i] for i in zigzag_order(n)], axis=axis)


def zigzag_inverse(x: jax.Array, n: int, axis: int = 1) -> jax.Array:
    """Inverse of :func:`zigzag_permute`."""
    order = zigzag_order(n)
    inv = [0] * len(order)
    for pos, cid in enumerate(order):
        inv[cid] = pos
    chunks = jnp.split(x, 2 * n, axis=axis)
    return jnp.concatenate([chunks[i] for i in inv], axis=axis)


def zigzag_positions(me, s_local: int, n: int) -> jax.Array:
    """Absolute token positions of rank ``me``'s local zigzag slice
    (chunks me and 2n-1-me), for RoPE. ``me`` may be traced
    (``lax.axis_index``)."""
    c = s_local // 2
    ar = jnp.arange(c)
    return jnp.concatenate([me * c + ar, (2 * n - 1 - me) * c + ar])


# --------------------------------------------------------- online softmax


def _update(scores, vf, num, den, mx):
    """Fold one (b,h,q,k) score block into the (num, den, mx) state."""
    blk_max = jnp.max(scores, axis=-1)                    # (b,h,q)
    new_mx = jnp.maximum(mx, blk_max)
    corr = jnp.exp(mx - new_mx)
    p = jnp.exp(scores - new_mx[..., None])               # (b,h,q,k)
    num = num * corr[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, vf)
    den = den * corr + jnp.sum(p, axis=-1)
    return num, den, new_mx


def flash_hops_supported(q_shape, k_shape, *, layout: str = "zigzag",
                         causal: bool = True, n_shards: int = 2) -> bool:
    """Can the per-hop chunk shapes run the flash kernel? Zigzag hops
    operate on half-shard chunks (c = s_local/2); contig hops on whole
    shards; a degenerate size-1 ring (``n_shards=1``) issues exactly one
    whole-shard call, so only that shape must qualify. The causal contig
    schedule masks against a *traced* source rank, which the kernel's
    static mask cannot express — einsum only."""
    from tpudist.ops.pallas import flash_attention as fa
    b, s, h, d = q_shape
    kvh = k_shape[2]
    if n_shards == 1:
        return fa.supports((b, s, h, d), (b, k_shape[1], kvh, d),
                           causal=causal)
    if layout == "zigzag" and causal:
        if s % 2:
            return False
        c = s // 2
        # remote hops: unmasked (c × c); local block: causal (s × s)
        return (fa.supports((b, c, h, d), (b, c, kvh, d), causal=False)
                and fa.supports((b, s, h, d), (b, s, kvh, d), causal=True))
    if not causal:
        return fa.supports((b, s, h, d), (b, k_shape[1], kvh, d),
                           causal=False)
    return False


def _auto_use_flash(q_shape, k_shape, layout: str, causal: bool,
                    n_shards: int) -> bool:
    """TPU default; ``TPUDIST_NO_FLASH=1`` escape hatch;
    ``TPUDIST_RING_FLASH_INTERPRET=1`` opts the CPU interpreter in (tests
    and the multichip dryrun — by default off-TPU stays on the einsum
    reference path, which is the CPU-fast oracle)."""
    if os.environ.get("TPUDIST_NO_FLASH"):
        return False
    if jax.default_backend() != "tpu" \
            and not os.environ.get("TPUDIST_RING_FLASH_INTERPRET"):
        return False
    return flash_hops_supported(q_shape, k_shape, layout=layout,
                                causal=causal, n_shards=n_shards)


def ring_attention_local(q: jax.Array, k: jax.Array, v: jax.Array,
                         axis: str, *, causal: bool = True,
                         layout: str = "zigzag",
                         unroll: int | bool = False,
                         use_flash: bool | None = None,
                         rank=None) -> jax.Array:
    """Per-shard ring attention; call INSIDE shard_map.

    q: local block ``(batch, s_local, heads, head_dim)``; k, v may have
    fewer (grouped-query) kv heads — GQA expansion happens inside the block
    compute, so only the COMPACT kv blocks travel the ring. The sequence
    dim is sharded over ``axis``; with ``layout="zigzag"`` (causal only)
    the caller must have permuted the sequence with :func:`zigzag_permute`.
    Returns the local output block ``(batch, s_local, heads, head_dim)``.

    ``use_flash``: None = auto (flash kernel hops on TPU when the chunk
    shapes qualify, einsum otherwise); True forces the kernel (raising if
    the shapes don't qualify); False forces the einsum reference path.

    ``rank``: this shard's index on ``axis``. None = derive via
    ``lax.axis_index``, which is correct whenever it lowers — but under a
    PARTIALLY-manual shard_map on old jax the SPMD partitioner rejects
    the resulting PartitionId instruction, so partial-auto callers (the
    context-parallel loss builders) pass the rank in as a sharded-iota
    input instead (see models.transformer.make_cp_loss).
    """
    if layout not in ("zigzag", "contig"):
        raise ValueError(f"unknown ring layout {layout!r}")
    n = compat.axis_size(axis)
    if use_flash is None:
        use_flash = _auto_use_flash(q.shape, k.shape, layout, causal, n)
    elif use_flash and not flash_hops_supported(q.shape, k.shape,
                                                layout=layout,
                                                causal=causal, n_shards=n):
        raise ValueError(
            f"use_flash=True but hop shapes q {q.shape} k {k.shape} "
            f"(layout={layout!r}, causal={causal}, n={n}) don't satisfy "
            f"the flash kernel's rules; gate on flash_hops_supported()")
    # n=1 is a degenerate ring (no remote hops): one local kernel call —
    # the zigzag schedule's peeled final hop would re-consume the local
    # block (and the contig-flash init+peel pair would consume it twice)
    if n == 1:
        if use_flash:
            o, _ = _flash_chunk(q, k, v, causal=causal)
            return o.astype(q.dtype)
        return _ring_contig(q, k, v, axis, causal=causal, unroll=unroll,
                            rank=rank)
    if layout == "zigzag" and causal:
        if use_flash:
            return _ring_zigzag_flash(q, k, v, axis, unroll=unroll,
                                      rank=rank)
        return _ring_zigzag(q, k, v, axis, unroll=unroll, rank=rank)
    if use_flash and not causal:
        return _ring_contig_flash(q, k, v, axis, unroll=unroll)
    return _ring_contig(q, k, v, axis, causal=causal, unroll=unroll,
                        rank=rank)


def _expand_gqa(x: jax.Array, rep: int) -> jax.Array:
    xf = x.astype(jnp.float32)
    return jnp.repeat(xf, rep, axis=2) if rep != 1 else xf


def _ring_sweep(k, v, axis: str, state, consume, *, start: int,
                unroll: int | bool = False):
    """Shared ring driver — the scaffolding all four hop implementations
    use (one copy: the r2 degenerate-ring fix showed how peel logic
    drifts when repeated).

    ``consume(i, k_cur, v_cur, state) -> state`` folds hop ``i`` (the
    block that originated ``i`` ranks upstream) into the state. Each
    hop's ppermute of the NEXT block is issued *before* consume, so the
    neighbour ICI transfer has no data dependence on the hop's compute
    and XLA's scheduler overlaps them; the final hop is peeled (consume
    only, nothing left to rotate). ``start=0`` consumes the resident
    local block inside the sweep (contig); ``start=1`` expects the
    caller to have consumed it already (zigzag local specialisation)
    and begins with one rotation."""
    n = compat.axis_size(axis)
    perm = [(j, (j + 1) % n) for j in range(n)]
    if start:
        k = lax.ppermute(k, axis, perm=perm)
        v = lax.ppermute(v, axis, perm=perm)

    def step(i, carry):
        k_cur, v_cur, st = carry
        k_nxt = lax.ppermute(k_cur, axis, perm=perm)
        v_nxt = lax.ppermute(v_cur, axis, perm=perm)
        return k_nxt, v_nxt, consume(i, k_cur, v_cur, st)

    k_l, v_l, state = lax.fori_loop(start, n - 1, step, (k, v, state),
                                    unroll=unroll)
    return consume(n - 1, k_l, v_l, state)


def _ring_contig(q, k, v, axis: str, *, causal: bool,
                 unroll: int | bool = False, rank=None) -> jax.Array:
    """Contiguous-shard ring: every rank consumes every kv block (the only
    option without causality; under causality prefer zigzag)."""
    n = compat.axis_size(axis)
    me = lax.axis_index(axis) if rank is None else rank
    b, s, h, d = q.shape
    rep = h // k.shape[2]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    qf = q.astype(jnp.float32)
    q_pos = me * s + jnp.arange(s)

    def consume(i, k_cur, v_cur, st):
        kf = _expand_gqa(k_cur, rep)
        vf = _expand_gqa(v_cur, rep)
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * scale
        if causal:
            k_pos = ((me - i) % n) * s + jnp.arange(s)
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask[None, None], scores, NEG)
        return _update(scores, vf, *st)

    state = (jnp.zeros((b, h, s, d), jnp.float32),
             jnp.zeros((b, h, s), jnp.float32),
             jnp.full((b, h, s), NEG, jnp.float32))
    num, den, _ = _ring_sweep(k, v, axis, state, consume, start=0,
                              unroll=unroll)

    out = num / jnp.maximum(den, 1e-30)[..., None]            # (b,h,q,d)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)          # (b,q,h,d)


def _ring_zigzag(q, k, v, axis: str, *,
                 unroll: int | bool = False, rank=None) -> jax.Array:
    """Zigzag-layout causal ring (see module docstring for the schedule)."""
    n = compat.axis_size(axis)
    me = lax.axis_index(axis) if rank is None else rank
    b, s, h, d = q.shape
    if s % 2:
        raise ValueError("zigzag layout needs an even local sequence length")
    c = s // 2
    rep = h // k.shape[2]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    qf = q.astype(jnp.float32)
    q_lo, q_hi = qf[:, :c], qf[:, c:]
    tri = (jnp.arange(c)[:, None] >= jnp.arange(c)[None, :])[None, None]

    def scores_of(q_chunk, k_chunk, mask=None):
        sc = jnp.einsum("bqhd,bkhd->bhqk", q_chunk, k_chunk) * scale
        if mask is not None:
            sc = jnp.where(mask, sc, NEG)
        return sc

    def zero_state():
        return (jnp.zeros((b, h, c, d), jnp.float32),
                jnp.zeros((b, h, c), jnp.float32),
                jnp.full((b, h, c), NEG, jnp.float32))

    # --- local block (the only masked hop): 3 live chunk pairs ---
    kf = _expand_gqa(k, rep)
    vf = _expand_gqa(v, rep)
    k_lo, k_hi = kf[:, :c], kf[:, c:]
    v_lo, v_hi = vf[:, :c], vf[:, c:]
    lo = _update(scores_of(q_lo, k_lo, tri), v_lo, *zero_state())
    hi = _update(scores_of(q_hi, k_lo), v_lo, *zero_state())
    hi = _update(scores_of(q_hi, k_hi, tri), v_hi, *hi)

    def consume_remote(i, k_cur, v_cur, st):
        """Two unmasked chunk pairs: q_hi×k_lo always; the diagonal pair
        goes to q_lo (src < me) or q_hi (src > me) — chunk operands and the
        target state are selected by predicate, the matmuls run once."""
        lo, hi = st
        src = (me - i) % n
        kf = _expand_gqa(k_cur, rep)
        vf = _expand_gqa(v_cur, rep)
        k_lo, k_hi = kf[:, :c], kf[:, c:]
        v_lo, v_hi = vf[:, :c], vf[:, c:]
        hi = _update(scores_of(q_hi, k_lo), v_lo, *hi)

        pred = src < me
        q_sel = jnp.where(pred, q_lo, q_hi)
        k_sel = jnp.where(pred, k_lo, k_hi)
        v_sel = jnp.where(pred, v_lo, v_hi)
        st = jax.tree.map(lambda a, b: jnp.where(pred, a, b), lo, hi)
        st = _update(scores_of(q_sel, k_sel), v_sel, *st)
        lo = jax.tree.map(lambda new, old: jnp.where(pred, new, old), st, lo)
        hi = jax.tree.map(lambda new, old: jnp.where(pred, old, new), st, hi)
        return lo, hi

    # hops 1..n-1: the local block was consumed above (start=1)
    lo, hi = _ring_sweep(k, v, axis, (lo, hi), consume_remote, start=1,
                         unroll=unroll)

    def finish(num, den, mx):
        out = num / jnp.maximum(den, 1e-30)[..., None]        # (b,h,c,d)
        return out.transpose(0, 2, 1, 3)                      # (b,c,h,d)

    return jnp.concatenate([finish(*lo), finish(*hi)],
                           axis=1).astype(q.dtype)


# ----------------------------------------------------- flash-kernel hops


def _flash_chunk(q, k, v, *, causal: bool):
    """One hop's chunk attention through the pallas kernel.

    Returns ``(o, lse)`` with o (b, c, h, d) upcast to f32 — the cross-hop
    merge accumulates in f32 regardless of the kernel's compute dtype —
    and lse (b, h, c) f32. q/k arrive pre-rotated (the CP path applies
    RoPE with per-shard zigzag positions before attention), so the
    kernel's RoPE fusion is not used here."""
    from tpudist.ops.pallas.flash_attention import flash_attention_with_lse
    o, lse = flash_attention_with_lse(q, k, v, causal=causal)
    return o.astype(jnp.float32), lse


def merge_partials(o_a, lse_a, o_b, lse_b):
    """Merge two partial-attention results over disjoint kv sets.

    o: (b, c, h, d) f32 partial outputs; lse: (b, h, c) f32 per-row
    log-sum-exp. ``lse = logaddexp(lse_a, lse_b)`` and the outputs
    combine with weights ``exp(lse_i − lse)`` — exactly the online-softmax
    rescale, expressed on finished partials. Differentiating through this
    merge feeds each hop's kernel backward an (do, dlse) cotangent pair,
    which the kernel folds into its delta row constant (see
    flash_attention._bwd). Also used by the on-chip selfcheck."""
    lse = jnp.logaddexp(lse_a, lse_b)
    w_a = jnp.exp(lse_a - lse).transpose(0, 2, 1)[..., None]
    w_b = jnp.exp(lse_b - lse).transpose(0, 2, 1)[..., None]
    return o_a * w_a + o_b * w_b, lse


def _ring_zigzag_flash(q, k, v, axis: str, *,
                       unroll: int | bool = False, rank=None) -> jax.Array:
    """Zigzag causal ring with every hop in the flash kernel.

    Same schedule as :func:`_ring_zigzag` (see module docstring); the
    per-hop online-softmax state is replaced by finished kernel partials
    (o, lse) merged with :func:`merge_partials`. The local block runs ONE
    causal kernel call over the whole local (lo ++ hi) shard: local index
    order equals absolute position order within the shard, so the plain
    causal mask is exactly the zigzag local mask (lo×lo triangle, hi×lo
    full, lo×hi masked, hi×hi triangle). Remote hops are the two fully
    unmasked chunk calls of the zigzag schedule."""
    n = compat.axis_size(axis)
    me = lax.axis_index(axis) if rank is None else rank
    b, s, h, d = q.shape
    if s % 2:
        raise ValueError("zigzag layout needs an even local sequence length")
    c = s // 2
    q_lo, q_hi = q[:, :c], q[:, c:]

    o_loc, lse_loc = _flash_chunk(q, k, v, causal=True)
    lo = (o_loc[:, :c], lse_loc[..., :c])
    hi = (o_loc[:, c:], lse_loc[..., c:])

    def consume_remote(i, k_cur, v_cur, st):
        """Two unmasked kernel calls per hop: q_hi×k_lo always; the
        diagonal pair goes to q_lo (src < me) or q_hi (src > me) —
        operands and target state selected by predicate, the kernel runs
        once (mirrors the einsum schedule)."""
        lo, hi = st
        src = (me - i) % n
        k_lo, k_hi = k_cur[:, :c], k_cur[:, c:]
        v_lo, v_hi = v_cur[:, :c], v_cur[:, c:]
        hi = merge_partials(*hi, *_flash_chunk(q_hi, k_lo, v_lo,
                                               causal=False))

        pred = src < me
        q_sel = jnp.where(pred, q_lo, q_hi)
        k_sel = jnp.where(pred, k_lo, k_hi)
        v_sel = jnp.where(pred, v_lo, v_hi)
        st = jax.tree.map(lambda a, b_: jnp.where(pred, a, b_), lo, hi)
        st = merge_partials(*st, *_flash_chunk(q_sel, k_sel, v_sel,
                                               causal=False))
        lo = jax.tree.map(lambda new, old: jnp.where(pred, new, old), st, lo)
        hi = jax.tree.map(lambda new, old: jnp.where(pred, old, new), st, hi)
        return lo, hi

    # hops 1..n-1: the local block was consumed above (start=1)
    lo, hi = _ring_sweep(k, v, axis, (lo, hi), consume_remote, start=1,
                         unroll=unroll)

    return jnp.concatenate([lo[0], hi[0]], axis=1).astype(q.dtype)


def _ring_contig_flash(q, k, v, axis: str, *,
                       unroll: int | bool = False) -> jax.Array:
    """Non-causal contiguous ring with flash-kernel hops: every hop is a
    fully unmasked whole-shard kernel call, merged by lse. (The causal
    contig schedule masks against a traced source rank — einsum only;
    causal rings use zigzag.)"""
    state = _flash_chunk(q, k, v, causal=False)

    def consume(i, k_cur, v_cur, st):
        return merge_partials(*st, *_flash_chunk(q, k_cur, v_cur,
                                                 causal=False))

    o, _ = _ring_sweep(k, v, axis, state, consume, start=1, unroll=unroll)
    return o.astype(q.dtype)


def make_ring_attention(mesh: Mesh, axis: str = "context", *,
                        causal: bool = True, layout: str = "zigzag",
                        use_flash: bool | None = None):
    """Standalone jitted ring attention on globally (seq-)sharded arrays.

    q, k, v: ``(batch, seq, heads, head_dim)`` with seq sharded over
    ``axis``. With the zigzag layout the permutation/inverse are applied
    here, so inputs and outputs are in natural sequence order. Used
    directly by tests and by context-parallel model code.
    """
    n = mesh.shape[axis]
    spec = P(None, axis, None, None)
    zig = layout == "zigzag" and causal and n > 1

    @functools.partial(compat.shard_map, mesh=mesh,
                       in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    def f(q, k, v):
        return ring_attention_local(q, k, v, axis, causal=causal,
                                    layout=layout, use_flash=use_flash)

    jf = jax.jit(f)

    def apply(q, k, v):
        if zig:
            q, k, v = (zigzag_permute(x, n) for x in (q, k, v))
        sh = NamedSharding(mesh, spec)
        out = jf(jax.device_put(q, sh), jax.device_put(k, sh),
                 jax.device_put(v, sh))
        return zigzag_inverse(out, n) if zig else out
    return apply
